"""Claim the carbon-frontier point (VERDICT r4 next #4).

Round 4's multiregion flagship beats the rule on both headlines but not
its own carbon teacher's carbon (0.787x vs 0.759x): the tier-2 fitness
(`max` over min(rule, teacher) bars) settles where the WORSE ratio is
best, which parks candidates at the cost edge of the frontier. This
driver applies direct frontier pressure instead — CEM fitness with
asymmetric bars (`CEMConfig.usd_bar="rule"`, `co2_bar="teacher"`,
`attain_bar="rule"`): fitness < 1 means carbon STRICTLY below the
carbon-greedy teacher at rule-level cost and attainment, i.e. a point
the round-4 run left unclaimed (`ARCHITECTURE.md` §5 residual).

Selection is carbon-first lexicographic on held-out selection traces
(seed block 20k, disjoint from training and bench): among candidates
with cost <= rule and attainment >= rule - eps, minimize carbon. The
checkpoint ships to `ccka_tpu/checkpoints/ppo_flagship_multiregion_
frontier.npz` ONLY if the selected candidate's carbon beats the
teacher's on the selection traces; otherwise the result lands in runs/
with the shortfall recorded — no stand-ins under flagship names
(round-3 rule).

Run: ``python scripts/train_carbon_frontier.py --generations 400``
(TPU required — the CEM mega engine carries the search).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

from ccka_tpu.config import multi_region_config  # noqa: E402
from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy  # noqa: E402
from ccka_tpu.signals.synthetic import SyntheticSignalSource  # noqa: E402
from ccka_tpu.train.cem import CEMConfig, cem_refine  # noqa: E402
from ccka_tpu.train.checkpoint import save_params_npz  # noqa: E402
from ccka_tpu.train.evaluate import evaluate_backend, heldout_traces  # noqa: E402
from ccka_tpu.train.flagship import (_ATTAIN_EPS,  # noqa: E402
                                     _SELECTION_SEED0,
                                     flagship_checkpoint_path)
from ccka_tpu.train.imitate import distill_teacher  # noqa: E402
from ccka_tpu.train.ppo import PPOBackend  # noqa: E402


def log(s: str) -> None:
    print(s, file=sys.stderr, flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--generations", type=int, default=400)
    ap.add_argument("--eval-every", type=int, default=40)
    ap.add_argument("--traces-per-gen", type=int, default=256)
    ap.add_argument("--eval-steps", type=int, default=2880)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--distill-iterations", type=int, default=2000)
    ap.add_argument("--out", default="",
                    help="override output path (default: ship to the "
                         "frontier variant location iff the frontier "
                         "bar is met, else runs/)")
    args = ap.parse_args(argv)

    cfg = multi_region_config()
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals)
    teacher = CarbonAwarePolicy(cfg.cluster)

    sel_traces = heldout_traces(src, steps=args.eval_steps, n=5,
                                seed0=_SELECTION_SEED0)
    rule_res = evaluate_backend(cfg, RulePolicy(cfg.cluster), sel_traces)
    teacher_res = evaluate_backend(cfg, teacher, sel_traces)
    log(f"rule:    usd {rule_res['usd_per_slo_hour']:.4f} "
        f"co2 {rule_res['g_co2_per_kreq']:.4f} "
        f"attain {rule_res['slo_attainment']:.4f}")
    log(f"teacher: usd x"
        f"{teacher_res['usd_per_slo_hour'] / rule_res['usd_per_slo_hour']:.3f}"
        f" co2 x"
        f"{teacher_res['g_co2_per_kreq'] / rule_res['g_co2_per_kreq']:.3f}"
        f" attain {teacher_res['slo_attainment']:.4f}")

    log(f"distilling carbon teacher ({args.distill_iterations} iters)...")
    params_cur, hist = distill_teacher(cfg, "carbon", seed=args.seed,
                                       iterations=args.distill_iterations)
    log(f"distilled: actor_mse {hist[-1]['actor_mse']:.4f}")

    def frontier_eval(params):
        res = evaluate_backend(cfg, PPOBackend(cfg, params), sel_traces)
        usd = res["usd_per_slo_hour"] / rule_res["usd_per_slo_hour"]
        co2 = res["g_co2_per_kreq"] / rule_res["g_co2_per_kreq"]
        co2_vs_teacher = (res["g_co2_per_kreq"]
                          / teacher_res["g_co2_per_kreq"])
        feasible = (usd <= 1.0
                    and res["slo_attainment"]
                    >= rule_res["slo_attainment"] - _ATTAIN_EPS)
        return res, {"usd_ratio": usd, "co2_ratio": co2,
                     "co2_vs_teacher": co2_vs_teacher,
                     "slo_attainment": res["slo_attainment"],
                     "feasible": feasible}

    res0, m0 = frontier_eval(params_cur)
    log(f"init: usd x{m0['usd_ratio']:.3f} co2 x{m0['co2_ratio']:.3f} "
        f"(vs teacher x{m0['co2_vs_teacher']:.3f}) "
        f"attain {m0['slo_attainment']:.4f}")
    # Carbon-first lexicographic: feasible beats infeasible; then lower
    # carbon wins (cost only matters through feasibility).
    best = {"params": jax.device_get(params_cur), "metrics": m0,
            "res": res0, "generation": 0}

    def better(m, b):
        if m["feasible"] != b["metrics"]["feasible"]:
            return m["feasible"]
        return m["co2_ratio"] < b["metrics"]["co2_ratio"]

    history = [dict(m0, generation=0)]
    sigma = CEMConfig().sigma0
    done = 0
    t0 = time.time()
    while done < args.generations:
        n = min(args.eval_every, args.generations - done)
        params_cur, _h, info = cem_refine(
            cfg, params_cur, src,
            cem=CEMConfig(generations=n, sigma0=sigma,
                          traces_per_gen=args.traces_per_gen,
                          usd_bar="rule", co2_bar="teacher",
                          attain_bar="rule"),
            engine="mega", teacher_policy=teacher,
            seed=args.seed + 31 * done,
            log=lambda s: log("  cem " + s))
        sigma = info["final_sigma"]
        done += n
        res, m = frontier_eval(params_cur)
        m["generation"] = done
        m["cem_fitness"] = info["fitness"]
        history.append(m)
        log(f"gen {done:4d}: usd x{m['usd_ratio']:.3f} "
            f"co2 x{m['co2_ratio']:.3f} "
            f"(vs teacher x{m['co2_vs_teacher']:.3f}) "
            f"attain {m['slo_attainment']:.4f} "
            f"{'FEASIBLE' if m['feasible'] else 'infeasible'} "
            f"({time.time() - t0:.0f}s)")
        if better(m, best):
            best = {"params": jax.device_get(params_cur), "metrics": m,
                    "res": res, "generation": done}
            log("  ^ new best")

    bm = best["metrics"]
    claimed = bool(bm["feasible"] and bm["co2_vs_teacher"] < 1.0)
    meta = {
        "family": "multiregion_frontier",
        "fitness": {"usd_bar": "rule", "co2_bar": "teacher",
                    "attain_bar": "rule"},
        "cem_engine": "mega",
        "generations_total": args.generations,
        "traces_per_gen": args.traces_per_gen,
        "selected_iteration": best["generation"],
        "init_from": "distill:carbon",
        "refine": "cem",
        "seed": args.seed,
        "selection_seed0": _SELECTION_SEED0,
        "frontier_claimed": claimed,
        # The full eval-chunk trajectory — the evidence record for an
        # unclaimed run (and provenance for a claimed one).
        "history": history,
        "wins_both": bool(bm["usd_ratio"] <= 1.0
                          and bm["co2_ratio"] <= 1.0
                          and bm["feasible"]),
        "selection_scoreboard": {
            "rule": {k: float(rule_res[k]) for k in
                     ("usd_per_slo_hour", "g_co2_per_kreq",
                      "slo_attainment")},
            "teacher": {k: float(teacher_res[k]) for k in
                        ("usd_per_slo_hour", "g_co2_per_kreq",
                         "slo_attainment")},
            "ppo": {k: float(best["res"][k]) for k in
                    ("usd_per_slo_hour", "g_co2_per_kreq",
                     "slo_attainment")},
        },
    }
    if args.out:
        out_path = args.out
    elif claimed:
        out_path = flagship_checkpoint_path(
            cfg, variant="multiregion_frontier")
    else:
        out_path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "runs",
            "mr_frontier_unclaimed.npz")
        log("frontier NOT claimed — checkpoint goes to runs/, not the "
            "package (no stand-ins under flagship names)")
    path = save_params_npz(out_path, best["params"], meta=meta)
    print(json.dumps({"checkpoint": path, **meta}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
