"""Train + select the replay-family flagship checkpoint.

BASELINE config #3 scores backends on the committed replay trace
(`data/replay_2day.npz`) — a different generative family than the
synthetic training world. Round 3's transfer result was cost-only (no
learned backend won carbon there); this driver closes that gap (VERDICT
r3 #4) by training ON the replay family:

- fine-tuning data: the FIRST 4 days of `data/replay_train_6day.npz` —
  the SAME generative process as the scoring trace, a DIFFERENT
  realization (seed/days; see `scripts/make_replay_trace.py --variant
  train`), so nothing ever trains on the scoring trace's windows, only
  on its family;
- init: behavior-clone the carbon-aware teacher on those training days
  (round-3 measured the teacher a hair from a replay dual win: usd
  x0.997 / co2 x0.994 at a 0.002 attainment shortfall);
- refinement: (1+λ)-ES (`train/cem.py`) on full-day windows of the
  training days, teacher-paired bars;
- selection: init and refined candidates score on the LAST 2 days of
  the train trace — day-aligned windows the training stream never
  touches (a real holdout, enforced by slicing the source, not by
  offset conventions); the best ships as
  `ccka_tpu/checkpoints/ppo_flagship_replay.npz`, which
  `bench.bench_quality_replay` prefers over the synthetic-family
  flagship for its "ppo" row.

Run from the repo root:
    python scripts/make_replay_trace.py --variant train
    python scripts/train_replay_flagship.py --generations 40
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import jax
import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from ccka_tpu.config import default_config  # noqa: E402
from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy  # noqa: E402
from ccka_tpu.signals.replay import ReplaySignalSource  # noqa: E402
from ccka_tpu.train.cem import CEMConfig, cem_refine  # noqa: E402
from ccka_tpu.train.checkpoint import save_params_npz  # noqa: E402
from ccka_tpu.train.evaluate import evaluate_backend  # noqa: E402
from ccka_tpu.train.flagship import score_vs_rule  # noqa: E402
from ccka_tpu.train.imitate import imitate  # noqa: E402
from ccka_tpu.train.ppo import PPOBackend  # noqa: E402

TRAIN_TRACE = os.path.join(_ROOT, "data", "replay_train_6day.npz")
OUT = os.path.join(_ROOT, "ccka_tpu", "checkpoints",
                   "ppo_flagship_replay.npz")
_HOLDOUT_DAYS = 2


def split_sources(path: str, steps_per_day: int):
    """(train_source, selection_traces): the ES samples windows ONLY
    from the first N-2 days; selection scores on day-aligned windows of
    the last 2 days — a real holdout enforced by slicing the stored
    trace, not by offset conventions."""
    full = ReplaySignalSource.from_file(path)
    stored = full._trace.steps
    holdout = _HOLDOUT_DAYS * steps_per_day
    if stored <= holdout + steps_per_day:
        raise SystemExit(f"{path}: {stored} steps cannot hold "
                         f"{_HOLDOUT_DAYS} holdout days + training data")
    train_src = ReplaySignalSource(
        full._trace.slice_steps(0, stored - holdout), full._meta)
    sel = [full._trace.slice_steps(stored - holdout + i * steps_per_day,
                                   steps_per_day)
           for i in range(_HOLDOUT_DAYS)]
    return train_src, sel


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--generations", type=int, default=40)
    ap.add_argument("--popsize", type=int, default=32)
    ap.add_argument("--distill-iterations", type=int, default=2000)
    ap.add_argument("--traces", type=int, default=4,
                    help="training windows per ES generation")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args(argv)

    if not os.path.exists(TRAIN_TRACE):
        raise SystemExit(f"{TRAIN_TRACE} missing — run "
                         "scripts/make_replay_trace.py --variant train")
    cfg = default_config()
    steps_per_day = int(86400 / cfg.sim.dt_s)
    train_src, sel = split_sources(TRAIN_TRACE, steps_per_day)

    log = lambda s: print(s, file=sys.stderr, flush=True)  # noqa: E731
    rule_res = evaluate_backend(cfg, RulePolicy(cfg.cluster), sel)
    teacher = CarbonAwarePolicy(cfg.cluster)
    teacher_res = evaluate_backend(cfg, teacher, sel)
    log(f"rule:    usd {rule_res['usd_per_slo_hour']:.4f} "
        f"co2 {rule_res['g_co2_per_kreq']:.4f} "
        f"attain {rule_res['slo_attainment']:.4f}")
    log(f"teacher: usd x{teacher_res['usd_per_slo_hour'] / rule_res['usd_per_slo_hour']:.4f} "
        f"co2 x{teacher_res['g_co2_per_kreq'] / rule_res['g_co2_per_kreq']:.4f} "
        f"attain {teacher_res['slo_attainment']:.4f}")

    log("distilling carbon teacher on replay-train windows...")
    params0, hist = imitate(cfg, teacher, train_src, seed=args.seed,
                            iterations=args.distill_iterations)
    log(f"distilled: actor_mse {hist[-1]['actor_mse']:.4f}")

    refined, cem_hist, info = cem_refine(
        cfg, params0, train_src,
        cem=CEMConfig(generations=args.generations, popsize=args.popsize,
                      traces_per_gen=args.traces,
                      eval_steps=steps_per_day),
        teacher_fn=teacher.action_fn(), seed=args.seed + 17, log=log)

    # Select on the held-out windows: init vs refined.
    candidates = {"init": (params0, 0),
                  "refined": (refined, info["gen"])}
    best_name, best = None, None
    for name, (params, gen) in candidates.items():
        res = evaluate_backend(cfg, PPOBackend(cfg, params), sel)
        wins, score = score_vs_rule(res, rule_res)
        log(f"{name:>8}: usd x{res['usd_per_slo_hour'] / rule_res['usd_per_slo_hour']:.4f} "
            f"co2 x{res['g_co2_per_kreq'] / rule_res['g_co2_per_kreq']:.4f} "
            f"attain {res['slo_attainment']:.4f} "
            f"{'WIN' if wins else '   '} score {score:.4f}")
        cand = {"name": name, "params": params, "gen": gen, "res": res,
                "wins": wins, "score": score}
        if best is None or (cand["wins"], -cand["score"]) > (
                best["wins"], -best["score"]):
            best, best_name = cand, name

    meta = {
        "family": "replay",
        "train_trace": os.path.basename(TRAIN_TRACE),
        "init_from": "distill:carbon(replay-train)",
        "refine": "cem",
        "selected": best_name,
        "selected_iteration": int(best["gen"]),
        "wins_both": bool(best["wins"]),
        "generations": args.generations,
        "seed": args.seed,
        "selection_scoreboard": {
            "rule": {k: float(rule_res[k]) for k in
                     ("usd_per_slo_hour", "g_co2_per_kreq",
                      "slo_attainment")},
            "teacher": {k: float(teacher_res[k]) for k in
                        ("usd_per_slo_hour", "g_co2_per_kreq",
                         "slo_attainment")},
            "ppo": {k: float(best["res"][k]) for k in
                    ("usd_per_slo_hour", "g_co2_per_kreq",
                     "slo_attainment")},
        },
    }
    path = save_params_npz(args.out, best["params"], meta=meta)
    print(json.dumps({"checkpoint": path, **{k: v for k, v in meta.items()
                                             if k != "params"}}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
