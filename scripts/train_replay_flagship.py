"""Train + select the replay-family flagship checkpoint.

BASELINE config #3 scores backends on the committed replay trace — a
different generative family than the synthetic training world. Round 4
shipped a distilled init under this name (`selected_iteration=0`:
refinement never beat distillation at 4 noisy traces/generation);
round 5 (VERDICT r4 next #2) attacks with the CEM mega engine:

- fine-tuning data: the FIRST 6 days of `data/replay_train_9day.npz` —
  the SAME generative process as the scoring traces, DIFFERENT
  realizations (`scripts/make_replay_trace.py`), so nothing trains on
  the scoring trace's windows, only on its family;
- init: behavior-clone the carbon-aware teacher on the training days;
- refinement: (1+λ)-ES on the Pallas population kernel
  (`cem_refine(engine="mega")`) — 128 on-device-sampled training
  windows per generation (fitness se ~5x tighter than round 4's 4) at
  ~1s/generation, teacher-paired bars, damped-hpa trust region;
  multiple ES seeds from the same init, best-of by selection;
- selection: every eval-chunk candidate scores on 5 half-day-staggered
  windows of the LAST 3 days (a real holdout, enforced by slicing the
  source). The selection win now requires EVERY window's cost ratio
  < 1 — the same per-window standard the significance-gated bench
  scoreboard applies — so a candidate that wins on average but loses a
  window cannot ship. Best candidate ships as
  `ccka_tpu/checkpoints/ppo_flagship_replay.npz`.

Run from the repo root (TPU):
    python scripts/make_replay_trace.py --variant train9
    python scripts/train_replay_flagship.py --generations 300
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import jax
import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from ccka_tpu.config import default_config  # noqa: E402
from ccka_tpu.obs.runlog import RunLog  # noqa: E402
from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy  # noqa: E402
from ccka_tpu.signals.replay import ReplaySignalSource  # noqa: E402
from ccka_tpu.train.cem import CEMConfig, cem_refine  # noqa: E402
from ccka_tpu.train.checkpoint import save_params_npz  # noqa: E402
from ccka_tpu.train.evaluate import evaluate_backend  # noqa: E402
from ccka_tpu.train.flagship import _ATTAIN_EPS, score_vs_rule  # noqa: E402
from ccka_tpu.train.imitate import imitate  # noqa: E402
from ccka_tpu.train.ppo import PPOBackend  # noqa: E402

TRAIN_TRACE = os.path.join(_ROOT, "data", "replay_train_9day.npz")
TRAIN_TRACE_FALLBACK = os.path.join(_ROOT, "data",
                                    "replay_train_6day.npz")
OUT = os.path.join(_ROOT, "ccka_tpu", "checkpoints",
                   "ppo_flagship_replay.npz")
_HOLDOUT_DAYS = 3
_SEL_WINDOWS = 5


def split_sources(path: str, steps_per_day: int):
    """(train_source, selection_traces): the ES samples windows ONLY
    from the first N-3 days; selection scores on ``_SEL_WINDOWS``
    half-day-staggered day-long windows of the last 3 days — a real
    holdout enforced by slicing the stored trace."""
    full = ReplaySignalSource.from_file(path)
    stored = full._trace.steps
    holdout = _HOLDOUT_DAYS * steps_per_day
    if stored <= holdout + steps_per_day:
        raise SystemExit(f"{path}: {stored} steps cannot hold "
                         f"{_HOLDOUT_DAYS} holdout days + training data")
    train_src = ReplaySignalSource(
        full._trace.slice_steps(0, stored - holdout), full._meta)
    # 5 day-long windows over 3 holdout days: starts every half day.
    stride = (holdout - steps_per_day) // (_SEL_WINDOWS - 1)
    sel = [full._trace.slice_steps(stored - holdout + i * stride,
                                   steps_per_day)
           for i in range(_SEL_WINDOWS)]
    return train_src, sel


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--generations", type=int, default=300,
                    help="ES generations PER SEED")
    ap.add_argument("--es-seeds", type=int, default=2,
                    help="independent ES runs from the same distilled "
                         "init (best-of by holdout selection)")
    ap.add_argument("--eval-every", type=int, default=40)
    ap.add_argument("--popsize", type=int, default=64)
    ap.add_argument("--distill-iterations", type=int, default=2000)
    ap.add_argument("--traces", type=int, default=128,
                    help="training windows per ES generation")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--engine", default="mega", choices=("mega", "lax"))
    ap.add_argument("--attain-margin", type=float, default=0.0,
                    help="CEMConfig.attain_margin: keep the ES operating "
                         "point this far ABOVE the attainment bar so "
                         "holdout realizations don't land below it")
    ap.add_argument("--usd-bar", default="min",
                    choices=("min", "rule", "teacher"))
    ap.add_argument("--co2-bar", default="min",
                    choices=("min", "rule", "teacher"))
    ap.add_argument("--out", default=OUT)
    ap.add_argument("--runlog", default="runs/replay_flagship.jsonl",
                    help="structured JSONL run log (obs/runlog; inspect "
                         "with `ccka obs tail|summarize`); '' disables")
    args = ap.parse_args(argv)

    train_path = (TRAIN_TRACE if os.path.exists(TRAIN_TRACE)
                  else TRAIN_TRACE_FALLBACK)
    if not os.path.exists(train_path):
        raise SystemExit(f"{TRAIN_TRACE} missing — run "
                         "scripts/make_replay_trace.py --variant train9")
    cfg = default_config()
    steps_per_day = int(86400 / cfg.sim.dt_s)
    train_src, sel = split_sources(train_path, steps_per_day)

    # Structured run log (obs/runlog): the old stderr-only logging left a
    # crashed multi-hour ES run with no machine-parseable record of its
    # completed generations. `log` stays the human echo; every candidate
    # evaluation and ES generation is now also a JSONL event.
    rl = RunLog(args.runlog or None, kind="replay-flagship",
                meta={"generations": args.generations,
                      "es_seeds": args.es_seeds, "engine": args.engine,
                      "popsize": args.popsize, "seed": args.seed})
    log = rl
    rule_res = evaluate_backend(cfg, RulePolicy(cfg.cluster), sel)
    teacher = CarbonAwarePolicy(cfg.cluster)
    teacher_res = evaluate_backend(cfg, teacher, sel)
    log(f"holdout windows: {len(sel)} x 1 day of {train_path}")
    log(f"rule:    usd {rule_res['usd_per_slo_hour']:.4f} "
        f"co2 {rule_res['g_co2_per_kreq']:.4f} "
        f"attain {rule_res['slo_attainment']:.4f}")
    log(f"teacher: usd x{teacher_res['usd_per_slo_hour'] / rule_res['usd_per_slo_hour']:.4f} "
        f"co2 x{teacher_res['g_co2_per_kreq'] / rule_res['g_co2_per_kreq']:.4f} "
        f"attain {teacher_res['slo_attainment']:.4f}")

    log("distilling carbon teacher on replay-train windows...")
    params0, hist = imitate(cfg, teacher, train_src, seed=args.seed,
                            iterations=args.distill_iterations)
    log(f"distilled: actor_mse {hist[-1]['actor_mse']:.4f}")

    def consider(name, params, gen):
        """Score on the holdout; the win requires EVERY window's cost
        AND carbon ratio < 1 at rule-level attainment (the bench
        scoreboard's per-window standard, VERDICT r4 next #2)."""
        res = evaluate_backend(cfg, PPOBackend(cfg, params), sel)
        wins_mean, score = score_vs_rule(res, rule_res)
        pw_usd = [a / max(b, 1e-9) for a, b in zip(
            res["per_trace"]["usd_per_slo_hour"],
            rule_res["per_trace"]["usd_per_slo_hour"])]
        pw_co2 = [a / max(b, 1e-9) for a, b in zip(
            res["per_trace"]["g_co2_per_kreq"],
            rule_res["per_trace"]["g_co2_per_kreq"])]
        all_windows = (max(pw_usd) < 1.0 and max(pw_co2) < 1.0
                       and res["slo_attainment"]
                       >= rule_res["slo_attainment"] - _ATTAIN_EPS)
        rl.event("eval", _echo=(
            f"{name:>14}: usd x{res['usd_per_slo_hour'] / rule_res['usd_per_slo_hour']:.4f} "
            f"co2 x{res['g_co2_per_kreq'] / rule_res['g_co2_per_kreq']:.4f} "
            f"attain {res['slo_attainment']:.4f} "
            f"worst-window usd x{max(pw_usd):.4f} co2 x{max(pw_co2):.4f} "
            f"{'ALL-WINDOWS-WIN' if all_windows else ('WIN' if wins_mean else '')}"),
            name=name, gen=gen,
            usd_ratio=res["usd_per_slo_hour"] / rule_res["usd_per_slo_hour"],
            co2_ratio=res["g_co2_per_kreq"] / rule_res["g_co2_per_kreq"],
            slo_attainment=res["slo_attainment"], wins_mean=wins_mean,
            all_windows_win=all_windows, score=score,
            worst_window_usd=max(pw_usd), worst_window_co2=max(pw_co2))
        return {"name": name, "params": jax.device_get(params),
                "gen": gen, "res": res, "wins": wins_mean,
                "all_windows_win": all_windows, "score": score,
                "worst_window_usd": max(pw_usd),
                "worst_window_co2": max(pw_co2)}

    def better(a, b):
        """Tier: all-windows win > mean win > neither; then score."""
        ka = (a["all_windows_win"], a["wins"], -a["score"])
        kb = (b["all_windows_win"], b["wins"], -b["score"])
        return ka > kb

    best = consider("init", params0, 0)
    for es_seed in range(args.es_seeds):
        params_cur = params0
        sigma = CEMConfig().sigma0
        done = 0
        while done < args.generations:
            n = min(args.eval_every, args.generations - done)
            extra = {"sigma_min": 1e-3} if args.engine == "mega" else {}
            params_cur, _h, info = cem_refine(
                cfg, params_cur, train_src,
                cem=CEMConfig(generations=n, sigma0=sigma,
                              popsize=args.popsize,
                              traces_per_gen=args.traces,
                              eval_steps=steps_per_day,
                              attain_margin=args.attain_margin,
                              usd_bar=args.usd_bar, co2_bar=args.co2_bar,
                              **extra),
                engine=args.engine,
                teacher_policy=(teacher if args.engine == "mega"
                                else None),
                teacher_fn=(None if args.engine == "mega"
                            else teacher.action_fn()),
                seed=args.seed + 1000 * es_seed + 17 * done,
                # Echo-only here: the structured record comes from
                # runlog's per-generation "gen" event (no double lines).
                log=lambda s: print(f"  cem[s{es_seed}] " + s,
                                    file=sys.stderr, flush=True),
                runlog=rl)
            sigma = info["final_sigma"]
            done += n
            cand = consider(f"seed{es_seed}@gen{done}", params_cur, done)
            if better(cand, best):
                best = cand
                log("  ^ new best")

    meta = {
        "family": "replay",
        "train_trace": os.path.basename(train_path),
        "init_from": "distill:carbon(replay-train)",
        "refine": "cem",
        "cem_engine": args.engine,
        "traces_per_gen": args.traces,
        "es_seeds": args.es_seeds,
        "selection_windows": len(sel),
        "selected": best["name"],
        "selected_iteration": int(best["gen"]),
        "wins_both": bool(best["wins"]),
        "all_windows_win": bool(best["all_windows_win"]),
        "worst_window_usd_ratio": round(float(best["worst_window_usd"]),
                                        4),
        "worst_window_co2_ratio": round(float(best["worst_window_co2"]),
                                        4),
        "generations": args.generations,
        "seed": args.seed,
        "selection_scoreboard": {
            "rule": {k: float(rule_res[k]) for k in
                     ("usd_per_slo_hour", "g_co2_per_kreq",
                      "slo_attainment")},
            "teacher": {k: float(teacher_res[k]) for k in
                        ("usd_per_slo_hour", "g_co2_per_kreq",
                         "slo_attainment")},
            "ppo": {k: float(best["res"][k]) for k in
                    ("usd_per_slo_hour", "g_co2_per_kreq",
                     "slo_attainment")},
        },
    }
    path = save_params_npz(args.out, best["params"], meta=meta)
    rl.close(selected=best["name"], checkpoint=path)
    print(json.dumps({"checkpoint": path, **{k: v for k, v in meta.items()
                                             if k != "params"}}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
