"""Fixed-action zone probes: is single-region zone carbon monetizable?

The committed evidence for ARCHITECTURE.md §5's load-bearing negative
result (VERDICT r3 missing #4): on the single-region demo topology the
zone-to-zone carbon spread (~6%, same grid — the reference's static
`carbon.simulated=low|medium` labels were a stub for exactly this signal,
`demo_10_setup_configure.sh:61-62`) is too small for ANY zone-selection
policy to cut gCO₂/kreq without paying cost or attainment.

Method: paired evaluation (identical traces, identical world randomness)
of fixed zone-pinning actions — the strongest possible zone commitment a
policy could make — against the rule baseline, on full-day held-out
stochastic traces from the bench's scoring family:

- ``neutral``      — all zones open (demo_19 reset profile);
- ``pin:<zone>``   — all provisioning forced into one zone, for each zone
  (a *policy* can only mix these; if no pure pin monetizes carbon, no
  mixture monetizes more than the best pin's margin);
- ``carbon``       — the per-tick lowest-carbon zone follower with
  hysteresis (the multiregion flagship's teacher), as the adaptive
  upper-envelope probe.

A probe "monetizes" the spread if it wins carbon beyond eval noise
(co2 ratio < 1 − 2σ of the per-trace ratio spread) while holding cost
(usd ≤ 1) and attainment (≥ rule − 1e-3). The committed artifact
(`data/zone_spread_probe.json`) records every probe's ratios and the
verdict; re-running this script reproduces it.

Run from the repo root:
    python scripts/zone_spread_probe.py --out data/zone_spread_probe.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ccka_tpu.config import default_config  # noqa: E402
from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy  # noqa: E402
from ccka_tpu.policy.base import PolicyBackend  # noqa: E402
from ccka_tpu.sim.types import Action  # noqa: E402
from ccka_tpu.signals.synthetic import SyntheticSignalSource  # noqa: E402
from ccka_tpu.train.evaluate import evaluate_backend, heldout_traces  # noqa: E402

_ATTAIN_EPS = 1e-3


class FixedActionPolicy(PolicyBackend):
    """decide() returns one constant action — the pure-probe backend."""

    def __init__(self, action: Action, name: str):
        self._action = action
        self._name = name

    @property
    def name(self) -> str:  # PolicyBackend's display name
        return self._name

    def decide(self, state, exo, t):
        return self._action


def zone_pin_action(cluster, zone_index: int) -> Action:
    """Neutral profile with provisioning forced into one zone."""
    neutral = Action.neutral(cluster.n_pools, cluster.n_zones)
    w = jnp.zeros((cluster.n_pools, cluster.n_zones), jnp.float32)
    w = w.at[:, zone_index].set(1.0)
    return neutral._replace(zone_weight=w)


def run_probe(steps: int, n_traces: int, seed0: int) -> dict:
    cfg = default_config()
    cluster = cfg.cluster
    src = SyntheticSignalSource(cluster, cfg.workload, cfg.sim, cfg.signals)
    traces = heldout_traces(src, steps=steps, n=n_traces, seed0=seed0)

    # Measured zone carbon spread over the evaluation window.
    carbon = np.stack([np.asarray(tr.carbon_g_kwh) for tr in traces])
    zone_mean = carbon.mean(axis=(0, 1))          # [Z]
    spread = float(zone_mean.max() / zone_mean.min() - 1.0)

    backends: dict[str, PolicyBackend] = {
        "neutral": FixedActionPolicy(
            Action.neutral(cluster.n_pools, cluster.n_zones), "neutral"),
        "carbon": CarbonAwarePolicy(cluster),
    }
    for zi, zone in enumerate(cluster.zones):
        backends[f"pin:{zone}"] = FixedActionPolicy(
            zone_pin_action(cluster, zi), f"pin:{zone}")

    rule = evaluate_backend(cfg, RulePolicy(cluster), traces)
    probes = {}
    for name, backend in backends.items():
        res = evaluate_backend(cfg, backend, traces)
        usd = res["usd_per_slo_hour"] / max(rule["usd_per_slo_hour"], 1e-9)
        co2 = res["g_co2_per_kreq"] / max(rule["g_co2_per_kreq"], 1e-9)
        ratios = [a / max(b, 1e-9) for a, b in zip(
            res["per_trace"]["g_co2_per_kreq"],
            rule["per_trace"]["g_co2_per_kreq"])]
        noise = 2.0 * float(np.std(ratios)) if len(ratios) > 1 else 0.01
        monetizes = (co2 < 1.0 - noise
                     and usd <= 1.0
                     and res["slo_attainment"]
                     >= rule["slo_attainment"] - _ATTAIN_EPS)
        probes[name] = {
            "usd_ratio": round(usd, 4),
            "co2_ratio": round(co2, 4),
            "co2_ratio_per_trace": [round(r, 4) for r in ratios],
            "co2_noise_2sigma": round(noise, 4),
            "slo_attainment": round(res["slo_attainment"], 4),
            "monetizes_carbon": bool(monetizes),
        }
        print(f"# {name:>16}: usd x{usd:.4f} co2 x{co2:.4f} "
              f"attain {res['slo_attainment']:.4f}"
              f"{'  MONETIZES' if monetizes else ''}", file=sys.stderr)

    return {
        "config": "default (single-region)",
        "eval_steps": steps,
        "n_traces": n_traces,
        "seed0": seed0,
        "zone_carbon_mean_g_kwh": [round(float(v), 2) for v in zone_mean],
        "zone_carbon_spread": round(spread, 4),
        "rule": {
            "usd_per_slo_hour": round(rule["usd_per_slo_hour"], 4),
            "g_co2_per_kreq": round(rule["g_co2_per_kreq"], 4),
            "slo_attainment": round(rule["slo_attainment"], 4),
        },
        "probes": probes,
        "any_probe_monetizes_carbon": bool(
            any(p["monetizes_carbon"] for p in probes.values())),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=2880,
                    help="ticks per trace (2880 = one full day; shorter "
                         "windows never reach peak hours)")
    ap.add_argument("--traces", type=int, default=5)
    ap.add_argument("--seed0", type=int, default=10_000,
                    help="held-out seed block (bench scoring family)")
    ap.add_argument("--out", default="",
                    help="write the JSON artifact here (e.g. "
                         "data/zone_spread_probe.json)")
    args = ap.parse_args(argv)

    result = run_probe(args.steps, args.traces, args.seed0)
    text = json.dumps(result, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"# wrote {args.out}", file=sys.stderr)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
