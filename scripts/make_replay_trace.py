"""Generate the committed replay trace: data/replay_2day.npz.

BASELINE.json config #3 trains/scores "on replayed OpenCost/
ElectricityMaps traces". No AWS account exists in CI, so the repo ships a
deterministic 2-day trace with the *shape* of real feeds — built from a
different generative family than `signals/synthetic.py` (which is pure
sinusoid + AR(1)), so replay scores measure transfer, not memorization:

- demand: weekday double-peak (09:30 / 19:30 local) with a lunch dip,
  heavy-tailed flash-crowd bursts, and a quieter day 2;
- spot $/hr: per-zone mean-reverting walk around the m6i.large historical
  band (~$0.03) with capacity-crunch spikes during demand peaks — the
  price behavior `describe-spot-price-history` actually shows;
- carbon gCO2/kWh: CAISO-shaped duck curve (midday solar dip, steep
  evening ramp) with a cloud front on day 2 that halves the dip — the
  regime change a carbon-aware policy must react to;
- on-demand $/hr: flat per zone (od prices do not move intraday).

Deterministic (PCG64 seed 20260730); re-running this script reproduces
the committed artifact byte-for-byte (np.savez_compressed is
content-deterministic for fixed arrays).

Run from the repo root: ``python scripts/make_replay_trace.py``
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ccka_tpu.config import default_config  # noqa: E402
from ccka_tpu.signals.base import ExogenousTrace, TraceMeta, as_f32  # noqa: E402
from ccka_tpu.signals.replay import save_trace  # noqa: E402

SEED = 20260730
DAYS = 2
_DATA = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "data")
OUT = os.path.join(_DATA, "replay_2day.npz")
# Train variant: SAME generative family, different realization (seed) and
# longer (4 days) — the replay-family fine-tuning data
# (`scripts/train_replay_flagship.py`), so policies scored on the eval
# trace never trained on its exact windows, only on its family.
TRAIN_SEED = 20260731
TRAIN_DAYS = 6
OUT_TRAIN = os.path.join(_DATA, "replay_train_6day.npz")
# Round-5 long variants (VERDICT r4 next #2): a 5-day eval trace so the
# replay scoreboard gets >=5 day-scale windows (3 windows of the 2-day
# trace carried too little power to significance-gate a ~1% effect),
# and a 9-day training realization (6 train + 3 holdout days -> 5
# half-day-staggered selection windows).
EVAL5_SEED = 20260801
EVAL5_DAYS = 5
OUT_EVAL5 = os.path.join(_DATA, "replay_5day.npz")
TRAIN9_SEED = 20260802
TRAIN9_DAYS = 9
OUT_TRAIN9 = os.path.join(_DATA, "replay_train_9day.npz")


def build_trace(cfg, *, seed: int = SEED,
                days: int = DAYS) -> tuple[ExogenousTrace, TraceMeta]:
    rng = np.random.Generator(np.random.PCG64(seed))
    dt_s = cfg.sim.dt_s
    steps = int(days * 86400 / dt_s)
    z = cfg.cluster.n_zones
    t_hr = (np.arange(steps) * dt_s / 3600.0) % 24.0       # local hour
    day = (np.arange(steps) * dt_s // 86400).astype(int)    # 0, 1

    # -- demand: double peak + lunch dip + flash crowds -------------------
    total = cfg.workload.total_pods                          # 60-pod scale
    peak1 = np.exp(-0.5 * ((t_hr - 9.5) / 2.0) ** 2)
    peak2 = np.exp(-0.5 * ((t_hr - 19.5) / 2.5) ** 2)
    lunch_dip = 1.0 - 0.25 * np.exp(-0.5 * ((t_hr - 13.0) / 1.0) ** 2)
    base_level = 0.35 + 0.85 * np.maximum(peak1, peak2)
    base_level *= lunch_dip
    base_level *= np.where(day % 2 == 1, 0.8, 1.0)           # quieter alt days
    # Flash crowds: ~6 events/day, 10-30 min, 1.3-2x multiplier.
    burst = np.ones(steps)
    n_events = rng.poisson(6 * days)
    for _ in range(n_events):
        start = rng.integers(0, steps)
        dur = int(rng.integers(20, 60))                      # 10-30 min
        burst[start:start + dur] *= rng.uniform(1.3, 2.0)
    noise = np.exp(rng.normal(0.0, 0.06, size=steps))        # log-normal
    demand_total = total * base_level * burst * noise
    split = 0.55 + 0.05 * np.sin(2 * np.pi * t_hr / 24.0)    # class drift
    demand = np.stack([demand_total * split,
                       demand_total * (1.0 - split)], axis=-1)

    # -- spot prices: mean-reverting walk + crunch spikes -----------------
    nt = cfg.cluster.node_type
    mean_z = nt.spot_price_hr_mean * (1.0 + 0.08 * np.arange(z) / max(z - 1, 1)
                                      - 0.04)                # per-zone band
    spot = np.empty((steps, z))
    x = np.zeros(z)
    for i in range(steps):
        # OU step toward 0 (log-deviation), tick-scale vol.
        x += -0.02 * x + rng.normal(0.0, 0.015, size=z)
        crunch = 1.0 + 0.6 * max(base_level[i] - 1.0, 0.0)   # peak crunch
        spot[i] = mean_z * np.exp(x) * crunch
    # Occasional zone-local spot spikes (capacity reclaim events).
    for _ in range(rng.poisson(3 * days)):
        zi = rng.integers(0, z)
        start = rng.integers(0, steps)
        dur = int(rng.integers(10, 40))
        spot[start:start + dur, zi] *= rng.uniform(1.5, 2.4)
    spot = np.clip(spot, 0.2 * nt.od_price_hr, 0.95 * nt.od_price_hr)

    # -- on-demand: flat per zone -----------------------------------------
    od = np.tile(nt.od_price_hr * (1.0 + 0.01 * np.arange(z)), (steps, 1))

    # -- carbon: duck curve + cloudy day 2 --------------------------------
    base_c = 420.0
    solar = np.exp(-0.5 * ((t_hr - 12.5) / 2.8) ** 2)        # midday sun
    dip_depth = np.where(day % 2 == 1, 0.22, 0.45)           # clouds alt days
    evening_ramp = 0.18 * np.exp(-0.5 * ((t_hr - 19.0) / 1.5) ** 2)
    carbon_t = base_c * (1.0 - dip_depth * solar + evening_ramp)
    zone_off = 1.0 + 0.06 * (np.arange(z) / max(z - 1, 1) - 0.5)
    carbon = carbon_t[:, None] * zone_off[None, :]
    carbon += rng.normal(0.0, 6.0, size=(steps, z))          # metering noise
    carbon = np.clip(carbon, 80.0, None)

    is_peak = ((t_hr >= 9.0) & (t_hr < 21.0)).astype(np.float32)

    trace = ExogenousTrace(
        spot_price_hr=as_f32(spot), od_price_hr=as_f32(od),
        carbon_g_kwh=as_f32(carbon), demand_pods=as_f32(demand),
        is_peak=as_f32(is_peak))
    meta = TraceMeta(
        source="generated-replay",
        start_unix_s=0.0, dt_s=dt_s, zones=cfg.cluster.zones,
        description=(f"deterministic {days}-day replay trace, seed {seed} "
                     "(scripts/make_replay_trace.py): double-peak weekday "
                     "demand + flash crowds, OU spot walk + crunch "
                     "spikes, duck-curve carbon with cloudy alt days"))
    return trace, meta


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--variant", default="eval",
                    choices=("eval", "train", "eval5", "train9"),
                    help="eval: the round-4 scoring trace (seed "
                         f"{SEED}, {DAYS}d); train: the round-4 "
                         f"fine-tuning realization (seed {TRAIN_SEED}, "
                         f"{TRAIN_DAYS}d); eval5/train9: the round-5 "
                         "long variants (distinct seeds — a different "
                         "day count reshuffles the whole event stream, "
                         "so these are new realizations, not extensions)")
    args = ap.parse_args(argv)
    cfg = default_config()
    variants = {
        "eval": (SEED, DAYS, OUT),
        "train": (TRAIN_SEED, TRAIN_DAYS, OUT_TRAIN),
        "eval5": (EVAL5_SEED, EVAL5_DAYS, OUT_EVAL5),
        "train9": (TRAIN9_SEED, TRAIN9_DAYS, OUT_TRAIN9),
    }
    seed, days, out = variants[args.variant]
    trace, meta = build_trace(cfg, seed=seed, days=days)
    save_trace(out, trace, meta)
    print(f"wrote {out}: {trace.steps} steps x {cfg.cluster.n_zones} zones "
          f"({os.path.getsize(out) / 1024:.0f} KiB)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
