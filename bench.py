"""Performance benchmark harness — prints ONE machine-parseable JSON line.

Headline metric: simulated cluster-days/sec/chip for the batched rule-policy
rollout in stochastic mode (the BASELINE.json north-star measure; the
round-1 judge measured 3,781 at B=2048 on one v5e chip, and the v5e-8 goal
is >=10k across 8 chips). Sub-metrics: PPO iterations/sec at BASELINE
config #3 (256 clusters) and diff-MPC plans/sec.

Methodology: trace generation and compilation are setup (excluded), timed
regions are device-bound with `block_until_ready`; each config is timed over
several repeats and the best wall-clock is reported (standard for
throughput benches — the steady state is what a fleet controller sees).

Usage: ``python bench.py`` (full sweep, B up to 8192);
``python bench.py --quick`` (CI-sized).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import jax

if os.environ.get("CCKA_BENCH_FORCE_CPU") == "1":
    # Child process for the CPU-virtual mesh stage: the axon sitecustomize
    # pins jax_platforms at interpreter start, so the env var alone cannot
    # switch platforms — the live config must be updated before any
    # backend touch (same dance as tests/conftest.py).
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from ccka_tpu.obs.trace import SpanTracer

_JUDGE_R1_BASELINE = 3781.0  # cluster-days/sec/chip, judge round-1, B=2048

# One tracer for the whole bench process: every timed sample and every
# stage becomes a span, exported as a Perfetto-loadable Chrome trace at
# exit (--trace-out). The subprocess phases write their own files.
_TRACER = SpanTracer()

# How this harness times by default: every sample is forced synchronous
# (`block_until_ready` inside the timed callable), best-of-N over
# distinct-work repeats, samples below the roofline floor discarded.
TIMING_MODE = "forced_sync_best_of_n_roofline_gated"


def bench_provenance(*, timing_mode: str = TIMING_MODE,
                     mesh=None, scenarios=None) -> dict:
    """The context a headline needs to be auditable (VERDICT r5 weak #3:
    perf levers shipped with no published, gated wall-clock number —
    and the records that did exist carried no device/version/timing
    provenance). Stamped on every BENCH record. ``mesh``: the
    `jax.sharding.Mesh` a multi-chip stage ran on — its shape and axis
    sizes make multi-chip records self-describing (ISSUE 3); without
    one the field still records the visible device count.
    ``scenarios``: the named workload scenarios a stage swept
    (`ccka_tpu/workloads`) — stamped so scenario records name their own
    vocabulary."""
    import platform as _platform

    try:
        import jaxlib
        jaxlib_version = getattr(getattr(jaxlib, "version", None),
                                 "__version__", None)
    except ImportError:  # jaxlib always ships with jax, but stay honest
        jaxlib_version = None
    dev = jax.devices()[0]
    if mesh is not None:
        mesh_info = {"shape": {str(a): int(mesh.shape[a])
                               for a in mesh.axis_names},
                     "axis_names": [str(a) for a in mesh.axis_names],
                     "n_devices": int(np.prod(list(mesh.shape.values())))}
    else:
        mesh_info = {"shape": None, "axis_names": None,
                     "n_devices": len(jax.devices())}
    out = {
        "device_kind": dev.device_kind,
        "platform": dev.platform,
        "n_devices": len(jax.devices()),
        "mesh": mesh_info,
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib_version,
        "python_version": _platform.python_version(),
        "timing_mode": timing_mode,
        "roofline_floor": {
            "basis": "0.5 * bytes_touched / measured streaming bandwidth "
                     "(see _roofline_floor_s); static 2ms floor when a "
                     "stage cannot state its bytes",
            # None until the probe has run (it is lazy — first roofline-
            # floored timing triggers it); the probed value thereafter.
            "measured_bw_bytes_per_s": _HBM_BW_CACHE.get("bytes_per_s"),
        },
    }
    if scenarios is not None:
        out["scenarios"] = list(scenarios)
    return out


def _make_src(cfg):
    from ccka_tpu.signals.synthetic import SyntheticSignalSource

    return SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                 cfg.signals)


_HBM_BW_CACHE: dict = {}


def _measured_hbm_bandwidth() -> float:
    """Achievable streaming bandwidth (bytes/s) of the default device —
    ONE probe for the whole process, shared with `ccka perf`:
    `obs.costmodel.measured_stream_bandwidth` (best-of-5 distinct-scalar
    saxpy over a 128 MB operand, 2 TB/s ceiling on an implausible ~0s
    best). The bench-local cache mirrors it for `bench_provenance`'s
    roofline stamp; two diverging copies of the probe would make the two
    drivers disagree on the achieved fraction of the identical kernel."""
    if "bytes_per_s" not in _HBM_BW_CACHE:
        from ccka_tpu.obs.costmodel import measured_stream_bandwidth

        bw = measured_stream_bandwidth()
        _HBM_BW_CACHE["bytes_per_s"] = bw
        print(f"# hbm probe: {bw / 1e9:.0f} GB/s streaming "
              "(roofline floor basis)", file=sys.stderr)
    return _HBM_BW_CACHE["bytes_per_s"]


def _roofline_floor_s(bytes_touched: float) -> float:
    """Physical plausibility floor for a timed region that must move at
    least ``bytes_touched`` through device memory: bytes / measured
    bandwidth, halved (measured saxpy bandwidth can undershoot what a
    fused kernel streams, and a floor that over-rejects would silently
    drop honest rows). Any sample below this is physically impossible
    throughput — the VERDICT r5 weak-#2 hole: the old static 2 ms floor
    passed a 3.5 ms sample for a workload whose own docs quote ~11 ms."""
    return max(0.5 * bytes_touched / _measured_hbm_bandwidth(), 1e-4)


def _trace_row_bytes(cfg) -> int:
    """float32 bytes per (cluster, tick) of an ExogenousTrace: spot/od/
    carbon [Z] + demand [C=2] + is_peak [1] — the minimum a rollout
    streams per simulated step, before any state/metric traffic."""
    z = cfg.cluster.n_zones
    return 4 * (3 * z + 2 + 1)


def _time_best(fn, repeats: int = 3,
               *, bytes_touched: float = 0.0,
               min_valid_s: float | None = None,
               label: str = "timed") -> float | None:
    """Best-of-N wall timing with a roofline implausibility guard: under
    heavy host contention the tunnel-backed block_until_ready has been
    observed returning ~0s for work that takes hundreds of ms — a 0.000s
    sample would publish an absurd headline. The floor is derived from
    the work itself (``bytes_touched`` / measured HBM bandwidth, see
    :func:`_roofline_floor_s`) rather than a static 2 ms — a fixed floor
    both passed impossible samples for big workloads and would reject
    honest ones for small ones. Samples below the floor are discarded
    (with a note) and retried; if NOTHING valid remains the measurement
    is unusable and ``None`` is returned so the caller drops the row —
    round 4 observed even max(raw) at ~1ms for a 0.5s workload, so no
    raw sample is publishable in that state.

    Callers that cannot state their traffic (``bytes_touched`` omitted/0)
    keep the legacy static 2 ms floor rather than the 0.1 ms absolute
    minimum — the roofline floor must never be WEAKER than the guard it
    replaced."""
    if min_valid_s is not None:
        floor = min_valid_s
    elif bytes_touched > 0:
        floor = _roofline_floor_s(bytes_touched)
    else:
        floor = 2e-3
    samples = []
    attempts = 0
    while len(samples) < repeats and attempts < repeats * 3:
        attempts += 1
        # Every sample is a span in the bench Chrome trace (the callable
        # itself fences with block_until_ready — the span measures the
        # fenced work, and the trace shows exactly what was timed).
        with _TRACER.span(f"bench.{label}", sample=attempts,
                          floor_ms=round(floor * 1e3, 3)) as sp:
            fn()
        dt = sp.dur_s
        if dt >= floor:
            samples.append(dt)
        else:
            print(f"# discarding implausible {dt * 1e3:.3f}ms sample "
                  f"(< {floor * 1e3:.3f}ms roofline floor — host "
                  "contention / async-return?)", file=sys.stderr)
    if samples:
        return min(samples)
    print("# WARNING: no plausible timing sample; measurement dropped",
          file=sys.stderr)
    return None


def _megakernel_parity_gate(cfg, params, src, *, b: int = 8192,
                            steps: int = 2880) -> dict:
    # B=8192 x a full day: big enough that the rare-event counters'
    # paired shot noise drops to ~0.9% relative se, so the z>4
    # significance filter still DETECTS biases near the 3% tolerance
    # (at B=2048 x 960 the se is ~4% and the z-gate would let ~16%
    # biases pass — the tolerance would be dead letter). The gate runs
    # in an isolated child process, so the memory cost is contained.
    """Inline statistical-parity gate (VERDICT r3 #2): the Pallas
    megakernel may carry the headline ONLY if its batch-mean KPIs match
    the lax path on every EpisodeSummary field, on this machine, in this
    run. The full gate (interpret-exact + both modes) lives in
    `tests/test_megakernel.py`; this is the belt-and-suspenders check at
    bench time."""
    from ccka_tpu.policy import RulePolicy
    from ccka_tpu.policy.rule import offpeak_action, peak_action
    from ccka_tpu.sim import batched_rollout_summary, initial_state
    from ccka_tpu.sim.megakernel import (megakernel_rollout_summary,
                                         mean_parity_violations)

    traces = src.batch_trace_device(steps, jax.random.key(23), b)
    off = offpeak_action(cfg.cluster)
    peak = peak_action(cfg.cluster)
    sk = megakernel_rollout_summary(params, off, peak, traces, seed=9,
                                    stochastic=True)
    states = jax.tree.map(lambda x: jnp.broadcast_to(x, (b,) + x.shape),
                          initial_state(cfg))
    keys = jax.random.split(jax.random.key(0), b)
    _, sl = batched_rollout_summary(
        params, states, RulePolicy(cfg.cluster).action_fn(), traces, keys,
        stochastic=True)
    bad = mean_parity_violations(sk, sl)
    # The plan-playback entry rides the SAME gate (ISSUE 4): a
    # per-cluster plan replaying the rule profile selection per
    # (cluster, tick) must match the lax rule rollout under the one
    # shared tolerance table, same seed → paired with the profile
    # kernel's draws.
    from ccka_tpu.sim.megakernel import plan_megakernel_rollout_summary

    is_peak = traces.is_peak > 0.5                       # [b, steps]
    rule_plan = jax.tree.map(
        lambda o, p: jnp.where(
            is_peak.reshape(is_peak.shape + (1,) * o.ndim), p, o),
        off, peak)
    sp = plan_megakernel_rollout_summary(params, rule_plan, traces,
                                         seed=9, stochastic=True)
    bad_plan = mean_parity_violations(sp, sl)
    out = {"ok": not bad and not bad_plan, "b": b, "steps": steps,
           "plan_playback_ok": not bad_plan}
    if bad or bad_plan:
        out["failed_fields"] = dict(bad, **{f"plan:{k}": v
                                            for k, v in bad_plan.items()})
        print(f"# megakernel parity gate FAILED: {out['failed_fields']} — "
              "kernel excluded from the headline", file=sys.stderr)
    else:
        print("# megakernel parity gate ok (profile + plan playback)",
              file=sys.stderr)
    return out


def bench_rollout(cfg, batch_sizes, horizon_steps: int, repeats: int,
                  summary_batch_sizes=(), mega_batch_sizes=(),
                  mega_gate: str = "subprocess",
                  mega_trace_out: str = "") -> dict:
    """Batched rollout sweep. ``batch_sizes`` use the metric-stacking path
    (per-tick StepMetrics over the horizon); ``summary_batch_sizes`` use
    the O(B)-memory summarize-in-scan path; ``mega_batch_sizes`` use the
    Pallas megakernel (`sim/megakernel.py`) — gated on a statistical-
    parity check against the lax path, without which its rows are
    dropped and cannot carry the headline.

    ``mega_gate``: "subprocess" (default — gate AND kernel timing each
    run in their own isolated child process: the tunneled backend does
    not reliably reclaim the kernel path's ~11 GB, so anything sharing
    its process degrades or RESOURCE_EXHAUSTs), "inline" (gate after
    the sweep in-process), or "skip" (no gate — ONLY for the timing
    child, whose parent already gated).
    """
    from ccka_tpu.policy import RulePolicy
    from ccka_tpu.policy.rule import offpeak_action, peak_action
    from ccka_tpu.sim import (SimParams, batched_rollout,
                              batched_rollout_summary, initial_state)
    from ccka_tpu.sim.megakernel import megakernel_rollout_summary

    from ccka_tpu.obs.compile import watch_jit

    params = SimParams.from_config(cfg)
    src = _make_src(cfg)
    action_fn = RulePolicy(cfg.cluster).action_fn()
    off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
    days_per_traj = horizon_steps * cfg.sim.dt_s / 86400.0

    # Compile-watched (obs/compile.py): one compile per batch size is the
    # budget; a recompile on a REPEAT of the same shape would mean the
    # timed region silently includes tracing+XLA time — exactly the kind
    # of contamination the methodology note above excludes.
    run_metrics = watch_jit(
        jax.jit(lambda s, tr, k: batched_rollout(
            params, s, action_fn, tr, k, stochastic=True)),
        "bench.rollout_metrics", hot=True,
        warmup_compiles=max(len(batch_sizes), 1))
    run_summary = watch_jit(
        jax.jit(lambda s, tr, k: batched_rollout_summary(
            params, s, action_fn, tr, k, stochastic=True)),
        "bench.rollout_summary", hot=True,
        warmup_compiles=max(len(summary_batch_sizes), 1))

    results = {}
    mega_local = []
    if mega_batch_sizes and horizon_steps < 960:
        # Below the gate's calibration floor (rare-event shot noise
        # dominates): don't pretend to gate — skip the kernel rows.
        parity = {"ok": False,
                  "skipped": f"horizon {horizon_steps} < 960-step gate "
                             "calibration floor (quick mode)"}
        print(f"# megakernel gate skipped: {parity['skipped']}",
              file=sys.stderr)
        results["megakernel_parity"] = parity
    elif mega_batch_sizes and mega_gate == "subprocess":
        sub = _mega_subprocess(mega_batch_sizes, horizon_steps, repeats,
                               trace_out=mega_trace_out)
        if sub:
            results.update(sub)
        else:
            # The recorded-reason contract holds even when the child
            # itself died (timeout, OOM-kill, import error).
            results["megakernel_parity"] = {
                "ok": False, "error": "mega child process failed"}
    elif mega_batch_sizes:
        # Kernel rows are timed FIRST on the fresh heap; an "inline"
        # parity gate runs AFTER the sweep (below) — its allocations
        # degrade the timed path, and gate validity doesn't depend on
        # heap state. Rows are dropped post-hoc if it fails.
        mega_local = [(b, "mega") for b in mega_batch_sizes]

    sweep = (mega_local
             + [(b, "metrics") for b in batch_sizes]
             + [(b, "summary") for b in summary_batch_sizes])
    for b, mode in sweep:
        key = f"{b}:{mode}"
        # Per-row guard: one OOM (e.g. the B=64k packed-exo row on a
        # smaller-HBM part) must not kill the stages that follow.
        try:
            # Device-side synthesis: setup stays off the host at B=32768.
            traces = src.batch_trace_device(horizon_steps,
                                            jax.random.key(7), b)
            states = jax.tree.map(
                lambda x: jnp.broadcast_to(x, (b,) + x.shape),
                initial_state(cfg))
            states, traces = jax.device_put((states, traces))
            # Every timed call gets a DISTINCT world key: the tunneled
            # backend has been observed short-circuiting byte-identical
            # repeat requests to ~0s (the implausible-sample pathology),
            # so repeats must be genuinely different work.
            n_calls = 3 * repeats + 2
            key_variants = [jax.random.split(jax.random.key(1000 + i), b)
                            for i in range(n_calls)]
            call_i = [0]

            if mode == "mega":
                def once():
                    call_i[0] += 1
                    s = megakernel_rollout_summary(
                        params, off, peak, traces, seed=call_i[0],
                        stochastic=True)
                    jax.block_until_ready(s.cost_usd)
            else:
                run = run_summary if mode == "summary" else run_metrics

                def once():
                    k = key_variants[call_i[0] % n_calls]
                    call_i[0] += 1
                    final, _ = run(states, traces, k)
                    jax.block_until_ready(final)

            once()  # compile
            # Roofline bytes: one full read of the exo trace batch is the
            # irreducible traffic of any rollout mode (state/metrics add
            # more; a lower bound is what a floor needs).
            row_bytes = float(b) * horizon_steps * _trace_row_bytes(cfg)
            dt = _time_best(once, repeats, bytes_touched=row_bytes,
                            label=f"rollout.{key}")
        except Exception as e:  # noqa: BLE001
            print(f"# rollout B={b} [{mode}] failed (skipped): "
                  f"{repr(e)[:160]}", file=sys.stderr)
            continue
        if dt is None:
            print(f"# rollout B={b} [{mode}]: no plausible timing — "
                  "row dropped", file=sys.stderr)
            continue
        results[key] = {
            "batch": b,
            "seconds": dt,
            "mode": mode,
            "cluster_days_per_sec": b * days_per_traj / dt,
            "cluster_steps_per_sec": b * horizon_steps / dt,
            # Provenance: the plausibility floor THIS row's samples had
            # to clear (auditable against `seconds`).
            "roofline_floor_ms": round(_roofline_floor_s(row_bytes) * 1e3,
                                       3),
        }
        print(f"# rollout B={b} [{mode}]: {dt:.3f}s -> "
              f"{results[key]['cluster_days_per_sec']:,.0f} cluster-days/sec",
              file=sys.stderr)
        del traces, states, key_variants

    if mega_local and mega_gate == "inline":
        try:
            parity = _megakernel_parity_gate(
                cfg, params, src, b=min(8192, max(mega_batch_sizes)),
                steps=min(2880, max(horizon_steps, 960)))
        except Exception as e:  # noqa: BLE001 — drop rows, bench lives
            print(f"# megakernel parity gate errored: {e!r}",
                  file=sys.stderr)
            parity = {"ok": False, "error": repr(e)[:200]}
        results["megakernel_parity"] = parity
        if not parity["ok"]:
            for b, _mode in mega_local:
                results.pop(f"{b}:mega", None)
            print("# megakernel rows DROPPED (gate failed)",
                  file=sys.stderr)
    return results


def bench_ppo(cfg, iterations: int) -> dict:
    from ccka_tpu.train.ppo import PPOTrainer

    trainer = PPOTrainer(cfg)
    src = _make_src(cfg)
    ts = trainer.init_state()  # includes net-init compile (one-off)
    w = trainer.make_windows(src, iterations + 1, seed=999)  # warm compile
    jax.block_until_ready(w.spot_price_hr)
    t0 = time.perf_counter()
    windows = trainer.make_windows(src, iterations + 1, seed=1000)
    jax.block_until_ready(windows.spot_price_hr)
    t_trace = time.perf_counter() - t0

    t_len = cfg.train.unroll_steps
    ts, _ = trainer._iteration_fn(
        ts, windows.slice_steps(0, t_len + 1))  # compile
    jax.block_until_ready(ts.params)

    t0 = time.perf_counter()
    for it in range(1, iterations + 1):
        ts, diag = trainer._iteration_fn(
            ts, windows.slice_steps(it * t_len, t_len + 1))
    jax.block_until_ready(ts.params)
    dt = time.perf_counter() - t0

    b = cfg.train.batch_clusters
    out = {
        "iterations_per_sec": iterations / dt,
        "env_steps_per_sec": iterations * b * t_len / dt,
        "trace_gen_seconds": t_trace,
        "train_seconds": dt,
        # VERDICT item 6: end-to-end wall (host trace gen + train) must stay
        # within ~2x of device-bound train time. Compile time excluded (the
        # one-off XLA cost, cached across runs).
        "wall_over_device": (t_trace + dt) / dt,
    }
    print(f"# ppo B={b}: {out['iterations_per_sec']:.2f} it/s, "
          f"{out['env_steps_per_sec']:,.0f} env-steps/s, "
          f"wall/device={out['wall_over_device']:.2f}", file=sys.stderr)
    return out


def bench_mpc(cfg, plans: int, fleet_batch: int = 256) -> dict:
    from ccka_tpu.models import action_to_latent
    from ccka_tpu.policy.rule import neutral_action
    from ccka_tpu.sim import SimParams, initial_state
    from ccka_tpu.train.mpc import optimize_plan, optimize_plan_batch

    params = SimParams.from_config(cfg)
    src = _make_src(cfg)
    h = cfg.train.mpc_horizon
    trace = src.trace(h, seed=0)
    state0 = initial_state(cfg)
    base = action_to_latent(neutral_action(cfg.cluster), cfg.cluster)
    latent0 = jnp.broadcast_to(base, (h,) + base.shape)

    def once():
        r = optimize_plan(params, cfg.cluster, cfg.train, state0, trace,
                          latent0, iters=cfg.train.mpc_iters)
        jax.block_until_ready(r.plan_latent)

    once()  # compile

    def plan_round():
        for _ in range(plans):
            once()

    # Roofline bytes: each Adam iteration re-streams the H-step window
    # (forward + backward), `plans` sequential plans per round.
    plan_bytes = (float(plans) * cfg.train.mpc_iters * 2
                  * h * _trace_row_bytes(cfg))
    dt = _time_best(plan_round, repeats=2, bytes_touched=plan_bytes,
                    label="mpc.plans")
    out = {"horizon": h, "iters": cfg.train.mpc_iters}
    if dt is not None:
        out["plans_per_sec"] = plans / dt
        print(f"# mpc: {out['plans_per_sec']:.1f} plans/s "
              f"(H={h}, {cfg.train.mpc_iters} Adam iters)", file=sys.stderr)

    # Fleet-scale receding-horizon planning: vmap'd optimize_plan over a
    # cluster batch — the batched analog that closes the single-plan
    # throughput gap to fleet control (VERDICT r2 weak #7).
    b = fleet_batch
    states = jax.tree.map(lambda x: jnp.broadcast_to(x, (b,) + x.shape),
                          state0)
    traces = src.batch_trace_device(h, jax.random.key(3), b)
    lat_b = jnp.broadcast_to(latent0, (b,) + latent0.shape)

    def once_batch():
        r = optimize_plan_batch(params, cfg.cluster, cfg.train, states,
                                traces, lat_b, iters=cfg.train.mpc_iters)
        jax.block_until_ready(r.plan_latent)

    once_batch()  # compile
    reps = max(1, plans // 4)

    def batch_round():
        for _ in range(reps):
            once_batch()

    # Same implausibility guard as the rollout timings (a near-zero
    # contended sample would publish an absurd fleet-plans/sec).
    dt_b = _time_best(batch_round, repeats=2,
                      bytes_touched=float(b) * reps * cfg.train.mpc_iters
                      * 2 * h * _trace_row_bytes(cfg),
                      label="mpc.fleet_plans")
    out["fleet_batch"] = b
    if dt_b is not None:
        out["fleet_plans_per_sec"] = b * reps / dt_b
        print(f"# mpc fleet: {out['fleet_plans_per_sec']:,.0f} plans/s "
              f"(B={b} vmap'd)", file=sys.stderr)
    try:
        out["playback"] = _bench_mpc_playback(cfg, params, src, latent0)
    except Exception as e:  # noqa: BLE001 — kernel stage must not kill
        print(f"# mpc playback stage failed (omitted): {e!r}",
              file=sys.stderr)
    return out


def _bench_mpc_playback(cfg, params, src, latent0) -> dict:
    """MPC execution on the plan-playback megakernel (ISSUE 4): the
    quick planner's receding-horizon plan, tiled into a PER-CLUSTER
    packed plan stream and executed/scored by the fused kernel —
    kernel-scored cluster-days/sec with the same roofline-floor gating
    as the rollout rows (plan + exo stream traffic both counted). On a
    TPU host this is the Mosaic kernel in stochastic mode; elsewhere it
    runs interpret-mode deterministic at CI sizes (labeled — validates
    the path and records an honest small number, not a headline)."""
    import math as _math

    from ccka_tpu.models import latent_to_action
    from ccka_tpu.sim import initial_state
    from ccka_tpu.sim.megakernel import (
        _plan_rows, pack_plan, plan_megakernel_summary_from_packed)
    from ccka_tpu.train.mpc import receding_horizon_plan

    on_tpu = jax.default_backend() == "tpu"
    steps = 2880 if on_tpu else 96
    b = 16384 if on_tpu else 256
    t_chunk = 64 if on_tpu else 32
    b_block = min(512, b)
    days = steps * cfg.sim.dt_s / 86400.0
    T_pad = _math.ceil(steps / t_chunk) * t_chunk

    # Plan on the lax path: the quick planner (the flag-carrying
    # scoreboard's settings) over one representative trace.
    quick = dict(horizon=8, replan_every=8, iters=2)
    lat_seq = receding_horizon_plan(
        params, cfg.cluster, cfg.train, initial_state(cfg),
        src.trace(steps, seed=11), latent0[:quick["horizon"]], **quick)
    actions = jax.vmap(lambda u: latent_to_action(u, cfg.cluster))(lat_seq)
    plan2d = pack_plan(actions, T_pad)                  # [T_pad, rows]
    pr = _plan_rows(cfg.cluster.n_pools, cfg.cluster.n_zones)
    # Per-cluster layout (the scoreboard's real traffic shape), tiled on
    # device — playback throughput does not depend on plan CONTENT, and
    # the stream the kernel reads is a genuine [T_pad, rows, B] buffer.
    plan_stream = jax.jit(
        lambda q: jnp.broadcast_to(q[:, :, None], (T_pad, pr, b)))(plan2d)
    jax.block_until_ready(plan_stream)

    kw = dict(stochastic=on_tpu, b_block=b_block, t_chunk=t_chunk,
              interpret=not on_tpu)
    state = {"stream": src.packed_trace_device(steps, jax.random.key(29),
                                               b, t_chunk=t_chunk),
             "seed": 0}

    def once():
        # Donation ping-pong on the EXO stream (the plan is reused —
        # one plan scored against fresh worlds every repeat).
        state["seed"] += 1
        s, dead = plan_megakernel_summary_from_packed(
            params, cfg.cluster, plan_stream, state["stream"], steps,
            seed=state["seed"], donate_stream=True, **kw)
        jax.block_until_ready(s.cost_usd)
        state["stream"] = src.packed_trace_device(
            steps, jax.random.key(200 + state["seed"]), b,
            t_chunk=t_chunk, recycle=dead)

    once()  # compile
    row_bytes = float(b) * steps * (_trace_row_bytes(cfg) + 4 * pr)
    dt = _time_best(once, repeats=2, bytes_touched=row_bytes,
                    label="mpc.playback")
    row = {
        "engine": "plan_playback_megakernel(packed per-cluster plan, "
                  "donated exo stream)",
        "planner": dict(quick, mode="lax_quick_plan"),
        "batch": b, "steps": steps, "b_block": b_block,
        "t_chunk": t_chunk,
        "stochastic": on_tpu, "interpret": not on_tpu,
    }
    if dt is not None:
        row["seconds"] = round(dt, 4)
        row["cluster_days_per_sec"] = round(b * days / dt, 1)
        row["roofline_floor_ms"] = round(
            _roofline_floor_s(row_bytes) * 1e3, 3)
        print(f"# mpc playback: {row['cluster_days_per_sec']:,.0f} "
              f"kernel-scored cluster-days/s (B={b}, T={steps}"
              f"{', INTERPRET' if not on_tpu else ''})", file=sys.stderr)
    return row


def bench_fleet(cfg, n_clusters: int, ticks: int) -> dict:
    """Fleet control (BASELINE #5): one batched on-device decide over N
    cluster states fanning out to N dry-run sinks per tick, pipelined so
    the device chain rides under host actuation. Reports the full tick
    rate, the host-blocked/host-fanout split, and a separately-measured
    pure device-chain rate (``decide_ms`` is host time *blocked* on
    device work — near zero when pipelining hides the chain — so device
    throughput must not be derived from it)."""
    from ccka_tpu.harness.fleet import fleet_controller_from_config
    from ccka_tpu.policy import RulePolicy

    ctrl = fleet_controller_from_config(
        cfg, RulePolicy(cfg.cluster), n_clusters,
        horizon_ticks=2 * ticks + 4)
    ctrl.tick(0)  # compile
    t0 = time.perf_counter()
    reports = ctrl.run(ticks, start_tick=1)
    dt = time.perf_counter() - t0
    decide_ms = float(np.mean([r.decide_ms for r in reports]))
    fanout_ms = float(np.mean([r.fanout_ms for r in reports]))

    # Pure device chain: K chained decide+estimate dispatches, one block.
    t0 = time.perf_counter()
    chain = [ctrl._dispatch(t) for t in range(ticks + 1, 2 * ticks + 1)]
    jax.block_until_ready(chain[-1].packed)
    dt_chain = max(time.perf_counter() - t0, 1e-9)
    ctrl.close()

    out = {
        "clusters": n_clusters,
        "ticks_per_sec": ticks / dt,
        "cluster_ticks_per_sec": n_clusters * ticks / dt,
        "decide_blocked_ms": decide_ms,
        "fanout_ms": fanout_ms,
        # Device-side decide throughput, measured as its own chain (the
        # part that scales on TPU; fan-out is parallel host work).
        "decide_cluster_ticks_per_sec": n_clusters * ticks / dt_chain,
    }
    print(f"# fleet N={n_clusters}: {out['ticks_per_sec']:.2f} ticks/s "
          f"({out['cluster_ticks_per_sec']:,.0f} cluster-ticks/s; blocked "
          f"{decide_ms:.1f}ms, fanout {fanout_ms:.1f}ms, device chain "
          f"{out['decide_cluster_ticks_per_sec']:,.0f} cluster-ticks/s)",
          file=sys.stderr)
    return out


def _flag_wins(section: dict, rule_row: dict) -> None:
    """Stamp the win flags on every learned/hand-coded row of a
    scoreboard section — ONE criterion for synthetic, multiregion and
    replay scoreboards alike.

    `beats_rule_both_headlines` is SIGNIFICANCE-GATED (VERDICT r4 weak
    #2 / next #3): each headline's paired per-trace ratio mean must
    clear 1.0 by two standard errors (mean + 2·se < 1.0), so an exact
    tie or a noise-level mean can never publish as a win (which also
    closes the ADVICE r4 tie-counts-as-beats hole). The raw criterion
    the flag used through round 4 survives as
    `matches_or_beats_rule_raw` for continuity."""
    names = ("ppo", "ppo_frontier", "mpc", "carbon") + tuple(
        n for n in section if isinstance(n, str) and n.startswith("mpc_")
        and isinstance(section.get(n), dict)
        and "slo_attainment" in section[n])
    for name in names:
        if name not in section:
            continue
        r = section[name]
        attain_ok = (r["slo_attainment"]
                     >= rule_row["slo_attainment"] - 1e-3)
        raw = (r.get("vs_rule_usd_per_slo_hour", 9) <= 1.0
               and r.get("vs_rule_g_co2_per_kreq", 9) <= 1.0
               and attain_ok)
        r["matches_or_beats_rule_raw"] = bool(raw)

        def sig_win(k: str) -> bool:
            win = r.get(f"vs_rule_{k}_win2se")
            if win is not None:
                return win
            # Single-trace sections carry no spread; fall back to a
            # strict raw improvement and say so in the flag name below.
            return r.get(f"vs_rule_{k}", 9) < 1.0

        gated = all(f"vs_rule_{k}_win2se" in r
                    for k in ("usd_per_slo_hour", "g_co2_per_kreq"))
        # The published headline is the ratio of AGGREGATES; the gate
        # additionally requires it <= 1.0 so the flag can never sit next
        # to a >1.0x headline (a heavy-trace loss can flip the aggregate
        # while the per-trace mean still clears the CI).
        wins = (sig_win("usd_per_slo_hour") and sig_win("g_co2_per_kreq")
                and raw and attain_ok)
        r["beats_rule_both_headlines"] = bool(wins)
        r["win_flag_significance_gated"] = bool(gated)


# The MPC evidence standard (ISSUE 4): every PUBLISHED MPC
# `beats_rule_both_headlines` flag rests on win2se at >= this many
# KERNEL-paired traces (bench_quality_mega's plan-playback row). The
# lax stages keep their raw ratios and paired statistics but defer the
# flag — at their trace counts the 2-se machinery has no power against
# the ~1% effects the flag claims.
MPC_FLAG_MIN_TRACES = 256


def _defer_mpc_flags(section: dict) -> None:
    """Null the headline win flag on every lax-stage MPC row, recording
    where the flag now lives. `matches_or_beats_rule_raw` and the
    paired-ratio statistics stay — they are evidence, just not the
    flag."""
    note = (f"deferred: MPC flags publish only from the kernel-paired "
            f"n>={MPC_FLAG_MIN_TRACES} plan-playback stage "
            "(quality_mega.mpc)")
    for name, r in section.items():
        if not isinstance(r, dict) or "beats_rule_both_headlines" not in r:
            continue
        if name == "mpc" or name.startswith("mpc_"):
            r["beats_rule_both_headlines"] = None
            r["headline_flag"] = note


def bench_mesh(cfg, *, batch: int = 8192, steps: int = 480,
               repeats: int = 3) -> dict | None:
    """Multi-device throughput (VERDICT r3 weak #8): the sharded
    summarize-in-scan rollout over the full device mesh, reported as
    aggregate + per-device rates. Runs whenever more than one device is
    visible — real chips, or the CPU-virtual mesh under
    ``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
    (labeled as such; virtual-CPU numbers validate scaling shape, not
    absolute speed). Single-device hosts report None (the single-chip
    number IS the headline)."""
    n_dev = len(jax.devices())
    if n_dev < 2:
        print("# mesh: single device — skipped (headline is the "
              "single-chip number)", file=sys.stderr)
        return None
    from ccka_tpu.parallel import (make_mesh,
                                   sharded_batched_rollout_summary)
    from ccka_tpu.policy import RulePolicy
    from ccka_tpu.sim import SimParams, initial_state

    mesh = make_mesh(cfg.mesh)  # data_parallel=-1: all devices
    params = SimParams.from_config(cfg)
    src = _make_src(cfg)
    action_fn = RulePolicy(cfg.cluster).action_fn()
    b = (batch // n_dev) * n_dev
    traces = src.batch_trace_device(steps, jax.random.key(7), b)
    states = jax.tree.map(lambda x: jnp.broadcast_to(x, (b,) + x.shape),
                          initial_state(cfg))
    keys = jax.random.split(jax.random.key(0), b)
    days = steps * cfg.sim.dt_s / 86400.0

    def once():
        _, s = sharded_batched_rollout_summary(
            mesh, params, states, action_fn, traces, keys, stochastic=True)
        jax.block_until_ready(s.cost_usd)

    once()  # compile
    # Aggregate roofline over the mesh: each device streams its shard.
    dt = _time_best(once, repeats,
                    bytes_touched=float(b) * steps
                    * _trace_row_bytes(cfg) / n_dev,
                    label="mesh.rollout")
    if dt is None:
        print("# mesh: no plausible timing — stage dropped",
              file=sys.stderr)
        return None
    platform = jax.devices()[0].platform
    out = {
        "devices": n_dev,
        "platform": platform,
        "virtual_cpu_mesh": platform == "cpu",
        "batch": b,
        "steps": steps,
        "seconds": round(dt, 4),
        "cluster_days_per_sec_aggregate": round(b * days / dt, 1),
        "cluster_days_per_sec_per_device": round(b * days / dt / n_dev, 1),
    }
    print(f"# mesh {n_dev}x{platform}: {out['cluster_days_per_sec_aggregate']:,.0f} "
          f"cluster-days/s aggregate "
          f"({out['cluster_days_per_sec_per_device']:,.0f}/device"
          f"{', VIRTUAL CPU' if out['virtual_cpu_mesh'] else ''})",
          file=sys.stderr)
    return out


def bench_multichip(cfg, *, steps: int | None = None,
                    per_device_batch: int | None = None,
                    repeats: int | None = None,
                    shard_counts=(1, 2, 4, 8)) -> dict | None:
    """Multi-chip MEGAKERNEL throughput (ISSUE 3 tentpole): the sharded
    packed pipeline (`parallel/sharded_kernel.py` — shard-local trace
    synthesis → sharded Pallas launch → per-shard finalize) timed as a
    weak-scaling sweep: per-device batch fixed, shard count rising.
    Reports per-chip and aggregate cluster-days/sec per row, with the
    roofline floor scaled to SHARD bytes (each chip streams only its own
    exo block — the floor a row's samples must clear is per-shard
    traffic over measured bandwidth, not the global batch's).

    On a multi-TPU host this is the Mosaic kernel in stochastic mode; on
    a single-device host the caller falls back to a child process on the
    8-device virtual CPU mesh, where the kernel runs in INTERPRET mode,
    deterministic (the pltpu PRNG only lowers on real TPUs) — those rows
    are labeled ``virtual_cpu_mesh`` + ``interpret`` and validate
    sharding/scaling shape, not absolute speed. Every repeat donates the
    stream through the launch and recycles it into the next repeat's
    synthesis — back-to-back rounds hold ONE stream per chip, and the
    stage asserts jax raised no 'donated buffers were not usable'
    warning (the donation satellite's gate).
    """
    import warnings as _warnings

    n_dev = len(jax.devices())
    if n_dev < 2:
        print("# multichip: single device — skipped (virtual-mesh child "
              "carries the stage)", file=sys.stderr)
        return None
    from ccka_tpu.config import MeshConfig
    from ccka_tpu.parallel import (make_mesh,
                                   sharded_megakernel_summary_from_packed,
                                   sharded_packed_trace)
    from ccka_tpu.policy.rule import offpeak_action, peak_action
    from ccka_tpu.sim import SimParams

    platform = jax.devices()[0].platform
    virtual = platform == "cpu"
    # CPU virtual mesh: interpret-mode kernel — keep shapes small enough
    # that an 8-shard sweep finishes in ~a minute of interpreter time.
    if steps is None:
        steps = 96 if virtual else 2880
    if per_device_batch is None:
        per_device_batch = 64 if virtual else 4096
    if repeats is None:
        repeats = 2 if virtual else 3
    b_block = min(512, per_device_batch)
    t_chunk = 32 if virtual else 64
    params = SimParams.from_config(cfg)
    src = _make_src(cfg)
    off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
    days = steps * cfg.sim.dt_s / 86400.0
    shard_bytes = float(per_device_batch) * steps * _trace_row_bytes(cfg)
    kernel_kw = dict(stochastic=not virtual, b_block=b_block,
                     t_chunk=t_chunk, interpret=virtual)

    mesh8 = None
    rows = {}
    donation_msgs: list[str] = []
    with _warnings.catch_warnings(record=True) as wlist:
        _warnings.simplefilter("always")
        for n in [c for c in sorted(set(shard_counts)) if c <= n_dev]:
            mesh = make_mesh(MeshConfig(data_parallel=n),
                             devices=jax.devices()[:n])
            B = per_device_batch * n
            try:
                state = {"stream": sharded_packed_trace(
                    mesh, src, steps, jax.random.key(7), B,
                    t_chunk=t_chunk), "seed": 0}

                def once():
                    # Donation ping-pong: consume the stream, get the
                    # aliased buffer back, resynthesize the next world
                    # batch into it — every repeat is genuinely
                    # different work on a single resident stream.
                    state["seed"] += 1
                    s, dead = sharded_megakernel_summary_from_packed(
                        mesh, params, off, peak, state["stream"], steps,
                        seed=state["seed"], donate_stream=True,
                        **kernel_kw)
                    jax.block_until_ready(s.cost_usd)
                    state["stream"] = sharded_packed_trace(
                        mesh, src, steps,
                        jax.random.key(100 + state["seed"]), B,
                        t_chunk=t_chunk, recycle=dead)

                once()  # compile
                dt = _time_best(once, repeats, bytes_touched=shard_bytes,
                                label=f"multichip.{n}dev")
            except Exception as e:  # noqa: BLE001 — per-row guard
                print(f"# multichip n={n} failed (skipped): "
                      f"{repr(e)[:160]}", file=sys.stderr)
                continue
            if dt is None:
                continue
            # Provenance mesh = the largest mesh that actually PRODUCED
            # a row (an OOM'd 8dev attempt must not label 4dev rows).
            mesh8 = mesh
            rows[f"{n}dev"] = {
                "devices": n,
                "batch": B,
                "per_device_batch": per_device_batch,
                "seconds": round(dt, 4),
                "cluster_days_per_sec_aggregate": round(B * days / dt, 1),
                "cluster_days_per_sec_per_device": round(
                    B * days / dt / n, 1),
                "roofline_floor_ms_per_shard": round(
                    _roofline_floor_s(shard_bytes) * 1e3, 3),
            }
            print(f"# multichip {n}x{platform}: "
                  f"{rows[f'{n}dev']['cluster_days_per_sec_aggregate']:,.0f} "
                  "cluster-days/s aggregate "
                  f"({rows[f'{n}dev']['cluster_days_per_sec_per_device']:,.0f}"
                  f"/device{', VIRTUAL+INTERPRET' if virtual else ''})",
                  file=sys.stderr)
        donation_msgs = [str(m.message) for m in wlist
                         if "donated" in str(m.message).lower()]
        # catch_warnings swallows EVERYTHING in the block — re-surface
        # what the donation filter did not claim, or a sharding/overflow
        # warning that explains a dropped row would vanish here.
        for m in wlist:
            if "donated" not in str(m.message).lower():
                print(f"# multichip warning: {m.category.__name__}: "
                      f"{str(m.message)[:200]}", file=sys.stderr)

    playback = None
    if mesh8 is not None:
        try:
            playback = _multichip_plan_playback(
                cfg, params, src, mesh8, steps=steps,
                per_device_batch=per_device_batch, b_block=b_block,
                t_chunk=t_chunk, repeats=repeats, virtual=virtual)
        except Exception as e:  # noqa: BLE001 — row guard
            print(f"# multichip plan-playback failed (skipped): "
                  f"{repr(e)[:160]}", file=sys.stderr)

    if not rows:
        print("# multichip: no row survived — stage dropped",
              file=sys.stderr)
        return None
    base = next(iter(rows.values()))
    for r in rows.values():
        # Weak-scaling efficiency vs the 1-device row (or the smallest
        # measured): per-device rate retained as shards are added.
        r["weak_scaling_efficiency"] = round(
            r["cluster_days_per_sec_per_device"]
            / max(base["cluster_days_per_sec_per_device"], 1e-9), 3)
    # Mesh-stamped provenance (ISSUE 3: multi-chip records are
    # self-describing — mesh shape + axis sizes ride the record). ONE
    # construction of the mesh stamp; the top-level "mesh" key mirrors
    # it for direct readers of the section.
    provenance = bench_provenance(mesh=mesh8)
    out = {
        "engine": "sharded_megakernel(packed, shard-local synthesis)",
        "platform": platform,
        "virtual_cpu_mesh": virtual,
        "interpret": virtual,
        "stochastic": not virtual,
        "steps": steps,
        "b_block": b_block,
        "t_chunk": t_chunk,
        "mesh": provenance["mesh"],
        "weak_scaling": rows,
        # The donation satellite's assertion: the whole donated chain
        # (stream → kernel → recycle) must alias cleanly. A message here
        # means a donated buffer was silently ignored — the single-
        # stream memory story would be fiction.
        "donation": {"ok": not donation_msgs,
                     "warnings": donation_msgs[:3]},
        "provenance": provenance,
    }
    if playback is not None:
        out["plan_playback"] = playback
    if donation_msgs:
        print("# WARNING: donation warnings in the multichip stage: "
              f"{donation_msgs[0][:120]}", file=sys.stderr)
    if virtual:
        out["note"] = ("8-device VIRTUAL CPU mesh, interpret-mode "
                       "kernel: validates sharding + scaling shape, "
                       "not absolute speed; real-chip rows come from a "
                       "multi-TPU host")
    return out


def _multichip_plan_playback(cfg, params, src, mesh, *, steps: int,
                             per_device_batch: int, b_block: int,
                             t_chunk: int, repeats: int,
                             virtual: bool) -> dict | None:
    """Sharded PLAN-PLAYBACK row (ISSUE 4): the quick planner's plan,
    tiled into a per-cluster packed stream SPLIT over the mesh lanes,
    executed by `sharded_plan_summary_from_packed` on the largest mesh
    the weak-scaling sweep measured. Roofline floor counts BOTH streams
    each shard reads (exo + plan rows) — the playback kernel's
    irreducible traffic is ~2x the profile kernel's."""
    import math as _math

    from jax.sharding import NamedSharding, PartitionSpec

    from ccka_tpu.models import action_to_latent, latent_to_action
    from ccka_tpu.parallel import (sharded_packed_trace,
                                   sharded_plan_summary_from_packed)
    from ccka_tpu.policy.rule import neutral_action
    from ccka_tpu.sim import initial_state
    from ccka_tpu.sim.megakernel import _plan_rows, pack_plan
    from ccka_tpu.train.mpc import optimize_plan

    n = int(mesh.shape[mesh.axis_names[0]])
    B = per_device_batch * n
    T_pad = _math.ceil(steps / t_chunk) * t_chunk
    pr = _plan_rows(cfg.cluster.n_pools, cfg.cluster.n_zones)
    days = steps * cfg.sim.dt_s / 86400.0

    # A real (quick) plan, tiled across the horizon — playback
    # throughput is content-independent, the stream layout is not.
    h = 8
    base = jnp.zeros_like(action_to_latent(neutral_action(cfg.cluster),
                                           cfg.cluster))
    lat = optimize_plan(params, cfg.cluster, cfg.train,
                        initial_state(cfg), src.trace(h, seed=13),
                        jnp.broadcast_to(base, (h,) + base.shape),
                        iters=2).plan_latent
    lat_t = jnp.tile(lat, (T_pad // h + 1, 1))[:T_pad]
    actions = jax.vmap(lambda u: latent_to_action(u, cfg.cluster))(lat_t)
    plan2d = pack_plan(actions, T_pad)                   # [T_pad, pr]
    spec = NamedSharding(mesh, PartitionSpec(None, None,
                                             mesh.axis_names[0]))
    # Tiled ON the mesh: each shard materializes only its lane block.
    plan_stream = jax.jit(
        lambda q: jnp.broadcast_to(q[:, :, None], (T_pad, pr, B)),
        out_shardings=spec)(plan2d)
    jax.block_until_ready(plan_stream)

    kw = dict(stochastic=not virtual, b_block=b_block, t_chunk=t_chunk,
              interpret=virtual)
    state = {"stream": sharded_packed_trace(mesh, src, steps,
                                            jax.random.key(17), B,
                                            t_chunk=t_chunk),
             "seed": 0}

    def once():
        state["seed"] += 1
        s, dead = sharded_plan_summary_from_packed(
            mesh, params, cfg.cluster, plan_stream, state["stream"],
            steps, seed=state["seed"], donate_stream=True, **kw)
        jax.block_until_ready(s.cost_usd)
        state["stream"] = sharded_packed_trace(
            mesh, src, steps, jax.random.key(300 + state["seed"]), B,
            t_chunk=t_chunk, recycle=dead)

    once()  # compile
    shard_bytes = float(per_device_batch) * steps \
        * (_trace_row_bytes(cfg) + 4 * pr)
    dt = _time_best(once, repeats, bytes_touched=shard_bytes,
                    label=f"multichip.plan_playback.{n}dev")
    if dt is None:
        return None
    row = {
        "engine": "sharded_plan_playback_megakernel(per-cluster plan, "
                  "donated exo stream)",
        "devices": n, "batch": B, "per_device_batch": per_device_batch,
        "steps": steps, "plan_rows": pr,
        "seconds": round(dt, 4),
        "cluster_days_per_sec_aggregate": round(B * days / dt, 1),
        "cluster_days_per_sec_per_device": round(B * days / dt / n, 1),
        "roofline_floor_ms_per_shard": round(
            _roofline_floor_s(shard_bytes) * 1e3, 3),
    }
    print(f"# multichip plan-playback {n}dev: "
          f"{row['cluster_days_per_sec_aggregate']:,.0f} cluster-days/s "
          f"aggregate{' (VIRTUAL+INTERPRET)' if virtual else ''}",
          file=sys.stderr)
    return row


def _multichip_virtual_fallback() -> dict | None:
    """Single-device host: run the multichip kernel stage on an 8-device
    CPU-virtual mesh in a child process (labeled as such)."""
    env = dict(os.environ)
    env["CCKA_BENCH_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    return _run_child(
        [sys.executable, os.path.abspath(__file__), "--multichip-only"],
        timeout_s=1500, env=env)


def _paired_ratios(board: dict, name: str, *, max_list: int = 16) -> dict:
    """Per-trace paired ratios vs rule for the two headline metrics,
    with the paired-difference statistics the win flag gates on — mean
    alone can't distinguish a ±2% 'win' from trace noise (VERDICT r2
    weak #3), and a raw mean comparison can't either (VERDICT r4 weak
    #2), so the scoreboard now ships mean, se, z and a 2-se CI per
    headline, mirroring the megakernel gate's paired machinery."""
    out = {}
    rule_pt = board["rule"].get("per_trace", {})
    pt = board[name].get("per_trace", {})
    for k in ("usd_per_slo_hour", "g_co2_per_kreq"):
        if k in pt and k in rule_pt and len(pt[k]) == len(rule_pt[k]):
            r = [a / max(b, 1e-9) for a, b in zip(pt[k], rule_pt[k])]
            out[f"vs_rule_{k}_n"] = len(r)
            if len(r) <= max_list:   # raw list only at readable sizes
                out[f"vs_rule_{k}_per_trace"] = [round(x, 4) for x in r]
            out[f"vs_rule_{k}_std"] = round(float(np.std(r)), 4)
            mean = float(np.mean(r))
            out[f"vs_rule_{k}_mean"] = round(mean, 4)
            if len(r) >= 2:
                se = float(np.std(r, ddof=1)) / len(r) ** 0.5
                out[f"vs_rule_{k}_se"] = round(se, 5)
                out[f"vs_rule_{k}_ci2se"] = [round(mean - 2 * se, 4),
                                             round(mean + 2 * se, 4)]
                if se > 1e-8:
                    out[f"vs_rule_{k}_z"] = round((1.0 - mean) / se, 2)
                # else: zero spread — a z statistic is undefined, not
                # astronomically large; the CI (collapsed to a point)
                # and win2se below still decide.
                # The gate decision itself rides UNROUNDED so the flag
                # can never contradict the z it encodes (a rounded CI
                # bound of exactly 1.0 would deny a z=2.01 win).
                out[f"vs_rule_{k}_win2se"] = bool(mean + 2 * se < 1.0)
    return out


def bench_quality(cfg, eval_steps: int = 2880,
                  n_traces: int = 5, *, mpc_quick: bool = False,
                  mpc_n_traces: int = 64) -> dict:
    # eval_steps covers one FULL simulated day: windows anchored at
    # midnight that stop short of 2880 ticks never reach peak hours, so
    # peak-regime behavior would drop out of the scoreboard entirely.
    """Policy quality vs the rule baseline — the other half of
    BASELINE.json's metric ("$/SLO-hour & gCO2/req vs rule baseline").

    Scores rule / carbon / ppo / mpc on held-out stochastic traces
    (paired worlds, per-trace ratio spread reported). PPO loads the
    shipped flagship checkpoint (trained + selection-validated,
    `ccka_tpu/train/flagship.py`); with no committed checkpoint the row
    is OMITTED rather than filled by an untrained stand-in
    (`ppo_source` records the reason). MPC rides the jitted
    receding-horizon path. Plus the multi-region check (config #4):
    carbon-aware zone selection must cut gCO2/kreq on the
    diverging-carbon fleet at comparable SLO.

    ISSUE 3 satellite (VERDICT r5 Next #5's minimal form): in full mode
    the whole board runs on ``mpc_n_traces`` (>=64) paired traces, with
    the MPC row on the QUICK planner (horizon=8, iters=2,
    replan_every=8) so n=64 receding-horizon evaluation is affordable —
    no published `beats_rule_both_headlines` flag rests on an n=5 gate
    any more (at n=5 the 2-se machinery has ~no power against ~1%
    effects). The full planner's quality is measured where it is
    affordable: the forecast stage's `mpc_oracle` row (h=32, 20 Adam
    iters). The planner settings behind the flag are recorded in
    ``mpc_planner``.
    """
    from ccka_tpu.config import multi_region_config
    from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy
    from ccka_tpu.train.evaluate import compare_backends, heldout_traces
    from ccka_tpu.train.flagship import load_flagship_backend
    from ccka_tpu.train.mpc import MPCBackend

    src = _make_src(cfg)
    ppo_backend, ckpt_meta = load_flagship_backend(cfg)
    ppo_source = "flagship_checkpoint"
    if ppo_backend is None:
        # No committed single-region checkpoint is a DECISION, not a gap
        # (VERDICT r3 weak #1: an untrained net under a flagship name is
        # worse than no row): the single-region learned-policy story is
        # diff-MPC's (wins $/SLO-hr at carbon parity); the static-policy
        # margin there is below noise — scripts/zone_spread_probe.py is
        # the committed evidence. A scratch mini-train here would put
        # exactly that noise back on the scoreboard.
        ppo_source = "no_checkpoint_by_design(see ARCHITECTURE §5)"
    quick_planner = dict(horizon=8, iters=2, replan_every=8)
    if mpc_quick:
        mpc_backend = MPCBackend(cfg, **quick_planner)
        mpc_planner = dict(quick_planner, n_traces=n_traces,
                           mode="quick(CI)")
    else:
        # Full mode: quick planner at n>=64 so the significance gate has
        # real power behind the published flag (docstring).
        n_traces = max(n_traces, mpc_n_traces)
        mpc_backend = MPCBackend(cfg, **quick_planner)
        mpc_planner = dict(
            quick_planner, n_traces=n_traces,
            mode="quick_planner_n64",
            note="flag-carrying MPC rows use the quick planner at "
                 "n>=64 paired traces; the full planner (h=32, 20 "
                 "iters) is scored in the forecast stage's mpc_oracle "
                 "row")
    backends = {
        "rule": RulePolicy(cfg.cluster),
        "carbon": CarbonAwarePolicy(cfg.cluster),
        "mpc": mpc_backend,
    }
    if ppo_backend is not None:
        backends["ppo"] = ppo_backend
    traces = heldout_traces(src, steps=eval_steps, n=n_traces)
    board = compare_backends(cfg, backends, traces, stochastic=True)

    mcfg = multi_region_config()
    msrc = _make_src(mcfg)
    mbackends = {"rule": RulePolicy(mcfg.cluster),
                 "carbon": CarbonAwarePolicy(mcfg.cluster)}
    mppo, _mmeta = load_flagship_backend(mcfg)  # multiregion checkpoint
    if mppo is not None:
        mbackends["ppo"] = mppo
    # Same planner policy as the single-region board: quick planner so
    # the multiregion MPC flag also rides n>=64 paired traces.
    mbackends["mpc"] = MPCBackend(mcfg, **quick_planner)
    mboard = compare_backends(
        mcfg, mbackends,
        heldout_traces(msrc, steps=eval_steps, n=n_traces),
        stochastic=True)

    def pick(r):
        return {k: round(r[k], 4) for k in (
            "usd_per_slo_hour", "g_co2_per_kreq", "slo_attainment",
            "vs_rule_usd_per_slo_hour", "vs_rule_g_co2_per_kreq",
            "vs_rule_objective") if k in r}

    def ckpt_provenance(meta):
        return {
            "selected_iteration": meta.get("selected_iteration"),
            "wins_both_on_selection": meta.get("wins_both"),
            "refine": meta.get("refine"),
            "init_from": meta.get("init_from"),
        }

    out = {
        # Scoped per board: the single-region row is omitted by design
        # (see above); the multiregion row's provenance rides with its
        # section — the machine-readable evidence of a TRAINED winner.
        "ppo_source": ppo_source,
        "eval_steps": eval_steps,
        "n_traces": n_traces,
        "mpc_planner": mpc_planner,
    }
    if ckpt_meta:
        out["ppo_checkpoint"] = ckpt_provenance(ckpt_meta)
    for name, r in board.items():
        out[name] = pick(r)
        if name != "rule":
            out[name].update(_paired_ratios(board, name))
    out["multiregion"] = {}
    if _mmeta:
        out["multiregion"]["ppo_checkpoint"] = ckpt_provenance(_mmeta)
    for name, r in mboard.items():
        out["multiregion"][name] = pick(r)
        if name != "rule":
            out["multiregion"][name].update(_paired_ratios(mboard, name))

    _flag_wins(out, out["rule"])
    _flag_wins(out["multiregion"], out["multiregion"]["rule"])
    _defer_mpc_flags(out)
    _defer_mpc_flags(out["multiregion"])
    for label, section in (("", out), ("multiregion.", out["multiregion"])):
        for name in ("ppo", "mpc"):
            if name not in section:
                continue
            r = section[name]
            print(f"# quality[{label}{name}]: usd x"
                  f"{r.get('vs_rule_usd_per_slo_hour', float('nan')):.3f} "
                  f"co2 x{r.get('vs_rule_g_co2_per_kreq', float('nan')):.3f}"
                  f" attain {r['slo_attainment']:.4f} "
                  f"{'BEATS RULE' if r.get('beats_rule_both_headlines') else ''}",
                  file=sys.stderr)
    return out


def bench_quality_replay(cfg, eval_steps: int = 2880, n_windows: int = 0,
                         *, mpc_quick: bool = False) -> dict | None:
    """BASELINE config #3: score backends on the committed *replay* trace
    (a different generative family than the synthetic training world —
    so this measures transfer). Prefers the round-5 5-day trace
    (`data/replay_5day.npz`, 5 day-scale windows — VERDICT r4 weak #2:
    3 windows of the 2-day trace carried too little power to
    significance-gate a ~1% effect), falling back to the round-4 2-day
    trace with 3 windows. ``n_windows=0`` means that per-trace default.
    Windows are offset-staggered slices of the stored trace."""
    import os

    from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy
    from ccka_tpu.signals.replay import ReplaySignalSource
    from ccka_tpu.train.evaluate import compare_backends
    from ccka_tpu.train.flagship import load_flagship_backend
    from ccka_tpu.train.mpc import MPCBackend

    data_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "data")
    candidates = [(os.path.join(data_dir, "replay_5day.npz"), 5),
                  (os.path.join(data_dir, "replay_2day.npz"), 3)]
    path = next((p for p, _ in candidates if os.path.exists(p)), None)
    if path is None:
        print("# quality_replay: no replay trace — skipped "
              "(run scripts/make_replay_trace.py)", file=sys.stderr)
        return None
    if not n_windows:
        n_windows = dict(candidates)[path]
    stored = ReplaySignalSource.from_file(path)
    n_stored = np.asarray(stored._trace.spot_price_hr).shape[0]
    stride = max(1, n_stored // max(n_windows, 1) + 7)  # staggered windows
    traces = [
        ReplaySignalSource.from_file(
            path, offset_steps=(i * stride) % n_stored).trace(eval_steps)
        for i in range(n_windows)]

    backends = {
        "rule": RulePolicy(cfg.cluster),
        "carbon": CarbonAwarePolicy(cfg.cluster),
    }
    # The replay-family flagship (trained on a DIFFERENT realization of
    # the replay generative process — scripts/train_replay_flagship.py)
    # carries the ppo row; with no committed replay checkpoint the row
    # is OMITTED and the reason recorded (no stand-ins).
    ppo_backend, rmeta = load_flagship_backend(cfg, variant="replay")
    if ppo_backend is not None:
        backends["ppo"] = ppo_backend
        ppo_source = {"checkpoint": "ppo_flagship_replay.npz",
                      "selected_iteration": rmeta.get("selected_iteration"),
                      "wins_both_on_selection": rmeta.get("wins_both")}
    else:
        # Same omit-and-record-why contract as bench_quality: no stand-in.
        ppo_source = {"checkpoint": None,
                      "reason": "no replay checkpoint committed (train "
                                "with scripts/train_replay_flagship.py)"}
    backends["mpc"] = (MPCBackend(cfg, horizon=8, iters=2, replan_every=8)
                       if mpc_quick else MPCBackend(cfg))
    board = compare_backends(cfg, backends, traces, stochastic=True)

    def pick(r):
        return {k: round(r[k], 4) for k in (
            "usd_per_slo_hour", "g_co2_per_kreq", "slo_attainment",
            "vs_rule_usd_per_slo_hour", "vs_rule_g_co2_per_kreq") if k in r}

    out = {"eval_steps": eval_steps, "n_windows": n_windows,
           "trace": f"data/{os.path.basename(path)}"}
    if ppo_source:
        out["ppo_source"] = ppo_source
    for name, r in board.items():
        out[name] = pick(r)
        if name != "rule":
            out[name].update(_paired_ratios(board, name))
    # VERDICT r3 weak #4: the transfer scoreboard carries the SAME win
    # flag as the synthetic one (shared helper — the criterion cannot
    # drift between the two), so a replay-family shortfall can't hide
    # behind raw ratios.
    _flag_wins(out, out["rule"])
    _defer_mpc_flags(out)
    learned = [n for n in ("mpc", "ppo") if n in out]
    for name in learned:
        print(f"# quality_replay[{name}]: usd x"
              f"{out[name].get('vs_rule_usd_per_slo_hour', float('nan')):.3f}"
              f" co2 x"
              f"{out[name].get('vs_rule_g_co2_per_kreq', float('nan')):.3f}"
              f"{' BEATS RULE' if out[name]['beats_rule_both_headlines'] else ''}",
              file=sys.stderr)
    return out


def bench_forecast(cfg, eval_steps: int = 2880, n_windows: int = 2,
                   *, mpc_quick: bool = False) -> dict | None:
    """Oracle-gap scoreboard: {oracle, persistence, seasonal-naive,
    ridge} × MPC on the committed replay trace (`data/replay_2day.npz`).

    Every controller-quality number published before round 6 planned
    against *perfect foresight* (`SignalSource.forecast` = the true
    future slice). This stage measures, honestly, how much of the
    oracle-MPC win survives when the planner sees only *predicted*
    windows (`ccka_tpu/forecast`): per-forecaster cost/carbon ratios vs
    the rule baseline on paired worlds, the degradation vs the oracle
    row, and each forecaster's horizon-resolved MAPE on the same trace.
    The rule baseline needs no forecast at all — if a forecaster-fed MPC
    loses an axis to rule, the row says so; that IS the result."""
    import os

    from ccka_tpu.forecast import evaluate_forecaster, make_forecaster
    from ccka_tpu.policy import RulePolicy
    from ccka_tpu.signals.replay import ReplaySignalSource
    from ccka_tpu.train.evaluate import compare_backends
    from ccka_tpu.train.mpc import MPCBackend

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "replay_2day.npz")
    if not os.path.exists(path):
        print("# forecast: no data/replay_2day.npz — skipped",
              file=sys.stderr)
        return None
    stored = ReplaySignalSource.from_file(path)
    n_stored = np.asarray(stored._trace.spot_price_hr).shape[0]
    stride = max(1, n_stored // max(n_windows, 1) + 7)
    traces = [
        ReplaySignalSource.from_file(
            path, offset_steps=(i * stride) % n_stored).trace(eval_steps)
        for i in range(n_windows)]

    mpc_kw = (dict(horizon=8, iters=2, replan_every=8) if mpc_quick
              else {})
    sweep = ("oracle", "persistence", "seasonal-naive", "ridge")
    backends = {"rule": RulePolicy(cfg.cluster)}
    forecasters = {}
    # Seasonal period from the TRACE's own cadence (its meta), not the
    # config's — a dt override must not shift the 24h lag.
    dt_s = stored.meta().dt_s or cfg.sim.dt_s
    for name in sweep:
        fc = make_forecaster(name, dt_s=dt_s)
        row = f"mpc_{(fc.name if fc is not None else 'oracle')}"
        forecasters[row] = fc
        backends[row] = MPCBackend(cfg, forecaster=fc, **mpc_kw)
    board = compare_backends(cfg, backends, traces, stochastic=True)

    def pick(r):
        return {k: round(r[k], 4) for k in (
            "usd_per_slo_hour", "g_co2_per_kreq", "slo_attainment",
            "vs_rule_usd_per_slo_hour", "vs_rule_g_co2_per_kreq") if k in r}

    horizon = backends["mpc_oracle"].horizon
    out = {"trace": "data/replay_2day.npz", "eval_steps": eval_steps,
           "n_windows": n_windows, "mpc_horizon": horizon,
           "mpc_iters": backends["mpc_oracle"].iters,
           "replan_every": backends["mpc_oracle"].replan_every}
    for name, r in board.items():
        out[name] = pick(r)
        if name != "rule":
            out[name].update(_paired_ratios(board, name))
    _flag_wins(out, out["rule"])
    _defer_mpc_flags(out)

    # Oracle → forecast degradation, the stage's headline: how much of
    # the perfect-foresight ratio each real forecaster gives back.
    oracle = out.get("mpc_oracle", {})
    for name in out:
        if (not name.startswith("mpc_") or name == "mpc_oracle"
                or not isinstance(out[name], dict)):
            continue
        r = out[name]
        for k in ("usd_per_slo_hour", "g_co2_per_kreq"):
            o, f = oracle.get(f"vs_rule_{k}"), r.get(f"vs_rule_{k}")
            if o and f:
                r[f"degradation_vs_oracle_{k}"] = round(f / max(o, 1e-9), 4)

    # Horizon-resolved forecast error on the same trace — compressed to
    # the curve endpoints per channel plus the horizon-mean (full curves
    # via `ccka forecast-eval --per-horizon`).
    out["forecast_error"] = {}
    # Full stored length: seasonal-naive needs a whole period of history
    # per anchor, so anything shorter starves it of windows while the
    # short-history forecasters get plenty — an asymmetric comparison.
    err_trace = stored.trace(max(n_stored, eval_steps))
    for row, fc in forecasters.items():
        if fc is None:
            continue
        try:
            e = evaluate_forecaster(fc, err_trace, horizon=horizon,
                                    stride=max(eval_steps // 16, 8))
        except ValueError as exc:
            out["forecast_error"][fc.name] = {"error": str(exc)}
            continue
        out["forecast_error"][fc.name] = {
            "mape_mean": round(e["overall"]["mape_mean"], 5),
            "n_windows": e["n_windows"],
            "per_channel_mape_h1_hlast": {
                f: [round(e[f]["mape"][0], 5),
                    round(e[f]["mape"][-1], 5)]
                for f in ("spot_price_hr", "od_price_hr", "carbon_g_kwh",
                          "demand_pods", "is_peak")},
        }

    for name in out:
        if name.startswith("mpc_") and isinstance(out[name], dict):
            r = out[name]
            print(f"# forecast[{name}]: usd x"
                  f"{r.get('vs_rule_usd_per_slo_hour', float('nan')):.3f} "
                  f"co2 x"
                  f"{r.get('vs_rule_g_co2_per_kreq', float('nan')):.3f}"
                  f"{' BEATS RULE' if r.get('beats_rule_both_headlines') else ''}",
                  file=sys.stderr)
    return out


def bench_quality_mega(n_traces: int = 256, eval_steps: int = 2880,
                       *, seed: int = 31) -> dict | None:
    """High-power kernel scoreboard (VERDICT r4 next #1 + #3): rule,
    carbon, the learned flagships AND diff-MPC scored on ``n_traces``
    PAIRED full-day traces via the Pallas megakernels — ~50x the lax
    quality stage's trace count, so the 2-se significance gate resolves
    sub-percent effects instead of drowning them. All rows of a section
    share one (seed, b_block, t_chunk): identical per-(trace, tick)
    interruption randomness (`sim/megakernel.py` pairing contract).

    MPC rides the plan-playback kernel (ISSUE 4; VERDICT r5 Next #5's
    strong form): the quick planner plans each trace on the LAX path
    (`receding_horizon_plan_batch` — deterministic expectation
    dynamics, so the plan depends only on the trace), then the kernel
    executes those per-cluster plans on the SAME paired stochastic
    worlds as every other row — MPC's `beats_rule_both_headlines` flag
    finally rests on the same win2se evidence standard as ppo/carbon.
    Mosaic-only: returns None off-TPU (CPU and GPU hosts both skip
    cleanly)."""
    if jax.default_backend() != "tpu":
        print("# quality_mega: no TPU — skipped (Mosaic kernels)",
              file=sys.stderr)
        return None
    from ccka_tpu.config import default_config, multi_region_config
    from ccka_tpu.models import action_to_latent, latent_to_action
    from ccka_tpu.policy import CarbonAwarePolicy
    from ccka_tpu.policy.rule import neutral_action, offpeak_action, \
        peak_action
    from ccka_tpu.sim import SimParams, initial_state
    from ccka_tpu.sim.megakernel import (
        carbon_megakernel_rollout_summary, megakernel_rollout_summary,
        neural_megakernel_rollout_summary,
        plan_megakernel_rollout_summary)
    from ccka_tpu.train.flagship import load_flagship_backend
    from ccka_tpu.train.mpc import receding_horizon_plan_batch

    quick_planner = dict(horizon=8, iters=2, replan_every=8)
    out: dict = {"n_traces": n_traces, "eval_steps": eval_steps,
                 "engine": "megakernel",
                 "mpc_planner": dict(
                     quick_planner, n_traces=n_traces,
                     mode="lax_quick_plan->kernel_playback",
                     note="plans computed per paired trace on the lax "
                          "path (expectation dynamics), executed/scored "
                          "by the plan-playback kernel on the shared "
                          "(seed, stream) — the flag-carrying MPC row")}
    for label, cfg in (("default", default_config()),
                       ("multiregion", multi_region_config())):
        src = _make_src(cfg)
        params = SimParams.from_config(cfg)
        off = offpeak_action(cfg.cluster)
        peak = peak_action(cfg.cluster)
        traces = src.batch_trace_device(eval_steps, jax.random.key(97),
                                        n_traces)
        kw = dict(seed=seed, stochastic=True, b_block=256)
        cp = CarbonAwarePolicy(cfg.cluster)
        summaries = {
            "rule": megakernel_rollout_summary(params, off, peak, traces,
                                               **kw),
            "carbon": carbon_megakernel_rollout_summary(
                params, off, peak, traces, sharpness=cp.sharpness,
                min_weight=cp.min_weight, stickiness=cp.stickiness, **kw),
        }
        # MPC: lax planning over every paired trace, kernel execution.
        base = jnp.zeros_like(action_to_latent(
            neutral_action(cfg.cluster), cfg.cluster))
        lat0 = jnp.broadcast_to(
            base, (n_traces, quick_planner["horizon"]) + base.shape)
        states = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n_traces,) + x.shape),
            initial_state(cfg))
        plans = receding_horizon_plan_batch(
            params, cfg.cluster, cfg.train, states, traces, lat0,
            **quick_planner)                         # [N, T, A]
        plan_actions = jax.vmap(jax.vmap(
            lambda u: latent_to_action(u, cfg.cluster)))(plans)
        summaries["mpc"] = plan_megakernel_rollout_summary(
            params, plan_actions, traces, **kw)
        variants = [("ppo", "")]
        if label == "multiregion":
            variants.append(("ppo_frontier", "multiregion_frontier"))
        provenance = {}
        for row_name, variant in variants:
            backend, meta = load_flagship_backend(cfg, variant=variant)
            if backend is None:
                continue
            summaries[row_name] = neural_megakernel_rollout_summary(
                params, cfg.cluster, backend.params, traces, **kw)
            provenance[row_name] = {
                "selected_iteration": meta.get("selected_iteration"),
                "init_from": meta.get("init_from"),
            }
        board = {}
        for name, s in summaries.items():
            vals = {k: np.asarray(getattr(s, k), np.float64)
                    for k in ("usd_per_slo_hour", "g_co2_per_kreq",
                              "slo_attainment")}
            board[name] = {
                **{k: float(v.mean()) for k, v in vals.items()},
                "per_trace": {k: [float(x) for x in v]
                              for k, v in vals.items()},
            }
        section: dict = {"ppo_checkpoints": provenance} if provenance \
            else {}
        for name, r in board.items():
            row = {k: round(r[k], 4) for k in (
                "usd_per_slo_hour", "g_co2_per_kreq", "slo_attainment")}
            if name != "rule":
                for k in ("usd_per_slo_hour", "g_co2_per_kreq"):
                    row[f"vs_rule_{k}"] = round(
                        r[k] / max(board["rule"][k], 1e-9), 4)
                row.update(_paired_ratios(board, name))
            section[name] = row
        _flag_wins(section, section["rule"])
        for name in ("carbon", "mpc", "ppo", "ppo_frontier"):
            r = section.get(name)
            if not r:
                continue
            print(f"# quality_mega[{label}.{name}]: usd x"
                  f"{r.get('vs_rule_usd_per_slo_hour', float('nan')):.4f}"
                  f" (z {r.get('vs_rule_usd_per_slo_hour_z', '-')}) co2 x"
                  f"{r.get('vs_rule_g_co2_per_kreq', float('nan')):.4f}"
                  f" (z {r.get('vs_rule_g_co2_per_kreq_z', '-')})"
                  f"{' BEATS RULE' if r.get('beats_rule_both_headlines') else ''}",
                  file=sys.stderr)
        out[label] = section
    return out


def bench_faults(n_traces: int = 256, eval_steps: int | None = None,
                 *, seed: int = 31) -> dict | None:
    """Robustness scoreboard (ISSUE 5): >=3 fault intensities x
    {rule, flagship, MPC-playback} on n>=256 PAIRED traces through the
    kernel path — $/SLO-hr degradation curves + interruption/denial/
    stale counts, recorded into BASELINE.json round10. Runs on the
    multiregion preset (the topology with a committed flagship
    checkpoint, so the learned row is a real trained policy, not a
    stand-in). On TPU: stochastic Mosaic kernels over full days; off-TPU:
    deterministic interpret-mode at CI horizons (labeled on the record —
    the degradation curve's SHAPE is the result)."""
    from ccka_tpu.config import multi_region_config
    from ccka_tpu.faults.scoreboard import fault_scoreboard

    board = fault_scoreboard(multi_region_config(), n_traces=n_traces,
                             eval_steps=eval_steps, seed=seed)
    board["config"] = "multiregion(flagship checkpoint committed)"
    return board


def bench_workloads(n_traces: int = 256, eval_steps: int | None = None,
                    *, seed: int = 31,
                    scenarios=("diurnal-inference", "flash-crowd",
                               "batch-backfill", "mixed")) -> dict | None:
    """Per-family scenario scoreboard (ISSUE 6): {rule, flagship,
    MPC-playback} x >=4 named workload scenarios on n>=256 PAIRED
    traces through the kernel path — aggregate $/SLO-hr next to
    per-family inference SLO-violation and batch deadline-miss columns,
    recorded into BASELINE.json round11. Runs on the multiregion preset
    (the topology with a committed flagship checkpoint). On TPU:
    stochastic Mosaic kernels over full days; off-TPU: deterministic
    interpret-mode at CI horizons (labeled on the record — the
    per-family column CONTRASTS are the result).

    Each scenario row carries a roofline floor derived from its own
    stream geometry (exo + fault + workload lane bytes) — the standard
    any future timing of that row must clear (`_roofline_floor_s`)."""
    from ccka_tpu.config import multi_region_config
    from ccka_tpu.workloads.scoreboard import workload_scoreboard

    board = workload_scoreboard(multi_region_config(), n_traces=n_traces,
                                eval_steps=eval_steps, seed=seed,
                                scenarios=scenarios)
    board["config"] = "multiregion(flagship checkpoint committed)"
    # Per-row roofline floors: bytes the kernel must stream per scenario
    # = stream rows (incl. the fault/workload lane blocks) x 4 B x
    # traces x ticks. Recorded next to each row so a published timing
    # for that scenario can be audited against physics.
    steps = board["eval_steps"]
    plan_rows = board.get("mpc_planner", {}).get("plan_rows", 0)
    for name, sec in board["scenarios"].items():
        bytes_touched = (float(sec["stream_bytes_per_cluster_tick"])
                         * board["n_traces"] * steps)
        sec["roofline_floor_ms"] = round(
            _roofline_floor_s(bytes_touched) * 1e3, 3)
        if plan_rows and "mpc" in sec["rows"]:
            # The playback row streams the per-cluster plan block ON
            # TOP of the scenario stream — its floor counts both.
            sec["roofline_floor_mpc_ms"] = round(_roofline_floor_s(
                bytes_touched + 4.0 * plan_rows
                * board["n_traces"] * steps) * 1e3, 3)
    return board


def bench_recovery(runs_per_cell: int = 8, ticks: int = 32,
                   *, seed: int = 101) -> dict | None:
    """Crash-recovery scoreboard (ISSUE 9): paired kill/no-kill
    controller runs per {rule, flagship} x >=3 actuation-fault
    intensities through a ChaosSink'd dry-run cluster with the
    reconciler converging every tick and durable snapshots at tick
    boundaries — duplicate/lost patch counts (MUST be 0),
    bitwise-resume fraction, ticks-to-reconverge, and the paired
    $/SLO-hour delta killed-vs-uninterrupted, recorded into
    BASELINE.json round12. Runs on the multiregion preset (the topology
    with a committed flagship checkpoint). Host-side harness: the
    result is the INVARIANT (zero dup/lost, ratio 1.0), not a
    wall-clock number — no roofline floor applies."""
    from ccka_tpu.config import multi_region_config
    from ccka_tpu.harness.recovery import recovery_scoreboard

    board = recovery_scoreboard(multi_region_config(),
                                runs_per_cell=runs_per_cell, ticks=ticks,
                                seed=seed)
    board["config"] = "multiregion(flagship checkpoint committed)"
    return board


def bench_overload(*, tenants=(16, 64),
                   intensities=("off", "moderate", "severe"),
                   slow_fracs=(0.0, 0.25, 0.5),
                   ticks: int = 48, seed: int = 211) -> dict | None:
    """Overload scoreboard (ISSUE 10): paired stressed/calm multi-tenant
    FleetService runs per {tenant count x chaos intensity x slow-tenant
    fraction} — healthy tenants' paired $/SLO-hr isolation ratios,
    per-tick p50/p99 latency vs the configured deadline, shed/deferral/
    bulkhead counters and breaker transitions, recorded into
    BASELINE.json round13. Runs on the multiregion preset (the topology
    with a committed flagship checkpoint). Host-side harness on a
    virtual clock: the result is the ISOLATION INVARIANT (healthy ratio
    1.0, p99 under the deadline), not device throughput — no roofline
    floor applies."""
    from ccka_tpu.config import multi_region_config
    from ccka_tpu.harness.overload import overload_scoreboard

    board = overload_scoreboard(multi_region_config(), tenants=tenants,
                                intensities=intensities,
                                slow_fracs=slow_fracs, ticks=ticks,
                                seed=seed)
    board["config"] = "multiregion(flagship checkpoint committed)"
    return board


def _verify_obs_dumps(run_out: dict) -> tuple[int, list[str]]:
    """Checksum-verify every stamped incident's recorder dump; returns
    (verified_count, failures). Runs BEFORE the scratch dump dir is
    cleaned up."""
    from ccka_tpu.obs.recorder import verify_dump

    ok = 0
    failures: list[str] = []
    for rec in run_out["incident_records"]:
        if rec.dump_path is None:
            continue
        try:
            verify_dump(rec.dump_path)
            ok += 1
        except Exception as e:  # noqa: BLE001 — recorded, not raised
            failures.append(repr(e)[:120])
    return ok, failures


def bench_obs(*, n_tenants: int = 16, ticks: int = 48, seed: int = 211,
              repeats: int = 3) -> dict | None:
    """Flight-recorder overhead + non-interference stage (round 14,
    `ccka_tpu/obs`): paired recorder-ON / recorder-OFF FleetService
    runs over the SAME seeded world (slow + flaky tenants so the
    incident triggers genuinely fire), measuring the obs layer's cost
    as the delta in p50 tick latency — best p50 over ``repeats``
    paired runs, the same noise posture as the throughput stages'
    best-of-N. The acceptance gates ride the record itself:

    - ``recorder_overhead_frac`` < 5% of the OFF run's p50 tick
      latency (the `ccka bench-diff` obs invariant);
    - ``bitwise_identical``: decisions (per-tenant $/SLO-hr and SLO
      tick accumulators) AND patch streams (per-sink command lists)
      byte-equal between the paired runs — observation must never
      steer;
    - every incident's recorder dump verifies its checksum, and every
      breaker open / reconcile give-up is attributable to exactly one
      incident record (counter == stamp parity).

    Host-side harness on the virtual clock — no roofline floor
    applies; the INVARIANTS are the result, the overhead number is
    the budget. The bitwise gate runs on a fully-deterministic
    injected base clock (every clock read advances a fixed step), so
    the claim is exactly "observation never steers a decision" —
    real-clock runs are NOT run-to-run reproducible on a loaded host
    (deadline arithmetic reads real time), with or without the
    recorder, and pinning bitwise identity on them would measure host
    noise, not interference. The overhead pair runs on the real
    clock, where cost is real."""
    import tempfile

    from ccka_tpu.config import ObsConfig, SERVICE_PRESETS, \
        default_config
    from ccka_tpu.harness.service import (VirtualClock,
                                          fleet_service_from_config)
    from ccka_tpu.policy import RulePolicy

    cfg = default_config().with_overrides(
        **{"sim.horizon_steps": max(ticks + 4, 16)})
    backend = RulePolicy(cfg.cluster)
    # 1/4 slow (hung scrapes -> breaker opens) + 1/4 flaky (severe
    # kubectl chaos -> reconcile give-ups): the triggers must fire for
    # the attribution parity to be a real check, not a 0 == 0.
    n_stress = max(2, n_tenants // 4)
    profiles = (["healthy"] * (n_tenants - 2 * n_stress)
                + ["slow"] * n_stress + ["flaky"] * n_stress)
    dump_dir = tempfile.mkdtemp(prefix="ccka-obs-bench-")
    # decisions_enabled=False keeps this stage's number the RECORDER's
    # cost, as recorded since r14 — the round-18 decision ledger is
    # priced by its own paired stage (`bench_decisions`).
    obs_on = ObsConfig(enabled=True, dump_dir=dump_dir,
                       decisions_enabled=False)

    def det_clock():
        """Deterministic base: +0.1 virtual ms per read, fresh per
        run — paired runs see IDENTICAL clock sequences."""
        state = {"s": 0.0}

        def base():
            state["s"] += 1e-4
            return state["s"]
        return VirtualClock(base=base)

    def run(obs, clock=None):
        svc = fleet_service_from_config(
            cfg, backend, n_tenants, profiles=profiles,
            service=SERVICE_PRESETS["default"], obs=obs,
            horizon_ticks=max(ticks + 4, 8), seed=seed, clock=clock)
        svc.warmup()
        svc.run(ticks)
        lats = np.asarray(svc.latencies_ms)
        out = {
            "p50_ms": float(np.percentile(lats, 50)),
            "mean_ms": float(lats.mean()),
            "usd": svc.tenant_usd_per_slo_hr().copy(),
            "slo_ticks": svc.tenant_slo_ticks.copy(),
            # Chaos-wrapped tenants keep their command log on the
            # inner DryRunSink (the ChaosSink is a pass-through shim).
            "commands": [[(c.name, c.patch_type, json.dumps(
                c.patch, sort_keys=True))
                for c in getattr(s, "inner", s).commands]
                for s in svc.sinks],
            "breaker_opens": sum(b.transitions["opened"]
                                 for b in svc.breakers),
            "giveups": int(svc.actuation_giveups_total),
            "incidents": (svc.incidents.counts()
                          if svc.incidents is not None else {}),
            "incident_records": (list(svc.incidents.incidents)
                                 if svc.incidents is not None else []),
            "dumps_total": (svc.recorder.dumps_total
                            if svc.recorder is not None else 0),
            "burn": (svc.burn.rates() if svc.burn is not None else {}),
        }
        svc.close()
        return out

    # Bitwise non-interference on the deterministic clock: one pair
    # suffices — the runs have no noise source left to average over.
    try:
        det_off = run(None, clock=det_clock())
        det_on = run(obs_on, clock=det_clock())
        bitwise = bool(np.array_equal(det_off["usd"], det_on["usd"])
                       and np.array_equal(det_off["slo_ticks"],
                                          det_on["slo_ticks"])
                       and det_off["commands"] == det_on["commands"])

        # Overhead on the REAL clock: the recorder's per-tick cost is
        # the delta of MEAN tick latency between paired runs (every
        # tick pays ring recording; incident ticks additionally pay
        # their shared dump), medianed over N repeats so one noisy
        # pairing cannot set the number; the gate expresses it as a
        # fraction of the OFF run's p50 tick latency (the acceptance
        # bound's denominator). A p50 delta would be the wrong
        # estimator — the median of a shifted mixture moves with the
        # distribution's shape, not the cost.
        best_off = best_on = None
        deltas = []
        on = None
        for _ in range(max(repeats, 1)):
            off = run(None)
            on = run(obs_on)
            deltas.append(on["mean_ms"] - off["mean_ms"])
            best_off = (off["p50_ms"] if best_off is None
                        else min(best_off, off["p50_ms"]))
            best_on = (on["p50_ms"] if best_on is None
                       else min(best_on, on["p50_ms"]))
        overhead_ms = float(np.median(deltas))
        overhead = overhead_ms / max(best_off, 1e-9)

        dumps_ok, dump_failures = _verify_obs_dumps(on)
    finally:
        # The dumps were verified above — the scratch dir must not
        # accumulate across bench invocations.
        import shutil

        shutil.rmtree(dump_dir, ignore_errors=True)

    # Attribution parity: counter == stamp, per trigger (the dump
    # checksums were verified inside the try block, before cleanup).
    inc = on["incidents"]
    attributable = (
        inc.get("breaker_open", 0) == on["breaker_opens"]
        and inc.get("reconcile_giveup", 0) == on["giveups"])
    out = {
        "engine": "paired recorder-on/recorder-off fleet service "
                  "(virtual clock, seeded slow+flaky tenants)",
        "n_tenants": n_tenants,
        "ticks": ticks,
        "seed": seed,
        "repeats": repeats,
        "profiles": {"healthy": n_tenants - 2 * n_stress,
                     "slow": n_stress, "flaky": n_stress},
        "p50_tick_ms_off": round(best_off, 3),
        "p50_tick_ms_on": round(best_on, 3),
        "recorder_overhead_ms_per_tick": round(overhead_ms, 4),
        "recorder_overhead_frac": round(max(overhead, 0.0), 4),
        "recorder_overhead_raw_frac": round(overhead, 4),
        "bitwise_identical": bool(bitwise),
        "incidents": inc,
        "incidents_total": sum(inc.values()),
        "breaker_opens": on["breaker_opens"],
        "reconcile_giveups": on["giveups"],
        "attributable": bool(attributable),
        "dumps_total": on["dumps_total"],
        "dumps_verified": dumps_ok,
        "dump_failures": dump_failures,
        "burn_rates_final": on["burn"],
        "overhead_gate_frac": 0.05,
        "overhead_gate_ok": bool(max(overhead, 0.0) < 0.05),
    }
    print(f"# obs: p50 off {out['p50_tick_ms_off']:.3f}ms, recorder "
          f"overhead {out['recorder_overhead_ms_per_tick']:.3f}ms/tick "
          f"({out['recorder_overhead_frac'] * 100:.2f}% of p50), bitwise="
          f"{out['bitwise_identical']}, "
          f"{out['incidents_total']} incidents "
          f"({out['dumps_verified']}/{out['dumps_total']} dumps "
          "verified)", file=sys.stderr)
    return out


def bench_decisions(*, n_tenants: int = 16, ticks: int = 48,
                    seed: int = 211, repeats: int = 3) -> dict | None:
    """Decision-provenance ledger stage (round 18, `obs/decisions.py`):
    paired ledger-ON / ledger-OFF FleetService runs over the SAME
    seeded world — the LEARNED FLAGSHIP against its rule shadow (the
    divergence the paper's pitch is actually about: the flagship moves
    hpa_scale/ct_allow, so the one-step counterfactual's $/carbon
    deltas are genuinely nonzero, where a zone-weight-only policy's
    one-step deltas are ~0 behind the provisioning delay; carbon is
    the fallback when no checkpoint is committed) with slow + flaky
    tenants so the incident substrate fires too. Both arms run the
    full round-14 obs layer; the ONLY
    difference is `obs.decisions_enabled`, so the delta prices exactly
    the ledger — and because the shadow lanes ride the compiled tick
    unconditionally, the two arms share one XLA program by
    construction. Gates on the record (the `ccka bench-diff` decision
    invariants):

    - ``bitwise_identical``: decisions (per-tenant $/SLO-hr + SLO tick
      accumulators) AND patch streams byte-equal between the paired
      det-clock runs — provenance must never steer;
    - ``ledger_overhead_frac`` < 5% of the OFF run's p50 tick latency
      (the PR 11/12 standard), measured on the real clock as the
      median over ``repeats`` paired mean-latency deltas. This prices
      the HOST-side recording only: the shadow lanes' device compute
      is unconditional by design (program identity across obs
      postures — ARCHITECTURE 20) and therefore part of both arms'
      p50 denominator, not the delta;
    - ``term_share_err_max``: |Σ shares − 1| over EVERY recorded row
      (attribution must account for the whole objective);
    - ≥1 ``policy_divergence`` incident, each attributable 1:1 to a
      checksum-verified flight-recorder dump.

    Host-side harness on the virtual clock — the INVARIANTS are the
    result; no roofline floor applies."""
    import tempfile

    from ccka_tpu.config import ObsConfig, SERVICE_PRESETS, \
        multi_region_config
    from ccka_tpu.harness.service import (VirtualClock,
                                          fleet_service_from_config)
    from ccka_tpu.train.flagship import load_flagship_backend

    cfg = multi_region_config().with_overrides(
        **{"sim.horizon_steps": max(ticks + 4, 16)})
    backend, _meta = load_flagship_backend(cfg)
    backend_name = "flagship"
    config_name = "multiregion(flagship checkpoint committed)"
    if backend is None:
        from ccka_tpu.policy import CarbonAwarePolicy
        backend = CarbonAwarePolicy(cfg.cluster)
        backend_name = "carbon (no flagship checkpoint committed)"
        config_name = "multiregion (carbon fallback — no flagship " \
                      "checkpoint)"
    n_stress = max(2, n_tenants // 4)
    profiles = (["healthy"] * (n_tenants - 2 * n_stress)
                + ["slow"] * n_stress + ["flaky"] * n_stress)
    scratch = tempfile.mkdtemp(prefix="ccka-decisions-bench-")
    run_idx = [0]

    def obs_cfg(decisions: bool) -> ObsConfig:
        run_idx[0] += 1
        return ObsConfig(
            enabled=True,
            dump_dir=os.path.join(scratch, f"dumps-{run_idx[0]}"),
            decisions_enabled=decisions,
            decision_log_path=(os.path.join(
                scratch, f"decisions-{run_idx[0]}.jsonl")
                if decisions else ""))

    def det_clock():
        state = {"s": 0.0}

        def base():
            state["s"] += 1e-4
            return state["s"]
        return VirtualClock(base=base)

    def run(decisions: bool, clock=None):
        svc = fleet_service_from_config(
            cfg, backend, n_tenants, profiles=profiles,
            service=SERVICE_PRESETS["default"], obs=obs_cfg(decisions),
            horizon_ticks=max(ticks + 4, 8), seed=seed, clock=clock)
        svc.warmup()
        reports = svc.run(ticks)
        lats = np.asarray(svc.latencies_ms)
        led = svc.decisions
        out = {
            "p50_ms": float(np.percentile(lats, 50)),
            "mean_ms": float(lats.mean()),
            "usd": svc.tenant_usd_per_slo_hr().copy(),
            "slo_ticks": svc.tenant_slo_ticks.copy(),
            "commands": [[(c.name, c.patch_type, json.dumps(
                c.patch, sort_keys=True))
                for c in getattr(s, "inner", s).commands]
                for s in svc.sinks],
            "incidents": svc.incidents.counts(),
            "incident_records": list(svc.incidents.incidents),
            "rows_total": led.rows_total if led is not None else 0,
            "diverged_total": (led.diverged_total
                               if led is not None else 0),
            "spikes_total": led.spikes_total if led is not None else 0,
            "divergence_rate_last": reports[-1].policy_divergence_rate,
            "term_shares_last": reports[-1].objective_term_shares,
            "shadow_slo_delta_last": reports[-1].shadow_slo_delta,
            "shadow_usd_delta_total": (led.shadow_usd_delta_total
                                       if led is not None else 0.0),
        }
        led_path = led.path if led is not None else ""
        svc.close()
        # The every-row share gate must see EVERY row: the ledger's
        # in-memory tail is retention-bounded (rows_retained), so a
        # long run's oldest rows only survive on disk — read them back
        # from the JSONL the run just flushed.
        if led_path:
            from ccka_tpu.obs.decisions import read_decisions
            out["rows"] = read_decisions(led_path)
            assert len(out["rows"]) == out["rows_total"]
        else:
            out["rows"] = []
        return out

    try:
        # Bitwise non-interference on the deterministic clock: one
        # pair suffices — no noise source left to average over.
        det_off = run(False, clock=det_clock())
        det_on = run(True, clock=det_clock())
        bitwise = bool(
            np.array_equal(det_off["usd"], det_on["usd"])
            and np.array_equal(det_off["slo_ticks"],
                               det_on["slo_ticks"])
            and det_off["commands"] == det_on["commands"])

        # Attribution invariant: shares sum to ~1 on EVERY row.
        share_errs = [abs(sum(r["objective"]["shares"].values()) - 1.0)
                      for r in det_on["rows"]]
        shadow_share_errs = [
            abs(sum(r["shadow"]["objective"]["shares"].values()) - 1.0)
            for r in det_on["rows"]]
        term_share_err_max = float(max(share_errs + shadow_share_errs,
                                       default=1.0))

        # Divergence incidents attributable 1:1 to verified dumps.
        from ccka_tpu.obs.recorder import verify_dump
        pd_records = [rec for rec in det_on["incident_records"]
                      if rec.trigger == "policy_divergence"]
        pd_dump_failures: list[str] = []
        pd_dumps_verified = 0
        for rec in pd_records:
            if rec.dump_path is None:
                pd_dump_failures.append(f"incident {rec.id} dump-less")
                continue
            try:
                body = verify_dump(rec.dump_path)
                assert body["t"] == rec.t
                pd_dumps_verified += 1
            except Exception as e:  # noqa: BLE001 — recorded, not raised
                pd_dump_failures.append(repr(e)[:120])

        # Overhead on the REAL clock (the bench_obs estimator: median
        # of paired mean-latency deltas over the OFF p50 denominator).
        best_off = None
        deltas = []
        for _ in range(max(repeats, 1)):
            off = run(False)
            on = run(True)
            deltas.append(on["mean_ms"] - off["mean_ms"])
            best_off = (off["p50_ms"] if best_off is None
                        else min(best_off, off["p50_ms"]))
        overhead_ms = float(np.median(deltas))
        overhead = overhead_ms / max(best_off, 1e-9)
    finally:
        import shutil

        shutil.rmtree(scratch, ignore_errors=True)

    out = {
        "engine": "paired ledger-on/ledger-off fleet service (virtual "
                  "clock, flagship backend vs rule shadow, seeded "
                  "slow+flaky tenants)",
        "n_tenants": n_tenants,
        "ticks": ticks,
        "seed": seed,
        "repeats": repeats,
        "backend": backend_name,
        "config": config_name,
        "shadow_policy": "rule",
        "profiles": {"healthy": n_tenants - 2 * n_stress,
                     "slow": n_stress, "flaky": n_stress},
        "p50_tick_ms_off": round(best_off, 3),
        "ledger_overhead_ms_per_tick": round(overhead_ms, 4),
        "ledger_overhead_frac": round(max(overhead, 0.0), 4),
        "ledger_overhead_raw_frac": round(overhead, 4),
        "bitwise_identical": bool(bitwise),
        "rows_total": det_on["rows_total"],
        "rows_per_tick": n_tenants,
        "term_share_err_max": term_share_err_max,
        "diverged_total": det_on["diverged_total"],
        "divergence_rate_last": det_on["divergence_rate_last"],
        "term_shares_last": det_on["term_shares_last"],
        "shadow_slo_delta_last": det_on["shadow_slo_delta_last"],
        "shadow_usd_delta_total": round(
            det_on["shadow_usd_delta_total"], 6),
        "divergence_incidents": len(pd_records),
        "divergence_spikes": det_on["spikes_total"],
        "divergence_dumps_verified": pd_dumps_verified,
        "divergence_dump_failures": pd_dump_failures,
        "incidents": det_on["incidents"],
        "overhead_gate_frac": 0.05,
        "overhead_gate_ok": bool(max(overhead, 0.0) < 0.05),
        "share_gate_err": 0.02,
        "share_gate_ok": bool(term_share_err_max <= 0.02),
    }
    print(f"# decisions: p50 off {out['p50_tick_ms_off']:.3f}ms, ledger "
          f"overhead {out['ledger_overhead_ms_per_tick']:.3f}ms/tick "
          f"({out['ledger_overhead_frac'] * 100:.2f}% of p50), bitwise="
          f"{out['bitwise_identical']}, {out['rows_total']} rows "
          f"(share err {out['term_share_err_max']:.2e}), "
          f"{out['divergence_incidents']} policy_divergence incident(s) "
          f"({out['divergence_dumps_verified']} dumps verified)",
          file=sys.stderr)
    return out


def bench_tournament(*, n_tenants: int = 16, ticks: int = 48,
                     seed: int = 211, repeats: int = 3,
                     k_points: tuple = (1, 2, 4, 8),
                     challenger_ticks: int = 32) -> dict | None:
    """Shadow-tournament observatory stage (round 20,
    `obs/tournament.py`): the K-policy counterfactual lanes and their
    host-side win ledger, priced and proven on the fleet service.
    Sections, each its own gate in the record (the `ccka bench-diff`
    tournament invariants):

    - ``bitwise_identical``: paired det-clock runs differing ONLY in
      the host toggle ``obs.tournament_enabled`` (same K=4 roster, so
      the candidate lanes ride BOTH programs unconditionally) produce
      byte-equal per-tenant $/SLO accumulators and patch streams — the
      tournament must never steer the fleet it scores;
    - ``ledger_overhead_frac`` < 5% at K=4 (the round-18 bound):
      real-clock paired runs price the HOST-side scoring only — the
      median over ticks of per-tick PAIRED on/off latency deltas (the
      arms replay the bitwise-same world, so tick t pairs), medianed
      again over ``repeats``; the lanes' device compute is part of
      both arms' p50 by construction;
    - ``k_curve``: the K∈{1,2,4,8} roster-width sweep vs the K=0
      (laneless) program — the DEVICE cost of widening the population,
      recorded not gated (each K is its own XLA program);
    - ``board_gate_ok``: the final board carries exactly one row per
      roster name, every win rate in [0,1], and the full per-workload-
      class split (inference/batch/background);
    - the seeded challenger scenario: an :class:`OverProvisionPolicy`
      incumbent (static peak profile, HPA overscaled, consolidation
      off) vs a one-candidate ``("carbon",)`` roster must raise EXACTLY
      ONE edge-triggered ``challenger_sustained_win`` incident, its
      flight-recorder dump checksum-verified and every promotion audit
      row in the tournament JSONL HMAC-valid.

    Host-side harness on the virtual clock — the INVARIANTS are the
    result; no roofline floor applies."""
    import tempfile

    from ccka_tpu.config import ObsConfig, SERVICE_PRESETS, \
        multi_region_config
    from ccka_tpu.harness.service import (VirtualClock,
                                          fleet_service_from_config)
    from ccka_tpu.obs.tournament import (OverProvisionPolicy,
                                         WORKLOAD_CLASSES,
                                         read_tournament, verify_audit)
    from ccka_tpu.train.flagship import load_flagship_backend

    # The record roster (K=4) and the width-sweep superset: the rule
    # profile + carbon intensity specializations — checkpoint-free, so
    # the stage runs on any checkout.
    roster8 = ("rule", "carbon", "carbon-sharp", "carbon-smooth",
               "carbon-sticky", "carbon-eager", "carbon-floor",
               "carbon-greedy")
    roster = roster8[:4]
    base = multi_region_config().with_overrides(
        **{"sim.horizon_steps": max(ticks, challenger_ticks) + 8})
    cfg = base.with_overrides(**{"obs.tournament_roster": roster})
    # The four-way mix serves both gates at once: slow + flaky tenants
    # reproduce the round-18 production tick (reconciler retries and
    # breaker churn are part of the p50 the 5% bound prices against —
    # BENCH_r18's 55.7ms standard, not a retry-free toy tick), and
    # batch tenants make every workload class on the board carry real
    # comparisons instead of a None placeholder.
    n_stress = max(2, n_tenants // 4)
    profiles = (["healthy"] * max(n_tenants - 3 * n_stress, 0)
                + ["batch"] * n_stress + ["slow"] * n_stress
                + ["flaky"] * n_stress)[:n_tenants]
    scratch = tempfile.mkdtemp(prefix="ccka-tournament-bench-")
    run_idx = [0]

    def obs_cfg(tournament: bool, **kw) -> ObsConfig:
        run_idx[0] += 1
        return ObsConfig(
            enabled=True,
            dump_dir=os.path.join(scratch, f"dumps-{run_idx[0]}"),
            tournament_enabled=tournament,
            tournament_log_path=(os.path.join(
                scratch, f"tournament-{run_idx[0]}.jsonl")
                if tournament else ""), **kw)

    def det_clock():
        state = {"s": 0.0}

        def base_t():
            state["s"] += 1e-4
            return state["s"]
        return VirtualClock(base=base_t)

    def run(run_cfg, backend, tournament: bool, n: int, n_ticks: int,
            clock=None, prof=None, **obs_kw):
        svc = fleet_service_from_config(
            run_cfg, backend, n, profiles=(prof or profiles)[:n],
            service=SERVICE_PRESETS["default"],
            obs=obs_cfg(tournament, **obs_kw),
            horizon_ticks=n_ticks + 4, seed=seed, clock=clock)
        svc.warmup()
        # Wall-clock per-tick timing alongside the service's own
        # latency ledger: under a VirtualClock the ledger records
        # virtual durations (deterministic, identical across K), so
        # any compute-cost comparison must use the wall numbers.
        wall = []
        reports = []
        for t in range(n_ticks):
            t0 = time.perf_counter()
            reports.append(svc.tick(t))
            wall.append((time.perf_counter() - t0) * 1e3)
        lats = np.asarray(svc.latencies_ms)
        led = svc.tournament
        out = {
            "p50_ms": float(np.percentile(lats, 50)),
            "mean_ms": float(lats.mean()),
            "lats_ms": lats,
            "wall_p50_ms": float(np.percentile(np.asarray(wall), 50)),
            "usd": svc.tenant_usd_per_slo_hr().copy(),
            "slo_ticks": svc.tenant_slo_ticks.copy(),
            "commands": [[(c.name, c.patch_type, json.dumps(
                c.patch, sort_keys=True))
                for c in getattr(s, "inner", s).commands]
                for s in svc.sinks],
            "incidents": svc.incidents.counts(),
            "incident_records": list(svc.incidents.incidents),
            "board": led._board() if led is not None else {},
            "win_rate_last": dict(reports[-1].candidate_win_rate),
            "leader_last": reports[-1].tournament_leader,
            "ticks_total": led.ticks_total if led is not None else 0,
            "log_path": led.path if led is not None else "",
        }
        svc.close()
        return out

    # The primary is the fleet the paper actually ships — the learned
    # flagship (the round-18 denominator standard: the 5%-of-p50 bound
    # prices the ledger against the PRODUCTION tick, not a toy rule
    # tick); carbon is the fallback when no checkpoint is committed.
    primary, _meta = load_flagship_backend(cfg)
    primary_name = "flagship"
    if primary is None:
        from ccka_tpu.policy import CarbonAwarePolicy
        primary = CarbonAwarePolicy(cfg.cluster)
        primary_name = "carbon (no flagship checkpoint committed)"
    try:
        # 1. Bitwise non-interference on the deterministic clock: one
        # pair suffices — no noise source left to average over. Both
        # arms compile the SAME K=4-lane program; only the host ledger
        # toggles.
        det_off = run(cfg, primary, False, n_tenants, ticks,
                      clock=det_clock())
        det_on = run(cfg, primary, True, n_tenants, ticks,
                     clock=det_clock())
        bitwise = bool(
            np.array_equal(det_off["usd"], det_on["usd"])
            and np.array_equal(det_off["slo_ticks"],
                               det_on["slo_ticks"])
            and det_off["commands"] == det_on["commands"])

        # 2. Board invariants on the ON arm's final window.
        board = det_on["board"]
        rates_ok = all(
            0.0 <= (e.get("win_rate") or 0.0) <= 1.0
            and all(cls.get("win_rate") is None
                    or 0.0 <= cls["win_rate"] <= 1.0
                    for cls in e.get("classes", {}).values())
            for e in board.values())
        classes_ok = all(
            set(e.get("classes", {})) == set(WORKLOAD_CLASSES)
            for e in board.values())
        board_gate_ok = bool(tuple(board) == roster and rates_ok
                             and classes_ok)

        # 3. Host-ledger overhead on the REAL clock at K=4. The two
        # arms replay the bitwise-same seeded world (section 1), so
        # tick t is the same work in both — per-tick PAIRED deltas are
        # comparable, and the median over ticks discards the heavy
        # tail (GC/OS jitter lands on single ticks, where an arm-mean
        # delta would smear one outlier across the whole arm).
        best_off = None
        deltas = []
        for _ in range(max(repeats, 1)):
            off = run(cfg, primary, False, n_tenants, ticks)
            on = run(cfg, primary, True, n_tenants, ticks)
            m = min(len(on["lats_ms"]), len(off["lats_ms"]))
            deltas.append(float(np.median(
                on["lats_ms"][:m] - off["lats_ms"][:m])))
            best_off = (off["p50_ms"] if best_off is None
                        else min(best_off, off["p50_ms"]))
        overhead_ms = float(np.median(deltas))
        overhead = overhead_ms / max(best_off, 1e-9)

        # 4. The K-lane width sweep: each K is its own XLA program
        # (the roster is program-shaping), priced against the K=0
        # laneless build. Recorded, not gated — the lanes are paid
        # unconditionally by design. Runs on the det clock so the
        # reconciler's backoff sleeps are virtual: the real-clock tick
        # is quantized by 50ms retry sleeps that bury the lane compute
        # entirely; here the wall latency IS the compute.
        k_curve = {}
        p50_k0 = None
        for k in (0,) + tuple(k_points):
            cfg_k = base.with_overrides(
                **{"obs.tournament_roster": roster8[:k]})
            p50_k = min(
                run(cfg_k, primary, k > 0, n_tenants, ticks,
                    clock=det_clock())["wall_p50_ms"]
                for _ in range(max(repeats, 1)))
            if k == 0:
                p50_k0 = p50_k
            k_curve[str(k)] = {
                "p50_ms": round(p50_k, 3),
                "frac_vs_k0": (round(p50_k / p50_k0 - 1.0, 4)
                               if k else 0.0),
            }

        # 5. The seeded challenger scenario: wasteful incumbent, one
        # carbon challenger, tight window — exactly one edge-triggered
        # incident, dump + audit signatures verified.
        ch_cfg = base.with_overrides(**{
            "obs.tournament_roster": ("carbon",),
            "obs.tournament_window": 8,
            "obs.tournament_sustain_ticks": 4,
            "obs.tournament_win_rate": 0.6,
        })
        ch_n = min(n_tenants, 6)
        ch = run(ch_cfg, OverProvisionPolicy(ch_cfg.cluster), True,
                 ch_n, challenger_ticks, prof=["healthy"] * ch_n)
        from ccka_tpu.obs.recorder import verify_dump
        ch_records = [rec for rec in ch["incident_records"]
                      if rec.trigger == "challenger_sustained_win"]
        ch_failures: list[str] = []
        ch_dumps_verified = 0
        for rec in ch_records:
            if rec.dump_path is None:
                ch_failures.append(f"incident {rec.id} dump-less")
                continue
            try:
                body = verify_dump(rec.dump_path)
                assert body["t"] == rec.t
                ch_dumps_verified += 1
            except Exception as e:  # noqa: BLE001 — recorded, not raised
                ch_failures.append(repr(e)[:120])
        audit_rows = [r for r in read_tournament(ch["log_path"])
                      if r.get("kind") == "promotion_audit"]
        audits_verified = sum(
            verify_audit(r, ch_cfg.obs.tournament_audit_key)
            for r in audit_rows)
        challenger_gate_ok = bool(
            len(ch_records) == 1 and ch_dumps_verified == 1
            and not ch_failures and audit_rows
            and audits_verified == len(audit_rows))
    finally:
        import shutil

        shutil.rmtree(scratch, ignore_errors=True)

    out = {
        "engine": "paired tournament-on/off fleet service (virtual "
                  "clock, flagship primary vs the K=4 carbon-variant "
                  "roster, seeded batch+slow+flaky tenants) + the "
                  "overprovisioned-incumbent challenger scenario",
        "n_tenants": n_tenants,
        "ticks": ticks,
        "seed": seed,
        "repeats": repeats,
        "primary": primary_name,
        "roster": list(roster),
        "k": len(roster),
        "profiles": {"healthy": max(n_tenants - 3 * n_stress, 0),
                     "batch": n_stress, "slow": n_stress,
                     "flaky": n_stress},
        "p50_tick_ms_off": round(best_off, 3),
        "ledger_overhead_ms_per_tick": round(overhead_ms, 4),
        "ledger_overhead_frac": round(max(overhead, 0.0), 4),
        "ledger_overhead_raw_frac": round(overhead, 4),
        "bitwise_identical": bool(bitwise),
        "k_curve": k_curve,
        "board": board,
        "board_gate_ok": board_gate_ok,
        "win_rate_last": det_on["win_rate_last"],
        "leader_last": det_on["leader_last"],
        "window_ticks": det_on["ticks_total"],
        "challenger": {
            "scenario": "OverProvisionPolicy incumbent (hpa 1.5, "
                        "consolidation off) vs ('carbon',) roster, "
                        f"window 8 / sustain 4 / bar 0.6, {ch_n} "
                        f"tenants x {challenger_ticks} ticks",
            "incidents": len(ch_records),
            "dumps_verified": ch_dumps_verified,
            "dump_failures": ch_failures,
            "win_rate_last": ch["win_rate_last"],
            "audit_rows": len(audit_rows),
            "audits_verified": int(audits_verified),
        },
        "challenger_gate_ok": challenger_gate_ok,
        "incidents": det_on["incidents"],
        "overhead_gate_frac": 0.05,
        "overhead_gate_ok": bool(max(overhead, 0.0) < 0.05),
    }
    print(f"# tournament: p50 off {out['p50_tick_ms_off']:.3f}ms, "
          f"ledger overhead {out['ledger_overhead_ms_per_tick']:.3f}"
          f"ms/tick ({out['ledger_overhead_frac'] * 100:.2f}% of p50) "
          f"at K={out['k']}, bitwise={out['bitwise_identical']}, "
          f"board gate {out['board_gate_ok']}, challenger "
          f"{out['challenger']['incidents']} incident(s) "
          f"({out['challenger']['dumps_verified']} dump(s), "
          f"{out['challenger']['audits_verified']} audit(s) verified)",
          file=sys.stderr)
    return out


def bench_geo(*, steps: int = 192, batch: int = 8, suite_seed: int = 0,
              seed: int = 23) -> dict | None:
    """Geo-arbitrage stage (ISSUE 16, `ccka_tpu/regions`): the
    DCcluster-Opt-style scenario suite (regional spot storms, capacity
    denials, migratable batch backfill) scored as per-workload-class
    cost/carbon/SLO Pareto fronts, plus the zero-migration parity arm
    the acceptance criterion pins. Gates on the record (the `ccka
    bench-diff` geo invariants):

    - ``zero_migration_parity``: (a) widening a stream with the
      "regions" lane family leaves the pre-geo rows bitwise unchanged
      and the lax + kernel engines consume the widened stream bitwise
      (the round-17 registry contract — the round-18 multiregion
      rollout is exactly this path with geo off, so zero-rate geo is
      bitwise the round-18 record); (b) the lane block is bitwise the
      hand-threaded generation; (c) the `none` policy's migration term
      is EXACTLY 0 and its rollout is bitwise a zero-rate override
      rollout;
    - ``dominance_found``: >=1 scenario where a migration policy
      STRICTLY dominates `none` on some class front;
    - every per-class front row present and mutually non-dominated;
    - ledger rows carry the migration term with |sum(shares) - 1|
      <= 1e-12.

    Host-side invariants stage — no roofline floor applies."""
    import dataclasses

    from ccka_tpu.config import ObsConfig, multi_region_config
    from ccka_tpu.obs.decisions import DecisionLedger
    from ccka_tpu.regions import geo as geo_dyn
    from ccka_tpu.regions import pareto as geo_pareto
    from ccka_tpu.regions.migrate import GEO_POLICIES
    from ccka_tpu.regions.process import packed_region_lanes
    from ccka_tpu.sim import SimParams, lanes
    from ccka_tpu.sim.megakernel import packed_mode_summary_fn
    from ccka_tpu.sim.rollout import lax_mode_summary
    from ccka_tpu.signals.synthetic import SyntheticSignalSource

    cfg = multi_region_config()
    Z = cfg.cluster.n_zones
    zri = cfg.cluster.zone_region_index
    geo = dataclasses.replace(
        geo_pareto.GEO_SCENARIOS["spot-storm"].geo, zone_region_index=zri)

    # -- parity arm (bitwise; small geometry, interpret kernels) ------
    P_B, P_T, P_TC = 32, 16, 8
    plain_src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                      cfg.signals)
    wide_src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                     cfg.signals,
                                     extra_lanes={"regions": geo})
    key = jax.random.key(seed)
    ps = plain_src.packed_trace_device(P_T, key, P_B, t_chunk=P_TC)
    ws = wide_src.packed_trace_device(P_T, key, P_B, t_chunk=P_TC)
    lay = lanes.resolve_layout(ws.shape[1], Z)
    lo, hi = lay.block("regions")
    parity = {}
    parity["pre_geo_rows_bitwise"] = bool(
        np.array_equal(np.asarray(ps), np.asarray(ws[:, :lo])))
    # jit the reference: the widened stream is synthesized under jit,
    # and XLA's fused float ops differ from eager at the ulp level.
    ref = jax.jit(lambda k: packed_region_lanes(
        geo, k, P_T, ws.shape[0], Z, P_B, dt_s=cfg.sim.dt_s))(key)
    parity["lane_block_bitwise_reference"] = bool(
        np.array_equal(np.asarray(ws[:, lo:hi]), np.asarray(ref)))
    params = SimParams.from_config(cfg)
    kkey = jax.random.key(7)
    a = lax_mode_summary(params, cfg.cluster, "rule", ps, P_T, kkey)
    b = lax_mode_summary(params, cfg.cluster, "rule", ws, P_T, kkey)
    parity["lax_engine_bitwise"] = not {
        f for f in a._fields
        if not np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f)))}
    kfn = packed_mode_summary_fn(params, cfg.cluster, "rule", T=P_T,
                                 b_block=8, t_chunk=P_TC, interpret=True,
                                 stochastic=False)
    ka, kb = kfn(ps, 3), kfn(ws, 3)
    parity["kernel_engine_bitwise"] = not {
        f for f in ka._fields
        if not np.array_equal(np.asarray(getattr(ka, f)),
                              np.asarray(getattr(kb, f)))}
    # Zero-rate overlay: `none` policy == zero override, bitwise, and
    # its migration dollars are EXACTLY zero.
    from ccka_tpu.regions.process import region_step_from_block
    step = region_step_from_block(ws[:, lo:hi], P_T, Z, geo)
    roll_none = geo_dyn.geo_rollout(geo, GEO_POLICIES["none"], step)
    zeros = np.zeros((geo.n_regions, geo.n_regions, 3), np.float32)
    roll_zero = geo_dyn.geo_rollout(geo, GEO_POLICIES["balanced"], step,
                                    rates_override=zeros)
    parity["zero_rate_migration_term_exact_zero"] = bool(
        float(np.abs(np.asarray(roll_none.migration_cost_usd)).max())
        == 0.0)
    parity["zero_rate_rollout_bitwise_none"] = not {
        f for f in roll_none._fields
        if not np.array_equal(np.asarray(getattr(roll_none, f)),
                              np.asarray(getattr(roll_zero, f)))}
    zero_migration_parity = all(parity.values())

    # -- the scenario suite -------------------------------------------
    suite = geo_pareto.run_geo_suite(
        scenarios=sorted(geo_pareto.GEO_SCENARIOS),
        policies=sorted(GEO_POLICIES),
        zone_region_index=zri, seed=suite_seed, steps=steps,
        batch=batch, dt_s=cfg.sim.dt_s)

    # -- ledger integration: geo ticks carry the migration term -------
    ledger = DecisionLedger(
        ObsConfig(enabled=True, decisions_enabled=True),
        cfg.train, policy="geo-balanced")
    roll = geo_dyn.geo_rollout(
        geo, GEO_POLICIES["balanced"],
        region_step_from_block(ws[:, lo:hi], P_T, Z, geo))
    n_rows, mig_share_max, share_err_max = 8, 0.0, 0.0
    act = np.zeros(4)
    for t in range(n_rows):
        mig_usd = float(np.asarray(roll.migration_cost_usd[t, 0]))
        base = dict(cost_usd=float(np.asarray(roll.cost_usd[t, 0])),
                    carbon_g=float(np.asarray(roll.carbon_g[t, 0])),
                    pend_c0=float(np.asarray(
                        roll.pending[t, 0, :, 0].sum())),
                    pend_c1=float(np.asarray(
                        roll.pending[t, 0, :, 1].sum())),
                    slo_ok=1.0)
        rec = ledger.observe_single(
            t, lane="fresh", action=act, exo={}, state={},
            chosen=dict(base, migration_cost_usd=mig_usd),
            shadow=base, shadow_action=act,
            migration_components={"total": mig_usd})
        del rec
    for row in ledger.rows:
        shares = row["objective"]["shares"]
        share_err_max = max(share_err_max,
                            abs(sum(shares.values()) - 1.0))
        mig_share_max = max(mig_share_max, shares.get("migration", 0.0))
    ledger_out = {
        "rows": len(ledger.rows),
        "term_share_err_max": float(share_err_max),
        "migration_share_max": float(mig_share_max),
        "migration_term_present": all(
            "migration" in r["objective"]["terms"] for r in ledger.rows),
    }

    out = {
        "engine": "geo scenario suite (shared lanes per scenario, "
                  "batched expectation dynamics) + bitwise parity arm "
                  "(plain vs regions-widened stream, lax + interpret "
                  "kernel)",
        "steps": steps,
        "batch": batch,
        "suite_seed": suite_seed,
        "zone_region_index": list(zri),
        "parity": parity,
        "zero_migration_parity": bool(zero_migration_parity),
        "scenarios": suite["scenarios"],
        "policies": suite["policies"],
        "classes": suite["classes"],
        "dominance_found": bool(suite["dominance_found"]),
        "max_conservation_residual": suite["max_conservation_residual"],
        "conservation_gate_pods": 0.01,
        "conservation_gate_ok": bool(
            suite["max_conservation_residual"] <= 0.01),
        "ledger": ledger_out,
        "share_gate_err": 1e-12,
        "share_gate_ok": bool(share_err_max <= 1e-12),
    }
    dom_rows = [
        f"{s['scenario']}/{k}:{'+'.join(f['dominates_none'])}"
        for s in suite["scenarios"] for k, f in s["pareto"].items()
        if f["dominates_none"]]
    print(f"# geo: parity={zero_migration_parity} "
          f"dominance={out['dominance_found']} "
          f"({'; '.join(dom_rows) or 'none'}), residual "
          f"{out['max_conservation_residual']:.2e} pods, ledger "
          f"{ledger_out['rows']} rows (share err "
          f"{ledger_out['term_share_err_max']:.2e}, migration share "
          f"max {ledger_out['migration_share_max']:.3f})",
          file=sys.stderr)
    return out


def bench_fleet_scale(*, tenants=(16, 256, 1024, 4096, 10240),
                      ticks: int = 12, seed: int = 211,
                      speedup_n: int = 4096) -> dict | None:
    """Fleet-scale host-loop stage (round 21,
    `harness/fleetscale.py`): the 10^4-tenant tail-latency record.
    Sweeps N x {calm, 25% slow + moderate chaos} through the
    vectorized host loop (chunked tenant-axis dispatch at N>=1024 via
    `sim/lanes.chunk_layout`), and states the acceptance surface on
    the record itself (the `ccka bench-diff` fleet-scale gates):

    - ``parity.bitwise_identical``: vectorized-vs-object host loop,
      same seeded world on the det clock — report counters, patch
      streams, held rows, accumulators, breaker transitions;
    - ``chunk_parity.bitwise_identical``: chunked vs unchunked
      dispatch at N=1024;
    - ``speedup.ratio``: object/vectorized host-loop µs per tenant at
      N=4096 calm (gate >= 10x);
    - ``invariants.healthy_ratio_exact_all``: paired healthy-tenant
      $/SLO-hr ratio EXACTLY 1.0 in every stressed cell.

    Host-side wall-clock harness (latencies are real time; the
    host-loop gauge subtracts virtual-clock offsets) — no roofline
    floor applies."""
    from ccka_tpu.config import default_config
    from ccka_tpu.harness.fleetscale import fleet_scale_record

    return fleet_scale_record(default_config(), tenants=tenants,
                              ticks=ticks, seed=seed,
                              speedup_n=speedup_n)


def bench_search(cfg=None, *, s_cells: int = 6, repeats: int = 3,
                 loop_cells: int = 3, cem_iters: int = 2,
                 seed: int = 17) -> dict:
    """Traced scenario-parameter axis stage (round 22,
    `ccka_tpu/search/`): ONE compiled program for S×B scenario sweeps,
    and the adversarial search it unlocks. The record states its own
    acceptance surface (the `ccka bench-diff` search gates):

    - ``speedup.ratio``: traced-axis scenario-cells/sec (steady-state:
      post-warmup ``set_params`` swaps re-dispatch one compiled
      program) over the per-config recompile loop (a fresh config-baked
      source + kernel per cell — the retrace THAT path pays per cell is
      its steady state, so it is timed compile-inclusive). Gate >= 10x.
    - ``traced.recompiles_during_swaps``: watch_jit-counted kernel
      compiles across the timed swap window — must be 0 (the whole
      point of lifting params out of compile-time config).
    - ``parity.s1_stream_bitwise`` / ``parity.s1_summary_bitwise``:
      the S=1 traced axis vs the config-baked generation path, same
      key/geometry — BITWISE (`tests/test_search.py` pins the same).
      Cross-width S>1 programs differ at ulp (XLA fusion order), so the
      N-cell cross-check is ``parity.ncell_allclose`` with the observed
      max |Δ| recorded.
    - ``search.dominates``: a short CEM run's minted worst case must
      STRICTLY exceed the policy's worst hand-named scenario cell on
      the same harness ($/SLO-hr, same key, same geometry).

    CPU hosts run CI-sized interpret-mode geometry (the scoreboard's
    own sizing); real chips run the Mosaic kernel stochastic."""
    import dataclasses as _dc

    from ccka_tpu.config import default_config
    from ccka_tpu.obs.compile import compile_report
    from ccka_tpu.search.adversarial import (ScenarioScorer,
                                             intensity_bounds,
                                             search_scenarios)
    from ccka_tpu.search.params import PARAM_NAMES, ScenarioParams
    from ccka_tpu.signals.synthetic import SyntheticSignalSource
    from ccka_tpu.sim import SimParams
    from ccka_tpu.sim.megakernel import packed_mode_summary_fn

    cfg = cfg or default_config()
    objective = "usd_per_slo_hour"
    scorer = ScenarioScorer(cfg, policy="rule", seed=seed)

    # Deterministic cell batch: uniform in the "moderate" box (rates >0
    # so every family's lanes do work in every cell).
    box = intensity_bounds("moderate")
    lo = np.asarray([box[n][0] for n in PARAM_NAMES])
    hi = np.asarray([box[n][1] for n in PARAM_NAMES])
    rng = np.random.default_rng(seed)
    nat = lo + rng.uniform(size=(s_cells, len(PARAM_NAMES))) * (hi - lo)
    cells = ScenarioParams.from_array(nat).clip_to_bounds(box)

    # -- traced axis: warm once, then time set_params swaps ----------
    traced_vals = scorer.score(cells)[objective]          # warmup compile
    rep0 = compile_report()
    axis_fns0 = len(scorer.source._axis_fns)
    t0 = time.perf_counter()
    for r in range(repeats):
        rolled = ScenarioParams.from_array(
            np.roll(cells.to_array(), r, axis=0))
        scorer.score(rolled)                  # one dispatch, S cells
    dt_traced = time.perf_counter() - t0
    rep1 = compile_report()
    # Kernel compiles are watch_jit-counted; generation retraces show
    # up as new entries in the axis source's trace cache. Both must be
    # zero across the swap window — set_params is a data swap.
    recompiles = (sum(v.get("compiles", 0) for v in rep1.values())
                  - sum(v.get("compiles", 0) for v in rep0.values())
                  + len(scorer.source._axis_fns) - axis_fns0)
    traced_cps = s_cells * repeats / dt_traced

    # -- per-config recompile loop: fresh baked source + kernel/cell --
    loop_cells = min(loop_cells, s_cells)
    loop_vals = []
    t0 = time.perf_counter()
    for i in range(loop_cells):
        fa, wl, geo = cells.row(i).to_config(
            0, base_faults=scorer.base_faults,
            base_workloads=scorer.base_workloads,
            base_geo=scorer.base_geo)
        src = SyntheticSignalSource(
            cfg.cluster, cfg.workload, cfg.sim, cfg.signals,
            faults=fa, workloads=wl, extra_lanes={"regions": geo})
        stream = src.packed_trace_device(
            scorer.steps, scorer.key, scorer.inner,
            t_chunk=scorer.t_chunk)
        fn = packed_mode_summary_fn(
            SimParams.from_config(
                _dc.replace(cfg, faults=fa, workloads=wl, geo=geo)),
            cfg.cluster, "rule", T=scorer.steps, b_block=scorer.b_block,
            t_chunk=scorer.t_chunk, interpret=not scorer.on_tpu,
            stochastic=scorer.on_tpu)
        summary = fn(stream, scorer.seed)
        loop_vals.append(float(np.asarray(
            getattr(summary, objective)).mean()))
    dt_loop = time.perf_counter() - t0
    loop_cps = loop_cells / dt_loop
    speedup = traced_cps / loop_cps if loop_cps > 0 else float("inf")

    # -- N-cell cross-check: traced batch vs per-config loop ---------
    deltas = [abs(float(traced_vals[i]) - loop_vals[i])
              for i in range(loop_cells)]
    ncell_ok = all(d <= 1e-4 + 1e-3 * abs(v)
                   for d, v in zip(deltas, loop_vals))

    # -- S=1 bitwise parity: cell 0 through both paths ---------------
    fa, wl, geo = cells.row(0).to_config(
        0, base_faults=scorer.base_faults,
        base_workloads=scorer.base_workloads, base_geo=scorer.base_geo)
    baked_src = SyntheticSignalSource(
        cfg.cluster, cfg.workload, cfg.sim, cfg.signals,
        faults=fa, workloads=wl, extra_lanes={"regions": geo})
    baked_stream = baked_src.packed_trace_device(
        scorer.steps, scorer.key, scorer.inner, t_chunk=scorer.t_chunk)
    scorer.source.set_params(cells.row(0))
    axis_stream = scorer.source.packed_trace_device(
        scorer.steps, scorer.key, scorer.inner, t_chunk=scorer.t_chunk)
    stream_bitwise = bool(np.array_equal(np.asarray(baked_stream),
                                         np.asarray(axis_stream)))
    summary_bitwise = bool(_summaries_bitwise_equal(
        scorer.mode_fn(baked_stream, scorer.seed),
        scorer.mode_fn(axis_stream, scorer.seed)))

    # -- the harness the axis unlocks: mini CEM + minted dominance ---
    result = search_scenarios(cfg, policy="rule", objective=objective,
                              iters=cem_iters, pop=s_cells, seed=seed,
                              intensity="moderate", scorer=scorer)
    sign = -1.0 if objective == "slo_attainment" else 1.0
    hand_worst = max(sign * v for v in result.hand_named.values()) * sign

    return {
        "engine": "traced ScenarioParams axis (search/axis.py): derived "
                  "per-family parameters as traced pytree args, vmapped "
                  "lane cores, one compiled program per (S, geometry)",
        "geometry": {"steps": scorer.steps, "inner_batch": scorer.inner,
                     "t_chunk": scorer.t_chunk, "b_block": scorer.b_block,
                     "s_cells": s_cells, "repeats": repeats,
                     "seed": seed, "policy": "rule",
                     "objective": objective,
                     "backend": "tpu" if scorer.on_tpu else "cpu"},
        "traced": {"cells": s_cells, "repeats": repeats,
                   "seconds": round(dt_traced, 4),
                   "cells_per_sec": round(traced_cps, 3),
                   "recompiles_during_swaps": int(recompiles)},
        "recompile_loop": {"cells": loop_cells,
                           "seconds": round(dt_loop, 4),
                           "cells_per_sec": round(loop_cps, 4),
                           "basis": "fresh config-baked source + kernel "
                                    "per cell; compile-inclusive (the "
                                    "retrace is that path's steady "
                                    "state)"},
        "speedup": {"ratio": round(speedup, 2),
                    "gate": ">= 10x traced-axis scenario-cells/sec over "
                            "the per-config recompile loop",
                    "pass": bool(speedup >= 10.0)},
        "parity": {"s1_stream_bitwise": stream_bitwise,
                   "s1_summary_bitwise": summary_bitwise,
                   "ncell_allclose": bool(ncell_ok),
                   "ncell_max_abs_delta": round(max(deltas), 9)
                   if deltas else 0.0,
                   "ncell_values_traced": [round(float(v), 6)
                                           for v in traced_vals[:loop_cells]],
                   "ncell_values_loop": [round(v, 6) for v in loop_vals]},
        "search": {"policy": result.policy, "objective": result.objective,
                   "iters": cem_iters, "pop": s_cells,
                   "evals": result.evals,
                   "minted": {"name": result.scenario.name,
                              "params_digest": result.scenario.params_digest,
                              "value": round(result.best_value, 6)},
                   "hand_named": {k: round(v, 6)
                                  for k, v in result.hand_named.items()},
                   "hand_worst": round(hand_worst, 6),
                   "dominates": bool(result.dominates),
                   "history": result.history},
    }


PERF_MODES = ("rule", "carbon", "neural", "plan")


def _perf_net_params(cfg, seed: int = 3):
    """Non-trivial ActorCritic weights for the neural mode's timing
    (content-independent throughput, but a zero-init head would let a
    layout bug emit constants and still look fast)."""
    from ccka_tpu.models import ActorCritic, latent_dim
    from ccka_tpu.sim.megakernel import _obs_dim

    net = ActorCritic(act_dim=latent_dim(cfg.cluster))
    key = jax.random.key(seed)
    return net.init(key, jnp.zeros(
        (_obs_dim(cfg.cluster.n_pools, cfg.cluster.n_zones),)))


def _perf_kernel_fn(cfg, params, mode: str, *, steps: int, b_block: int,
                    t_chunk: int, interpret: bool, stochastic: bool):
    """One jitted ``(stream, seed) -> EpisodeSummary`` closure per
    megakernel policy mode — `sim.megakernel.packed_mode_summary_fn`
    (shared with `ccka perf`) with the neural mode's fresh weights
    supplied."""
    from ccka_tpu.sim.megakernel import packed_mode_summary_fn

    return packed_mode_summary_fn(
        params, cfg.cluster, mode, T=steps, b_block=b_block,
        t_chunk=t_chunk, interpret=interpret, stochastic=stochastic,
        net_params=_perf_net_params(cfg) if mode == "neural" else None)


def _observatory_span_cost_s(samples: int = 20) -> float:
    """The observatory instrument's own fixed cost: wall time of
    opening and closing one FENCED device span around no work (a fence
    on an already-resident tiny array), median over ``samples``. This —
    not the difference of two noisy kernel timings — is what the 5%
    overhead gate divides by kernel-stage time: a ~15 ms interpret
    kernel swings more than 5% run-to-run on a shared host, so a
    differenced estimate would gate on host jitter instead of the
    instrument."""
    x = jnp.zeros((8,), jnp.float32)
    jax.block_until_ready(x)
    costs = []
    for i in range(samples):
        with _TRACER.span("perf.overhead_probe", sample=i) as outer:
            with _TRACER.device_span("perf.overhead_inner") as sp:
                sp.fence(x)
        costs.append(outer.dur_s)
    return float(np.median(costs))


def _summaries_bitwise_equal(a, b) -> bool:
    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    return len(leaves_a) == len(leaves_b) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(leaves_a, leaves_b))


def bench_perf(cfg, *, steps: int = 96, batch: int = 256,
               b_block: int = 128, t_chunk: int = 32, repeats: int = 3,
               modes=PERF_MODES) -> dict:
    """Device-time performance observatory (round 15): for every packed
    megakernel policy mode, (a) the OCCUPANCY LEDGER of the packed
    generate→rollout→summary pipeline — fenced per-stage seconds and
    fractions (`obs/occupancy.py`), the baseline any double-buffering
    claim must beat; (b) XLA COST-MODEL ATTRIBUTION — the fused
    program's FLOPs / bytes accessed / peak memory from
    `Compiled.cost_analysis()`/`memory_analysis()`, cross-checked
    against the hand-counted byte floor (>2x disagreement warns, both
    recorded); (c) the ACHIEVED-ROOFLINE FRACTION of the measured
    kernel stage (XLA bytes per second over measured streaming
    bandwidth); and (d) two self-gates the record carries —
    observatory-on/off decision streams BITWISE identical, and the
    measurement's own overhead within 5% of kernel-stage wall time.
    On a CPU host the kernel runs interpret-mode deterministic (labeled
    — it validates the instrument, not absolute speed); real chips run
    the Mosaic kernel stochastic."""
    from ccka_tpu.obs import costmodel
    from ccka_tpu.obs import occupancy as occ
    from ccka_tpu.sim import SimParams

    platform = jax.devices()[0].platform
    virtual = platform == "cpu"
    interpret, stochastic = virtual, not virtual
    params = SimParams.from_config(cfg)
    src = _make_src(cfg)
    bw = _measured_hbm_bandwidth()
    days = steps * cfg.sim.dt_s / 86400.0

    # Generation program: compiled once per (steps, batch, t_chunk) and
    # shared by every mode; attribution reads its XLA-reported cost.
    from ccka_tpu.obs.compile import watch_jit as _watch
    gen_jit = _watch(jax.jit(src.packed_generate_fn(steps, batch,
                                                    t_chunk=t_chunk)),
                     "perf.packed_generation", shared_stats=True)
    stream0 = gen_jit(jax.random.key(7))
    jax.block_until_ready(stream0)  # compile = setup, excluded
    gen_rec = costmodel.attribute("perf.packed_generation", gen_jit,
                                  jax.random.key(7))
    hand_bytes = float(stream0.size * 4)  # one full read of the stream

    span_cost_s = _observatory_span_cost_s()
    out_modes = {}
    overheads = []
    bitwise_all = True
    for mode in modes:
        kfn = _perf_kernel_fn(cfg, params, mode, steps=steps,
                              b_block=b_block, t_chunk=t_chunk,
                              interpret=interpret, stochastic=stochastic)
        warm = kfn(stream0, 0)
        jax.block_until_ready(warm)  # compile = setup, excluded
        rec = costmodel.attribute(f"megakernel.mode.{mode}", kfn,
                                  stream0, 0)
        cross = costmodel.crosscheck_bytes(
            f"megakernel.mode.{mode}", hand_bytes, rec.bytes_accessed)

        # Occupancy: fresh world per repeat (byte-identical repeats can
        # be short-circuited by tunneled backends), fenced spans.
        def gen_i(i):
            return gen_jit(jax.random.key(1000 + i))

        def kern_i(stream, i):
            return kfn(stream, i + 1)

        def host_i(summary):
            # The host stage the controller actually pays: pull the
            # batch-mean KPIs off the device.
            return {f: float(np.asarray(getattr(summary, f)).mean())
                    for f in summary._fields}

        ledger, _ = occ.measure_packed_pipeline(
            gen_i, kern_i, host_i, repeats=repeats, tracer=_TRACER,
            label=f"perf.{mode}")

        # Kernel-stage wall time without instrumentation (best-of-N,
        # distinct seeds): the mode's published rate, and the
        # denominator of the overhead gate — the instrument's fixed
        # span cost (probed once above) over this kernel's stage time.
        call_i = [100]

        def bare_once():
            call_i[0] += 1
            s = kfn(stream0, call_i[0])
            jax.block_until_ready(s.cost_usd)

        dt_bare = _time_best(bare_once, max(repeats, 3),
                             bytes_touched=hand_bytes,
                             label=f"perf.{mode}.kernel_bare")
        overhead = (span_cost_s / dt_bare if dt_bare else None)
        if overhead is not None:
            overheads.append(overhead)

        # Non-interference: the SAME (stream, seed) with and without
        # the observatory's spans must be bitwise identical.
        with _TRACER.device_span(f"perf.{mode}.bitwise_on") as sp:
            s_on = kfn(stream0, 5)
            sp.fence(s_on)
        s_off = kfn(stream0, 5)
        jax.block_until_ready(s_off)
        bitwise = _summaries_bitwise_equal(s_on, s_off)
        bitwise_all = bitwise_all and bitwise

        kernel_s = (dt_bare if dt_bare is not None
                    else ledger.seconds["kernel"]
                    / max(ledger.repeats, 1))
        ach = costmodel.achieved_roofline_fraction(
            kernel_s, bytes_accessed=rec.bytes_accessed or hand_bytes,
            bandwidth_bytes_per_s=bw) if kernel_s else None
        out_modes[mode] = {
            "occupancy": ledger.to_dict(),
            "kernel_seconds": (round(kernel_s, 6)
                               if kernel_s is not None else None),
            "cluster_days_per_sec": (round(batch * days / kernel_s, 2)
                                     if kernel_s else None),
            "achieved_roofline_fraction": (round(ach, 6)
                                           if ach is not None else None),
            "programs": [r.to_dict() for r in (rec, gen_rec)],
            "bytes_crosscheck": cross,
            "bitwise_identical": bool(bitwise),
            "observer_overhead_frac": (round(overhead, 6)
                                       if overhead is not None else None),
        }
        print(f"# perf[{mode}]: kernel "
              f"{kernel_s:.4f}s" if kernel_s is not None else
              f"# perf[{mode}]: kernel unmeasured", file=sys.stderr)
        print("#   occupancy "
              + "/".join(f"{k}={v:.2f}"
                         for k, v in ledger.fractions().items())
              + f", achieved {ach if ach is None else round(ach, 4)}, "
              f"bitwise={bitwise}", file=sys.stderr)

    rule = out_modes.get("rule") or next(iter(out_modes.values()))
    # Publish the rule-mode pipeline for the promexport gauges (the
    # fleet service's obs block exports what was last measured).
    costmodel.publish_pipeline_snapshot(
        occupancy=rule["occupancy"]["fractions"],
        achieved_fraction=rule["achieved_roofline_fraction"])
    out = {
        "metric": "device-time observatory: occupancy ledger + XLA "
                  "cost-model attribution per megakernel mode",
        "engine": "packed generate->rollout->summary pipeline "
                  "(obs/occupancy + obs/costmodel)",
        "platform": platform,
        "virtual": virtual,
        "interpret": interpret,
        "stochastic": stochastic,
        "steps": steps, "batch": batch, "b_block": b_block,
        "t_chunk": t_chunk, "repeats": repeats,
        "bandwidth_bytes_per_s": round(bw, 1),
        "hand_stream_bytes": hand_bytes,
        "modes": out_modes,
        "observatory": {
            # Instrument cost over the FASTEST mode's kernel stage —
            # the worst case the 5% budget must cover.
            "span_cost_s": round(span_cost_s, 8),
            "overhead_frac": (round(max(overheads), 6)
                              if overheads else None),
            "overhead_gate_frac": 0.05,
            "overhead_gate_ok": (bool(max(overheads) <= 0.05)
                                 if overheads else None),
            "bitwise_all": bool(bitwise_all),
        },
        # The refreshed single-chip record (the ARCHITECTURE §6 claim
        # predates the packed/donated pipeline and the 21-row layout;
        # this row is what THIS host measures under the observatory,
        # platform-labeled so a CPU interpret row can never masquerade
        # as the v5e number).
        "single_chip": {
            "engine": "megakernel packed rule (single device)",
            "batch": batch, "steps": steps,
            "seconds": rule["kernel_seconds"],
            "cluster_days_per_sec": rule["cluster_days_per_sec"],
            "note": ("interpret-mode deterministic on a CPU host — "
                     "validates the instrument, not absolute speed"
                     if virtual else "Mosaic kernel, stochastic"),
        },
    }
    if virtual:
        out["note"] = ("CPU host: interpret-mode deterministic kernel — "
                       "the occupancy/attribution INSTRUMENT is the "
                       "result; real-chip rates come from a TPU host")
    return out


def bench_perf_mesh(cfg, *, shards: int = 8, steps: int = 96,
                    per_shard_batch: int = 64, t_chunk: int = 32,
                    repeats: int = 2) -> dict | None:
    """The observatory's 8-shard section: the sharded packed pipeline's
    occupancy ledger (shard-local generation → sharded kernel launch →
    host reduction, fenced) plus PER-SHARD kernel seconds — the
    measured mesh stream sliced into the exact lane blocks the data
    axis gave each chip (`parallel.shard_lane_blocks`), each replayed
    through the single-device entry with its `shard_seed` offset
    (bitwise that shard's own work), so the max/mean SHARD-IMBALANCE
    metric attributes slowness to a shard instead of inferring it from
    the mesh barrier."""
    from ccka_tpu.config import MeshConfig
    from ccka_tpu.obs import occupancy as occ
    from ccka_tpu.parallel import (make_mesh, shard_lane_blocks,
                                   shard_seed,
                                   sharded_megakernel_summary_from_packed,
                                   sharded_packed_trace)
    from ccka_tpu.policy.rule import offpeak_action, peak_action
    from ccka_tpu.sim import SimParams
    from ccka_tpu.sim.megakernel import megakernel_summary_from_packed

    if len(jax.devices()) < shards:
        print(f"# perf-mesh: {len(jax.devices())} device(s) < {shards} "
              "shards — skipped (virtual-mesh child carries the "
              "section)", file=sys.stderr)
        return None
    platform = jax.devices()[0].platform
    virtual = platform == "cpu"
    interpret, stochastic = virtual, not virtual
    params = SimParams.from_config(cfg)
    src = _make_src(cfg)
    off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
    b_block = per_shard_batch
    B = shards * per_shard_batch
    mesh = make_mesh(MeshConfig(data_parallel=shards),
                     devices=jax.devices()[:shards])
    kw = dict(stochastic=stochastic, b_block=b_block, t_chunk=t_chunk,
              interpret=interpret)

    # Warm both programs (compile = setup, excluded from the ledger).
    stream = sharded_packed_trace(mesh, src, steps, jax.random.key(7), B,
                                  t_chunk=t_chunk)
    s = sharded_megakernel_summary_from_packed(
        mesh, params, off, peak, stream, steps, seed=0, **kw)
    jax.block_until_ready(s.cost_usd)

    def gen_i(i):
        return sharded_packed_trace(mesh, src, steps,
                                    jax.random.key(500 + i), B,
                                    t_chunk=t_chunk)

    def kern_i(stream, i):
        return sharded_megakernel_summary_from_packed(
            mesh, params, off, peak, stream, steps, seed=i + 1, **kw)

    def host_i(summary):
        return {f: float(np.asarray(getattr(summary, f)).mean())
                for f in summary._fields}

    ledger, _ = occ.measure_packed_pipeline(
        gen_i, kern_i, host_i, repeats=repeats, tracer=_TRACER,
        label="perf.mesh8")

    # Per-shard replay: the ledger's LAST measured mesh launch —
    # stream regenerated bitwise from its key (deterministic
    # synthesis), sliced into the exact lane blocks the data axis gave
    # each chip (`shard_lane_blocks`), each block replayed with the
    # `shard_seed` offset that launch's seed gave that shard — bitwise
    # that shard's own measured work. Pulled to one device as setup so
    # each fenced span times ONLY that shard's kernel, never a
    # cross-chip gather.
    last_rep = max(repeats, 1) - 1
    last_stream = gen_i(last_rep)        # bitwise: same key, same world
    last_seed = last_rep + 1             # kern_i's seed for that repeat
    blocks = shard_lane_blocks(
        jax.device_put(last_stream, jax.devices()[0]), shards)
    jax.block_until_ready(blocks)
    blocks_per_shard = per_shard_batch // b_block

    def shard_fn(i):
        s = megakernel_summary_from_packed(
            params, off, peak, blocks[i], steps,
            seed=shard_seed(last_seed, i, blocks_per_shard), **kw)
        return s.cost_usd

    jax.block_until_ready(shard_fn(0))  # compile (setup)
    times = occ.measure_shard_times(shard_fn, shards, tracer=_TRACER,
                                    label="perf.mesh8.shard")
    imb = occ.shard_imbalance(times)
    out = {
        "engine": "sharded packed pipeline (shard-local synthesis) + "
                  "per-shard single-device replay",
        "shards": shards,
        "per_shard_batch": per_shard_batch,
        "steps": steps, "b_block": b_block, "t_chunk": t_chunk,
        "platform": platform,
        "virtual": virtual, "interpret": interpret,
        "occupancy": ledger.to_dict(),
        "per_shard_s": [round(t, 6) for t in times],
        "shard_imbalance": round(imb, 6) if imb is not None else None,
        "mesh": bench_provenance(mesh=mesh)["mesh"],
    }
    print(f"# perf-mesh {shards}x{platform}: imbalance "
          f"{out['shard_imbalance']}, occupancy "
          + "/".join(f"{k}={v:.2f}"
                     for k, v in ledger.fractions().items())
          + (" (VIRTUAL+INTERPRET)" if virtual else ""), file=sys.stderr)
    return out


def _perf_mesh_virtual_fallback() -> dict | None:
    """Single-device host: run the observatory's 8-shard section on the
    8-device CPU-virtual mesh in a child process (labeled virtual)."""
    env = dict(os.environ)
    env["CCKA_BENCH_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    return _run_child(
        [sys.executable, os.path.abspath(__file__), "--perf-mesh-only"],
        timeout_s=1200, env=env)


# ---- streaming rollout pipeline stage (ISSUE 13, the BENCH_r16 path) -----

# The paired single-chip sweep: (batch, b_block, T, block_T, t_chunk)
# per row. The small-batch rows are the throughput headline's geometry
# (best kernel-stage rate on the CPU record); the fleet rows are where
# the 10^4-cluster chunked path lives and where overlap matters.
STREAM_SWEEP = (
    (128, 128, 768, 384, 192),
    (256, 256, 384, 192, 96),
    (1024, 256, 384, 192, 96),
    (2048, 512, 192, 96, 96),
)


def _bare_kernel_rate(cfg, params, src, *, B, BB, T, TC,
                      repeats: int = 6, label: str) -> dict:
    """The round-15 headline PROTOCOL at an arbitrary geometry: one
    resident stream, bare best-of-N kernel calls with distinct seeds
    (`bench_perf`'s ``dt_bare``). The streaming record uses it twice —
    once at the r15 geometry (the same-session replication of the
    554.66 headline) and once at the streaming headline geometry — so
    its "improves over round 15" claim is one protocol at two
    geometries, not two protocols."""
    from ccka_tpu.sim.megakernel import packed_mode_summary_fn

    platform = jax.devices()[0].platform
    virtual = platform == "cpu"
    gen = jax.jit(src.packed_generate_fn(T, B, t_chunk=TC))
    stream0 = gen(jax.random.key(7))
    jax.block_until_ready(stream0)
    kfn = packed_mode_summary_fn(params, cfg.cluster, "rule", T=T,
                                 b_block=BB, t_chunk=TC,
                                 interpret=virtual,
                                 stochastic=not virtual)
    jax.block_until_ready(kfn(stream0, 0).cost_usd)   # compile = setup
    call_i = [100]

    def once():
        call_i[0] += 1
        jax.block_until_ready(kfn(stream0, call_i[0]).cost_usd)

    dt = _time_best(once, repeats,
                    bytes_touched=float(stream0.size * 4),
                    label=label)
    days = T * cfg.sim.dt_s / 86400.0
    return {
        "batch": B, "b_block": BB, "steps": T, "t_chunk": TC,
        "seconds": round(dt, 6) if dt else None,
        "cluster_days_per_sec": (round(B * days / dt, 2) if dt
                                 else None),
    }


def _r15_replication(cfg, params, src, *, repeats: int = 6) -> dict:
    """The round-15 headline, REPLICATED by its own protocol in THIS
    session: the r15 geometry (B=256, b_block=128, T=96, t_chunk=32)
    timed exactly as `bench_perf` timed it. Hosts drift between
    sessions, so the streaming record's "improves over round 15"
    comparison is made against THIS row, with the historical 554.66
    quoted beside it — a cross-session absolute would attribute host
    weather to the code."""
    row = _bare_kernel_rate(cfg, params, src, B=256, BB=128, T=96,
                            TC=32, repeats=repeats,
                            label="stream.r15_replication")
    row["engine"] = ("megakernel packed rule (single device) — the "
                     "round-15 protocol re-measured this session")
    row["historical_round15_cluster_days_per_sec"] = 554.66
    return row


def _stream_sync_baseline(cfg, params, src, *, B, BB, T, TC,
                          repeats: int, label: str,
                          interpret: bool, stochastic: bool) -> dict:
    """The SYNCHRONOUS baseline of the streaming comparison — exactly
    the round-15 pipeline unit (`obs.occupancy.measure_packed_pipeline`
    shape): full-horizon packed generation, one kernel launch, host KPI
    reads, every stage fenced. Best-of-N wall + that run's stage
    split."""
    from ccka_tpu.sim.megakernel import packed_mode_summary_fn

    gen_full = jax.jit(src.packed_generate_fn(T, B, t_chunk=TC))
    kfn = packed_mode_summary_fn(params, cfg.cluster, "rule", T=T,
                                 b_block=BB, t_chunk=TC,
                                 interpret=interpret,
                                 stochastic=stochastic)
    s0 = gen_full(jax.random.key(7))
    jax.block_until_ready(s0)
    jax.block_until_ready(kfn(s0, 0).cost_usd)   # compile = setup
    stream_bytes = float(s0.size * 4)
    walls, gens, kerns, hosts = [], [], [], []
    for i in range(max(repeats, 1)):
        with _TRACER.device_span(f"{label}.sync.generation",
                                 repeat=i) as sp:
            stream = gen_full(jax.random.key(300 + i))
            sp.fence(stream)
        g = sp.dur_s
        with _TRACER.device_span(f"{label}.sync.kernel", repeat=i) as sp:
            out = kfn(stream, i)
            sp.fence(out.cost_usd)
        k = sp.dur_s
        with _TRACER.span(f"{label}.sync.host", repeat=i) as sp:
            {f: float(np.asarray(getattr(out, f)).mean())
             for f in out._fields}
        h = sp.dur_s
        walls.append(g + k + h)
        gens.append(g)
        kerns.append(k)
        hosts.append(h)
    best = int(np.argmin(walls))
    wall = walls[best]
    fr = {"generation": gens[best] / wall, "kernel": kerns[best] / wall,
          "host": hosts[best] / wall}
    return {
        "engine": "unblocked synchronous pipeline (round-15 unit: "
                  "full-stream generation -> one launch -> host reads, "
                  "fenced per stage)",
        "wall_s": round(wall, 6),
        "kernel_s": round(kerns[best], 6),
        "occupancy_fractions": {s: round(v, 6) for s, v in fr.items()},
        "repeats": len(walls),
        "stream_bytes": stream_bytes,
        "roofline_floor_s": round(_roofline_floor_s(stream_bytes), 6),
    }


def bench_stream(cfg, *, sweep=STREAM_SWEEP, repeats: int = 4,
                 chunked_batch: int = 10240,
                 chunked_chunk: int = 1024) -> dict:
    """Streaming rollout pipeline stage (ISSUE 13): for each sweep row,
    the SYNCHRONOUS unblocked baseline (the round-15 pipeline unit,
    fenced per stage) against the DOUBLE-BUFFERED blocked drive
    (`sim/streaming.py` — one fence around the whole block loop), plus
    the bitwise gates the record carries about itself:

    - blocked-vs-unblocked: the pipelined summary equals the
      single-launch carry rollout on the concatenated blocks, bitwise;
    - pipelined-vs-sync(blocked): the overlap machinery reorders
      dispatch only — same blocks, same seeds, bitwise;
    - the donation chain holds exactly TWO stream buffers per chip.

    Rates come in two honest flavors per row: ``cluster_days_per_sec``
    (end-to-end wall, generation included) and the KERNEL-STAGE rate
    (the round-15 single-chip metric — its 554.7 CPU-interpret headline
    is the comparison target). ``overlap_capable`` labels whether this
    host can physically overlap two device programs (a single-core CPU
    cannot — its ratio row validates the instrument, not the overlap);
    `ccka bench-diff` gates ratio >= 1.0 only on overlap-capable
    records and holds a 0.9 non-regression floor otherwise."""
    from ccka_tpu.sim import SimParams
    from ccka_tpu.sim import streaming as streaming_mod

    platform = jax.devices()[0].platform
    virtual = platform == "cpu"
    params = SimParams.from_config(cfg)
    src = _make_src(cfg)
    overlap_capable = (os.cpu_count() or 1) > 1
    rows = []
    bitwise_all = True
    for (B, BB, T, BT, TC) in sweep:
        days = T * cfg.sim.dt_s / 86400.0
        label = f"stream.{B}x{T}"
        sync = _stream_sync_baseline(cfg, params, src, B=B, BB=BB, T=T,
                                     TC=TC, repeats=repeats, label=label,
                                     interpret=virtual,
                                     stochastic=not virtual)
        kw = dict(T=T, block_T=BT, t_chunk=TC, b_block=BB,
                  interpret=virtual, stochastic=not virtual)
        # Warm (compile = setup), then best-of-N fresh-world repeats.
        streaming_mod.streaming_rollout_summary(
            src, params, cfg.cluster, "rule", key=jax.random.key(0),
            batch=B, pipelined=True, tracer=_TRACER, label=label, **kw)
        pipe_walls = []
        for i in range(max(repeats, 1)):
            _s, rep = streaming_mod.streaming_rollout_summary(
                src, params, cfg.cluster, "rule",
                key=jax.random.key(100 + i), batch=B, pipelined=True,
                tracer=_TRACER, label=label, **kw)
            pipe_walls.append(rep["wall_s"])
        pipe_wall = float(min(pipe_walls))
        # Bitwise gates on a dedicated (untimed) key: pipelined vs the
        # blocked-sync drive, and vs the unblocked single-launch
        # reference on the same concatenated blocks.
        gate_key = jax.random.key(42)
        s_pipe, rep_b = streaming_mod.streaming_rollout_summary(
            src, params, cfg.cluster, "rule", key=gate_key, batch=B,
            seed=9, pipelined=True, count_buffers=True, tracer=_TRACER,
            label=label, **kw)
        s_sync, _ = streaming_mod.streaming_rollout_summary(
            src, params, cfg.cluster, "rule", key=gate_key, batch=B,
            seed=9, pipelined=False, tracer=_TRACER, label=label, **kw)
        s_ref = streaming_mod.unblocked_reference_summary(
            src, params, cfg.cluster, "rule", key=gate_key, batch=B,
            seed=9, **kw)
        bit_sync = _summaries_bitwise_equal(s_pipe, s_sync)
        bit_unblocked = _summaries_bitwise_equal(s_pipe, s_ref)
        bitwise_all = bitwise_all and bit_sync and bit_unblocked
        ratio = sync["wall_s"] / pipe_wall if pipe_wall else None
        kocc_pipe = (sync["kernel_s"] / pipe_wall if pipe_wall else None)
        row = {
            "batch": B, "b_block": BB, "steps": T, "block_T": BT,
            "t_chunk": TC, "n_blocks": rep["n_blocks"],
            "sync": dict(
                sync,
                cluster_days_per_sec=round(B * days / sync["wall_s"], 2),
                cluster_days_per_sec_kernel_stage=round(
                    B * days / sync["kernel_s"], 2)),
            "pipelined": {
                "engine": "double-buffered blocked drive "
                          "(sim/streaming.py; 2 stream blocks/chip)",
                "wall_s": round(pipe_wall, 6),
                "cluster_days_per_sec": round(B * days / pipe_wall, 2),
                # Attributed: the sync-measured kernel seconds over the
                # pipelined wall — what fraction of the pipelined wall
                # the kernel's own work accounts for.
                "kernel_occupancy_fraction": (round(kocc_pipe, 6)
                                              if kocc_pipe else None),
                "stream_buffers": rep_b.get("stream_buffers"),
                "repeats": len(pipe_walls),
            },
            "throughput_ratio": round(ratio, 4) if ratio else None,
            "bitwise_pipelined_vs_sync": bool(bit_sync),
            "bitwise_blocked_vs_unblocked": bool(bit_unblocked),
        }
        rows.append(row)
        print(f"# stream[{B}x{T}]: sync "
              f"{row['sync']['cluster_days_per_sec']:,} cd/s "
              f"(kernel-stage "
              f"{row['sync']['cluster_days_per_sec_kernel_stage']:,}), "
              f"pipe {row['pipelined']['cluster_days_per_sec']:,} cd/s, "
              f"ratio {row['throughput_ratio']}, "
              f"bitwise={bit_sync and bit_unblocked}, "
              f"buffers={rep_b.get('stream_buffers')}", file=sys.stderr)

    # 10^4-cluster chunked row: bounded memory (2 blocks x lanes x
    # chunk live), with its own sync-drive occupancy ledger and the
    # roofline floor of the bytes one chunk's blocks stream.
    _cb, _cc = chunked_batch, chunked_chunk
    _ck = dict(T=192, block_T=96, t_chunk=96, b_block=min(_cc, 256),
               interpret=virtual, stochastic=not virtual)
    days_c = _ck["T"] * cfg.sim.dt_s / 86400.0
    streaming_mod.chunked_streaming_summary(
        src, params, cfg.cluster, "rule", key=jax.random.key(1),
        batch=_cc, chunk=_cc, pipelined=True, tracer=_TRACER, **_ck)
    _s, rep_c = streaming_mod.chunked_streaming_summary(
        src, params, cfg.cluster, "rule", key=jax.random.key(2),
        batch=_cb, chunk=_cc, pipelined=True, tracer=_TRACER, **_ck)
    _s2, rep_cs = streaming_mod.chunked_streaming_summary(
        src, params, cfg.cluster, "rule", key=jax.random.key(2),
        batch=_cb, chunk=_cc, pipelined=False, tracer=_TRACER, **_ck)
    bit_chunk = _summaries_bitwise_equal(_s, _s2)
    bitwise_all = bitwise_all and bit_chunk
    chunk_block_bytes = rep_c["live_block_bytes"]
    chunked = {
        "engine": "cluster-axis chunked double-buffered streaming "
                  "(sim/streaming.chunked_streaming_summary)",
        "batch": _cb, "chunk": _cc, "chunks": rep_c["chunks"],
        "steps": _ck["T"], "block_T": _ck["block_T"],
        "b_block": _ck["b_block"], "n_blocks": rep_c["n_blocks"],
        "wall_s": round(rep_c["wall_s"], 6),
        "cluster_days_per_sec_aggregate": round(
            _cb * days_c / rep_c["wall_s"], 2),
        "live_block_bytes": chunk_block_bytes,
        "live_block_mib": round(chunk_block_bytes / 2**20, 3),
        "sync_wall_s": round(rep_cs["wall_s"], 6),
        "occupancy": rep_cs["occupancy"],
        "throughput_ratio": round(rep_cs["wall_s"] / rep_c["wall_s"], 4),
        "bitwise_pipelined_vs_sync": bool(bit_chunk),
        "roofline_floor_s": round(_roofline_floor_s(
            chunk_block_bytes / 2 * rep_c["chunks"]
            * rep_c["n_blocks"]), 6),
    }
    print(f"# stream chunked {_cb} clusters ({_cc}/chunk): "
          f"{chunked['cluster_days_per_sec_aggregate']:,} cd/s agg, "
          f"{chunked['live_block_mib']} MiB live blocks, ratio "
          f"{chunked['throughput_ratio']}, bitwise={bit_chunk}",
          file=sys.stderr)

    r15 = _r15_replication(cfg, params, src, repeats=max(repeats, 6))
    print(f"# stream r15 replication: "
          f"{r15['cluster_days_per_sec']} cd/s this session "
          f"(historical record 554.66)", file=sys.stderr)
    head = max(rows, key=lambda r: r["sync"]
               ["cluster_days_per_sec_kernel_stage"])
    # The headline: the r15 bare protocol swept over every row's
    # geometry, best kept — one protocol everywhere, so the
    # vs-replication ratio measures the code/geometry freedom the
    # blocked engine opened (large t_chunk/b_block), not cache
    # temperature or host weather.
    bare_sweep = []
    bare_geoms = [(B, BB, T, TC) for (B, BB, T, _BT, TC) in sweep]
    # Plus the whole-block single-chunk geometries the blocked engine
    # makes natural (t_chunk = block span): fastest on the CPU record.
    bare_geoms += [(256, 256, 192, 192), (256, 256, 96, 96)]
    for (B, BB, T, TC) in bare_geoms:
        b = _bare_kernel_rate(cfg, params, src, B=B, BB=BB, T=T, TC=TC,
                              repeats=max(repeats, 6),
                              label=f"stream.bare.{B}x{T}")
        if b.get("cluster_days_per_sec"):
            bare_sweep.append(b)
        print(f"# stream bare[{B}x{T} tc{TC}]: "
              f"{b['cluster_days_per_sec']} cd/s", file=sys.stderr)
    if bare_sweep:
        head_bare = max(bare_sweep,
                        key=lambda b: b["cluster_days_per_sec"])
    else:
        # Every bare sample fell under the roofline implausibility
        # guard (contended host): fall back to the headline row's
        # fenced fresh-world kernel stage so the stage still emits a
        # record — weaker evidence beats an aborted run.
        print("# stream: every bare kernel sample was implausible — "
              "falling back to the fenced kernel stage",
              file=sys.stderr)
        head_bare = {
            "batch": head["batch"], "b_block": head["b_block"],
            "steps": head["steps"], "t_chunk": head["t_chunk"],
            "seconds": head["sync"]["kernel_s"],
            "cluster_days_per_sec": head["sync"]
            ["cluster_days_per_sec_kernel_stage"],
        }
    paired = max(rows, key=lambda r: r["throughput_ratio"] or 0.0)
    out = {
        "metric": "streaming rollout pipeline: blocked double-buffered "
                  "drive vs the synchronous round-15 pipeline unit, "
                  "paired + bitwise-gated",
        "engine": "sim/streaming.py over the carried-state megakernel "
                  "block entries",
        "platform": platform, "virtual": virtual,
        "interpret": virtual, "stochastic": not virtual,
        "overlap_capable": bool(overlap_capable),
        "host_cpu_count": os.cpu_count(),
        "repeats": repeats,
        "rows": rows,
        "chunked": chunked,
        "bitwise_all": bool(bitwise_all),
        # The round-15-comparable single-chip row: kernel-stage rate at
        # the headline geometry, set against the SAME-SESSION
        # replication of the r15 headline (hosts drift between
        # sessions; the historical 554.66 rides the replication row).
        "r15_replication": r15,
        "kernel_bare_sweep": bare_sweep,
        "single_chip": {
            "engine": "megakernel packed rule at the streaming "
                      "headline geometry (kernel stage, r15 bare "
                      "protocol — blocked launches are bitwise this "
                      "kernel's work)",
            "batch": head_bare["batch"], "steps": head_bare["steps"],
            "b_block": head_bare["b_block"],
            "t_chunk": head_bare["t_chunk"],
            "seconds": head_bare["seconds"],
            "cluster_days_per_sec": head_bare["cluster_days_per_sec"],
            "kernel_stage_fresh_world_cluster_days_per_sec": head[
                "sync"]["cluster_days_per_sec_kernel_stage"],
            "vs_r15_replication": (round(
                head_bare["cluster_days_per_sec"]
                / r15["cluster_days_per_sec"], 4)
                if r15.get("cluster_days_per_sec")
                and head_bare.get("cluster_days_per_sec") else None),
            "note": ("interpret-mode deterministic on a CPU host — "
                     "validates the instrument, not absolute speed"
                     if virtual else "Mosaic kernel, stochastic"),
        },
        "best_paired": {
            "batch": paired["batch"], "steps": paired["steps"],
            "throughput_ratio": paired["throughput_ratio"],
            "sync_kernel_occupancy": paired["sync"]
            ["occupancy_fractions"]["kernel"],
            "pipelined_kernel_occupancy": paired["pipelined"]
            ["kernel_occupancy_fraction"],
        },
    }
    if virtual:
        out["note"] = ("CPU host: interpret-mode deterministic kernel; "
                       "a single-core host cannot physically overlap "
                       "generation with the kernel — the bitwise gates "
                       "and the bounded-memory chunked row are the "
                       "result, real overlap rates come from a "
                       "multi-core/TPU host")
    return out


def bench_stream_mesh(cfg, *, shards: int = 8,
                      per_shard_batch: int = 256, T: int = 384,
                      block_T: int = 192, t_chunk: int = 192,
                      repeats: int = 3) -> dict | None:
    """The streaming stage's 8-shard section: the SAME double-buffered
    block loop over the mesh ``data`` axis — shard-local blocked
    generation, lane-sharded carried state — against the synchronous
    sharded baseline (full-stream shard-local generation + one mesh
    launch, fenced). Also pins the mesh-vs-single-chip pairing: the
    mesh pipelined summary must be bitwise the single-chip
    cluster-chunked run of the same (key, seed)."""
    from ccka_tpu.config import MeshConfig
    from ccka_tpu.parallel import (make_mesh, sharded_packed_trace,
                                   sharded_megakernel_summary_from_packed)
    from ccka_tpu.policy.rule import offpeak_action, peak_action
    from ccka_tpu.sim import SimParams
    from ccka_tpu.sim import streaming as streaming_mod

    if len(jax.devices()) < shards:
        print(f"# stream-mesh: {len(jax.devices())} device(s) < "
              f"{shards} shards — skipped (virtual-mesh child carries "
              "the section)", file=sys.stderr)
        return None
    platform = jax.devices()[0].platform
    virtual = platform == "cpu"
    params = SimParams.from_config(cfg)
    src = _make_src(cfg)
    off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
    B = shards * per_shard_batch
    BB = min(per_shard_batch, 256)
    days = T * cfg.sim.dt_s / 86400.0
    mesh = make_mesh(MeshConfig(data_parallel=shards),
                     devices=jax.devices()[:shards])
    kw = dict(stochastic=not virtual, b_block=BB, t_chunk=t_chunk,
              interpret=virtual)

    # Synchronous sharded baseline (round-15 mesh unit), fenced stages.
    stream0 = sharded_packed_trace(mesh, src, T, jax.random.key(7), B,
                                   t_chunk=t_chunk)
    s0 = sharded_megakernel_summary_from_packed(
        mesh, params, off, peak, stream0, T, seed=0, **kw)
    jax.block_until_ready(s0.cost_usd)     # compile = setup
    walls, kerns = [], []
    gens = []
    for i in range(max(repeats, 1)):
        with _TRACER.device_span("stream.mesh8.sync.generation",
                                 repeat=i) as sp:
            stream = sharded_packed_trace(mesh, src, T,
                                          jax.random.key(300 + i), B,
                                          t_chunk=t_chunk)
            sp.fence(stream)
        g = sp.dur_s
        with _TRACER.device_span("stream.mesh8.sync.kernel",
                                 repeat=i) as sp:
            out = sharded_megakernel_summary_from_packed(
                mesh, params, off, peak, stream, T, seed=i + 1, **kw)
            sp.fence(out.cost_usd)
        k = sp.dur_s
        with _TRACER.span("stream.mesh8.sync.host", repeat=i) as sp:
            {f: float(np.asarray(getattr(out, f)).mean())
             for f in out._fields}
        walls.append(g + k + sp.dur_s)
        gens.append(g)
        kerns.append(k)
    best = int(np.argmin(walls))
    sync_wall, sync_kernel = walls[best], kerns[best]
    occ = {"generation": gens[best] / sync_wall,
           "kernel": sync_kernel / sync_wall,
           "host": 1.0 - (gens[best] + sync_kernel) / sync_wall}

    # The r15 bare protocol on the mesh: resident stream, best-of-N
    # sharded kernel launches — the aggregate the parent compares
    # against the same-session r15 replication (one protocol, both
    # sides).
    call_i = [100]

    def bare_once():
        call_i[0] += 1
        s = sharded_megakernel_summary_from_packed(
            mesh, params, off, peak, stream0, T, seed=call_i[0], **kw)
        jax.block_until_ready(s.cost_usd)

    dt_bare = _time_best(bare_once, max(repeats, 5),
                         bytes_touched=float(stream0.size * 4),
                         label="stream.mesh8.kernel_bare")

    # Double-buffered sharded drive, best-of-N fresh worlds.
    skw = dict(T=T, block_T=block_T, t_chunk=t_chunk, b_block=BB,
               interpret=virtual, stochastic=not virtual)
    streaming_mod.streaming_rollout_summary(
        src, params, cfg.cluster, "rule", key=jax.random.key(0),
        batch=B, mesh=mesh, pipelined=True, tracer=_TRACER,
        label="stream.mesh8", **skw)
    pipe_walls = []
    for i in range(max(repeats, 1)):
        _s, rep = streaming_mod.streaming_rollout_summary(
            src, params, cfg.cluster, "rule", key=jax.random.key(100 + i),
            batch=B, mesh=mesh, pipelined=True, tracer=_TRACER,
            label="stream.mesh8", **skw)
        pipe_walls.append(rep["wall_s"])
    pipe_wall = float(min(pipe_walls))

    # Pairing gate: mesh pipelined == single-chip cluster-chunked,
    # bitwise, same (key, seed).
    gate_key = jax.random.key(42)
    s_mesh, _ = streaming_mod.streaming_rollout_summary(
        src, params, cfg.cluster, "rule", key=gate_key, batch=B, seed=9,
        mesh=mesh, pipelined=True, tracer=_TRACER,
        label="stream.mesh8", **skw)
    s_chunk, _ = streaming_mod.chunked_streaming_summary(
        src, params, cfg.cluster, "rule", key=gate_key, batch=B,
        chunk=per_shard_batch, seed=9, pipelined=True, tracer=_TRACER,
        **skw)
    bitwise = _summaries_bitwise_equal(
        jax.tree.map(np.asarray, s_mesh), s_chunk)
    ratio = sync_wall / pipe_wall if pipe_wall else None
    out = {
        "engine": "sharded double-buffered streaming (shard-local "
                  "blocked generation, lane-sharded carried state) vs "
                  "the synchronous sharded pipeline",
        "shards": shards, "per_shard_batch": per_shard_batch,
        "batch": B, "steps": T, "block_T": block_T,
        "b_block": BB, "t_chunk": t_chunk,
        "platform": platform, "virtual": virtual, "interpret": virtual,
        "sync": {
            "wall_s": round(sync_wall, 6),
            "kernel_s": round(sync_kernel, 6),
            "occupancy_fractions": {s: round(v, 6)
                                    for s, v in occ.items()},
            "cluster_days_per_sec_aggregate": round(
                B * days / sync_wall, 2),
            "cluster_days_per_sec_kernel_stage": round(
                B * days / sync_kernel, 2),
            "kernel_bare_s": (round(dt_bare, 6) if dt_bare else None),
            "cluster_days_per_sec_kernel_bare": (round(
                B * days / dt_bare, 2) if dt_bare else None),
        },
        "pipelined": {
            "wall_s": round(pipe_wall, 6),
            "cluster_days_per_sec_aggregate": round(
                B * days / pipe_wall, 2),
            "kernel_occupancy_fraction": round(
                sync_kernel / pipe_wall, 6),
            "repeats": len(pipe_walls),
        },
        "throughput_ratio": round(ratio, 4) if ratio else None,
        "bitwise_mesh_vs_chunked": bool(bitwise),
        "mesh": bench_provenance(mesh=mesh)["mesh"],
    }
    print(f"# stream-mesh {shards}x{platform}: sync "
          f"{out['sync']['cluster_days_per_sec_aggregate']:,} cd/s agg "
          f"(kernel-stage "
          f"{out['sync']['cluster_days_per_sec_kernel_stage']:,}), "
          f"pipe {out['pipelined']['cluster_days_per_sec_aggregate']:,}"
          f" cd/s, ratio {out['throughput_ratio']}, bitwise={bitwise}"
          + (" (VIRTUAL+INTERPRET)" if virtual else ""), file=sys.stderr)
    return out


def _stream_mesh_virtual_fallback() -> dict | None:
    """Single-device host: run the streaming stage's 8-shard section on
    the 8-device CPU-virtual mesh in a child process (labeled)."""
    env = dict(os.environ)
    env["CCKA_BENCH_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    return _run_child(
        [sys.executable, os.path.abspath(__file__), "--stream-mesh-only"],
        timeout_s=1800, env=env)


FACTORY_SCENARIOS = ("diurnal-inference", "batch-backfill")
FACTORY_INTENSITIES = ("off", "moderate")


def bench_factory(cfg, *, scenarios=FACTORY_SCENARIOS,
                  intensities=FACTORY_INTENSITIES, teacher: str = "mpc",
                  pairs_per_cell: int = 64, steps: int = 96,
                  block_T: int = 48, t_chunk: int = 48,
                  b_block: int = 64, iters: int | None = None,
                  naive_pairs: int = 4, student_iterations: int = 400,
                  seed: int = 41) -> dict:
    """MPC-distillation data factory stage (ISSUE 14): the factory sweep
    (`train/factory.factory_run` — batched full-window planning →
    double-buffered streaming plan playback → batched pair collection)
    across scenario × fault-intensity cells, measured PAIRED against the
    naive per-pair lax `receding_horizon_rollout` loop
    (`naive_lax_pair_rate`, the status-quo protocol at
    cfg.train.mpc_horizon/mpc_iters) in the same record. The headline:
    factory pairs/sec ≥ 5× the naive loop's, on THIS host (labeled
    CPU-interpret off-TPU), with the playback roofline floor and the
    first cell's occupancy ledger attached.

    A full warmup sweep runs first (same shapes, different seeds) so
    the timed sweep measures warm programs on BOTH sides — the naive
    loop is likewise timed warm (its first pair compiles untimed).

    The student column closes the loop: the combined dataset distills
    into a fresh ActorCritic (`imitate(dataset=...)`), and the student
    is scored by the NEURAL kernel on each cell's exact shared worlds —
    paired student-vs-teacher / student-vs-rule $/SLO-hr per cell."""
    from ccka_tpu.sim import SimParams
    from ccka_tpu.sim.megakernel import packed_mode_summary_fn
    from ccka_tpu.train import factory as factory_mod
    from ccka_tpu.train.imitate import imitate

    platform = jax.devices()[0].platform
    virtual = platform == "cpu"
    resolved = factory_mod.validate_factory_names(
        scenarios=scenarios, intensities=intensities, teacher=teacher)
    params = SimParams.from_config(cfg)
    if iters is None:
        iters = factory_mod.FACTORY_ITERS
    fkw = dict(scenarios=scenarios, intensities=intensities,
               teacher=teacher, pairs_per_cell=pairs_per_cell,
               steps=steps, block_T=block_T, t_chunk=t_chunk,
               b_block=b_block, iters=iters)

    # Warm sweep (compile = setup), then the timed sweep.
    with _TRACER.span("factory.warmup"):
        t0 = time.perf_counter()
        factory_mod.factory_run(cfg, seed=seed + 7, **fkw)
        warm_s = time.perf_counter() - t0
    with _TRACER.span("factory.sweep"):
        dataset, report, cells = factory_mod.factory_run(
            cfg, seed=seed, with_ledger=True, return_cells=True, **fkw)

    # The paired baseline: per-pair closed-loop lax MPC, timed warm, on
    # the first cell's trace family.
    first = next(iter(resolved.values()))
    with _TRACER.span("factory.naive_baseline"):
        naive = factory_mod.naive_lax_pair_rate(
            cfg, first, intensities[0], pairs=naive_pairs, steps=steps,
            block_T=block_T, t_chunk=t_chunk, seed=seed)
    ratio = None
    if report.get("pairs_per_sec") and naive.get("pairs_per_sec"):
        ratio = round(report["pairs_per_sec"] / naive["pairs_per_sec"],
                      4)
    print(f"# factory: {report['pairs_total']} pairs at "
          f"{report['pairs_per_sec']} pairs/s "
          f"(plans {report['plans_per_sec']}/s) vs naive "
          f"{naive['pairs_per_sec']} pairs/s -> ratio {ratio}",
          file=sys.stderr)

    # Student: distill the combined dataset, score on each cell's exact
    # shared worlds via the neural kernel (paired with the teacher's
    # playback labels and the rule column from the same streams). The
    # first cell's stream doubles as the playback roofline byte count
    # (every cell's stream has the same shape): exo stream + per-cluster
    # plan stream both stream through the kernel.
    from ccka_tpu.sim.megakernel import _plan_rows
    playback_bytes = None
    with _TRACER.span("factory.distill"):
        student_params, hist = imitate(cfg, None, None, dataset=dataset,
                                       iterations=student_iterations,
                                       seed=seed)
    # One jitted program scores every cell — everything but the stream
    # is loop-invariant.
    kfn = packed_mode_summary_fn(
        params, cfg.cluster, "neural", T=steps, b_block=b_block,
        t_chunk=t_chunk, interpret=virtual, stochastic=not virtual,
        net_params=student_params)
    student_rows = []
    for cell in cells:
        sc = resolved[cell.scenario]
        stream = factory_mod._cell_stream(
            factory_mod._cell_source(cfg, sc, cell.intensity),
            steps=steps, block_T=block_T, t_chunk=t_chunk,
            pairs=pairs_per_cell, key=jax.random.key(cell.report["seed"]))
        if playback_bytes is None:
            plan_bytes = 4 * stream.shape[0] * _plan_rows(
                cfg.cluster.n_pools, cfg.cluster.n_zones) * pairs_per_cell
            playback_bytes = float(stream.size * 4 + plan_bytes)
        s_student = kfn(stream, cell.report["seed"])
        row = {
            "scenario": cell.scenario, "intensity": cell.intensity,
            "student_vs_teacher_usd_per_slo_hour": round(
                factory_mod._paired_usd_ratio(s_student,
                                              cell.teacher_summary), 4),
            "student_vs_rule_usd_per_slo_hour": round(
                factory_mod._paired_usd_ratio(s_student,
                                              cell.rule_summary), 4),
            "teacher_vs_rule_usd_per_slo_hour": round(
                factory_mod._paired_usd_ratio(cell.teacher_summary,
                                              cell.rule_summary), 4),
        }
        student_rows.append(row)
        print(f"# factory student[{cell.scenario}.{cell.intensity}]: "
              f"vs teacher x"
              f"{row['student_vs_teacher_usd_per_slo_hour']}, vs rule x"
              f"{row['student_vs_rule_usd_per_slo_hour']}",
              file=sys.stderr)
    s_vs_t = [r["student_vs_teacher_usd_per_slo_hour"]
              for r in student_rows]

    out = {
        "metric": "MPC-distillation factory throughput (pairs/sec) vs "
                  "the naive per-pair lax receding-horizon loop, paired "
                  "in one record, + student-vs-teacher scoreboard",
        "engine": report["engine"],
        "platform": platform, "virtual": virtual,
        "interpret": virtual, "stochastic": not virtual,
        "teacher": teacher,
        "protocol": {
            "pairs_per_cell": pairs_per_cell, "steps": steps,
            "block_T": block_T, "t_chunk": t_chunk, "b_block": b_block,
            "factory_iters": iters,
            "naive_mpc_horizon": naive["mpc_horizon"],
            "naive_mpc_iters": naive["mpc_iters"],
            "note": "factory plans are one-shot full-window "
                    "quick-distill plans (lr x10); the naive loop is "
                    "the closed-loop protocol — the plan-quality gap "
                    "this opens is what the student/teacher columns "
                    "report, the throughput gap is the headline",
        },
        "cells": report["cells"],
        "pairs_total": report["pairs_total"],
        "dataset_rows": report["dataset_rows"],
        "wall_s": report["wall_s"],
        "pairs_per_sec": report["pairs_per_sec"],
        "plans_per_sec": report["plans_per_sec"],
        "warmup_wall_s": round(warm_s, 4),
        "baseline": naive,
        "throughput_ratio_vs_baseline": ratio,
        "gate_min_ratio": 5.0,
        "playback_stream_bytes": playback_bytes,
        "playback_roofline_floor_s": round(
            _roofline_floor_s(playback_bytes), 6),
        "student": {
            "iterations": student_iterations,
            "final_actor_mse": round(hist[-1]["actor_mse"], 5),
            "dataset_rows": int(dataset.obs.shape[0]),
            "per_cell": student_rows,
            "student_vs_teacher_usd_per_slo_hour": round(
                float(np.mean(s_vs_t)), 4) if s_vs_t else None,
        },
    }
    if virtual:
        out["note"] = ("CPU host: interpret-mode deterministic kernels "
                       "and lax planning on one core — the pairs/sec "
                       "ratio measures batching + kernel playback vs "
                       "the per-pair loop on this host; real chips "
                       "widen the kernel-stage gap")
    return out


def _run_child(argv, timeout_s=1800, env=None) -> dict | None:
    """Run a bench child phase; relay its narration; parse its JSON."""
    try:
        proc = subprocess.run(argv, env=env or dict(os.environ),
                              capture_output=True, text=True,
                              timeout=timeout_s)
        for line in proc.stderr.splitlines():
            if line.startswith("#"):
                print(line, file=sys.stderr)
        if proc.returncode != 0:
            print(f"# bench child failed: {proc.stderr.strip()[-200:]}",
                  file=sys.stderr)
            return None
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (OSError, subprocess.TimeoutExpired, json.JSONDecodeError,
            IndexError) as e:
        print(f"# bench child errored: {e!r}", file=sys.stderr)
        return None


def _mega_subprocess(mega_sizes, horizon: int, repeats: int,
                     trace_out: str = "") -> dict | None:
    """Gate, then time, each in its OWN child process: the kernel path's
    ~11 GB and the gate's lax+kernel buffers each poison whatever shares
    their process on the tunneled backend (memory is not reliably
    reclaimed), so every phase gets a clean device session. Timing rows
    merge back only when the gate passed. ``trace_out`` is the timing
    child's Chrome-trace path ("" disables, honoring the parent's
    --trace-out '')."""
    me = os.path.abspath(__file__)
    parity = _run_child([sys.executable, me, "--mega-phase", "gate"])
    if parity is None:
        return None
    out = {"megakernel_parity": parity}
    if not parity.get("ok"):
        print("# megakernel rows skipped (gate failed)", file=sys.stderr)
        return out
    rows = _run_child([sys.executable, me, "--mega-phase", "time",
                       "--mega-sizes",
                       ",".join(str(b) for b in mega_sizes),
                       "--mega-horizon", str(horizon),
                       "--mega-repeats", str(repeats),
                       "--trace-out", trace_out])
    if rows:
        # The child's record-level metadata (provenance, trace_file,
        # compile_report) must NOT merge in as fake rollout rows: the
        # final record builder iterates rollout values as dicts, and a
        # bare string there would crash the whole bench at the end.
        meta = {k: rows.pop(k)
                for k in ("provenance", "trace_file", "compile_report")
                if k in rows}
        if meta:
            out["megakernel_child"] = meta
        out.update(rows)
    return out


def bench_flywheel(*, generations: int = 2, n_tenants: int = 6,
                   record_ticks: int = 16, shadow_ticks: int = 16,
                   watch_ticks: int = 10, top_k: int = 3,
                   steps: int = 40, iterations: int = 150,
                   pairs_base: int = 3, pairs_max: int = 6,
                   seed: int = 23) -> dict | None:
    """Continual-learning flywheel stage (round 23,
    `train/flywheel.py` + `harness/flywheel.py`): the seeded
    end-to-end record the acceptance criterion pins. Gates (the `ccka
    bench-diff` flywheel invariants):

    - ``flywheel_gate_ok``: ≥ ``generations`` gate-passing promotions,
      each strictly improving the pair-weighted $/SLO-hr ratio on its
      mined weakness cells (mean ratio < 1) with no workload class
      regressing beyond tolerance;
    - ``provenance_ok``: every generation's checksummed provenance
      record verifies after the run;
    - ``rollback_ok``: a post-promotion divergence watch stamps ONE
      edge-triggered policy_divergence incident, the demotion restores
      the parent checkpoint, and the restored live params re-hash
      BITWISE to the digest the promotion recorded;
    - ``deterministic_ok``: generation 1 re-mined and re-distilled in
      a fresh root under the same seed reproduces the same curriculum
      digest AND the same challenger checkpoint digest.
    """
    import tempfile

    from ccka_tpu.config import default_config
    from ccka_tpu.harness.flywheel import FlywheelRunner
    from ccka_tpu.train.checkpoint import load_params_npz, params_digest
    from ccka_tpu.train.flywheel import Flywheel, load_provenance

    cfg = default_config()
    scratch = tempfile.mkdtemp(prefix="ccka-flywheel-bench-")

    def build(tag: str):
        fw = Flywheel(cfg, os.path.join(scratch, tag, "root"),
                      steps=steps, block_T=steps, t_chunk=steps,
                      pairs_base=pairs_base, pairs_max=pairs_max,
                      iterations=iterations, seed=seed)
        runner = FlywheelRunner(
            cfg, fw, scratch=os.path.join(scratch, tag, "runs"),
            n_tenants=n_tenants, record_ticks=record_ticks,
            shadow_ticks=shadow_ticks, watch_ticks=watch_ticks,
            top_k=top_k, seed=seed + 188)
        return fw, runner

    try:
        fw, runner = build("a")
        res = runner.run(generations=generations)
        gens = res["generations"]
        promoted = [g for g in gens if g["promoted"]]
        gate_ok = bool(
            len(promoted) >= generations
            and all(g["decision"]["eligible"]
                    and g["decision"]["gates"]["mean_ratio"] < 1.0
                    and g["decision"]["gates"]["class_regression_ok"]
                    for g in promoted))
        prov_ok = True
        for g in gens:
            try:
                load_provenance(os.path.join(
                    fw.gen_dir(g["generation"]), "provenance.json"))
            except ValueError:
                prov_ok = False
        rb = res.get("rollback", {})
        rollback_ok = False
        if rb.get("rolled_back"):
            tree, _meta = load_params_npz(fw.live_npz)
            restored = params_digest(tree)
            want = promoted[-1]["parent"]["digest"]
            rollback_ok = bool(restored == rb["restored"]["digest"]
                               == want)
        # Paired determinism rerun: generation 1 from scratch, fresh
        # artifact root + fresh service scratch, same seeds.
        _fw_b, runner_b = build("b")
        g1b = runner_b.generation(1)
        g1a = gens[0]
        det_ok = bool(
            g1b["curriculum_digest"] == g1a["curriculum_digest"]
            and g1b["checkpoint_digest"] == g1a["checkpoint_digest"]
            and g1b["mined_cells"] == g1a["mined_cells"])
    finally:
        import shutil

        shutil.rmtree(scratch, ignore_errors=True)

    out = {
        "engine": "FlywheelRunner on the det-clock fleet service "
                  "(record → mine → weakness-weighted distill → "
                  "flywheel-challenger shadow lane → gate battery → "
                  "atomic promote), then the armed divergence watch "
                  "and bitwise parent restore; paired fresh-root "
                  "gen-1 rerun for the determinism gate",
        "generations_requested": generations,
        "n_tenants": n_tenants,
        "record_ticks": record_ticks,
        "shadow_ticks": shadow_ticks,
        "seed": seed,
        "curriculum": {"steps": steps, "iterations": iterations,
                       "pairs_base": pairs_base,
                       "pairs_max": pairs_max, "top_k": top_k},
        "generations": [{
            "generation": g["generation"],
            "incumbent": g["incumbent"],
            "mined_cells": g["mined_cells"],
            "curriculum_digest": g["curriculum_digest"],
            "checkpoint_digest": g["checkpoint_digest"],
            "parent": g["parent"],
            "mean_ratio": g["decision"]["gates"]["mean_ratio"],
            "worst_class_rel_delta":
                g["decision"]["gates"]["worst_class_rel_delta"],
            "shadow_outcome":
                g["decision"]["gates"].get("shadow_outcome"),
            "shadow_comparisons":
                g["decision"]["gates"].get("shadow_comparisons"),
            "gates": {k: v for k, v in g["decision"]["gates"].items()
                      if isinstance(v, bool)},
            "eligible": g["decision"]["eligible"],
            "promoted": g["promoted"],
        } for g in gens],
        "promotions": len(promoted),
        "rollback": {
            "rolled_back": bool(rb.get("rolled_back")),
            "incident": rb.get("incident"),
            "demoted": rb.get("demoted"),
            "restored": rb.get("restored"),
            "watch_incidents": (rb.get("watch") or {}).get("incidents"),
        },
        "flywheel_gate_ok": gate_ok,
        "provenance_ok": prov_ok,
        "rollback_ok": rollback_ok,
        "deterministic_ok": det_ok,
    }
    ratios = [g["mean_ratio"] for g in out["generations"]]
    print(f"# flywheel: {len(promoted)}/{generations} promotions, "
          f"paired $/SLO ratios {ratios}, rollback_ok={rollback_ok}, "
          f"deterministic_ok={det_ok}", file=sys.stderr)
    return out


def _mesh_virtual_fallback() -> dict | None:
    """Single-device host: measure the sharded path on an 8-device
    CPU-virtual mesh in a child process (labeled as virtual — validates
    scaling shape, not absolute speed)."""
    env = dict(os.environ)
    env["CCKA_BENCH_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    return _run_child(
        [sys.executable, os.path.abspath(__file__), "--mesh-only"],
        timeout_s=1200, env=env)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized run (small batches, short horizon)")
    ap.add_argument("--mesh-only", action="store_true",
                    help="run ONLY the mesh stage and print its JSON "
                         "(used by the CPU-virtual fallback subprocess)")
    ap.add_argument("--multichip-only", action="store_true",
                    help="run ONLY the multi-chip megakernel stage and "
                         "print its JSON (used by the CPU-virtual "
                         "fallback subprocess)")
    ap.add_argument("--mpc-only", action="store_true",
                    help="run ONLY the MPC stage (plans/s + the kernel "
                         "plan-playback row) and print its JSON — the "
                         "BENCH_r09 record path; CI-sized off-TPU")
    ap.add_argument("--faults-only", action="store_true",
                    help="run ONLY the fault-injection robustness "
                         "scoreboard (bench_faults) and print its JSON "
                         "— the BENCH_r10 record path; interpret-mode "
                         "deterministic off-TPU")
    ap.add_argument("--recovery-only", action="store_true",
                    help="run ONLY the crash-recovery kill/resume "
                         "scoreboard (bench_recovery) and print its "
                         "JSON — the BENCH_r12 record path; host-side "
                         "dry-run harness")
    ap.add_argument("--overload-only", action="store_true",
                    help="run ONLY the multi-tenant overload scoreboard "
                         "(bench_overload) and print its JSON — the "
                         "BENCH_r13 record path; host-side virtual-clock "
                         "harness")
    ap.add_argument("--obs-only", action="store_true",
                    help="run ONLY the flight-recorder overhead + "
                         "non-interference stage (bench_obs) and print "
                         "its JSON — the BENCH_r14 record path; "
                         "host-side virtual-clock harness")
    ap.add_argument("--decisions-only", action="store_true",
                    help="run ONLY the decision-provenance ledger "
                         "stage (bench_decisions: paired ledger-on/off "
                         "fleet service, flagship backend vs the rule "
                         "shadow — bitwise gate, overhead budget, "
                         "term-share invariant, policy_divergence "
                         "attribution) and print its JSON — the "
                         "BENCH_r18 record path; host-side "
                         "virtual-clock harness")
    ap.add_argument("--tournament-only", action="store_true",
                    help="run ONLY the shadow-tournament observatory "
                         "stage (bench_tournament: paired tournament-"
                         "on/off fleet service at K=4 — bitwise gate, "
                         "host-ledger overhead budget, the K∈{1,2,4,8} "
                         "lane-width curve, board invariants, and the "
                         "seeded challenger scenario with verified "
                         "dump + signed audits) and print its JSON — "
                         "the BENCH_r20 record path; host-side "
                         "virtual-clock harness")
    ap.add_argument("--fleet-scale-only", action="store_true",
                    help="run ONLY the fleet-scale host-loop stage "
                         "(bench_fleet_scale: N ∈ {16…10240} × {calm, "
                         "25% slow + moderate chaos} tail-latency "
                         "sweep, vectorized-vs-object bitwise parity "
                         "+ ≥10× speedup at N=4096, chunked-dispatch "
                         "parity at N=1024, healthy-tenant isolation "
                         "ratio) and print its JSON — the BENCH_r21 "
                         "record path; host-side wall-clock harness")
    ap.add_argument("--geo-only", action="store_true",
                    help="run ONLY the geo-arbitrage stage (bench_geo: "
                         "zero-migration bitwise parity arm + the "
                         "DCcluster-Opt-style scenario suite scored as "
                         "per-class cost/carbon/SLO Pareto fronts + the "
                         "migration-term ledger invariant) and print "
                         "its JSON — the BENCH_r19 record path; "
                         "host-side deterministic off-TPU")
    ap.add_argument("--perf-only", action="store_true",
                    help="run ONLY the device-time performance "
                         "observatory (bench_perf: occupancy ledger + "
                         "XLA cost-model attribution per megakernel "
                         "mode + the 8-shard imbalance section) and "
                         "print its JSON — the BENCH_r15 record path; "
                         "interpret-mode deterministic off-TPU")
    ap.add_argument("--perf-mesh-only", action="store_true",
                    help="child phase of --perf-only: the 8-shard "
                         "occupancy/imbalance section on the CPU-"
                         "virtual mesh (run with "
                         "--xla_force_host_platform_device_count=8)")
    ap.add_argument("--stream-only", action="store_true",
                    help="run ONLY the streaming rollout pipeline stage "
                         "(bench_stream: blocked double-buffered drive "
                         "vs the synchronous round-15 pipeline unit, "
                         "bitwise-gated, + the 10^4-cluster chunked row "
                         "and the 8-shard mesh section) and print its "
                         "JSON — the BENCH_r16 record path; interpret-"
                         "mode deterministic off-TPU")
    ap.add_argument("--stream-mesh-only", action="store_true",
                    help="child phase of --stream-only: the 8-shard "
                         "streaming section on the CPU-virtual mesh "
                         "(run with "
                         "--xla_force_host_platform_device_count=8)")
    ap.add_argument("--workloads-only", action="store_true",
                    help="run ONLY the per-family workload scenario "
                         "scoreboard (bench_workloads) and print its "
                         "JSON — the BENCH_r11 record path; "
                         "interpret-mode deterministic off-TPU")
    ap.add_argument("--factory-only", action="store_true",
                    help="run ONLY the MPC-distillation data-factory "
                         "stage (bench_factory: batched planning + "
                         "streaming plan-playback labeling vs the "
                         "naive per-pair lax loop, paired, + the "
                         "student-vs-teacher scoreboard) and print its "
                         "JSON — the BENCH_r17 record path; interpret-"
                         "mode deterministic off-TPU")
    ap.add_argument("--search-only", action="store_true",
                    help="run ONLY the traced scenario-parameter axis "
                         "stage (speedup vs per-config recompile loop, "
                         "S=1 bitwise parity, CEM minted-dominance) and "
                         "print its JSON — the BENCH_r22 record path; "
                         "interpret-mode CI-sized off-TPU")
    ap.add_argument("--flywheel-only", action="store_true",
                    help="run ONLY the continual-learning flywheel "
                         "stage (two seeded generations of mine → "
                         "weighted distill → shadow-gated promote, the "
                         "forced-divergence rollback, and the paired "
                         "determinism rerun) and print its JSON — the "
                         "BENCH_r23 record path; interpret-mode "
                         "CI-sized off-TPU")
    ap.add_argument("--mega-phase", choices=("gate", "time"),
                    help="child phases of the isolated megakernel stage "
                         "(see _mega_subprocess): 'gate' prints the "
                         "parity JSON, 'time' prints the timing rows")
    ap.add_argument("--mega-sizes", default="16384,32768")
    ap.add_argument("--mega-horizon", type=int, default=2880)
    ap.add_argument("--mega-repeats", type=int, default=3)
    ap.add_argument("--trace-out", default="bench_trace.json",
                    help="write the bench's span trace here as Chrome "
                         "trace-event JSON (load in ui.perfetto.dev); "
                         "'' disables")
    args = ap.parse_args(argv)

    if args.mesh_only:
        from ccka_tpu.config import default_config
        mesh = bench_mesh(default_config(), batch=2048, steps=240,
                          repeats=2)
        print(json.dumps(mesh))
        return 0 if mesh is not None else 1

    if args.multichip_only:
        from ccka_tpu.config import default_config
        multichip = bench_multichip(default_config())
        print(json.dumps(multichip))
        return 0 if multichip is not None else 1

    if args.mpc_only:
        from ccka_tpu.config import default_config
        on_tpu = jax.default_backend() == "tpu"
        mpc = bench_mpc(default_config(),
                        plans=20 if on_tpu else 5,
                        fleet_batch=256 if on_tpu else 64)
        mpc["provenance"] = bench_provenance()
        print(json.dumps(mpc))
        return 0

    if args.faults_only:
        with _TRACER.span("bench.faults_stage"):
            faults = bench_faults()
        if faults is not None:
            faults["provenance"] = bench_provenance()
        print(json.dumps(faults))
        return 0 if faults is not None else 1

    if args.workloads_only:
        with _TRACER.span("bench.workloads_stage"):
            wl = bench_workloads()
        if wl is not None:
            wl["provenance"] = bench_provenance(
                scenarios=list(wl["scenarios"]))
        print(json.dumps(wl))
        return 0 if wl is not None else 1

    if args.recovery_only:
        with _TRACER.span("bench.recovery_stage"):
            rec = bench_recovery()
        if rec is not None:
            rec["provenance"] = bench_provenance()
        print(json.dumps(rec))
        return 0 if rec is not None else 1

    if args.overload_only:
        with _TRACER.span("bench.overload_stage"):
            ov = bench_overload()
        if ov is not None:
            ov["provenance"] = bench_provenance()
        print(json.dumps(ov))
        return 0 if ov is not None else 1

    if args.obs_only:
        with _TRACER.span("bench.obs_stage"):
            ob = bench_obs()
        if ob is not None:
            ob["provenance"] = bench_provenance()
        print(json.dumps(ob))
        return 0 if ob is not None else 1

    if args.decisions_only:
        with _TRACER.span("bench.decisions_stage"):
            dec = bench_decisions()
        if dec is not None:
            # Record-path stamp (see --perf-only): a raw redirect into
            # BENCH_rNN.json arms the bench-diff decision gates.
            dec["stage"] = "--decisions-only"
            dec["provenance"] = bench_provenance()
        print(json.dumps(dec))
        return 0 if dec is not None else 1

    if args.tournament_only:
        with _TRACER.span("bench.tournament_stage"):
            tr = bench_tournament()
        if tr is not None:
            # Record-path stamp (see --perf-only): a raw redirect into
            # BENCH_rNN.json arms the bench-diff tournament gates.
            tr["stage"] = "--tournament-only"
            tr["provenance"] = bench_provenance()
        print(json.dumps(tr))
        return 0 if tr is not None else 1

    if args.fleet_scale_only:
        with _TRACER.span("bench.fleet_scale_stage"):
            fs = bench_fleet_scale()
        if fs is not None:
            # Record-path stamp (see --perf-only): a raw redirect into
            # BENCH_rNN.json arms the bench-diff fleet-scale gates.
            fs["stage"] = "--fleet-scale-only"
            fs["provenance"] = bench_provenance(
                scenarios=list(fs["scenarios"]))
            from ccka_tpu.obs.compile import compile_report
            fs["compile_report"] = compile_report()
        print(json.dumps(fs))
        return 0 if fs is not None else 1

    if args.search_only:
        from ccka_tpu.config import default_config
        with _TRACER.span("bench.search_stage"):
            se = bench_search(default_config())
        if se is not None:
            # Record-path stamp (see --perf-only): a raw redirect into
            # BENCH_rNN.json arms the bench-diff search gates.
            se["stage"] = "--search-only"
            se["provenance"] = bench_provenance()
            from ccka_tpu.obs.compile import compile_report
            se["compile_report"] = compile_report()
        print(json.dumps(se))
        return 0 if se is not None else 1

    if args.flywheel_only:
        with _TRACER.span("bench.flywheel_stage"):
            fl = bench_flywheel()
        if fl is not None:
            # Record-path stamp (see --perf-only): a raw redirect into
            # BENCH_rNN.json arms the bench-diff flywheel gates.
            fl["stage"] = "--flywheel-only"
            fl["provenance"] = bench_provenance()
        print(json.dumps(fl))
        return 0 if fl is not None else 1

    if args.geo_only:
        with _TRACER.span("bench.geo_stage"):
            ge = bench_geo()
        if ge is not None:
            # Record-path stamp (see --perf-only): a raw redirect into
            # BENCH_rNN.json arms the bench-diff geo gates.
            ge["stage"] = "--geo-only"
            ge["provenance"] = bench_provenance()
        print(json.dumps(ge))
        return 0 if ge is not None else 1

    if args.perf_mesh_only:
        from ccka_tpu.config import default_config
        with _TRACER.span("bench.perf_mesh_stage"):
            pm = bench_perf_mesh(default_config())
        print(json.dumps(pm))
        return 0 if pm is not None else 1

    if args.stream_mesh_only:
        from ccka_tpu.config import default_config
        with _TRACER.span("bench.stream_mesh_stage"):
            sm = bench_stream_mesh(default_config())
        print(json.dumps(sm))
        return 0 if sm is not None else 1

    if args.stream_only:
        from ccka_tpu.config import default_config
        cfg = default_config()
        with _TRACER.span("bench.stream_stage"):
            stream = bench_stream(cfg)
            mesh8 = (bench_stream_mesh(cfg)
                     if len(jax.devices()) >= 8
                     else _stream_mesh_virtual_fallback())
        if mesh8 is not None:
            stream["mesh8"] = mesh8
            r15_rate = (stream.get("r15_replication") or {}).get(
                "cluster_days_per_sec")
            mesh_rate = ((mesh8.get("sync") or {}).get(
                "cluster_days_per_sec_kernel_bare")
                or (mesh8.get("sync") or {}).get(
                    "cluster_days_per_sec_kernel_stage"))
            if r15_rate and mesh_rate:
                mesh8["vs_r15_replication"] = round(
                    mesh_rate / r15_rate, 4)
        # Record-path stamp (see --perf-only): a raw redirect into
        # BENCH_rNN.json arms the bench-diff streaming gates.
        stream["stage"] = "--stream-only"
        stream["provenance"] = bench_provenance()
        from ccka_tpu.obs.compile import compile_report
        stream["compile_report"] = compile_report()
        print(json.dumps(stream))
        return 0

    if args.factory_only:
        from ccka_tpu.config import default_config
        cfg = default_config()
        with _TRACER.span("bench.factory_stage"):
            fac = bench_factory(cfg)
        # Record-path stamp (see --perf-only): a raw redirect into
        # BENCH_rNN.json arms the bench-diff factory gates.
        fac["stage"] = "--factory-only"
        fac["provenance"] = bench_provenance(
            scenarios=list(FACTORY_SCENARIOS))
        from ccka_tpu.obs.compile import compile_report
        fac["compile_report"] = compile_report()
        print(json.dumps(fac))
        return 0

    if args.perf_only:
        from ccka_tpu.config import default_config
        cfg = default_config()
        with _TRACER.span("bench.perf_stage"):
            perf = bench_perf(cfg)
            mesh8 = (bench_perf_mesh(cfg) if len(jax.devices()) >= 8
                     else _perf_mesh_virtual_fallback())
        if mesh8 is not None:
            perf["mesh8"] = mesh8
            from ccka_tpu.obs import costmodel as _cm
            rule = perf["modes"].get("rule", {})
            _cm.publish_pipeline_snapshot(
                occupancy=rule.get("occupancy", {}).get("fractions", {}),
                shard_imbalance=mesh8.get("shard_imbalance"),
                achieved_fraction=rule.get("achieved_roofline_fraction"))
        # The record-path stamp the bench-diff PARTIAL gate keys on
        # (`obs/bench_history._extract_perf(full_stage=...)`): a raw
        # `bench.py --perf-only > BENCH_rNN.json` redirect must arm the
        # all-four-modes + mesh-section requirement without hand edits.
        perf["stage"] = "--perf-only"
        perf["provenance"] = bench_provenance()
        from ccka_tpu.obs.compile import compile_report
        perf["compile_report"] = compile_report()
        print(json.dumps(perf))
        return 0

    if args.mega_phase == "gate":
        from ccka_tpu.config import default_config
        cfg = default_config()
        from ccka_tpu.sim import SimParams
        try:
            parity = _megakernel_parity_gate(
                cfg, SimParams.from_config(cfg), _make_src(cfg))
        except Exception as e:  # noqa: BLE001
            parity = {"ok": False, "error": repr(e)[:200]}
            print(f"# megakernel parity gate errored: {e!r}",
                  file=sys.stderr)
        print(json.dumps(parity))
        return 0

    if args.mega_phase == "time":
        from ccka_tpu.config import default_config
        sizes = [int(s) for s in args.mega_sizes.split(",") if s]
        with _TRACER.span("bench.mega_time_phase", sizes=args.mega_sizes):
            rows = bench_rollout(default_config(), [], args.mega_horizon,
                                 args.mega_repeats, mega_batch_sizes=sizes,
                                 mega_gate="skip")
        # The timing child's record is a BENCH record in its own right:
        # it carries full provenance and its own Perfetto trace.
        rows["provenance"] = bench_provenance()
        from ccka_tpu.obs.compile import compile_report
        rows["compile_report"] = compile_report()
        if args.trace_out:
            rows["trace_file"] = _TRACER.write_chrome_trace(args.trace_out)
            print(f"# chrome trace -> {rows['trace_file']} "
                  "(load in ui.perfetto.dev)", file=sys.stderr)
        print(json.dumps(rows))
        return 0

    from ccka_tpu.config import default_config

    dev = jax.devices()[0]
    print(f"# device: {dev.device_kind} ({dev.platform})", file=sys.stderr)

    if args.quick:
        batch_sizes, horizon, repeats = [64, 256], 240, 2
        summary_sizes = [512]
        mega_sizes = [512]
        ppo_iters, plans = 3, 5
        ppo_cfg = default_config().with_overrides(**{
            "train.batch_clusters": 64, "train.unroll_steps": 16})
    else:
        batch_sizes, horizon, repeats = [256, 2048, 8192], 2880, 3
        summary_sizes = [16384, 32768]
        # B=64k is out of reach for the kernel path on a 16 GB part
        # (9 GB traces + 12 GB packed stream must coexist).
        mega_sizes = [16384, 32768]
        ppo_iters, plans = 10, 20
        ppo_cfg = default_config()  # config #3: 256 clusters, 64 steps

    cfg = default_config()
    # The mega timing child writes its own trace next to the parent's
    # ("<stem>_mega<ext>" — suffix-safe, so a path without ".json" can
    # never collide with the parent's file); empty disables both.
    if args.trace_out:
        _root, _ext = os.path.splitext(args.trace_out)
        mega_trace = f"{_root}_mega{_ext or '.json'}"
    else:
        mega_trace = ""
    with _TRACER.span("bench.rollout_stage"):
        rollout = bench_rollout(cfg, batch_sizes, horizon, repeats,
                                summary_batch_sizes=summary_sizes,
                                mega_batch_sizes=mega_sizes,
                                mega_trace_out=mega_trace)
    with _TRACER.span("bench.ppo_stage"):
        ppo = bench_ppo(ppo_cfg, ppo_iters)
    with _TRACER.span("bench.mpc_stage"):
        mpc = bench_mpc(cfg, plans)
    # Guarded like the quality stages: a fleet-tick failure must not
    # discard the throughput results already measured above.
    try:
        with _TRACER.span("bench.fleet_stage"):
            fleet = bench_fleet(cfg,
                                n_clusters=128 if args.quick else 1024,
                                ticks=4 if args.quick else 10)
    except Exception as e:  # noqa: BLE001
        print(f"# fleet stage failed (omitted): {e!r}", file=sys.stderr)
        fleet = None
    # Multi-device stage (VERDICT r3 weak #8): real mesh when >1 device is
    # visible; otherwise the labeled CPU-virtual fallback, so BENCH always
    # carries a mesh section.
    try:
        mesh = bench_mesh(cfg) if not args.quick else None
        if mesh is None and not args.quick:
            mesh = _mesh_virtual_fallback()
    except Exception as e:  # noqa: BLE001
        print(f"# mesh stage failed (omitted): {e!r}", file=sys.stderr)
        mesh = None
    # Multi-chip MEGAKERNEL stage (ISSUE 3): the sharded packed pipeline
    # on real chips, or the labeled virtual-mesh child on a single-device
    # host — BENCH always carries a multichip kernel section.
    try:
        multichip = bench_multichip(cfg) if not args.quick else None
        if multichip is None and not args.quick:
            multichip = _multichip_virtual_fallback()
    except Exception as e:  # noqa: BLE001
        print(f"# multichip stage failed (omitted): {e!r}",
              file=sys.stderr)
        multichip = None
    # Quality stage is guarded: a failure here must not discard the
    # minutes of throughput results already measured above.
    try:
        if args.quick:
            quality = bench_quality(cfg, eval_steps=240,
                                    n_traces=2, mpc_quick=True)
        else:
            quality = bench_quality(cfg)
    except Exception as e:  # noqa: BLE001
        print(f"# quality stage failed (omitted): {e!r}", file=sys.stderr)
        quality = None
    try:
        if args.quick:
            quality_replay = bench_quality_replay(cfg, eval_steps=240,
                                                  n_windows=1,
                                                  mpc_quick=True)
        else:
            quality_replay = bench_quality_replay(cfg)
    except Exception as e:  # noqa: BLE001
        print(f"# quality_replay stage failed (omitted): {e!r}",
              file=sys.stderr)
        quality_replay = None
    try:
        if args.quick:
            forecast = bench_forecast(cfg, eval_steps=240, n_windows=1,
                                      mpc_quick=True)
        else:
            forecast = bench_forecast(cfg)
    except Exception as e:  # noqa: BLE001
        print(f"# forecast stage failed (omitted): {e!r}", file=sys.stderr)
        forecast = None
    try:
        quality_mega = None if args.quick else bench_quality_mega()
    except Exception as e:  # noqa: BLE001
        print(f"# quality_mega stage failed (omitted): {e!r}",
              file=sys.stderr)
        quality_mega = None
    # Robustness scoreboard (ISSUE 5): kernel-paired fault sweep —
    # guarded like every quality stage, CI-sized under --quick.
    try:
        with _TRACER.span("bench.faults_stage"):
            faults = (bench_faults(n_traces=64, eval_steps=48)
                      if args.quick else bench_faults())
    except Exception as e:  # noqa: BLE001
        print(f"# faults stage failed (omitted): {e!r}", file=sys.stderr)
        faults = None
    # Per-family workload scenario scoreboard (ISSUE 6): same guard.
    try:
        with _TRACER.span("bench.workloads_stage"):
            workloads = (bench_workloads(n_traces=64, eval_steps=48)
                         if args.quick else bench_workloads())
    except Exception as e:  # noqa: BLE001
        print(f"# workloads stage failed (omitted): {e!r}",
              file=sys.stderr)
        workloads = None
    # Crash-recovery scoreboard (ISSUE 9): kill/resume invariant sweep —
    # same guard; host-side, so --quick only shrinks the pair count.
    try:
        with _TRACER.span("bench.recovery_stage"):
            recovery = (bench_recovery(runs_per_cell=2, ticks=12)
                        if args.quick else bench_recovery())
    except Exception as e:  # noqa: BLE001
        print(f"# recovery stage failed (omitted): {e!r}",
              file=sys.stderr)
        recovery = None
    # Multi-tenant overload scoreboard (ISSUE 10): isolation invariant
    # sweep — same guard; host-side virtual clock, so --quick only
    # shrinks the grid.
    try:
        with _TRACER.span("bench.overload_stage"):
            overload = (bench_overload(tenants=(8,),
                                       intensities=("off", "severe"),
                                       slow_fracs=(0.0, 0.25),
                                       ticks=12)
                        if args.quick else bench_overload())
    except Exception as e:  # noqa: BLE001
        print(f"# overload stage failed (omitted): {e!r}",
              file=sys.stderr)
        overload = None
    # Flight-recorder overhead + non-interference stage (round 14):
    # same guard; host-side paired runs, so --quick only shrinks them.
    try:
        with _TRACER.span("bench.obs_stage"):
            obs_stage = (bench_obs(n_tenants=8, ticks=12, repeats=2)
                         if args.quick else bench_obs())
    except Exception as e:  # noqa: BLE001
        print(f"# obs stage failed (omitted): {e!r}", file=sys.stderr)
        obs_stage = None
    # Decision-provenance ledger stage (round 18): paired ledger-on/off
    # runs — same guard; host-side, so --quick only shrinks them.
    try:
        with _TRACER.span("bench.decisions_stage"):
            decisions_stage = (
                bench_decisions(n_tenants=8, ticks=12, repeats=2)
                if args.quick else bench_decisions())
    except Exception as e:  # noqa: BLE001
        print(f"# decisions stage failed (omitted): {e!r}",
              file=sys.stderr)
        decisions_stage = None
    # Shadow-tournament stage (round 20): paired tournament-on/off runs
    # + the seeded challenger scenario — same guard; host-side, so
    # --quick only shrinks them.
    try:
        with _TRACER.span("bench.tournament_stage"):
            tournament_stage = (
                bench_tournament(n_tenants=6, ticks=10, repeats=2,
                                 k_points=(1, 4), challenger_ticks=24)
                if args.quick else bench_tournament())
    except Exception as e:  # noqa: BLE001
        print(f"# tournament stage failed (omitted): {e!r}",
              file=sys.stderr)
        tournament_stage = None
    # Fleet-scale host-loop stage (round 21): the tenant-axis sweep —
    # same guard; host-side, so --quick shrinks N and the tick count.
    try:
        with _TRACER.span("bench.fleet_scale_stage"):
            fleet_scale_stage = (
                bench_fleet_scale(tenants=(16, 256), ticks=8,
                                  speedup_n=256)
                if args.quick else bench_fleet_scale())
    except Exception as e:  # noqa: BLE001
        print(f"# fleet-scale stage failed (omitted): {e!r}",
              file=sys.stderr)
        fleet_scale_stage = None
    # Device-time observatory stage (round 15): occupancy ledger + XLA
    # attribution per kernel mode — same guard; --quick shrinks sizes
    # and drops the neural/carbon modes + the mesh section.
    try:
        with _TRACER.span("bench.perf_stage"):
            if args.quick:
                perf_stage = bench_perf(cfg, steps=48, batch=128,
                                        repeats=1,
                                        modes=("rule", "plan"))
            else:
                perf_stage = bench_perf(cfg)
                mesh8 = (bench_perf_mesh(cfg)
                         if len(jax.devices()) >= 8
                         else _perf_mesh_virtual_fallback())
                if mesh8 is not None:
                    perf_stage["mesh8"] = mesh8
    except Exception as e:  # noqa: BLE001
        print(f"# perf stage failed (omitted): {e!r}", file=sys.stderr)
        perf_stage = None

    rates = {k: v for k, v in rollout.items()
             if isinstance(v, dict) and "cluster_days_per_sec" in v}
    if not rates:
        print("# FATAL: every rollout row dropped — no headline",
              file=sys.stderr)
        print(json.dumps({"metric": "sim_cluster_days_per_sec_per_chip",
                          "value": None, "unit": "cluster-days/sec/chip",
                          "error": "no plausible rollout timing"}))
        return 1
    best_k = max(rates, key=lambda k: rates[k]["cluster_days_per_sec"])
    headline = rates[best_k]["cluster_days_per_sec"]
    line = {
        "metric": "sim_cluster_days_per_sec_per_chip",
        "value": round(headline, 1),
        "unit": "cluster-days/sec/chip",
        "vs_baseline": round(headline / _JUDGE_R1_BASELINE, 3),
        "baseline": f"{_JUDGE_R1_BASELINE:.0f} (judge r1, B=2048, 1 chip)",
        "device": f"{dev.device_kind}/{dev.platform}",
        "best_batch": rollout[best_k]["batch"],
        "best_mode": rollout[best_k]["mode"],
        "rollout": {kk: {k: (round(v, 3) if isinstance(v, float) else v)
                         for k, v in r.items()}
                    for kk, r in rollout.items()},
        "ppo": {k: round(v, 3) for k, v in ppo.items()},
        # mpc carries the nested playback row (already rounded); only
        # scalars round here.
        "mpc": {k: (round(float(v), 3) if isinstance(v, (int, float))
                    else v)
                for k, v in mpc.items()},
    }
    if fleet is not None:
        line["fleet"] = {k: round(float(v), 3) for k, v in fleet.items()}
        # This stage's numbers are NOT forced-sync best-of-N: the tick
        # loop is pipelined host wall-clock, and the pure device rate is
        # an amortized K-dispatch chain behind one fence (see
        # bench_fleet) — say so next to the numbers.
        line["fleet"]["timing_mode"] = (
            "pipelined_host_loop+amortized_dispatch_chain")
    if mesh is not None:
        line["mesh"] = mesh
    if multichip is not None:
        line["multichip"] = multichip
    if quality is not None:
        line["quality"] = quality
    if quality_replay is not None:
        line["quality_replay"] = quality_replay
    if forecast is not None:
        line["forecast"] = forecast
    if quality_mega is not None:
        line["quality_mega"] = quality_mega
    if faults is not None:
        line["faults"] = faults
    if workloads is not None:
        line["workloads"] = workloads
    if recovery is not None:
        line["recovery"] = recovery
    if overload is not None:
        line["overload"] = overload
    if obs_stage is not None:
        line["obs"] = obs_stage
    if decisions_stage is not None:
        line["decisions"] = decisions_stage
    if tournament_stage is not None:
        line["tournament"] = tournament_stage
    if fleet_scale_stage is not None:
        line["fleet_scale"] = fleet_scale_stage
    if perf_stage is not None:
        line["perf"] = perf_stage
    # Provenance + the session's span trace: a headline without device/
    # version/timing context cannot be audited (VERDICT r5 weak #3).
    line["provenance"] = bench_provenance()
    # Per-hot-path compile accounting (obs/compile.py): calls, compiles,
    # cache hits and the compile/execute wall split for every watched
    # jitted entry point this run dispatched.
    from ccka_tpu.obs.compile import compile_report
    line["compile_report"] = compile_report()
    if args.trace_out:
        line["trace_file"] = _TRACER.write_chrome_trace(args.trace_out)
        print(f"# chrome trace -> {line['trace_file']} "
              "(load in ui.perfetto.dev)", file=sys.stderr)
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
