"""Performance benchmark harness — prints ONE machine-parseable JSON line.

Headline metric: simulated cluster-days/sec/chip for the batched rule-policy
rollout in stochastic mode (the BASELINE.json north-star measure; the
round-1 judge measured 3,781 at B=2048 on one v5e chip, and the v5e-8 goal
is >=10k across 8 chips). Sub-metrics: PPO iterations/sec at BASELINE
config #3 (256 clusters) and diff-MPC plans/sec.

Methodology: trace generation and compilation are setup (excluded), timed
regions are device-bound with `block_until_ready`; each config is timed over
several repeats and the best wall-clock is reported (standard for
throughput benches — the steady state is what a fleet controller sees).

Usage: ``python bench.py`` (full sweep, B up to 8192);
``python bench.py --quick`` (CI-sized).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

_JUDGE_R1_BASELINE = 3781.0  # cluster-days/sec/chip, judge round-1, B=2048


def _make_src(cfg):
    from ccka_tpu.signals.synthetic import SyntheticSignalSource

    return SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                 cfg.signals)


def _time_best(fn, repeats: int = 3) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def bench_rollout(cfg, batch_sizes, horizon_steps: int, repeats: int,
                  summary_batch_sizes=()) -> dict:
    """Batched rollout sweep. ``batch_sizes`` use the metric-stacking path
    (per-tick StepMetrics over the horizon); ``summary_batch_sizes`` use
    the O(B)-memory summarize-in-scan path, which is how fleet-scale
    scoring actually runs (B=32k × a day OOMs on metric stacking alone).
    """
    from ccka_tpu.policy import RulePolicy
    from ccka_tpu.sim import (SimParams, batched_rollout,
                              batched_rollout_summary, initial_state)

    params = SimParams.from_config(cfg)
    src = _make_src(cfg)
    action_fn = RulePolicy(cfg.cluster).action_fn()
    days_per_traj = horizon_steps * cfg.sim.dt_s / 86400.0

    run_metrics = jax.jit(lambda s, tr, k: batched_rollout(
        params, s, action_fn, tr, k, stochastic=True))
    run_summary = jax.jit(lambda s, tr, k: batched_rollout_summary(
        params, s, action_fn, tr, k, stochastic=True))

    results = {}
    sweep = ([(b, "metrics") for b in batch_sizes]
             + [(b, "summary") for b in summary_batch_sizes])
    for b, mode in sweep:
        key = f"{b}:{mode}"
        # Device-side synthesis: setup stays off the host even at B=32768.
        traces = src.batch_trace_device(horizon_steps, jax.random.key(7), b)
        states = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (b,) + x.shape), initial_state(cfg))
        keys = jax.random.split(jax.random.key(0), b)
        states, traces, keys = jax.device_put((states, traces, keys))
        run = run_summary if mode == "summary" else run_metrics

        def once():
            final, _ = run(states, traces, keys)
            jax.block_until_ready(final)

        once()  # compile
        dt = _time_best(once, repeats)
        results[key] = {
            "batch": b,
            "seconds": dt,
            "mode": mode,
            "cluster_days_per_sec": b * days_per_traj / dt,
            "cluster_steps_per_sec": b * horizon_steps / dt,
        }
        print(f"# rollout B={b} [{mode}]: {dt:.3f}s -> "
              f"{results[key]['cluster_days_per_sec']:,.0f} cluster-days/sec",
              file=sys.stderr)
        del traces, states, keys
    return results


def bench_ppo(cfg, iterations: int) -> dict:
    from ccka_tpu.train.ppo import PPOTrainer

    trainer = PPOTrainer(cfg)
    src = _make_src(cfg)
    ts = trainer.init_state()  # includes net-init compile (one-off)
    w = trainer.make_windows(src, iterations + 1, seed=999)  # warm compile
    jax.block_until_ready(w.spot_price_hr)
    t0 = time.perf_counter()
    windows = trainer.make_windows(src, iterations + 1, seed=1000)
    jax.block_until_ready(windows.spot_price_hr)
    t_trace = time.perf_counter() - t0

    t_len = cfg.train.unroll_steps
    ts, _ = trainer._iteration_fn(
        ts, windows.slice_steps(0, t_len + 1))  # compile
    jax.block_until_ready(ts.params)

    t0 = time.perf_counter()
    for it in range(1, iterations + 1):
        ts, diag = trainer._iteration_fn(
            ts, windows.slice_steps(it * t_len, t_len + 1))
    jax.block_until_ready(ts.params)
    dt = time.perf_counter() - t0

    b = cfg.train.batch_clusters
    out = {
        "iterations_per_sec": iterations / dt,
        "env_steps_per_sec": iterations * b * t_len / dt,
        "trace_gen_seconds": t_trace,
        "train_seconds": dt,
        # VERDICT item 6: end-to-end wall (host trace gen + train) must stay
        # within ~2x of device-bound train time. Compile time excluded (the
        # one-off XLA cost, cached across runs).
        "wall_over_device": (t_trace + dt) / dt,
    }
    print(f"# ppo B={b}: {out['iterations_per_sec']:.2f} it/s, "
          f"{out['env_steps_per_sec']:,.0f} env-steps/s, "
          f"wall/device={out['wall_over_device']:.2f}", file=sys.stderr)
    return out


def bench_mpc(cfg, plans: int) -> dict:
    from ccka_tpu.models import action_to_latent
    from ccka_tpu.policy.rule import neutral_action
    from ccka_tpu.sim import SimParams, initial_state
    from ccka_tpu.train.mpc import optimize_plan

    params = SimParams.from_config(cfg)
    src = _make_src(cfg)
    h = cfg.train.mpc_horizon
    trace = src.trace(h, seed=0)
    state0 = initial_state(cfg)
    base = action_to_latent(neutral_action(cfg.cluster), cfg.cluster)
    latent0 = jnp.broadcast_to(base, (h,) + base.shape)

    def once():
        r = optimize_plan(params, cfg.cluster, cfg.train, state0, trace,
                          latent0, iters=cfg.train.mpc_iters)
        jax.block_until_ready(r.plan_latent)

    once()  # compile
    t0 = time.perf_counter()
    for _ in range(plans):
        once()
    dt = time.perf_counter() - t0
    out = {"plans_per_sec": plans / dt,
           "horizon": h, "iters": cfg.train.mpc_iters}
    print(f"# mpc: {out['plans_per_sec']:.1f} plans/s "
          f"(H={h}, {cfg.train.mpc_iters} Adam iters)", file=sys.stderr)
    return out


def bench_quality(cfg, ppo_iters: int = 30, eval_steps: int = 1440,
                  n_traces: int = 2) -> dict:
    """Policy quality vs the rule baseline — the other half of
    BASELINE.json's metric ("$/SLO-hour & gCO2/req vs rule baseline").

    Trains a short PPO run (synthetic world, training seeds), then scores
    rule / carbon / ppo on held-out stochastic traces; plus the
    multi-region check (config #4): carbon-aware zone selection must cut
    gCO2/kreq on the diverging-carbon fleet at comparable SLO.
    """
    from ccka_tpu.config import multi_region_config
    from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy
    from ccka_tpu.train.evaluate import compare_backends, heldout_traces
    from ccka_tpu.train.ppo import ppo_train

    src = _make_src(cfg)
    ppo_backend, _ = ppo_train(cfg, src, ppo_iters)
    backends = {
        "rule": RulePolicy(cfg.cluster),
        "carbon": CarbonAwarePolicy(cfg.cluster),
        "ppo": ppo_backend,
    }
    traces = heldout_traces(src, steps=eval_steps, n=n_traces)
    board = compare_backends(cfg, backends, traces, stochastic=True)

    mcfg = multi_region_config()
    msrc = _make_src(mcfg)
    mboard = compare_backends(
        mcfg,
        {"rule": RulePolicy(mcfg.cluster),
         "carbon": CarbonAwarePolicy(mcfg.cluster)},
        heldout_traces(msrc, steps=eval_steps, n=1), stochastic=True)

    def pick(r):
        return {k: round(r[k], 4) for k in (
            "usd_per_slo_hour", "g_co2_per_kreq", "slo_attainment",
            "vs_rule_usd_per_slo_hour", "vs_rule_g_co2_per_kreq",
            "vs_rule_objective") if k in r}

    out = {
        "ppo_iters": ppo_iters,
        "eval_steps": eval_steps,
        **{name: pick(r) for name, r in board.items()},
        "multiregion_carbon": pick(mboard["carbon"]),
    }
    print(f"# quality: ppo vs rule objective="
          f"{board['ppo'].get('vs_rule_objective', float('nan')):.3f}, "
          f"multiregion carbon gCO2 ratio="
          f"{mboard['carbon']['vs_rule_g_co2_per_kreq']:.3f}",
          file=sys.stderr)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized run (small batches, short horizon)")
    args = ap.parse_args(argv)

    from ccka_tpu.config import default_config

    dev = jax.devices()[0]
    print(f"# device: {dev.device_kind} ({dev.platform})", file=sys.stderr)

    if args.quick:
        batch_sizes, horizon, repeats = [64, 256], 240, 2
        summary_sizes = [512]
        ppo_iters, plans = 3, 5
        ppo_cfg = default_config().with_overrides(**{
            "train.batch_clusters": 64, "train.unroll_steps": 16})
    else:
        batch_sizes, horizon, repeats = [256, 2048, 8192], 2880, 3
        summary_sizes = [16384, 32768]
        ppo_iters, plans = 10, 20
        ppo_cfg = default_config()  # config #3: 256 clusters, 64 steps

    cfg = default_config()
    rollout = bench_rollout(cfg, batch_sizes, horizon, repeats,
                            summary_batch_sizes=summary_sizes)
    ppo = bench_ppo(ppo_cfg, ppo_iters)
    mpc = bench_mpc(cfg, plans)
    # Quality stage is guarded: a failure here must not discard the
    # minutes of throughput results already measured above.
    try:
        if args.quick:
            quality = bench_quality(cfg, ppo_iters=2, eval_steps=240,
                                    n_traces=1)
        else:
            quality = bench_quality(cfg)
    except Exception as e:  # noqa: BLE001
        print(f"# quality stage failed (omitted): {e!r}", file=sys.stderr)
        quality = None

    best_k = max(rollout, key=lambda k: rollout[k]["cluster_days_per_sec"])
    headline = rollout[best_k]["cluster_days_per_sec"]
    line = {
        "metric": "sim_cluster_days_per_sec_per_chip",
        "value": round(headline, 1),
        "unit": "cluster-days/sec/chip",
        "vs_baseline": round(headline / _JUDGE_R1_BASELINE, 3),
        "baseline": f"{_JUDGE_R1_BASELINE:.0f} (judge r1, B=2048, 1 chip)",
        "device": f"{dev.device_kind}/{dev.platform}",
        "best_batch": rollout[best_k]["batch"],
        "best_mode": rollout[best_k]["mode"],
        "rollout": {kk: {k: (round(v, 3) if isinstance(v, float) else v)
                         for k, v in r.items()}
                    for kk, r in rollout.items()},
        "ppo": {k: round(v, 3) for k, v in ppo.items()},
        "mpc": {k: round(float(v), 3) for k, v in mpc.items()},
    }
    if quality is not None:
        line["quality"] = quality
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
