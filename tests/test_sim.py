"""Simulator dynamics tests: scheduling, provisioning, consolidation,
interruptions, accounting, differentiability, vmap/scan/jit.

These are the "fake cluster backend" tests the reference lacks entirely
(SURVEY.md §4: no tests, only live-cluster observation). Behavioral oracles
come from the reference's semantics: provisioning reacts to Pending pods
(Karpenter, `05_karpenter.sh`), consolidation follows
{WhenEmpty|WhenEmptyOrUnderutilized, consolidateAfter}
(`demo_20_offpeak_configure.sh:59-60`), PDB bounds evictions
(`demo_10_setup_configure.sh:46-57`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.sim import (
    Action,
    CT_OD,
    CT_SPOT,
    SimParams,
    batched_rollout,
    initial_state,
    rollout,
    rollout_actions,
    step,
    summarize,
)
from ccka_tpu.sim.dynamics import ExoStep
from ccka_tpu.signals import SyntheticSignalSource


_jstep = jax.jit(step, static_argnames="stochastic")


@pytest.fixture(scope="module")
def cfg():
    return default_config().with_overrides(**{"sim.horizon_steps": 128})


@pytest.fixture(scope="module")
def params(cfg):
    return SimParams.from_config(cfg)


@pytest.fixture(scope="module")
def trace(cfg):
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim, cfg.signals)
    return src.trace(128, seed=0)


def _exo(cfg, demand=(30.0, 30.0), spot=0.03, od=0.096, carbon=400.0):
    z = cfg.cluster.n_zones
    return ExoStep(
        spot_price_hr=jnp.full((z,), spot, jnp.float32),
        od_price_hr=jnp.full((z,), od, jnp.float32),
        carbon_g_kwh=jnp.full((z,), carbon, jnp.float32),
        demand_pods=jnp.asarray(demand, jnp.float32),
        is_peak=jnp.float32(0.0),
    )


def _neutral(cfg):
    return Action.neutral(cfg.cluster.n_pools, cfg.cluster.n_zones)


def test_pods_per_node(params):
    # m6i.large: (2-0.2)/0.2 = 9 by CPU; (8-0.6)/0.125 = 59 by mem → 9
    assert float(params.pods_per_node) == 9.0


def test_base_capacity_serves_small_od_demand(cfg, params):
    # 3 base nodes × 9 pods = 27 od pods served with zero Karpenter nodes.
    state = initial_state(cfg)
    exo = _exo(cfg, demand=(0.0, 20.0))
    state, m = _jstep(params, state, _neutral(cfg), exo, jax.random.key(0))
    assert float(m.served_pods[1]) == pytest.approx(20.0)
    assert float(m.pending_pods[1]) == pytest.approx(0.0)
    assert float(m.nodes_by_ct.sum()) == pytest.approx(0.0)


def test_provisioning_fills_shortage_after_delay(cfg, params):
    # 30 spot-class pods need ceil-ish 30/9 spot nodes; they arrive after
    # the provisioning pipeline delay (3 ticks at 90s/30s) and get served.
    state = initial_state(cfg)
    exo = _exo(cfg, demand=(30.0, 0.0))
    act = _neutral(cfg)
    key = jax.random.key(0)
    served = []
    for _ in range(cfg.sim.provision_delay_steps + 2):
        state, m = _jstep(params, state, act, exo, key)
        served.append(float(m.served_pods[0]))
    assert served[0] == 0.0                      # nothing yet
    assert served[-1] == pytest.approx(30.0, rel=5e-3)  # capacity arrived (minus interruption decay)
    assert float(state.nodes[..., CT_SPOT].sum()) >= 30.0 / 9.0 - 0.01


def test_no_double_provisioning_while_in_flight(cfg, params):
    # Shortage stays constant while nodes are in flight; pipeline total must
    # not keep growing (Karpenter discounts in-flight NodeClaims).
    state = initial_state(cfg)
    exo = _exo(cfg, demand=(30.0, 0.0))
    act = _neutral(cfg)
    key = jax.random.key(0)
    state, _ = _jstep(params, state, act, exo, key)
    after_first = float(state.pipeline.sum())
    state, _ = _jstep(params, state, act, exo, key)
    after_second = float(state.pipeline.sum())
    assert after_second == pytest.approx(after_first, rel=0.05)


def test_consolidation_when_empty_after_timer(cfg, params):
    # Scale up for burst, then demand drops to zero: WhenEmpty with 30s
    # timer should reclaim (1-fragmentation-stranded) slack within a few ticks.
    state = initial_state(cfg)
    act = _neutral(cfg)
    key = jax.random.key(0)
    hi = _exo(cfg, demand=(45.0, 0.0))
    for _ in range(6):
        state, _ = _jstep(params, state, act, hi, key)
    nodes_peak = float(state.nodes.sum())
    assert nodes_peak > 4.0
    lo = _exo(cfg, demand=(0.0, 0.0))
    for _ in range(6):
        state, m = _jstep(params, state, act, lo, key)
    assert float(state.nodes.sum()) < 0.35 * nodes_peak


def test_aggressive_consolidation_reclaims_fragmentation(cfg, params):
    # With running pods pinning fragmented capacity, aggr=1 (Underutilized)
    # reclaims more than aggr=0 (WhenEmpty).
    def run(aggr):
        state = initial_state(cfg)
        act = _neutral(cfg)
        key = jax.random.key(0)
        for _ in range(6):
            state, _ = _jstep(params, state, act, _exo(cfg, demand=(45.0, 0.0)), key)
        act = act._replace(
            consolidation_aggr=jnp.full((cfg.cluster.n_pools,), aggr, jnp.float32))
        for _ in range(8):
            state, _ = _jstep(params, state, act, _exo(cfg, demand=(18.0, 0.0)), key)
        return float(state.nodes.sum())

    assert run(1.0) < run(0.0) - 0.1


def test_consolidate_after_delays_reclaim(cfg, params):
    # A 10-minute consolidateAfter keeps slack nodes alive through a short lull.
    def run(after_s):
        state = initial_state(cfg)
        act = _neutral(cfg)._replace(
            consolidate_after_s=jnp.full((cfg.cluster.n_pools,), after_s,
                                         jnp.float32))
        key = jax.random.key(0)
        for _ in range(6):
            state, _ = _jstep(params, state, act, _exo(cfg, demand=(45.0, 0.0)), key)
        for _ in range(4):  # 2 minutes of lull
            state, _ = _jstep(params, state, act, _exo(cfg, demand=(0.0, 0.0)), key)
        return float(state.nodes.sum())

    assert run(600.0) > run(30.0) + 0.5


def test_spot_interruption_deterministic_decay(cfg, params):
    state = initial_state(cfg)
    state = state._replace(nodes=state.nodes.at[0, 0, CT_SPOT].set(10.0))
    exo = _exo(cfg, demand=(0.0, 0.0))
    # Zero consolidation influence: huge consolidate_after.
    act = _neutral(cfg)._replace(
        consolidate_after_s=jnp.full((cfg.cluster.n_pools,), 1e9, jnp.float32))
    state, m = _jstep(params, state, act, exo, jax.random.key(0))
    expect = 10.0 * float(params.interrupt_p_step)
    assert float(m.interrupted_nodes) == pytest.approx(expect, rel=1e-4)


def test_spot_interruption_stochastic_poisson(cfg, params):
    # Stochastic mode: Poisson reclaim — correct long-run rate (the clipped
    # Gaussian approximation it replaced inflated rare-event rates ~15x),
    # varies by key, bounded by the spot fleet.
    hi = params._replace(interrupt_p_step=jnp.float32(0.1))
    state = initial_state(cfg)
    state = state._replace(nodes=state.nodes.at[0, 0, CT_SPOT].set(50.0))
    exo = _exo(cfg, demand=(400.0, 0.0))
    act = _neutral(cfg)
    outs = []
    for s in range(40):
        _, m = _jstep(hi, state, act, exo, jax.random.key(s), stochastic=True)
        v = float(m.interrupted_nodes)
        assert 0.0 <= v <= 50.0
        outs.append(v)
    assert len(set(outs)) > 1
    assert abs(np.mean(outs) - 5.0) < 1.5  # E = 50 * 0.1


def test_cost_accounting_matches_hand_calc(cfg, params):
    # 2 spot nodes @ $0.03 + 3 base od nodes @ $0.096 for one 30s tick.
    state = initial_state(cfg)
    state = state._replace(nodes=state.nodes.at[0, 0, CT_SPOT].set(2.0))
    exo = _exo(cfg, demand=(0.0, 0.0))
    act = _neutral(cfg)._replace(
        consolidate_after_s=jnp.full((cfg.cluster.n_pools,), 1e9, jnp.float32))
    p_noint = params._replace(interrupt_p_step=jnp.float32(0.0))
    _, m = _jstep(p_noint, state, act, exo, jax.random.key(0))
    expect = (2 * 0.03 + 3 * 0.096) * 30.0 / 3600.0
    assert float(m.cost_usd) == pytest.approx(expect, rel=1e-5)


def test_carbon_accounting_idle_fleet(cfg, params):
    # Idle fleet: 3 base nodes at idle watts, 400 g/kWh.
    state = initial_state(cfg)
    exo = _exo(cfg, demand=(0.0, 0.0), carbon=400.0)
    _, m = _jstep(params, state, _neutral(cfg), exo, jax.random.key(0))
    expect = 3 * (40.0 / 1000.0) * (30.0 / 3600.0) * 400.0
    assert float(m.carbon_g) == pytest.approx(expect, rel=1e-4)


def test_slo_gate(cfg, params):
    state = initial_state(cfg)
    # 100 od pods vs 27 base capacity → SLO miss.
    _, m = _jstep(params, state, _neutral(cfg), _exo(cfg, demand=(0.0, 100.0)),
                jax.random.key(0))
    assert float(m.slo_ok) == 0.0
    # zero demand → trivially met.
    _, m = _jstep(params, state, _neutral(cfg), _exo(cfg, demand=(0.0, 0.0)),
                jax.random.key(0))
    assert float(m.slo_ok) == 1.0


def test_zone_weight_steers_provisioning(cfg, params):
    # Pin zone 2 (one-hot): all new nodes land in zone index 2.
    state = initial_state(cfg)
    zw = jnp.zeros((cfg.cluster.n_pools, cfg.cluster.n_zones), jnp.float32)
    zw = zw.at[:, 2].set(1.0)
    act = _neutral(cfg)._replace(zone_weight=zw)
    exo = _exo(cfg, demand=(30.0, 0.0))
    state, _ = _jstep(params, state, act, exo, jax.random.key(0))
    pipe = np.asarray(state.pipeline.sum(axis=(0, 1)))  # [Z, CT]
    assert pipe[2, CT_SPOT] > 0
    assert pipe[0, CT_SPOT] == pytest.approx(0.0)
    assert pipe[1, CT_SPOT] == pytest.approx(0.0)


def test_ct_disallow_blocks_provisioning(cfg, params):
    # Forbidding spot everywhere leaves spot-class pods pending forever
    # (their nodeSelector can't be satisfied) — matches Karpenter semantics
    # when requirements exclude the needed capacity type.
    state = initial_state(cfg)
    act = _neutral(cfg)._replace(
        ct_allow=jnp.stack([jnp.zeros(2), jnp.ones(2)], axis=-1).T * 0.0 +
        jnp.asarray([[0.0, 1.0], [0.0, 1.0]], jnp.float32))
    exo = _exo(cfg, demand=(30.0, 0.0))
    key = jax.random.key(0)
    for _ in range(6):
        state, m = _jstep(params, state, act, exo, key)
    assert float(m.pending_pods[0]) == pytest.approx(30.0)
    assert float(state.nodes[..., CT_SPOT].sum()) == pytest.approx(0.0)


def test_pool_max_nodes_cap(cfg, params):
    small = params._replace(max_nodes=jnp.asarray([2.0, 2.0], jnp.float32))
    state = initial_state(cfg)
    exo = _exo(cfg, demand=(500.0, 0.0))
    key = jax.random.key(0)
    for _ in range(8):
        state, _ = _jstep(small, state, _neutral(cfg), exo, key)
    assert float(state.nodes.sum() + state.pipeline.sum()) <= 4.0 + 1e-3


def test_rollout_scan_jit_and_summary(cfg, params, trace):
    act = _neutral(cfg)

    def action_fn(state, exo, t):
        return act

    run = jax.jit(lambda s, k: rollout(params, s, action_fn, trace, k))
    final, metrics = run(initial_state(cfg), jax.random.key(0))
    assert metrics.cost_usd.shape == (128,)
    summary = summarize(params, metrics)
    assert float(summary.cost_usd) > 0
    assert float(summary.cost_usd) == pytest.approx(float(final.acc_cost_usd),
                                                    rel=1e-4)
    assert 0.0 <= float(summary.slo_attainment) <= 1.0
    assert 0.0 <= float(summary.spot_exposure) <= 1.0


def test_batched_rollout_vmap(cfg, params, trace):
    B = 4
    states = jax.tree.map(lambda x: jnp.broadcast_to(x, (B,) + x.shape),
                          initial_state(cfg))
    traces = jax.tree.map(lambda x: jnp.broadcast_to(x, (B,) + x.shape), trace)
    keys = jax.random.split(jax.random.key(0), B)
    act = _neutral(cfg)
    final, metrics = batched_rollout(params, states, lambda s, e, t: act,
                                     traces, keys)
    assert metrics.cost_usd.shape == (B, 128)
    # identical inputs + deterministic dynamics → identical outputs
    assert np.allclose(np.asarray(metrics.cost_usd[0]),
                       np.asarray(metrics.cost_usd[1]))


def test_gradients_flow_through_rollout(cfg, params, trace):
    """diff-MPC viability: d(episode objective)/d(action plan) is nonzero."""
    T = 32
    tr = trace.slice_steps(0, T)
    base = _neutral(cfg)
    plan = jax.tree.map(lambda x: jnp.broadcast_to(x, (T,) + x.shape), base)

    def objective(plan):
        final, _ = rollout_actions(params, initial_state(cfg), plan, tr,
                                   jax.random.key(0))
        return final.acc_cost_usd + 0.001 * final.acc_carbon_g

    grads = jax.grad(objective)(plan)
    gnorm = sum(float(jnp.abs(g).sum()) for g in jax.tree.leaves(grads))
    assert np.isfinite(gnorm)
    assert gnorm > 0.0


def test_state_stays_finite_and_nonnegative(cfg, params, trace):
    final, metrics = rollout(params, initial_state(cfg),
                             lambda s, e, t: _neutral(cfg), trace,
                             jax.random.key(1), stochastic=True)
    for leaf in jax.tree.leaves(final):
        assert np.all(np.isfinite(np.asarray(leaf)))
    assert np.all(np.asarray(final.nodes) >= 0)
    assert np.all(np.asarray(metrics.served_pods) >= 0)


def test_slo_judged_against_raw_demand_not_hpa_target(cfg, params):
    # Reward-hacking guard: a policy cannot meet SLO by scaling its own
    # HPA target to zero; SLO compares served against exogenous demand.
    state = initial_state(cfg)
    act = _neutral(cfg)._replace(hpa_scale=jnp.zeros((2,), jnp.float32))
    _, m = _jstep(params, state, act, _exo(cfg, demand=(10.0, 10.0)),
                  jax.random.key(0))
    assert float(m.slo_ok) == 0.0


def test_slo_per_class_no_cross_subsidy(cfg, params):
    # Overserving the spot class cannot mask starving the od class.
    state = initial_state(cfg)
    state = state._replace(nodes=state.nodes.at[0, 0, CT_SPOT].set(10.0))
    act = _neutral(cfg)._replace(
        hpa_scale=jnp.asarray([3.0, 0.0], jnp.float32))
    _, m = _jstep(params, state, act, _exo(cfg, demand=(10.0, 10.0)),
                  jax.random.key(0))
    assert float(m.served_pods[0]) == pytest.approx(30.0)  # overserved
    assert float(m.slo_ok) == 0.0                          # od class starved


def test_requests_clamped_to_raw_demand(cfg, params):
    # hpa_scale=2 headroom must not inflate served-request accounting.
    state = initial_state(cfg)
    state = state._replace(nodes=state.nodes.at[0, 0, CT_SPOT].set(10.0))
    act = _neutral(cfg)._replace(
        hpa_scale=jnp.asarray([2.0, 1.0], jnp.float32))
    s2, m = _jstep(params, state, act, _exo(cfg, demand=(10.0, 10.0)),
                   jax.random.key(0))
    expect = 20.0 * float(params.rps_per_pod) * 30.0
    assert float(s2.acc_requests) == pytest.approx(expect, rel=1e-5)


def test_underutil_threshold_gates_aggressive_repack(cfg, params):
    # At utilization above the threshold, Underutilized behaves like
    # WhenEmpty (no repack evictions); far below, it repacks.
    def run(threshold):
        p2 = params._replace(underutil_threshold=jnp.float32(threshold))
        state = initial_state(cfg)
        key = jax.random.key(0)
        act = _neutral(cfg)._replace(
            consolidation_aggr=jnp.ones((cfg.cluster.n_pools,), jnp.float32))
        for _ in range(6):
            state, _ = _jstep(p2, state, act, _exo(cfg, demand=(45.0, 0.0)), key)
        for _ in range(8):
            state, _ = _jstep(p2, state, act, _exo(cfg, demand=(30.0, 0.0)), key)
        return float(state.nodes.sum())

    # util ~30/45: threshold 0.95 → repack engaged; threshold 0.05 → not.
    assert run(0.95) < run(0.05) - 0.1


class TestRolloutSummaryParity:
    """rollout_summary (O(B) memory, fleet-scoring path) must produce the
    exact EpisodeSummary that summarize() computes over stacked per-tick
    metrics — same scan, same key splits, so parity is bitwise-tight."""

    def test_matches_summarize_deterministic(self, cfg, params, trace):
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.sim import rollout_summary

        fn = RulePolicy(cfg.cluster).action_fn()
        key = jax.random.key(3)
        s0 = initial_state(cfg)
        final_a, metrics = rollout(params, s0, fn, trace, key)
        want = summarize(params, metrics)
        final_b, got = rollout_summary(params, s0, fn, trace, key)
        for name in want._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(want, name)), rtol=1e-5, atol=1e-5,
                err_msg=name)
        np.testing.assert_allclose(np.asarray(final_b.nodes),
                                   np.asarray(final_a.nodes), rtol=1e-6)

    def test_warm_start_excludes_prior_episode(self, cfg, params, trace):
        """Accumulators in ClusterState are lifetime totals; a summary over
        a warm-started rollout must report only THIS episode's share (and
        slo_attainment must stay <= 1)."""
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.sim import rollout_summary

        fn = RulePolicy(cfg.cluster).action_fn()
        key = jax.random.key(5)
        mid, _ = rollout(params, initial_state(cfg), fn, trace, key)
        assert float(mid.acc_cost_usd) > 0  # warm state carries totals

        _, metrics = rollout(params, mid, fn, trace, key)
        want = summarize(params, metrics)
        _, got = rollout_summary(params, mid, fn, trace, key)
        for name in want._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(want, name)), rtol=1e-4, atol=1e-4,
                err_msg=name)
        assert float(got.slo_attainment) <= 1.0 + 1e-6

    @pytest.mark.slow  # ISSUE 14 lane-time rule (~18s): the same
    # in-scan reduction is pinned deterministically above, and the
    # batched/stochastic composition is re-proven fast-lane by every
    # megakernel parity test (their lax side IS
    # batched_rollout_summary under stochastic keys).
    def test_matches_summarize_stochastic_batched(self, cfg, params):
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.sim import batched_rollout_summary
        from ccka_tpu.signals import SyntheticSignalSource

        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        b, t = 4, 48
        traces = src.batch_trace(t, range(b))
        states = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (b,) + x.shape),
            initial_state(cfg))
        keys = jax.random.split(jax.random.key(0), b)
        fn = RulePolicy(cfg.cluster).action_fn()
        _, metrics = batched_rollout(params, states, fn, traces, keys,
                                     stochastic=True)
        want = summarize(params, metrics)
        _, got = batched_rollout_summary(params, states, fn, traces, keys,
                                         stochastic=True)
        for name in want._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(want, name)), rtol=1e-5, atol=1e-5,
                err_msg=name)
        assert got.cost_usd.shape == (b,)
