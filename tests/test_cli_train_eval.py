"""End-to-end CLI path: train -> checkpoint -> evaluate/observe/simulate/run
without writing Python (VERDICT round-1 item 7: learned backends were
unreachable from the CLI)."""

import json

import pytest

from ccka_tpu.cli import main

# End-to-end CLI train/evaluate runs: compile-heavy.
pytestmark = pytest.mark.slow

_TINY = ["--set", "train.batch_clusters=4", "--set", "train.unroll_steps=8",
         "--set", "train.mpc_horizon=8", "--set", "train.mpc_iters=3"]


def test_train_ppo_then_evaluate_vs_rule(tmp_path, capsys):
    ckpt = str(tmp_path / "ppo")
    rc = main([*_TINY, "train", "--backend", "ppo", "--iterations", "2",
               "--checkpoint-dir", ckpt, "--log-every", "1"])
    out = capsys.readouterr()
    assert rc == 0
    history = [json.loads(line) for line in out.out.splitlines() if line]
    assert history and "mean_reward" in history[0]

    rc = main([*_TINY, "evaluate", "--backends", "rule,ppo",
               "--checkpoint", ckpt, "--days", "0.05", "--traces", "2"])
    out = capsys.readouterr()
    assert rc == 0
    board = json.loads(out.out)
    assert set(board) == {"rule", "ppo"}
    # The BASELINE.json criterion surface: vs-rule ratios present.
    assert "vs_rule_usd_per_slo_hour" in board["ppo"]
    assert "vs_rule_g_co2_per_kreq" in board["ppo"]
    assert board["rule"]["usd_per_slo_hour"] > 0


def test_train_mpc_warm_start_then_evaluate(tmp_path, capsys):
    ckpt = str(tmp_path / "mpc")
    rc = main([*_TINY, "train", "--backend", "mpc", "--iterations", "4",
               "--checkpoint-dir", ckpt])
    out = capsys.readouterr()
    assert rc == 0
    rec = json.loads(out.out.splitlines()[0])
    assert rec["final_objective"] <= rec["first_objective"]

    rc = main([*_TINY, "evaluate", "--backends", "mpc",
               "--checkpoint", ckpt, "--days", "0.02", "--traces", "1"])
    out = capsys.readouterr()
    assert rc == 0
    board = json.loads(out.out)
    assert board["mpc"]["objective_usd"] > 0


def test_simulate_with_ppo_checkpoint(tmp_path, capsys):
    ckpt = str(tmp_path / "ppo")
    main([*_TINY, "train", "--backend", "ppo", "--iterations", "1",
          "--checkpoint-dir", ckpt, "--log-every", "0"])
    capsys.readouterr()
    rc = main([*_TINY, "simulate", "--backend", "ppo",
               "--checkpoint", ckpt, "--days", "0.02"])
    out = capsys.readouterr()
    assert rc == 0
    doc = json.loads(out.out)
    assert doc["backend"] == "ppo" and doc["cost_usd"] > 0


def test_run_with_ppo_checkpoint(tmp_path, capsys):
    ckpt = str(tmp_path / "ppo")
    main([*_TINY, "train", "--backend", "ppo", "--iterations", "1",
          "--checkpoint-dir", ckpt, "--log-every", "0"])
    capsys.readouterr()
    rc = main([*_TINY, "run", "--backend", "ppo", "--checkpoint", ckpt,
               "--ticks", "2", "--interval", "0"])
    out = capsys.readouterr()
    assert rc == 0
    lines = [json.loads(x) for x in out.out.splitlines() if x.startswith("{")]
    assert len(lines) == 2 and all(r["applied"] for r in lines)


def test_ppo_backend_defaults_to_flagship_checkpoint(capsys):
    """`--backend ppo` without --checkpoint loads the shipped flagship
    checkpoint for the topology; the hard error only fires when no
    checkpoint ships (asserted via a topology with none)."""
    import os
    from unittest import mock

    from ccka_tpu.config import default_config
    from ccka_tpu.train.flagship import flagship_checkpoint_path

    # Default topology: the shipped checkpoint makes ppo work out of the
    # box (package-absolute path — the same one the loader resolves).
    if os.path.exists(flagship_checkpoint_path(default_config())):
        assert main(["observe", "--backend", "ppo"]) == 0
        capsys.readouterr()
    # No shipped checkpoint -> actionable SystemExit.
    with mock.patch("ccka_tpu.train.flagship.flagship_checkpoint_path",
                    return_value="/nonexistent/ckpt.npz"):
        with pytest.raises(SystemExit, match="flagship"):
            main(["observe", "--backend", "ppo"])


def test_simulate_mpc_backend(capsys):
    """simulate --backend mpc runs the receding-horizon closed loop for a
    single cluster, and refuses a multi-cluster batch."""
    import json

    from ccka_tpu.cli import main

    assert main(["--set", "train.mpc_horizon=8", "--set",
                 "train.mpc_iters=3", "simulate", "--backend", "mpc",
                 "--days", "0.005"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["backend"] == "mpc" and doc["cost_usd"] > 0

    import pytest
    with pytest.raises(SystemExit, match="one cluster"):
        main(["simulate", "--backend", "mpc", "--clusters", "2",
              "--days", "0.005"])
