"""Multi-region fleet tests (BASELINE.json config #4).

The reference's multi-region capability is paper-only ("multi-region
~$450/mo", report PDF p.4 §8; GSLB routing, proposal PDF p.5). These tests
assert the realized version: zones spanning two regions with diverging
carbon profiles, a carbon-aware policy that shifts placement toward the
cleaner region, gradients that see the cross-region carbon ordering, and
per-region actuation rendering.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import (
    ConfigError,
    FrameworkConfig,
    RegionSpec,
    multi_region_config,
)
from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy, carbon_zone_weight
from ccka_tpu.sim import (
    SimParams,
    initial_state,
    rollout,
    rollout_actions,
    summarize,
)
from ccka_tpu.sim.types import Action
from ccka_tpu.signals import SyntheticSignalSource


@pytest.fixture(scope="module")
def mcfg():
    return multi_region_config()


@pytest.fixture(scope="module")
def msrc(mcfg):
    return SyntheticSignalSource(mcfg.cluster, mcfg.workload, mcfg.sim,
                                 mcfg.signals)


# one simulated day at 30s ticks
_DAY = 2880


def _region_masks(cluster):
    idx = np.asarray(cluster.zone_region_index)
    return [(idx == r) for r in range(cluster.n_regions)]


# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------


class TestMultiRegionConfig:
    def test_zones_derived_from_regions(self, mcfg):
        assert mcfg.cluster.zones == (
            "us-east-2a", "us-east-2b", "us-west-2a", "us-west-2b")
        assert mcfg.cluster.n_zones == 4
        assert mcfg.cluster.n_regions == 2

    def test_zone_region_index(self, mcfg):
        assert mcfg.cluster.zone_region_index == (0, 0, 1, 1)
        assert mcfg.cluster.region_of_zone("us-west-2b") == "us-west-2"
        with pytest.raises(ConfigError):
            mcfg.cluster.region_of_zone("eu-central-1a")

    def test_roundtrip(self, mcfg):
        again = FrameworkConfig.from_json(mcfg.to_json())
        assert again == mcfg
        assert again.cluster.regions[1].carbon_zone == "US-CAL-CISO"

    def test_duplicate_zones_rejected(self, mcfg):
        bad = (RegionSpec(name="a", zones=("z1", "z2")),
               RegionSpec(name="b", zones=("z2",)))
        with pytest.raises(ConfigError):
            mcfg.with_overrides(**{
                "cluster.regions": [r.__dict__ for r in bad],
                "cluster.offpeak_zones": ["z1"],
                "cluster.peak_zones": ["z1"],
            })

    def test_single_region_unchanged(self):
        cfg = FrameworkConfig().validate()
        assert cfg.cluster.n_regions == 1
        assert cfg.cluster.zone_region_index == (0, 0, 0)
        assert cfg.cluster.region_of_zone("us-east-2b") == "us-east-2"


# ---------------------------------------------------------------------------
# Signals: per-region carbon profiles genuinely diverge
# ---------------------------------------------------------------------------


class TestRegionSignals:
    def test_carbon_levels_diverge(self, mcfg, msrc):
        trace = msrc.trace(_DAY, seed=0)
        carbon = np.asarray(trace.carbon_g_kwh)  # [T, 4]
        east, west = _region_masks(mcfg.cluster)
        # MISO-east (base 520, shallow dip) runs dirtier than
        # CAISO-west (base 300, deep dip) on the daily mean.
        assert carbon[:, east].mean() > 1.3 * carbon[:, west].mean()

    def test_west_solar_dip_is_later_and_deeper(self, mcfg, msrc):
        trace = msrc.trace(_DAY, seed=1)
        carbon = np.asarray(trace.carbon_g_kwh)
        ticks_hr = 3600.0 / mcfg.sim.dt_s
        east_min_hr = carbon[:, 0].argmin() / ticks_hr
        west_min_hr = carbon[:, 2].argmin() / ticks_hr
        # tz_offset_hr=-3 → the west dip lands ~3h later in trace time.
        assert 1.5 < (west_min_hr - east_min_hr) < 4.5
        # Deep duck curve: west dips below 60% of its own base; east barely.
        west_base = 300.0
        assert carbon[:, 2].min() < 0.6 * west_base
        east_base = 520.0
        assert carbon[:, 0].min() > 0.6 * east_base

    def test_od_price_scale_applied(self, mcfg, msrc):
        trace = msrc.trace(16, seed=0)
        od = np.asarray(trace.od_price_hr)
        east, west = _region_masks(mcfg.cluster)
        np.testing.assert_allclose(
            od[:, west].mean() / od[:, east].mean(), 1.04, rtol=1e-5)

    def test_single_region_trace_unchanged_by_refactor(self):
        """The region-aware assembly must reproduce the classic single-
        region profile bit-for-bit (prefix-stable cache contract)."""
        cfg = FrameworkConfig().validate()
        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        tr = src.trace(64, seed=3)
        zp = src._zp
        np.testing.assert_allclose(zp["solar_phase"], 0.0)
        np.testing.assert_allclose(zp["od_scale"], 1.0)
        # od price constant across zones in the classic profile
        assert float(np.asarray(tr.od_price_hr).std()) == pytest.approx(
            0.0, abs=1e-6)


# ---------------------------------------------------------------------------
# Carbon-aware policy: placement follows the cleaner region
# ---------------------------------------------------------------------------


class TestCarbonAwarePolicy:
    def test_zone_weight_orders_by_carbon(self):
        carbon = jnp.asarray([520.0, 500.0, 250.0, 260.0])
        w = np.asarray(carbon_zone_weight(carbon))
        assert w[2] > 0.5 > w[0]
        assert w[2] > w[3] > w[1] > w[0]

    def test_decide_keeps_rule_disruption_semantics(self, mcfg, msrc):
        from ccka_tpu.sim.rollout import exo_steps

        policy = CarbonAwarePolicy(mcfg.cluster)
        rule = RulePolicy(mcfg.cluster)
        tick = jax.tree.map(lambda x: x[0], exo_steps(msrc.tick(0)))
        a = policy.decide(initial_state(mcfg), tick, jnp.int32(0))
        b = rule.decide(initial_state(mcfg), tick, jnp.int32(0))
        np.testing.assert_allclose(np.asarray(a.consolidation_aggr),
                                   np.asarray(b.consolidation_aggr))
        np.testing.assert_allclose(np.asarray(a.consolidate_after_s),
                                   np.asarray(b.consolidate_after_s))
        np.testing.assert_allclose(np.asarray(a.ct_allow),
                                   np.asarray(b.ct_allow))

    def test_fleet_migrates_to_cleaner_region(self, mcfg, msrc):
        """The headline BASELINE config #4 behavior: when carbon diverges
        across regions, node-hours shift toward the cleaner one, and
        emissions per request drop vs the region-pinned rule baseline at
        equal SLO."""
        params = SimParams.from_config(mcfg)
        steps = 720  # quarter day, 09:00-15:00: peak demand, deep west solar
        trace = msrc.forecast(1080, steps, seed=0)
        key = jax.random.key(0)
        s0 = initial_state(mcfg)

        runs = {}
        for name, policy in (("carbon", CarbonAwarePolicy(mcfg.cluster)),
                             ("rule", RulePolicy(mcfg.cluster))):
            final, metrics = jax.jit(
                lambda s, k, fn=policy.action_fn(): rollout(
                    params, s, fn, trace, k))(s0, key)
            runs[name] = (summarize(params, metrics), metrics)

        east, west = _region_masks(mcfg.cluster)
        nz = {name: np.asarray(m.nodes_by_zone) for name, (_, m) in runs.items()}
        west_share = {
            name: nz[name][:, west].sum() / max(nz[name].sum(), 1e-9)
            for name in nz}
        # Rule policy pins zones to us-east-2{a,b}; carbon-aware provisions
        # into the cleaner west region.
        assert west_share["rule"] < 0.05
        assert west_share["carbon"] > 0.5

        s_carbon, s_rule = runs["carbon"][0], runs["rule"][0]
        assert float(s_carbon.g_co2_per_kreq) < 0.8 * float(s_rule.g_co2_per_kreq)
        assert float(s_carbon.slo_attainment) >= float(s_rule.slo_attainment) - 0.05

    @pytest.mark.slow  # ISSUE 14 lane-time rule (~15s): the ordering
    # is subsumed fast-lane by TestMPCLearnsMigration's
    # test_optimized_plan_prefers_clean_region_and_cuts_carbon, which
    # proves the planner EXPLOITS the same cross-region carbon
    # ordering end to end through the identical scanned dynamics.
    def test_carbon_gradient_orders_zones(self, mcfg, msrc):
        """Gradients through the scanned dynamics see the cross-region
        carbon ordering: more weight on a dirty-region zone raises total
        emissions faster than on a clean-region zone."""
        params = SimParams.from_config(mcfg)
        steps = 96
        trace = msrc.trace(steps, seed=0)
        s0 = initial_state(mcfg)
        neutral = Action.neutral(mcfg.cluster.n_pools, mcfg.cluster.n_zones)

        def total_carbon(zone_w):
            action = neutral._replace(
                zone_weight=jnp.broadcast_to(
                    zone_w, neutral.zone_weight.shape))
            actions = jax.tree.map(
                lambda x: jnp.broadcast_to(x, (steps,) + x.shape), action)
            final, _ = rollout_actions(params, s0, actions, trace,
                                       jax.random.key(0))
            return final.acc_carbon_g

        g = np.asarray(jax.grad(total_carbon)(
            jnp.ones((mcfg.cluster.n_zones,), jnp.float32)))
        east, west = _region_masks(mcfg.cluster)
        assert g[east].mean() > g[west].mean()


# ---------------------------------------------------------------------------
# Actuation: per-region patch rendering
# ---------------------------------------------------------------------------


class TestRegionActuation:
    def test_patches_split_by_region(self, mcfg):
        from ccka_tpu.actuation import render_region_nodepool_patches

        # Carbon strongly favors west → global zone set = west zones only.
        action = Action.neutral(mcfg.cluster.n_pools, mcfg.cluster.n_zones)
        w = jnp.asarray([0.1, 0.1, 0.9, 0.9], jnp.float32)
        action = action._replace(
            zone_weight=jnp.broadcast_to(w, action.zone_weight.shape))
        per_region = render_region_nodepool_patches(action, mcfg.cluster)
        assert set(per_region) == {"us-east-2", "us-west-2"}

        def zones_of(ps):
            req = ps.requirements_json[0]["value"]
            return next(r["values"] for r in req
                        if r["key"] == "topology.kubernetes.io/zone")

        for ps in per_region["us-west-2"]:
            assert zones_of(ps) == ["us-west-2a", "us-west-2b"]
        # East intersection is empty → falls back to the region's own zones
        # (never an unsatisfiable empty In requirement).
        for ps in per_region["us-east-2"]:
            assert zones_of(ps) == ["us-east-2a", "us-east-2b"]

    def test_single_region_equivalent(self):
        from ccka_tpu.actuation import (render_nodepool_patches,
                                        render_region_nodepool_patches)

        cfg = FrameworkConfig().validate()
        action = Action.neutral(cfg.cluster.n_pools, cfg.cluster.n_zones)
        flat = render_nodepool_patches(action, cfg.cluster)
        per_region = render_region_nodepool_patches(action, cfg.cluster)
        assert per_region == {"us-east-2": flat}


# ---------------------------------------------------------------------------
# Controller: per-region sinks receive only their region's zones
# ---------------------------------------------------------------------------


class TestMultiRegionController:
    def test_tick_routes_patches_per_region_sink(self, mcfg):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller

        src = SyntheticSignalSource(mcfg.cluster, mcfg.workload, mcfg.sim,
                                    mcfg.signals,
                                    start_unix_s=12 * 3600)  # midday
        sinks = {r.name: DryRunSink() for r in mcfg.cluster.regions}
        ctrl = Controller(mcfg, CarbonAwarePolicy(mcfg.cluster), src, sinks,
                          interval_s=0.0, log_fn=lambda _line: None)
        reports = ctrl.run(ticks=3)
        assert all(r.applied and r.verified for r in reports)
        for region in mcfg.cluster.regions:
            observed = sinks[region.name].observed_state(
                mcfg.cluster.pools[0].name)
            # Each regional cluster only ever sees its own zones — never a
            # cross-region requirement it could not satisfy.
            assert observed["zones"]
            assert set(observed["zones"]) <= set(region.zones)

    def test_missing_region_sink_rejected(self, mcfg):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller

        src = SyntheticSignalSource(mcfg.cluster, mcfg.workload, mcfg.sim,
                                    mcfg.signals)
        with pytest.raises(ValueError, match="us-west-2"):
            Controller(mcfg, RulePolicy(mcfg.cluster), src,
                       {"us-east-2": DryRunSink()}, interval_s=0.0)


# ---------------------------------------------------------------------------
# Hysteresis: placement is sticky through noisy carbon crossovers
# ---------------------------------------------------------------------------


class TestHysteresis:
    def _exo(self, mcfg, carbon):
        from ccka_tpu.sim.dynamics import ExoStep

        z = mcfg.cluster.n_zones
        return ExoStep(
            spot_price_hr=jnp.full((z,), 0.035, jnp.float32),
            od_price_hr=jnp.full((z,), 0.096, jnp.float32),
            carbon_g_kwh=jnp.asarray(carbon, jnp.float32),
            demand_pods=jnp.asarray([30.0, 30.0], jnp.float32),
            is_peak=jnp.float32(0.0),
        )

    def test_occupied_zone_wins_ties(self, mcfg):
        """At a carbon crossover (all zones equal), the fleet's current
        home keeps weight > 0.5 and empty zones stay < 0.5 — no flapping
        from sub-percent carbon noise."""
        policy = CarbonAwarePolicy(mcfg.cluster)
        state = initial_state(mcfg)
        # All Karpenter nodes in zone 0 (pool 0, spot).
        state = state._replace(nodes=state.nodes.at[0, 0, 0].set(6.0))
        exo = self._exo(mcfg, [400.0, 401.0, 399.0, 400.0])
        w = np.asarray(policy.decide(state, exo, jnp.int32(0)).zone_weight[0])
        assert w[0] > 0.5
        assert all(w[j] < 0.5 for j in (1, 2, 3))

    def test_large_carbon_margin_overrides_stickiness(self, mcfg):
        policy = CarbonAwarePolicy(mcfg.cluster)
        state = initial_state(mcfg)
        state = state._replace(nodes=state.nodes.at[0, 0, 0].set(6.0))
        # Zone 2 is 40% cleaner than the mean — migration must win.
        exo = self._exo(mcfg, [520.0, 520.0, 250.0, 500.0])
        w = np.asarray(policy.decide(state, exo, jnp.int32(0)).zone_weight[0])
        assert w[2] > 0.5 > w[1]


# ---------------------------------------------------------------------------
# Learned migration: diff-MPC discovers the cleaner region by gradient
# ---------------------------------------------------------------------------


class TestMPCLearnsMigration:
    @pytest.mark.slow  # ISSUE 16 lane-time rule: the migration preference is
    # pinned by the flagship BASELINE records; heavy MPC optimize run.
    def test_optimized_plan_prefers_clean_region_and_cuts_carbon(
            self, mcfg, msrc):
        """BASELINE config #4 with a *learned* backend: optimizing the plan
        through the scanned dynamics must (a) reduce the objective,
        (b) shift zone weight toward the cleaner west region, and
        (c) improve SLO time without degrading carbon intensity (g/req).

        (c) is deliberately *intensity*, not absolute grams: over a short
        horizon the 3-node base nodegroup sets a carbon floor the action
        cannot touch, and serving more requests costs watts — an optimizer
        that buys +20% SLO time is allowed those grams. The long-horizon
        absolute carbon cut from migration is asserted separately by
        test_fleet_migrates_to_cleaner_region."""
        import jax.numpy as jnp

        from ccka_tpu.models import action_to_latent, latent_to_action
        from ccka_tpu.policy.rule import neutral_action
        from ccka_tpu.train.mpc import optimize_plan

        cfg2 = mcfg.with_overrides(**{"train.carbon_weight": 2e-3})
        params = SimParams.from_config(cfg2)
        h = 48  # daytime window with strong carbon divergence
        trace = msrc.forecast(1200, h, seed=0)  # 10:00 onward
        s0 = initial_state(cfg2)
        base = action_to_latent(neutral_action(cfg2.cluster), cfg2.cluster)
        init = jnp.broadcast_to(base, (h,) + base.shape)

        result = optimize_plan(params, cfg2.cluster, cfg2.train, s0, trace,
                               init, iters=30)
        assert float(result.losses[-1]) < float(result.losses[0])  # (a)

        east, west = _region_masks(mcfg.cluster)
        actions = jax.vmap(
            lambda u: latent_to_action(u, mcfg.cluster))(result.plan_latent)
        zone_w = np.asarray(actions.zone_weight).mean(axis=(0, 1))  # [Z]
        assert zone_w[west].mean() > zone_w[east].mean()            # (b)

        def stats(plan_latent):
            acts = jax.vmap(
                lambda u: latent_to_action(u, mcfg.cluster))(plan_latent)
            final, _ = rollout_actions(params, s0, acts, trace,
                                       jax.random.key(0))
            return (float(final.acc_carbon_g) / float(final.acc_requests),
                    float(final.acc_slo_ok_s))

        g_per_req_opt, slo_opt = stats(result.plan_latent)
        g_per_req_init, slo_init = stats(init)
        assert slo_opt > slo_init                                   # (c)
        assert g_per_req_opt < 1.05 * g_per_req_init


# ---------------------------------------------------------------------------
# Live signals: per-region grid carbon
# ---------------------------------------------------------------------------


class TestLiveMultiRegionCarbon:
    def test_tick_carries_per_region_carbon(self, mcfg):
        """The live carbon tick must preserve cross-region divergence: each
        zone is priced by ITS region's grid zone, not one global value
        (a flat tick would blind the carbon-aware policy in live mode)."""
        import json as _json

        from ccka_tpu.signals.live import LiveSignalSource

        grid_values = {"US-MIDW-MISO": 540.0, "US-CAL-CISO": 210.0}
        calls = []

        def fetch(url, headers):
            if "carbon-intensity" in url:
                zone = url.split("zone=")[-1].split("&")[0]
                zone = zone.replace("%2F", "/")
                calls.append(zone)
                return _json.dumps(
                    {"carbonIntensity": grid_values[zone]}).encode()
            raise OSError("no prometheus in this test")

        cfg2 = mcfg.with_overrides(**{"signals.carbon_api_key": "k"})
        src = LiveSignalSource(cfg2.cluster, cfg2.workload, cfg2.sim,
                               cfg2.signals, fetch=fetch, start_unix_s=0.0)
        tick = src.tick(0)
        carbon = np.asarray(tick.carbon_g_kwh)[0]  # [4]
        np.testing.assert_allclose(carbon[:2], 540.0)  # east zones
        np.testing.assert_allclose(carbon[2:], 210.0)  # west zones
        # One API call per distinct grid zone, not per cluster zone.
        assert sorted(set(calls)) == ["US-CAL-CISO", "US-MIDW-MISO"]
        assert len(calls) == 2

    def test_single_region_unchanged(self):
        from ccka_tpu.config import default_config
        from ccka_tpu.signals.live import LiveSignalSource

        cfg = default_config()

        def fetch(url, headers):
            raise OSError("offline")

        src = LiveSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                               cfg.signals, fetch=fetch, start_unix_s=0.0)
        carbon = np.asarray(src.tick(0).carbon_g_kwh)[0]
        # No key + offline → documented 400 g/kWh fallback, all zones.
        np.testing.assert_allclose(carbon, 400.0)

    def test_api_failure_falls_back_to_region_base(self, mcfg):
        """One region's API blip must not invert the cross-region carbon
        ordering: each zone falls back to ITS region's base intensity,
        never the flat global default."""
        import json as _json

        from ccka_tpu.signals.live import LiveSignalSource

        def fetch(url, headers):
            if "US-CAL-CISO" in url:
                return _json.dumps({"carbonIntensity": 210.0}).encode()
            raise OSError("MISO endpoint timeout")  # east fails this tick

        cfg2 = mcfg.with_overrides(**{"signals.carbon_api_key": "k"})
        src = LiveSignalSource(cfg2.cluster, cfg2.workload, cfg2.sim,
                               cfg2.signals, fetch=fetch, start_unix_s=0.0)
        carbon = np.asarray(src.tick(0).carbon_g_kwh)[0]
        np.testing.assert_allclose(carbon[:2], 520.0)  # east region base
        np.testing.assert_allclose(carbon[2:], 210.0)  # live west value
        assert carbon[:2].min() > carbon[2:].max()     # ordering preserved

    def test_live_multiregion_requires_carbon_zones(self, mcfg):
        regions = [dict(r.__dict__) for r in mcfg.cluster.regions]
        regions[0]["carbon_zone"] = ""
        with pytest.raises(ConfigError, match="carbon_zone"):
            mcfg.with_overrides(**{"signals.backend": "live",
                                   "cluster.regions": regions})

    def test_forecast_preserves_per_zone_live_anomaly(self, mcfg):
        """The planner's forecast must scale each zone by ITS measured
        anomaly — live divergence that disagrees with the synthetic prior
        has to reach the horizon window."""
        import json as _json

        from ccka_tpu.signals.live import LiveSignalSource

        # Live says east is CLEANER than west — opposite of the prior.
        grid_values = {"US-MIDW-MISO": 200.0, "US-CAL-CISO": 600.0}

        def fetch(url, headers):
            if "carbon-intensity" in url:
                zone = url.split("zone=")[-1].split("&")[0].replace("%2F", "/")
                return _json.dumps(
                    {"carbonIntensity": grid_values[zone]}).encode()
            raise OSError("no prometheus")

        cfg2 = mcfg.with_overrides(**{"signals.carbon_api_key": "k"})
        src = LiveSignalSource(cfg2.cluster, cfg2.workload, cfg2.sim,
                               cfg2.signals, fetch=fetch, start_unix_s=0.0)
        window = np.asarray(src.forecast(0, 8).carbon_g_kwh)  # [8, 4]
        assert window[:, :2].mean() < window[:, 2:].mean()
