"""Signals layer tests: synthetic generation, replay round-trip, live parsing.

Live clients are tested against canned JSON in the exact wire shapes the
reference queries: Prometheus `/api/v1/query` (`demo_40_watch_observe.sh:110`)
and label values (`:108`); carbon falls back to the documented dummy
~400 g/kWh (`.env:14-16`).
"""

import json

import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.signals import (
    CarbonIntensityClient,
    ExogenousTrace,
    LiveSignalSource,
    OpenCostClient,
    PrometheusClient,
    ReplaySignalSource,
    SyntheticSignalSource,
    load_trace,
    save_trace,
)
from ccka_tpu.signals.live import SignalUnavailable, make_signal_source


@pytest.fixture(scope="module")
def synth():
    cfg = default_config()
    return SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim, cfg.signals)


def test_synthetic_shapes(synth):
    tr = synth.trace(128, seed=0)
    assert tr.spot_price_hr.shape == (128, 3)
    assert tr.od_price_hr.shape == (128, 3)
    assert tr.carbon_g_kwh.shape == (128, 3)
    assert tr.demand_pods.shape == (128, 2)
    assert tr.is_peak.shape == (128,)


def test_synthetic_deterministic_per_seed(synth):
    a = synth.trace(64, seed=3)
    b = synth.trace(64, seed=3)
    c = synth.trace(64, seed=4)
    assert np.allclose(a.spot_price_hr, b.spot_price_hr)
    assert not np.allclose(a.spot_price_hr, c.spot_price_hr)


def test_batch_trace_bitwise_matches_stacked_traces(synth):
    """Load-bearing identity: training (make_windows) uses batch_trace,
    single-cluster paths use trace(); they must see the same world."""
    seeds = range(11, 15)
    batch = synth.batch_trace(96, seeds)
    for name in batch._fields:
        stacked = np.stack(
            [np.asarray(getattr(synth.trace(96, seed=s), name))
             for s in seeds])
        assert np.array_equal(stacked, np.asarray(getattr(batch, name))), name


@pytest.mark.slow  # ISSUE 14 lane-time rule (~11s): a 2880-tick
# statistical composition — device-synthesized traces are consumed
# bitwise by every packed parity test fast-lane, and the host path has
# its own exactness pins; only the host-vs-device moment match rides
# here.
def test_device_trace_statistically_matches_host_path(synth):
    """batch_trace_device is the same signal family as batch_trace: same
    diurnal structure (exact, it's deterministic) and AR(1) noise moments."""
    import jax

    host = synth.batch_trace(2880, range(32))
    dev = synth.batch_trace_device(2880, jax.random.key(0), 32)
    for name in host._fields:
        h, d = np.asarray(getattr(host, name)), np.asarray(getattr(dev, name))
        assert h.shape == d.shape, name
        # Batch-mean traces are noise-free-ish -> tight agreement on the
        # deterministic structure; per-element values differ (other stream).
        np.testing.assert_allclose(h.mean(axis=0), d.mean(axis=0),
                                   rtol=0.12, atol=2.0)
    # Noise scale agrees: per-element std over the batch.
    h_std = np.asarray(host.spot_price_hr).std(axis=0).mean()
    d_std = np.asarray(dev.spot_price_hr).std(axis=0).mean()
    np.testing.assert_allclose(h_std, d_std, rtol=0.2)


def test_ar1_device_moments():
    """Stationary mean/var/autocorr of the device AR(1) match the model."""
    import jax

    from ccka_tpu.signals.synthetic import _ar1_device

    rho, sigma = 0.9, 0.5
    x = np.asarray(_ar1_device(jax.random.key(3), (64, 512), rho, sigma))
    assert abs(x.mean()) < 0.02
    np.testing.assert_allclose(x.var(), sigma**2, rtol=0.05)
    lag1 = (x[:, 1:] * x[:, :-1]).mean() / x.var()
    np.testing.assert_allclose(lag1, rho, rtol=0.05)


def test_synthetic_spot_below_od(synth):
    tr = synth.trace(2880, seed=0)  # full day
    assert np.all(np.asarray(tr.spot_price_hr) <= np.asarray(tr.od_price_hr) + 1e-6)
    assert np.all(np.asarray(tr.spot_price_hr) > 0)


def test_synthetic_carbon_positive_and_diurnal(synth):
    tr = synth.trace(2880, seed=0)
    carbon = np.asarray(tr.carbon_g_kwh)
    assert np.all(carbon > 0)
    # mid-day solar dip: day mean below evening mean
    steps_per_hr = int(3600 / 30)
    noon = carbon[12 * steps_per_hr:15 * steps_per_hr].mean()
    evening = carbon[19 * steps_per_hr:21 * steps_per_hr].mean()
    assert noon < evening


def test_synthetic_peak_flag(synth):
    tr = synth.trace(2880, seed=0)
    steps_per_hr = int(3600 / 30)
    is_peak = np.asarray(tr.is_peak)
    assert is_peak[10 * steps_per_hr] == 1.0  # 10:00
    assert is_peak[3 * steps_per_hr] == 0.0   # 03:00


def test_replay_round_trip(tmp_path, synth):
    tr = synth.trace(96, seed=1)
    path = str(tmp_path / "trace.npz")
    save_trace(path, tr, synth.meta())
    loaded, meta = load_trace(path)
    assert np.allclose(np.asarray(loaded.demand_pods), np.asarray(tr.demand_pods))
    assert meta.zones == synth.meta().zones
    assert meta.dt_s == 30.0


def test_replay_tiling_and_offset(tmp_path, synth):
    tr = synth.trace(32, seed=1)
    path = str(tmp_path / "t.npz")
    save_trace(path, tr, synth.meta())
    src = ReplaySignalSource.from_file(path, offset_steps=8)
    longer = src.trace(100)
    assert longer.steps == 100
    # periodic extension: step 0 of the replay == step 8 of the original
    assert np.allclose(np.asarray(longer.spot_price_hr)[0],
                       np.asarray(tr.spot_price_hr)[8])


def _canned_fetch(responses):
    calls = []

    def fetch(url, headers):
        calls.append(url)
        for frag, body in responses.items():
            if frag in url:
                return json.dumps(body).encode()
        raise OSError(f"no canned response for {url}")

    fetch.calls = calls
    return fetch


def test_prometheus_instant_query_parsing():
    fetch = _canned_fetch({
        "/api/v1/query?": {
            "status": "success",
            "data": {"resultType": "vector", "result": [
                {"metric": {"__name__": "up", "job": "ksm"},
                 "value": [1700000000, "1"]},
            ]},
        },
    })
    client = PrometheusClient("http://amp.local/workspaces/w", fetch=fetch)
    out = client.query("up")
    assert out == [({"__name__": "up", "job": "ksm"}, 1.0)]


def test_prometheus_error_raises():
    fetch = _canned_fetch({"/api/v1/query?": {"status": "error", "error": "bad"}})
    client = PrometheusClient("http://amp.local", fetch=fetch)
    with pytest.raises(SignalUnavailable):
        client.query("up")


def test_prometheus_label_values():
    fetch = _canned_fetch({
        "/api/v1/label/__name__/values": {"status": "success",
                                          "data": ["up", "kube_pod_status_phase"]},
    })
    client = PrometheusClient("http://amp.local", fetch=fetch)
    assert "kube_pod_status_phase" in client.label_values("__name__")


def test_opencost_allocation_parsing():
    fetch = _canned_fetch({
        "/allocation": {"code": 200, "data": [
            {"nov-22": {"name": "nov-22", "totalCost": 1.25},
             "kube-system": {"name": "kube-system", "totalCost": 0.75}},
        ]},
    })
    client = OpenCostClient("http://opencost.local:9090", fetch=fetch)
    costs = client.allocation()
    assert costs["nov-22"] == pytest.approx(1.25)


def test_carbon_dummy_fallback_no_key():
    client = CarbonIntensityClient("https://api.example", api_key="",
                                   zone="US-CAL-CISO", default_g_kwh=400.0)
    assert client.latest() == 400.0  # .env:16 documented fallback


def test_carbon_live_parse_and_fallback_on_error():
    fetch = _canned_fetch({"carbon-intensity/latest": {"carbonIntensity": 123.4}})
    client = CarbonIntensityClient("https://api.example", api_key="k",
                                   zone="DE", default_g_kwh=400.0, fetch=fetch)
    assert client.latest() == pytest.approx(123.4)

    def broken(url, headers):
        raise OSError("net down")

    client2 = CarbonIntensityClient("https://api.example", api_key="k",
                                    zone="DE", default_g_kwh=400.0, fetch=broken)
    assert client2.latest() == 400.0


def test_live_source_tick_merges_live_data():
    cfg = default_config()
    fetch = _canned_fetch({
        "/api/v1/query?": {"status": "success", "data": {"result": [
            {"metric": {}, "value": [0, "40"]}]}},
        "/allocation": {"data": []},
        "/assets": {"data": {}},
    })
    src = LiveSignalSource(cfg.cluster, cfg.workload, cfg.sim, cfg.signals,
                           fetch=fetch)
    tick = src.tick(0)
    assert tick.steps == 1
    # pending(40) + running(40) = 80 pods spread over 2 classes
    assert np.asarray(tick.demand_pods).sum() == pytest.approx(80.0)


def test_spot_price_client_parses_latest_per_zone():
    """VERDICT r2 missing #8: canned describe-spot-price-history JSON →
    newest price per AZ; junk records skipped; failures → {}."""
    import json as _json

    from ccka_tpu.signals.live import SpotPriceClient

    doc = {"SpotPriceHistory": [
        {"AvailabilityZone": "us-east-2a", "SpotPrice": "0.0301",
         "Timestamp": "2026-07-30T08:00:00Z"},
        {"AvailabilityZone": "us-east-2a", "SpotPrice": "0.0333",
         "Timestamp": "2026-07-30T09:00:00Z"},   # newer — wins
        {"AvailabilityZone": "us-east-2b", "SpotPrice": "0.0288",
         "Timestamp": "2026-07-30T08:30:00Z"},
        {"AvailabilityZone": "us-east-2c", "SpotPrice": "not-a-price"},
        {"SpotPrice": "0.05"},                    # no AZ — skipped
    ]}
    argvs = []

    def runner(argv):
        argvs.append(argv)
        return 0, _json.dumps(doc)

    client = SpotPriceClient("us-east-2", "m6i.large", runner=runner)
    prices = client.latest_by_zone()
    assert prices == {"us-east-2a": 0.0333, "us-east-2b": 0.0288}
    # CLI shape: region + instance type + json output all pinned.
    joined = " ".join(argvs[0])
    assert "describe-spot-price-history" in joined
    assert "--region us-east-2" in joined and "m6i.large" in joined

    assert SpotPriceClient("r", "t", runner=lambda a: (1, "boom")
                           ).latest_by_zone() == {}
    assert SpotPriceClient("r", "t", runner=lambda a: (0, "not json")
                           ).latest_by_zone() == {}


def test_spot_price_client_ttl_cache():
    """The CLI call sits inside the 30s control tick: results (and
    failures) are cached for the TTL so a brownout can't block every
    tick on the runner's timeout+retry budget."""
    from ccka_tpu.signals.live import SpotPriceClient

    calls = []
    clock = [0.0]

    def runner(argv):
        calls.append(1)
        return 0, ('{"SpotPriceHistory": [{"AvailabilityZone": "z",'
                   ' "SpotPrice": "0.03", "Timestamp": "t"}]}')

    c = SpotPriceClient("r", "t", runner=runner, cache_ttl_s=300.0,
                        clock=lambda: clock[0])
    assert c.latest_by_zone() == {"z": 0.03}
    assert c.latest_by_zone() == {"z": 0.03}
    assert len(calls) == 1          # second hit served from cache
    clock[0] = 301.0
    c.latest_by_zone()
    assert len(calls) == 2          # TTL expiry refetches
    # Failures cache too — but on the SHORTER failure TTL: an empty
    # result marks the tick stale (degraded-mode input), and holding a
    # transient hiccup for the success TTL would pin rule-fallback for
    # ~10 ticks after the CLI recovered.
    fails = []
    cf = SpotPriceClient("r", "t", runner=lambda a: (fails.append(1),
                                                     (1, "boom"))[1],
                         cache_ttl_s=300.0, failure_ttl_s=60.0,
                         clock=lambda: clock[0])
    assert cf.latest_by_zone() == {} and cf.latest_by_zone() == {}
    assert len(fails) == 1
    clock[0] += 61.0
    assert cf.latest_by_zone() == {}
    assert len(fails) == 2          # failure TTL expiry re-probes sooner


def test_live_tick_uses_measured_spot_prices():
    """Zones with a live spot price get it; uncovered zones keep the
    synthetic prior (never fabricate a number for a zone the feed missed)."""
    import json as _json

    cfg = default_config()
    fetch = _canned_fetch({})

    def spot_runner(argv):
        return 0, _json.dumps({"SpotPriceHistory": [
            {"AvailabilityZone": "us-east-2a", "SpotPrice": "0.0123",
             "Timestamp": "2026-07-30T09:00:00Z"}]})

    src = LiveSignalSource(cfg.cluster, cfg.workload, cfg.sim, cfg.signals,
                           fetch=fetch, spot_runner=spot_runner)
    baseline = LiveSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals, fetch=fetch,
                                start_unix_s=src.start_unix_s)
    tick, base = src.tick(0), baseline.tick(0)
    spot = np.asarray(tick.spot_price_hr)[0]
    prior = np.asarray(base.spot_price_hr)[0]
    assert spot[0] == pytest.approx(0.0123)          # measured
    assert spot[1] == pytest.approx(prior[1])        # prior passthrough
    assert spot[2] == pytest.approx(prior[2])


def test_live_demand_classified_per_class_from_pod_series():
    """VERDICT r2 weak #4: demand should be namespace-scoped and split by
    workload class (burst odd→spot / even→od), not a whole-cluster total
    spread evenly."""
    cfg = default_config()
    pods = [
        ({"pod": "burst-web-1-abc-x"}, 1.0),   # odd → spot (x3 replicas)
        ({"pod": "burst-web-1-abc-y"}, 1.0),
        ({"pod": "burst-web-1-abc-z"}, 1.0),
        ({"pod": "burst-web-2-def-x"}, 1.0),   # even → od
        ({"pod": "helper-7d9-q"}, 1.0),        # unpinned → split
    ]
    from urllib.parse import quote
    fetch = _canned_fetch({
        # URL fragment is percent-encoded by the client.
        quote('phase=~"Pending|Running"'): {
            "status": "success",
            "data": {"result": [
                {"metric": m, "value": [0, str(v)]} for m, v in pods]},
        },
        "/allocation": {"data": []},
        "/assets": {"data": {}},
    })
    src = LiveSignalSource(cfg.cluster, cfg.workload, cfg.sim, cfg.signals,
                           fetch=fetch)
    tick = src.tick(0)
    demand = np.asarray(tick.demand_pods)[0]
    assert demand[0] == pytest.approx(3.5)   # 3 spot + half the helper
    assert demand[1] == pytest.approx(1.5)   # 1 od + half the helper


def test_spot_feed_config_gate():
    """signals.spot_feed="aws" wires the CLI clients (one per region);
    default config leaves the feed disabled; bad values are ConfigError."""
    import pytest as _pytest

    from ccka_tpu.config import ConfigError

    cfg = default_config()
    src = LiveSignalSource(cfg.cluster, cfg.workload, cfg.sim, cfg.signals,
                           fetch=_canned_fetch({}))
    assert src.spot_clients == []
    cfg2 = cfg.with_overrides(**{"signals.spot_feed": "aws"})
    src2 = LiveSignalSource(cfg2.cluster, cfg2.workload, cfg2.sim,
                            cfg2.signals, fetch=_canned_fetch({}))
    assert [c.region for c in src2.spot_clients] == ["us-east-2"]
    with _pytest.raises(ConfigError):
        cfg.with_overrides(**{"signals.spot_feed": "gcp"})


def test_live_source_forecast_is_forward_and_level_matched():
    """The live forecast must track NOW's measured levels (persistence of
    anomaly), not replay the backfilled history window (round-2 review
    finding: a frozen window would mis-plan every MPC replan)."""
    cfg = default_config()
    fetch = _canned_fetch({
        "/api/v1/query?": {"status": "success", "data": {"result": [
            {"metric": {}, "value": [0, "40"]}]}},
        "/allocation": {"data": []},
        "/assets": {"data": {}},
    })
    src = LiveSignalSource(cfg.cluster, cfg.workload, cfg.sim, cfg.signals,
                           fetch=fetch, start_unix_s=0.0)
    fc = src.forecast(0, 16)
    assert fc.steps == 16
    # Measured demand (80 pods) dominates the synthetic prior's first tick.
    first = float(np.asarray(fc.demand_pods)[0].sum())
    assert first == pytest.approx(80.0, rel=0.05)
    # Forecast differs from the backfilled-history slice (the old bug).
    hist = src.trace(16)
    assert not np.allclose(np.asarray(fc.demand_pods),
                           np.asarray(hist.demand_pods))


def test_synthetic_forecast_matches_trace_slice(synth):
    fc = synth.forecast(37, 16, seed=5)
    full = synth.trace(53, seed=5)
    assert np.array_equal(np.asarray(fc.spot_price_hr),
                          np.asarray(full.spot_price_hr)[37:53])


def test_factory_dispatch():
    cfg = default_config()
    src = make_signal_source(cfg.cluster, cfg.workload, cfg.sim, cfg.signals)
    assert isinstance(src, SyntheticSignalSource)
    assert isinstance(src.trace(4), ExogenousTrace)


def test_synthetic_prefix_stable_and_cached(synth):
    """trace(k) must equal trace(n)[:k] — tick-by-tick consumers rely on it."""
    long = synth.trace(200, seed=11)
    short = synth.trace(50, seed=11)
    assert np.allclose(np.asarray(short.carbon_g_kwh),
                       np.asarray(long.carbon_g_kwh)[:50])
    assert np.allclose(np.asarray(short.demand_pods),
                       np.asarray(long.demand_pods)[:50])


def test_trace_shape_validation_raises():
    import jax.numpy as jnp
    with pytest.raises(ValueError, match="inconsistent trace shapes"):
        ExogenousTrace(
            spot_price_hr=jnp.zeros((4, 3)), od_price_hr=jnp.zeros((4, 3)),
            carbon_g_kwh=jnp.zeros((4, 3)), demand_pods=jnp.zeros((5, 2)),
            is_peak=jnp.zeros((4,)),
        ).validate_shapes()


def test_live_trace_backfills_pending_plus_running():
    cfg = default_config()
    anchor = 86400.0 * 100
    start = anchor - 8 * 30.0
    pts = [[start + i * 30.0, "10"] for i in range(8)]
    fetch = _canned_fetch({
        "/api/v1/query_range": {"status": "success", "data": {"result": [
            {"metric": {}, "values": pts}]}},
    })
    src = LiveSignalSource(cfg.cluster, cfg.workload, cfg.sim, cfg.signals,
                           fetch=fetch, start_unix_s=anchor)
    tr = src.trace(8)
    # pending(10) + running(10) = 20 pods per step across 2 classes
    assert np.asarray(tr.demand_pods).sum(-1) == pytest.approx(np.full(8, 20.0))


def test_replay_backend_reachable_via_config(tmp_path):
    cfg0 = default_config()
    synth = SyntheticSignalSource(cfg0.cluster, cfg0.workload, cfg0.sim,
                                  cfg0.signals)
    path = str(tmp_path / "rt.npz")
    save_trace(path, synth.trace(16, seed=0), synth.meta())
    cfg = cfg0.with_overrides(**{"signals.backend": "replay",
                                 "signals.replay_path": path})
    src = make_signal_source(cfg.cluster, cfg.workload, cfg.sim, cfg.signals)
    assert isinstance(src, ReplaySignalSource)
    assert src.trace(8).steps == 8


def test_replay_backend_missing_path_is_config_error():
    from ccka_tpu.config import ConfigError
    with pytest.raises(ConfigError, match="replay_path"):
        default_config().with_overrides(**{"signals.backend": "replay"})


def test_live_trace_backfill_aligned_by_timestamp():
    # Samples are placed by returned timestamps: a range result covering only
    # the last 4 ticks must land at indices 4..7, not 0..3.
    cfg = default_config()
    anchor = 86400.0 * 10
    steps = 8
    start = anchor - steps * 30.0
    pts = [[start + i * 30.0, "7"] for i in range(4, 8)]
    fetch = _canned_fetch({
        "/api/v1/query_range": {"status": "success", "data": {"result": [
            {"metric": {}, "values": pts}]}},
    })
    src = LiveSignalSource(cfg.cluster, cfg.workload, cfg.sim, cfg.signals,
                           fetch=fetch, start_unix_s=anchor)
    tr = src.trace(steps)
    demand = np.asarray(tr.demand_pods).sum(-1)
    assert demand[4:] == pytest.approx(np.full(4, 14.0))  # 7 pending + 7 running
    assert not np.allclose(demand[:4], 14.0)


class TestReplayBatchWindows:
    """BASELINE config #3: a replayed-trace PPO batch must be B distinct
    windows, not B copies (replay ignores seeds, so the base default
    would collapse the batch)."""

    def _source(self, steps=256):
        from ccka_tpu.config import default_config
        from ccka_tpu.signals.replay import ReplaySignalSource
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        cfg = default_config()
        synth = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                      cfg.signals)
        return ReplaySignalSource(synth.trace(steps), synth.meta())

    def test_batch_windows_are_distinct_and_deterministic(self):
        import numpy as np

        src = self._source()
        batch = src.batch_trace(32, range(8))
        carbon = np.asarray(batch.carbon_g_kwh)
        assert carbon.shape[:2] == (8, 32)
        # Pairwise distinct windows (golden-ratio offsets never collide
        # for small batches over a 256-step store).
        flat = carbon.reshape(8, -1)
        for i in range(8):
            for j in range(i + 1, 8):
                assert not np.allclose(flat[i], flat[j]), (i, j)
        # Deterministic: same seeds → identical batch.
        again = np.asarray(src.batch_trace(32, range(8)).carbon_g_kwh)
        np.testing.assert_array_equal(carbon, again)

    def test_full_store_of_seeds_is_collision_free(self):
        """Coprime-multiplier offsets are a bijection: as many distinct
        windows as the store can hold, zero collisions (the golden-ratio
        float truncation this replaces lost ~14% of a 256-batch)."""
        import math

        src = self._source(steps=256)
        stored = 256
        step = max(1, round(stored * 0.6180339887498949))
        while math.gcd(step, stored) != 1:
            step += 1
        offsets = {(s * step) % stored for s in range(stored)}
        assert len(offsets) == stored

    def test_pigeonhole_batch_warns(self):
        import warnings as w

        src = self._source(steps=16)
        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            src.batch_trace(8, range(32))  # 32 seeds, 16-step store
        assert any("pigeonhole" in str(c.message) for c in caught)

    def test_seed_zero_matches_plain_trace(self):
        import numpy as np

        src = self._source()
        batch = src.batch_trace(16, [0])
        np.testing.assert_array_equal(
            np.asarray(batch.carbon_g_kwh[0]),
            np.asarray(src.trace(16).carbon_g_kwh))

    def test_batch_trace_device_windows(self):
        """On-device window sampling (the mega ES engine's trace feed):
        [n, T] shapes, every window a contiguous slice of the periodic
        extension, deterministic per key, fresh per key."""
        import jax
        import numpy as np

        src = self._source(steps=256)
        stored_c = np.asarray(src._trace.carbon_g_kwh)
        ext = np.concatenate([stored_c, stored_c], axis=0)
        batch = src.batch_trace_device(32, jax.random.key(3), 8)
        carbon = np.asarray(batch.carbon_g_kwh)
        assert carbon.shape[:2] == (8, 32)
        for w in carbon:
            # Each window matches the stored trace at SOME offset.
            assert any(np.array_equal(w, ext[o:o + 32])
                       for o in range(256)), "window not a stored slice"
        again = np.asarray(
            src.batch_trace_device(32, jax.random.key(3), 8).carbon_g_kwh)
        np.testing.assert_array_equal(carbon, again)
        other = np.asarray(
            src.batch_trace_device(32, jax.random.key(4), 8).carbon_g_kwh)
        assert not np.array_equal(carbon, other)

    def test_batch_trace_device_respects_offset(self):
        import jax
        import numpy as np

        src = self._source(steps=64)
        src.offset_steps = 7
        b0 = np.asarray(src.batch_trace_device(
            16, jax.random.key(0), 4).carbon_g_kwh)
        src.offset_steps = 0
        b1 = np.asarray(src.batch_trace_device(
            16, jax.random.key(0), 4).carbon_g_kwh)
        assert not np.array_equal(b0, b1)

    @pytest.mark.slow  # ISSUE 16 lane-time rule: PPO-on-replay duplicates
    # the fast-lane replay-window parity + PPO reward tests.
    def test_ppo_trains_on_replayed_traces(self):
        """Config #3 end to end: PPO over a replayed-trace batch runs and
        produces finite diagnostics (device_traces is ignored — replay
        has no device path)."""
        import numpy as np

        from ccka_tpu.config import default_config
        from ccka_tpu.train.ppo import PPOTrainer

        cfg = default_config().with_overrides(**{
            "train.batch_clusters": 4, "train.unroll_steps": 8})
        src = self._source()
        ts, history = PPOTrainer(cfg).train(src, iterations=2, log_every=1)
        assert int(ts.iteration) == 2
        assert all(np.isfinite(h["mean_reward"]) for h in history)
