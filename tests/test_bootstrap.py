"""Bootstrap / cleanup / generic-manifest tests (VERDICT items 4 and 5).

The reference never creates the NodePools or EC2NodeClass its demos consume
(SURVEY §2.1 `demo_01` row) and never applies the HPA/KEDA objects it names
(§2.3). These tests cover the framework's realization of both: manifest
shapes, the bootstrap -> preroll-neutral -> profile-patch round trip, the
demo_50 teardown ordering, and HPA flowing through the generic apply path
with lifecycle verification.
"""

import json

import pytest

from ccka_tpu.actuation import (
    DryRunSink,
    KubectlSink,
    bootstrap,
    cleanup,
    render_hpa_manifests,
    render_keda_scaledobject,
    render_nodepool_manifest,
    render_ec2nodeclass_manifest,
    render_nodepool_patches,
)
from ccka_tpu.actuation.bootstrap import NODECLASS_NAME
from ccka_tpu.policy import offpeak_action
from ccka_tpu.sim.types import Action


def test_nodepool_manifest_shape(cfg):
    pool = cfg.cluster.pools[0]
    doc = render_nodepool_manifest(cfg.cluster, pool)
    assert doc["kind"] == "NodePool"
    assert doc["metadata"]["name"] == "spot-preferred"
    # demo_10:59-62 labels
    assert doc["metadata"]["labels"] == {
        "autoscale.strategy": "cost", "carbon.simulated": "low"}
    reqs = {r["key"]: r["values"]
            for r in doc["spec"]["template"]["spec"]["requirements"]}
    # Neutral state the preroll gate asserts (demo_18:42-55): all zones,
    # the pool's intrinsic capacity types, WhenEmpty/30s.
    assert reqs["topology.kubernetes.io/zone"] == list(cfg.cluster.zones)
    assert reqs["karpenter.sh/capacity-type"] == ["spot", "on-demand"]
    assert doc["spec"]["disruption"] == {
        "consolidationPolicy": "WhenEmpty", "consolidateAfter": "30s"}
    assert doc["spec"]["template"]["spec"]["nodeClassRef"]["name"] == \
        NODECLASS_NAME


def test_od_pool_manifest_never_offers_spot(cfg):
    doc = render_nodepool_manifest(cfg.cluster, cfg.cluster.pools[1])
    reqs = {r["key"]: r["values"]
            for r in doc["spec"]["template"]["spec"]["requirements"]}
    assert reqs["karpenter.sh/capacity-type"] == ["on-demand"]
    assert doc["metadata"]["labels"]["autoscale.strategy"] == "slo"


def test_ec2nodeclass_manifest(cfg):
    doc = render_ec2nodeclass_manifest(cfg.cluster)
    assert doc["kind"] == "EC2NodeClass"
    assert doc["metadata"]["name"] == "default-ec2"  # demo_50:43-44
    assert doc["spec"]["role"] == f"KarpenterNodeRole-{cfg.cluster.name}"


def test_bootstrap_preroll_profile_round_trip(cfg):
    """The VERDICT 'done' criterion: bootstrap -> pools exist neutral ->
    profile patch applies -> reset returns to neutral, all via DryRunSink."""
    sink = DryRunSink()
    results = bootstrap(cfg, sink)
    assert all(r.ok for r in results)
    assert len(results) == 1 + len(cfg.cluster.pools)

    # Pools observable and neutral (what demo_18 asserts).
    for pool in cfg.cluster.pools:
        obs = sink.observed_state(pool.name)
        assert obs["consolidationPolicy"] == "WhenEmpty"
        assert obs["zones"] == list(cfg.cluster.zones)

    # Profile patches now land on the bootstrapped pools.
    patches = render_nodepool_patches(offpeak_action(cfg.cluster),
                                      cfg.cluster, op="replace")
    applied = sink.apply_all(patches)
    assert all(r.ok for r in applied)
    spot = sink.observed_state(cfg.cluster.pools[0].name)
    assert spot["consolidationPolicy"] == "WhenEmptyOrUnderutilized"
    assert spot["zones"] == list(cfg.cluster.offpeak_zones)


def test_bootstrap_aborts_without_nodeclass(cfg):
    class NoClassSink(DryRunSink):
        def _apply(self, cmd):
            if cmd.kind == "EC2NodeClass":
                self.commands.append(cmd)
                return False
            return super()._apply(cmd)

    sink = NoClassSink()
    results = bootstrap(cfg, sink)
    assert len(results) == 1 and not results[0].ok  # pools never attempted


def test_cleanup_order_and_wipe(cfg):
    sink = DryRunSink()
    bootstrap(cfg, sink)
    results = cleanup(cfg, sink, wipe_nodeclass=True)
    assert all(ok for _, ok in results)
    names = [n for n, _ in results]
    # demo_50 ordering: namespace, then ALL pools, then claims, then class.
    assert names[0] == "namespace/nov-22"
    assert names[1:3] == ["nodepool/spot-preferred",
                          "nodepool/on-demand-slo"]
    assert names[-1] == f"ec2nodeclass/{NODECLASS_NAME}"
    # Pools gone from both stores.
    assert sink.store == {}
    assert not sink.get_object("nodepool", "spot-preferred")


def test_kubectl_sink_manifest_verbs():
    """Generic apply/delete through the argv runner, including the
    finalizer-scrub rescue path for a stuck object."""
    store: dict[str, dict] = {}
    stuck = {"hpa-burst-spot"}  # survives the first delete
    calls = []

    def runner(argv):
        calls.append(list(argv))
        if argv[1] == "apply":
            path = argv[argv.index("-f") + 1]
            doc = json.load(open(path))
            store[doc["metadata"]["name"]] = doc
            return 0, "applied"
        if argv[1] == "delete":
            name = argv[3]
            if name in stuck:
                stuck.discard(name)  # scrub will release it
                return 0, "deleting (stuck on finalizer)"
            store.pop(name, None)
            return 0, "deleted"
        if argv[1] == "patch":  # finalizer scrub
            store.pop(argv[3], None)
            return 0, "patched"
        if argv[1] == "get":
            name = argv[3]
            if name in store:
                return 0, json.dumps(store[name])
            return 1, "not found"
        return 1, "unhandled"

    sink = KubectlSink(runner)
    doc = {"apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
           "metadata": {"name": "hpa-burst-spot", "namespace": "nov-22"},
           "spec": {}}
    res = sink.apply_manifest(doc)
    assert res.ok
    assert sink.get_object("HorizontalPodAutoscaler", "hpa-burst-spot",
                           namespace="nov-22")["metadata"]["name"] == \
        "hpa-burst-spot"
    # Delete with scrub: first delete "sticks", get shows it alive, scrub
    # patch releases, second delete completes.
    assert sink.delete_object("HorizontalPodAutoscaler", "hpa-burst-spot",
                              namespace="nov-22", scrub_finalizers=True)
    assert "hpa-burst-spot" not in store
    assert any(c[1] == "patch" for c in calls)


def test_hpa_through_lifecycle_verification(cfg):
    """VERDICT item 5 'done': a lifecycle-style stage verifies an applied
    HPA from the sink store (not from the rendered intent)."""
    action = Action.neutral(cfg.cluster.n_pools, cfg.cluster.n_zones)
    manifests = render_hpa_manifests(action, cfg.cluster, cfg.workload)
    sink = DryRunSink()
    results = sink.apply_manifests(manifests)
    assert all(r.ok for r in results)
    for doc in manifests:
        got = sink.get_object("HorizontalPodAutoscaler",
                              doc["metadata"]["name"],
                              namespace=doc["metadata"]["namespace"])
        assert got["spec"]["scaleTargetRef"] == \
            doc["spec"]["scaleTargetRef"]
        assert got["spec"]["maxReplicas"] >= got["spec"]["minReplicas"] >= 1


def test_keda_through_apply_path(cfg):
    action = Action.neutral(cfg.cluster.n_pools, cfg.cluster.n_zones)
    doc = render_keda_scaledobject(action, "burst-queue", "123456789012")
    sink = DryRunSink()
    assert sink.apply_manifest(doc).ok
    got = sink.get_object("ScaledObject", doc["metadata"]["name"],
                          namespace="nov-22")
    assert got["spec"]["triggers"][0]["type"] == "aws-sqs-queue"


def test_controller_applies_hpa_when_enabled(cfg):
    from ccka_tpu.harness.controller import Controller
    from ccka_tpu.policy import RulePolicy
    from ccka_tpu.signals.synthetic import SyntheticSignalSource

    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals)
    sink = DryRunSink()
    ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, sink,
                      interval_s=0.0, apply_hpa=True,
                      log_fn=lambda _l: None)
    reports = ctrl.run(ticks=2)
    assert all(r.applied for r in reports)
    assert sink.get_object("HorizontalPodAutoscaler", "hpa-burst-spot",
                           namespace="nov-22")


def test_cli_bootstrap_json(capsys):
    from ccka_tpu.cli import main
    assert main(["bootstrap", "--json"]) == 0
    docs = json.loads(capsys.readouterr().out)
    assert [d["kind"] for d in docs] == ["EC2NodeClass", "NodePool",
                                        "NodePool"]


def test_cli_bootstrap_then_cleanup_dry_run(capsys):
    from ccka_tpu.cli import main
    assert main(["bootstrap"]) == 0
    out = capsys.readouterr()
    assert "kubectl apply" in out.out
    assert main(["cleanup", "--wipe-nodeclass"]) == 0
    out = capsys.readouterr()
    assert "kubectl delete nodepool spot-preferred" in out.out
    assert "ec2nodeclass" in out.out
