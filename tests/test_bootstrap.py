"""Bootstrap / cleanup / generic-manifest tests (VERDICT items 4 and 5).

The reference never creates the NodePools or EC2NodeClass its demos consume
(SURVEY §2.1 `demo_01` row) and never applies the HPA/KEDA objects it names
(§2.3). These tests cover the framework's realization of both: manifest
shapes, the bootstrap -> preroll-neutral -> profile-patch round trip, the
demo_50 teardown ordering, and HPA flowing through the generic apply path
with lifecycle verification.
"""

import json

import pytest

from ccka_tpu.actuation import (
    DryRunSink,
    KubectlSink,
    bootstrap,
    cleanup,
    render_hpa_manifests,
    render_keda_scaledobject,
    render_nodepool_manifest,
    render_ec2nodeclass_manifest,
    render_nodepool_patches,
)
from ccka_tpu.actuation.bootstrap import NODECLASS_NAME
from ccka_tpu.policy import offpeak_action
from ccka_tpu.sim.types import Action


def test_nodepool_manifest_shape(cfg):
    pool = cfg.cluster.pools[0]
    doc = render_nodepool_manifest(cfg.cluster, pool)
    assert doc["kind"] == "NodePool"
    assert doc["metadata"]["name"] == "spot-preferred"
    # demo_10:59-62 labels
    assert doc["metadata"]["labels"] == {
        "autoscale.strategy": "cost", "carbon.simulated": "low"}
    reqs = {r["key"]: r["values"]
            for r in doc["spec"]["template"]["spec"]["requirements"]}
    # Neutral state the preroll gate asserts (demo_18:42-55): all zones,
    # the pool's intrinsic capacity types, WhenEmpty/30s.
    assert reqs["topology.kubernetes.io/zone"] == list(cfg.cluster.zones)
    assert reqs["karpenter.sh/capacity-type"] == ["spot", "on-demand"]
    assert doc["spec"]["disruption"] == {
        "consolidationPolicy": "WhenEmpty", "consolidateAfter": "30s"}
    assert doc["spec"]["template"]["spec"]["nodeClassRef"]["name"] == \
        NODECLASS_NAME


def test_od_pool_manifest_never_offers_spot(cfg):
    doc = render_nodepool_manifest(cfg.cluster, cfg.cluster.pools[1])
    reqs = {r["key"]: r["values"]
            for r in doc["spec"]["template"]["spec"]["requirements"]}
    assert reqs["karpenter.sh/capacity-type"] == ["on-demand"]
    assert doc["metadata"]["labels"]["autoscale.strategy"] == "slo"


def test_ec2nodeclass_manifest(cfg):
    doc = render_ec2nodeclass_manifest(cfg.cluster)
    assert doc["kind"] == "EC2NodeClass"
    assert doc["metadata"]["name"] == "default-ec2"  # demo_50:43-44
    assert doc["spec"]["role"] == f"KarpenterNodeRole-{cfg.cluster.name}"


def test_bootstrap_preroll_profile_round_trip(cfg):
    """The VERDICT 'done' criterion: bootstrap -> pools exist neutral ->
    profile patch applies -> reset returns to neutral, all via DryRunSink."""
    sink = DryRunSink()
    results = bootstrap(cfg, sink)
    assert all(r.ok for r in results)
    assert len(results) == 1 + len(cfg.cluster.pools)

    # Pools observable and neutral (what demo_18 asserts).
    for pool in cfg.cluster.pools:
        obs = sink.observed_state(pool.name)
        assert obs["consolidationPolicy"] == "WhenEmpty"
        assert obs["zones"] == list(cfg.cluster.zones)

    # Profile patches now land on the bootstrapped pools.
    patches = render_nodepool_patches(offpeak_action(cfg.cluster),
                                      cfg.cluster, op="replace")
    applied = sink.apply_all(patches)
    assert all(r.ok for r in applied)
    spot = sink.observed_state(cfg.cluster.pools[0].name)
    assert spot["consolidationPolicy"] == "WhenEmptyOrUnderutilized"
    assert spot["zones"] == list(cfg.cluster.offpeak_zones)


def test_bootstrap_aborts_without_nodeclass(cfg):
    class NoClassSink(DryRunSink):
        def _apply(self, cmd):
            if cmd.kind == "EC2NodeClass":
                self.commands.append(cmd)
                return False
            return super()._apply(cmd)

    sink = NoClassSink()
    results = bootstrap(cfg, sink)
    assert len(results) == 1 and not results[0].ok  # pools never attempted


def test_cleanup_order_and_wipe(cfg):
    sink = DryRunSink()
    bootstrap(cfg, sink)
    results = cleanup(cfg, sink, wipe_nodeclass=True)
    assert all(ok for _, ok in results)
    names = [n for n, _ in results]
    # demo_50 ordering: namespace, then ALL pools, then claims, then class.
    assert names[0] == "namespace/nov-22"
    assert names[1:3] == ["nodepool/spot-preferred",
                          "nodepool/on-demand-slo"]
    assert names[-1] == f"ec2nodeclass/{NODECLASS_NAME}"
    # Pools gone from both stores.
    assert sink.store == {}
    assert not sink.get_object("nodepool", "spot-preferred")


def test_kubectl_sink_manifest_verbs():
    """Generic apply/delete through the argv runner, including the
    finalizer-scrub rescue path for a stuck object."""
    store: dict[str, dict] = {}
    stuck = {"hpa-burst-spot"}  # survives the first delete
    calls = []

    def runner(argv):
        calls.append(list(argv))
        if argv[1] == "apply":
            path = argv[argv.index("-f") + 1]
            doc = json.load(open(path))
            store[doc["metadata"]["name"]] = doc
            return 0, "applied"
        if argv[1] == "delete":
            name = argv[3]
            if name in stuck:
                stuck.discard(name)  # scrub will release it
                return 0, "deleting (stuck on finalizer)"
            store.pop(name, None)
            return 0, "deleted"
        if argv[1] == "patch":  # finalizer scrub
            store.pop(argv[3], None)
            return 0, "patched"
        if argv[1] == "get":
            name = argv[3]
            if name in store:
                return 0, json.dumps(store[name])
            return 1, "not found"
        return 1, "unhandled"

    sink = KubectlSink(runner)
    doc = {"apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
           "metadata": {"name": "hpa-burst-spot", "namespace": "nov-22"},
           "spec": {}}
    res = sink.apply_manifest(doc)
    assert res.ok
    assert sink.get_object("HorizontalPodAutoscaler", "hpa-burst-spot",
                           namespace="nov-22")["metadata"]["name"] == \
        "hpa-burst-spot"
    # Delete with scrub: first delete "sticks", get shows it alive, scrub
    # patch releases, second delete completes.
    assert sink.delete_object("HorizontalPodAutoscaler", "hpa-burst-spot",
                              namespace="nov-22", scrub_finalizers=True)
    assert "hpa-burst-spot" not in store
    assert any(c[1] == "patch" for c in calls)


def test_hpa_through_lifecycle_verification(cfg):
    """VERDICT item 5 'done': a lifecycle-style stage verifies an applied
    HPA from the sink store (not from the rendered intent)."""
    action = Action.neutral(cfg.cluster.n_pools, cfg.cluster.n_zones)
    manifests = render_hpa_manifests(action, cfg.cluster, cfg.workload)
    sink = DryRunSink()
    results = sink.apply_manifests(manifests)
    assert all(r.ok for r in results)
    for doc in manifests:
        got = sink.get_object("HorizontalPodAutoscaler",
                              doc["metadata"]["name"],
                              namespace=doc["metadata"]["namespace"])
        assert got["spec"]["scaleTargetRef"] == \
            doc["spec"]["scaleTargetRef"]
        assert got["spec"]["maxReplicas"] >= got["spec"]["minReplicas"] >= 1


def test_keda_through_apply_path(cfg):
    action = Action.neutral(cfg.cluster.n_pools, cfg.cluster.n_zones)
    doc = render_keda_scaledobject(action, "burst-queue", "123456789012")
    sink = DryRunSink()
    assert sink.apply_manifest(doc).ok
    got = sink.get_object("ScaledObject", doc["metadata"]["name"],
                          namespace="nov-22")
    assert got["spec"]["triggers"][0]["type"] == "aws-sqs-queue"


def test_controller_applies_hpa_when_enabled(cfg):
    from ccka_tpu.harness.controller import Controller
    from ccka_tpu.policy import RulePolicy
    from ccka_tpu.signals.synthetic import SyntheticSignalSource

    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals)
    sink = DryRunSink()
    ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, sink,
                      interval_s=0.0, apply_hpa=True,
                      log_fn=lambda _l: None)
    reports = ctrl.run(ticks=2)
    assert all(r.applied for r in reports)
    assert sink.get_object("HorizontalPodAutoscaler", "hpa-burst-spot",
                           namespace="nov-22")


def test_cli_bootstrap_json(capsys):
    from ccka_tpu.cli import main
    assert main(["bootstrap", "--json"]) == 0
    docs = json.loads(capsys.readouterr().out)
    assert [d["kind"] for d in docs] == ["EC2NodeClass", "NodePool",
                                        "NodePool"]


def test_cli_bootstrap_then_cleanup_dry_run(capsys):
    from ccka_tpu.cli import main
    assert main(["bootstrap"]) == 0
    out = capsys.readouterr()
    assert "kubectl apply" in out.out
    assert main(["cleanup", "--wipe-nodeclass"]) == 0
    out = capsys.readouterr()
    assert "kubectl delete nodepool spot-preferred" in out.out
    assert "ec2nodeclass" in out.out


class TestAwsAuthMapping:
    """demo_15_map_karp_nodes.sh analog: without the node-role mapping,
    provisioned instances never join (demo_15:5-12)."""

    def _sink_with_aws_auth(self, map_roles=""):
        from ccka_tpu.actuation import DryRunSink
        sink = DryRunSink()
        sink.objects[("configmap", "kube-system", "aws-auth")] = {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "aws-auth", "namespace": "kube-system"},
            "data": {"mapRoles": map_roles},
        }
        return sink

    def test_adds_mapping_and_verifies(self):
        from ccka_tpu.actuation import ensure_node_role_mapping
        from ccka_tpu.config import default_config

        cfg = default_config()
        sink = self._sink_with_aws_auth("- rolearn: arn:aws:iam::1:role/x\n")
        r = ensure_node_role_mapping(cfg, sink, account_id="123456789012")
        assert r.ok
        cm = sink.get_object("configmap", "aws-auth",
                             namespace="kube-system")
        roles = cm["data"]["mapRoles"]
        assert "arn:aws:iam::123456789012:role/KarpenterNodeRole-demo1" in roles
        assert "system:node:{{EC2PrivateDNSName}}" in roles
        assert "system:bootstrappers" in roles
        # Pre-existing mappings survive (the awk-patch discipline,
        # demo_15:49-72 appends, never rewrites).
        assert "arn:aws:iam::1:role/x" in roles

    def test_idempotent(self):
        from ccka_tpu.actuation import ensure_node_role_mapping
        from ccka_tpu.config import default_config

        cfg = default_config()
        sink = self._sink_with_aws_auth()
        assert ensure_node_role_mapping(cfg, sink,
                                        account_id="123456789012").ok
        r2 = ensure_node_role_mapping(cfg, sink, account_id="123456789012")
        assert r2.ok and r2.detail == "already mapped"
        roles = sink.get_object("configmap", "aws-auth",
                                namespace="kube-system")["data"]["mapRoles"]
        assert roles.count("KarpenterNodeRole-demo1") == 1

    def test_missing_configmap_fails(self):
        from ccka_tpu.actuation import DryRunSink, ensure_node_role_mapping
        from ccka_tpu.config import default_config

        r = ensure_node_role_mapping(default_config(), DryRunSink(),
                                     account_id="123456789012")
        assert not r.ok and "not found" in r.detail

    def test_cli_dry_run(self, capsys):
        from ccka_tpu.cli import main

        assert main(["map-nodes", "--account-id", "123456789012"]) == 0
        assert "[ok] configmap/aws-auth" in capsys.readouterr().err


class TestPrerollLiveGates:
    """The demo_18 live assertions added this round: leftover burst
    workloads (:30-39) and the aws-auth mapping (:67-81)."""

    def _runner(self, responses):
        def runner(argv):
            key = " ".join(argv)
            for frag, (rc, out) in responses.items():
                if frag in key:
                    return rc, out
            return 0, "WhenEmpty"
        return runner

    def test_leftover_burst_fails_gate(self):
        from ccka_tpu.config import default_config
        from ccka_tpu.harness.preroll import check_no_leftover_burst

        cfg = default_config()
        bad = self._runner({"get deploy": (0, "deployment.apps/burst-web-1\n")})
        c = check_no_leftover_burst(cfg, bad)
        assert not c.ok and "ccka burst --delete" in c.hint
        clean = self._runner({"get deploy": (0, "")})
        assert check_no_leftover_burst(cfg, clean).ok

    def test_aws_auth_gate(self):
        from ccka_tpu.config import default_config
        from ccka_tpu.harness.preroll import check_aws_auth

        cfg = default_config()
        unmapped = self._runner({"configmap aws-auth": (0, "- rolearn: other\n")})
        c = check_aws_auth(cfg, unmapped)
        assert not c.ok and "map-nodes" in c.hint
        mapped = self._runner({"configmap aws-auth":
                               (0, "- rolearn: arn:aws:iam::1:role/"
                                   "KarpenterNodeRole-demo1\n")})
        assert check_aws_auth(cfg, mapped).ok

    def test_live_preroll_includes_new_gates(self):
        from ccka_tpu.config import default_config
        from ccka_tpu.harness.preroll import run_preroll

        ok_runner = self._runner({
            "get deploy": (0, ""),
            "configmap aws-auth": (0, "- rolearn: arn:aws:iam::1:role/"
                                      "KarpenterNodeRole-demo1"),
        })
        assert run_preroll(default_config(), live=True, runner=ok_runner,
                           echo=False) == 0


class TestMappingPrefixCollisions:
    """Exact-token matching: `demo1` must not be satisfied by another
    cluster's `KarpenterNodeRole-demo10` entry (prefix collision)."""

    def test_ensure_mapping_ignores_prefix_collision(self):
        from ccka_tpu.actuation import DryRunSink, ensure_node_role_mapping
        from ccka_tpu.config import default_config

        sink = DryRunSink()
        sink.objects[("configmap", "kube-system", "aws-auth")] = {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "aws-auth", "namespace": "kube-system"},
            "data": {"mapRoles": "- rolearn: arn:aws:iam::123456789012:"
                                 "role/KarpenterNodeRole-demo10\n"},
        }
        r = ensure_node_role_mapping(default_config(), sink,
                                     account_id="123456789012")
        assert r.ok and r.detail != "already mapped"
        roles = sink.get_object("configmap", "aws-auth",
                                namespace="kube-system")["data"]["mapRoles"]
        assert "role/KarpenterNodeRole-demo1\n" in roles

    def test_preroll_gate_rejects_prefix_collision(self):
        from ccka_tpu.config import default_config
        from ccka_tpu.harness.preroll import check_aws_auth

        def runner(argv):
            return 0, "- rolearn: arn:aws:iam::1:role/KarpenterNodeRole-demo10"
        assert not check_aws_auth(default_config(), runner).ok

    def test_burst_gate_fails_on_unreachable_kubectl(self):
        from ccka_tpu.config import default_config
        from ccka_tpu.harness.preroll import check_no_leftover_burst

        def broken(argv):
            return 127, "kubectl: command not found"
        c = check_no_leftover_burst(default_config(), broken)
        assert not c.ok
        def notfound(argv):
            return 1, 'Error from server (NotFound): namespaces "nov-22"'
        assert check_no_leftover_burst(default_config(), notfound).ok


def test_role_matcher_handles_quotes_and_rejects_nonarn_mentions():
    """Shared matcher edge cases: quoted rolearn values count; the role
    name appearing in a username/groups value does not."""
    from ccka_tpu.actuation.bootstrap import role_mapped

    quoted = '- rolearn: "arn:aws:iam::1:role/KarpenterNodeRole-demo1"\n'
    assert role_mapped(quoted, role_name="KarpenterNodeRole-demo1")
    assert role_mapped(quoted,
                       role_arn="arn:aws:iam::1:role/KarpenterNodeRole-demo1")
    stray = ("- rolearn: arn:aws:iam::1:role/other\n"
             "  username: KarpenterNodeRole-demo1\n")
    assert not role_mapped(stray, role_name="KarpenterNodeRole-demo1")


def test_role_matcher_flow_and_json_styles():
    """aws-iam-authenticator accepts flow mappings and JSON too; the
    matcher must see rolearn values in all encodings (a block-only parse
    would fail the preroll gate on a correctly mapped cluster and make
    map-nodes append duplicates)."""
    from ccka_tpu.actuation.bootstrap import role_mapped

    flow = "- {rolearn: arn:aws:iam::1:role/KarpenterNodeRole-demo1, username: x}\n"
    assert role_mapped(flow, role_name="KarpenterNodeRole-demo1")
    js = '[{"rolearn": "arn:aws:iam::1:role/KarpenterNodeRole-demo1"}]'
    assert role_mapped(js, role_name="KarpenterNodeRole-demo1")
    # Exactness still holds across styles.
    assert not role_mapped(flow, role_name="KarpenterNodeRole-demo")
