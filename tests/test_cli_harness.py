"""CLI + harness tests: subcommand contracts, preroll gate, lifecycle pairs."""

import json

import pytest

from ccka_tpu.actuation import DryRunSink, render_nodepool_patches
from ccka_tpu.cli import main
from ccka_tpu.config import default_config
from ccka_tpu.harness import ConfigureObserve, Stage, run_preroll
from ccka_tpu.policy import offpeak_action, peak_action


def test_cli_show_config(capsys):
    assert main(["show-config"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["cluster"]["name"] == "demo1"


def test_cli_offpeak_dry_run(capsys):
    assert main(["offpeak"]) == 0
    captured = capsys.readouterr()
    assert "kubectl patch nodepool spot-preferred" in captured.out
    assert "WhenEmptyOrUnderutilized" in captured.out
    assert "offpeak profile rendered (dry-run)" in captured.err


def test_cli_peak_json_output(capsys):
    assert main(["peak", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    pools = {d["pool"] for d in doc}
    assert pools == {"spot-preferred", "on-demand-slo"}
    assert doc[0]["requirements_json"][0]["op"] == "add"  # demo_21:65


def test_cli_reset_neutral(capsys):
    assert main(["reset"]) == 0
    out = capsys.readouterr().out
    assert '"consolidateAfter": "30s"' in out or "30s" in out  # demo_19:22-29


def test_cli_set_override(capsys):
    assert main(["--set", "cluster.name=prod", "show-config"]) == 0
    assert json.loads(capsys.readouterr().out)["cluster"]["name"] == "prod"


def test_cli_observe(capsys):
    assert main(["observe"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["profile"] in ("peak", "offpeak")
    assert len(doc["consolidate_after_s"]) == 2


def test_cli_simulate_small(capsys):
    assert main(["--set", "sim.horizon_steps=16", "simulate", "--days",
                 "0.01", "--backend", "rule"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["cost_usd"] > 0
    assert 0.0 <= doc["slo_attainment"] <= 1.0


@pytest.mark.slow  # ISSUE 14 lane-time rule (~14s): a composition of
# independently fast-pinned pieces — mesh fan-out in test_parallel,
# device traces in test_signals, the simulate CLI by its non-mesh
# siblings in this file.
def test_cli_simulate_fleet_mesh_device_traces(capsys):
    """BASELINE config #5 path: batch sharded over the 8-device mesh with
    device-synthesized traces. 16 clusters / 8 devices = 2 per shard."""
    assert main(["simulate", "--days", "0.01", "--backend", "carbon",
                 "--clusters", "16", "--mesh", "--device-traces",
                 "--stochastic"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["clusters"] == 16
    assert doc["cost_usd"] > 0
    assert 0.0 <= doc["slo_attainment"] <= 1.0


def test_cli_simulate_fleet_flags_rejected_on_single_cluster():
    """--mesh/--device-traces only act on the batch path; silently running
    the single-cluster path instead would fake a fleet benchmark."""
    with pytest.raises(SystemExit, match="--clusters"):
        main(["simulate", "--days", "0.01", "--mesh"])
    with pytest.raises(SystemExit, match="--clusters"):
        main(["simulate", "--days", "0.01", "--device-traces"])


def test_cli_simulate_device_traces_requires_synthetic(tmp_path):
    from ccka_tpu.signals.replay import save_trace
    from ccka_tpu.signals.synthetic import SyntheticSignalSource

    cfg = default_config()
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals)
    path = str(tmp_path / "t.npz")
    save_trace(path, src.trace(32), src.meta())
    with pytest.raises(SystemExit, match="synthetic"):
        main(["--set", "signals.backend=replay",
              "--set", f"signals.replay_path={path}",
              "simulate", "--days", "0.01", "--clusters", "4",
              "--device-traces"])


def test_preroll_passes_offline(capsys):
    cfg = default_config()
    assert run_preroll(cfg, live=False) == 0
    out = capsys.readouterr().out
    assert "[PASS] config-valid" in out
    assert "[PASS] simulator-compiles" in out


def test_preroll_live_checks_with_fake_kubectl():
    cfg = default_config()

    def healthy_env(policy):
        # One fake kubectl serving all three live gates: NodePool
        # disruption reads, leftover-burst listing, aws-auth mapRoles.
        def runner(argv):
            joined = " ".join(argv)
            if "get deploy" in joined:
                return 0, ""
            if "configmap aws-auth" in joined:
                return 0, "- rolearn: arn:aws:iam::1:role/KarpenterNodeRole-demo1"
            return 0, policy
        return runner

    assert run_preroll(cfg, live=True, runner=healthy_env("WhenEmpty"),
                       echo=False) == 0

    # demo_18:42-55 — non-neutral pools must fail the gate
    assert run_preroll(cfg, live=True,
                       runner=healthy_env("WhenEmptyOrUnderutilized"),
                       echo=False) == 1

    def missing_runner(argv):
        return 1, "Error from server (NotFound)"

    assert run_preroll(cfg, live=True, runner=missing_runner, echo=False) == 1


def test_preroll_port_checks():
    """demo_18:58-65 analog: a squatted dashboard port fails the gate with
    the stale-port-forward hint; free ports pass."""
    import socket

    from ccka_tpu.harness.preroll import _local_ports, check_ports_free

    cfg = default_config()
    # Ports derive from the signals URLs + Grafana: 3000/8005/9090 for the
    # default config — exactly the reference's list.
    assert _local_ports(cfg) == [3000, 8005, 9090]

    # Grab an ephemeral port, hold it, and assert the check flags it.
    holder = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    holder.bind(("127.0.0.1", 0))
    port = holder.getsockname()[1]
    holder.listen(1)
    try:
        checks = check_ports_free(cfg, ports=[port])
        assert len(checks) == 1 and not checks[0].ok
        assert "port-forward" in checks[0].hint
    finally:
        holder.close()
    free = check_ports_free(cfg, ports=[port])
    assert free[0].ok


class TestWatchSession:
    """demo_40_watch_observe analog: tunnel plan from config, injectable
    spawner/fetch, socket wait, smoke queries."""

    def test_plan_derives_from_config(self):
        from ccka_tpu.harness.watch import watch_plan

        plan = watch_plan(default_config())
        by_name = {fw.name: fw for fw in plan}
        assert set(by_name) == {"grafana", "prometheus", "opencost"}
        assert by_name["grafana"].local_port == 3000
        assert by_name["prometheus"].local_port == 8005   # from signals URL
        assert by_name["opencost"].local_port == 9090
        argv = by_name["grafana"].argv()
        assert argv[:2] == ["kubectl", "port-forward"]
        assert "svc/ccka-grafana" in argv

    def test_session_spawns_waits_and_smokes(self):
        import json as _json
        import socket as _socket

        from ccka_tpu.harness.watch import WatchSession

        # Route the derived tunnels to ephemeral free ports so the test
        # never depends on 3000/8005/9090 being free on the CI host
        # (grafana's 3000 is fixed; probe it and skip its assertion if a
        # real service owns it).
        def free_port():
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
            s.close()
            return p

        p1, p2 = free_port(), free_port()
        cfg = default_config().with_overrides(**{
            "signals.prometheus_url":
                f"http://localhost:{p1}/workspaces/local",
            "signals.opencost_url": f"http://localhost:{p2}"})
        spawned, terminated = [], []

        # Fake PF: actually listen on the planned local ports so the
        # socket wait succeeds without kubectl.
        class FakePF:
            def __init__(self, argv):
                spawned.append(argv)
                port = int(argv[-1].split(":")[0])
                self.sock = _socket.socket()
                self.sock.setsockopt(_socket.SOL_SOCKET,
                                     _socket.SO_REUSEADDR, 1)
                self.sock.bind(("127.0.0.1", port))
                self.sock.listen(1)

            def terminate(self):
                terminated.append(1)
                self.sock.close()

        def fetch(url, headers):
            if "label/__name__" in url:
                return _json.dumps({"status": "success",
                                    "data": ["up", "ccka_cost_usd_hr"]}
                                   ).encode()
            return _json.dumps({"status": "success", "data": {"result": [
                {"metric": {}, "value": [0, "1"]}]}}).encode()

        import socket as _sock2
        probe = _sock2.socket()
        try:
            probe.bind(("127.0.0.1", 3000))
            grafana_port_free = True
        except OSError:
            grafana_port_free = False
        finally:
            probe.close()

        with WatchSession(cfg, spawner=FakePF, fetch=fetch,
                          sleep=lambda _s: None,
                          socket_timeout_s=2.0) as session:
            ready = session.start()
            assert ready["prometheus"] and ready["opencost"], ready
            if grafana_port_free:
                assert ready["grafana"], ready
            smoke = session.smoke()
        assert smoke["reachable"] and smoke["has_ccka_series"]
        assert smoke["metric_names"] == 2
        expected = 3 if grafana_port_free else 2
        assert len(spawned) == expected and len(terminated) == expected

    def test_stale_port_reports_not_ready(self):
        """A listener already squatting a planned port (stale PF) must NOT
        count as a ready tunnel — the socket would answer but it's the
        wrong service (the demo_19 stale-port-forward hazard)."""
        import socket as _socket

        from ccka_tpu.harness.watch import WatchSession

        holder = _socket.socket()
        holder.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        try:
            holder.bind(("127.0.0.1", 3000))
        except OSError:
            holder.close()
            pytest.skip("port 3000 already owned on this host")
        holder.listen(1)
        spawned = []

        class NeverPF:
            def __init__(self, argv):
                spawned.append(argv)

            def terminate(self):
                pass

        try:
            session = WatchSession(default_config(), spawner=NeverPF,
                                   sleep=lambda _s: None,
                                   socket_timeout_s=0.5)
            ready = session.start()
            session.stop()
        finally:
            holder.close()
        assert ready["grafana"] is False
        # And no tunnel was spawned onto the occupied port.
        assert not any("3000:3000" in " ".join(a) for a in spawned)

    def test_dead_child_fails_readiness(self):
        """kubectl exiting immediately (e.g. bad target) must not report
        ready even if some other socket answers."""
        import socket as _socket

        from ccka_tpu.harness.watch import WatchSession

        listeners = []

        class DiesPF:
            def __init__(self, argv):
                # Something answers the port (simulating a race)...
                port = int(argv[-1].split(":")[0])
                s = _socket.socket()
                s.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", port))
                s.listen(1)
                listeners.append(s)

            def poll(self):
                return 1  # ...but the child itself is dead

            def terminate(self):
                pass

        session = WatchSession(default_config(), spawner=DiesPF,
                               sleep=lambda _s: None, socket_timeout_s=1.0)
        try:
            ready = session.start()
        finally:
            session.stop()
            for s in listeners:
                s.close()
        assert not any(ready.values())

    def test_smoke_degrades_unreachable(self):
        from ccka_tpu.harness.watch import WatchSession

        def dead_fetch(url, headers):
            raise OSError("connection refused")

        smoke = WatchSession(default_config(), fetch=dead_fetch).smoke()
        assert smoke["reachable"] is False

    def test_cli_watch_dry_run(self, capsys):
        from ccka_tpu.cli import main

        assert main(["watch"]) == 0
        captured = capsys.readouterr()
        assert "would run: kubectl port-forward" in captured.err
        import json as _json
        doc = _json.loads(captured.out)
        assert doc["plan"] == ["grafana", "prometheus", "opencost"]
        # ADVICE r3: dry-run performs NO network I/O — smoke queries
        # against the configured Prometheus URL belong to --live only.
        assert "smoke" not in doc


def test_configure_observe_pair():
    cfg = default_config()
    co = ConfigureObserve(DryRunSink())
    stage = Stage(
        name="offpeak",
        patchsets=render_nodepool_patches(offpeak_action(cfg.cluster),
                                          cfg.cluster),
        expect={
            # demo_20_offpeak_observe.sh expectations
            "spot-preferred": ("WhenEmptyOrUnderutilized",
                               ["spot", "on-demand"]),
            "on-demand-slo": ("WhenEmpty", ["on-demand"]),
        })
    assert co.run(stage)


def test_configure_observe_detects_mismatch():
    cfg = default_config()
    co = ConfigureObserve(DryRunSink())
    stage = Stage(
        name="bad-oracle",
        patchsets=render_nodepool_patches(peak_action(cfg.cluster),
                                          cfg.cluster, op="add"),
        expect={"spot-preferred": ("WhenEmptyOrUnderutilized", ["spot"])})
    assert not co.run(stage)


def test_cli_bad_set_clean_error(capsys):
    assert main(["--set", "sim.nope=1", "show-config"]) == 2
    assert "config error" in capsys.readouterr().err


def test_cli_replay_load_failure_clean_error(capsys):
    rc = main(["--set", "signals.backend=replay",
               "--set", "signals.replay_path=/tmp/definitely-missing.npz",
               "simulate", "--days", "0.01"])
    assert rc == 2
    assert "config error" in capsys.readouterr().err
