"""Traced scenario-parameter axis + adversarial search (ISSUE 19).

Contract map:

- **Config round trip**: `ScenarioParams.from_config` /
  `to_config` invert each other EXACTLY — a searched cell written back
  to config sections is the same point, and a config read into the axis
  scores as itself.
- **S=1 bitwise parity**: the traced axis at S=1 produces the SAME
  packed stream as the config-baked generation path (same key, same
  geometry) — and the kernel summaries on top are bitwise, for all four
  packed modes, through the streaming pipeline, and through the 8-shard
  mesh trace. Cross-width S>1 programs differ at ulp (XLA fusion
  order), so the N-cell cross-check is allclose, never bitwise.
- **Box discipline**: unknown knob names and inverted/out-of-box ranges
  are rejected up front; `clip_to_bounds` is idempotent (int-kind knobs
  round first).
- **CEM determinism**: same seed, same scorer → identical proposals,
  identical minted cell (digest and objective value).
- **Mint provenance**: a minted scenario replays to EXACTLY its
  recorded objective; a tampered params_json is refused; one-sided
  provenance is refused; minted names cannot shadow the hand-named
  library.
- **bench-diff gates**: a doctored/partial `--search-only` record exits
  1; the repo's real history stays clean.
"""

from __future__ import annotations

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import (FAULT_PRESETS, FaultsConfig, GeoConfig,
                             WorkloadsConfig, default_config)
from ccka_tpu.search.params import (PARAM_NAMES, SEARCH_BOUNDS,
                                    ScenarioParams, params_digest,
                                    validate_bounds)
from ccka_tpu.signals.synthetic import SyntheticSignalSource
from ccka_tpu.sim import SimParams

# One shared CI geometry (matches the streaming suite's sizing).
INNER, T, BLOCK_T, T_CHUNK, B_BLOCK = 8, 64, 32, 16, 8
KERNEL_KW = dict(T=T, b_block=B_BLOCK, t_chunk=T_CHUNK, interpret=True,
                 stochastic=False)


@pytest.fixture(scope="module")
def cfg():
    return default_config()


def _active_cell() -> ScenarioParams:
    """One S=1 cell with EVERY searchable mechanism live (nonzero storm
    hazard AND price coupling AND ICE AND delay AND outage AND both
    workload windows AND the geo storm) — parity pinned here covers all
    the traced twins' branches at once."""
    rng = np.random.default_rng(9)
    lo = np.asarray([SEARCH_BOUNDS[n][0] for n in PARAM_NAMES])
    hi = np.asarray([SEARCH_BOUNDS[n][1] for n in PARAM_NAMES])
    # Uniform inside the middle of the box: strictly > lo everywhere.
    nat = lo + (0.2 + 0.6 * rng.uniform(size=(1, len(PARAM_NAMES)))) \
        * (hi - lo)
    return ScenarioParams.from_array(nat).clip_to_bounds()


@pytest.fixture(scope="module")
def cell():
    return _active_cell()


@pytest.fixture(scope="module")
def sources(cfg, cell):
    """(baked source, axis source, fa, wl, geo): the SAME cell through
    the config-baked constructor and the traced axis."""
    from ccka_tpu.search.axis import ScenarioAxisSource

    fa, wl, geo = cell.to_config(0)
    baked = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                  cfg.signals, faults=fa, workloads=wl,
                                  extra_lanes={"regions": geo})
    axis = ScenarioAxisSource(cfg.cluster, cfg.workload, cfg.sim,
                              cfg.signals, cell, faults=fa, workloads=wl,
                              geo=geo)
    return baked, axis, fa, wl, geo


@pytest.fixture(scope="module")
def net_params(cfg):
    from ccka_tpu.models import ActorCritic, latent_dim
    from ccka_tpu.sim.megakernel import _obs_dim

    net = ActorCritic(act_dim=latent_dim(cfg.cluster))
    return net.init(jax.random.key(5), jnp.zeros(
        (_obs_dim(cfg.cluster.n_pools, cfg.cluster.n_zones),)))


def _bitwise_fields(a, b):
    return {f for f in a._fields
            if not np.array_equal(np.asarray(getattr(a, f)),
                                  np.asarray(getattr(b, f)))}


class TestParamsCodec:
    def test_from_config_to_config_round_trip_exact(self):
        """Config sections → params → config sections is the identity
        (EXACT, not approximate): dataclass equality on all three."""
        fa = FAULT_PRESETS["severe"]
        wl = WorkloadsConfig(enabled=True, inference_rate_pods=6.0,
                             inference_flash_frac=0.06,
                             inference_flash_mult=8.0,
                             batch_rate_pods=5.0)
        geo = GeoConfig(enabled=True)
        p = ScenarioParams.from_config(faults=fa, workloads=wl, geo=geo)
        fa2, wl2, geo2 = p.to_config(0, base_faults=fa,
                                     base_workloads=wl, base_geo=geo)
        assert fa2 == fa and wl2 == wl and geo2 == geo

    def test_to_config_from_config_closes_the_loop(self, cell):
        """Params → config → params lands on the SAME values (int-kind
        knobs were already integral after clip)."""
        fa, wl, geo = cell.to_config(0)
        back = ScenarioParams.from_config(faults=fa, workloads=wl,
                                          geo=geo)
        assert np.array_equal(cell.to_array(), back.to_array())

    def test_json_digest_canonical(self, cell):
        p2 = ScenarioParams.from_json(cell.to_json())
        assert p2.to_json() == cell.to_json()
        assert p2.digest() == cell.digest()
        assert cell.digest() == params_digest(cell.to_json())

    def test_stack_and_row_invert(self, cell):
        other = cell.clip_to_bounds({"inf_rate": (0.0, 1.0)})
        batch = ScenarioParams.stack([cell, other])
        assert batch.S == 2
        assert np.array_equal(batch.row(1).to_array(), other.to_array())

    def test_clip_is_idempotent_and_rounds_ints(self):
        lo = np.asarray([SEARCH_BOUNDS[n][0] for n in PARAM_NAMES])
        hi = np.asarray([SEARCH_BOUNDS[n][1] for n in PARAM_NAMES])
        rng = np.random.default_rng(3)
        # Deliberately OUTSIDE the box on both sides, fractional ints.
        nat = lo - 5.0 + rng.uniform(size=(4, len(PARAM_NAMES))) \
            * (hi - lo + 10.0)
        once = ScenarioParams.from_array(nat).clip_to_bounds()
        twice = once.clip_to_bounds()
        assert np.array_equal(once.to_array(), twice.to_array())
        for name in ("storm_mean_ticks", "ice_mean_ticks",
                     "inf_flash_mean_ticks", "geo_storm_mean_ticks"):
            v = once.values[name]
            assert np.array_equal(v, np.round(v)), name

    def test_unknown_and_inverted_bounds_rejected(self):
        with pytest.raises(ValueError, match="unknown scenario params"):
            validate_bounds({"bogus": (0.0, 1.0)})
        with pytest.raises(ValueError, match="bounds"):
            validate_bounds({"inf_rate": (2.0, 1.0)})
        with pytest.raises(ValueError, match="bounds"):
            validate_bounds({"inf_rate": (0.0, 1e9)})  # above the box


class TestAxisParity:
    @pytest.mark.slow  # ISSUE 14 lane-time rule (~24s): full raw-stream
    # compare; the fast-lane S=1 bitwise pin is the rule-mode kernel
    # summary below, which rides the same generation path.
    def test_s1_stream_bitwise_vs_baked(self, sources):
        """THE tentpole pin: the traced axis at S=1 IS the config-baked
        generation path, bitwise, with every searchable mechanism live
        (storm+coupling+ICE+delay+outage+flash+burst+geo storm)."""
        baked, axis, *_ = sources
        key = jax.random.key(11)
        bs = baked.packed_trace_device(T, key, INNER, t_chunk=T_CHUNK)
        xs = axis.packed_trace_device(T, key, INNER, t_chunk=T_CHUNK)
        assert np.array_equal(np.asarray(bs), np.asarray(xs))

    @pytest.mark.slow  # ISSUE 14 lane-time rule (~23s): the block-keyed
    # variant of the fast-lane plain-stream pin — same fold chain, so a
    # drift would also break the slow streaming-pipeline gate.
    def test_s1_blocked_stream_bitwise_vs_baked(self, sources):
        """Block-keyed generation (the streaming pipeline's path) stays
        bitwise through the axis too — same BLOCK_KEY_TAG fold chain."""
        baked, axis, *_ = sources
        key = jax.random.key(12)
        for j in range(T // BLOCK_T):
            bs = baked.packed_block_trace_device(
                BLOCK_T, key, INNER, j, t_chunk=T_CHUNK)
            xs = axis.packed_block_trace_device(
                BLOCK_T, key, INNER, j, t_chunk=T_CHUNK)
            assert np.array_equal(np.asarray(bs), np.asarray(xs)), j

    @pytest.mark.parametrize("mode", [
        "rule",
        # ISSUE 16 lane-time rule: the four modes ride the same stream;
        # one fast-lane mode pins the contract, the rest ride slow.
        pytest.param("carbon", marks=pytest.mark.slow),
        pytest.param("neural", marks=pytest.mark.slow),
        pytest.param("plan", marks=pytest.mark.slow)])
    def test_s1_kernel_summary_bitwise_per_mode(self, cfg, sources,
                                                net_params, mode):
        from ccka_tpu.sim.megakernel import packed_mode_summary_fn

        baked, axis, fa, wl, geo = sources
        params = SimParams.from_config(
            dataclasses.replace(cfg, faults=fa, workloads=wl, geo=geo))
        key = jax.random.key(13)
        bs = baked.packed_trace_device(T, key, INNER, t_chunk=T_CHUNK)
        xs = axis.packed_trace_device(T, key, INNER, t_chunk=T_CHUNK)
        fn = packed_mode_summary_fn(
            params, cfg.cluster, mode,
            net_params=net_params if mode == "neural" else None,
            **KERNEL_KW)
        assert not _bitwise_fields(fn(bs, 7), fn(xs, 7)), mode

    @pytest.mark.slow  # ISSUE 14 lane-time rule (~29s): double-buffered
    # drive over the axis source — heavy variant of the fast-lane pin.
    def test_s1_streaming_pipeline_bitwise(self, cfg, sources):
        """The double-buffered streaming drive consumes the axis source
        through the SAME generic interface — summaries bitwise vs the
        baked source's drive."""
        from ccka_tpu.sim import streaming as streaming_mod

        baked, axis, fa, wl, geo = sources
        params = SimParams.from_config(
            dataclasses.replace(cfg, faults=fa, workloads=wl, geo=geo))
        key = jax.random.key(14)
        kw = dict(T=T, block_T=BLOCK_T, t_chunk=T_CHUNK,
                  b_block=B_BLOCK, interpret=True, stochastic=False)
        s_baked, _ = streaming_mod.streaming_rollout_summary(
            baked, params, cfg.cluster, "rule", key=key, batch=INNER,
            seed=7, pipelined=True, **kw)
        s_axis, _ = streaming_mod.streaming_rollout_summary(
            axis, params, cfg.cluster, "rule", key=key, batch=INNER,
            seed=7, pipelined=True, **kw)
        assert not _bitwise_fields(s_baked, s_axis)

    @pytest.mark.slow  # 8-device mesh compile — slow-lane per the rule.
    def test_s1_8shard_trace_bitwise(self, sources):
        from ccka_tpu.parallel import make_mesh, sharded_packed_trace

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        baked, axis, *_ = sources
        mesh = make_mesh()
        key = jax.random.key(15)
        bs = sharded_packed_trace(mesh, baked, T, key, 16,
                                  t_chunk=T_CHUNK)
        xs = sharded_packed_trace(mesh, axis, T, key, 16,
                                  t_chunk=T_CHUNK)
        assert np.array_equal(np.asarray(bs), np.asarray(xs))

    @pytest.mark.slow  # extra S=3 program compile — slow-lane.
    def test_ncell_batch_allclose_vs_per_cell(self, cfg, cell):
        """S=3 one-dispatch values match three S=1 dispatches to ulp
        tolerance (cross-width programs are NOT bitwise — XLA fusion
        order differs between widths; that caveat is the documented
        contract, and this test pins the allclose side of it)."""
        from ccka_tpu.search.adversarial import ScenarioScorer

        scorer = ScenarioScorer(cfg, policy="rule", steps=T,
                                inner_batch=INNER, t_chunk=T_CHUNK,
                                seed=3)
        a = cell
        b = cell.clip_to_bounds({"inf_rate": (0.0, 2.0)})
        c = cell.clip_to_bounds({"storm_hazard": (0.0, 0.5)})
        batch = ScenarioParams.stack([a, b, c])
        vals3 = scorer.score(batch)["usd_per_slo_hour"]
        vals1 = [float(scorer.score(p)["usd_per_slo_hour"][0])
                 for p in (a, b, c)]
        np.testing.assert_allclose(np.asarray(vals3), vals1,
                                   rtol=1e-4, atol=1e-6)

    def test_batch_not_multiple_of_s_rejected(self, sources):
        _, axis, *_ = sources
        two = ScenarioParams.stack([_active_cell(), _active_cell()])
        axis.set_params(two)
        try:
            with pytest.raises(ValueError, match="divisible"):
                axis.packed_trace_device(T, jax.random.key(0), INNER + 1,
                                         t_chunk=T_CHUNK)
        finally:
            axis.set_params(_active_cell())

    def test_set_params_rejects_non_params(self, sources):
        _, axis, *_ = sources
        with pytest.raises(TypeError, match="ScenarioParams"):
            axis.set_params({"inf_rate": np.zeros(1)})


class TestAdversarialSearch:
    @pytest.fixture(scope="class")
    def scorer(self, cfg):
        from ccka_tpu.search.adversarial import ScenarioScorer

        return ScenarioScorer(cfg, policy="rule", steps=T,
                              inner_batch=4, t_chunk=T_CHUNK, seed=5)

    def test_rejections_happen_before_any_compile(self, cfg):
        """Unknown policy/objective/intensity/bounds and degenerate
        CEM sizes all raise BEFORE a scorer (and its compile) exists —
        scorer=None never gets touched."""
        from ccka_tpu.search.adversarial import (intensity_bounds,
                                                 search_scenarios)

        with pytest.raises(ValueError, match="policy"):
            search_scenarios(cfg, policy="flagship")
        with pytest.raises(ValueError, match="objective"):
            search_scenarios(cfg, objective="profit")
        with pytest.raises(ValueError, match="intensity"):
            intensity_bounds("apocalyptic")
        with pytest.raises(ValueError, match="unknown scenario params"):
            search_scenarios(cfg, bounds={"bogus": (0, 1)})
        with pytest.raises(ValueError, match="iters"):
            search_scenarios(cfg, iters=0)

    @pytest.mark.slow  # two CEM runs through compiled scoring — ~30s.
    def test_cem_deterministic_under_fixed_seed(self, cfg, scorer):
        from ccka_tpu.search.adversarial import search_scenarios

        kw = dict(policy="rule", iters=2, pop=4, seed=23,
                  intensity="moderate", scorer=scorer)
        r1 = search_scenarios(cfg, **kw)
        r2 = search_scenarios(cfg, **kw)
        assert r1.scenario.params_digest == r2.scenario.params_digest
        assert r1.best_value == r2.best_value
        assert r1.history == r2.history

    @pytest.mark.slow  # replay builds its own scorer (fresh compile).
    def test_minted_replay_reproduces_recorded_objective(self, cfg,
                                                         scorer):
        """The reproducibility contract: the minted document alone is
        enough to recompute the EXACT recorded objective (S=1 re-score
        through the recorded eval geometry)."""
        from ccka_tpu.search.adversarial import (replay_minted,
                                                 search_scenarios)

        res = search_scenarios(cfg, policy="rule", iters=1, pop=4,
                               seed=29, intensity="moderate",
                               scorer=scorer)
        doc = json.loads(json.dumps(res.to_doc()))   # disk round trip
        cells = replay_minted(cfg, doc)
        assert cells[res.objective] == res.best_value
        assert cells == res.best_cells

    @pytest.mark.slow  # rides the class scorer's compiled programs.
    def test_minted_scenario_validates_and_lists(self, cfg, scorer,
                                                 tmp_path):
        from ccka_tpu.search.adversarial import search_scenarios
        from ccka_tpu.workloads.scenarios import load_minted_scenarios

        res = search_scenarios(cfg, policy="rule", iters=1, pop=4,
                               seed=31, intensity="mild", scorer=scorer)
        assert res.scenario.minted
        out = tmp_path / "mint.json"
        out.write_text(json.dumps(res.to_doc()))
        loaded = load_minted_scenarios(str(out))
        assert set(loaded) == {res.scenario.name}
        assert loaded[res.scenario.name].params_digest \
            == res.scenario.params_digest


class TestMintProvenance:
    def _doc(self) -> dict:
        p = _active_cell()
        fa, wl, geo = p.to_config(0)
        from ccka_tpu.workloads.scenarios import Scenario

        sc = Scenario(name="minted-test-cell", description="t",
                      workloads=wl, faults=fa, geo=geo,
                      params_json=p.to_json(),
                      params_digest=p.digest(), minted_by="test")
        sc.validate()
        return sc.to_doc()

    def test_tampered_params_refused(self):
        from ccka_tpu.workloads.scenarios import scenario_from_doc

        doc = self._doc()
        tampered = json.loads(doc["params_json"])
        tampered["inf_rate"] = [0.0]
        doc["params_json"] = json.dumps(tampered, sort_keys=True,
                                        separators=(",", ":"))
        with pytest.raises(ValueError, match="tampered"):
            scenario_from_doc(doc)

    def test_one_sided_provenance_refused(self):
        from ccka_tpu.workloads.scenarios import scenario_from_doc

        doc = self._doc()
        doc["params_digest"] = ""
        with pytest.raises(ValueError, match="BOTH"):
            scenario_from_doc(doc)

    def test_minted_name_cannot_shadow_library(self, tmp_path):
        from ccka_tpu.workloads.scenarios import load_minted_scenarios

        doc = self._doc()
        doc["name"] = "mixed"                  # a hand-named entry
        (tmp_path / "m.json").write_text(json.dumps({"scenario": doc}))
        with pytest.raises(ValueError, match="collides"):
            load_minted_scenarios(str(tmp_path))


def _good_search_record() -> dict:
    """A minimal healthy `--search-only` record (the gate surface only;
    the real BENCH_r22.json carries much more)."""
    return {
        "stage": "--search-only",
        "traced": {"cells": 6, "repeats": 3, "seconds": 0.05,
                   "cells_per_sec": 360.0,
                   "recompiles_during_swaps": 0},
        "recompile_loop": {"cells": 3, "seconds": 47.0,
                           "cells_per_sec": 0.064},
        "speedup": {"ratio": 5625.0, "pass": True},
        "parity": {"s1_stream_bitwise": True, "s1_summary_bitwise": True,
                   "ncell_allclose": True, "ncell_max_abs_delta": 2e-8},
        "search": {"policy": "rule", "objective": "usd_per_slo_hour",
                   "minted": {"name": "minted-rule-ff",
                              "params_digest": "ff", "value": 0.37},
                   "hand_worst": 0.358, "dominates": True},
    }


class TestBenchDiffSearchGates:
    """The bench-diff search invariants (ISSUE 19 satellite): doctored
    or partial records exit 1, the real history stays clean."""

    def _diff_of(self, tmp_path, rec):
        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        (tmp_path / "BENCH_r95.json").write_text(json.dumps(rec))
        return bench_diff(load_bench_history(str(tmp_path)))

    def _search_regressions(self, diff):
        return [r for r in diff["regressions"]
                if r["kind"] == "search_invariant"]

    def test_good_record_is_clean(self, tmp_path):
        diff = self._diff_of(tmp_path, _good_search_record())
        assert diff["ok"], diff["regressions"]

    def test_speedup_below_floor_regresses_and_cli_exits_one(
            self, tmp_path, capsys):
        rec = _good_search_record()
        rec["speedup"]["ratio"] = 9.0
        diff = self._diff_of(tmp_path, rec)
        assert any(r.get("threshold") == 10.0 and r.get("value") == 9.0
                   for r in self._search_regressions(diff))
        from ccka_tpu.cli import main

        assert main(["bench-diff", "--root", str(tmp_path)]) == 1
        assert "REGRESSION" in capsys.readouterr().err

    def test_swap_window_recompile_regresses(self, tmp_path):
        rec = _good_search_record()
        rec["traced"]["recompiles_during_swaps"] = 2
        diff = self._diff_of(tmp_path, rec)
        assert any("recompiled" in r["detail"]
                   for r in self._search_regressions(diff))

    def test_false_or_missing_bitwise_flags_regress(self, tmp_path):
        for key in ("s1_stream_bitwise", "s1_summary_bitwise",
                    "ncell_allclose"):
            rec = _good_search_record()
            rec["parity"][key] = False
            diff = self._diff_of(tmp_path, rec)
            assert not diff["ok"], key
            rec = _good_search_record()
            del rec["parity"][key]
            diff = self._diff_of(tmp_path, rec)
            assert any("partial" in r["detail"]
                       for r in self._search_regressions(diff)), key

    def test_doctored_dominance_flag_regresses(self, tmp_path):
        """A record whose flag claims dominance while its own numbers
        say otherwise is doctored — both the contradiction and the
        dominance gate fire."""
        rec = _good_search_record()
        rec["search"]["minted"]["value"] = 0.30     # below hand_worst
        diff = self._diff_of(tmp_path, rec)
        bad = self._search_regressions(diff)
        assert any("contradicts" in r["detail"] for r in bad)
        assert any("strictly" in r["detail"] for r in bad)

    def test_partial_record_regresses(self, tmp_path):
        for key in ("speedup", "traced", "search"):
            rec = _good_search_record()
            del rec[key]
            diff = self._diff_of(tmp_path, rec)
            assert any("partial" in r["detail"]
                       for r in self._search_regressions(diff)), key

    def test_real_history_is_clean_and_round22_extracted(self):
        import os

        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        history = load_bench_history(root)
        rows = [r for r in history["records"]
                if r.get("search_speedup") is not None]
        assert rows, "BENCH_r22.json lost its search columns"
        assert rows[-1]["search_speedup"] >= 10.0
        assert rows[-1]["search_recompiles"] == 0
        assert rows[-1]["search_s1_stream"] is True
        assert rows[-1]["search_dominates"] is True
        diff = bench_diff(history)
        assert diff["ok"], diff["regressions"]


class TestRunlogEvents:
    def test_search_events_registered(self):
        from ccka_tpu.obs.runlog import RUNLOG_EVENTS

        assert {"search_iter", "search_mint"} <= RUNLOG_EVENTS
