"""Real-kubectl integration lane (VERDICT r3 missing #2).

Every other test drives the live path through injected fake runners; this
module is the first execution of ACTUAL kubectl in the repo's history: the
full bootstrap → preroll → offpeak → verify → burst → cleanup cycle —
the reference's operational loop (`README.md:52-57`) — against a real
Kubernetes API server (kind/k3d/minikube) with the real
``_subprocess_runner``.

Opt-in + auto-skip: the lane runs only when BOTH hold —

- ``CCKA_TEST_CLUSTER=1`` is set (never touch a developer's current
  kube-context uninvited), and
- ``kubectl get --raw /readyz`` answers ok within 5s.

Run it locally:

    kind create cluster --name ccka-it
    CCKA_TEST_CLUSTER=1 python -m pytest tests/test_kubectl_integration.py -v
    kind delete cluster --name ccka-it

The lane installs schema-light Karpenter CRDs (NodePool / NodeClaim /
EC2NodeClass with ``x-kubernetes-preserve-unknown-fields``) so the API
server accepts the same `kubectl patch nodepool` verbs the reference
issues (`demo_20_offpeak_configure.sh:59-96`) without a Karpenter
controller — the lane verifies OUR wire formats against a REAL apiserver,
not Karpenter's reconciliation.
"""

from __future__ import annotations

import json
import os
import subprocess

import pytest

from ccka_tpu.config import default_config

pytestmark = pytest.mark.live_cluster


def _cluster_ready() -> tuple[bool, str]:
    if os.environ.get("CCKA_TEST_CLUSTER", "") != "1":
        return False, "set CCKA_TEST_CLUSTER=1 to opt in"
    try:
        proc = subprocess.run(["kubectl", "get", "--raw", "/readyz"],
                              capture_output=True, text=True, timeout=5)
    except (OSError, subprocess.TimeoutExpired) as e:
        return False, f"kubectl unreachable: {e}"
    if proc.returncode != 0:
        return False, f"apiserver not ready: {proc.stderr.strip()[:120]}"
    return True, ""


_READY, _WHY = _cluster_ready()
if not _READY:
    pytest.skip(f"real-cluster lane skipped: {_WHY}",
                allow_module_level=True)


def _crd(plural: str, group: str, kind: str, *,
         scope: str = "Cluster") -> dict:
    """Schema-light CRD: accepts any spec (preserve-unknown-fields), which
    is all the patch/read-back wire-format lane needs."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{group}"},
        "spec": {
            "group": group,
            "names": {"plural": plural, "singular": kind.lower(),
                      "kind": kind},
            "scope": scope,
            "versions": [{
                "name": "v1",
                "served": True,
                "storage": True,
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "x-kubernetes-preserve-unknown-fields": True}},
            }],
        },
    }


@pytest.fixture(scope="module")
def cfg():
    return default_config()


@pytest.fixture(scope="module")
def sink():
    """KubectlSink over the REAL subprocess runner — the live path."""
    from ccka_tpu.actuation.sink import KubectlSink

    return KubectlSink()


@pytest.fixture(scope="module", autouse=True)
def karpenter_crds(sink):
    """Install the CRDs the wire formats target; remove them after."""
    crds = [
        _crd("nodepools", "karpenter.sh", "NodePool"),
        _crd("nodeclaims", "karpenter.sh", "NodeClaim"),
        _crd("ec2nodeclasses", "karpenter.k8s.aws", "EC2NodeClass"),
    ]
    for doc in crds:
        res = sink.apply_manifest(doc)
        assert res.ok, f"CRD install failed: {res.detail}"
    # CRD establishment is asynchronous; wait for each to be served.
    from ccka_tpu.actuation.sink import _subprocess_runner
    for doc in crds:
        name = doc["metadata"]["name"]
        rc, out = _subprocess_runner(
            ["kubectl", "wait", "--for=condition=Established",
             f"crd/{name}", "--timeout=30s"])
        assert rc == 0, f"CRD {name} never established: {out}"
    yield
    for doc in crds:
        sink.delete_object("crd", doc["metadata"]["name"])


def test_full_operational_cycle(cfg, sink):
    """bootstrap → map-nodes → preroll → offpeak → verify → burst →
    observe → cleanup, all through real kubectl."""
    from ccka_tpu.actuation.bootstrap import (bootstrap, cleanup,
                                              ensure_node_role_mapping)
    from ccka_tpu.actuation.burst import (apply_burst, burst_status,
                                          delete_burst,
                                          pending_pod_diagnostics)
    from ccka_tpu.actuation.patches import render_nodepool_patches
    from ccka_tpu.harness.preroll import run_preroll
    from ccka_tpu.policy.rule import offpeak_action

    ns = cfg.workload.namespace

    # 1. bootstrap: EC2NodeClass + both NodePools land and read back.
    results = bootstrap(cfg, sink)
    assert all(r.ok for r in results), [r.detail for r in results]
    for pool in cfg.cluster.pools:
        obj = sink.get_object("nodepool", pool.name)
        assert obj.get("kind") == "NodePool"
        assert (obj["spec"]["disruption"]["consolidationPolicy"]
                == "WhenEmpty")

    # 2. demo_15 analog: aws-auth mapping. kind has no aws-auth ConfigMap
    #    (it's an EKS object), so seed an empty one — the mapping logic
    #    then exercises the reference's append+verify ConfigMap path
    #    (`demo_15_map_karp_nodes.sh:49-85`) against the real apiserver.
    seeded = sink.apply_manifest({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": "aws-auth", "namespace": "kube-system"},
        "data": {"mapRoles": ""}})
    assert seeded.ok, seeded.detail
    mapped = ensure_node_role_mapping(cfg, sink, account_id="000000000000")
    assert mapped.ok, mapped.detail

    # 3. preroll gate passes against the real cluster.
    rc = run_preroll(cfg, live=True, echo=False)
    assert rc == 0

    # 4. off-peak profile: REAL `kubectl patch nodepool` (merge + json),
    #    REAL jsonpath read-back, then skeptical observed_state verify.
    patches = render_nodepool_patches(offpeak_action(cfg.cluster),
                                      cfg.cluster, op="replace")
    apply_results = sink.apply_all(patches)
    assert all(r.ok for r in apply_results), [
        r.detail for r in apply_results]
    spot = sink.observed_state("spot-preferred")
    assert spot["consolidationPolicy"] == "WhenEmptyOrUnderutilized"
    assert spot["capacity_types"] == ["spot", "on-demand"]
    assert spot["zones"] == list(cfg.cluster.offpeak_zones)
    od = sink.observed_state("on-demand-slo")
    assert od["consolidationPolicy"] == "WhenEmpty"
    assert od["capacity_types"] == ["on-demand"]

    # 5. burst (small: 2x1): RBAC + PDB + deployments on the real API
    #    server. Pods go Pending (no node satisfies the capacity-type
    #    nodeSelector without Karpenter) — exactly what the Pending-pod
    #    diagnostics exist to show (`demo_30_burst_observe.sh:20-28`).
    burst_results = apply_burst(cfg.workload, sink, namespace=ns,
                                count=2, replicas=1)
    assert all(r.ok for r in burst_results), [
        r.detail for r in burst_results]
    status = burst_status(sink, namespace=ns)
    assert len(status["deployments"]) == 2
    pods = sink.list_objects("pods", namespace=ns,
                             selector="group=scale-burst")
    diags = pending_pod_diagnostics(pods)
    assert isinstance(diags, list)   # diagnosable (may be empty early)

    # 6. teardown in demo_50 order; the namespace delete is async, so
    #    assert the burst subset + pools are gone.
    assert delete_burst(sink, namespace=ns)
    out = cleanup(cfg, sink, wipe_nodeclass=True, namespace=ns)
    assert all(ok for _name, ok in out), out
    for pool in cfg.cluster.pools:
        assert sink.get_object("nodepool", pool.name) == {}
    assert sink.get_object("ec2nodeclass", "default-ec2") == {}


def test_patch_fallback_path_on_real_apiserver(cfg, sink):
    """The demo_20:109-120 fallback: a NodePool whose stored shape lacks
    `.spec.template.spec` still accepts the legacy-path requirements
    patch, through real kubectl."""
    from ccka_tpu.actuation.patches import render_nodepool_patches
    from ccka_tpu.policy.rule import peak_action

    pool = cfg.cluster.pools[0].name
    legacy = {
        "apiVersion": "karpenter.sh/v1",
        "kind": "NodePool",
        "metadata": {"name": pool},
        "spec": {"disruption": {"consolidationPolicy": "WhenEmpty",
                                "consolidateAfter": "30s"},
                 "template": {"requirements": []}},
    }
    assert sink.apply_manifest(legacy).ok
    try:
        ps = next(p for p in render_nodepool_patches(
            peak_action(cfg.cluster), cfg.cluster, op="add")
            if p.pool == pool)
        res = sink.apply_nodepool(ps)
        assert res.ok
        assert res.used_fallback    # primary path read-back was empty
    finally:
        sink.delete_object("nodepool", pool)
