"""Incident-grade observability (round 14, `ccka_tpu/obs`).

The contracts pinned here:

- **bitwise non-interference**: a paired recorder-on/recorder-off
  FleetService run on a deterministic clock is bitwise identical in
  decisions (per-tenant $/SLO-hr and SLO-tick accumulators) AND patch
  streams (per-sink command lists) — observation never steers;
- **trigger attribution** (ISSUE 11 satellite): under a seeded
  ChaosSink + slow-tenant run, every breaker open, reconcile give-up
  and deadline overshoot produces EXACTLY ONE incident record, each
  with a recorder dump whose checksum verifies;
- **burn-rate engine**: fast/slow window arithmetic and the two-window
  AND that stops flapping;
- **recorder integrity**: dumps reuse the snapshot codec — a corrupt
  capture is refused at load, never half-trusted;
- **bench-history sentinel**: `ccka bench-diff` exits non-zero on an
  injected synthetic regression and zero on the repo's real history.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from ccka_tpu.config import (OBS_PRESETS, SERVICE_PRESETS, ConfigError,
                             ObsConfig, ServiceConfig, default_config)
from ccka_tpu.harness.service import (VirtualClock,
                                      fleet_service_from_config)
from ccka_tpu.obs.burnrate import BurnRate, BurnRateEngine
from ccka_tpu.obs.incidents import (TRIGGERS, IncidentLog,
                                    attach_dump_entries, build_timeline,
                                    read_incidents)
from ccka_tpu.obs.recorder import FLEET_KEY, FlightRecorder, verify_dump
from ccka_tpu.policy import RulePolicy

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cfg():
    return default_config().with_overrides(**{"sim.horizon_steps": 16})


@pytest.fixture(scope="module")
def rule(cfg):
    # ONE backend instance module-wide: the service-tick compile cache
    # keys on it (the test_service idiom).
    return RulePolicy(cfg.cluster)


def det_clock() -> VirtualClock:
    """Deterministic base clock: +0.1 virtual ms per read, fresh per
    run — paired runs see identical clock sequences, so decisions
    cannot be steered by host timing noise."""
    state = {"s": 0.0}

    def base():
        state["s"] += 1e-4
        return state["s"]
    return VirtualClock(base=base)


def _obs(tmp_path, **kw) -> ObsConfig:
    base = dict(enabled=True, dump_dir=str(tmp_path / "dumps"),
                incident_log_path=str(tmp_path / "incidents.jsonl"))
    base.update(kw)
    return ObsConfig(**base)


class TestBurnRate:
    def test_window_rates_and_two_window_and(self):
        br = BurnRate(fast_ticks=2, slow_ticks=8, threshold=0.5)
        assert br.fast_rate == 0.0 and not br.burning
        for _ in range(2):
            br.update(1.0, 1.0)        # two fully-bad ticks
        assert br.fast_rate == 1.0
        # Slow window still diluted by nothing-yet: 2 bad of 2 seen.
        assert br.slow_rate == 1.0 and br.burning
        for _ in range(6):
            br.update(0.0, 1.0)        # recovery
        # Fast window clean immediately; slow remembers the fire.
        assert br.fast_rate == 0.0
        assert br.slow_rate == pytest.approx(2.0 / 8.0)
        assert not br.burning          # the AND stops the flap

    def test_single_blip_never_alerts(self):
        br = BurnRate(fast_ticks=1, slow_ticks=8, threshold=0.5)
        br.update(0.0, 1.0)
        for _ in range(7):
            br.update(0.0, 1.0)
        br.update(1.0, 1.0)            # one bad tick
        assert br.fast_rate == 1.0
        assert br.slow_rate == pytest.approx(1.0 / 8.0)
        assert not br.burning

    def test_engine_series_and_any_burning(self):
        eng = BurnRateEngine(2, 4, threshold=0.5)
        eng.update("slo", 0.0, 4.0)
        assert not eng.any_burning
        for _ in range(4):
            eng.update("shed", 4.0, 4.0)
        assert eng.any_burning
        rates = eng.rates()
        assert rates["shed"]["burning"] is True
        assert rates["slo"]["burning"] is False

    def test_fast_window_must_not_exceed_slow(self):
        with pytest.raises(ValueError, match="fast window"):
            BurnRate(fast_ticks=8, slow_ticks=2)
        with pytest.raises(ConfigError, match="burn_fast_window"):
            ObsConfig(burn_fast_window=65).validate()


class TestFlightRecorder:
    def test_ring_bounded_and_dump_verifies(self, tmp_path):
        ob = ObsConfig(enabled=True, ring_size=4,
                       dump_dir=str(tmp_path))
        rec = FlightRecorder(ob)
        for t in range(10):
            rec.record(FLEET_KEY, {"t": t})
            rec.record(3, {"t": t, "lane": 1})
        assert [r["t"] for r in rec.ring(FLEET_KEY)] == [6, 7, 8, 9]
        path, sha = rec.dump(trigger="breaker_open", t=9, tenant=3,
                             incident_id=1)
        assert rec.dumps_total == 1
        body = verify_dump(path)
        assert body["trigger"] == "breaker_open"
        assert body["rings"]["3"][-1] == {"t": 9, "lane": 1}
        assert len(sha) == 64

    def test_same_tick_same_tenant_shares_one_dump(self, tmp_path):
        ob = ObsConfig(enabled=True, dump_dir=str(tmp_path))
        rec = FlightRecorder(ob)
        rec.record(2, {"t": 5})
        a = rec.dump(trigger="breaker_open", t=5, tenant=2,
                     incident_id=1)
        b = rec.dump(trigger="hold_fallback", t=5, tenant=2,
                     incident_id=2)
        c = rec.dump(trigger="breaker_open", t=6, tenant=2,
                     incident_id=3)
        assert a == b                  # shared capture, one file
        assert c != a
        assert rec.dumps_total == 2

    def test_corrupt_dump_refused(self, tmp_path):
        from ccka_tpu.harness.snapshot import SnapshotError

        ob = ObsConfig(enabled=True, dump_dir=str(tmp_path))
        rec = FlightRecorder(ob)
        rec.record(0, {"t": 1})
        path, _sha = rec.dump(trigger="shed_spike", t=1, tenant=0,
                              incident_id=1)
        doc = json.load(open(path))
        doc["body"]["t"] = 999         # hand-edit: checksum must trip
        with open(path, "w") as fh:
            json.dump(doc, fh)
        with pytest.raises(SnapshotError, match="checksum"):
            verify_dump(path)
        # And a NON-dump snapshot is refused by kind, not rendered.
        from ccka_tpu.harness.snapshot import save_snapshot
        other = str(tmp_path / "ctrl.snap")
        save_snapshot(other, {"kind": "controller", "next_tick": 3})
        with pytest.raises(SnapshotError, match="not a recorder-dump"):
            verify_dump(other)

    def test_dumpless_posture_returns_none(self):
        rec = FlightRecorder(ObsConfig(enabled=True))
        rec.record(0, {"t": 0})
        assert rec.dump(trigger="breaker_open", t=0, tenant=0) is None
        assert rec.dumps_total == 0


class TestIncidentLog:
    def test_unknown_trigger_rejected(self):
        log = IncidentLog()
        with pytest.raises(ValueError, match="unknown incident trigger"):
            log.stamp("novel_trigger", t=0)

    def test_jsonl_roundtrip_and_counts(self, tmp_path):
        path = str(tmp_path / "inc.jsonl")
        log = IncidentLog(path)
        log.stamp("breaker_open", t=3, tenant=1, state="open")
        log.stamp("shed_spike", t=4, shed=5)
        log.close()
        recs = read_incidents(path)
        assert [r["trigger"] for r in recs] == ["breaker_open",
                                               "shed_spike"]
        assert recs[0]["tenant"] == 1 and recs[1]["tenant"] is None
        assert log.counts() == {"breaker_open": 1, "shed_spike": 1}

    def test_appending_session_continues_the_id_sequence(self,
                                                         tmp_path):
        """A second session appending to an existing incident log must
        continue its ids — restarting at 1 would collide `show --id`
        lookups AND overwrite the previous session's dump files (their
        names carry the incident id) while the old records still
        reference the old checksums."""
        path = str(tmp_path / "inc.jsonl")
        dumps = ObsConfig(enabled=True, dump_dir=str(tmp_path / "d"))
        rec1 = FlightRecorder(dumps)
        rec1.record(0, {"t": 1})
        log = IncidentLog(path, recorder=rec1)
        first = log.stamp("breaker_open", t=1, tenant=0)
        log.close()
        rec2 = FlightRecorder(dumps)
        rec2.record(0, {"t": 2})
        log2 = IncidentLog(path, recorder=rec2)
        second = log2.stamp("breaker_open", t=2, tenant=0)
        log2.close()
        assert second.id == first.id + 1
        assert second.dump_path != first.dump_path
        recs = read_incidents(path)
        assert [r["id"] for r in recs] == [first.id, second.id]
        # Both sessions' dumps still verify against their records.
        for r in recs:
            assert verify_dump(r["dump_path"])["t"] == r["t"]

    def test_reopen_after_torn_tail_repairs_before_appending(
            self, tmp_path):
        """A crash mid-stamp leaves a torn final line; the next session
        must TRIM it before appending or the first new record would
        concatenate onto the partial line and corrupt the log for
        every later reader."""
        path = str(tmp_path / "inc.jsonl")
        log = IncidentLog(path)
        log.stamp("breaker_open", t=1, tenant=0)
        log.stamp("shed_spike", t=2, shed=4)
        log.close()
        raw = open(path, "rb").read()
        with open(path, "wb") as fh:       # tear the final record
            fh.write(raw[:-10])
        log2 = IncidentLog(path)
        third = log2.stamp("breaker_open", t=3, tenant=1)
        log2.close()
        recs = read_incidents(path)        # fully parseable again
        assert [r["t"] for r in recs] == [1, 3]
        # Ids still continue past the (intact) prior records.
        assert third.id == 2
        # The NEWLINE-TERMINATED malformed final line (partial write
        # whose trailing block landed): must be trimmed too, or the
        # append strands an interior malformed line the reader
        # refuses forever.
        raw2 = open(path, "rb").read()
        with open(path, "wb") as fh:
            fh.write(raw2[:-10] + b"\n")
        log3 = IncidentLog(path)
        log3.stamp("shed_spike", t=9, shed=2)
        log3.close()
        recs = read_incidents(path)
        assert [r["t"] for r in recs] == [1, 9]

    def test_corrupt_prior_log_refused_diagnosably(self, tmp_path):
        path = str(tmp_path / "inc.jsonl")
        with open(path, "w") as fh:
            fh.write('{"id": 1, "t": 0}\n')
            fh.write("GARBAGE\n")
            fh.write('{"id": 2, "t": 1}\n')
        with pytest.raises(ValueError, match="corrupt incident log"):
            IncidentLog(path)
        from ccka_tpu.cli import main
        with pytest.raises(SystemExit, match="corrupt incident log"):
            main(["fleet", "--clusters", "2", "--ticks", "1",
                  "--service", "default", "--incidents-out", path])
        # And an explicit off posture must not be silently inverted
        # by the output flag — the contradiction is rejected.
        with pytest.raises(SystemExit, match="off posture"):
            main(["fleet", "--clusters", "2", "--ticks", "1",
                  "--service", "default", "--obs", "off",
                  "--incidents-out", str(tmp_path / "x.jsonl")])

    def test_io_failure_degrades_record_not_control_loop(self,
                                                         tmp_path,
                                                         capsys):
        """The observer must never kill the loop it observes: a dump
        or append that hits an OSError is counted, the incident stays
        in-memory, and nothing raises — plus the reconciler backstops
        a hook that raises anyway."""
        class FailingRecorder:
            def dump(self, **_kw):
                raise OSError(28, "No space left on device")

        log = IncidentLog(str(tmp_path / "inc.jsonl"),
                          recorder=FailingRecorder())
        log._fh.close()                    # appends now fail too
        inc = log.stamp("breaker_open", t=1, tenant=0)
        assert inc.dump_path is None and log.total == 1
        assert log.io_errors == 2          # dump + append, no raise
        assert "incident-log" in capsys.readouterr().err
        log._fh = None
        log.close()

        # Reconciler backstop: a hook raising must not abort converge.
        from ccka_tpu.actuation.chaos import ChaosSink
        from ccka_tpu.actuation.patches import render_nodepool_patches
        from ccka_tpu.actuation.reconcile import Reconciler
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.config import ChaosConfig, default_config
        from ccka_tpu.policy.rule import offpeak_action

        cfg = default_config()
        sink = ChaosSink(DryRunSink(),
                         ChaosConfig(enabled=True, drop_prob=1.0),
                         seed=1)
        def bad_hook(_outcome):
            raise RuntimeError("broken observer")
        rec = Reconciler(sink, max_rounds=1, on_giveup=bad_hook)
        patches = render_nodepool_patches(offpeak_action(cfg.cluster),
                                          cfg.cluster, op="replace")
        outcome = rec.converge(patches)    # must NOT raise
        assert not outcome.converged
        assert rec.hook_errors == 1

    def test_timeline_joins_and_orders_sources(self):
        log = IncidentLog()
        log.stamp("breaker_open", t=5, tenant=0)
        runlog = [{"event": "iter", "t": 4, "loss": 1.0},
                  {"event": "iter", "t": 5, "loss": 2.0},
                  {"event": "note", "msg": "no tick key"}]
        spans = [{"name": "service.tick", "args": {"t": 5},
                  "dur_us": 1500.0},
                 {"name": "service.tick", "args": {"t": 99},
                  "dur_us": 1.0}]
        tl = build_timeline(log.incidents, runlog=runlog, spans=spans,
                            around=5, window=1)
        # Un-keyed rows and out-of-window ticks are dropped; within a
        # tick the incident sorts LAST (after the state explaining it).
        assert [(r["t"], r["source"]) for r in tl] == [
            (4, "runlog"), (5, "span"), (5, "runlog"), (5, "incident")]
        assert tl[1]["dur_ms"] == 1.5


class TestServiceTriggersUnderChaos:
    """The ISSUE 11 satellite: a seeded ChaosSink + slow-tenant run
    must leave a FULLY ATTRIBUTABLE incident record — one incident per
    breaker open / give-up / overshoot, each with a verifying dump."""

    @pytest.fixture(scope="class")
    def chaos_run(self, cfg, rule, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("chaos-incidents")
        obs = ObsConfig(enabled=True,
                        dump_dir=str(tmp / "dumps"),
                        incident_log_path=str(tmp / "incidents.jsonl"))
        svc = fleet_service_from_config(
            cfg, rule, 6,
            profiles=["healthy"] * 3 + ["slow", "flaky", "flaky"],
            service=SERVICE_PRESETS["default"], obs=obs,
            horizon_ticks=16, seed=3, clock=det_clock())
        svc.warmup()
        reports = svc.run(12)
        yield svc, reports, obs
        svc.close()

    def test_every_breaker_open_has_exactly_one_incident(self,
                                                         chaos_run):
        svc, _reports, _obs = chaos_run
        opens = sum(b.transitions["opened"] for b in svc.breakers)
        assert opens > 0, "stress fleet opened no breakers — vacuous"
        assert svc.incidents.counts().get("breaker_open", 0) == opens

    def test_every_giveup_has_exactly_one_incident(self, chaos_run):
        svc, _reports, _obs = chaos_run
        assert svc.actuation_giveups_total > 0, "no give-ups — vacuous"
        assert svc.incidents.counts().get("reconcile_giveup", 0) \
            == svc.actuation_giveups_total

    def test_every_incident_dump_checksum_verifies(self, chaos_run):
        svc, _reports, _obs = chaos_run
        assert svc.incidents.total > 0
        for inc in svc.incidents.incidents:
            assert inc.dump_path is not None
            body = verify_dump(inc.dump_path)
            assert body["t"] == inc.t
            # The capture's per-tenant ring covers the incident tick's
            # recent history for the RIGHT tenant.
            if inc.tenant is not None:
                ring = body["rings"][str(inc.tenant)]
                assert ring and ring[-1]["t"] == inc.t

    def test_timeline_nonempty_and_attributes_each_incident(
            self, chaos_run):
        svc, _reports, obs = chaos_run
        recs = read_incidents(obs.incident_log_path)
        spans = [s.to_record() for s in svc.tracer.spans()]
        tl = build_timeline(recs, spans=spans)
        incidents = [r for r in tl if r["source"] == "incident"]
        assert len(incidents) == svc.incidents.total
        # Every incident row sits next to span rows of its own tick.
        span_ticks = {r["t"] for r in tl if r["source"] == "span"}
        for inc in incidents:
            assert inc["t"] in span_ticks
        assert all(r["trigger"] in TRIGGERS for r in incidents)

    def test_report_gauges_reflect_the_obs_layer(self, chaos_run):
        svc, reports, _obs = chaos_run
        last = reports[-1]
        assert last.incidents_total == svc.incidents.total
        assert last.recorder_dumps_total == svc.recorder.dumps_total
        assert 0.0 <= last.slo_burn_rate <= 1.0
        # Incidents fired within the fast window of the last tick OR
        # the burn engine is burning -> the active flag is honest.
        lastinc = svc.incidents.last_tick()
        expect = int(svc.burn.any_burning
                     or (lastinc is not None
                         and last.t - lastinc < svc.obs.burn_fast_window))
        assert last.incident_active == expect

    def test_deadline_overshoot_stamps_one_incident_per_tick(
            self, cfg, rule, tmp_path):
        svc = fleet_service_from_config(
            cfg, rule, 3, profiles=["healthy"] * 3,
            service=SERVICE_PRESETS["default"], obs=_obs(tmp_path),
            horizon_ticks=16, seed=7, clock=det_clock())
        svc.warmup()
        orig = svc._tick_fn

        def slow_dispatch(*a, **kw):
            # The real overshoot cause: an un-preemptible device
            # dispatch running past the deadline, modeled as virtual
            # clock advance (deterministic, unlike a real stall).
            out = orig(*a, **kw)
            svc.clock.advance(0.3)     # 300ms > the 250ms deadline
            return out

        svc._tick_fn = slow_dispatch
        reports = svc.run(4)
        overshoots = [r for r in reports
                      if r.tick_latency_ms > svc.svc.tick_deadline_ms]
        assert len(overshoots) == 4
        assert svc.incidents.counts()["deadline_overshoot"] == 4
        for inc in svc.incidents.incidents:
            if inc.trigger == "deadline_overshoot":
                assert inc.details["latency_ms"] \
                    > inc.details["deadline_ms"]
        svc.close()

    def test_shed_spike_and_hold_fallback_stamped_once_each(
            self, cfg, rule, tmp_path):
        # Cap 3 over {2 healthy, 1 slow, 3 batch}: the back-of-order
        # batch tenants shed 3/tick >= the 50% spike bar while the
        # slow tenant still scrapes (priority 1), times out, opens its
        # breaker, and escalates hold -> rule-fallback ONCE.
        import math

        n = 6
        svc = fleet_service_from_config(
            cfg, rule, n,
            profiles=["healthy", "healthy", "slow"] + ["batch"] * 3,
            service=ServiceConfig(
                enabled=True, tick_deadline_ms=200.0,
                admission_queue_cap=3, breaker_failures=1,
                hold_fallback_after=2, breaker_probe_ticks=32),
            obs=_obs(tmp_path), horizon_ticks=16, seed=5,
            clock=det_clock())
        svc.warmup()
        reports = svc.run(8)
        counts = svc.incidents.counts()
        bar = max(1, math.ceil(svc.obs.shed_spike_frac * n))
        spike_ticks = sum(1 for r in reports if r.shed >= bar)
        assert spike_ticks > 0
        assert counts["shed_spike"] == spike_ticks
        # The slow tenant escalated hold -> rule-fallback exactly once.
        assert counts.get("hold_fallback", 0) == 1
        fallback = [i for i in svc.incidents.incidents
                    if i.trigger == "hold_fallback"]
        assert fallback[0].tenant == 2
        svc.close()


class TestNonInterference:
    """The round-13 zero-overhead-control idiom applied to the obs
    layer: recorder-on and recorder-off runs over one seeded world on
    a deterministic clock are BITWISE identical in decisions and patch
    streams."""

    def _run(self, cfg, rule, obs, tmp_path=None):
        svc = fleet_service_from_config(
            cfg, rule, 5, profiles=["healthy"] * 4 + ["slow"],
            service=SERVICE_PRESETS["default"], obs=obs,
            horizon_ticks=16, seed=11, clock=det_clock())
        svc.warmup()
        svc.run(10)
        out = {
            "usd": svc.tenant_usd_per_slo_hr().copy(),
            "slo": svc.tenant_slo_ticks.copy(),
            "fresh": svc.tenant_fresh_ticks.copy(),
            "commands": [[(c.name, c.patch_type, json.dumps(
                c.patch, sort_keys=True))
                for c in getattr(s, "inner", s).commands]
                for s in svc.sinks],
            "incidents": (svc.incidents.total
                          if svc.incidents is not None else 0),
        }
        svc.close()
        return out

    def test_recorder_on_off_bitwise_identical(self, cfg, rule,
                                               tmp_path):
        off = self._run(cfg, rule, None)
        on = self._run(cfg, rule, _obs(tmp_path))
        np.testing.assert_array_equal(off["usd"], on["usd"])
        np.testing.assert_array_equal(off["slo"], on["slo"])
        np.testing.assert_array_equal(off["fresh"], on["fresh"])
        assert off["commands"] == on["commands"]
        # Non-vacuous: the observed run genuinely stamped incidents
        # (the slow tenant opened its breaker) while changing nothing.
        assert on["incidents"] > 0

    def test_obs_off_builds_no_machinery(self, cfg, rule):
        svc = fleet_service_from_config(
            cfg, rule, 2, service=SERVICE_PRESETS["default"],
            horizon_ticks=16, seed=1)
        assert svc.recorder is None and svc.incidents is None \
            and svc.burn is None
        assert OBS_PRESETS["off"].enabled is False
        svc.close()


class TestControllerIncidents:
    """The single-cluster wiring: the degraded machine's fallback
    escalation and the reconciler's give-up hook stamp incidents."""

    def test_stale_source_fallback_stamps_hold_fallback(self, cfg):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        class StaleSource(SyntheticSignalSource):
            last_scrape_stale = True

        src = StaleSource(cfg.cluster, cfg.workload, cfg.sim,
                          cfg.signals)
        log = IncidentLog()
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src,
                          DryRunSink(), interval_s=0.0,
                          degraded_fallback_after=1, incident_log=log,
                          log_fn=lambda _l: None)
        ctrl.run(ticks=3)
        ctrl.close()
        # ONE escalation incident (the machine stays in fallback), at
        # the tick the transition happened.
        assert log.counts() == {"hold_fallback": 1}
        assert log.incidents[0].t == 0
        assert log.incidents[0].details["stale_streak"] >= 1

    def test_reconcile_giveup_stamps_via_the_hook(self, cfg):
        from ccka_tpu.actuation.chaos import ChaosSink
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.config import ChaosConfig
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        # Every write silently dropped: read-back always diverges, so
        # each tick's converge gives up (1 round -> no retry sleeps).
        sink = ChaosSink(DryRunSink(),
                         ChaosConfig(enabled=True, drop_prob=1.0),
                         seed=2)
        log = IncidentLog()
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, sink,
                          interval_s=0.0, reconcile_rounds=1,
                          incident_log=log, log_fn=lambda _l: None)
        ctrl.run(ticks=2)
        ctrl.close()
        giveups = [i for i in log.incidents
                   if i.trigger == "reconcile_giveup"]
        assert len(giveups) == 2
        assert giveups[0].t == 0 and giveups[1].t == 1
        assert giveups[0].details["region"] == cfg.cluster.region
        assert giveups[0].details["diverged"]


class TestIncidentsCLI:
    @pytest.fixture(scope="class")
    def cli_artifacts(self, cfg, rule, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("cli-incidents")
        obs = ObsConfig(enabled=True,
                        dump_dir=str(tmp / "dumps"),
                        incident_log_path=str(tmp / "incidents.jsonl"))
        svc = fleet_service_from_config(
            cfg, rule, 4, profiles=["healthy"] * 3 + ["slow"],
            service=SERVICE_PRESETS["default"], obs=obs,
            horizon_ticks=16, seed=13, clock=det_clock())
        svc.warmup()
        svc.run(8)
        spans_path = str(tmp / "spans.jsonl")
        with open(spans_path, "w") as fh:
            for s in svc.tracer.spans():
                fh.write(json.dumps(s.to_record()) + "\n")
        svc.close()
        return obs.incident_log_path, spans_path

    def test_list_show_timeline(self, cli_artifacts, capsys):
        from ccka_tpu.cli import main

        inc_path, spans_path = cli_artifacts
        assert main(["incidents", "list", inc_path]) == 0
        lines = capsys.readouterr().out.strip().splitlines()
        assert lines and all("trigger" in json.loads(l) for l in lines)

        first = json.loads(lines[0])
        assert main(["incidents", "show", inc_path,
                     "--id", str(first["id"])]) == 0
        shown = json.loads(capsys.readouterr().out)
        assert shown["dump_verified"] is True
        assert shown["dump"]["kind"] == "recorder-dump"

        assert main(["incidents", "timeline", inc_path,
                     "--trace", spans_path,
                     "--id", str(first["id"])]) == 0
        rows = [json.loads(l) for l in
                capsys.readouterr().out.strip().splitlines()]
        assert any(r["source"] == "incident" for r in rows)
        assert any(r["source"] == "span" for r in rows)

    def test_show_refuses_corrupt_dump(self, cli_artifacts, capsys):
        from ccka_tpu.cli import main

        inc_path, _spans = cli_artifacts
        recs = read_incidents(inc_path)
        doc = json.load(open(recs[0]["dump_path"]))
        doc["body"]["t"] = 777
        with open(recs[0]["dump_path"], "w") as fh:
            json.dump(doc, fh)
        with pytest.raises(SystemExit, match="failed verification"):
            main(["incidents", "show", inc_path,
                  "--id", str(recs[0]["id"])])
        # attach_dump_entries is the same refusal, library-side.
        from ccka_tpu.harness.snapshot import SnapshotError
        with pytest.raises(SnapshotError):
            attach_dump_entries(recs[0])

    def test_show_without_id_and_unknown_id(self, cli_artifacts):
        from ccka_tpu.cli import main

        inc_path, _spans = cli_artifacts
        with pytest.raises(SystemExit, match="needs --id"):
            main(["incidents", "show", inc_path])
        with pytest.raises(SystemExit, match="no incident with id"):
            main(["incidents", "show", inc_path, "--id", "9999"])


class TestBenchHistorySentinel:
    def test_real_history_loads_and_is_clean(self):
        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        history = load_bench_history(_ROOT)
        rounds = {r["round"] for r in history["records"]}
        assert {1, 13}.issubset(rounds)
        assert any(r["round"] >= 13 for r in history["lane"])
        # Partial/interrupted lane rows (e.g. round 11's 4.8s
        # passed-0 row) are excluded from the trend series, while the
        # legacy hand-seeded rows (passed=None — incl. the r5 TPU
        # lane, the repo's only TPU evidence) stay, marked unknown.
        for r in history["lane"]:
            assert r["passed_unknown"] or r["passed_max"] >= 100
        assert any(r["platform"] == "tpu" and r["passed_unknown"]
                   for r in history["lane"])
        diff = bench_diff(history)
        assert diff["ok"], diff["regressions"]
        assert diff["comparisons"]

    def test_injected_regressions_trip_each_gate(self):
        from ccka_tpu.obs.bench_history import bench_diff

        clean = {
            "records": [
                {"round": 13, "file": "BENCH_r13.json",
                 "platform": "cpu", "healthy_usd_ratio_max": 1.0},
                {"round": 14, "file": "BENCH_r14.json",
                 "platform": "cpu", "recorder_overhead_frac": 0.03,
                 "obs_bitwise_identical": True},
            ],
            "lane": [
                {"round": 13, "platform": "cpu", "best_wall_s": 630.0,
                 "runs": 2, "best_over_budget": False,
                 "passed_max": 500, "passed_unknown": False},
                {"round": 14, "platform": "cpu", "best_wall_s": 660.0,
                 "runs": 1, "best_over_budget": False,
                 "passed_max": 520, "passed_unknown": False},
            ],
        }
        assert bench_diff(clean)["ok"]

        import copy

        def mutate(fn):
            h = copy.deepcopy(clean)
            fn(h)
            d = bench_diff(h)
            assert not d["ok"]
            return d["regressions"]

        regs = mutate(lambda h: h["lane"][1].update(best_wall_s=1300.0))
        assert any(r["kind"] == "lane_wall_s" for r in regs)
        assert any(r["kind"] == "lane_over_budget" for r in regs)
        regs = mutate(lambda h: h["records"][1].update(
            recorder_overhead_frac=0.12))
        assert any(r["kind"] == "obs_invariant" for r in regs)
        regs = mutate(lambda h: h["records"][1].update(
            obs_bitwise_identical=False))
        assert any("bitwise" in r["detail"] for r in regs)
        regs = mutate(lambda h: h["records"][0].update(
            healthy_usd_ratio_max=1.31))
        assert any(r["kind"] == "overload_invariant" for r in regs)
        regs = mutate(lambda h: h["records"][1].update(
            error="unreadable: boom"))
        assert any(r["kind"] == "unreadable_record" for r in regs)

    def test_headline_gate_same_platform_only(self):
        from ccka_tpu.obs.bench_history import bench_diff

        h = {"records": [
            {"round": 4, "platform": "tpu",
             "headline_cluster_days_per_sec": 1.8e6},
            {"round": 14, "platform": "cpu",
             "headline_cluster_days_per_sec": 5.0e4},
        ], "lane": []}
        # A platform change is not a regression.
        assert bench_diff(h)["ok"]
        h["records"][1]["platform"] = "tpu"
        d = bench_diff(h)
        assert not d["ok"]
        assert d["regressions"][0]["kind"] == "headline"

    def test_cli_bench_diff_real_and_doctored(self, tmp_path, capsys):
        from ccka_tpu.cli import main

        assert main(["bench-diff", "--root", _ROOT]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["ok"] is True

        # Doctored root: a synthetic r14 record violating the obs
        # invariant must flip the exit code.
        os.makedirs(tmp_path / "data", exist_ok=True)
        with open(tmp_path / "BENCH_r14.json", "w") as fh:
            json.dump({"recorder_overhead_frac": 0.5,
                       "provenance": {"platform": "cpu"}}, fh)
        with open(tmp_path / "data" / "lane_times.json", "w") as fh:
            json.dump([], fh)
        assert main(["bench-diff", "--root", str(tmp_path)]) == 1
        out = json.loads(capsys.readouterr().out)
        assert out["regressions"][0]["kind"] == "obs_invariant"

        with pytest.raises(SystemExit, match="wrong --root"):
            main(["bench-diff", "--root", str(tmp_path / "empty")])
