"""Geo-arbitrage subsystem contract (ISSUE 16 tentpole + satellites).

The claims under test:

- **registry-only derivation**: the "regions" lane family reaches every
  engine (lax, megakernel modes, streaming, sharded) THROUGH THE
  REGISTRY ALONE — the widened stream's pre-geo rows and every engine
  summary stay bitwise identical to the un-widened stream (region lanes
  are passive; no engine consumes them in-kernel), while the lane block
  itself is bitwise the hand-threaded `packed_region_lanes` reference
  (the `test_engine_registry` discipline, now on a real family).
- **work conservation**: the migration dynamics move pending mass,
  never create or destroy it — including when the rendered migration
  command stream is thinned/rewritten by a seeded ChaosSink and parsed
  back (`apply_migration_commands` re-sanitizes).
- **zero-migration parity**: all-zero rates are a bitwise no-op vs the
  `none` policy, and the migration objective term is EXACTLY 0.0
  (`step_cost(migration_cost=None)` is bitwise the pre-geo path).
- **Pareto scoreboard**: `dominates`/`pareto_front` invariants, the
  per-class suite record shape, and the `ccka bench-diff` geo gates
  (zero-rate parity flag present AND true, fronts mutually
  non-dominated, partial records are regressions, doctored root exits
  1 through the CLI).
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import ChaosConfig, ConfigError, multi_region_config
from ccka_tpu.regions import (REGION_KEY_TAG, REGION_LANE_FIELDS,
                              packed_region_lanes, region_rows,
                              region_step_from_block, unpack_region_lanes)
from ccka_tpu.regions import geo as geo_dyn
from ccka_tpu.regions import migrate, pareto
from ccka_tpu.sim import SimParams, lanes
from ccka_tpu.signals.synthetic import SyntheticSignalSource

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The shared small geometry of test_engine_registry (interpret-mode
# kernels; one compile per mode per stream layout).
B, T, T_CHUNK, B_BLOCK = 32, 16, 8, 8


@pytest.fixture(scope="module")
def cfg():
    return multi_region_config()


@pytest.fixture(scope="module")
def geo(cfg):
    """The spot-storm scenario's geo config bound to the multiregion
    cluster topology — active lanes on a 2-region, 4-zone layout."""
    scn = pareto.GEO_SCENARIOS["spot-storm"]
    g = dataclasses.replace(
        scn.geo, zone_region_index=cfg.cluster.zone_region_index)
    g.validate()
    return g


@pytest.fixture(scope="module")
def sources(cfg, geo):
    plain = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                  cfg.signals)
    widened = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals,
                                    extra_lanes={"regions": geo})
    return plain, widened


@pytest.fixture(scope="module")
def streams(sources):
    key = jax.random.key(11)
    plain, widened = sources
    return (plain.packed_trace_device(T, key, B, t_chunk=T_CHUNK),
            widened.packed_trace_device(T, key, B, t_chunk=T_CHUNK))


@pytest.fixture(scope="module")
def step(geo, cfg):
    """One bare region-lane block as a RegionStep (packed [T, R, B]
    layout squeezed through the block unpacker)."""
    Z = cfg.cluster.n_zones
    block = packed_region_lanes(geo, jax.random.key(3), 48, 48, Z, 4,
                                dt_s=cfg.sim.dt_s)
    return region_step_from_block(block, 48, Z, geo)


def _fields_equal(a, b):
    return {f for f in a._fields
            if not np.array_equal(np.asarray(getattr(a, f)),
                                  np.asarray(getattr(b, f)))}


class TestRegionLaneFamily:
    def test_registered_as_third_builtin(self):
        names = [f.name for f in lanes.lane_families()]
        assert "regions" in names
        assert names.index("regions") > names.index("workloads")
        assert lanes.LANE_FAMILIES["regions"].key_tag == REGION_KEY_TAG
        assert lanes.LANE_FAMILIES["regions"].rows is lanes.region_rows
        assert region_rows(4) == 64

    def test_neutral_contract_default_config_is_exact_zero(self, cfg):
        from ccka_tpu.config import GeoConfig

        Z = cfg.cluster.n_zones
        block = packed_region_lanes(GeoConfig(), jax.random.key(0),
                                    8, 8, Z, 2, dt_s=30.0)
        assert block.shape == (8, region_rows(Z), 2)
        assert float(jnp.max(jnp.abs(block))) == 0.0

    def test_builtin_via_extra_lanes_rejected(self, cfg):
        with pytest.raises(ValueError, match="unknown lane family|built-in"):
            SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                  cfg.signals,
                                  extra_lanes={"no-such-family": 1.0})

    def test_widened_stream_resolves_and_block_is_bitwise_reference(
            self, cfg, geo, sources, streams):
        Z = cfg.cluster.n_zones
        plain_s, wide_s = streams
        assert wide_s.shape[1] == plain_s.shape[1] + region_rows(Z)
        lay = lanes.resolve_layout(wide_s.shape[1], Z)
        assert lay.families == ("regions",)
        assert lay.has("regions")
        # Passive lanes: the two-tuple (faults?, workloads?) layout the
        # engines branch on is unchanged — zero per-engine edits.
        assert lanes.stream_layout(wide_s.shape[1], Z) \
            == lanes.stream_layout(plain_s.shape[1], Z)
        lo, hi = lay.block("regions")
        assert np.array_equal(np.asarray(plain_s),
                              np.asarray(wide_s[:, :lo]))
        # The lane block is bitwise the hand-threaded reference. The
        # reference must run under jit: the source synthesizes under
        # jit and XLA's fused float ops differ from eager at ulp level.
        ref = jax.jit(lambda k: packed_region_lanes(
            geo, k, T, wide_s.shape[0], Z, B,
            dt_s=cfg.sim.dt_s))(jax.random.key(11))
        assert np.array_equal(np.asarray(wide_s[:, lo:hi]),
                              np.asarray(ref))
        _, widened = sources
        assert widened.packed_rows() == wide_s.shape[1]

    def test_unpack_roundtrips_the_widened_stream(self, cfg, geo,
                                                  streams):
        Z = cfg.cluster.n_zones
        _, wide_s = streams
        lay = lanes.resolve_layout(wide_s.shape[1], Z)
        lo, hi = lay.block("regions")
        a = unpack_region_lanes(wide_s, T, Z, geo)
        b = region_step_from_block(wide_s[:, lo:hi], T, Z, geo)
        assert not _fields_equal(a, b)
        assert a._fields == REGION_LANE_FIELDS

    @pytest.mark.slow  # lane-time rule: bench --geo-only pins lax
    # parity per record; tier-1 keeps the rule-kernel representative.
    def test_lax_engine_consumes_it_bitwise(self, cfg, streams):
        from ccka_tpu.sim.rollout import lax_mode_summary

        params = SimParams.from_config(cfg)
        plain_s, wide_s = streams
        key = jax.random.key(7)
        a = lax_mode_summary(params, cfg.cluster, "rule", plain_s, T, key)
        b = lax_mode_summary(params, cfg.cluster, "rule", wide_s, T, key)
        assert not _fields_equal(a, b)

    @pytest.mark.parametrize("mode", (
        "rule",
        pytest.param("carbon", marks=pytest.mark.slow),
        pytest.param("neural", marks=pytest.mark.slow),
        pytest.param("plan", marks=pytest.mark.slow),
    ))
    def test_kernel_modes_consume_it_bitwise(self, cfg, streams, mode):
        # ISSUE 16 lane-time rule: one mode pins the fast-lane claim;
        # the other three duplicate the same registry path and ride
        # the slow lane.
        from ccka_tpu.sim.megakernel import packed_mode_summary_fn

        net_params = None
        if mode == "neural":
            from ccka_tpu.models import ActorCritic, latent_dim
            from ccka_tpu.sim.megakernel import _obs_dim

            net = ActorCritic(act_dim=latent_dim(cfg.cluster))
            net_params = net.init(jax.random.key(5), jnp.zeros(
                (_obs_dim(cfg.cluster.n_pools, cfg.cluster.n_zones),)))
        params = SimParams.from_config(cfg)
        plain_s, wide_s = streams
        kfn = packed_mode_summary_fn(params, cfg.cluster, mode, T=T,
                                     b_block=B_BLOCK, t_chunk=T_CHUNK,
                                     interpret=True, stochastic=False,
                                     net_params=net_params)
        assert not _fields_equal(kfn(plain_s, 3), kfn(wide_s, 3)), mode

    @pytest.mark.slow  # ISSUE 16 lane-time rule: duplicates the
    # registry path test_engine_registry already pins per-block.
    def test_streaming_pipeline_consumes_it_bitwise(self, cfg, sources):
        from ccka_tpu.sim import streaming as streaming_mod

        params = SimParams.from_config(cfg)
        plain, widened = sources
        kw = dict(key=jax.random.key(13), batch=B, T=T, block_T=T_CHUNK,
                  t_chunk=T_CHUNK, b_block=B_BLOCK, seed=5,
                  interpret=True, stochastic=False, pipelined=True)
        a, _ = streaming_mod.streaming_rollout_summary(
            plain, params, cfg.cluster, "rule", **kw)
        b, rep = streaming_mod.streaming_rollout_summary(
            widened, params, cfg.cluster, "rule", **kw)
        assert rep["n_blocks"] == T // T_CHUNK
        # Decisions and dollar accounting are bitwise. The two carbon
        # integrals may differ at the ulp level only: the block
        # kernel's compiled program keys on the stream's row count, and
        # XLA reassociates that one reduction differently at this
        # width (~1e-9 absolute; the region lanes are still passive —
        # a consumed lane would shift decisions macroscopically).
        assert _fields_equal(a, b) <= {"carbon_kg", "g_co2_per_kreq"}
        np.testing.assert_allclose(np.asarray(b.carbon_kg),
                                   np.asarray(a.carbon_kg), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(b.g_co2_per_kreq),
                                   np.asarray(a.g_co2_per_kreq),
                                   rtol=1e-5)

    @pytest.mark.slow  # 8-device mesh compile — slow-lane per the rule.
    def test_8shard_wrapper_consumes_it_bitwise(self, cfg, geo, sources):
        from ccka_tpu.parallel import make_mesh, sharded_packed_trace
        from ccka_tpu.parallel.sharded_kernel import (
            sharded_megakernel_summary_from_packed)
        from ccka_tpu.policy.rule import offpeak_action, peak_action

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        params = SimParams.from_config(cfg)
        mesh = make_mesh()
        plain, widened = sources
        key = jax.random.key(17)
        Z = cfg.cluster.n_zones
        sp = sharded_packed_trace(mesh, plain, T, key, B, t_chunk=T_CHUNK)
        sw = sharded_packed_trace(mesh, widened, T, key, B,
                                  t_chunk=T_CHUNK)
        lay = lanes.resolve_layout(sw.shape[1], Z)
        lo, _hi = lay.block("regions")
        assert np.array_equal(np.asarray(sp), np.asarray(sw[:, :lo]))
        off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
        kw = dict(stochastic=False, b_block=B // 8, t_chunk=T_CHUNK,
                  interpret=True)
        a = sharded_megakernel_summary_from_packed(
            mesh, params, off, peak, sp, T, 3, **kw)
        b = sharded_megakernel_summary_from_packed(
            mesh, params, off, peak, sw, T, 3, **kw)
        assert not _fields_equal(a, b)


class TestMigrationActionSpace:
    def test_sanitize_rates_invariants(self):
        key = jax.random.key(0)
        raw = jax.random.uniform(key, (3, 3, 3), minval=-0.5,
                                 maxval=2.5)
        r = np.asarray(migrate.sanitize_rates(raw))
        assert r.min() >= 0.0 and r.max() <= 1.0
        assert np.all(np.diagonal(r, axis1=0, axis2=1) == 0.0)
        # Outflow per (source, family) never exceeds 1: at most the
        # existing queued mass can move (conservation by construction).
        assert np.all(r.sum(axis=1) <= 1.0 + 1e-6)
        # Idempotent.
        assert np.allclose(np.asarray(migrate.sanitize_rates(r)), r)

    def test_policy_library_and_unknown_names_rejected(self):
        assert set(migrate.GEO_POLICIES) \
            == {"none", "cost-first", "carbon-first", "balanced"}
        with pytest.raises(ValueError, match="unknown geo policies"):
            migrate.resolve_geo_policies(["nope"])
        with pytest.raises(ValueError, match="no geo policies"):
            migrate.resolve_geo_policies([])
        with pytest.raises(ValueError, match="unknown geo scenarios"):
            pareto.resolve_geo_scenarios(["nope"])
        with pytest.raises(ValueError, match="no geo scenarios"):
            pareto.resolve_geo_scenarios([])

    def test_zero_rate_rollout_bitwise_none_policy(self, geo, step):
        R = geo.n_regions
        zero = np.zeros((R, R, migrate.N_FAMILIES), np.float32)
        a = geo_dyn.geo_rollout(geo, migrate.GEO_POLICIES["none"], step)
        b = geo_dyn.geo_rollout(geo, None, step, rates_override=zero)
        assert not _fields_equal(a, b)
        assert float(jnp.max(a.migration_cost_usd)) == 0.0
        assert float(jnp.max(a.moved_pods)) == 0.0

    def test_step_cost_none_path_is_bitwise_pre_geo(self, cfg):
        from ccka_tpu.sim.types import StepMetrics
        from ccka_tpu.train.objective import step_cost

        fields = {f: jnp.zeros(2) for f in StepMetrics._fields}
        fields.update(
            cost_usd=jnp.asarray([2.0, 3.0]),
            carbon_g=jnp.asarray([100.0, 50.0]),
            served_pods=jnp.asarray([[1.0], [0.0]]),
            demand_pods=jnp.asarray([[2.0], [2.0]]),
            slo_ok=jnp.asarray([1.0, 1.0]))
        metrics = StepMetrics(**fields)
        base = step_cost(metrics, cfg.train)
        # None (the pre-geo call shape) and an explicit zero migration
        # cost are both bitwise the original objective.
        assert np.array_equal(
            np.asarray(base),
            np.asarray(step_cost(metrics, cfg.train, migration_cost=None)))
        assert np.array_equal(
            np.asarray(base),
            np.asarray(step_cost(metrics, cfg.train,
                                 migration_cost=jnp.zeros(2))))
        with_mig = step_cost(metrics, cfg.train,
                             migration_cost=jnp.asarray([0.5, 0.0]))
        d = np.asarray(with_mig) - np.asarray(base)
        assert d[0] == pytest.approx(cfg.train.migration_weight * 0.5)
        assert d[1] == 0.0

    def test_work_conservation_under_chaos(self, geo, step):
        """The tentpole invariant end-to-end through the actuation
        wire: policy rates -> rendered PatchCommands -> seeded
        ChaosSink (drops + admission rewrites) -> parse-back of what
        LANDED -> rollout. Pending mass is moved, never created or
        destroyed, whatever subset of commands survives."""
        from ccka_tpu.actuation.chaos import ChaosSink
        from ccka_tpu.actuation.sink import DryRunSink

        R = geo.n_regions
        sig = migrate.RegionSignals(
            price_dev=jnp.asarray([1.2, 0.0]),
            carbon_dev=jnp.asarray([180.0, -40.0]),
            capacity=jnp.asarray([8.0, 10.0]),
            queues=jnp.full((R, migrate.N_FAMILIES), 5.0))
        rates = np.asarray(
            migrate.GEO_POLICIES["balanced"].rates(sig))
        cmds = migrate.render_migration_commands(rates)
        assert cmds, "balanced policy moved nothing on a hot gradient"
        dry = DryRunSink()
        chaos = ChaosSink(dry, ChaosConfig(enabled=True, drop_prob=0.4,
                                           rewrite_prob=0.2), seed=7)
        for cmd in cmds:
            chaos._patch(cmd)
        landed = [c for c in dry.commands
                  if getattr(c, "name", "").startswith("geo-mig-")]
        assert 0 < len(landed) < len(cmds), (
            "seed 7 must realize a thinned-but-nonempty stream")
        effective = migrate.apply_migration_commands(landed, R)
        out = geo_dyn.geo_rollout(geo, None, step,
                                  rates_override=effective)
        residual = geo_dyn.conservation_residual(step, out)
        assert residual < 1e-3, residual
        # And the un-thinned wire round-trips the sanitized rates.
        full = migrate.apply_migration_commands(cmds, R)
        assert np.allclose(full, np.asarray(migrate.sanitize_rates(
            jnp.asarray(rates))), atol=1e-8)

    def test_conservation_across_policies(self, geo, step):
        for name, pol in migrate.GEO_POLICIES.items():
            out = geo_dyn.geo_rollout(geo, pol, step)
            residual = geo_dyn.conservation_residual(step, out)
            assert residual < 1e-3, (name, residual)


class TestParetoScoreboard:
    def test_dominates_and_front_properties(self):
        pts = {"a": (1.0, 1.0, 0.0), "b": (2.0, 2.0, 0.0),
               "c": (0.5, 3.0, 0.0), "d": (1.0, 1.0, 0.0)}
        assert pareto.dominates(pts["a"], pts["b"])
        assert not pareto.dominates(pts["b"], pts["a"])
        # Equal points never strictly dominate.
        assert not pareto.dominates(pts["a"], pts["d"])
        front = pareto.pareto_front(pts)
        assert "b" not in front
        assert {"a", "c"} <= set(front)
        # Front members are mutually non-dominated.
        for x in front:
            for y in front:
                if x != y:
                    assert not pareto.dominates(pts[x], pts[y])

    @pytest.mark.slow  # lane-time rule: the bench-diff gate tests
    # pin the record shape cheaply on a literal dict.
    def test_small_suite_record_shape(self, cfg):
        suite = pareto.run_geo_suite(
            scenarios=["spot-storm"], policies=["none", "carbon-first"],
            zone_region_index=cfg.cluster.zone_region_index,
            seed=0, steps=24, batch=2, dt_s=cfg.sim.dt_s)
        assert suite["policies"] == ["carbon-first", "none"]
        assert suite["classes"] == sorted(pareto._CLASS_SLO)
        (scn,) = suite["scenarios"]
        for klass in suite["classes"]:
            fr = scn["pareto"][klass]
            assert set(fr["points"]) == {"none", "carbon-first"}
            assert fr["front"], "empty Pareto front"
            for n in fr["front"]:
                assert n in fr["points"]
        assert suite["max_conservation_residual"] < 1e-3


class TestLedgerMigrationTerm:
    def test_migration_term_always_present_and_shares_sum_to_one(
            self, cfg):
        from ccka_tpu.obs.decisions import (TERM_NAMES, objective_terms,
                                            term_shares)

        assert TERM_NAMES[-1] == "migration"
        base = dict(cost_usd=2.0, carbon_g=120.0, pend_c0=1.0,
                    pend_c1=0.5, slo_ok=1.0)
        terms, _ = objective_terms(cfg.train, **base)
        assert terms["migration"] == 0.0
        assert set(terms) == set(TERM_NAMES)
        terms, _ = objective_terms(cfg.train, **base,
                                   migration_cost_usd=0.25)
        assert terms["migration"] == pytest.approx(
            cfg.train.migration_weight * 0.25)
        shares = term_shares(terms)
        assert set(shares) == set(TERM_NAMES)
        assert abs(sum(shares.values()) - 1.0) < 1e-12

    def test_observe_single_attaches_and_explain_renders_components(
            self, cfg):
        from ccka_tpu.config import ObsConfig
        from ccka_tpu.obs.decisions import DecisionLedger, explain_row

        led = DecisionLedger(
            ObsConfig(enabled=True, decisions_enabled=True), cfg.train,
            policy="geo-balanced")
        chosen = dict(cost_usd=1.0, carbon_g=80.0, pend_c0=0.0,
                      pend_c1=0.0, slo_ok=1.0, migration_cost_usd=0.04)
        shadow = dict(cost_usd=1.1, carbon_g=90.0, pend_c0=0.0,
                      pend_c1=0.0, slo_ok=1.0)
        led.observe_single(
            3, lane="peak", action=[1.0, 0.0], exo={}, state={},
            chosen=chosen, shadow=shadow, shadow_action=[1.0, 0.0],
            migration_components={"inference:r0->r1": 0.01,
                                  "batch:r0->r1": 0.03})
        (row,) = led.rows
        shares = row["objective"]["shares"]
        assert "migration" in shares
        assert abs(sum(shares.values()) - 1.0) < 1e-12
        text = explain_row(row)
        assert "migration components" in text
        # Largest component first.
        assert text.index("batch:r0->r1") < text.index("inference:r0->r1")


class TestGeoConfigValidation:
    @pytest.mark.parametrize("field,value", (
        ("price_dev_sigma", -1.0),
        ("price_storm_frac", 1.5),
        ("capacity_deny_frac", -0.1),
        ("price_storm_mult", 0.5),
        ("transfer_latency_ticks", 0),
        ("transfer_cost_usd_per_pod", -0.01),
        ("price_storm_carbon_g_kwh", -5.0),
        ("zone_region_index", (0, 2)),
    ))
    def test_bad_values_rejected(self, geo, field, value):
        with pytest.raises(ConfigError, match="geo"):
            dataclasses.replace(geo, **{field: value}).validate()

    def test_bound_to_binds_the_cluster_topology(self, cfg):
        from ccka_tpu.config import GeoConfig

        g = GeoConfig(enabled=True).bound_to(cfg.cluster)
        assert g.zone_region_index == cfg.cluster.zone_region_index
        assert g.n_regions == max(cfg.cluster.zone_region_index) + 1


class TestBenchDiffGeoGates:
    """The round-19 geo invariant gates (satellite 6)."""

    CLEAN = {
        "stage": "--geo-only",
        "zero_migration_parity": True,
        "dominance_found": True,
        "max_conservation_residual": 9e-4,
        "conservation_gate_pods": 0.01,
        "classes": ["background", "batch", "inference"],
        "scenarios": [{
            "scenario": "spot-storm",
            "pareto": {
                k: {"points": {"none": [2.0, 2.0, 0.0],
                               "carbon-first": [1.0, 1.0, 0.0]},
                    "front": ["carbon-first"],
                    "dominates_none": ["carbon-first"]}
                for k in ("background", "batch", "inference")},
        }],
        "ledger": {"rows": 8, "term_share_err_max": 1e-15,
                   "migration_share_max": 0.08,
                   "migration_term_present": True},
    }

    def _diff(self, doc):
        from ccka_tpu.obs import bench_history

        return bench_history.bench_diff({
            "records": [{"round": 19, "file": "BENCH_r19.json",
                         "platform": "cpu",
                         **bench_history._extract_geo(doc)}],
            "lane": []})

    def test_clean_record_passes(self):
        assert self._diff(json.loads(json.dumps(self.CLEAN)))["ok"]

    def test_each_gate_trips(self):
        import copy

        front_bad = copy.deepcopy(self.CLEAN)
        # A 'front' that hides a dominated member is a corrupt board.
        front_bad["scenarios"][0]["pareto"]["batch"]["front"] = [
            "carbon-first", "none"]
        residual_bad = dict(self.CLEAN, max_conservation_residual=0.5)
        ledger_bad = copy.deepcopy(self.CLEAN)
        ledger_bad["ledger"]["migration_term_present"] = False
        shares_bad = copy.deepcopy(self.CLEAN)
        shares_bad["ledger"]["term_share_err_max"] = 0.1
        cases = [
            (dict(self.CLEAN, zero_migration_parity=False), "bitwise"),
            (front_bad, "dominated Pareto front"),
            (residual_bad, "conserved"),
            (ledger_bad, "migration term absent"),
            (shares_bad, "sum to ~1"),
        ]
        for doc, needle in cases:
            d = self._diff(doc)
            assert not d["ok"], needle
            assert any(needle in r["detail"] for r in d["regressions"]), \
                (needle, d["regressions"])
        # Missing claims are PARTIAL regressions, not silent passes.
        for missing in ("zero_migration_parity", "dominance_found",
                        "scenarios", "ledger", "classes",
                        "max_conservation_residual"):
            doc = {k: v for k, v in self.CLEAN.items() if k != missing}
            d = self._diff(doc)
            assert not d["ok"], missing
            assert any("partial geo record" in r["detail"]
                       for r in d["regressions"]), missing

    def test_cli_bench_diff_doctored_root_exits_one(self, tmp_path,
                                                    capsys):
        from ccka_tpu.cli import main

        os.makedirs(tmp_path / "data", exist_ok=True)
        doc = json.loads(json.dumps(self.CLEAN))
        doc["zero_migration_parity"] = False
        doc["provenance"] = {"platform": "cpu"}
        with open(tmp_path / "BENCH_r19.json", "w") as fh:
            json.dump(doc, fh)
        with open(tmp_path / "data" / "lane_times.json", "w") as fh:
            json.dump([], fh)
        assert main(["bench-diff", "--root", str(tmp_path)]) == 1
        out = json.loads(capsys.readouterr().out)
        assert out["regressions"][0]["kind"] == "geo_invariant"

    def test_real_history_carries_round19_and_stays_clean(self):
        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        history = load_bench_history(_ROOT)
        r19 = [r for r in history["records"] if r["round"] == 19]
        assert r19, "BENCH_r19.json missing from the repo root"
        rec = r19[0]
        assert rec["geo_zero_migration_parity"] is True
        assert rec["geo_dominance_found"] is True
        assert rec["geo_conservation_ok"] is True
        assert rec["geo_migration_term_present"] is True
        assert rec["geo_partial"] == []
        assert rec["geo_front_violations"] == []
        diff = bench_diff(history)
        assert diff["ok"], diff["regressions"]


class TestGeoCLI:
    def test_unknown_names_rejected_up_front(self):
        from ccka_tpu.cli import main

        with pytest.raises(SystemExit, match="unknown geo scenarios"):
            main(["--preset", "multiregion", "geo",
                  "--scenarios", "nope"])
        with pytest.raises(SystemExit, match="unknown geo policies"):
            main(["--preset", "multiregion", "geo",
                  "--policies", "teleport"])

    @pytest.mark.slow  # lane-time rule: the rejection test keeps
    # the CLI entry in tier-1; rendering runs a real suite.
    def test_renders_front_per_class(self, capsys):
        from ccka_tpu.cli import main

        assert main(["--preset", "multiregion", "geo",
                     "--scenarios", "calm",
                     "--policies", "none,balanced",
                     "--steps", "16", "--batch", "2"]) == 0
        out = capsys.readouterr().out
        assert "== calm" in out
        for klass in ("inference", "batch", "background"):
            assert f"{klass}: front = " in out
        assert "conservation residual" in out
