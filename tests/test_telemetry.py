"""Telemetry tests: stage timing, JSONL export, profiler capture, trace
capture CLI (VERDICT rows 20/23: no profiler hooks, no structured timing,
`trace_from_arrays`/`save_trace` without a capture path).
"""

import json
import os

import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.harness.telemetry import (
    StageTimer,
    TelemetryWriter,
    profile_trace,
    read_telemetry,
)

_PHASES = ("scrape", "decide", "render", "apply", "verify", "estimate",
           "slo_scrape")


class TestStageTimer:
    def test_accumulates_phases(self):
        timer = StageTimer()
        with timer.stage("a"):
            pass
        with timer.stage("b"):
            pass
        with timer.stage("a"):  # re-entry accumulates
            pass
        t = timer.timings_ms()
        assert set(t) == {"a", "b"}
        assert all(v >= 0.0 for v in t.values())
        assert timer.total_ms >= max(t.values())

    def test_records_on_exception(self):
        timer = StageTimer()
        with pytest.raises(RuntimeError):
            with timer.stage("boom"):
                raise RuntimeError
        assert "boom" in timer.timings_ms()


class TestTelemetryWriter:
    def test_jsonl_roundtrip(self, tmp_path):
        path = str(tmp_path / "sub" / "ticks.jsonl")
        with TelemetryWriter(path) as w:
            w.write({"t": 0, "cost": 1.5})
            w.write({"t": 1, "cost": 2.5})
        records = read_telemetry(path)
        assert [r["t"] for r in records] == [0, 1]

    def test_append_across_writers(self, tmp_path):
        path = str(tmp_path / "ticks.jsonl")
        with TelemetryWriter(path) as w:
            w.write({"t": 0})
        with TelemetryWriter(path) as w:  # daemon restart appends
            w.write({"t": 1})
        assert len(read_telemetry(path)) == 2


class TestControllerTelemetry:
    def test_tick_reports_phase_timings_and_jsonl(self, tmp_path):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        cfg = default_config()
        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        path = str(tmp_path / "telemetry.jsonl")
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, DryRunSink(),
                          interval_s=0.0, telemetry_path=path,
                          log_fn=lambda _line: None)
        reports = ctrl.run(ticks=3)

        for r in reports:
            assert set(r.timings_ms) == set(_PHASES)
            assert all(v >= 0.0 for v in r.timings_ms.values())

        records = read_telemetry(path)
        assert len(records) == 3
        assert records[0]["t"] == 0
        assert set(records[0]["timings_ms"]) == set(_PHASES)

        # A resumed run keeps appending — run() must not close the writer
        # (the controller's owner does, via close()).
        ctrl.run(ticks=2, start_tick=3)
        assert [r["t"] for r in read_telemetry(path)] == [0, 1, 2, 3, 4]
        ctrl.close()
        assert ctrl.telemetry is None
        ctrl.close()  # idempotent


class TestSummarizeTelemetry:
    def test_empty(self):
        from ccka_tpu.harness.telemetry import summarize_telemetry
        assert summarize_telemetry([]) == {"ticks": 0}

    def test_scoreboard_from_controller_run(self, tmp_path):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.harness.telemetry import (read_telemetry,
                                                summarize_telemetry)
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        cfg = default_config()
        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals,
                                    start_unix_s=8 * 3600 + 59 * 60)
        path = str(tmp_path / "t.jsonl")
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, DryRunSink(),
                          interval_s=0.0, telemetry_path=path,
                          log_fn=lambda _line: None)
        ctrl.run(ticks=6)
        ctrl.close()

        board = summarize_telemetry(read_telemetry(path))
        assert board["ticks"] == 6
        # The 09:00 peak edge lands inside the run (started 08:59).
        assert 0 < board["peak_ticks"] < 6
        assert board["applied_frac"] == 1.0
        assert board["verified_frac"] == 1.0
        assert board["cost_usd_hr"]["mean"] > 0
        assert set(board["profiles"]) == {"offpeak", "peak"}
        assert board["timings_ms"]["decide"]["p95"] >= 0
        assert board["latency_p95_ms"]["max"] >= board[
            "latency_p95_ms"]["mean"]

    def test_p95_is_nearest_rank_not_max(self):
        from ccka_tpu.harness.telemetry import summarize_telemetry

        # 20 ticks, one outlier: nearest-rank p95 (19th of 20) must pick
        # the outlier-free tail value, not collapse to max.
        records = [{"cost_usd_hr": 1.0} for _ in range(19)]
        records.append({"cost_usd_hr": 100.0})
        stats = summarize_telemetry(records)["cost_usd_hr"]
        assert stats["p95"] == 1.0
        assert stats["max"] == 100.0

    def test_report_cli_rejects_corrupt_line(self, tmp_path):
        from ccka_tpu.cli import main

        path = str(tmp_path / "bad.jsonl")
        with open(path, "w") as fh:
            fh.write('{"t": 0}\n{"t": 1, "cost')  # killed mid-write
        with pytest.raises(SystemExit, match="corrupt telemetry"):
            main(["report", "--telemetry", path])

    def test_report_cli(self, tmp_path, capsys):
        from ccka_tpu.cli import main
        from ccka_tpu.harness.telemetry import TelemetryWriter

        path = str(tmp_path / "t.jsonl")
        with TelemetryWriter(path) as w:
            w.write({"t": 0, "slo_ok": True, "applied": True,
                     "verified": True, "cost_usd_hr": 0.3,
                     "timings_ms": {"decide": 1.0}})
        assert main(["report", "--telemetry", path]) == 0
        board = json.loads(capsys.readouterr().out)
        assert board["ticks"] == 1 and board["slo_attainment"] == 1.0


class TestKedaApplyPath:
    def test_controller_applies_scaledobject(self):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        cfg = default_config().with_overrides(**{
            "workload.sqs_queue_name": "burst-queue",
            "workload.aws_account_id": "123456789012"})
        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        sink = DryRunSink()
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, sink,
                          interval_s=0.0, apply_keda=True,
                          log_fn=lambda _line: None)
        reports = ctrl.run(ticks=2)
        assert all(r.applied for r in reports)
        so = sink.get_object("ScaledObject", "scaled-burst-queue",
                             namespace="nov-22")
        assert so["spec"]["triggers"][0]["metadata"]["queueURL"].endswith(
            "123456789012/burst-queue")

    def test_keda_without_config_rejected(self):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        cfg = default_config()
        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        with pytest.raises(ValueError, match="sqs_queue_name"):
            Controller(cfg, RulePolicy(cfg.cluster), src, DryRunSink(),
                       interval_s=0.0, apply_keda=True)


class TestProfileTrace:
    def test_noop_without_dir(self):
        with profile_trace(""):
            pass  # must not create anything or require jax

    @pytest.mark.slow  # round 10 lane budget: ~21s of jax.profiler
    # start/stop for pure upstream plumbing; the gated no-op contract
    # (the ccka logic) stays fast-lane above.
    def test_captures_device_trace(self, tmp_path):
        import jax
        import jax.numpy as jnp

        d = str(tmp_path / "profile")
        with profile_trace(d):
            jax.block_until_ready(jnp.ones((128, 128)) @ jnp.ones((128, 128)))
        captured = [os.path.join(root, f)
                    for root, _dirs, files in os.walk(d) for f in files]
        assert captured, "profiler produced no files"


class TestCaptureCLI:
    def test_capture_roundtrips_through_replay(self, tmp_path, capsys):
        from ccka_tpu.cli import main
        from ccka_tpu.signals.replay import ReplaySignalSource
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        out = str(tmp_path / "day.npz")
        assert main(["capture", "--out", out, "--steps", "64"]) == 0
        rec = json.loads(capsys.readouterr().out)
        assert rec["out"] == out and rec["steps"] == 64

        replay = ReplaySignalSource.from_file(out)
        cfg = default_config()
        synth = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                      cfg.signals)
        np.testing.assert_allclose(
            np.asarray(replay.trace(64).carbon_g_kwh),
            np.asarray(synth.trace(64, seed=0).carbon_g_kwh), rtol=1e-6)
        assert replay.meta().zones == cfg.cluster.zones


class TestDashboard:
    """demo_40 analog: Grafana provisioning for the proposal's planned
    panels ("SLO burn, $/1k req, gCO2e/1k req, waste%, Spot exposure")."""

    def test_dashboard_has_proposal_panels(self):
        from ccka_tpu.harness.dashboard import render_dashboard

        dash = render_dashboard()
        titles = {p["title"] for p in dash["panels"]}
        for wanted in ("SLO burn", "$ per 1k requests",
                       "gCO2e per 1k requests", "Waste %", "Spot exposure"):
            assert wanted in titles
        assert dash["refresh"] == "30s"  # the scrape cadence

    def test_provisioning_configmaps_apply(self):
        from ccka_tpu.actuation import DryRunSink
        from ccka_tpu.harness.dashboard import render_dashboard_configmap

        sink = DryRunSink()
        docs = render_dashboard_configmap("http://prom:9090", "nov-22")
        results = sink.apply_manifests(docs)
        assert all(r.ok for r in results)
        ds = sink.get_object("ConfigMap", "ccka-grafana-datasource",
                             namespace="nov-22")
        assert "http://prom:9090" in ds["data"]["ccka-datasource.yaml"]
        dash = sink.get_object("ConfigMap", "ccka-grafana-dashboard",
                               namespace="nov-22")
        assert json.loads(dash["data"]["ccka-dashboard.json"])["uid"] == (
            "ccka-autoscaler")

    def test_cli_dashboard_json(self, capsys):
        from ccka_tpu.cli import main

        assert main(["dashboard", "--json"]) == 0
        docs = json.loads(capsys.readouterr().out)
        # Full demo_40 stage: provisioning CMs + provider CM + Secret +
        # Deployment + Service.
        assert [d["kind"] for d in docs] == [
            "ConfigMap", "ConfigMap", "Secret", "ConfigMap", "Deployment",
            "Service"]
        assert main(["dashboard", "--json", "--provision-only"]) == 0
        docs = json.loads(capsys.readouterr().out)
        assert [d["kind"] for d in docs] == ["ConfigMap", "ConfigMap"]

    def test_grafana_stack_golden(self):
        """demo_40_watch_config.sh:75-138 analog, hardened: the rendered
        Grafana pod must satisfy this framework's OWN Kyverno guardrail
        (requests+limits on every container — the reference's Grafana pod
        would be rejected by its own 04_kyverno.sh policy)."""
        from ccka_tpu.actuation import DryRunSink
        from ccka_tpu.harness.dashboard import render_observability_stack

        docs = render_observability_stack("http://prom:9090", "nov-22",
                                          admin_password="golden-pw")
        by_kind = {}
        for d in docs:
            by_kind.setdefault(d["kind"], []).append(d)
        secret = by_kind["Secret"][0]
        assert secret["stringData"]["admin-password"] == "golden-pw"
        dep = by_kind["Deployment"][0]
        pod = dep["spec"]["template"]["spec"]
        c = pod["containers"][0]
        # Guardrail compliance + hardened pod conventions.
        assert c["resources"]["requests"] and c["resources"]["limits"]
        assert pod["securityContext"]["runAsNonRoot"] is True
        assert c["securityContext"]["allowPrivilegeEscalation"] is False
        # Admin creds come from the Secret, never inline.
        env_names = {e["name"] for e in c["env"]}
        assert {"GF_SECURITY_ADMIN_USER",
                "GF_SECURITY_ADMIN_PASSWORD"} <= env_names
        assert all("value" not in e for e in c["env"]
                   if e["name"].startswith("GF_SECURITY"))
        # All three provisioning mounts are wired to the rendered CMs.
        vol_cms = {v["configMap"]["name"] for v in pod["volumes"]}
        assert vol_cms == {"ccka-grafana-datasource",
                           "ccka-grafana-dashboard-provider",
                           "ccka-grafana-dashboard"}
        svc = by_kind["Service"][0]
        assert svc["spec"]["ports"][0]["port"] == 3000  # demo_40 PF port
        # The whole stack applies through a sink.
        results = DryRunSink().apply_manifests(docs)
        assert all(r.ok for r in results)

    def test_metrics_pipeline_golden(self):
        """06_opencost.sh:277-387 analog: collector RBAC + OTel pipeline
        ConfigMap + hardened Deployment, with the controller's own
        ccka_* exposition in the scrape pool (the reference never
        scraped its decision loop)."""
        from ccka_tpu.actuation import DryRunSink
        from ccka_tpu.harness.pipeline import render_metrics_pipeline

        docs = render_metrics_pipeline(
            "https://aps.example/workspaces/ws-1/api/v1/remote_write",
            "nov-22", region="us-east-2",
            writer_role_arn="arn:aws:iam::1:role/writer")
        kinds = [d["kind"] for d in docs]
        assert kinds == ["ClusterRole", "ClusterRoleBinding",
                         "ServiceAccount", "ConfigMap", "Deployment"]
        role = docs[0]
        assert role["rules"][0]["verbs"] == ["get", "list", "watch"]
        sa = docs[2]
        assert sa["metadata"]["annotations"][
            "eks.amazonaws.com/role-arn"] == "arn:aws:iam::1:role/writer"
        conf = json.loads(docs[3]["data"]["collector.yaml"])
        # The OTel pipeline: prometheus receiver → sigv4auth →
        # prometheusremotewrite (06_opencost.sh:316-341).
        assert conf["service"]["pipelines"]["metrics"] == {
            "receivers": ["prometheus"],
            "exporters": ["prometheusremotewrite"]}
        assert conf["service"]["extensions"] == ["sigv4auth"]
        assert conf["extensions"]["sigv4auth"]["region"] == "us-east-2"
        assert conf["exporters"]["prometheusremotewrite"]["auth"] == {
            "authenticator": "sigv4auth"}
        jobs = {s["job_name"]
                for s in conf["receivers"]["prometheus"]["config"][
                    "scrape_configs"]}
        assert jobs == {"ccka-controller", "ksm-static"}
        assert conf["receivers"]["prometheus"]["config"]["global"][
            "scrape_interval"] == "30s"
        # Hardened pod: passes the framework's own Kyverno guardrail.
        pod = docs[4]["spec"]["template"]["spec"]
        c = pod["containers"][0]
        assert c["resources"]["requests"] and c["resources"]["limits"]
        assert pod["securityContext"]["runAsNonRoot"] is True
        assert c["securityContext"]["capabilities"] == {"drop": ["ALL"]}
        assert pod["volumes"][0]["configMap"]["name"] == (
            "ccka-collector-config")
        results = DryRunSink().apply_manifests(docs)
        assert all(r.ok for r in results)

    def test_metrics_pipeline_plain_prometheus(self):
        """Without a region the same pipeline lands on any Prometheus-
        compatible endpoint: no sigv4 extension, no auth block."""
        from ccka_tpu.harness.pipeline import render_metrics_pipeline

        docs = render_metrics_pipeline("http://prom:9090/api/v1/write",
                                       "nov-22")
        conf = json.loads(
            [d for d in docs if d["kind"] == "ConfigMap"][0]
            ["data"]["collector.yaml"])
        assert "extensions" not in conf
        assert "auth" not in conf["exporters"]["prometheusremotewrite"]
        sa = [d for d in docs if d["kind"] == "ServiceAccount"][0]
        assert "annotations" not in sa["metadata"]

    def test_query_proxy_golden(self):
        """06_opencost.sh:204-264 analog: SigV4 proxy SA + Deployment +
        Service with the reference's args shape."""
        from ccka_tpu.harness.pipeline import render_metrics_pipeline

        docs = render_metrics_pipeline(
            "https://aps.example/api/v1/remote_write", "nov-22",
            region="us-east-2", proxy=True,
            query_role_arn="arn:aws:iam::1:role/query")
        proxy_docs = [d for d in docs
                      if d["metadata"]["name"] == "ccka-query-proxy"]
        assert [d["kind"] for d in proxy_docs] == [
            "ServiceAccount", "Deployment", "Service"]
        dep = proxy_docs[1]
        args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--name=aps" in args and "--region=us-east-2" in args
        assert "--host=aps-workspaces.us-east-2.amazonaws.com" in args
        svc = proxy_docs[2]
        assert svc["spec"]["ports"][0]["port"] == 8005
        # Proxy without a region is a config error, not a silent render.
        with pytest.raises(ValueError, match="region"):
            render_metrics_pipeline("http://prom/api/v1/write", "nov-22",
                                    proxy=True)

    def test_cli_pipeline_json(self, capsys):
        from ccka_tpu.cli import main

        assert main(["pipeline", "--json"]) == 0
        docs = json.loads(capsys.readouterr().out)
        assert [d["kind"] for d in docs] == [
            "ClusterRole", "ClusterRoleBinding", "ServiceAccount",
            "ConfigMap", "Deployment"]
        conf = json.loads(docs[3]["data"]["collector.yaml"])
        # Default remote-write derives from the configured Prometheus.
        assert conf["exporters"]["prometheusremotewrite"][
            "endpoint"].endswith("/api/v1/write")

    def test_random_admin_password_generated(self):
        from ccka_tpu.harness.dashboard import render_grafana_admin_secret

        a = render_grafana_admin_secret()["stringData"]["admin-password"]
        b = render_grafana_admin_secret()["stringData"]["admin-password"]
        assert a != b and len(a) >= 12

    def test_cli_dashboard_preserves_existing_admin_secret(self, capsys):
        """Re-applying the stack must NOT rotate the admin Secret — the
        running pod resolved its password at start, so an overwrite locks
        the operator out until the next (credential-rotating) restart."""
        from unittest import mock

        from ccka_tpu.actuation import DryRunSink
        from ccka_tpu.cli import main

        sink = DryRunSink()
        with mock.patch("ccka_tpu.actuation.DryRunSink",
                        return_value=sink):
            assert main(["dashboard"]) == 0
            first = sink.get_object("Secret", "ccka-grafana-admin",
                                    namespace="nov-22")
            pw1 = first["stringData"]["admin-password"]
            assert main(["dashboard"]) == 0
            second = sink.get_object("Secret", "ccka-grafana-admin",
                                     namespace="nov-22")
        assert second["stringData"]["admin-password"] == pw1
        assert "secret preserved" in capsys.readouterr().err


def _service_report():
    """A representative fleet-service tick record (round 13): the
    service-only gauge tests resolve against this."""
    from ccka_tpu.harness.service import ServiceTickReport

    return ServiceTickReport(
        t=5, n_tenants=8, admitted=4, deferred=1, shed=2,
        cadence_skipped=0, bulkhead_skipped=1, scrape_failed=1,
        probes=1, applied=6,
        fanout_deferred=0, slo_ok=7, cost_usd_hr=1.5, carbon_g_hr=300.0,
        pending_pods=2.0, tick_latency_ms=112.5, admission_queue_depth=8,
        sheds_total=24, deferrals_total=9, breaker_transitions_total=3,
        cadence_divisor=2, decide_ms=2.1, fanout_ms=4.2,
        breaker_states={"0": 0, "1": 2, "2": 1},
        slo_burn_rate=0.25, slo_burn_rate_slow=0.125,
        incident_active=1, incidents_total=3, recorder_dumps_total=2,
        program_dispatches_total=123,
        achieved_roofline_fraction=0.75,
        pipeline_occupancy={"generation": 0.3, "kernel": 0.6,
                            "host": 0.1},
        shard_imbalance=1.25,
        policy_divergence_rate=0.375,
        objective_term_shares={"cost": 0.7, "carbon": 0.2,
                               "slo_pending": 0.06,
                               "slo_violation": 0.04},
        shadow_slo_delta=-1.0,
        shadow_usd_delta=0.0125,
        candidate_win_rate={"carbon": 0.7, "rule": 0.4},
        tournament_leader=1,
        region_migration_rate={"mean": 0.12},
        region_carbon_intensity={"r0": 380.0, "r1": 420.0},
        host_loop_us_per_tenant=0.875, active_tenants=4)


class TestPromExport:
    """VERDICT r2 missing #3: the dashboards queried ccka_* series that
    nothing exported. The exporter closes the fabric; these tests pin
    panel-expr <-> exported-series parity and a real scrape."""

    def test_every_panel_expr_is_exported(self):
        import dataclasses

        from ccka_tpu.harness.controller import TickReport
        from ccka_tpu.harness.dashboard import _PANEL_DEFS
        from ccka_tpu.harness.promexport import (SERIES,
                                                 SERVICE_ONLY_SERIES,
                                                 referenced_series)
        from ccka_tpu.harness.service import ServiceTickReport

        exported = set(SERIES)
        tick_fields = {f.name for f in dataclasses.fields(TickReport)}
        service_fields = {f.name
                          for f in dataclasses.fields(ServiceTickReport)}
        for _title, expr, _unit in _PANEL_DEFS:
            refs = referenced_series(expr)
            assert refs, f"panel expr references no ccka_* series: {expr}"
            missing = refs - exported
            assert not missing, (f"panel queries unexported series "
                                 f"{missing}: {expr}")
        # And every exported series maps to a real report field — the
        # controller's TickReport, or (for the declared service-only
        # set) the fleet service's ServiceTickReport. Dotted specs (the
        # span-sourced tick timing gauges, the per-tenant breaker dict)
        # resolve against their base field.
        for name, (field, _help) in SERIES.items():
            base = field.split(".", 1)[0]
            want = (service_fields if name in SERVICE_ONLY_SERIES
                    else tick_fields)
            assert base in want, f"{name} maps to unknown field {field}"

    def test_tick_timing_gauges_cover_the_span_phases(self):
        """The per-stage gauges (satellite of the obs PR) must stay in
        SERIES, resolve from a real tick's timings dict, and appear in a
        dashboard panel — both directions of the parity contract."""
        from ccka_tpu.harness.dashboard import _PANEL_DEFS
        from ccka_tpu.harness.promexport import (SERIES, referenced_series,
                                                 resolve_field)

        gauges = {"ccka_tick_scrape_ms", "ccka_tick_decide_ms",
                  "ccka_tick_act_ms", "ccka_tick_total_ms"}
        assert gauges <= set(SERIES)
        paneled = set()
        for _t, expr, _u in _PANEL_DEFS:
            paneled |= referenced_series(expr)
        assert gauges <= paneled, "tick gauges missing from the dashboard"

        rec = {"timings_ms": {"scrape": 1.0, "decide": 2.0, "render": 0.5,
                              "apply": 0.25, "verify": 0.25,
                              "estimate": 3.0, "slo_scrape": 0.5}}
        assert resolve_field(rec, SERIES["ccka_tick_scrape_ms"][0]) == 1.5
        assert resolve_field(rec, SERIES["ccka_tick_decide_ms"][0]) == 2.0
        assert resolve_field(rec, SERIES["ccka_tick_act_ms"][0]) == 1.0
        assert resolve_field(rec, SERIES["ccka_tick_total_ms"][0]) == 7.5
        # No timings yet (e.g. a hand-built record): skipped, not 0.
        assert resolve_field({}, SERIES["ccka_tick_total_ms"][0]) is None

    def test_recovery_gauges_cover_both_directions(self):
        """Round-12 satellite: the crash-safety series (reconciler
        convergence, actuation failures, snapshot/resume health) must be
        exported, panel-referenced, AND resolve from a real TickReport —
        both directions of the parity contract, like the tick gauges."""
        import dataclasses

        from ccka_tpu.harness.controller import TickReport
        from ccka_tpu.harness.dashboard import _PANEL_DEFS
        from ccka_tpu.harness.promexport import (SERIES, referenced_series,
                                                 resolve_field)

        gauges = {"ccka_reconcile_retries_total", "ccka_reconcile_diverged",
                  "ccka_actuation_failures_total",
                  "ccka_snapshot_age_ticks", "ccka_resumes_total"}
        assert gauges <= set(SERIES)
        paneled = set()
        for _t, expr, _u in _PANEL_DEFS:
            paneled |= referenced_series(expr)
        assert gauges <= paneled, "recovery gauges missing from dashboard"
        rec = dataclasses.asdict(TickReport(
            t=3, is_peak=False, profile="offpeak", applied=True,
            verified=False, fallbacks=0, cost_usd_hr=1.0, carbon_g_hr=1.0,
            nodes_spot=1.0, nodes_od=1.0, pending_pods=0.0, slo_ok=True,
            reconcile_retries=2, reconcile_retries_total=7,
            reconcile_diverged=1, actuation_failures=3,
            actuation_failures_total=9, snapshot_age_ticks=0,
            resumes_total=2))
        assert resolve_field(
            rec, SERIES["ccka_reconcile_retries_total"][0]) == 7
        assert resolve_field(rec, SERIES["ccka_reconcile_diverged"][0]) == 1
        assert resolve_field(
            rec, SERIES["ccka_actuation_failures_total"][0]) == 9
        assert resolve_field(
            rec, SERIES["ccka_snapshot_age_ticks"][0]) == 0
        assert resolve_field(rec, SERIES["ccka_resumes_total"][0]) == 2

    def test_service_gauges_cover_both_directions(self):
        """Round-13 satellite: the multi-tenant service series (breaker
        pressure, shed counter, admission depth, tick latency) must be
        exported, panel-referenced, AND resolve from a real
        ServiceTickReport — both directions of the parity contract. The
        breaker gauge sums the per-tenant level dict via the dotted
        spec, so one open (2) + one half-open (1) tenant reads 3."""
        import dataclasses

        from ccka_tpu.harness.dashboard import _PANEL_DEFS
        from ccka_tpu.harness.promexport import (SERIES,
                                                 SERVICE_ONLY_SERIES,
                                                 referenced_series,
                                                 render_exposition,
                                                 resolve_field)

        gauges = {"ccka_tenant_breaker_state", "ccka_ticks_shed_total",
                  "ccka_admission_queue_depth", "ccka_tick_latency_ms"}
        assert gauges <= set(SERIES)
        # Round 14 grew the service-only set by the obs gauges (their
        # own both-direction test below); this one keeps pinning the
        # round-13 members.
        assert gauges <= set(SERVICE_ONLY_SERIES)
        paneled = set()
        for _t, expr, _u in _PANEL_DEFS:
            paneled |= referenced_series(expr)
        assert gauges <= paneled, "service gauges missing from dashboard"

        rec = dataclasses.asdict(_service_report())
        assert resolve_field(
            rec, SERIES["ccka_tenant_breaker_state"][0]) == 3.0
        assert resolve_field(rec, SERIES["ccka_ticks_shed_total"][0]) == 24
        assert resolve_field(
            rec, SERIES["ccka_admission_queue_depth"][0]) == 8
        assert resolve_field(
            rec, SERIES["ccka_tick_latency_ms"][0]) == 112.5
        text = render_exposition(rec)
        assert "ccka_tenant_breaker_state 3" in text
        assert "ccka_ticks_shed_total 24" in text
        # A controller TickReport (no service fields) SKIPS the service
        # series rather than exporting fake zeros.
        assert resolve_field(
            {"t": 1}, SERIES["ccka_tenant_breaker_state"][0]) is None
        assert "ccka_tenant_breaker_state" not in render_exposition(
            {"t": 1})

    def test_obs_gauges_cover_both_directions(self):
        """Round-14 satellite: the incident-grade obs series (SLO burn
        rate, incident-active flag, recorder dump counter) must be
        exported, panel-referenced, AND resolve from a real
        ServiceTickReport — both directions of the parity contract —
        while a controller TickReport (no obs fields) SKIPS them
        rather than exporting fake zeros."""
        import dataclasses

        from ccka_tpu.harness.dashboard import _PANEL_DEFS
        from ccka_tpu.harness.promexport import (SERIES,
                                                 SERVICE_ONLY_SERIES,
                                                 referenced_series,
                                                 render_exposition,
                                                 resolve_field)

        gauges = {"ccka_slo_burn_rate", "ccka_incident_active",
                  "ccka_recorder_dumps_total"}
        assert gauges <= set(SERIES)
        assert gauges <= set(SERVICE_ONLY_SERIES)
        paneled = set()
        for _t, expr, _u in _PANEL_DEFS:
            paneled |= referenced_series(expr)
        assert gauges <= paneled, "obs gauges missing from the dashboard"

        rec = dataclasses.asdict(_service_report())
        assert resolve_field(rec, SERIES["ccka_slo_burn_rate"][0]) == 0.25
        assert resolve_field(
            rec, SERIES["ccka_incident_active"][0]) == 1
        assert resolve_field(
            rec, SERIES["ccka_recorder_dumps_total"][0]) == 2
        text = render_exposition(rec)
        assert "ccka_slo_burn_rate 0.25" in text
        assert "ccka_incident_active 1" in text
        assert "ccka_recorder_dumps_total 2" in text
        for series in gauges:
            assert resolve_field({"t": 1}, SERIES[series][0]) is None
            assert series not in render_exposition({"t": 1})

    def test_perf_gauges_cover_both_directions(self):
        """Round-15 satellite: the device-time observatory series
        (program dispatches, achieved roofline fraction, kernel-stage
        occupancy via the dotted dict spec, shard imbalance) must be
        exported, panel-referenced, AND resolve from a real
        ServiceTickReport — both directions — while a controller
        TickReport (no perf fields) SKIPS them rather than exporting
        fake zeros, and a service tick with NO published measurement
        (the snapshot-less default) skips the measurement-backed three
        while still stating its dispatch counter."""
        import dataclasses

        from ccka_tpu.harness.dashboard import _PANEL_DEFS
        from ccka_tpu.harness.promexport import (SERIES,
                                                 SERVICE_ONLY_SERIES,
                                                 referenced_series,
                                                 render_exposition,
                                                 resolve_field)
        from ccka_tpu.harness.service import ServiceTickReport

        gauges = {"ccka_program_dispatches_total",
                  "ccka_achieved_roofline_fraction",
                  "ccka_pipeline_occupancy", "ccka_shard_imbalance"}
        assert gauges <= set(SERIES)
        assert gauges <= set(SERVICE_ONLY_SERIES)
        paneled = set()
        for _t, expr, _u in _PANEL_DEFS:
            paneled |= referenced_series(expr)
        assert gauges <= paneled, "perf gauges missing from dashboard"

        rec = dataclasses.asdict(_service_report())
        assert resolve_field(
            rec, SERIES["ccka_program_dispatches_total"][0]) == 123
        assert resolve_field(
            rec, SERIES["ccka_achieved_roofline_fraction"][0]) == 0.75
        assert resolve_field(
            rec, SERIES["ccka_pipeline_occupancy"][0]) == 0.6
        assert resolve_field(
            rec, SERIES["ccka_shard_imbalance"][0]) == 1.25
        text = render_exposition(rec)
        assert "ccka_program_dispatches_total 123" in text
        assert "ccka_achieved_roofline_fraction 0.75" in text
        assert "ccka_pipeline_occupancy 0.6" in text
        assert "ccka_shard_imbalance 1.25" in text
        # Controller-skips contract: a TickReport has none of these.
        for series in gauges:
            assert resolve_field({"t": 1}, SERIES[series][0]) is None
            assert series not in render_exposition({"t": 1})
        # Measurement-less service tick: the defaulted report states
        # dispatches only when filled, and the snapshot-backed gauges
        # skip (None / empty dict) instead of exporting zeros.
        bare = dataclasses.asdict(ServiceTickReport(
            t=1, n_tenants=2, admitted=2, deferred=0, shed=0,
            cadence_skipped=0, bulkhead_skipped=0, scrape_failed=0,
            probes=0, applied=2, fanout_deferred=0, slo_ok=2,
            cost_usd_hr=1.0, carbon_g_hr=10.0, pending_pods=0.0,
            tick_latency_ms=5.0, admission_queue_depth=2,
            sheds_total=0, deferrals_total=0,
            breaker_transitions_total=0, cadence_divisor=1,
            decide_ms=1.0, fanout_ms=1.0))
        bare_text = render_exposition(bare)
        for series in ("ccka_achieved_roofline_fraction",
                       "ccka_pipeline_occupancy",
                       "ccka_shard_imbalance",
                       "ccka_program_dispatches_total"):
            assert series not in bare_text

    def test_decision_gauges_cover_both_directions(self):
        """Round-18 satellite: the decision-provenance series (windowed
        divergence rate, the objective cost share via the dotted term
        spec, the projected shadow SLO delta) must be exported,
        panel-referenced, AND resolve from a real ServiceTickReport —
        both directions of the parity contract — while a controller
        TickReport (no decision fields) SKIPS them rather than
        exporting fake zeros, and a service tick with the ledger OFF
        (None/{} defaults) skips them too."""
        import dataclasses

        from ccka_tpu.harness.dashboard import _PANEL_DEFS
        from ccka_tpu.harness.promexport import (SERIES,
                                                 SERVICE_ONLY_SERIES,
                                                 referenced_series,
                                                 render_exposition,
                                                 resolve_field)
        from ccka_tpu.harness.service import ServiceTickReport

        gauges = {"ccka_policy_divergence_rate",
                  "ccka_objective_term_share", "ccka_shadow_slo_delta"}
        assert gauges <= set(SERIES)
        assert gauges <= set(SERVICE_ONLY_SERIES)
        paneled = set()
        for _t, expr, _u in _PANEL_DEFS:
            paneled |= referenced_series(expr)
        assert gauges <= paneled, ("decision gauges missing from the "
                                   "dashboard")

        rec = dataclasses.asdict(_service_report())
        assert resolve_field(
            rec, SERIES["ccka_policy_divergence_rate"][0]) == 0.375
        # The dotted term spec reads the COST share out of the
        # attribution dict (the other terms ride the same dict).
        assert resolve_field(
            rec, SERIES["ccka_objective_term_share"][0]) == 0.7
        assert resolve_field(
            rec, SERIES["ccka_shadow_slo_delta"][0]) == -1.0
        text = render_exposition(rec)
        assert "ccka_policy_divergence_rate 0.375" in text
        assert "ccka_objective_term_share 0.7" in text
        assert "ccka_shadow_slo_delta -1" in text
        # Controller-skips contract: a TickReport has none of these.
        for series in gauges:
            assert resolve_field({"t": 1}, SERIES[series][0]) is None
            assert series not in render_exposition({"t": 1})
        # Ledger-off service tick: the defaulted report (None rate/
        # delta, empty shares dict) skips all three instead of
        # exporting zeros.
        bare = dataclasses.asdict(ServiceTickReport(
            t=1, n_tenants=2, admitted=2, deferred=0, shed=0,
            cadence_skipped=0, bulkhead_skipped=0, scrape_failed=0,
            probes=0, applied=2, fanout_deferred=0, slo_ok=2,
            cost_usd_hr=1.0, carbon_g_hr=10.0, pending_pods=0.0,
            tick_latency_ms=5.0, admission_queue_depth=2,
            sheds_total=0, deferrals_total=0,
            breaker_transitions_total=0, cadence_divisor=1,
            decide_ms=1.0, fanout_ms=1.0))
        bare_text = render_exposition(bare)
        for series in gauges:
            assert series not in bare_text

    def test_geo_gauges_cover_both_directions(self):
        """Round-19 satellite: the geo-arbitrage series (the mean
        applied migration rate via the dotted .mean spec, the summed
        regional grid carbon intensity via the dict.* spec) must be
        exported, panel-referenced, AND resolve from a real
        ServiceTickReport — both directions of the parity contract —
        while a controller TickReport (no geo fields) SKIPS them
        rather than exporting fake zeros, and a service tick with no
        published geo snapshot (empty default dicts) skips them too."""
        import dataclasses

        from ccka_tpu.harness.dashboard import _PANEL_DEFS
        from ccka_tpu.harness.promexport import (SERIES,
                                                 SERVICE_ONLY_SERIES,
                                                 referenced_series,
                                                 render_exposition,
                                                 resolve_field)
        from ccka_tpu.harness.service import ServiceTickReport

        gauges = {"ccka_region_migration_rate",
                  "ccka_region_carbon_intensity"}
        assert gauges <= set(SERIES)
        assert gauges <= set(SERVICE_ONLY_SERIES)
        paneled = set()
        for _t, expr, _u in _PANEL_DEFS:
            paneled |= referenced_series(expr)
        assert gauges <= paneled, "geo gauges missing from the dashboard"

        rec = dataclasses.asdict(_service_report())
        assert resolve_field(
            rec, SERIES["ccka_region_migration_rate"][0]) == 0.12
        # The .* spec sums the per-region intensity dict — the scrape
        # sees total grid burden, the per-region split stays local.
        assert resolve_field(
            rec, SERIES["ccka_region_carbon_intensity"][0]) == 800.0
        text = render_exposition(rec)
        assert "ccka_region_migration_rate 0.12" in text
        assert "ccka_region_carbon_intensity 800" in text
        # Controller-skips contract: a TickReport has neither field.
        for series in gauges:
            assert resolve_field({"t": 1}, SERIES[series][0]) is None
            assert series not in render_exposition({"t": 1})
        # Geo-off service tick: the defaulted report (empty dicts for
        # both surfaces) skips the series instead of exporting zeros.
        bare = dataclasses.asdict(ServiceTickReport(
            t=1, n_tenants=2, admitted=2, deferred=0, shed=0,
            cadence_skipped=0, bulkhead_skipped=0, scrape_failed=0,
            probes=0, applied=2, fanout_deferred=0, slo_ok=2,
            cost_usd_hr=1.0, carbon_g_hr=10.0, pending_pods=0.0,
            tick_latency_ms=5.0, admission_queue_depth=2,
            sheds_total=0, deferrals_total=0,
            breaker_transitions_total=0, cadence_divisor=1,
            decide_ms=1.0, fanout_ms=1.0))
        bare_text = render_exposition(bare)
        for series in gauges:
            assert series not in bare_text

    def test_tournament_gauges_cover_both_directions(self):
        """Round-20 satellite: the shadow-tournament series (the summed
        per-candidate win rate via the dict.* spec, the leader index)
        must be exported, panel-referenced, AND resolve from a real
        ServiceTickReport — both directions of the parity contract —
        while a controller TickReport (no tournament fields) SKIPS
        them rather than exporting fake zeros, and a service tick with
        the tournament OFF (empty dict / None defaults) skips them
        too."""
        import dataclasses

        from ccka_tpu.harness.dashboard import _PANEL_DEFS
        from ccka_tpu.harness.promexport import (SERIES,
                                                 SERVICE_ONLY_SERIES,
                                                 referenced_series,
                                                 render_exposition,
                                                 resolve_field)
        from ccka_tpu.harness.service import ServiceTickReport

        gauges = {"ccka_policy_candidate_win_rate",
                  "ccka_tournament_leader"}
        assert gauges <= set(SERIES)
        assert gauges <= set(SERVICE_ONLY_SERIES)
        paneled = set()
        for _t, expr, _u in _PANEL_DEFS:
            paneled |= referenced_series(expr)
        assert gauges <= paneled, ("tournament gauges missing from the "
                                   "dashboard")

        rec = dataclasses.asdict(_service_report())
        # The .* spec sums the per-candidate dict — the scrape sees
        # total challenger pressure; the per-name split stays on the
        # board (`ccka tournament board`).
        assert resolve_field(
            rec, SERIES["ccka_policy_candidate_win_rate"][0]) \
            == pytest.approx(1.1)
        assert resolve_field(
            rec, SERIES["ccka_tournament_leader"][0]) == 1
        text = render_exposition(rec)
        assert "ccka_policy_candidate_win_rate 1.1" in text
        assert "ccka_tournament_leader 1" in text
        # Controller-skips contract: a TickReport has neither field.
        for series in gauges:
            assert resolve_field({"t": 1}, SERIES[series][0]) is None
            assert series not in render_exposition({"t": 1})
        # Tournament-off service tick: the defaulted report (empty win
        # dict, None leader) skips both instead of exporting zeros —
        # a flat-zero win rate would read as "every candidate always
        # loses", which is a claim, not an absence.
        bare = dataclasses.asdict(ServiceTickReport(
            t=1, n_tenants=2, admitted=2, deferred=0, shed=0,
            cadence_skipped=0, bulkhead_skipped=0, scrape_failed=0,
            probes=0, applied=2, fanout_deferred=0, slo_ok=2,
            cost_usd_hr=1.0, carbon_g_hr=10.0, pending_pods=0.0,
            tick_latency_ms=5.0, admission_queue_depth=2,
            sheds_total=0, deferrals_total=0,
            breaker_transitions_total=0, cadence_divisor=1,
            decide_ms=1.0, fanout_ms=1.0))
        bare_text = render_exposition(bare)
        for series in gauges:
            assert series not in bare_text

    def test_fleet_scale_gauges_cover_both_directions(self):
        """Round-21 satellite: the fleet-scale host-loop series (real
        microseconds of host admission+accounting per tenant, admitted
        tenant count) must be exported, panel-referenced, AND resolve
        from a real ServiceTickReport — both directions of the parity
        contract — while a controller TickReport (no service fields)
        SKIPS them rather than exporting fake zeros, and a service tick
        predating the gauge (None defaults) skips them too: a fake
        0us/tenant would read as an infinitely fast host loop."""
        import dataclasses

        from ccka_tpu.harness.dashboard import _PANEL_DEFS
        from ccka_tpu.harness.promexport import (SERIES,
                                                 SERVICE_ONLY_SERIES,
                                                 referenced_series,
                                                 render_exposition,
                                                 resolve_field)
        from ccka_tpu.harness.service import ServiceTickReport

        gauges = {"ccka_host_loop_us_per_tenant", "ccka_active_tenants"}
        assert gauges <= set(SERIES)
        assert gauges <= set(SERVICE_ONLY_SERIES)
        paneled = set()
        for _t, expr, _u in _PANEL_DEFS:
            paneled |= referenced_series(expr)
        assert gauges <= paneled, ("fleet-scale gauges missing from the "
                                   "dashboard")

        rec = dataclasses.asdict(_service_report())
        assert resolve_field(
            rec, SERIES["ccka_host_loop_us_per_tenant"][0]) == 0.875
        assert resolve_field(
            rec, SERIES["ccka_active_tenants"][0]) == 4
        text = render_exposition(rec)
        assert "ccka_host_loop_us_per_tenant 0.875" in text
        assert "ccka_active_tenants 4" in text
        # Controller-skips contract: a TickReport has neither field.
        for series in gauges:
            assert resolve_field({"t": 1}, SERIES[series][0]) is None
            assert series not in render_exposition({"t": 1})
        # A defaulted service report (None gauge) skips, not zeros.
        bare = dataclasses.asdict(ServiceTickReport(
            t=1, n_tenants=2, admitted=2, deferred=0, shed=0,
            cadence_skipped=0, bulkhead_skipped=0, scrape_failed=0,
            probes=0, applied=2, fanout_deferred=0, slo_ok=2,
            cost_usd_hr=1.0, carbon_g_hr=10.0, pending_pods=0.0,
            tick_latency_ms=5.0, admission_queue_depth=2,
            sheds_total=0, deferrals_total=0,
            breaker_transitions_total=0, cadence_divisor=1,
            decide_ms=1.0, fanout_ms=1.0))
        assert "ccka_host_loop_us_per_tenant" not in render_exposition(
            bare)

    def test_live_scrape_serves_all_panel_series(self):
        """Drive two controller ticks with an exporter on a real socket
        and scrape /metrics — every panel series must come back (the
        declared service-only set is asserted against a service tick
        exposition instead: a single-cluster scrape legitimately omits
        it, but it must never silently vanish from BOTH surfaces)."""
        from urllib.request import urlopen

        from ccka_tpu.actuation import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.harness.dashboard import _PANEL_DEFS
        from ccka_tpu.harness.promexport import (MetricsExporter,
                                                 SERVICE_ONLY_SERIES,
                                                 referenced_series,
                                                 render_exposition)
        from ccka_tpu.harness.service import ServiceTickReport
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        cfg = default_config()
        exporter = MetricsExporter(port=0, cluster=cfg.cluster.name)
        try:
            src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                        cfg.signals)
            ctrl = Controller(cfg, RulePolicy(cfg.cluster), src,
                              DryRunSink(), interval_s=0.0,
                              exporter=exporter, log_fn=lambda _l: None)
            ctrl.run(ticks=2)
            body = urlopen(
                f"http://127.0.0.1:{exporter.port}/metrics",
                timeout=5).read().decode()
        finally:
            exporter.close()
        for _t, expr, _u in _PANEL_DEFS:
            for series in referenced_series(expr):
                if series in SERVICE_ONLY_SERIES:
                    continue
                assert f"{series}{{" in body, f"scrape missing {series}"
        assert 'cluster="demo1"' in body
        service_text = render_exposition(_service_report())
        for series in SERVICE_ONLY_SERIES:
            assert f"\n{series} " in service_text, (
                f"service exposition missing {series}")
        # Gauge values are parseable floats.
        import math
        for line in body.splitlines():
            if line.startswith("ccka_"):
                assert math.isfinite(float(line.rsplit(" ", 1)[1]))

    def test_degraded_and_fault_gauges_in_series_and_panels(self):
        """ISSUE 5 observability satellite: the degraded-mode state
        machine and fault-event gauges stay exported, resolvable from a
        TickReport, and on the dashboard — both parity directions, like
        the tick-timing gauges above."""
        import dataclasses

        from ccka_tpu.harness.controller import TickReport
        from ccka_tpu.harness.dashboard import _PANEL_DEFS
        from ccka_tpu.harness.promexport import (SERIES,
                                                 referenced_series,
                                                 render_exposition,
                                                 resolve_field)

        gauges = {"ccka_degraded", "ccka_degraded_ticks_total",
                  "ccka_signal_stale", "ccka_nodes_denied",
                  "ccka_nodes_delayed"}
        assert gauges <= set(SERIES)
        paneled = set()
        for _t, expr, _u in _PANEL_DEFS:
            paneled |= referenced_series(expr)
        # Every degraded/fault gauge except the delayed counter has its
        # own panel; delayed rides the "Fault events" sum expression.
        assert {"ccka_degraded", "ccka_degraded_ticks_total",
                "ccka_signal_stale", "ccka_nodes_denied"} <= paneled

        rec = dataclasses.asdict(TickReport(
            t=3, is_peak=False, profile="degraded-fallback:offpeak",
            applied=True, verified=True, fallbacks=0, cost_usd_hr=0.0,
            carbon_g_hr=0.0, nodes_spot=0.0, nodes_od=0.0,
            pending_pods=0.0, slo_ok=True, signal_stale=True,
            degraded="fallback", degraded_level=2,
            degraded_ticks_total=4, denied_nodes=1.5, delayed_nodes=0.5))
        assert resolve_field(rec, SERIES["ccka_degraded"][0]) == 2
        assert resolve_field(
            rec, SERIES["ccka_degraded_ticks_total"][0]) == 4
        text = render_exposition(rec)
        assert "ccka_degraded 2" in text
        assert "ccka_degraded_ticks_total 4" in text
        assert "ccka_signal_stale 1" in text
        assert "ccka_nodes_denied 1.5" in text

    def test_label_value_escaping(self):
        """ADVICE r3: a cluster name containing '"', '\\' or newline must
        render as a valid exposition, not break the whole scrape."""
        from ccka_tpu.harness.promexport import render_exposition

        text = render_exposition({"t": 1}, cluster='we"ird\\name\nx')
        line = next(l for l in text.splitlines()
                    if l.startswith("ccka_tick{"))
        assert line == 'ccka_tick{cluster="we\\"ird\\\\name\\nx"} 1'
        # And a benign name is untouched.
        benign = render_exposition({"t": 1}, cluster="demo1")
        assert 'ccka_tick{cluster="demo1"} 1' in benign

    def test_textfile_export_atomic(self, tmp_path):
        from ccka_tpu.harness.promexport import MetricsExporter

        path = str(tmp_path / "sub" / "ccka.prom")
        exporter = MetricsExporter(textfile=path)
        exporter.update({"cost_usd_hr": 1.25, "slo_ok": True, "t": 3})
        text = open(path).read()
        assert "ccka_cost_usd_hr 1.25" in text
        assert "ccka_slo_ok 1" in text
        # No tmp litter from the atomic replace.
        assert list((tmp_path / "sub").glob("*.tmp")) == []

    def test_cli_run_with_metrics_textfile(self, tmp_path, capsys):
        from ccka_tpu.cli import main

        prom = str(tmp_path / "kpi.prom")
        assert main(["run", "--ticks", "2", "--interval", "0",
                     "--metrics-textfile", prom]) == 0
        assert 'ccka_tick{cluster="demo1"} 1' in open(prom).read()
