"""Config system tests — validation, env overrides, round-tripping.

Covers the behaviors the reference implements as `.env` + `00_common.sh`
(defaults-if-unset `:8-10`, `require_var` hard-fail `:18-20`) and per-script
tunables (`demo_30_burst_configure.sh:7-8`).
"""

import pytest

from ccka_tpu.config import (
    ClusterConfig,
    ConfigError,
    FrameworkConfig,
    PoolSpec,
    config_from_env,
    default_config,
)


def test_default_config_validates():
    cfg = default_config()
    assert cfg.cluster.name == "demo1"
    assert cfg.cluster.n_pools == 2
    assert cfg.cluster.n_zones == 3
    assert cfg.workload.total_pods == 60  # 12 deployments x 5 replicas


def test_pool_names_match_reference():
    # demo_00_env.sh:18-19
    cfg = default_config()
    assert [p.name for p in cfg.cluster.pools] == ["spot-preferred", "on-demand-slo"]
    assert cfg.cluster.pools[0].capacity_types == ("spot", "on-demand")
    assert cfg.cluster.pools[1].capacity_types == ("on-demand",)


def test_round_trip_json():
    cfg = default_config()
    again = FrameworkConfig.from_json(cfg.to_json())
    assert again == cfg


def test_with_overrides_dotted():
    cfg = default_config().with_overrides(**{"sim.dt_s": 15.0, "train.seed": 7})
    assert cfg.sim.dt_s == 15.0
    assert cfg.train.seed == 7
    # original untouched (frozen)
    assert default_config().sim.dt_s == 30.0


def test_with_overrides_unknown_field():
    with pytest.raises(ConfigError):
        default_config().with_overrides(**{"sim.not_a_field": 1})


def test_env_overrides():
    cfg = config_from_env(environ={
        "CCKA_SIM_DT_S": "15",
        "CCKA_SIGNALS_CARBON_ZONE": "DE",
        "UNRELATED": "x",
    })
    assert cfg.sim.dt_s == 15
    assert cfg.signals.carbon_zone == "DE"


def test_validation_bad_strategy():
    with pytest.raises(ConfigError):
        ClusterConfig(pools=(PoolSpec(name="x", strategy="bogus"),)).validate()


def test_validation_zone_membership():
    with pytest.raises(ConfigError):
        ClusterConfig(offpeak_zones=("nowhere-1x",)).validate()


def test_validation_negative_dt():
    with pytest.raises(ConfigError):
        default_config().with_overrides(**{"sim.dt_s": -1.0})


def test_config_hashable_for_jit_static_args():
    cfg = default_config()
    assert hash(cfg) == hash(default_config())
