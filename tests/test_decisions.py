"""Decision provenance observatory (round 18, `obs/decisions.py`).

The contracts pinned here:

- **objective attribution**: per-term decomposition matches
  `train/objective.step_cost` arithmetic, shares sum to 1 on every
  recorded row, and the per-class split accounts for the pending term;
- **shadow pairing** (ISSUE 15 satellite): the rule shadow row riding
  the compiled tick is BITWISE a standalone rule evaluation on the
  same pre-step states and observed exo, and a rule-backend service's
  fresh decides are bitwise their own shadow (divergence exactly 0);
- **ledger-on/off bitwise non-interference** under seeded ChaosSink +
  slow-tenant chaos: decisions AND patch streams identical, while the
  on-run genuinely records divergent rows;
- **divergence-incident attribution**: the edge-triggered
  `policy_divergence` trigger stamps exactly one incident per windowed
  spike, each attributable 1:1 to a checksum-verified recorder dump;
- **CLI + bench-diff gates**: `ccka decisions list|show|explain`, and
  the decision invariant gates (injected bad record exits 1, real
  history stays clean).
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import (OBS_PRESETS, SERVICE_PRESETS, ConfigError,
                             ObsConfig, default_config)
from ccka_tpu.harness.service import (VirtualClock,
                                      fleet_service_from_config)
from ccka_tpu.obs.decisions import (DECISION_COLS, LANE_NAMES,
                                    TERM_NAMES, DecisionLedger,
                                    action_dim, decision_row_layout,
                                    explain_row, flat_action_names,
                                    objective_terms, read_decisions,
                                    term_shares)
from ccka_tpu.obs.recorder import verify_dump
from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cfg():
    return default_config().with_overrides(**{"sim.horizon_steps": 16})


@pytest.fixture(scope="module")
def rule(cfg):
    # ONE backend instance module-wide: the service-tick compile cache
    # keys on it (the test_service idiom).
    return RulePolicy(cfg.cluster)


@pytest.fixture(scope="module")
def carbon(cfg):
    return CarbonAwarePolicy(cfg.cluster)


def det_clock() -> VirtualClock:
    state = {"s": 0.0}

    def base():
        state["s"] += 1e-4
        return state["s"]
    return VirtualClock(base=base)


def _obs(tmp_path=None, **kw) -> ObsConfig:
    base = dict(enabled=True)
    if tmp_path is not None:
        base.update(dump_dir=str(tmp_path / "dumps"),
                    incident_log_path=str(tmp_path / "incidents.jsonl"),
                    decision_log_path=str(tmp_path / "decisions.jsonl"))
    base.update(kw)
    return ObsConfig(**base)


class TestDecomposition:
    def test_terms_match_step_cost_and_shares_sum_to_one(self, cfg):
        """The decomposition IS step_cost: summed terms equal the
        scalarization for the same inputs, shares sum to 1, and the
        per-class split accounts for the whole pending term."""
        from ccka_tpu.sim.types import StepMetrics

        tcfg = cfg.train
        terms, by_class = objective_terms(
            tcfg, cost_usd=0.5, carbon_g=100.0, pend_c0=3.0,
            pend_c1=1.0, slo_ok=0.0)
        # Against the canonical scalarization on a minimal metrics row.
        fields = {f: jnp.zeros(()) for f in StepMetrics._fields}
        fields.update(cost_usd=jnp.float32(0.5),
                      carbon_g=jnp.float32(100.0),
                      demand_pods=jnp.asarray([4.0, 2.0], jnp.float32),
                      served_pods=jnp.asarray([1.0, 1.0], jnp.float32),
                      slo_ok=jnp.float32(0.0))
        from ccka_tpu.train.objective import step_cost
        j = float(step_cost(StepMetrics(**fields), tcfg))
        assert sum(terms.values()) == pytest.approx(j, rel=1e-6)
        shares = term_shares(terms)
        assert set(shares) == set(TERM_NAMES)
        assert sum(shares.values()) == pytest.approx(1.0, abs=1e-12)
        assert sum(by_class.values()) == pytest.approx(
            terms["slo_pending"], rel=1e-12)

    def test_zero_objective_yields_no_fake_shares(self):
        assert term_shares({k: 0.0 for k in TERM_NAMES}) == {}

    def test_layout_and_action_names_consistent(self, cfg):
        lay = decision_row_layout(cfg.cluster)
        a = action_dim(cfg.cluster)
        assert lay.a_dim == a == len(flat_action_names(cfg.cluster))
        assert lay.cols == slice(4, 4 + len(DECISION_COLS))
        assert lay.shadow_action.stop == lay.width \
            == 4 + len(DECISION_COLS) + a
        assert lay.col("div_max_abs") \
            == 4 + DECISION_COLS.index("div_max_abs")

    def test_obs_config_validation(self):
        with pytest.raises(ConfigError, match="decision_window"):
            ObsConfig(decision_window=0).validate()
        with pytest.raises(ConfigError, match="divergence_spike_rate"):
            ObsConfig(divergence_spike_rate=0.0).validate()
        with pytest.raises(ConfigError, match="divergence_threshold"):
            ObsConfig(divergence_threshold=-1.0).validate()
        # The shipped default posture records decisions.
        assert OBS_PRESETS["default"].decisions_enabled is True


def _run_service(cfg, backend, n, obs, *, ticks=8, seed=11,
                 profiles=None, capture_states=False):
    svc = fleet_service_from_config(
        cfg, backend, n,
        profiles=profiles or ["healthy"] * n,
        service=SERVICE_PRESETS["default"], obs=obs,
        horizon_ticks=16, seed=seed, clock=det_clock())
    svc.warmup()
    states_pre = []
    for t in range(ticks):
        if capture_states:
            states_pre.append(jax.tree.map(np.asarray, svc.ctrl.states))
        svc.tick(t)
    return svc, states_pre


class TestShadowPairing:
    """The counterfactual is real: the shadow rows ARE the rule on the
    same observed inputs, bitwise."""

    def test_shadow_rows_bitwise_equal_standalone_rule(self, cfg,
                                                       carbon):
        """For every recorded tick, the ledger's shadow action rows
        must be BITWISE a standalone vmapped rule evaluation on the
        same pre-step states and the same observed exo slice — the
        in-dispatch lanes add provenance, never a different
        counterfactual."""
        from ccka_tpu.harness.fleet import exo_at, flatten_actions

        svc, states_pre = _run_service(cfg, carbon, 3, _obs(),
                                       ticks=4, capture_states=True)
        rule_fn = RulePolicy(cfg.cluster).action_fn()
        rows = list(svc.decisions.rows)
        assert len(rows) == 4 * 3
        for t in range(4):
            exo_n = jax.tree.map(
                np.asarray, exo_at(svc.ctrl._xs_all, jnp.int32(t), 16))
            states_t = jax.tree.map(jnp.asarray, states_pre[t])
            expect = np.asarray(flatten_actions(
                jax.vmap(lambda s, e: rule_fn(s, e, jnp.int32(t)))(
                    states_t, jax.tree.map(jnp.asarray, exo_n)), 3))
            for i in range(3):
                row = next(r for r in rows
                           if r["t"] == t and r["tenant"] == i)
                got = np.asarray(row["shadow"]["action"], np.float32)
                np.testing.assert_array_equal(got, expect[i])
                # And the observed exo on the row is the slice the
                # policy saw (zone-mean/summed, exactly).
                assert row["exo"]["is_peak"] == bool(
                    float(exo_n.is_peak[i]) > 0.5)
                assert row["exo"]["demand_pods"] == pytest.approx(
                    float(np.asarray(exo_n.demand_pods[i]).sum()),
                    rel=1e-6)
        svc.close()

    def test_rule_backend_fresh_rows_are_their_own_shadow(self, cfg,
                                                          rule):
        """Chosen == rule on every fresh lane: divergence exactly 0 and
        the shadow step's metrics bitwise the chosen step's (same
        program, same inputs — the pairing gate's other side)."""
        svc, _ = _run_service(cfg, rule, 3, _obs(), ticks=6)
        rows = list(svc.decisions.rows)
        assert rows and all(r["lane"] == "fresh" for r in rows)
        for r in rows:
            assert r["shadow"]["div_max_abs"] == 0.0
            assert r["shadow"]["div_l2"] == 0.0
            assert r["shadow"]["diverged"] is False
            assert r["shadow"]["action"] == r["action"]
            assert r["shadow"]["objective"]["terms"] \
                == r["objective"]["terms"]
            assert r["shadow"]["usd_delta"] == 0.0
            assert r["shadow"]["slo_delta"] == 0.0
        assert svc.decisions.diverged_total == 0
        assert svc.decisions.spikes_total == 0
        assert svc.incidents.counts().get("policy_divergence", 0) == 0
        svc.close()

    def test_carbon_backend_genuinely_diverges(self, cfg, carbon):
        svc, _ = _run_service(cfg, carbon, 3, _obs(), ticks=6)
        rows = list(svc.decisions.rows)
        assert any(r["shadow"]["diverged"] for r in rows)
        assert svc.decisions.diverged_total > 0
        svc.close()

    def test_every_row_shares_sum_to_one(self, cfg, carbon):
        svc, _ = _run_service(cfg, carbon, 3, _obs(), ticks=6)
        for r in svc.decisions.rows:
            for side in (r["objective"], r["shadow"]["objective"]):
                assert sum(side["shares"].values()) \
                    == pytest.approx(1.0, abs=1e-9)
                assert set(side["shares"]) == set(TERM_NAMES)
                assert side["total"] > 0.0
        svc.close()


class TestNonInterference:
    """Ledger-on vs ledger-off over one seeded world (chaos + slow
    tenants, deterministic clock): decisions and patch streams bitwise
    identical — the shadow lanes ride the tick either way."""

    def _run(self, cfg, backend, decisions_enabled, tmp_path=None):
        obs = _obs(tmp_path, decisions_enabled=decisions_enabled) \
            if tmp_path is not None \
            else ObsConfig(enabled=True,
                           decisions_enabled=decisions_enabled)
        svc = fleet_service_from_config(
            cfg, backend, 5,
            profiles=["healthy"] * 3 + ["slow", "flaky"],
            service=SERVICE_PRESETS["default"], obs=obs,
            horizon_ticks=16, seed=11, clock=det_clock())
        svc.warmup()
        svc.run(10)
        out = {
            "usd": svc.tenant_usd_per_slo_hr().copy(),
            "slo": svc.tenant_slo_ticks.copy(),
            "fresh": svc.tenant_fresh_ticks.copy(),
            "commands": [[(c.name, c.patch_type, json.dumps(
                c.patch, sort_keys=True))
                for c in getattr(s, "inner", s).commands]
                for s in svc.sinks],
            "rows": (svc.decisions.rows_total
                     if svc.decisions is not None else 0),
            "diverged": (svc.decisions.diverged_total
                         if svc.decisions is not None else 0),
        }
        svc.close()
        return out

    def test_ledger_on_off_bitwise_identical(self, cfg, carbon,
                                             tmp_path):
        off = self._run(cfg, carbon, False)
        on = self._run(cfg, carbon, True, tmp_path)
        np.testing.assert_array_equal(off["usd"], on["usd"])
        np.testing.assert_array_equal(off["slo"], on["slo"])
        np.testing.assert_array_equal(off["fresh"], on["fresh"])
        assert off["commands"] == on["commands"]
        # Non-vacuous both ways: the off-arm built no ledger, the
        # on-arm recorded genuinely divergent rows while changing
        # nothing.
        assert off["rows"] == 0
        assert on["rows"] > 0 and on["diverged"] > 0

    def test_decisions_off_builds_no_ledger(self, cfg, rule):
        svc = fleet_service_from_config(
            cfg, rule, 2, service=SERVICE_PRESETS["default"],
            obs=ObsConfig(enabled=True, decisions_enabled=False),
            horizon_ticks=16, seed=1)
        assert svc.decisions is None
        rep = svc.tick(0)
        assert rep.policy_divergence_rate is None
        assert rep.objective_term_shares == {}
        assert rep.shadow_slo_delta is None
        svc.close()


class TestDivergenceIncident:
    """ISSUE 15: the policy_divergence trigger is edge-triggered, 1:1
    dump-attributable, and wired through the report gauges."""

    @pytest.fixture(scope="class")
    def div_run(self, cfg, carbon, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("divergence")
        svc, _ = _run_service(cfg, carbon, 4, _obs(tmp), ticks=8,
                              profiles=["healthy"] * 3 + ["slow"])
        yield svc
        svc.close()

    def test_exactly_one_stamp_per_spike(self, div_run):
        svc = div_run
        counts = svc.incidents.counts()
        assert svc.decisions.spikes_total >= 1
        assert counts.get("policy_divergence", 0) \
            == svc.decisions.spikes_total
        # Carbon diverges every tick, so the windowed rate crosses the
        # bar ONCE and stays above it — edge-triggering means exactly
        # one stamp, not one per tick.
        assert counts["policy_divergence"] == 1

    def test_each_incident_attributable_to_verified_dump(self,
                                                         div_run):
        svc = div_run
        pd = [i for i in svc.incidents.incidents
              if i.trigger == "policy_divergence"]
        assert pd
        for inc in pd:
            assert inc.dump_path is not None
            body = verify_dump(inc.dump_path)
            assert body["t"] == inc.t
            assert inc.details["rate"] >= inc.details["threshold"]
            assert inc.details["window_ticks"] >= 1

    def test_report_surfaces_honest(self, div_run):
        svc = div_run
        rep = svc.tick(8)
        assert 0.0 < rep.policy_divergence_rate <= 1.0
        assert sum(rep.objective_term_shares.values()) \
            == pytest.approx(1.0, abs=1e-5)
        assert rep.shadow_slo_delta is not None
        assert rep.shadow_usd_delta is not None

    def test_ledger_jsonl_roundtrips(self, div_run):
        svc = div_run
        rows = read_decisions(svc.obs.decision_log_path)
        assert len(rows) == svc.decisions.rows_total
        assert rows[0]["t"] == 0 and "shadow" in rows[0]


class TestControllerLedger:
    def test_controller_records_rows_with_shadow(self, cfg):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        led = DecisionLedger(ObsConfig(enabled=True), cfg.train,
                             policy="carbon")
        ctrl = Controller(cfg, CarbonAwarePolicy(cfg.cluster), src,
                          DryRunSink(), interval_s=0.0,
                          decision_ledger=led, log_fn=lambda _l: None)
        ctrl.run(ticks=3)
        ctrl.close()
        assert led.rows_total == 3
        rows = list(led.rows)
        assert all(r["lane"] == "fresh" and r["tenant"] is None
                   for r in rows)
        assert all(r["shadow"]["diverged"] for r in rows)
        for r in rows:
            assert sum(r["objective"]["shares"].values()) \
                == pytest.approx(1.0, abs=1e-9)
            assert r["exo"]["stale"] is False

    def test_fallback_lane_divergence_is_zero(self, cfg):
        """A degraded-fallback tick's chosen action IS the rule — the
        row must say lane=fallback, divergence 0."""
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        class StaleSource(SyntheticSignalSource):
            last_scrape_stale = True

        src = StaleSource(cfg.cluster, cfg.workload, cfg.sim,
                          cfg.signals)
        led = DecisionLedger(ObsConfig(enabled=True), cfg.train,
                             policy="carbon")
        ctrl = Controller(cfg, CarbonAwarePolicy(cfg.cluster), src,
                          DryRunSink(), interval_s=0.0,
                          degraded_fallback_after=1,
                          decision_ledger=led, log_fn=lambda _l: None)
        ctrl.run(ticks=2)
        ctrl.close()
        rows = list(led.rows)
        assert all(r["lane"] == "fallback" for r in rows)
        assert all(r["shadow"]["div_max_abs"] == 0.0 for r in rows)
        assert all(r["exo"]["stale"] for r in rows)
        assert led.diverged_total == 0

    def test_controller_divergence_spike_stamps_incident(self, cfg):
        """The declared trigger is not service-scoped: a single-cluster
        controller with both an incident log and a ledger stamps ONE
        edge-triggered policy_divergence incident when the windowed
        rate crosses the bar (ledger.spikes_total == the log's
        count)."""
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.obs.incidents import IncidentLog
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        log = IncidentLog()
        led = DecisionLedger(ObsConfig(enabled=True), cfg.train,
                             policy="carbon")
        ctrl = Controller(cfg, CarbonAwarePolicy(cfg.cluster), src,
                          DryRunSink(), interval_s=0.0,
                          incident_log=log, decision_ledger=led,
                          log_fn=lambda _l: None)
        ctrl.run(ticks=4)
        ctrl.close()
        assert led.spikes_total == 1
        assert log.counts().get("policy_divergence", 0) == 1
        inc = log.incidents[0]
        assert inc.details["rate"] >= inc.details["threshold"]

    def test_fleet_controller_records_through_ledger(self, cfg):
        from ccka_tpu.harness.fleet import fleet_controller_from_config
        from ccka_tpu.obs.incidents import IncidentLog

        led = DecisionLedger(ObsConfig(enabled=True), cfg.train,
                             policy="carbon")
        log = IncidentLog()
        ctrl = fleet_controller_from_config(
            cfg, CarbonAwarePolicy(cfg.cluster), 3, horizon_ticks=16,
            seed=0, log_fn=lambda _l: None)
        ctrl.ledger = led
        ctrl.incident_log = log
        ctrl.run(2)
        ctrl.close()
        assert led.rows_total == 6
        assert led.diverged_total == 6
        # The 1:1 spikes==incidents invariant holds from the bare
        # fleet entry point too.
        assert led.spikes_total == 1
        assert log.counts().get("policy_divergence", 0) == 1

    def test_lane_names_track_service_constants(self):
        from ccka_tpu.harness import service as svc_mod

        assert LANE_NAMES[svc_mod.LANE_FRESH] == "fresh"
        assert LANE_NAMES[svc_mod.LANE_HOLD] == "hold"
        assert LANE_NAMES[svc_mod.LANE_FALLBACK] == "fallback"


class TestDecisionsCLI:
    @pytest.fixture(scope="class")
    def cli_log(self, cfg, carbon, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("cli-decisions")
        svc, _ = _run_service(cfg, carbon, 3, _obs(tmp), ticks=4)
        svc.close()
        return svc.obs.decision_log_path

    def test_list_show_explain(self, cli_log, capsys):
        from ccka_tpu.cli import main

        assert main(["decisions", "list", cli_log]) == 0
        out = capsys.readouterr()
        lines = out.out.strip().splitlines()
        assert lines and all("diverged" in json.loads(l) for l in lines)
        assert "decision row(s)" in out.err

        assert main(["decisions", "show", cli_log, "--t", "2",
                     "--tenant", "1"]) == 0
        rows = [json.loads(l) for l in
                capsys.readouterr().out.strip().splitlines()]
        assert len(rows) == 1
        assert rows[0]["t"] == 2 and rows[0]["tenant"] == 1

        assert main(["decisions", "explain", cli_log, "--t", "2"]) == 0
        text = capsys.readouterr().out
        assert "objective $" in text
        assert "rule shadow" in text
        assert "tick 2" in text

    def test_errors(self, cli_log, tmp_path):
        from ccka_tpu.cli import main

        with pytest.raises(SystemExit, match="needs --t"):
            main(["decisions", "show", cli_log])
        with pytest.raises(SystemExit, match="no decision rows"):
            main(["decisions", "show", cli_log, "--t", "999"])
        bad = str(tmp_path / "bad.jsonl")
        with open(bad, "w") as fh:
            fh.write('{"t": 0}\nGARBAGE\n{"t": 1}\n')
        with pytest.raises(SystemExit, match="corrupt decision log"):
            main(["decisions", "list", bad])

    def test_fleet_decisions_out_flag(self, tmp_path, capsys):
        from ccka_tpu.cli import main

        out = str(tmp_path / "dec.jsonl")
        assert main(["fleet", "--clusters", "2", "--ticks", "2",
                     "--service", "default", "--backend", "carbon",
                     "--decisions-out", out]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["decision_rows_total"] == 4
        assert os.path.exists(out)
        assert main(["decisions", "list", out]) == 0
        capsys.readouterr()
        # The explicit off posture must not be silently inverted.
        with pytest.raises(SystemExit, match="off posture"):
            main(["fleet", "--clusters", "2", "--ticks", "1",
                  "--service", "default", "--obs", "off",
                  "--decisions-out", out])
        # And without a service loop the flag must refuse, not no-op.
        with pytest.raises(SystemExit, match="ENABLED --service"):
            main(["fleet", "--clusters", "2", "--ticks", "1",
                  "--decisions-out", out])

    def test_explain_renderer_names_action_deltas(self, cfg):
        row = {
            "t": 3, "tenant": 0, "lane": "fresh", "policy": "carbon",
            "exo": {"spot_price_hr": 0.03, "od_price_hr": 0.096,
                    "carbon_g_kwh": 400.0, "demand_pods": 25.0,
                    "is_peak": False},
            "state": {"nodes_spot": 1.0, "nodes_od": 0.5},
            "action": [0.25, 1.0],
            "objective": {"total": 0.1,
                          "terms": {k: 0.025 for k in TERM_NAMES},
                          "shares": {k: 0.25 for k in TERM_NAMES},
                          "by_class": {"class0": 0.02,
                                       "class1": 0.005}},
            "shadow": {"policy": "rule", "action": [1.0, 1.0],
                       "objective": {"total": 0.1, "terms": {},
                                     "shares": {}, "by_class": {}},
                       "usd_delta": -0.01, "slo_delta": 1.0,
                       "div_max_abs": 0.75, "div_l2": 0.75,
                       "diverged": True},
        }
        text = explain_row(row, action_names=["zone_weight[0][0]",
                                              "zone_weight[0][1]"])
        assert "DIVERGED" in text
        assert "zone_weight[0][0]: 0.250 vs rule 1.000" in text
        assert "cost 25.0%" in text
        assert "$-0.010000/tick" in text
        # Label-length mismatch (a log recorded under another cluster
        # topology): labels are OMITTED with a note, never mislabeled.
        wrong = explain_row(row, action_names=["a", "b", "c"])
        assert "action labels omitted" in wrong
        assert "a[0]: 0.250 vs rule 1.000" in wrong


class TestBenchDiffDecisionGates:
    CLEAN = {
        "bitwise_identical": True,
        "ledger_overhead_frac": 0.02,
        "term_share_err_max": 1e-12,
        "rows_total": 768,
        "divergence_incidents": 1,
        "divergence_dumps_verified": 1,
        "divergence_dump_failures": [],
    }

    def _diff(self, dec):
        from ccka_tpu.obs import bench_history

        return bench_history.bench_diff({
            "records": [{"round": 18, "file": "BENCH_r18.json",
                         "platform": "cpu",
                         **bench_history._extract_decisions(dec)}],
            "lane": []})

    def test_clean_record_passes(self):
        assert self._diff(dict(self.CLEAN))["ok"]

    def test_each_gate_trips(self):
        cases = [
            (dict(self.CLEAN, bitwise_identical=False), "bitwise"),
            (dict(self.CLEAN, ledger_overhead_frac=0.12), "overhead"),
            (dict(self.CLEAN, term_share_err_max=0.1), "shares"),
            (dict(self.CLEAN, rows_total=0), "no decision rows"),
            (dict(self.CLEAN, divergence_dumps_verified=0),
             "attributable"),
            (dict(self.CLEAN, divergence_incidents=0), "attributable"),
            (dict(self.CLEAN,
                  divergence_dump_failures=["checksum"]),
             "attributable"),
        ]
        for dec, needle in cases:
            d = self._diff(dec)
            assert not d["ok"], dec
            assert any(needle in r["detail"] for r in d["regressions"])
        # Missing claims are PARTIAL regressions, not silent passes.
        for missing in ("bitwise_identical", "ledger_overhead_frac",
                        "term_share_err_max", "divergence_incidents"):
            dec = dict(self.CLEAN)
            dec.pop(missing)
            d = self._diff(dec)
            assert not d["ok"], missing
            assert any("partial decision record" in r["detail"]
                       for r in d["regressions"])

    def test_cli_bench_diff_doctored_root_exits_one(self, tmp_path,
                                                    capsys):
        from ccka_tpu.cli import main

        os.makedirs(tmp_path / "data", exist_ok=True)
        with open(tmp_path / "BENCH_r18.json", "w") as fh:
            json.dump({"stage": "--decisions-only",
                       "bitwise_identical": False,
                       "ledger_overhead_frac": 0.01,
                       "term_share_err_max": 1e-12,
                       "rows_total": 10,
                       "divergence_incidents": 1,
                       "divergence_dumps_verified": 1,
                       "divergence_dump_failures": [],
                       "provenance": {"platform": "cpu"}}, fh)
        with open(tmp_path / "data" / "lane_times.json", "w") as fh:
            json.dump([], fh)
        assert main(["bench-diff", "--root", str(tmp_path)]) == 1
        out = json.loads(capsys.readouterr().out)
        assert out["regressions"][0]["kind"] == "decisions_invariant"

    def test_real_history_carries_round18_and_stays_clean(self):
        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        history = load_bench_history(_ROOT)
        r18 = [r for r in history["records"] if r["round"] == 18]
        assert r18, "BENCH_r18.json missing from the repo root"
        rec = r18[0]
        assert rec["decisions_bitwise"] is True
        assert rec["decisions_overhead_frac"] <= 0.05
        assert rec["decisions_share_err"] <= 0.02
        assert rec["decisions_divergence_dumps_ok"] is True
        assert rec["decisions_partial"] == []
        diff = bench_diff(history)
        assert diff["ok"], diff["regressions"]
