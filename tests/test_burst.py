"""Burst workload generator tests — the demo_30 analog (VERDICT item 6).

Oracles come straight from the reference's generator and observer:
odd→spot / even→on-demand alternation with the critical toleration on even
deployments (`demo_30_burst_configure.sh:59-70`), the hardened pod spec
with 200m/128Mi→500m/256Mi resources (`:110-140`), and the Pending-pod
PodScheduled diagnostics table (`demo_30_burst_observe.sh:20-28`).
"""

import json

import pytest

from ccka_tpu.actuation import DryRunSink
from ccka_tpu.actuation.burst import (
    BURST_GROUP,
    apply_burst,
    burst_status,
    delete_burst,
    pending_pod_diagnostics,
    render_burst_deployments,
    render_burst_pdb,
    render_burst_rbac,
)
from ccka_tpu.config import default_config


@pytest.fixture()
def workload():
    return default_config().workload


class TestRenderBurst:
    def test_count_and_alternation(self, workload):
        docs = render_burst_deployments(workload)
        assert len(docs) == 12  # COUNT default, demo_30:7
        for i, doc in enumerate(docs, start=1):
            spec = doc["spec"]["template"]["spec"]
            cap = spec["nodeSelector"]["karpenter.sh/capacity-type"]
            # odd→spot with no tolerations; even→on-demand tolerating the
            # critical taint (demo_30:59-70).
            if i % 2 == 1:
                assert cap == "spot"
                assert spec["tolerations"] == []
            else:
                assert cap == "on-demand"
                assert spec["tolerations"] == [
                    {"key": "critical", "operator": "Equal",
                     "value": "true", "effect": "NoSchedule"}]
            assert doc["metadata"]["name"] == f"burst-web-{i}"
            assert doc["spec"]["replicas"] == 5  # REPLICAS default

    def test_pod_spec_hardening_and_resources(self, workload):
        doc = render_burst_deployments(workload)[0]
        pod = doc["spec"]["template"]["spec"]
        c = pod["containers"][0]
        # demo_30:135-140 resource shape.
        assert c["resources"]["requests"] == {"cpu": "200m", "memory": "128Mi"}
        assert c["resources"]["limits"] == {"cpu": "500m", "memory": "256Mi"}
        # Kyverno require-requests-limits would admit this (04_kyverno:24-42).
        assert c["readinessProbe"] and c["livenessProbe"]
        assert pod["securityContext"]["runAsNonRoot"] is True
        assert c["securityContext"]["capabilities"] == {"drop": ["ALL"]}

    def test_spot_pods_never_tolerate_critical(self, workload):
        """The Kyverno critical-no-spot guarantee (`04_kyverno.sh:47-75`):
        nothing schedulable onto spot carries the critical toleration."""
        for doc in render_burst_deployments(workload):
            spec = doc["spec"]["template"]["spec"]
            if spec["nodeSelector"]["karpenter.sh/capacity-type"] == "spot":
                assert all(t.get("key") != "critical"
                           for t in spec["tolerations"])

    def test_scale_overrides(self, workload):
        docs = render_burst_deployments(workload, count=3, replicas=7)
        assert len(docs) == 3
        assert all(d["spec"]["replicas"] == 7 for d in docs)

    def test_pdb_and_rbac(self, workload):
        pdb = render_burst_pdb(workload)
        assert pdb["spec"]["minAvailable"] == "50%"  # demo_10:52
        assert pdb["spec"]["selector"]["matchLabels"] == {
            "group": BURST_GROUP}
        kinds = [d["kind"] for d in render_burst_rbac()]
        assert kinds == ["Namespace", "ServiceAccount", "Role",
                         "RoleBinding"]


class TestApplyObserveDelete:
    def test_apply_roundtrip(self, workload):
        sink = DryRunSink()
        results = apply_burst(workload, sink)
        # 4 RBAC docs + PDB + 12 deployments.
        assert len(results) == 17
        assert all(r.ok for r in results)
        assert sink.get_object("Deployment", "burst-web-12",
                               namespace="nov-22")

    def test_status_summary(self, workload):
        sink = DryRunSink()
        apply_burst(workload, sink)
        status = burst_status(sink)
        assert status["count"] == 12
        assert status["count_spot"] == 6
        assert status["count_on_demand"] == 6
        assert status["desired_pods"] == 60  # the reference's burst scale

    def test_status_survives_sequence_gap(self, workload):
        """Listing is by group label, not sequential name probing: a gap
        (failed apply / operator delete) must not truncate the count."""
        sink = DryRunSink()
        apply_burst(workload, sink)
        sink.delete_object("Deployment", "burst-web-3", namespace="nov-22")
        status = burst_status(sink)
        assert status["count"] == 11
        assert status["desired_pods"] == 55

    def test_delete_by_group_label(self, workload):
        sink = DryRunSink()
        apply_burst(workload, sink)
        assert delete_burst(sink)
        assert burst_status(sink)["count"] == 0
        assert not sink.get_object("PodDisruptionBudget", "burst-pdb",
                                   namespace="nov-22")
        # RBAC survives for the next run.
        assert sink.get_object("ServiceAccount", "scale-burst",
                               namespace="nov-22")


class TestPendingDiagnostics:
    def test_extracts_podscheduled_reasons(self):
        pods = [
            {"metadata": {"name": "burst-web-1-abc"},
             "spec": {"nodeSelector":
                      {"karpenter.sh/capacity-type": "spot"}},
             "status": {"phase": "Pending", "conditions": [
                 {"type": "PodScheduled", "status": "False",
                  "reason": "Unschedulable",
                  "message": "0/3 nodes available: 3 node(s) didn't match "
                             "Pod's node affinity/selector."}]}},
            {"metadata": {"name": "burst-web-2-def"},
             "spec": {"nodeSelector":
                      {"karpenter.sh/capacity-type": "on-demand"}},
             "status": {"phase": "Running", "conditions": [
                 {"type": "PodScheduled", "status": "True"}]}},
        ]
        rows = pending_pod_diagnostics(pods)
        assert len(rows) == 1
        assert rows[0]["name"] == "burst-web-1-abc"
        assert rows[0]["node_selector"] == "spot"
        assert rows[0]["reason"] == "Unschedulable"
        assert "didn't match" in rows[0]["message"]


class TestBurstCLI:
    def test_json_render(self, capsys):
        from ccka_tpu.cli import main
        assert main(["burst", "--json", "--count", "2"]) == 0
        docs = json.loads(capsys.readouterr().out)
        kinds = [d["kind"] for d in docs]
        assert kinds.count("Deployment") == 2
        assert "PodDisruptionBudget" in kinds

    def test_dry_run_apply(self, capsys):
        from ccka_tpu.cli import main
        assert main(["burst"]) == 0
        err = capsys.readouterr().err
        assert "17 object(s) rendered (dry-run)" in err
