"""Tier-1 static guard: no unfenced wall-clock timing around device work.

The async-dispatch footgun (VERDICT r5 weak #2): `t0 = perf_counter();
jitted(x); dt = perf_counter() - t0` times the *dispatch*, not the work —
on an async backend the published number can be 100x off, and round 5
only caught it because a human re-derived the roofline bytes. This test
enforces the fix mechanically over `ccka_tpu/` and `bench.py`:

    any function that (a) calls `time.perf_counter()` or `time.time()`
    AND (b) touches device code (a `jax.`/`jnp.` reference in scope)
    MUST also have a fence or span wrapper in scope — a
    `block_until_ready` call, a `.span(`/`device_span` context, or a
    `StageTimer` stage (whose spans fence).

Host-only timing (wall-clock timestamps, subprocess timing) passes
untouched because it references no device code. The obs tracer itself
(`ccka_tpu/obs/trace.py`) is the one exempt file: it IS the primitive
the rule points everyone else at.
"""

from __future__ import annotations

import ast
import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_TARGETS = (os.path.join(ROOT, "ccka_tpu"),
                os.path.join(ROOT, "bench.py"))
# The timing primitive: spans fence *for* their callers, so this file
# legitimately holds bare perf_counter next to jax references.
EXEMPT = {os.path.join(ROOT, "ccka_tpu", "obs", "trace.py")}

# Round 13 added time.monotonic: the multi-tenant service's deadline
# arithmetic (`harness/service.py`) reads a monotonic clock in the SAME
# hot loop that dispatches device work, so un-fenced monotonic timing
# next to jax code is exactly the footgun this guard exists for — the
# service loop must carry its timing inside tracer spans.
_TIMING_FNS = {("time", "perf_counter"), ("time", "time"),
               ("time", "monotonic")}
_FENCE_MARKERS = ("block_until_ready", ".span(", "device_span(",
                  "StageTimer")
_DEVICE_MARKERS = ("jax.", "jnp.")


def _python_files():
    for target in SCAN_TARGETS:
        if os.path.isfile(target):
            yield target
            continue
        for dirpath, _dirs, files in os.walk(target):
            if "__pycache__" in dirpath:
                continue
            for f in files:
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f)


def _timing_calls(tree: ast.AST) -> list[ast.Call]:
    """Call nodes that are time.perf_counter() / time.time()."""
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and (node.func.value.id,
                     node.func.attr) in _TIMING_FNS):
            out.append(node)
    return out


def _enclosing_function(tree: ast.AST, call: ast.Call):
    """The innermost FunctionDef containing ``call`` (None = module)."""
    best = None
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if (node.lineno <= call.lineno <= (node.end_lineno or node.lineno)
                and (best is None or node.lineno > best.lineno)):
            best = node
    return best


def _segment(src_lines: list[str], node) -> str:
    if node is None:
        return "".join(src_lines)  # module scope
    return "".join(src_lines[node.lineno - 1:node.end_lineno])


def test_no_unfenced_device_timing():
    violations = []
    for path in _python_files():
        if path in EXEMPT:
            continue
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        tree = ast.parse(src)
        src_lines = src.splitlines(keepends=True)
        seen_scopes = set()
        for call in _timing_calls(tree):
            fn = _enclosing_function(tree, call)
            scope_key = (path, fn.lineno if fn else 0)
            if scope_key in seen_scopes:
                continue
            seen_scopes.add(scope_key)
            seg = _segment(src_lines, fn)
            touches_device = any(m in seg for m in _DEVICE_MARKERS)
            fenced = any(m in seg for m in _FENCE_MARKERS)
            if touches_device and not fenced:
                name = fn.name if fn else "<module>"
                violations.append(
                    f"{os.path.relpath(path, ROOT)}:{call.lineno} "
                    f"in {name}()")
    assert not violations, (
        "unfenced wall-clock timing next to device code (time the work "
        "through a span with a fence, or block_until_ready before "
        "reading the clock):\n  " + "\n  ".join(violations))


def test_guard_scans_a_nontrivial_tree():
    """The guard is only worth its pass if it actually scanned the files
    it claims to police (a refactor that breaks the walk must not turn
    this into a vacuous green)."""
    files = list(_python_files())
    assert len(files) > 40
    assert any(p.endswith("bench.py") for p in files)
    assert any(os.path.join("harness", "fleet.py") in p for p in files)
    # The round-13 service hot loop is inside the scanned tree (its
    # deadline clock reads are the newest instance of the footgun).
    assert any(os.path.join("harness", "service.py") in p for p in files)
    # Round 15: the device-time observatory's own modules time device
    # work for a living — they are held to the fenced-span rule like
    # everyone else (occupancy's ledger, costmodel's bandwidth probe,
    # the sharded kernel's per-shard observation helpers).
    assert any(os.path.join("obs", "occupancy.py") in p for p in files)
    assert any(os.path.join("obs", "costmodel.py") in p for p in files)
    assert any(os.path.join("parallel", "sharded_kernel.py") in p
               for p in files)
    # Round 16: the streaming pipeline's block loop is one long span of
    # overlapped async dispatch — the single place a bare clock next to
    # device code would be MOST tempting and MOST wrong (it would time
    # dispatch of the whole loop, not its execution).
    assert any(os.path.join("sim", "streaming.py") in p for p in files)
    # Round 18: the decision ledger sits directly beside the shadow
    # lanes of the compiled tick — host recording next to device code
    # is exactly where an un-fenced clock would sneak in.
    assert any(os.path.join("obs", "decisions.py") in p for p in files)
    # Round 21: the async scrape transport deals in REAL deadlines
    # (pool waits on the monotonic clock) — it must stay inside the
    # scanned tree so any future jax import there turns its bare
    # clocks into violations.
    assert any(os.path.join("signals", "transport.py") in p
               for p in files)
    # Round 22: the adversarial search's CEM loop and the scenario-axis
    # source both sit one call away from compiled device programs — a
    # bare clock timing a `scorer.score` dispatch would measure launch,
    # not execution, so the search tree rides the same scan.
    assert any(os.path.join("search", "adversarial.py") in p
               for p in files)
    assert any(os.path.join("search", "axis.py") in p for p in files)
    # Round 23: the continual-learning flywheel distills and evaluates
    # compiled programs (factory cells, the paired neural replays) and
    # its runner drives full fleet-service windows — training-loop
    # timing next to device dispatch is the classic place a bare clock
    # would measure launch latency and call it learning progress.
    assert any(os.path.join("train", "flywheel.py") in p for p in files)
    assert any(os.path.join("train", "mining.py") in p for p in files)
    assert any(os.path.join("harness", "flywheel.py") in p
               for p in files)
    assert any(os.path.join("search", "params.py") in p for p in files)


def test_scrape_transport_is_device_free():
    """Round-21 satellite: `signals/transport.py` reads the monotonic
    clock for a living (budget-edge arithmetic around socket waits) and
    passes the device-timing guard ONLY because it holds no device
    code. Pin that condition directly: the module must keep its bare
    timing calls (they are the contract) and must reference no jax —
    the day someone dispatches device work from the fan-in pool, the
    scoped guard above starts failing and this test says why."""
    path = os.path.join(ROOT, "ccka_tpu", "signals", "transport.py")
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    tree = ast.parse(src)
    assert _timing_calls(tree) or "time.monotonic" in src, (
        "the transport lost its deadline clock — the budget-edge "
        "contract needs one")
    assert not any(m in src for m in _DEVICE_MARKERS), (
        "signals/transport.py references device code — its bare "
        "deadline clocks are only legal while it stays host-only")
    assert "import jax" not in src


_HARNESS_DIR = os.path.join(ROOT, "ccka_tpu", "harness")


def _apply_all_calls(tree: ast.AST) -> list[int]:
    """Line numbers of ``<expr>.apply_all(...)`` call sites."""
    return [node.lineno for node in ast.walk(tree)
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "apply_all"]


def test_no_direct_apply_all_in_harness():
    """Round-12 guard: harness code must route NodePool actuation through
    `actuation.reconcile.Reconciler.converge` — a direct `sink.apply_all`
    is one-shot fire-and-hope, loses the retry/read-back/divergence
    discipline, and silently bypasses the degraded-mode surface. The
    one-shot verbs stay available to CLI demo commands and tests; the
    *control loops* (controller, fleet, lifecycle) may not use them."""
    violations = []
    for dirpath, _dirs, files in os.walk(_HARNESS_DIR):
        if "__pycache__" in dirpath:
            continue
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(dirpath, f)
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
            for lineno in _apply_all_calls(tree):
                violations.append(
                    f"{os.path.relpath(path, ROOT)}:{lineno}")
    assert not violations, (
        "direct sink.apply_all call(s) in harness code — route actuation "
        "through Reconciler.converge (actuation/reconcile.py):\n  "
        + "\n  ".join(violations))


def test_apply_all_guard_catches_the_pattern():
    """Self-test: the banned one-shot call is flagged; the reconciled
    form passes."""
    bad = ("def tick(self, patches):\n"
           "    return self.sink.apply_all(patches)\n")
    good = ("def tick(self, patches):\n"
            "    return self.reconciler.converge(patches).results\n")
    assert _apply_all_calls(ast.parse(bad))
    assert not _apply_all_calls(ast.parse(good))


# -- RunLog event-name registry guard (round 14) ----------------------------
#
# The incident timeline (`obs/incidents.py`) joins RunLog records with
# trace spans and recorder dumps on tick keys and TRUSTS event names as
# schema identifiers. Free-text names would silently fork the schema,
# so every `.event("name", ...)` literal in the tree must come from the
# declared registry (`obs.runlog.RUNLOG_EVENTS` — RunLog.event also
# enforces this at write time; the static guard catches call sites that
# never run in CI).

_RUNLOG_SCAN_TARGETS = SCAN_TARGETS + (os.path.join(ROOT, "scripts"),)


def _event_name_literals(tree: ast.AST) -> list[tuple[int, str]]:
    """(lineno, name) for every ``<expr>.event("literal", ...)`` call.
    Non-literal first args can't be checked statically — the runtime
    check in RunLog.event covers those."""
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "event"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.append((node.lineno, node.args[0].value))
    return out


def test_runlog_event_names_registered():
    from ccka_tpu.obs.runlog import RUNLOG_EVENTS

    violations = []
    for target in _RUNLOG_SCAN_TARGETS:
        paths = [target] if os.path.isfile(target) else [
            os.path.join(dirpath, f)
            for dirpath, _dirs, files in os.walk(target)
            if "__pycache__" not in dirpath
            for f in sorted(files) if f.endswith(".py")]
        for path in paths:
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
            for lineno, name in _event_name_literals(tree):
                if name not in RUNLOG_EVENTS:
                    violations.append(
                        f"{os.path.relpath(path, ROOT)}:{lineno} "
                        f"event {name!r}")
    assert not violations, (
        "unregistered RunLog event name(s) — add them to "
        "obs.runlog.RUNLOG_EVENTS next to the writer (the incident "
        "timeline join trusts event names as schema):\n  "
        + "\n  ".join(violations))


def test_runlog_guard_scans_the_writers():
    """The registry guard is only worth its pass if it sees the files
    that actually write run logs — the training drivers, the CLI, and
    the scripts tree."""
    paths = []
    for target in _RUNLOG_SCAN_TARGETS:
        if os.path.isfile(target):
            paths.append(target)
        else:
            for dirpath, _dirs, files in os.walk(target):
                paths += [os.path.join(dirpath, f) for f in files
                          if f.endswith(".py")]
    assert any(p.endswith(os.path.join("train", "flagship.py"))
               for p in paths)
    assert any(p.endswith("train_replay_flagship.py") for p in paths)
    assert any(p.endswith("cli.py") for p in paths)


def test_runlog_guard_catches_the_pattern():
    """Self-test: an unregistered literal is flagged; registered and
    non-literal (runtime-checked) forms pass."""
    from ccka_tpu.obs.runlog import RUNLOG_EVENTS

    bad = ast.parse('rl.event("totally_new_event", x=1)\n')
    hits = _event_name_literals(bad)
    assert hits and hits[0][1] not in RUNLOG_EVENTS
    good = ast.parse('rl.event("gen", x=1)\n')
    assert all(name in RUNLOG_EVENTS
               for _ln, name in _event_name_literals(good))
    dynamic = ast.parse("rl.event(name, x=1)\n")
    assert not _event_name_literals(dynamic)


def test_guard_catches_the_footgun_pattern(tmp_path):
    """Self-test on a synthetic violation: the exact VERDICT weak-#2
    pattern must be flagged, and its fenced fix must pass."""
    bad = (
        "import time\n"
        "import jax.numpy as jnp\n"
        "def bench(f, x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = f(jnp.asarray(x))\n"
        "    return time.perf_counter() - t0\n")
    good = bad.replace("    return time.perf_counter() - t0\n",
                       "    jax.block_until_ready(y)\n"
                       "    return time.perf_counter() - t0\n")

    def violations_of(src):
        tree = ast.parse(src)
        lines = src.splitlines(keepends=True)
        out = []
        for call in _timing_calls(tree):
            fn = _enclosing_function(tree, call)
            seg = _segment(lines, fn)
            if (any(m in seg for m in _DEVICE_MARKERS)
                    and not any(m in seg for m in _FENCE_MARKERS)):
                out.append(call.lineno)
        return out

    assert violations_of(bad), "guard missed the canonical footgun"
    assert not violations_of(good), "guard flagged the fenced fix"

    # Round-13 variant: an un-fenced monotonic deadline check around a
    # device dispatch (the service hot-loop shape) must be flagged; the
    # span-fenced service form must pass.
    bad_mono = (
        "import time\n"
        "import jax.numpy as jnp\n"
        "def tick(f, x, deadline):\n"
        "    if time.monotonic() > deadline:\n"
        "        return None\n"
        "    return f(jnp.asarray(x))\n")
    good_mono = bad_mono.replace(
        "def tick(f, x, deadline):\n",
        "def tick(self, f, x, deadline):\n"
        "    with self.tracer.span('service.tick'):\n"
        "        pass\n")
    assert violations_of(bad_mono), "guard missed un-fenced monotonic"
    assert not violations_of(good_mono), "guard flagged the span form"

    # Round-15 variant (the observatory's own shape): a per-stage
    # pipeline timer that reads perf_counter around a kernel launch
    # WITHOUT a fence would record dispatch, not execution — flagged;
    # the device_span form `obs/occupancy.py` actually uses passes.
    bad_stage = (
        "import time\n"
        "import jax\n"
        "def measure_stage(kernel_fn, stream):\n"
        "    t0 = time.perf_counter()\n"
        "    out = kernel_fn(jax.device_put(stream))\n"
        "    return out, time.perf_counter() - t0\n")
    good_stage = (
        "import time\n"
        "import jax\n"
        "def measure_stage(tracer, kernel_fn, stream):\n"
        "    with tracer.device_span('pipeline.kernel') as sp:\n"
        "        out = kernel_fn(jax.device_put(stream))\n"
        "        sp.fence(out)\n"
        "    return out, sp.dur_s\n")
    assert violations_of(bad_stage), \
        "guard missed the un-fenced occupancy-timer shape"
    assert not violations_of(good_stage), \
        "guard flagged the fenced occupancy ledger form"


def test_observatory_modules_time_only_through_spans():
    """Round-15 satellite self-check (extended round 16 to the
    streaming pipeline): the observatory modules and sim/streaming.py
    contain NO bare timing calls at all — every duration they record
    comes out of a closed Span (`sp.dur_s`), so the fenced-span rule
    holds by construction, not just by the scoped heuristic above.
    costmodel.py's bandwidth probe is the one allowed direct timer —
    and it must carry its fence in the same scope."""
    for rel in (os.path.join("ccka_tpu", "obs", "occupancy.py"),
                os.path.join("ccka_tpu", "sim", "streaming.py"),
                os.path.join("ccka_tpu", "parallel",
                             "sharded_kernel.py"),
                # Round 18: the decision ledger records strictly after
                # each tick's decisions and must never time anything
                # itself — zero bare clocks, like the occupancy ledger.
                os.path.join("ccka_tpu", "obs", "decisions.py")):
        path = os.path.join(ROOT, rel)
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
        assert not _timing_calls(tree), (
            f"{rel} reads a wall clock directly — observatory timing "
            "must come from closed spans (sp.dur_s)")
    cm = os.path.join(ROOT, "ccka_tpu", "obs", "costmodel.py")
    with open(cm, encoding="utf-8") as fh:
        src = fh.read()
    tree = ast.parse(src)
    lines = src.splitlines(keepends=True)
    for call in _timing_calls(tree):
        fn = _enclosing_function(tree, call)
        seg = _segment(lines, fn)
        assert any(m in seg for m in _FENCE_MARKERS), (
            "costmodel.py times device work without a fence at line "
            f"{call.lineno}")


def test_streaming_block_loop_is_span_fenced():
    """Round-16 satellite: the streaming driver's pipelined block loop
    must live inside a ``device_span`` whose CLOSING fence drains the
    whole pipeline — a fence inside the loop would serialize exactly
    the overlap being measured, and no fence at all would time
    dispatch. Checked structurally: `_run_group`'s pipelined branch
    opens a device_span, calls ``sp.fence`` on the loop's output, and
    the loop body itself contains no ``block_until_ready`` or
    mid-loop ``.fence(`` on intermediate blocks."""
    path = os.path.join(ROOT, "ccka_tpu", "sim", "streaming.py")
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    tree = ast.parse(src)
    run_group = next(n for n in ast.walk(tree)
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "_run_group")
    seg = "".join(src.splitlines(keepends=True)[
        run_group.lineno - 1:run_group.end_lineno])
    assert "device_span" in seg and "sp.fence(out)" in seg
    # The pipelined for-loop body must not fence: find the loop that
    # calls fns.step inside the device_span `with` and check it.
    loops = [n for n in ast.walk(run_group) if isinstance(n, ast.For)]
    assert loops, "streaming block loop disappeared — update this test"
    for loop in loops:
        body_src = "".join(src.splitlines(keepends=True)[
            loop.lineno - 1:loop.end_lineno])
        assert "block_until_ready" not in body_src, (
            "a fence inside the streaming block loop serializes the "
            "overlap the pipeline exists to create")
