"""Fault-injection subsystem tests (ISSUE 5, ARCHITECTURE §12).

Four contracts:

- **Zero-fault bitwise gate**: with faults DISABLED the packed stream and
  every consumer take the exact pre-fault code path — bitwise identical
  arrays/summaries, protecting every recorded BASELINE/BENCH number and
  the PR 3/4 paired-PRNG invariants. The enabled-but-neutral "off"
  preset additionally pins exo-row bitwise identity plus summary
  equality to 1e-5 (the fault-mode kernel is a DIFFERENT XLA program, so
  fusion may differ by 1 ulp — measured; anything beyond that is a bug).
- **Kernel↔lax fault parity**: the megakernel's fault path (hazard,
  denial, delay, stale observation) matches `dynamics.step(fault=)` +
  the faults-threaded lax rollout on the same lanes, deterministic
  interpret mode.
- **Paired realization**: the same (seed, shard) gives the same fault
  lanes — across 8 interpret-mode shards, and for every policy scored on
  one stream (rule vs plan-playback vs carbon see one storm).
- **Degraded-mode controller**: stale scrapes drive ok → hold-last-action
  → rule-fallback → recovery without a crash, and the state is exported
  through promexport.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import (FAULT_PRESETS, ConfigError, FaultsConfig,
                             FrameworkConfig, default_config)
from ccka_tpu.faults import (FaultStep, fault_rows, has_fault_lanes,
                             sample_fault_steps, unpack_fault_lanes)
from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy
from ccka_tpu.policy.rule import offpeak_action, peak_action
from ccka_tpu.signals.synthetic import SyntheticSignalSource
from ccka_tpu.sim import SimParams, initial_state
from ccka_tpu.sim.dynamics import ExoStep, step
from ccka_tpu.sim.megakernel import (
    _exo_rows,
    carbon_megakernel_summary_from_packed,
    megakernel_summary_from_packed,
    pack_plan,
    plan_megakernel_summary_from_packed,
    unpack_exo,
)
from ccka_tpu.sim.rollout import (batched_rollout_summary, exo_steps,
                                  observed_exo)

STEPS, B, T_CHUNK, B_BLOCK = 48, 16, 8, 8
KERNEL_KW = dict(stochastic=False, b_block=B_BLOCK, t_chunk=T_CHUNK,
                 interpret=True)


def _src(cfg, faults=None):
    return SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                 cfg.signals, faults=faults)


@pytest.fixture(scope="module")
def streams(cfg):
    """One generation key, three stream variants (shape-shared so the
    interpret-mode kernel compiles once per entry point)."""
    key = jax.random.key(5)
    return {
        "plain": _src(cfg).packed_trace_device(
            STEPS, key, B, t_chunk=T_CHUNK),
        "off": _src(cfg, FAULT_PRESETS["off"]).packed_trace_device(
            STEPS, key, B, t_chunk=T_CHUNK),
        "severe": _src(cfg, FAULT_PRESETS["severe"]).packed_trace_device(
            STEPS, key, B, t_chunk=T_CHUNK),
    }


class TestConfig:
    def test_presets_validate(self):
        for name, preset in FAULT_PRESETS.items():
            preset.validate()
            assert preset.enabled, name

    def test_roundtrip_and_overrides(self, cfg):
        c2 = cfg.with_overrides(**{"faults.enabled": True,
                                   "faults.ice_frac": 0.2})
        assert c2.faults.enabled and c2.faults.ice_frac == 0.2
        c3 = FrameworkConfig.from_json(c2.to_json())
        assert c3.faults == c2.faults

    def test_bad_values_rejected(self):
        with pytest.raises(ConfigError):
            FaultsConfig(delay_jitter_frac=0.95).validate()
        with pytest.raises(ConfigError):
            FaultsConfig(ice_frac=1.0).validate()
        with pytest.raises(ConfigError):
            FaultsConfig(outage_mean_ticks=0).validate()


class TestLanes:
    def test_disabled_is_bitwise_pre_fault_stream(self, cfg):
        """THE zero-fault gate, stream half: FaultsConfig(enabled=False)
        emits the exact pre-PR stream — same shape, same bits. Tiny
        standalone shape: a disabled source compiles its own generation
        program, and recompiling the full fixture shape twice would buy
        nothing (the comparison is generation-level, not kernel-level)."""
        key = jax.random.key(5)
        plain = _src(cfg).packed_trace_device(16, key, 4, t_chunk=8)
        disabled = _src(cfg, FaultsConfig(enabled=False)) \
            .packed_trace_device(16, key, 4, t_chunk=8)
        assert plain.shape == disabled.shape
        assert np.array_equal(np.asarray(plain), np.asarray(disabled))

    def test_widened_exo_rows_bitwise_and_lanes_neutral(self, cfg,
                                                        streams):
        Z = cfg.cluster.n_zones
        base = _exo_rows(Z)
        for name in ("off", "severe"):
            assert streams[name].shape[1] == base + fault_rows(Z)
            assert np.array_equal(np.asarray(streams["plain"]),
                                  np.asarray(streams[name][:, :base]))
        lanes = np.asarray(streams["off"][:STEPS, base:])
        assert np.all(lanes[:, 0:Z] == 1.0)          # hazard neutral
        assert np.all(lanes[:, Z:Z + 3] == 0.0)      # deny/delay/stale

    def test_severe_lanes_in_range(self, cfg, streams):
        Z = cfg.cluster.n_zones
        fs = unpack_fault_lanes(streams["severe"], STEPS, Z)
        haz = np.asarray(fs.preempt_hazard)
        assert haz.min() >= 1.0 and haz.max() > 1.0
        deny = np.asarray(fs.deny_frac)
        assert deny.min() >= 0.0 and deny.max() <= 1.0
        delay = np.asarray(fs.delay_frac)
        assert delay.min() >= 0.0 and delay.max() <= 0.9
        stale = np.asarray(fs.signal_stale)
        assert set(np.unique(stale)) <= {0.0, 1.0}
        # Window fractions near the configured rates (loose — finite T).
        p = FAULT_PRESETS["severe"]
        assert 0.0 < stale.mean() < 4 * p.outage_frac
        assert 0.0 < (deny > 0).mean() < 4 * p.ice_frac

    def test_bad_row_count_rejected(self, cfg, streams):
        Z = cfg.cluster.n_zones
        assert has_fault_lanes(streams["severe"], Z)
        assert not has_fault_lanes(streams["plain"], Z)
        with pytest.raises(ValueError, match="rows"):
            has_fault_lanes(streams["plain"][:, :-1], Z)

    def test_replay_packed_stream_carries_lanes(self, cfg):
        from ccka_tpu.signals.base import TraceMeta
        from ccka_tpu.signals.replay import ReplaySignalSource

        stored = _src(cfg).trace(48, seed=3)
        meta = TraceMeta(source="replay", start_unix_s=0.0, dt_s=30.0,
                         zones=cfg.cluster.zones)
        Z = cfg.cluster.n_zones
        key = jax.random.key(9)
        plain = ReplaySignalSource(stored, meta).packed_trace_device(
            16, key, 4, t_chunk=8)
        faulted = ReplaySignalSource(
            stored, meta,
            faults=FAULT_PRESETS["severe"]).packed_trace_device(
            16, key, 4, t_chunk=8)
        assert plain.shape[1] == _exo_rows(Z)
        assert faulted.shape[1] == _exo_rows(Z) + fault_rows(Z)
        # Same key → same windows: exo rows bitwise shared.
        assert np.array_equal(np.asarray(plain),
                              np.asarray(faulted[:, :_exo_rows(Z)]))


class TestZeroFaultGate:
    def test_lax_neutral_fault_step_bitwise(self, cfg):
        """step(fault=FaultStep.neutral) == step(fault=None), bitwise —
        state AND metrics' shared fields, stochastic mode included."""
        params = SimParams.from_config(cfg)
        src = _src(cfg)
        tr = src.trace(1, seed=0)
        exo = jax.tree.map(lambda x: x[0], exo_steps(tr))
        st = initial_state(cfg)
        act = RulePolicy(cfg.cluster).decide(st, exo, jnp.int32(0))
        key = jax.random.key(7)
        neutral = FaultStep.neutral(cfg.cluster.n_zones)
        s1, m1 = jax.jit(lambda: step(params, st, act, exo, key,
                                      stochastic=True))()
        s2, m2 = jax.jit(lambda: step(params, st, act, exo, key,
                                      stochastic=True, fault=neutral))()
        for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s2)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        for f in m1._fields:
            assert np.array_equal(np.asarray(getattr(m1, f)),
                                  np.asarray(getattr(m2, f))), f

    def test_kernel_disabled_stream_bitwise(self, cfg):
        """Disabled faults → un-widened stream → the pre-fault kernel
        program — summaries bitwise identical to the plain pipeline,
        end to end (tiny standalone shape: generation AND kernel both
        re-run from a disabled-config source)."""
        params = SimParams.from_config(cfg)
        off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
        key = jax.random.key(5)
        kw = dict(stochastic=False, b_block=4, t_chunk=8, interpret=True)
        s1 = megakernel_summary_from_packed(
            params, off, peak,
            _src(cfg).packed_trace_device(16, key, 4, t_chunk=8),
            16, seed=3, **kw)
        s2 = megakernel_summary_from_packed(
            params, off, peak,
            _src(cfg, FaultsConfig(enabled=False)).packed_trace_device(
                16, key, 4, t_chunk=8),
            16, seed=3, **kw)
        for f in s1._fields:
            assert np.array_equal(np.asarray(getattr(s1, f)),
                                  np.asarray(getattr(s2, f))), f

    def test_kernel_neutral_lanes_match_plain(self, cfg, streams):
        """The enabled-but-neutral 'off' preset: the fault-mode kernel on
        neutral lanes reproduces the plain kernel to 1e-5 (different XLA
        program → up to ~1 ulp of fusion skew; measured 1e-7) with the
        fault counters exactly zero."""
        params = SimParams.from_config(cfg)
        off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
        s1 = megakernel_summary_from_packed(
            params, off, peak, streams["plain"], STEPS, seed=3,
            **KERNEL_KW)
        s2 = megakernel_summary_from_packed(
            params, off, peak, streams["off"], STEPS, seed=3, **KERNEL_KW)
        for f in s1._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(s2, f)), np.asarray(getattr(s1, f)),
                rtol=1e-5, atol=1e-6, err_msg=f)
        assert np.all(np.asarray(s2.denials) == 0.0)
        assert np.all(np.asarray(s2.stale_ticks) == 0.0)


class TestFaultDynamics:
    """Lax-side semantics of each disturbance channel."""

    def _exo0(self, cfg, src):
        tr = src.trace(1, seed=0)
        return jax.tree.map(lambda x: x[0], exo_steps(tr))

    def test_full_denial_blocks_spot_provisioning(self, cfg):
        params = SimParams.from_config(cfg)
        src = _src(cfg)
        exo = self._exo0(cfg, src)
        st = initial_state(cfg)
        act = RulePolicy(cfg.cluster).decide(st, exo, jnp.int32(0))
        Z = cfg.cluster.n_zones
        deny_all = FaultStep.neutral(Z)._replace(
            deny_frac=jnp.float32(1.0))
        key = jax.random.key(0)
        stepf = jax.jit(lambda s_, f: step(params, s_, act, exo, key,
                                           fault=f))
        st_f = st
        for _t in range(4):
            st_f, m_f = stepf(st_f, deny_all)
        st_n, m_n = stepf(st, FaultStep.neutral(Z))
        assert float(m_f.denied_nodes) > 0.0
        # Everything denied: no spot capacity ever enters the pipeline.
        assert float(np.asarray(st_f.pipeline)[..., 0].sum()) == 0.0
        assert float(np.asarray(st_n.pipeline)[..., 0].sum()) > 0.0

    def test_delay_holds_arrivals(self, cfg):
        params = SimParams.from_config(cfg)
        src = _src(cfg)
        exo = self._exo0(cfg, src)
        st = initial_state(cfg)
        act = RulePolicy(cfg.cluster).decide(st, exo, jnp.int32(0))
        Z = cfg.cluster.n_zones
        half = FaultStep.neutral(Z)._replace(delay_frac=jnp.float32(0.5))
        key = jax.random.key(0)
        k = params.provision_pipeline_k
        st_d = st_n = st
        for t in range(k + 1):
            st_d, m_d = step(params, st_d, act, exo, key, fault=half)
            st_n, m_n = step(params, st_n, act, exo, key)
        # By tick k+1 the no-fault path has landed its first arrivals in
        # full; the delayed path held half of them back.
        assert float(m_d.delayed_nodes) > 0.0
        assert (float(np.asarray(st_d.nodes).sum())
                < float(np.asarray(st_n.nodes).sum()))

    def test_hazard_scales_interruptions(self, cfg):
        params = SimParams.from_config(cfg)
        src = _src(cfg)
        exo = self._exo0(cfg, src)
        Z = cfg.cluster.n_zones
        st = initial_state(cfg)._replace(
            nodes=jnp.ones((cfg.cluster.n_pools, Z, 2), jnp.float32))
        act = RulePolicy(cfg.cluster).decide(st, exo, jnp.int32(0))
        key = jax.random.key(0)
        _, m1 = step(params, st, act, exo, key,
                     fault=FaultStep.neutral(Z))
        _, m3 = step(params, st, act, exo, key,
                     fault=FaultStep.neutral(Z)._replace(
                         preempt_hazard=jnp.full((Z,), 3.0)))
        # Deterministic mode: interruptions are the mean — exactly 3x.
        np.testing.assert_allclose(float(m3.interrupted_nodes),
                                   3.0 * float(m1.interrupted_nodes),
                                   rtol=1e-5)

    def test_observed_exo_holds_signals_not_clock(self, cfg):
        src = _src(cfg)
        xs = exo_steps(src.trace(2, seed=0))
        e0 = jax.tree.map(lambda x: x[0], xs)
        e1 = jax.tree.map(lambda x: x[1], xs)
        held = observed_exo(e0, e1, jnp.float32(1.0))
        assert np.array_equal(np.asarray(held.demand_pods),
                              np.asarray(e0.demand_pods))
        assert np.array_equal(np.asarray(held.spot_price_hr),
                              np.asarray(e0.spot_price_hr))
        # is_peak is clock-derived: never held.
        assert np.array_equal(np.asarray(held.is_peak),
                              np.asarray(e1.is_peak))
        fresh = observed_exo(e0, e1, jnp.float32(0.0))
        assert np.array_equal(np.asarray(fresh.demand_pods),
                              np.asarray(e1.demand_pods))

    def test_sample_fault_steps_matches_presets(self, cfg):
        Z = cfg.cluster.n_zones
        fs = jax.jit(lambda k: sample_fault_steps(
            FAULT_PRESETS["severe"], k, 64, Z))(jax.random.key(3))
        assert fs.preempt_hazard.shape == (64, Z)
        assert fs.deny_frac.shape == (64,)
        neutral = jax.jit(lambda k: sample_fault_steps(
            FAULT_PRESETS["off"], k, 64, Z))(jax.random.key(3))
        assert np.all(np.asarray(neutral.preempt_hazard) == 1.0)
        assert np.all(np.asarray(neutral.signal_stale) == 0.0)


class TestKernelLaxFaultParity:
    """The fault-mode kernel against the faults-threaded lax rollout on
    the SAME lanes — deterministic interpret mode, so agreement is
    float-tolerance, not distribution-level."""

    def _lax(self, cfg, params, stream, action_fn):
        Z = cfg.cluster.n_zones
        traces = unpack_exo(stream, STEPS, Z)
        faults = unpack_fault_lanes(stream, STEPS, Z)
        states = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (B,) + x.shape),
            initial_state(cfg))
        keys = jax.random.split(jax.random.key(0), B)
        _, s = batched_rollout_summary(params, states, action_fn, traces,
                                       keys, stochastic=False,
                                       faults=faults)
        return s

    def _assert_close(self, sk, sl):
        for f in sk._fields:
            a, b_ = np.asarray(getattr(sk, f)), np.asarray(getattr(sl, f))
            np.testing.assert_allclose(a, b_, rtol=3e-4, atol=1e-4,
                                       err_msg=f)

    @pytest.mark.slow  # ISSUE 16 lane-time rule: fault-lane neutrality keeps
    # its fast bitwise lane test; the profile run duplicates workloads'.
    def test_rule_profile(self, cfg, streams):
        params = SimParams.from_config(cfg)
        off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
        sk = megakernel_summary_from_packed(
            params, off, peak, streams["severe"], STEPS, seed=3,
            **KERNEL_KW)
        sl = self._lax(cfg, params, streams["severe"],
                       RulePolicy(cfg.cluster).action_fn())
        self._assert_close(sk, sl)
        # The faults actually bit (this is not a trivial pass).
        assert float(np.asarray(sk.denials).mean()) > 0.0
        assert float(np.asarray(sk.stale_ticks).mean()) > 0.0

    @pytest.mark.slow  # duplicates test_rule_profile's kernel<->lax
    # fault machinery; the stale-obs HOLD semantics stay fast-lane via
    # TestFaultDynamics.test_observed_exo_holds_signals_not_clock and the
    # neutral-lane kernel gate — this end-to-end carbon repin rides the
    # slow lane (ISSUE 5 lane-hygiene satellite; ~22s of compiles).
    def test_carbon_policy_stale_observation(self, cfg, streams):
        """Covers the kernel's last_exo hold path end-to-end: the carbon
        policy OBSERVES carbon — under outage windows both sides must
        hold the same pre-outage values or zone weights diverge."""
        params = SimParams.from_config(cfg)
        off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
        cp = CarbonAwarePolicy(cfg.cluster)
        sk = carbon_megakernel_summary_from_packed(
            params, off, peak, streams["severe"], STEPS, seed=3,
            sharpness=cp.sharpness, min_weight=cp.min_weight,
            stickiness=cp.stickiness, **KERNEL_KW)
        sl = self._lax(cfg, params, streams["severe"], cp.action_fn())
        self._assert_close(sk, sl)


class TestPairedRealization:
    """Two policies under one seed see ONE fault realization."""

    def test_rule_vs_plan_playback_same_faulted_world(self, cfg, streams):
        """A rule-replaying per-cluster plan through the playback kernel
        reproduces the profile kernel on the SAME faulted stream — the
        PR 4 pin, extended to fault mode (both consume identical lanes)."""
        import math

        params = SimParams.from_config(cfg)
        off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
        s_rule = megakernel_summary_from_packed(
            params, off, peak, streams["severe"], STEPS, seed=3,
            **KERNEL_KW)
        Z = cfg.cluster.n_zones
        traces = unpack_exo(streams["severe"], STEPS, Z)
        is_peak = traces.is_peak > 0.5
        rule_plan = jax.tree.map(
            lambda o, p: jnp.where(
                is_peak.reshape(is_peak.shape + (1,) * o.ndim), p, o),
            off, peak)
        t_pad = math.ceil(STEPS / T_CHUNK) * T_CHUNK
        s_plan = plan_megakernel_summary_from_packed(
            params, cfg.cluster, pack_plan(rule_plan, t_pad),
            streams["severe"], STEPS, seed=3, **KERNEL_KW)
        for f in s_rule._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(s_plan, f)),
                np.asarray(getattr(s_rule, f)), rtol=1e-5, atol=1e-6,
                err_msg=f)
        # And the policy-independent exposure counter is EXACT.
        assert np.array_equal(np.asarray(s_plan.stale_ticks),
                              np.asarray(s_rule.stale_ticks))

    @pytest.mark.slow  # the 8-shard mesh + kernel compiles cost ~30s
    # and the sharding machinery it exercises is pinned plain-stream in
    # tests/test_sharded_kernel.py; the fast lane keeps the cross-policy
    # paired-realization pin (rule vs plan playback on one faulted
    # stream) — this extends it across shards in the slow lane.
    def test_sharded_generation_lanes_bitwise(self, cfg):
        """8 interpret-mode shards: each shard's fault lanes equal the
        single-device generation with that shard's folded key — the
        PR 3 shard-local pin, extended to the lane block — and the
        sharded rule kernel on the faulted stream matches the
        single-device kernel on the gathered stream."""
        from ccka_tpu.parallel import make_mesh
        from ccka_tpu.parallel.sharded_kernel import (
            sharded_megakernel_summary_from_packed, sharded_packed_trace)

        n_dev = len(jax.devices())
        if n_dev < 8:
            pytest.skip("needs the 8-device virtual CPU mesh")
        from ccka_tpu.config import MeshConfig
        mesh = make_mesh(MeshConfig(data_parallel=8))
        src = _src(cfg, FAULT_PRESETS["severe"])
        key = jax.random.key(11)
        b_loc = 2
        stream = sharded_packed_trace(mesh, src, STEPS, key, 8 * b_loc,
                                      t_chunk=T_CHUNK)
        gathered = np.asarray(stream)
        for shard in range(8):
            # Same reference the PR 3 pin uses: the single-device jitted
            # generation on that shard's folded key (jit-vs-shard_map
            # compilation may differ by float-tolerance, never by
            # realization).
            want = np.asarray(src.packed_trace_device(
                STEPS, jax.random.fold_in(key, shard), b_loc,
                t_chunk=T_CHUNK))
            got = gathered[:, :, shard * b_loc:(shard + 1) * b_loc]
            np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6,
                                       err_msg=f"shard {shard}")
            # The window/indicator lanes are thresholded — bit-exact.
            Z = cfg.cluster.n_zones
            base = _exo_rows(Z)
            assert np.array_equal(got[:, base + Z + 2],
                                  want[:, base + Z + 2]), f"shard {shard}"

        params = SimParams.from_config(cfg)
        off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
        kw = dict(stochastic=False, b_block=b_loc, t_chunk=T_CHUNK,
                  interpret=True)
        s_sh = sharded_megakernel_summary_from_packed(
            mesh, params, off, peak, stream, STEPS, seed=3, **kw)
        s_1d = megakernel_summary_from_packed(
            params, off, peak, jnp.asarray(gathered), STEPS, seed=3,
            **kw)
        for f in s_sh._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(s_sh, f)), np.asarray(getattr(s_1d, f)),
                rtol=1e-5, atol=1e-6, err_msg=f)


class _ScriptedStaleSource(SyntheticSignalSource):
    """Synthetic source whose tick() follows a scripted staleness
    pattern — the degraded-mode controller's test double."""

    def __init__(self, *args, script=(), **kwargs):
        super().__init__(*args, **kwargs)
        self.script = list(script)

    def tick(self, t_index, *, seed=0):
        self.last_scrape_stale = (self.script[t_index]
                                  if t_index < len(self.script) else False)
        return super().tick(t_index, seed=seed)


class TestDegradedController:
    def _controller(self, cfg, script, **kw):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller

        src = _ScriptedStaleSource(cfg.cluster, cfg.workload, cfg.sim,
                                   cfg.signals, script=script)
        lines = []
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, DryRunSink(),
                          interval_s=0.0, log_fn=lines.append, **kw)
        return ctrl, lines

    def test_outage_hold_then_fallback_then_recover(self, cfg):
        """The acceptance scenario: signal outage → hold → rule-fallback
        → recovery, without a crash, state machine on the record."""
        script = [False, False, True, True, True, True, False]
        ctrl, lines = self._controller(cfg, script,
                                       degraded_fallback_after=3)
        reports = ctrl.run(ticks=7)
        assert [r.degraded for r in reports] == [
            "ok", "ok", "hold", "hold", "fallback", "fallback", "ok"]
        assert [r.signal_stale for r in reports] == script
        assert reports[-1].degraded_ticks_total == 4
        # HOLD replays the last measured-data action verbatim.
        held, prev = reports[2], reports[1]
        assert held.profile == "degraded-hold"
        assert (held.nodes_spot, held.nodes_od) is not None  # no crash
        # FALLBACK runs the rule policy (profile names it).
        assert reports[4].profile.startswith("degraded-fallback:")
        # Recovery returns to the primary backend's profile.
        assert reports[6].profile in ("offpeak", "peak")
        # Transitions are logged for the operator.
        assert any("degraded-mode: ok -> hold" in ln for ln in lines)
        assert any("degraded-mode: hold -> fallback" in ln
                   for ln in lines)

    def test_hold_applies_identical_action(self, cfg):
        ctrl, _ = self._controller(cfg, [False, True],
                                   degraded_fallback_after=3)
        r0 = ctrl.tick(0)
        spot_pool = cfg.cluster.pools[0].name
        before = ctrl.sink.observed_state(spot_pool)
        r1 = ctrl.tick(1)
        after = ctrl.sink.observed_state(spot_pool)
        assert r1.degraded == "hold"
        assert before == after  # the held action re-renders identically

    def test_stale_from_tick_zero_goes_straight_to_fallback(self, cfg):
        """No held action yet → never decide on garbage: fall back."""
        ctrl, _ = self._controller(cfg, [True, True],
                                   degraded_fallback_after=5)
        reports = ctrl.run(ticks=2)
        assert [r.degraded for r in reports] == ["fallback", "fallback"]

    def test_degraded_state_exported_via_promexport(self, cfg):
        from ccka_tpu.harness.promexport import render_exposition

        ctrl, _ = self._controller(cfg, [True], degraded_fallback_after=1)
        report = ctrl.tick(0)
        text = render_exposition(report)
        assert "ccka_degraded 2" in text
        assert "ccka_degraded_ticks_total 1" in text
        assert "ccka_signal_stale 1" in text
        assert "ccka_nodes_denied 0" in text


class TestRetryingFetch:
    def _flaky(self, fail_n, exc=OSError("boom")):
        calls = []

        def fetch(url, headers):
            calls.append(url)
            if len(calls) <= fail_n:
                raise exc
            return b"ok"
        return fetch, calls

    def test_retries_then_succeeds_with_jittered_backoff(self):
        from ccka_tpu.signals.live import RetryingFetch

        fetch, calls = self._flaky(2)
        sleeps = []
        rf = RetryingFetch(fetch, retries=3, backoff_s=0.1,
                           deadline_s=10.0, sleep=sleeps.append,
                           clock=lambda: 0.0)
        assert rf("http://x", {}) == b"ok"
        assert len(calls) == 3 and len(sleeps) == 2
        # Full jitter around exponential doubling: 0.1*2^i*[0.5, 1.5).
        assert 0.05 <= sleeps[0] < 0.15
        assert 0.10 <= sleeps[1] < 0.30

    def test_exhaustion_reraises_last_error(self):
        from ccka_tpu.signals.live import RetryingFetch

        fetch, calls = self._flaky(99, exc=TimeoutError("t"))
        rf = RetryingFetch(fetch, retries=2, backoff_s=0.0,
                           deadline_s=10.0, sleep=lambda s: None,
                           clock=lambda: 0.0)
        with pytest.raises(TimeoutError):
            rf("http://x", {})
        assert len(calls) == 3

    def test_deadline_bounds_the_budget(self):
        from ccka_tpu.signals.live import RetryingFetch

        fetch, calls = self._flaky(99)
        t = {"now": 0.0}

        def clock():
            return t["now"]

        def sleep(s):
            t["now"] += s

        rf = RetryingFetch(fetch, retries=10, backoff_s=4.0,
                           deadline_s=10.0, sleep=sleep, clock=clock)
        with pytest.raises(OSError):
            rf("http://x", {})
        # Sleeps never push past the deadline: the budget caps attempts
        # far below retries+1.
        assert t["now"] <= 10.0 + 1e-9
        assert len(calls) < 11

    def test_live_tick_marks_stale_instead_of_raising(self, cfg):
        from ccka_tpu.signals.live import LiveSignalSource

        def dead_fetch(url, headers):
            raise OSError("connection refused")

        cfg2 = cfg.with_overrides(**{"signals.fetch_backoff_s": 0.0,
                                     "signals.fetch_retries": 1})
        src = LiveSignalSource(cfg2.cluster, cfg2.workload, cfg2.sim,
                               cfg2.signals, fetch=dead_fetch,
                               start_unix_s=0.0)
        trace = src.tick(0)          # no raise — prior-backed sample
        trace.validate_shapes()
        assert src.last_scrape_stale is True
