"""Claim-drift guard (ISSUE 3 satellite; VERDICT r5 weak #5): the
numbers README's results section and PARITY's performance section state
must match BASELINE.json — the round that made this test necessary had
README still quoting the round-3 flagship (gen 20, ~0.98×/~0.79×, +4 pp)
two rounds after round 5 superseded every one of those numbers.

Quick lane (pure text + json parsing). The regexes pin the CLAIM
PHRASES, deliberately: if a doc rewrite changes how a number is stated,
this test must be updated in the same commit — that is the sync working,
not a false positive. Tolerances are rounding-width only.
"""

from __future__ import annotations

import json
import os
import re

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read(name: str) -> str:
    with open(os.path.join(_ROOT, name), encoding="utf-8") as fh:
        return fh.read()


@pytest.fixture(scope="module")
def baseline() -> dict:
    return json.loads(_read("BASELINE.json"))


@pytest.fixture(scope="module")
def readme() -> str:
    return _read("README.md")


@pytest.fixture(scope="module")
def parity() -> str:
    return _read("PARITY.md")


def _flagship_row(baseline) -> dict:
    return (baseline["published"]["round5"]
            ["north_star_quality_selection_5_fullday_traces"]
            ["multiregion_ppo_flagship"])


class TestFlagshipClaims:
    """The multiregion flagship's headline ratios, attainments and
    selection generation — one source of truth (BASELINE round5)."""

    def test_readme_multiregion_bullet(self, readme, baseline):
        row = _flagship_row(baseline)
        m = re.search(
            r"([\d.]+)×\s+\$/SLO-hour,\s+([\d.]+)×\s+gCO₂/kreq\s+—\s+at"
            r"\s+\+([\d.]+)\s+pp\s+SLO\s+attainment\s*\(([\d.]+)\s+vs"
            r"\s+rule\s+([\d.]+)\)", readme)
        assert m, ("README's multiregion bullet no longer states the "
                   "flagship ratios in the pinned form — update the "
                   "claim AND this regex together")
        usd, co2, pp, attain, rule_attain = map(float, m.groups())
        assert abs(usd - row["vs_rule_usd_per_slo_hour"]) < 5e-3
        assert abs(co2 - row["vs_rule_g_co2_per_kreq"]) < 5e-3
        assert abs(attain - row["slo_attainment"]) < 5e-3
        assert abs(rule_attain - row["rule_attainment"]) < 5e-3
        assert abs(pp - 100 * (row["slo_attainment"]
                               - row["rule_attainment"])) < 0.15

    def test_readme_selection_generation(self, readme, baseline):
        row = _flagship_row(baseline)
        m = re.search(r"selected at generation (\d+)", readme)
        assert m, "README no longer states the selection generation"
        assert f"selected_iteration={m.group(1)}" in row["provenance"], (
            f"README says generation {m.group(1)}; BASELINE round5 "
            f"provenance says {row['provenance']!r}")

    def test_parity_quality_bullet(self, parity, baseline):
        row = _flagship_row(baseline)
        m = re.search(
            r"([\d.]+)×\s+\$/SLO-hour,\s+([\d.]+)×\s+gCO₂/kreq\s+at"
            r"\s+attainment\s+([\d.]+)\s+vs\s+rule\s+([\d.]+)\s+"
            r"\(teacher:\s+([\d.]+)×,\s+([\d.]+)×", parity)
        assert m, ("PARITY's performance section no longer states the "
                   "flagship numbers in the pinned form")
        usd, co2, attain, rule_attain, _t_usd, t_co2 = map(float,
                                                           m.groups())
        assert abs(usd - row["vs_rule_usd_per_slo_hour"]) < 5e-3
        assert abs(co2 - row["vs_rule_g_co2_per_kreq"]) < 5e-3
        assert abs(attain - row["slo_attainment"]) < 5e-3
        assert abs(rule_attain - row["rule_attainment"]) < 5e-3
        assert abs(t_co2 - row["teacher_vs_rule_g_co2_per_kreq"]) < 5e-3


class TestThroughputClaims:
    """The kernel headline (round-4 measured 1,847,836 cluster-days/sec
    at B=32768) — README states a range, PARITY a point value."""

    def test_readme_range_contains_measured(self, readme, baseline):
        measured = baseline["published"]["round4"][
            "sim_cluster_days_per_sec_per_chip"] / 1e6
        m = re.search(r"~([\d.]+)–([\d.]+)\s?M\s+simulated\s+"
                      r"cluster-days/sec", readme)
        assert m, "README no longer states the throughput range"
        lo, hi = float(m.group(1)), float(m.group(2))
        assert lo <= measured <= hi, (
            f"README range {lo}–{hi}M excludes the measured "
            f"{measured:.2f}M")

    def test_parity_point_value(self, parity, baseline):
        measured = baseline["published"]["round4"][
            "sim_cluster_days_per_sec_per_chip"] / 1e6
        m = re.search(r"~([\d.]+)M\s+simulated\s+cluster-days/sec/chip",
                      parity)
        assert m, "PARITY no longer states the throughput point value"
        assert abs(float(m.group(1)) - measured) < 0.01


class TestMultichipClaims:
    """Round 8's multi-chip kernel record: the PARITY bullet must quote
    BASELINE round8's 8-shard aggregate and keep the virtual-mesh label
    next to it (a virtual-CPU number published as a chip number would be
    the worst possible drift)."""

    def test_round8_record_is_self_describing(self, baseline):
        r8 = baseline["published"]["round8"]
        sec = r8["multichip_virtual_mesh"]
        assert sec["virtual_cpu_mesh"] is True
        assert sec["mesh"]["shape"]["data"] == 8
        assert sec["donation"]["ok"] is True
        assert "8dev" in sec["weak_scaling"]

    def test_parity_multichip_bullet(self, parity, baseline):
        sec = (baseline["published"]["round8"]
               ["multichip_virtual_mesh"])
        agg = sec["weak_scaling"]["8dev"]["cluster_days_per_sec_aggregate"]
        m = re.search(r"\*\*Multi-chip kernel\*\*.*?([\d,.]+)\s+"
                      r"cluster-days/sec\s+aggregate", parity, re.S)
        assert m, "PARITY no longer carries the multi-chip bullet"
        quoted = float(m.group(1).replace(",", ""))
        assert abs(quoted - agg) <= 1.0, (
            f"PARITY quotes {quoted}, BASELINE round8 says {agg}")
        bullet = parity[m.start():m.start() + 600]
        assert re.search(r"virtual", bullet, re.I), (
            "the multi-chip bullet lost its virtual-mesh label")


class TestMPCKernelClaims:
    """Round 9's kernel-grade MPC claims: the evidence-standard number
    (n>=256 kernel-paired traces) and the plan-playback throughput
    README/PARITY quote must come from BASELINE.json round9 — and the
    virtual-mesh label must stay welded to the virtual number."""

    def test_round9_record_is_self_describing(self, baseline):
        r9 = baseline["published"]["round9"]
        policy = r9["mpc_flag_policy"]
        assert policy["min_paired_traces"] == 256
        assert "deferred" in policy["lax_stage_flags"]
        assert "quality_mega" in policy["flag_source"]
        pb = r9["multichip_plan_playback"]
        assert pb["virtual_cpu_mesh"] is True and pb["interpret"] is True
        assert pb["mesh"]["shape"]["data"] == 8
        assert pb["donation_ok"] is True
        # No published sample below its physical floor (the acceptance
        # criterion, checked against the record itself).
        for row in (pb, r9["mpc_kernel_playback"]):
            floor_ms = row.get("roofline_floor_ms",
                               row.get("roofline_floor_ms_per_shard"))
            assert row["seconds"] * 1e3 >= floor_ms

    def test_readme_flag_standard(self, readme, baseline):
        m = re.search(r"n≥(\d+)\s+kernel-paired\s+traces", readme)
        assert m, ("README no longer states the MPC kernel evidence "
                   "standard — update the claim AND this regex together")
        assert int(m.group(1)) == (baseline["published"]["round9"]
                                   ["mpc_flag_policy"]
                                   ["min_paired_traces"])

    def test_parity_plan_playback_bullet(self, parity, baseline):
        pb = (baseline["published"]["round9"]
              ["multichip_plan_playback"])
        m = re.search(r"\*\*MPC plan-playback kernel\*\*.*?([\d,.]+)\s+"
                      r"cluster-days/sec\s+aggregate", parity, re.S)
        assert m, "PARITY no longer carries the plan-playback bullet"
        quoted = float(m.group(1).replace(",", ""))
        assert abs(quoted - pb["cluster_days_per_sec_aggregate"]) <= 1.0
        bullet = parity[m.start():m.start() + 900]
        assert re.search(r"virtual", bullet, re.I), (
            "the plan-playback bullet lost its virtual-mesh label")
        m2 = re.search(r"n≥(\d+)\s+kernel-paired\s+traces", bullet)
        assert m2 and int(m2.group(1)) == 256


class TestRobustnessClaims:
    """Round 10's fault-injection scoreboard (ISSUE 5 docs satellite):
    README's robustness claims are PARSED against the BASELINE round10
    record, not hand-synced."""

    def test_round10_record_is_self_describing(self, baseline):
        r10 = baseline["published"]["round10"]
        sb = r10["fault_robustness_scoreboard"]
        assert sb["n_traces"] >= 256
        assert len(sb["intensities"]) >= 4 and "off" in sb["intensities"]
        for policy in ("rule", "flagship", "mpc"):
            curve = sb["vs_calm_usd_per_slo_hour"][policy]
            assert curve[0] == 1.0            # calm denominator
            assert curve[-1] > curve[0]       # severe actually bites
        # Pairing evidence on the record itself: one stream = one fault
        # realization, so the policy-independent exposure counter is
        # identical across every policy row of an intensity.
        stales = {round(sb["severe"][p]["stale_ticks"], 4)
                  for p in ("rule", "flagship", "mpc")}
        assert len(stales) == 1
        assert "bitwise" in r10["zero_fault_bitwise_gate"]
        assert "fallback" in r10["degraded_mode_controller"]

    def test_readme_robustness_claims(self, readme, baseline):
        sb = (baseline["published"]["round10"]
              ["fault_robustness_scoreboard"])
        m = re.search(
            r"rule\s+baseline\s+degrades\s+to\s+([\d.]+)×\s+its\s+calm"
            r"\s+\$/SLO-hour\s+and\s+open-loop\s+MPC-playback\s+to\s+"
            r"([\d.]+)×,\s+while\s+the\s+closed-loop\s+flagship\s+holds"
            r"\s+([\d.]+)×", readme)
        assert m, ("README's robustness claim no longer states the "
                   "degradation ratios in the pinned form — update the "
                   "claim AND this regex together")
        rule_x, mpc_x, flag_x = map(float, m.groups())
        sev = {p: sb["vs_calm_usd_per_slo_hour"][p][-1]
               for p in ("rule", "flagship", "mpc")}
        assert abs(rule_x - sev["rule"]) < 5e-3
        assert abs(mpc_x - sev["mpc"]) < 5e-3
        assert abs(flag_x - sev["flagship"]) < 5e-3
        m2 = re.search(r"n≥(\d+)\s+kernel-paired\s+traces\s+\(BASELINE"
                       r"\s+round10", readme)
        assert m2 and int(m2.group(1)) <= sb["n_traces"]


class TestRecoveryClaims:
    """Round 12's crash-recovery scoreboard (ISSUE 9 docs satellite):
    README's crash-recovery claims are PARSED against the BASELINE
    round12 record, not hand-synced."""

    def test_round12_record_is_self_describing(self, baseline):
        r12 = baseline["published"]["round12"]
        sb = r12["recovery_scoreboard"]
        assert sb["n_paired_runs"] >= 64
        assert len(sb["intensities"]) >= 3 and "off" in sb["intensities"]
        assert set(sb["policies"]) >= {"rule", "flagship"}
        # The invariant holds on the record itself, cell by cell: zero
        # duplicate/lost patches, fully bitwise resume, paired $/SLO-hr
        # ratio exactly 1 — under every intensity, for every policy.
        for name, cell in sb["cells"].items():
            for policy, row in cell["rows"].items():
                assert row["duplicate_patches_total"] == 0, (name, policy)
                assert row["lost_patches_total"] == 0, (name, policy)
                assert row["resume_bitwise_frac"] == 1.0, (name, policy)
                assert row["ticks_to_reconverge_max"] == 0, (name, policy)
                assert row["usd_per_slo_hr_vs_baseline"] == 1.0
        # And the stress was real: the severe cell injected failures and
        # the reconciler actually retried through them.
        sev = sb["cells"]["severe"]["rows"]["rule"]
        assert sum(sev["chaos_injected"].values()) > 0
        assert sev["reconcile_retries_mean"] > 0
        assert "bitwise" in r12["kill_resume_bitwise_gate"]
        assert "command-for-command" in r12["zero_injection_gate"]

    def test_readme_recovery_claims(self, readme, baseline):
        sb = (baseline["published"]["round12"]["recovery_scoreboard"])
        m = re.search(
            r"(\d+)\s+paired\s+kill/no-kill\s+runs\s+\(BASELINE"
            r"\s+round12", readme)
        assert m, ("README's recovery claim no longer states the paired-"
                   "run count in the pinned form — update the claim AND "
                   "this regex together")
        assert int(m.group(1)) == sb["n_paired_runs"]
        m2 = re.search(
            r"(\d+)\s+duplicate\s+patches,\s+(\d+)\s+lost\s+patches,\s+"
            r"bitwise-resume\s+fraction\s+([\d.]+),\s+(\d+)\s+ticks\s+to"
            r"\s+reconverge,\s+and\s+a\s+killed-vs-uninterrupted\s+"
            r"\$/SLO-hour\s+ratio\s+of\s+([\d.]+)", readme)
        assert m2, "README's invariant sentence lost its pinned form"
        dup, lost, bitwise, reconv, ratio = m2.groups()
        inv = sb["invariants"]
        assert int(dup) == inv["duplicate_patches_total"]
        assert int(lost) == inv["lost_patches_total"]
        assert abs(float(bitwise) - inv["resume_bitwise_frac"]) < 5e-4
        sev = sb["cells"]["severe"]["rows"]["rule"]
        assert int(reconv) == sev["ticks_to_reconverge_max"]
        assert abs(float(ratio)
                   - sev["usd_per_slo_hr_vs_baseline"]) < 5e-5
        m3 = re.search(r"~(\d+)\s+injected\s+kubectl\s+failures\s+and\s+"
                       r"spent\s+([\d.]+)\s+reconcile\s+retries", readme)
        assert m3, "README's severe-cell stress claim lost its form"
        injected_per_run = (sum(sev["chaos_injected"].values())
                            / sev["n_pairs"])
        assert abs(float(m3.group(1)) - injected_per_run) < 1.0
        assert abs(float(m3.group(2))
                   - sev["reconcile_retries_mean"]) < 0.1


class TestOverloadClaims:
    """Round 13's multi-tenant overload scoreboard (ISSUE 10 docs
    satellite): README's service claims are PARSED against the BASELINE
    round13 record, not hand-synced."""

    def test_round13_record_is_self_describing(self, baseline):
        r13 = baseline["published"]["round13"]
        sb = r13["overload_scoreboard"]
        assert len(sb["cells"]) >= 12
        assert set(sb["policies"]) >= {"rule", "flagship"}
        assert 0.0 in sb["slow_fracs"] and "off" in sb["intensities"]
        inv = sb["invariants"]
        # The acceptance surface, cell by cell: healthy isolation holds
        # under every stress mix, and per-cell p99 stays under the
        # configured deadline — including every slow-frac >= 0.25 cell
        # at severe chaos (the issue's acceptance criterion).
        for name, cell in sb["cells"].items():
            for policy, row in cell["rows"].items():
                assert row["healthy_usd_ratio_max"] <= 1.05, (name,
                                                              policy)
                assert row["healthy_bitwise_frac"] == 1.0, (name, policy)
                assert row["latency_ms"]["p99"] \
                    < cell["tick_deadline_ms"], (name, policy)
        sev = [c for c in sb["cells"].values()
               if c["intensity"] == "severe" and c["slow_frac"] >= 0.25]
        assert sev, "no severe slow-frac >= 0.25 acceptance cells"
        # The stress was real: slow-fraction severe cells opened
        # breakers and injected kubectl chaos; the grid shed load.
        for cell in sev:
            row = cell["rows"]["rule"]
            assert row["breaker_transitions"]["opened"] > 0
            assert sum(row["chaos_injected"][k] for k in
                       ("timeouts", "transient_exits", "dropped",
                        "rewrites")) > 0
        assert inv["healthy_usd_ratio_max"] <= 1.05
        assert inv["null_cell_ratio_max"] == 1.0   # zero-overhead gate
        assert inv["sheds_total"] > 0
        assert "byte-identical" in r13["off_preset_gate"]
        assert "bitwise" in r13["isolation_evidence"]
        assert r13["bounded_ticks_evidence"][
            "p99_under_deadline_every_cell"] is True

    def test_readme_overload_claims(self, readme, baseline):
        r13 = baseline["published"]["round13"]
        sb = r13["overload_scoreboard"]
        inv = sb["invariants"]
        m = re.search(
            r"(\d+)\s+cells\s+×\s+\{rule,\s+flagship\},\s+(\d+)\s+"
            r"stressed\s+runs\s+of\s+(\d+)\s+ticks\s+\(BASELINE\s+"
            r"round13", readme)
        assert m, ("README's overload claim no longer states the grid "
                   "shape in the pinned form — update the claim AND "
                   "this regex together")
        cells, runs, ticks = map(int, m.groups())
        assert cells == len(sb["cells"])
        assert runs == sum(len(c["rows"]) for c in sb["cells"].values())
        assert ticks == sb["ticks_per_run"]
        m2 = re.search(
            r"healthy\s+tenants'\s+paired\s+\$/SLO-hour\s+ratio\s+is\s+"
            r"exactly\s+([\d.]+)\s+\(bitwise\s+fraction\s+([\d.]+)\)",
            readme)
        assert m2, "README's isolation sentence lost its pinned form"
        assert float(m2.group(1)) == inv["healthy_usd_ratio_max"]
        assert float(m2.group(2)) == 1.0
        m3 = re.search(
            r"under\s+the\s+(\d+)\s?ms\s+deadline:\s+per-cell\s+p99\s+"
            r"latency\s+tops\s+out\s+at\s+([\d.]+)\s?ms\s+with\s+(\d+)"
            r"\s+single-tick\s+max\s+overshoots\s+across\s+(\d+)\s+"
            r"stressed\s+ticks", readme)
        assert m3, "README's bounded-ticks sentence lost its pinned form"
        deadline, p99, overshoots, total_ticks = m3.groups()
        ev = r13["bounded_ticks_evidence"]
        assert float(deadline) == ev["tick_deadline_ms"]
        assert abs(float(p99) - ev["latency_p99_max_ms"]) < 0.05
        assert int(overshoots) == ev["single_tick_max_overshoots"]
        assert int(total_ticks) == ev["stressed_ticks"]
        assert float(p99) < float(deadline)
        m4 = re.search(r"(\d+)\s+decides\s+shed\s+and\s+(\d+)\s+breaker"
                       r"\s+opens", readme)
        assert m4, "README's shed/breaker tally lost its pinned form"
        assert int(m4.group(1)) == inv["sheds_total"]
        assert int(m4.group(2)) == inv["breakers_opened_total"]


class TestIncidentClaims:
    """Round 14's incident-grade obs layer (ISSUE 11 docs satellite):
    README's "Incidents & alerting" claims are PARSED against the
    BASELINE round14 record, not hand-synced."""

    def test_round14_record_is_self_describing(self, baseline):
        r14 = baseline["published"]["round14"]
        obs = r14["obs_stage"]
        # The acceptance criteria hold on the record itself.
        assert obs["recorder_overhead_frac"] < 0.05
        assert obs["overhead_gate_ok"] is True
        assert obs["bitwise_identical"] is True
        assert obs["attributable"] is True
        assert obs["dumps_verified"] == obs["incidents_total"]
        assert obs["incidents_total"] > 0
        ev = r14["attribution_evidence"]
        assert ev["one_incident_per_trigger_occurrence"] is True
        assert ev["incidents_total"] == obs["incidents_total"]
        assert (ev["breaker_opens"] + ev["reconcile_giveups"]
                + ev["hold_fallbacks"]) == ev["incidents_total"]
        assert "bitwise" in r14["non_interference_gate"]
        w = r14["burn_rate_windows"]
        assert 1 <= w["fast_ticks"] <= w["slow_ticks"]
        assert r14["bench_diff_sentinel"][
            "exit_zero_on_real_history"] is True

    def test_readme_overhead_claim(self, readme, baseline):
        obs = baseline["published"]["round14"]["obs_stage"]
        m = re.search(
            r"([\d.]+)\s?ms/tick\s+of\s+recorder\s+overhead\s+—\s+"
            r"([\d.]+)%\s+of\s+the\s+([\d.]+)\s?ms\s+p50\s+tick\s+"
            r"latency", readme)
        assert m, ("README's recorder-overhead claim no longer states "
                   "the numbers in the pinned form — update the claim "
                   "AND this regex together")
        ms, pct, p50 = map(float, m.groups())
        assert abs(ms - obs["recorder_overhead_ms_per_tick"]) < 5e-3
        assert abs(pct / 100 - obs["recorder_overhead_frac"]) < 5e-3
        assert abs(p50 - obs["p50_tick_ms_off"]) < 5e-3
        assert pct / 100 < 0.05

    def test_readme_attribution_claim(self, readme, baseline):
        ev = (baseline["published"]["round14"]["attribution_evidence"])
        m = re.search(
            r"(\d+)\s+incidents\s+\((\d+)\s+breaker\s+opens,\s+(\d+)\s+"
            r"reconcile\s+give-ups,\s+(\d+)\s+hold→fallback\s+"
            r"escalations\)", readme)
        assert m, "README's attribution claim lost its pinned form"
        total, opens, giveups, fallbacks = map(int, m.groups())
        assert total == ev["incidents_total"]
        assert opens == ev["breaker_opens"]
        assert giveups == ev["reconcile_giveups"]
        assert fallbacks == ev["hold_fallbacks"]
        m2 = re.search(r"\((\d+)/(\d+)\s+checksums\s+pass,\s+(\d+)\s+"
                       r"shared\s+capture\s+files\)", readme)
        assert m2, "README's dump-verification claim lost its form"
        verified, of, files = map(int, m2.groups())
        assert verified == of == ev["dumps_verified"]
        assert files == ev["dumps_files"]

    def test_readme_burn_windows(self, readme, baseline):
        w = baseline["published"]["round14"]["burn_rate_windows"]
        m = re.search(r"(\d+)/(\d+)-tick\s+fast/slow\s+windows", readme)
        assert m, "README's burn-window claim lost its pinned form"
        assert int(m.group(1)) == w["fast_ticks"]
        assert int(m.group(2)) == w["slow_ticks"]

    def test_architecture_has_section_16(self):
        arch = _read("ARCHITECTURE.md")
        assert "## 16. Incident-grade observability" in arch
        for phrase in ("Flight recorder", "burn-rate", "bench-diff",
                       "on_giveup", "RUNLOG_EVENTS",
                       "round_inferred"):
            assert phrase in arch, phrase


class TestDecisionClaims:
    """Round 18's decision provenance observatory (ISSUE 15 docs
    satellite): README's "Decision provenance" claims are PARSED
    against the BASELINE round18 record, not hand-synced."""

    def test_round18_record_is_self_describing(self, baseline):
        r18 = baseline["published"]["round18"]
        dec = r18["decisions_stage"]
        # The acceptance criteria hold on the record itself.
        assert dec["bitwise_identical"] is True
        assert dec["ledger_overhead_frac"] < 0.05
        assert dec["overhead_gate_ok"] is True
        assert dec["term_share_err_max"] <= 0.02
        assert dec["share_gate_ok"] is True
        assert dec["rows_total"] > 0
        assert dec["divergence_incidents"] >= 1
        assert dec["divergence_dumps_verified"] \
            == dec["divergence_incidents"]
        assert dec["divergence_dump_failures"] == []
        assert dec["backend"] == "flagship"
        assert dec["shadow_policy"] == "rule"
        ev = r18["attribution_evidence"]
        assert ev["rows_recorded"] == dec["rows_total"]
        assert ev["shares_sum_to_one_on_every_row"] is True
        assert ev["one_dump_per_divergence_incident"] is True
        assert 0 < ev["diverged_decides"] <= ev["rows_recorded"]
        assert "bitwise" in r18["non_interference_gate"]
        assert "one XLA program" in r18["non_interference_gate"]

    def test_readme_overhead_claim(self, readme, baseline):
        dec = baseline["published"]["round18"]["decisions_stage"]
        m = re.search(
            r"([\d.]+)\s?ms/tick\s+of\s+ledger\s+overhead\s+—\s+"
            r"([\d.]+)%\s+of\s+the\s+([\d.]+)\s?ms\s+p50\s+tick\s+"
            r"latency", readme)
        assert m, ("README's ledger-overhead claim no longer states "
                   "the numbers in the pinned form — update the claim "
                   "AND this regex together")
        ms, pct, p50 = map(float, m.groups())
        assert abs(ms - dec["ledger_overhead_ms_per_tick"]) < 5e-3
        assert abs(pct / 100 - dec["ledger_overhead_frac"]) < 5e-3
        assert abs(p50 - dec["p50_tick_ms_off"]) < 5e-3
        assert pct / 100 < 0.05

    def test_readme_attribution_claim(self, readme, baseline):
        dec = baseline["published"]["round18"]["decisions_stage"]
        m = re.search(
            r"(\d+)\s+decision\s+rows\s+\(max\s+attribution-share\s+"
            r"error\s+([\d.]+e-\d+)\),\s+of\s+which\s+(\d+)\s+diverged"
            r"\s+from\s+the\s+rule\s+shadow", readme)
        assert m, "README's attribution claim lost its pinned form"
        rows, err, diverged = (int(m.group(1)), float(m.group(2)),
                               int(m.group(3)))
        assert rows == dec["rows_total"]
        assert diverged == dec["diverged_total"]
        assert err <= 0.02
        assert err == pytest.approx(dec["term_share_err_max"],
                                    rel=0.05)
        m2 = re.search(
            r"(\d+)\s+policy_divergence\s+incident\s+\((\d+)/(\d+)\s+"
            r"dump\s+checksums\s+pass\)", readme)
        assert m2, "README's divergence-incident claim lost its form"
        inc, verified, of = map(int, m2.groups())
        assert inc == dec["divergence_incidents"]
        assert verified == of == dec["divergence_dumps_verified"]

    def test_readme_names_the_gauges_and_trigger(self, readme):
        flat = " ".join(readme.split())  # wrap-tolerant phrase match
        for needle in ("ccka_policy_divergence_rate",
                       "ccka_objective_term_share",
                       "ccka_shadow_slo_delta",
                       "policy_divergence",
                       "no second dispatch, no second compile"):
            assert needle in flat, needle

    def test_architecture_has_section_20(self):
        arch = _read("ARCHITECTURE.md")
        assert "## 20. Decision provenance observatory" in arch
        flat = " ".join(arch.split())
        for phrase in ("decision_row_layout", "DECISION_COLS",
                       "decisions_enabled", "policy_divergence",
                       "edge-triggered", "objective_terms",
                       "flat_action_names", "one XLA program"):
            assert phrase in flat, phrase


class TestWorkloadScenarioClaims:
    """Round 11's per-family scenario scoreboard (ISSUE 6 docs
    satellite): README's workload-scenario claims are PARSED against
    the BASELINE round11 record, not hand-synced."""

    def test_round11_record_is_self_describing(self, baseline):
        r11 = baseline["published"]["round11"]
        sb = r11["workload_scenario_scoreboard"]
        assert sb["n_traces"] >= 256
        assert len(sb["scenarios"]) >= 4
        per_family = {"inf_slo_violations", "inf_dropped",
                      "batch_deadline_misses"}
        for name, sec in sb["scenarios"].items():
            for policy in ("rule", "flagship", "mpc"):
                row = sec["rows"][policy]
                assert per_family <= set(row), (name, policy)
            # Roofline floor derived from that scenario's OWN stream
            # geometry is on the record (bench-hygiene satellite).
            assert sec["roofline_floor_ms"] > 0
            assert sec["stream_bytes_per_cluster_tick"] == \
                4 * sec["stream_rows"]
        # The headline-hides-the-families evidence: each policy posts
        # the SAME aggregate $/SLO-hr across every CALM scenario
        # (families consume headroom, not the primary pipeline), while
        # the per-family columns separate the scenarios.
        calm = [s for s, sec in sb["scenarios"].items()
                if not sec["fault_preset"]]
        assert len(calm) >= 3
        for policy in ("rule", "flagship", "mpc"):
            heads = {sb["scenarios"][s]["rows"][policy]
                     ["usd_per_slo_hour"] for s in calm}
            assert len(heads) == 1, (policy, heads)
        misses = [sb["scenarios"][s]["rows"]["rule"]
                  ["batch_deadline_misses"] for s in calm]
        assert min(misses) == 0.0 and max(misses) > 1.0
        assert "bitwise" in r11["zero_workload_bitwise_gate"]
        assert "8-shard" in r11["pairing_evidence"]

    def test_readme_workload_claims(self, readme, baseline):
        sb = (baseline["published"]["round11"]
              ["workload_scenario_scoreboard"])
        m = re.search(
            r"sheds\s+([\d.]+)\s+pods/trace\s+of\s+inference\s+load"
            r"\s+versus\s+the\s+rule\s+baseline's\s+([\d.]+),\s+with"
            r"\s+([\d.]+)\s+vs\s+([\d.]+)\s+SLO-violation\s+ticks",
            readme)
        assert m, ("README's flash-crowd claim no longer states the "
                   "per-family numbers in the pinned form — update the "
                   "claim AND this regex together")
        flag_shed, rule_shed, flag_viol, rule_viol = map(float, m.groups())
        fc = sb["scenarios"]["flash-crowd"]["rows"]
        assert abs(flag_shed - fc["flagship"]["inf_dropped"]) < 5e-3
        assert abs(rule_shed - fc["rule"]["inf_dropped"]) < 5e-3
        assert abs(flag_viol - fc["flagship"]["inf_slo_violations"]) < 5e-2
        assert abs(rule_viol - fc["rule"]["inf_slo_violations"]) < 5e-2
        m2 = re.search(r"misses\s+([\d.]+)\s+deadlines/trace\s+vs"
                       r"\s+([\d.]+)", readme)
        assert m2, "README's batch-backfill deadline claim lost its form"
        bb = sb["scenarios"]["batch-backfill"]["rows"]
        assert abs(float(m2.group(1))
                   - bb["flagship"]["batch_deadline_misses"]) < 5e-2
        assert abs(float(m2.group(2))
                   - bb["rule"]["batch_deadline_misses"]) < 5e-2
        m3 = re.search(r"n≥(\d+)\s+kernel-paired\s+traces\s+\(BASELINE"
                       r"\s+round11", readme)
        assert m3 and int(m3.group(1)) <= sb["n_traces"]


class TestPerfObservatoryClaims:
    """Round 15's device-time observatory (ISSUE 12 docs satellite):
    README's "Performance observatory" section is PARSED against the
    BASELINE round15 record — including the refreshed single-chip
    headline the section exists to keep honest."""

    def test_round15_record_is_self_describing(self, baseline):
        r15 = baseline["published"]["round15"]["perf_stage"]
        # The acceptance criteria hold on the record itself: achieved
        # fractions physically plausible, occupancy accounts for the
        # pipeline, imbalance is a real max/mean, the observatory
        # neither steers nor overspends.
        for mode, frac in r15["achieved_roofline_fraction"].items():
            assert 0.0 < frac <= 1.25, mode
        assert set(r15["achieved_roofline_fraction"]) == {
            "rule", "carbon", "neural", "plan"}
        for occ in (r15["occupancy_rule"], r15["occupancy_mesh8"]):
            assert abs(sum(occ.values()) - 1.0) < 0.02
            assert set(occ) == {"generation", "kernel", "host"}
        assert r15["shard_imbalance"] >= 1.0
        obs = r15["observatory"]
        assert obs["bitwise_all"] is True
        assert obs["overhead_frac"] <= obs["overhead_gate_frac"]
        assert obs["overhead_gate_ok"] is True
        cross = r15["bytes_crosscheck_rule"]
        assert cross["hand_bytes"] > 0 and cross["xla_bytes"] > 0
        assert cross["ratio"] is not None
        assert r15["single_chip"]["cluster_days_per_sec"] > 0
        # A CPU record must say so — the virtual label is load-bearing.
        assert r15["virtual"] is True and r15["platform"] == "cpu"

    def test_readme_occupancy_claim(self, readme, baseline):
        occ = (baseline["published"]["round15"]["perf_stage"]
               ["occupancy_rule"])
        m = re.search(r"generation\s+([\d.]+)%\s*/\s*kernel\s+([\d.]+)%"
                      r"\s*/\s*host\s+([\d.]+)%", readme)
        assert m, ("README's rule-mode occupancy claim no longer "
                   "states the split in the pinned form — update the "
                   "claim AND this regex together")
        gen, ker, host = (float(g) / 100 for g in m.groups())
        assert abs(gen - occ["generation"]) < 5e-3
        assert abs(ker - occ["kernel"]) < 5e-3
        assert abs(host - occ["host"]) < 5e-3

    def test_readme_imbalance_claim(self, readme, baseline):
        r15 = baseline["published"]["round15"]["perf_stage"]
        m = re.search(r"shard\s+imbalance\s+of\s+([\d.]+)", readme)
        assert m, "README's shard-imbalance claim lost its pinned form"
        assert abs(float(m.group(1)) - r15["shard_imbalance"]) < 5e-3

    def test_readme_overhead_and_crosscheck_claims(self, readme,
                                                   baseline):
        r15 = baseline["published"]["round15"]["perf_stage"]
        m = re.search(r"span\s+cost\s+is\s+([\d.]+)%\s+of", readme)
        assert m, "README's observatory-overhead claim lost its form"
        assert abs(float(m.group(1)) / 100
                   - r15["observatory"]["overhead_frac"]) < 5e-4
        assert float(m.group(1)) / 100 < 0.05
        m2 = re.search(r"XLA\s+reports\s+([\d.]+)×\s+the\s+hand-counted",
                       readme)
        assert m2, "README's byte-crosscheck claim lost its form"
        assert abs(float(m2.group(1))
                   - r15["bytes_crosscheck_rule"]["ratio"]) < 5e-3

    def test_readme_refreshed_single_chip_headline(self, readme,
                                                   baseline):
        sc = (baseline["published"]["round15"]["perf_stage"]
              ["single_chip"])
        m = re.search(r"\*\*([\d.]+)\s*\ncluster-days/sec\*\*\s+"
                      r"\(B=(\d+)\s+×\s+(\d+)\s+steps,\s+CPU\s+"
                      r"interpret", readme)
        assert m, ("README's refreshed single-chip headline lost its "
                   "pinned form (the number must stay labeled CPU "
                   "interpret)")
        assert abs(float(m.group(1)) - sc["cluster_days_per_sec"]) < 0.05
        assert int(m.group(2)) == sc["batch"]
        assert int(m.group(3)) == sc["steps"]

    def test_architecture_has_section_17(self):
        arch = _read("ARCHITECTURE.md")
        assert "## 17. The device-time performance observatory" in arch
        for phrase in ("Cost-model attribution", "OccupancyLedger",
                       "shard_lane_blocks", "shard_imbalance",
                       "packed_mode_summary_fn", "(0, 1.25]",
                       "historical", "scaling-curve"):
            assert phrase in arch, phrase
        # §6 carries the staleness pointer the refresh satellite adds.
        assert "historical; see §17" in arch


class TestStreamingClaims:
    """Round 16's streaming pipeline (ISSUE 13 docs satellite):
    README's "Streaming pipeline" section is PARSED against the
    BASELINE round16 record — the headline, the same-session r15
    comparison, the two-buffer bound and the chunked memory bound are
    all record-derived, never hand-synced."""

    def test_round16_record_is_self_describing(self, baseline):
        r16 = baseline["published"]["round16"]["stream_stage"]
        # A CPU record must say so, and must say it cannot overlap.
        assert r16["virtual"] is True and r16["platform"] == "cpu"
        assert r16["overlap_capable"] is False
        # The bitwise gates the acceptance criteria name.
        assert r16["bitwise_all"] is True
        assert r16["stream_buffers"] == 2
        sc = r16["single_chip"]
        assert sc["cluster_days_per_sec"] > 0
        # One protocol, two geometries: the streaming headline improves
        # on the SAME-SESSION replication of the round-15 headline.
        assert sc["vs_r15_replication"] >= 1.0
        repl = r16["r15_replication"]
        assert repl["historical_round15_cluster_days_per_sec"] == 554.66
        assert repl["cluster_days_per_sec"] > 0
        # The chunked row's stated memory bound is the formula, not a
        # hand-typed number: 2 blocks x lanes x chunk x 4 bytes.
        from ccka_tpu.config import default_config
        from ccka_tpu.sim import lanes

        ch = r16["chunked"]
        assert ch["batch"] >= 10_000
        Z = default_config().cluster.n_zones
        assert ch["live_block_bytes"] == 2 * lanes.block_bytes(
            ch["block_T"], lanes.exo_rows(Z), ch["chunk"])
        assert ch["bitwise_pipelined_vs_sync"] is True
        assert ch["roofline_floor_s"] > 0
        m = r16["mesh8"]
        assert m["bitwise_mesh_vs_chunked"] is True
        assert m["shards"] == 8
        # Single-core floor: the best paired row must not regress past
        # the sentinel's non-overlap floor.
        assert r16["best_paired"]["throughput_ratio"] >= 0.85

    def test_readme_streaming_headline(self, readme, baseline):
        r16 = baseline["published"]["round16"]["stream_stage"]
        sc = r16["single_chip"]
        m = re.search(r"\*\*([\d.,]+)\s*cluster-days/sec\*\*\s+"
                      r"\(B=(\d+)\s+×\s+(\d+)\s+steps,\s+kernel\s+"
                      r"stage,\s+CPU\s+interpret", readme)
        assert m, ("README's streaming headline lost its pinned form "
                   "(the number must stay labeled kernel-stage + CPU "
                   "interpret)")
        assert abs(float(m.group(1).replace(",", ""))
                   - sc["cluster_days_per_sec"]) < 0.05
        assert int(m.group(2)) == sc["batch"]
        assert int(m.group(3)) == sc["steps"]
        m2 = re.search(r"([\d.]+)×\s+the\s+same-session\s+round-15\s+"
                       r"replication\s+\(([\d.,]+)\s*cluster-days/sec",
                       readme)
        assert m2, "README's r15-comparison claim lost its form"
        assert abs(float(m2.group(1)) - sc["vs_r15_replication"]) < 5e-3
        assert abs(float(m2.group(2).replace(",", ""))
                   - r16["r15_replication"]["cluster_days_per_sec"]) \
            < 0.05

    def test_readme_chunked_and_buffer_claims(self, readme, baseline):
        r16 = baseline["published"]["round16"]["stream_stage"]
        ch = r16["chunked"]
        m = re.search(r"([\d,]+)\s+clusters\s+stream[^.]*?([\d.]+)\s*"
                      r"MiB\s+of\s+live\s+stream\s+blocks", readme)
        assert m, "README's chunked bounded-memory claim lost its form"
        assert int(m.group(1).replace(",", "")) == ch["batch"]
        assert abs(float(m.group(2)) - ch["live_block_mib"]) < 0.05
        assert re.search(r"exactly\s+\*\*two\s+stream\s+blocks\*\*\s+"
                         r"per\s+chip", readme)

    def test_architecture_has_section_18(self):
        arch = _read("ARCHITECTURE.md")
        assert ("## 18. The double-buffered streaming rollout pipeline"
                in arch)
        for phrase in ("block_layout", "BLOCK_KEY_TAG",
                       "block_chunk_seed",
                       "packed_mode_block_summary_fn",
                       "2 × block_T × rows × chunk",
                       "overlap_capable", "r15_replication",
                       "sharded_block_packed_trace"):
            assert phrase in arch, phrase


class TestFactoryClaims:
    """Round 17's distillation factory (ISSUE 14 docs satellite):
    README's "Distillation factory" section is PARSED against the
    BASELINE round17 record — the pairs/sec headline, the paired
    naive-loop ratio, and the student-vs-teacher column are all
    record-derived, never hand-synced."""

    def test_round17_record_is_self_describing(self, baseline):
        r17 = baseline["published"]["round17"]["factory_stage"]
        assert r17["stage"] == "--factory-only"
        # A CPU record must say so (interpret-mode, labeled).
        assert r17["virtual"] is True and r17["platform"] == "cpu"
        assert r17["interpret"] is True
        # The paired-throughput acceptance gate (>= 5x) with the ratio
        # recomputable from the record's own sides.
        assert r17["throughput_ratio_vs_baseline"] \
            >= r17["gate_min_ratio"] == 5.0
        recomputed = (r17["pairs_per_sec"]
                      / r17["baseline"]["pairs_per_sec"])
        assert abs(recomputed - r17["throughput_ratio_vs_baseline"]) \
            < 0.01
        assert "receding_horizon_rollout" in r17["baseline"]["engine"]
        # Every cell carries its throughput and its paired column; the
        # first cell carries the occupancy ledger.
        assert len(r17["cells"]) >= 4
        for cell in r17["cells"]:
            assert cell["pairs_per_sec"] > 0
            assert cell["plans_per_sec"] > 0
            assert cell["playback_cluster_days_per_sec"] > 0
            assert cell["teacher_vs_rule_usd_per_slo_hour"] > 0
        assert any("playback_occupancy" in c for c in r17["cells"])
        assert r17["playback_roofline_floor_s"] > 0
        # The student column: present, plausible, per-cell paired.
        st = r17["student"]
        assert 0 < st["student_vs_teacher_usd_per_slo_hour"] < 100
        assert len(st["per_cell"]) == len(r17["cells"])
        for row in st["per_cell"]:
            assert row["student_vs_teacher_usd_per_slo_hour"] > 0
            # The distilled student beats the rule baseline per cell
            # (the claim README states as "in every cell").
            assert row["student_vs_rule_usd_per_slo_hour"] < 1.0

    def test_readme_factory_headline(self, readme, baseline):
        r17 = baseline["published"]["round17"]["factory_stage"]
        m = re.search(
            r"\*\*([\d.,]+)\s*pairs/sec\*\*\s+\((\d+)\s+pairs\s+across"
            r"\s+(\d+)\s+scenario×intensity\s+cells.*?\*\*([\d.]+)×\*\*"
            r"\s+the\s+naive\s+per-pair\s+lax\s+receding-horizon\s+loop"
            r"\s+\(([\d.]+)\s*pairs/sec", readme, re.S)
        assert m, ("README's factory headline lost its pinned form "
                   "(pairs/sec + paired naive ratio must stay "
                   "together, labeled)")
        pps, pairs, n_cells, ratio, naive = m.groups()
        assert abs(float(pps.replace(",", ""))
                   - r17["pairs_per_sec"]) < 0.05
        assert int(pairs) == r17["pairs_total"]
        assert int(n_cells) == len(r17["cells"])
        assert abs(float(ratio)
                   - r17["throughput_ratio_vs_baseline"]) < 5e-3
        assert abs(float(naive)
                   - r17["baseline"]["pairs_per_sec"]) < 0.05
        m2 = re.search(r"([\d.]+)\s+plans/sec", readme)
        assert m2, "README lost the plans/sec claim"
        assert abs(float(m2.group(1)) - r17["plans_per_sec"]) < 0.05

    def test_readme_student_claim(self, readme, baseline):
        r17 = baseline["published"]["round17"]["factory_stage"]
        st = r17["student"]
        m = re.search(r"student\s+×([\d.]+)\s+\$/SLO-hr\s+vs\s+the\s+"
                      r"teacher", readme)
        assert m, "README's student-vs-teacher claim lost its form"
        assert abs(float(m.group(1))
                   - st["student_vs_teacher_usd_per_slo_hour"]) < 5e-3
        m2 = re.search(r"×([\d.]+)\s+vs\s+the\s+rule\s+baseline\s+on\s+"
                       r"average", readme)
        assert m2, "README's student-vs-rule claim lost its form"
        mean_rule = sum(r["student_vs_rule_usd_per_slo_hour"]
                        for r in st["per_cell"]) / len(st["per_cell"])
        assert abs(float(m2.group(1)) - mean_rule) < 5e-3

    def test_readme_dataset_rows(self, readme, baseline):
        r17 = baseline["published"]["round17"]["factory_stage"]
        m = re.search(r"([\d,]+)-row\s+dataset", readme)
        assert m, "README lost the dataset-size claim"
        assert int(m.group(1).replace(",", "")) == r17["dataset_rows"]


class TestGeoClaims:
    """Round 19's geo-arbitrage subsystem (ISSUE 16 docs satellite):
    README's "Geo arbitrage" section and ARCHITECTURE §21 are PARSED
    against the BASELINE round19 record, not hand-synced."""

    def test_round19_record_is_self_describing(self, baseline):
        r19 = baseline["published"]["round19"]
        geo = r19["geo_stage"]
        # The acceptance criteria hold on the record itself.
        assert geo["zero_migration_parity"] is True
        assert all(geo["parity"].values()), geo["parity"]
        assert set(geo["parity"]) >= {
            "pre_geo_rows_bitwise", "lane_block_bitwise_reference",
            "lax_engine_bitwise", "kernel_engine_bitwise",
            "zero_rate_migration_term_exact_zero",
            "zero_rate_rollout_bitwise_none"}
        assert geo["dominance_found"] is True
        assert geo["max_conservation_residual_pods"] \
            < geo["conservation_gate_pods"]
        led = geo["ledger"]
        assert led["migration_term_present"] is True
        assert led["rows"] > 0
        assert led["term_share_err_max"] <= 1e-12
        assert 0.0 < led["migration_share_max"] < 1.0
        # The pinned dominance evidence actually dominates: carbon-first
        # beats "none" on cost AND carbon at equal-or-better SLO.
        pts = geo["spot_storm_inference_points_usd_kg_slo"]
        assert "carbon-first" in geo["spot_storm_dominates_none"]
        cf, none = pts["carbon-first"], pts["none"]
        assert all(a <= b for a, b in zip(cf, none))
        assert any(a < b for a, b in zip(cf, none))
        assert geo["stage"] == "--geo-only"
        assert "none" in geo["policies"]
        assert "spot-storm" in geo["scenarios"]
        assert set(geo["classes"]) == {"inference", "batch",
                                       "background"}
        assert "bitwise" in r19["zero_rate_parity_gate"]
        assert "float64" in r19["conservation_gate"]

    def test_readme_conservation_claim(self, readme, baseline):
        geo = baseline["published"]["round19"]["geo_stage"]
        m = re.search(
            r"stays\s+at\s+([\d.]+e-\d+)\s+pods\s+against\s+the\s+"
            r"([\d.]+)-pod\s+gate", readme)
        assert m, ("README's conservation claim no longer states the "
                   "residual in the pinned form — update the claim AND "
                   "this regex together")
        residual, gate = float(m.group(1)), float(m.group(2))
        assert residual == pytest.approx(
            geo["max_conservation_residual_pods"], rel=0.05)
        assert gate == geo["conservation_gate_pods"]

    def test_readme_dominance_claim(self, readme, baseline):
        geo = baseline["published"]["round19"]["geo_stage"]
        pts = geo["spot_storm_inference_points_usd_kg_slo"]
        m = re.search(
            r"\$([\d.]+)\s+vs\s+\$([\d.]+)\s+and\s+([\d.]+)\s+vs\s+"
            r"([\d.]+)\s?kgCO₂e\s+at\s+equal\s+SLO", readme)
        assert m, "README's dominance claim lost its pinned form"
        cf_usd, none_usd, cf_kg, none_kg = map(float, m.groups())
        assert abs(cf_usd - pts["carbon-first"][0]) < 5e-3
        assert abs(none_usd - pts["none"][0]) < 5e-3
        assert abs(cf_kg - pts["carbon-first"][1]) < 5e-4
        assert abs(none_kg - pts["none"][1]) < 5e-4
        assert cf_usd < none_usd and cf_kg < none_kg

    def test_readme_ledger_claim(self, readme, baseline):
        led = baseline["published"]["round19"]["geo_stage"]["ledger"]
        m = re.search(
            r"(\d+)\s+geo\s+ledger\s+rows,\s+max\s+share\s+error\s+"
            r"([\d.]+e-\d+),\s+migration\s+share\s+peaking\s+at\s+"
            r"([\d.]+)%", readme)
        assert m, "README's geo-ledger claim lost its pinned form"
        rows, err, share_pct = (int(m.group(1)), float(m.group(2)),
                                float(m.group(3)))
        assert rows == led["rows"]
        assert err == pytest.approx(led["term_share_err_max"], rel=0.05)
        assert share_pct / 100 == pytest.approx(
            led["migration_share_max"], rel=0.05)

    def test_readme_names_the_gauges_and_surfaces(self, readme):
        flat = " ".join(readme.split())  # wrap-tolerant phrase match
        for needle in ("ccka_region_migration_rate",
                       "ccka_region_carbon_intensity",
                       "register_lane_family", "sanitize_rates",
                       "`ccka geo`", "--geo-only",
                       "zero per-engine edits"):
            assert needle in flat, needle

    def test_architecture_has_section_21(self):
        arch = _read("ARCHITECTURE.md")
        assert "## 21. Geo-arbitrage subsystem" in arch
        flat = " ".join(arch.split())
        for phrase in ("region_rows", "MIGRATABLE_FAMILIES",
                       "sanitize_rates", "conservation_residual",
                       "render_migration_commands",
                       "apply_migration_commands", "pareto_front",
                       "run_geo_suite", "_pareto_dominates",
                       "packed_region_lanes", "zero per-engine edits",
                       "arrive → move → serve"):
            assert phrase in flat, phrase


class TestTournamentClaims:
    """Round 20's shadow tournament observatory (ISSUE 17 docs
    satellite): README's "Shadow tournament" claims are PARSED against
    the BASELINE round20 record, not hand-synced."""

    def test_round20_record_is_self_describing(self, baseline):
        r20 = baseline["published"]["round20"]
        tour = r20["tournament_stage"]
        # The acceptance criteria hold on the record itself.
        assert tour["bitwise_identical"] is True
        assert tour["ledger_overhead_frac"] <= 0.05
        assert tour["overhead_gate_ok"] is True
        assert tour["board_gate_ok"] is True
        assert tour["challenger_gate_ok"] is True
        assert tour["primary"] == "flagship"
        assert len(tour["roster"]) == tour["k"] == 4
        # Every K point of the lane-width curve is present.
        assert set(tour["k_curve"]) == {"0", "1", "2", "4", "8"}
        ch = r20["challenger_evidence"]
        assert ch["incidents"] == 1
        assert ch["dumps_verified"] == 1
        assert ch["dump_failures"] == []
        assert ch["promotion_audit_rows"] >= 1
        assert (ch["promotion_audits_hmac_verified"]
                == ch["promotion_audit_rows"])
        assert ch["auto_switch"] is False
        ev = r20["win_ledger_evidence"]
        assert ev["roster"] == tour["roster"]
        assert ev["board_matches_roster_1_to_1"] is True
        assert set(ev["win_rate_last"]) == set(tour["roster"])
        assert all(0.0 <= v <= 1.0
                   for v in ev["win_rate_last"].values())
        assert "bitwise" in r20["non_interference_gate"]
        assert "one XLA program" in r20["non_interference_gate"]
        assert "round-18 rule-shadow" in r20["k1_degeneracy_gate"]

    def test_readme_overhead_claim(self, readme, baseline):
        tour = baseline["published"]["round20"]["tournament_stage"]
        m = re.search(
            r"(-?[\d.]+)\s?ms/tick\s+median\s+paired\s+delta\s+—\s+"
            r"([\d.]+)%\s+of\s+the\s+([\d.]+)\s?ms\s+p50\s+tick\s+"
            r"latency,\s+under\s+the\s+5%\s+gate", readme)
        assert m, ("README's tournament-overhead claim no longer "
                   "states the numbers in the pinned form — update "
                   "the claim AND this regex together")
        ms, pct, p50 = map(float, m.groups())
        assert abs(ms - tour["ledger_overhead_ms_per_tick"]) < 5e-3
        assert abs(pct / 100 - tour["ledger_overhead_frac"]) < 5e-3
        assert abs(p50 - tour["p50_tick_ms_off"]) < 5e-3
        assert pct / 100 <= 0.05

    def test_readme_k_curve_claim(self, readme, baseline):
        tour = baseline["published"]["round20"]["tournament_stage"]
        m = re.search(
            r"\+([\d.]+)%\s+\(K=1\),\s+\+([\d.]+)%\s+\(K=2\),\s+"
            r"\+([\d.]+)%\s+\(K=4\)\s+and\s+\+([\d.]+)%\s+\(K=8\)\s+"
            r"over\s+the\s+([\d.]+)\s?ms\s+laneless\s+tick", readme)
        assert m, "README's K-lane curve claim lost its pinned form"
        for k, pct in zip(("1", "2", "4", "8"), m.groups()[:4]):
            assert abs(float(pct) / 100
                       - tour["k_curve"][k]["frac_vs_k0"]) < 5e-3, k
        assert abs(float(m.group(5))
                   - tour["k_curve"]["0"]["p50_ms"]) < 5e-3

    def test_readme_challenger_claim(self, readme, baseline):
        tour = baseline["published"]["round20"]["tournament_stage"]
        m = re.search(
            r"exactly\s+(\d+)\s+challenger_sustained_win\s+incident\s+"
            r"\((\d+)/(\d+)\s+dump\s+checksums\s+pass,\s+(\d+)/(\d+)"
            r"\s+promotion\s+audits\s+HMAC-verified\)", readme)
        assert m, "README's challenger claim lost its pinned form"
        inc, dv, dof, av, aof = map(int, m.groups())
        ch = tour["challenger"]
        assert inc == ch["incidents"] == 1
        assert dv == dof == ch["dumps_verified"]
        assert av == ch["audits_verified"]
        assert aof == ch["audit_rows"]

    def test_readme_names_the_surfaces(self, readme):
        flat = " ".join(readme.split())  # wrap-tolerant phrase match
        for needle in ("ccka_policy_candidate_win_rate",
                       "ccka_tournament_leader",
                       "challenger_sustained_win",
                       "`ccka tournament list`", "--tournament-only",
                       "PromotionGate", "never automatic"):
            assert needle in flat, needle

    def test_architecture_has_section_22(self):
        arch = _read("ARCHITECTURE.md")
        assert ("## 22. Online shadow tournament observatory"
                in arch)
        flat = " ".join(arch.split())
        for phrase in ("tournament_roster", "CANDIDATE_BUILDERS",
                       "register_candidate", "resolve_candidates",
                       "TournamentRoster.register", "jax.eval_shape",
                       "CAND_COLS", "workload_class",
                       "tournament_win_margin",
                       "tournament_sustain_ticks",
                       "PromotionGate.review",
                       "sign_audit", "verify_audit",
                       "challenger_sustained_win",
                       "program-shaping"):
            assert phrase in flat, phrase


class TestFleetScaleClaims:
    """Round 21's fleet-scale host loop (ISSUE 18 docs satellite):
    README's "Fleet scale" claims are PARSED against the BASELINE
    round21 record, not hand-synced."""

    def test_round21_record_is_self_describing(self, baseline):
        r21 = baseline["published"]["round21"]
        fs = r21["fleet_scale_stage"]
        inv = fs["invariants"]
        # The acceptance criteria hold on the record itself.
        assert inv["parity_bitwise"] is True
        assert inv["chunk_parity_bitwise"] is True
        assert inv["speedup_ratio"] >= 10.0
        assert inv["healthy_usd_ratio_max"] == 1.0
        assert inv["healthy_ratio_exact_all"] is True
        assert inv["max_tenants"] == 10240
        assert fs["sweep_n"] == [16, 256, 1024, 4096, 10240]
        assert len(fs["scenarios"]) == 2
        # Every sweep cell the spec names is present.
        for n in fs["sweep_n"]:
            for scen in fs["scenarios"]:
                assert f"n{n}/{scen}" in fs["cells"], (n, scen)
        sp = r21["speedup_evidence"]
        assert sp["ratio"] == inv["speedup_ratio"]
        assert sp["ratio"] >= sp["floor"] == 10.0
        assert sp["warmup_ticks_dropped"] >= 1
        assert fs["parity"]["mismatches"] == []
        assert fs["chunk_parity"]["mismatches"] == []
        assert fs["parity"]["n_tenants"] <= 64
        assert fs["chunk_parity"]["n_tenants"] == 1024
        for gate, needle in (("parity_gate", "bitwise identical"),
                             ("isolation_gate", "EXACTLY"),
                             ("p99_curve_gate", "monotonically")):
            assert needle in r21[gate], gate

    def test_readme_speedup_claim(self, readme, baseline):
        sp = baseline["published"]["round21"]["speedup_evidence"]
        m = re.search(
            r"N=4096\s+calm\s+fleet\s+at\s+([\d.]+)\s?µs/tenant\s+"
            r"against\s+the\s+object\s+loop's\s+([\d.]+)\s?µs/tenant\s+"
            r"—\s+a\s+([\d.]+)×\s+speedup\s+over\s+the\s+≥10×\s+gate",
            readme)
        assert m, ("README's fleet-scale speedup claim no longer "
                   "states the numbers in the pinned form — update "
                   "the claim AND this regex together")
        vec, obj, ratio = map(float, m.groups())
        assert abs(vec - sp["vectorized_us_per_tenant"]) < 5e-4
        assert abs(obj - sp["object_us_per_tenant"]) < 5e-4
        assert abs(ratio - sp["ratio"]) < 5e-3
        assert ratio >= 10.0
        assert sp["n_tenants"] == 4096 and sp["scenario"] == "calm"

    def test_readme_tail_latency_claim(self, readme, baseline):
        fs = baseline["published"]["round21"]["fleet_scale_stage"]
        m = re.search(
            r"N=10240\s+the\s+calm\s+fleet's\s+p99\s+tick\s+latency\s+"
            r"is\s+([\d.]+)\s?ms\s+at\s+([\d.]+)\s?µs\s+of\s+host\s+"
            r"loop\s+per\s+tenant", readme)
        assert m, "README's 10^4-tenant tail claim lost its pinned form"
        p99, us = map(float, m.groups())
        cell = fs["cells"]["n10240/calm"]
        assert abs(p99 - cell["latency_ms"]["p99"]) < 5e-3
        assert abs(us - cell["host_loop_us_per_tenant"]) < 5e-4
        # The per-tenant p99 curve the README calls monotone really
        # falls over the record's upper sweep, both scenarios.
        for scen in fs["scenarios"]:
            series = [(n, fs["cells"][f"n{n}/{scen}"]["latency_ms"]
                       ["p99"]) for n in fs["sweep_n"] if n >= 256]
            per_tenant = [p / n for n, p in series]
            assert per_tenant == sorted(per_tenant, reverse=True), scen

    def test_readme_isolation_claim(self, readme, baseline):
        fs = baseline["published"]["round21"]["fleet_scale_stage"]
        flat = " ".join(readme.split())
        assert ("paired $/SLO-hour ratio is exactly 1.0 in every "
                "stressed cell, 16 through 10240 tenants") in flat
        ratio_cells = [c for c in fs["cells"].values()
                       if "healthy_usd_ratio_max" in c]
        assert len(ratio_cells) == len(fs["sweep_n"])
        assert all(c["healthy_usd_ratio_max"] == 1.0
                   and c["healthy_usd_ratio_mean"] == 1.0
                   for c in ratio_cells)

    def test_readme_names_the_surfaces(self, readme):
        flat = " ".join(readme.split())
        for needle in ("ccka_host_loop_us_per_tenant",
                       "ccka_active_tenants", "--fleet-scale-only",
                       "`ccka scaling-curve`", "BENCH_r21.json",
                       "ScrapeFanIn", "chunk_layout",
                       "_VectorBreakerBank", "splitmix64"):
            assert needle in flat, needle

    def test_architecture_has_section_23(self):
        arch = _read("ARCHITECTURE.md")
        assert "## 23. Fleet-scale host loop" in arch
        flat = " ".join(arch.split())
        for phrase in ("counter_u01", "n_tripped",
                       "_ObjectBreakerBank", "_run_paired",
                       "bitwise_identical", "chunk_layout",
                       "ScrapeFanIn", "FIRST_COMPLETED",
                       "_FLEET_SPEEDUP_FLOOR", "_FLEET_MAX_N",
                       "_FLEET_P99_MIN_N",
                       "_FLEET_P99_PER_TENANT_SLACK",
                       "skip-don't-fake-zeros",
                       "warmup ticks dropped"):
            assert phrase in flat, phrase


class TestSearchClaims:
    """Round 22's traced scenario axis + adversarial search (ISSUE 19
    docs satellite): README's "Adversarial scenario search" claims are
    PARSED against the BASELINE round22 record, not hand-synced."""

    def test_round22_record_is_self_describing(self, baseline):
        r22 = baseline["published"]["round22"]
        sp = r22["speedup_evidence"]
        # The acceptance criteria hold on the record itself.
        assert sp["pass"] is True
        assert sp["ratio"] >= 10.0
        assert abs(sp["ratio"] - sp["traced_cells_per_sec"]
                   / sp["loop_cells_per_sec"]) < 0.05 * sp["ratio"]
        st = r22["search_stage"]
        assert st["traced"]["recompiles_during_swaps"] == 0
        assert st["traced"]["cells_per_sec"] == sp["traced_cells_per_sec"]
        assert st["recompile_loop"]["cells_per_sec"] == \
            sp["loop_cells_per_sec"]
        par = r22["parity"]
        assert par["s1_stream_bitwise"] is True
        assert par["s1_summary_bitwise"] is True
        assert par["ncell_allclose"] is True
        assert par["ncell_values_traced"] == par["ncell_values_loop"]
        se = r22["search"]
        assert se["dominates"] is True
        assert se["minted"]["value"] > se["hand_worst"]
        assert se["hand_worst"] == max(se["hand_named"].values())
        assert len(se["minted"]["params_digest"]) == 64
        assert se["minted"]["name"].startswith("minted-rule-")
        assert se["history"][-1]["best"] == se["minted"]["value"]
        for gate, needle in (("parity_gate", "bitwise identical"),
                             ("dominance_gate", "strictly exceeds")):
            assert needle in r22[gate], gate

    def test_readme_speedup_claim(self, readme, baseline):
        sp = baseline["published"]["round22"]["speedup_evidence"]
        tr = baseline["published"]["round22"]["search_stage"]["traced"]
        m = re.search(
            r"([\d.]+)\s+traced\s+scenario-cells/sec\s+against\s+the\s+"
            r"per-config\s+recompile\s+loop's\s+([\d.]+)\s+—\s+a\s+"
            r"([\d.]+)×\s+speedup\s+over\s+the\s+≥10×\s+gate\s+—\s+"
            r"with\s+(\d+)\s+recompiles", " ".join(readme.split()))
        assert m, ("README's scenario-search speedup claim no longer "
                   "states the numbers in the pinned form — update "
                   "the claim AND this regex together")
        traced, loop, ratio, recompiles = m.groups()
        assert abs(float(traced) - sp["traced_cells_per_sec"]) < 5e-3
        assert abs(float(loop) - sp["loop_cells_per_sec"]) < 5e-4
        assert abs(float(ratio) - sp["ratio"]) < 5e-2
        assert float(ratio) >= 10.0
        assert int(recompiles) == tr["recompiles_during_swaps"] == 0

    def test_readme_parity_claim(self, readme, baseline):
        par = baseline["published"]["round22"]["parity"]
        flat = " ".join(readme.split())
        assert ("the S=1 traced axis is bitwise the config-baked path "
                "(stream AND kernel summary") in flat
        m = re.search(r"max\s+\|Δ\|\s+([\d.e-]+)\s+on\s+the\s+N-cell\s+"
                      r"allclose", flat)
        assert m, "README's ulp-tolerance claim lost its pinned form"
        assert abs(float(m.group(1)) - par["ncell_max_abs_delta"]) \
            <= 1e-9

    def test_readme_dominance_claim(self, readme, baseline):
        se = baseline["published"]["round22"]["search"]
        m = re.search(
            r"degrades\s+the\s+rule\s+policy\s+to\s+([\d.]+)\s+"
            r"\$/SLO-hr,\s+strictly\s+worse\s+than\s+its\s+worst\s+"
            r"hand-named\s+scenario\s+cell\s+\(`(\S+)`,\s+([\d.]+)\)",
            " ".join(readme.split()))
        assert m, "README's minted-dominance claim lost its pinned form"
        minted_v, hand_name, hand_v = m.groups()
        assert abs(float(minted_v) - se["minted"]["value"]) < 5e-7
        assert abs(float(hand_v) - se["hand_worst"]) < 5e-7
        assert float(minted_v) > float(hand_v)
        assert se["hand_named"][hand_name] == se["hand_worst"]

    def test_readme_names_the_surfaces(self, readme):
        flat = " ".join(readme.split())
        for needle in ("ScenarioParams", "`from_config`/`to_config`",
                       "generate_p", "ScenarioAxisSource", "set_params",
                       "ccka scenario-search", "--intensity", "--bound",
                       "--mint-out", "ccka scenarios --minted-dir",
                       "replay_minted", "`ccka bench-diff`",
                       "BENCH_r22.json", "common generation key"):
            assert needle in flat, needle

    def test_architecture_has_section_24(self):
        arch = _read("ARCHITECTURE.md")
        assert ("## 24. Traced scenario-parameter axis + adversarial "
                "search") in arch
        flat = " ".join(arch.split())
        for phrase in ("ScenarioParams", "SEARCH_SPEC",
                       "validate_bounds", "clip_to_bounds",
                       "params_digest", "generate_p",
                       "provide_lane_param_generator",
                       "packed_fault_lanes_p", "PRICE_DEV_SIGMA",
                       "ScenarioAxisSource", "summary_cells",
                       "set_params", "ScenarioScorer", "search_iter",
                       "search_mint", "replay_minted",
                       "load_minted_scenarios", "_SEARCH_SPEEDUP_FLOOR",
                       "tests/test_search.py"):
            assert phrase in flat, phrase

class TestFlywheelClaims:
    """Round 23's continual-learning flywheel (ISSUE 20 docs
    satellite): README's "Continual-learning flywheel" claims are
    PARSED against the BASELINE round23 record, not hand-synced."""

    def test_round23_record_is_self_describing(self, baseline):
        r23 = baseline["published"]["round23"]
        pe = r23["promotion_evidence"]
        assert pe["pass"] is True
        assert pe["promotions"] == 2
        assert all(r < 1.0 for r in pe["mean_ratios"])
        gens = r23["flywheel_stage"]["generations"]
        assert [g["mean_ratio"] for g in gens] == pe["mean_ratios"]
        assert gens[0]["incumbent"] == "rule"
        assert gens[1]["incumbent"] == gens[0]["incumbent"] or \
            gens[1]["incumbent"].startswith("gen-")
        for g in gens:
            assert g["promoted"] is True
            assert all(v <= 0.05
                       for v in g["worst_class_rel_delta"].values())
        rb = r23["rollback_evidence"]
        assert rb["bitwise"] is True
        assert rb["trigger"] == "policy_divergence"
        assert rb["restored"] == gens[0]["incumbent"] or \
            rb["restored"].startswith("gen-")
        assert len(rb["restored_digest"]) == 64
        assert r23["provenance_evidence"]["pass"] is True
        assert r23["determinism_evidence"]["pass"] is True

    def test_readme_ratio_claims(self, readme, baseline):
        pe = baseline["published"]["round23"]["promotion_evidence"]
        m = re.search(
            r"ratios\s+([\d.]+)\s+\(gen-1\s+vs\s+the\s+rule\s+"
            r"incumbent\)\s+and\s+([\d.]+)\s+\(gen-2\s+vs\s+its\s+own\s+"
            r"gen-1\s+parent\)", " ".join(readme.split()))
        assert m, ("README's flywheel ratio claim no longer states the "
                   "numbers in the pinned form — update the claim AND "
                   "this regex together")
        g1, g2 = (float(v) for v in m.groups())
        assert abs(g1 - pe["mean_ratios"][0]) < 5e-7
        assert abs(g2 - pe["mean_ratios"][1]) < 5e-7
        assert g1 < 1.0 and g2 < 1.0
        assert f"promotes {pe['promotions']}/2 gate-passing " \
            "generations" in " ".join(readme.split())

    def test_readme_rollback_claim(self, readme, baseline):
        rb = baseline["published"]["round23"]["rollback_evidence"]
        m = re.search(
            r"demotes\s+gen-002\s+and\s+restores\s+gen-001\s+bitwise\s+"
            r"\(digest\s+([0-9a-f]{12})…\)", " ".join(readme.split()))
        assert m, "README's flywheel rollback claim lost its pinned form"
        assert rb["restored_digest"].startswith(m.group(1))
        assert rb["demoted"] == "gen-002"
        assert rb["restored"] == "gen-001"

    def test_readme_names_the_surfaces(self, readme):
        flat = " ".join(readme.split())
        for needle in ("mine_weakness_cells", "curriculum_from_cells",
                       "curriculum_digest", "promotion_gates",
                       "flywheel-challenger",
                       "ccka flywheel mine|distill|promote| status",
                       "BENCH_r23.json", "policy_divergence",
                       "train/checkpoint.py"):
            assert needle in flat, needle

    def test_architecture_has_section_25(self):
        arch = _read("ARCHITECTURE.md")
        assert "## 25. Continual-learning flywheel" in arch
        flat = " ".join(arch.split())
        for phrase in ("mine_weakness_cells", "CLASS_SCENARIOS",
                       "MINTED_SCORE_BONUS", "curriculum_from_cells",
                       "curriculum_digest", "write_provenance",
                       "load_provenance", "params_sha256",
                       "promotion_gates", "cells_improved",
                       "class_regression_ok", "CLASS_TOLERANCE",
                       "shadow_ok", "set_challenger_checkpoint",
                       "flywheel-challenger", "FlywheelRunner",
                       "policy_divergence", "_FLYWHEEL_CLASS_TOL",
                       "tests/test_flywheel.py"):
            assert phrase in flat, phrase
