"""8-device CPU-mesh parity gate for the multi-chip megakernel
(`parallel/sharded_kernel.py`).

The sharded wrappers earn trust the same way the single-chip kernel did
(`tests/test_megakernel.py`): interpret mode on the virtual 8-device CPU
mesh (conftest forces ``--xla_force_host_platform_device_count=8``),
deterministic, against the single-device kernel — distribution-level on
every EpisodeSummary field via the ONE shared tolerance table
(`mean_parity_violations`), with the decomposition additionally exact by
construction (same per-block kernel math, same shard-locally generated
worlds). Stochastic-mode equivalence cannot execute on CPU (the pltpu
PRNG only lowers on real TPUs), so the PAIRED-PRNG invariant — each
shard's seed offset makes its block streams equal the single-chip
kernel's GLOBAL block streams — is pinned at the seed-arithmetic level
against the kernel's exported stride constants.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import ConfigError, default_config
from ccka_tpu.parallel import (
    make_mesh,
    shard_seed,
    sharded_carbon_summary_from_packed,
    sharded_megakernel_rollout_summary,
    sharded_megakernel_summary_from_packed,
    sharded_neural_summary_from_packed,
    sharded_packed_trace,
)
from ccka_tpu.policy.rule import offpeak_action, peak_action
from ccka_tpu.sim import SimParams
from ccka_tpu.sim.megakernel import (
    SEED_BLOCK_STRIDE,
    carbon_megakernel_summary_from_packed,
    mean_parity_violations,
    megakernel_rollout_summary,
    megakernel_summary_from_packed,
    neural_megakernel_summary_from_packed,
)
from ccka_tpu.signals.synthetic import SyntheticSignalSource

# One shared geometry for the whole module: every test reuses the same
# lru-cached sharded callables (and the single compile they cost), which
# is what keeps this in the fast lane.
B, T, T_CHUNK, B_BLOCK = 128, 64, 32, 16
N_SHARDS = 8


@pytest.fixture(scope="module")
def cfg():
    return default_config()


@pytest.fixture(scope="module")
def setup(cfg):
    params = SimParams.from_config(cfg)
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals)
    return params, src, offpeak_action(cfg.cluster), peak_action(cfg.cluster)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(devices=jax.devices()[:N_SHARDS])


@pytest.fixture(scope="module")
def streams(mesh, setup):
    """(sharded stream, bitwise-identical single-device stream): the
    sharded one generated SHARD-LOCALLY on the mesh, the reference by
    concatenating each shard's block generated with the same folded key
    on one device."""
    _params, src, _off, _peak = setup
    key = jax.random.key(3)
    stream = sharded_packed_trace(mesh, src, T, key, B, t_chunk=T_CHUNK)
    ref = jnp.concatenate(
        [src.packed_trace_device(T, jax.random.fold_in(key, s),
                                 B // N_SHARDS, t_chunk=T_CHUNK)
         for s in range(N_SHARDS)], axis=-1)
    return stream, ref


def _assert_parity(sk, ref, what, *, exact_tol=1e-5):
    """BOTH gates: the shared tolerance table (the pinned contract) and
    the deterministic decomposition's near-exactness."""
    bad = mean_parity_violations(sk, ref)
    assert not bad, f"{what}: shared-table parity broken: {bad}"
    for f in sk._fields:
        a = np.asarray(getattr(sk, f)).astype(np.float64)
        b = np.asarray(getattr(ref, f)).astype(np.float64)
        rel = float(np.max(np.abs(a - b) / (np.abs(b) + 1e-6)))
        assert rel <= exact_tol, f"{what}: field {f} diverged ({rel})"


def test_shard_local_generation_matches_per_shard_reference(streams):
    """The exo stream is born shard-local (fold_in(key, shard)) and is
    bitwise what each shard would generate alone — no ICI, no drift."""
    stream, ref = streams
    assert len(stream.addressable_shards) == N_SHARDS
    np.testing.assert_allclose(np.asarray(stream), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_profile_entry_sharded_parity(mesh, setup, streams):
    """Sharded `_fused_packed_summary` (rule profiles) == single-device
    kernel on the identical worlds, all EpisodeSummary fields."""
    params, _src, off, peak = setup
    stream, ref_stream = streams
    kw = dict(stochastic=False, b_block=B_BLOCK, t_chunk=T_CHUNK,
              interpret=True)
    sk = sharded_megakernel_summary_from_packed(
        mesh, params, off, peak, stream, T, **kw)
    assert len(sk.cost_usd.addressable_shards) == N_SHARDS
    ref = megakernel_summary_from_packed(params, off, peak, ref_stream, T,
                                         **kw)
    _assert_parity(sk, ref, "profile")


def test_carbon_entry_sharded_parity(mesh, setup, streams):
    params, _src, off, peak = setup
    stream, ref_stream = streams
    kw = dict(stochastic=False, b_block=B_BLOCK, t_chunk=T_CHUNK,
              interpret=True)
    sk = sharded_carbon_summary_from_packed(
        mesh, params, off, peak, stream, T, **kw)
    ref = carbon_megakernel_summary_from_packed(
        params, off, peak, ref_stream, T, **kw)
    _assert_parity(sk, ref, "carbon")


def _two_candidates(cfg):
    from ccka_tpu.models import ActorCritic, latent_dim
    from ccka_tpu.sim.megakernel import _obs_dim

    net = ActorCritic(act_dim=latent_dim(cfg.cluster))
    p0 = net.init(jax.random.key(5), jnp.zeros(
        (_obs_dim(cfg.cluster.n_pools, cfg.cluster.n_zones),)))
    p0 = jax.tree.map(
        lambda x: x + 0.3 * jax.random.normal(jax.random.key(7), x.shape),
        p0)
    p1 = jax.tree.map(lambda x: x * 0.5, p0)
    return jax.tree.map(lambda a, b: jnp.stack([a, b]), p0, p1)


@pytest.mark.slow  # ISSUE 16 lane-time rule: profile/carbon sharded
# parity stays fast; neural rides the slow lane with streaming's.
def test_neural_entry_sharded_parity(mesh, cfg, setup, streams):
    """Sharded population-MLP entry: candidates replicated, batch split —
    [NP, B] fields match the single-device population launch."""
    params, _src, _off, _peak = setup
    stream, ref_stream = streams
    stacked = _two_candidates(cfg)
    kw = dict(stochastic=False, b_block=B_BLOCK, t_chunk=T_CHUNK,
              interpret=True)
    sk = sharded_neural_summary_from_packed(
        mesh, params, cfg.cluster, stacked, stream, T, **kw)
    assert np.asarray(sk.cost_usd).shape == (2, B)
    ref = neural_megakernel_summary_from_packed(
        params, cfg.cluster, stacked, ref_stream, T, **kw)
    _assert_parity(sk, ref, "neural")
    # The two candidates genuinely differ (a zero-diff would mean the
    # replicated weights never reached the per-shard kernels).
    assert float(np.max(np.abs(np.asarray(sk.cost_usd)[1]
                               - np.asarray(sk.cost_usd)[0]))) > 0


def test_paired_prng_seed_invariant():
    """The invariant that keeps sharded stochastic runs PAIRED with the
    single-chip kernel (and candidates/rule/teacher with each other):
    local block b of shard s must seed its pltpu stream exactly like
    GLOBAL block s*nb + b on one chip. Pinned against the kernel's
    exported stride so a refactor of either side trips this."""
    seed = 1234
    for n_shards, blocks_per_shard in ((8, 1), (4, 4), (2, 16)):
        for s in range(n_shards):
            for b_loc in range(blocks_per_shard):
                local = shard_seed(seed, s, blocks_per_shard) \
                    + b_loc * SEED_BLOCK_STRIDE
                global_block = s * blocks_per_shard + b_loc
                assert local == seed + global_block * SEED_BLOCK_STRIDE
    # And the kernel actually consumes the exported constants (not stale
    # literals) — the stride arithmetic above is only meaningful then.
    import inspect

    from ccka_tpu.sim import megakernel as mk

    src = inspect.getsource(mk._make_kernel)
    assert "SEED_BLOCK_STRIDE" in src and "SEED_CHUNK_STRIDE" in src


def test_donation_chain_recycles_single_stream(mesh, setup, streams):
    """donate_stream=True: same results, the input buffer genuinely
    freed (CPU supports donation), the returned alias recyclable into
    the next generation's synthesis — and no 'donated buffers were not
    usable' warning anywhere in the chain."""
    import warnings

    params, src, off, peak = setup
    _stream, ref_stream = streams
    kw = dict(stochastic=False, b_block=B_BLOCK, t_chunk=T_CHUNK,
              interpret=True)
    ref = megakernel_summary_from_packed(params, off, peak, ref_stream, T,
                                         **kw)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        stream = sharded_packed_trace(mesh, src, T, jax.random.key(3), B,
                                      t_chunk=T_CHUNK)
        sk, stream2 = sharded_megakernel_summary_from_packed(
            mesh, params, off, peak, stream, T, donate_stream=True, **kw)
        jax.block_until_ready(sk.cost_usd)
        assert stream.is_deleted()
        recycled = sharded_packed_trace(mesh, src, T, jax.random.key(9),
                                        B, t_chunk=T_CHUNK,
                                        recycle=stream2)
        jax.block_until_ready(recycled)
        assert stream2.is_deleted()
    donation_msgs = [str(m.message) for m in w
                     if "donated" in str(m.message).lower()]
    assert not donation_msgs, donation_msgs
    _assert_parity(sk, ref, "donated profile")
    # The recycled buffer carries the NEW key's worlds, not the old ones.
    fresh = src.packed_trace_device(T, jax.random.fold_in(
        jax.random.key(9), 0), B // N_SHARDS, t_chunk=T_CHUNK)
    np.testing.assert_allclose(
        np.asarray(recycled)[..., :B // N_SHARDS], np.asarray(fresh),
        rtol=1e-6, atol=1e-6)


def test_rejects_indivisible_batches(mesh, setup, streams):
    params, src, off, peak = setup
    stream, _ = streams
    with pytest.raises(ConfigError, match="data shards"):
        sharded_packed_trace(mesh, src, T, jax.random.key(0), 12)
    with pytest.raises(ConfigError, match="b_block"):
        sharded_megakernel_summary_from_packed(
            mesh, params, off, peak, stream, T, b_block=12,
            t_chunk=T_CHUNK, interpret=True)


def test_compile_watch_no_recompile_on_repeat(mesh, setup, streams):
    """The sharded entries are compile-watched (obs/compile.py): a
    repeat call with identical shapes must be a cache hit — a recompile
    here would mean the mesh/static plumbing re-keys the cache per call."""
    from ccka_tpu.obs.compile import stats_for

    params, _src, off, peak = setup
    stream, _ = streams
    kw = dict(stochastic=False, b_block=B_BLOCK, t_chunk=T_CHUNK,
              interpret=True)
    s = sharded_megakernel_summary_from_packed(
        mesh, params, off, peak, stream, T, **kw)
    jax.block_until_ready(s.cost_usd)
    st = stats_for("sharded_kernel.packed_summary")
    compiles_before, calls_before = st.compiles, st.calls
    s = sharded_megakernel_summary_from_packed(
        mesh, params, off, peak, stream, T, **kw)
    jax.block_until_ready(s.cost_usd)
    assert st.calls == calls_before + 1
    assert st.compiles == compiles_before, "sharded entry recompiled"


@pytest.mark.slow
def test_trace_taking_wrappers_match_single_device(mesh, cfg, setup):
    """The [B, T]-trace wrappers (pack runs per shard, inside the fused
    jit): parity vs the single-device trace-taking kernel on the SAME
    pre-generated batch. Slow lane: duplicates the packed entries'
    fast-lane parity coverage through one extra layout path."""
    params, src, off, peak = setup
    traces = src.batch_trace_device(T, jax.random.key(11), B)
    kw = dict(stochastic=False, b_block=B_BLOCK, t_chunk=T_CHUNK,
              interpret=True)
    sk = sharded_megakernel_rollout_summary(
        mesh, params, off, peak, traces, **kw)
    ref = megakernel_rollout_summary(params, off, peak, traces, **kw)
    _assert_parity(sk, ref, "trace-taking profile")


@pytest.mark.slow
def test_cem_mega_engine_on_mesh(mesh, cfg):
    """One (1+λ)-ES generation with engine='mega', mesh=: candidates ×
    traces fan out over the 8 shards, worlds synthesized shard-locally.
    Slow lane: `__graft_entry__.dryrun_multichip` runs this same step as
    the driver contract, and the sharded entries' parity is pinned
    above — this adds only their composition."""
    from ccka_tpu.policy import CarbonAwarePolicy
    from ccka_tpu.train.cem import CEMConfig, cem_refine
    from ccka_tpu.train.ppo import PPOTrainer

    params0 = PPOTrainer(cfg).init_state().params
    best, hist, info = cem_refine(
        cfg, params0, SyntheticSignalSource(cfg.cluster, cfg.workload,
                                            cfg.sim, cfg.signals),
        cem=CEMConfig(generations=1, popsize=3, traces_per_gen=B,
                      eval_steps=16),
        engine="mega", mesh=mesh, mega_interpret=True,
        teacher_policy=CarbonAwarePolicy(cfg.cluster), seed=3)
    assert len(hist) == 1
    assert np.isfinite(hist[0]["incumbent_fitness"])
    assert "actor_mean" in best["params"]

    with pytest.raises(ValueError, match="divisible by the data-axis"):
        cem_refine(cfg, params0,
                   SyntheticSignalSource(cfg.cluster, cfg.workload,
                                         cfg.sim, cfg.signals),
                   cem=CEMConfig(generations=1, traces_per_gen=12,
                                 eval_steps=16),
                   engine="mega", mesh=mesh, mega_interpret=True)


@pytest.mark.slow  # ISSUE 16 lane-time rule: plan playback parity keeps
# its single-chip fast-lane proof; the mesh run is duplicative.
def test_plan_playback_entry_sharded_parity(mesh, cfg, setup, streams):
    """Sharded plan-playback entry (ISSUE 4): per-cluster plans split on
    the exo stream's lane axis (and a broadcast plan replicated) must
    match the single-device playback kernel on identical worlds — the
    MPC-vs-rule pairing survives sharding because every entry shares the
    same shard_seed offsets."""
    import math

    from ccka_tpu.models import latent_dim, latent_to_action
    from ccka_tpu.parallel import (shard_plan_stream,
                                   sharded_plan_summary_from_packed)
    from ccka_tpu.sim.megakernel import (
        pack_plan, plan_megakernel_summary_from_packed)

    params, _src, _off, _peak = setup
    stream, ref_stream = streams
    T_pad = math.ceil(T / T_CHUNK) * T_CHUNK
    kw = dict(stochastic=False, b_block=B_BLOCK, t_chunk=T_CHUNK,
              interpret=True)
    lat = 0.3 * jax.random.normal(jax.random.key(19),
                                  (B, T, latent_dim(cfg.cluster)))
    acts = jax.vmap(jax.vmap(
        lambda u: latent_to_action(u, cfg.cluster)))(lat)
    pp = pack_plan(acts, T_pad)
    sk = sharded_plan_summary_from_packed(
        mesh, params, cfg.cluster, shard_plan_stream(mesh, pp), stream,
        T, **kw)
    assert len(sk.cost_usd.addressable_shards) == N_SHARDS
    ref = plan_megakernel_summary_from_packed(
        params, cfg.cluster, pp, ref_stream, T, **kw)
    _assert_parity(sk, ref, "plan playback (per-cluster)")

    # Broadcast form: one plan replicated to every shard.
    acts1 = jax.vmap(lambda u: latent_to_action(u, cfg.cluster))(lat[0])
    pb = pack_plan(acts1, T_pad)
    sk1 = sharded_plan_summary_from_packed(
        mesh, params, cfg.cluster, shard_plan_stream(mesh, pb), stream,
        T, **kw)
    ref1 = plan_megakernel_summary_from_packed(
        params, cfg.cluster, pb, ref_stream, T, **kw)
    _assert_parity(sk1, ref1, "plan playback (broadcast)")
