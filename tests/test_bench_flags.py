"""The scoreboard win flag is significance-gated (VERDICT r4 weak #2):
`beats_rule_both_headlines` requires each headline's paired per-trace
ratio mean to clear 1.0 by two standard errors, so exact ties and
noise-level means can never publish as wins. These tests pin that
contract directly on bench.py's helpers (the reference published raw
eyeballed kubectl/Grafana comparisons — `demo_40_watch_observe.sh` —
with no statistics at all; the framework's scoreboard is held to a
stricter standard because it makes quantitative claims)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402

pytestmark = pytest.mark.quick


def _board(rule_vals, other_vals):
    """Minimal two-backend board in compare_backends' shape."""
    def row(vals):
        return {
            "usd_per_slo_hour": sum(vals) / len(vals),
            "g_co2_per_kreq": sum(vals) / len(vals),
            "slo_attainment": 0.95,
            "per_trace": {"usd_per_slo_hour": list(vals),
                          "g_co2_per_kreq": list(vals)},
        }
    return {"rule": row(rule_vals), "ppo": row(other_vals)}


def _section(rule_vals, other_vals):
    board = _board(rule_vals, other_vals)
    r = dict(board["ppo"])
    r["vs_rule_usd_per_slo_hour"] = (r["usd_per_slo_hour"]
                                     / board["rule"]["usd_per_slo_hour"])
    r["vs_rule_g_co2_per_kreq"] = (r["g_co2_per_kreq"]
                                   / board["rule"]["g_co2_per_kreq"])
    r.update(bench._paired_ratios(board, "ppo"))
    section = {"ppo": r}
    bench._flag_wins(section, board["rule"])
    return section["ppo"]


def test_paired_ratios_carry_ci_and_z():
    board = _board([1.0, 1.0, 1.0, 1.0], [0.9, 0.92, 0.88, 0.9])
    out = bench._paired_ratios(board, "ppo")
    for k in ("usd_per_slo_hour", "g_co2_per_kreq"):
        assert f"vs_rule_{k}_mean" in out
        assert f"vs_rule_{k}_se" in out
        assert f"vs_rule_{k}_ci2se" in out
        assert f"vs_rule_{k}_z" in out
        lo, hi = out[f"vs_rule_{k}_ci2se"]
        assert lo < out[f"vs_rule_{k}_mean"] < hi


def test_clear_win_flags_true():
    r = _section([1.0, 1.0, 1.0, 1.0, 1.0],
                 [0.90, 0.91, 0.89, 0.90, 0.90])
    assert r["beats_rule_both_headlines"] is True
    assert r["win_flag_significance_gated"] is True


def test_exact_tie_is_not_a_win():
    # ADVICE r4 (bench.py:360): a 1.000x/1.000x result must not be
    # labeled 'beats'. With zero spread the CI collapses to [1.0, 1.0]
    # which does not clear 1.0.
    r = _section([1.0, 1.0, 1.0], [1.0, 1.0, 1.0])
    assert r["beats_rule_both_headlines"] is False
    assert r["matches_or_beats_rule_raw"] is True  # continuity flag


def test_noise_level_mean_is_not_a_win():
    # Round 4's replay board in miniature: mean < 1 but one window
    # loses and the 2-se CI straddles 1.0 → no win.
    r = _section([1.0, 1.0, 1.0], [0.981, 0.988, 1.0003])
    assert r["vs_rule_usd_per_slo_hour_mean"] < 1.0
    assert r["vs_rule_usd_per_slo_hour_ci2se"][1] > 1.0
    assert r["beats_rule_both_headlines"] is False


def test_attainment_regression_blocks_win():
    board = _board([1.0] * 5, [0.9] * 5)
    r = dict(board["ppo"])
    r["slo_attainment"] = 0.90  # rule has 0.95
    r["vs_rule_usd_per_slo_hour"] = 0.9
    r["vs_rule_g_co2_per_kreq"] = 0.9
    r.update(bench._paired_ratios(board, "ppo"))
    section = {"ppo": r}
    bench._flag_wins(section, board["rule"])
    assert section["ppo"]["beats_rule_both_headlines"] is False


def test_roofline_floor_rejects_impossible_samples(monkeypatch):
    """VERDICT r5 weak #2: the timer's plausibility floor is derived from
    the work's own memory traffic, not a static 2 ms — a sample that
    implies moving N bytes faster than the measured HBM bandwidth is
    physically impossible and must be discarded, while honest samples of
    tiny workloads (floor << 2 ms) must NOT be rejected."""
    monkeypatch.setitem(bench._HBM_BW_CACHE, "bytes_per_s", 1e9)  # 1 GB/s
    # 1 GB of traffic at 1 GB/s → 0.5 s floor (halved for fused-kernel
    # headroom). A 10 ms "measurement" is impossible → dropped entirely.
    assert bench._roofline_floor_s(1e9) == pytest.approx(0.5)
    assert bench._time_best(lambda: None, repeats=2,
                            bytes_touched=1e9) is None
    # A tiny workload's floor sits near the 0.1 ms absolute minimum, so
    # a real ~1 ms sample passes where the old static 2 ms floor would
    # have rejected it.
    import time as _time
    dt = bench._time_best(lambda: _time.sleep(0.001), repeats=1,
                          bytes_touched=1e3)
    assert dt is not None and dt >= 0.001


def test_provenance_mesh_stamp():
    """Multi-chip records are self-describing (ISSUE 3 satellite): a
    mesh-stamped provenance block carries the mesh shape + axis sizes;
    without a mesh the field still records the visible device count."""
    import jax

    from ccka_tpu.parallel import make_mesh

    mesh = make_mesh(devices=jax.devices()[:8])
    p = bench.bench_provenance(mesh=mesh)
    assert p["mesh"]["shape"] == {"data": 8, "model": 1}
    assert p["mesh"]["axis_names"] == ["data", "model"]
    assert p["mesh"]["n_devices"] == 8
    p0 = bench.bench_provenance()
    assert p0["mesh"]["shape"] is None
    assert p0["mesh"]["n_devices"] >= 1


def test_faults_only_flag_and_stage_wiring():
    """ISSUE 5: the robustness scoreboard has a record path
    (`--faults-only`) and the main sweep carries the stage — argparse
    contract only (the scoreboard itself is exercised in
    tests/test_faults.py and the BENCH_r10 record)."""
    parser_src = open(bench.__file__, encoding="utf-8").read()
    assert "--faults-only" in parser_src
    assert "bench_faults" in parser_src
    # bench_faults delegates to the shared scoreboard module (the CLI's
    # chaos-eval uses the same one — one implementation, two drivers).
    import inspect

    src = inspect.getsource(bench.bench_faults)
    assert "fault_scoreboard" in src


def test_recovery_only_flag_and_stage_wiring():
    """ISSUE 9: the crash-recovery scoreboard has a record path
    (`--recovery-only`) and the main sweep carries the stage — argparse
    contract only (the harness itself is exercised in
    tests/test_recovery.py and the BENCH_r12 record)."""
    parser_src = open(bench.__file__, encoding="utf-8").read()
    assert "--recovery-only" in parser_src
    assert "bench_recovery" in parser_src
    # bench_recovery delegates to the shared harness module (the CLI's
    # recover-eval uses the same one — one implementation, two drivers).
    import inspect

    src = inspect.getsource(bench.bench_recovery)
    assert "recovery_scoreboard" in src


def test_overload_only_flag_and_stage_wiring():
    """ISSUE 10: the multi-tenant overload scoreboard has a record path
    (`--overload-only`) and the main sweep carries the stage — argparse
    contract only (the service itself is exercised in
    tests/test_service.py and the BENCH_r13 record)."""
    parser_src = open(bench.__file__, encoding="utf-8").read()
    assert "--overload-only" in parser_src
    assert "bench_overload" in parser_src
    # bench_overload delegates to the shared board module (the CLI's
    # overload-eval uses the same one — one implementation, two
    # drivers).
    import inspect

    src = inspect.getsource(bench.bench_overload)
    assert "overload_scoreboard" in src


def test_decisions_only_flag_and_stage_wiring():
    """Round 18: the decision-provenance ledger has a record path
    (`--decisions-only`) and the main sweep carries the stage —
    argparse contract only (the ledger itself is exercised in
    tests/test_decisions.py and the BENCH_r18 record)."""
    parser_src = open(bench.__file__, encoding="utf-8").read()
    assert "--decisions-only" in parser_src
    assert "bench_decisions" in parser_src
    import inspect

    src = inspect.getsource(bench.bench_decisions)
    # The stage drives the SAME service + ledger the tests pin (one
    # implementation), pairs ledger-on/off via obs.decisions_enabled,
    # and runs the flagship against the rule shadow.
    assert "fleet_service_from_config" in src
    assert "decisions_enabled" in src
    assert "load_flagship_backend" in src
    assert "verify_dump" in src


def test_perf_only_flag_and_stage_wiring():
    """Round 15: the device-time observatory has a record path
    (`--perf-only`, with `--perf-mesh-only` as its virtual-mesh child)
    and the main sweep carries the stage — argparse contract only (the
    observatory itself is exercised in tests/test_perf_obs.py and the
    BENCH_r15 record)."""
    parser_src = open(bench.__file__, encoding="utf-8").read()
    assert "--perf-only" in parser_src
    assert "--perf-mesh-only" in parser_src
    assert "bench_perf" in parser_src
    # bench_perf delegates to the shared observatory modules (ccka perf
    # drives the same ones — one implementation, two drivers) and the
    # shared per-mode closure builder.
    import inspect

    src = inspect.getsource(bench.bench_perf)
    assert "costmodel" in src and "occupancy" in src
    src_k = inspect.getsource(bench._perf_kernel_fn)
    assert "packed_mode_summary_fn" in src_k
    src_m = inspect.getsource(bench.bench_perf_mesh)
    assert "shard_lane_blocks" in src_m and "measure_shard_times" in src_m


def test_geo_only_flag_and_stage_wiring():
    """Round 19: the geo-arbitrage suite has a record path
    (`--geo-only`) and the main sweep carries the stage — argparse
    contract only (the subsystem itself is exercised in
    tests/test_regions.py and the BENCH_r19 record)."""
    parser_src = open(bench.__file__, encoding="utf-8").read()
    assert "--geo-only" in parser_src
    assert "bench_geo" in parser_src
    import inspect

    src = inspect.getsource(bench.bench_geo)
    # The stage drives the SAME suite/rollout/ledger modules the tests
    # pin (one implementation): the Pareto suite, the zero-rate parity
    # arm against the registry-widened stream, and the migration-term
    # ledger rows.
    assert "run_geo_suite" in src
    assert "packed_region_lanes" in src
    assert "geo_rollout" in src
    assert "DecisionLedger" in src
    assert "zero_migration_parity" in src
