"""Online shadow tournament observatory (round 20,
`obs/tournament.py`).

The contracts pinned here:

- **roster discipline**: unknown candidate names are rejected up
  front, duplicate registrations are refused (module registry AND
  per-roster), and a candidate whose policy fails the registration
  probe leaves the roster unchanged — a broken challenger can never
  corrupt the lanes of the ones already registered;
- **K=1 degeneracy**: a ``("rule",)`` roster's candidate columns are
  BITWISE the round-18 rule-shadow columns riding the same tick — the
  tournament generalizes the shadow, it does not fork it;
- **tournament-on/off bitwise non-interference**: the host ledger
  toggling changes NOTHING about decisions or patch streams (the
  candidate lanes ride the compiled tick unconditionally), while the
  on-run genuinely scores;
- **ledger semantics**: windowed per-workload-class win accounting on
  hand-crafted rows (who wins where is arithmetic, not vibes), window
  retention, and the empty-class None (never a fake 0.0 win rate);
- **the seeded challenger scenario**: an over-provisioned incumbent
  loses to a one-candidate carbon roster — exactly ONE edge-triggered
  ``challenger_sustained_win``, its dump checksum-verified, its
  promotion audit HMAC-valid and never an auto-switch;
- **CLI + bench-diff gates**: `ccka tournament list|board|explain`,
  the tournament invariant gates (injected bad record exits 1, real
  history stays clean), and bench_history staying stdlib-only.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ccka_tpu.config import (SERVICE_PRESETS, ConfigError, ObsConfig,
                             default_config)
from ccka_tpu.harness.service import (VirtualClock,
                                      fleet_service_from_config)
from ccka_tpu.obs.decisions import CAND_COLS, decision_row_layout
from ccka_tpu.obs.recorder import verify_dump
from ccka_tpu.obs.tournament import (CANDIDATE_BUILDERS,
                                     WORKLOAD_CLASSES,
                                     OverProvisionPolicy,
                                     PromotionGate, TournamentLedger,
                                     TournamentRoster,
                                     read_tournament,
                                     register_candidate,
                                     resolve_candidates, sign_audit,
                                     verify_audit, workload_class)
from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cfg():
    return default_config().with_overrides(**{"sim.horizon_steps": 16})


@pytest.fixture(scope="module")
def cfg_k1(cfg):
    """K=1 rule roster — the degeneracy program."""
    return cfg.with_overrides(**{"obs.tournament_roster": ("rule",)})


@pytest.fixture(scope="module")
def cfg_k2(cfg):
    return cfg.with_overrides(
        **{"obs.tournament_roster": ("rule", "carbon")})


def det_clock() -> VirtualClock:
    state = {"s": 0.0}

    def base():
        state["s"] += 1e-4
        return state["s"]
    return VirtualClock(base=base)


def _obs(tmp_path=None, **kw) -> ObsConfig:
    base = dict(enabled=True)
    if tmp_path is not None:
        base.update(dump_dir=str(tmp_path / "dumps"),
                    incident_log_path=str(tmp_path / "incidents.jsonl"),
                    tournament_log_path=str(tmp_path
                                            / "tournament.jsonl"))
    base.update(kw)
    return ObsConfig(**base)


def _run_service(run_cfg, backend, n, obs, *, ticks=8, seed=11,
                 profiles=None, capture_rows=False):
    svc = fleet_service_from_config(
        run_cfg, backend, n,
        profiles=profiles or ["healthy"] * n,
        service=SERVICE_PRESETS["default"], obs=obs,
        horizon_ticks=16, seed=seed, clock=det_clock())
    svc.warmup()
    rows = []
    if capture_rows and svc.tournament is not None:
        orig = svc.tournament.observe_tick

        def spy(t, per_np, layout, **kw):
            rows.append(np.array(per_np))
            return orig(t, per_np, layout, **kw)
        svc.tournament.observe_tick = spy
    reports = svc.run(ticks)
    return svc, reports, rows


class TestRoster:
    def test_unknown_candidate_rejected_up_front(self, cfg):
        with pytest.raises(ValueError,
                           match="unknown tournament candidates"):
            resolve_candidates(("rule", "no-such-policy"))
        with pytest.raises(ValueError,
                           match="unknown tournament candidates"):
            TournamentRoster(cfg, ("no-such-policy",))

    def test_registry_rejects_duplicate_names(self):
        with pytest.raises(ValueError, match="already registered"):
            register_candidate("rule", lambda cfg: None)
        # The losing registration must not have clobbered the original.
        assert "Peak/Off-Peak" in CANDIDATE_BUILDERS["rule"][1]

    def test_roster_rejects_duplicate_lane(self, cfg):
        roster = TournamentRoster(cfg, ("rule",))
        with pytest.raises(ValueError,
                           match="duplicate tournament candidate"):
            roster.register("rule", RulePolicy(cfg.cluster))
        assert roster.names == ("rule",)

    def test_probe_failure_leaves_roster_unchanged(self, cfg):
        """A candidate whose action_fn raises — or returns the wrong
        shape — is refused by the registration probe, and the lanes
        already registered survive untouched."""
        roster = TournamentRoster(cfg, ("rule", "carbon"))

        class Broken:
            def action_fn(self):
                def fn(state, exo, t):
                    raise RuntimeError("no checkpoint for you")
                return fn

        with pytest.raises(ValueError,
                           match="registration probe .roster "
                                 "unchanged."):
            roster.register("broken", Broken())

        class WrongShape:
            def action_fn(self):
                import jax.numpy as jnp
                return lambda state, exo, t: jnp.zeros((1,))

        with pytest.raises(ValueError,
                           match="registration probe .roster "
                                 "unchanged."):
            roster.register("wrong", WrongShape())
        assert roster.names == ("rule", "carbon")
        # And the survivors still resolve callable lanes.
        assert [n for n, _fn in roster.action_fns()] \
            == ["rule", "carbon"]

    def test_config_rejects_duplicate_roster(self):
        with pytest.raises(ConfigError, match="duplicate"):
            ObsConfig(enabled=True,
                      tournament_roster=("carbon", "carbon")).validate()
        with pytest.raises(ConfigError, match="must be a tuple"):
            ObsConfig(enabled=True,
                      tournament_roster=["carbon"]).validate()

    def test_workload_classes_cover_profiles(self):
        assert set(WORKLOAD_CLASSES) == {"inference", "batch",
                                         "background"}
        assert workload_class("healthy") == "inference"
        assert workload_class("batch") == "batch"
        assert workload_class("slow") == "background"
        assert workload_class("flaky") == "background"
        assert workload_class("never-heard-of-it") == "inference"


class TestK1Degeneracy:
    """A ("rule",) roster IS the round-18 rule shadow, bitwise."""

    def test_candidate_columns_bitwise_equal_shadow_columns(
            self, cfg_k1, tmp_path):
        svc, _reports, rows = _run_service(
            cfg_k1, CarbonAwarePolicy(cfg_k1.cluster), 3,
            _obs(tmp_path), ticks=4, capture_rows=True)
        assert len(rows) == 4
        lay = svc._dec_layout
        pairs = [("cand_cost_usd", "shadow_cost_usd"),
                 ("cand_carbon_g", "shadow_carbon_g"),
                 ("cand_pend_c0", "shadow_pend_c0"),
                 ("cand_pend_c1", "shadow_pend_c1"),
                 ("cand_slo_ok", "shadow_slo_ok"),
                 ("cand_div_max", "div_max_abs")]
        for per in rows:
            for cand_col, shadow_col in pairs:
                np.testing.assert_array_equal(
                    per[:, lay.cand_col("rule", cand_col)],
                    per[:, lay.col(shadow_col)],
                    err_msg=f"{cand_col} != {shadow_col}")
        svc.close()

    def test_k0_layout_is_exactly_round18(self, cfg):
        lay0 = decision_row_layout(cfg.cluster)
        lay1 = decision_row_layout(cfg.cluster, candidates=("rule",))
        assert lay0.width < lay1.width
        assert lay1.width == (lay0.width + cfg.cluster.n_regions
                              + len(CAND_COLS) + cfg.cluster.n_regions)
        # The widening is a pure tail: every round-18 column offset is
        # unchanged.
        assert lay0.cols == lay1.cols
        assert lay0.shadow_action == lay1.shadow_action


class TestNonInterference:
    """Tournament-ledger-on vs -off over one seeded world: decisions
    and patch streams bitwise identical — the candidate lanes ride the
    compiled tick either way; only host scoring toggles."""

    def _run(self, run_cfg, backend, tournament, tmp_path=None):
        obs = (_obs(tmp_path, tournament_enabled=tournament)
               if tmp_path is not None
               else ObsConfig(enabled=True,
                              tournament_enabled=tournament))
        svc, _reports, _ = _run_service(run_cfg, backend, 5, obs,
                                        ticks=10,
                                        profiles=["healthy"] * 3
                                        + ["slow", "flaky"])
        out = {
            "usd": svc.tenant_usd_per_slo_hr().copy(),
            "slo": svc.tenant_slo_ticks.copy(),
            "fresh": svc.tenant_fresh_ticks.copy(),
            "commands": [[(c.name, c.patch_type, json.dumps(
                c.patch, sort_keys=True))
                for c in getattr(s, "inner", s).commands]
                for s in svc.sinks],
            "ticks": (svc.tournament.ticks_total
                      if svc.tournament is not None else 0),
            "ledger": svc.tournament,
        }
        svc.close()
        return out

    def test_on_off_bitwise_identical(self, cfg_k2, tmp_path):
        backend = CarbonAwarePolicy(cfg_k2.cluster)
        off = self._run(cfg_k2, backend, False)
        on = self._run(cfg_k2, backend, True, tmp_path)
        np.testing.assert_array_equal(off["usd"], on["usd"])
        np.testing.assert_array_equal(off["slo"], on["slo"])
        np.testing.assert_array_equal(off["fresh"], on["fresh"])
        assert off["commands"] == on["commands"]
        # Non-vacuous both ways.
        assert off["ledger"] is None and off["ticks"] == 0
        assert on["ticks"] == 10
        assert on["ledger"].comparisons_total == 10 * 5 * 2

    def test_empty_roster_builds_no_ledger(self, cfg):
        svc, reports, _ = _run_service(
            cfg, RulePolicy(cfg.cluster), 2,
            ObsConfig(enabled=True), ticks=1)
        assert svc.tournament is None
        assert reports[-1].candidate_win_rate == {}
        assert reports[-1].tournament_leader is None
        svc.close()

    def test_obs_override_roster_mismatch_refused(self, cfg_k2):
        with pytest.raises(ValueError, match="program-shaping"):
            fleet_service_from_config(
                cfg_k2, RulePolicy(cfg_k2.cluster), 2,
                service=SERVICE_PRESETS["default"],
                obs=ObsConfig(enabled=True,
                              tournament_roster=("carbon",)),
                horizon_ticks=16, seed=1)


class TestLedgerSemantics:
    """Win accounting on hand-crafted rows: the board is arithmetic."""

    def _ledger(self, cfg, tmp_path, names=("carbon",), classes=(),
                **obs_kw):
        obs = ObsConfig(enabled=True,
                        tournament_log_path=str(
                            tmp_path / "tournament.jsonl"),
                        tournament_roster=tuple(names), **obs_kw)
        lay = decision_row_layout(cfg.cluster, candidates=names)
        led = TournamentLedger(obs, cfg.train, names,
                               classes=list(classes), policy="chosen")
        return led, lay

    def _row(self, lay, n, *, chosen_cost, cand_cost, name="carbon"):
        """Rows where ONLY the cost term differs: slo_ok=1 both sides,
        pendings zero — win iff cand_cost < chosen_cost."""
        per = np.zeros((n, lay.width), np.float32)
        per[:, 0] = 1.0                       # chosen slo_ok
        per[:, 1] = chosen_cost
        per[:, lay.cand_col(name, "cand_slo_ok")] = 1.0
        per[:, lay.cand_col(name, "cand_cost_usd")] = cand_cost
        return per

    def test_per_class_split_attributes_wins(self, cfg, tmp_path):
        classes = ["inference", "inference", "batch", "background"]
        led, lay = self._ledger(cfg, tmp_path, classes=classes)
        per = self._row(lay, 4, chosen_cost=1.0,
                        cand_cost=np.asarray([2.0, 2.0, 0.5, 0.25],
                                             np.float32))
        s = led.observe_tick(0, per, lay)
        # Candidate wins on the batch and background rows only.
        assert s["candidate_win_rate"] == {"carbon": 0.5}
        board = led._board()
        e = board["carbon"]
        assert e["wins"] == 2 and e["comparisons"] == 4
        assert e["classes"]["inference"]["win_rate"] == 0.0
        assert e["classes"]["inference"]["comparisons"] == 2
        assert e["classes"]["batch"]["win_rate"] == 1.0
        assert e["classes"]["background"]["win_rate"] == 1.0
        # The $ delta is chosen - candidate, summed per class.
        assert e["classes"]["batch"]["usd_delta"] \
            == pytest.approx(0.5, abs=1e-6)
        assert e["classes"]["inference"]["usd_delta"] \
            == pytest.approx(-2.0, abs=1e-6)
        led.close()

    def test_empty_class_is_none_not_fake_zero(self, cfg, tmp_path):
        led, lay = self._ledger(cfg, tmp_path,
                                classes=["inference", "inference"])
        led.observe_tick(0, self._row(lay, 2, chosen_cost=1.0,
                                      cand_cost=0.5), lay)
        e = led._board()["carbon"]
        assert e["classes"]["batch"]["win_rate"] is None
        assert e["classes"]["batch"]["comparisons"] == 0
        led.close()

    def test_window_slides_and_running_sums_stay_exact(self, cfg,
                                                       tmp_path):
        led, lay = self._ledger(cfg, tmp_path,
                                classes=["inference"] * 3,
                                tournament_window=4)
        rng = np.random.default_rng(7)
        for t in range(11):
            per = self._row(
                lay, 3, chosen_cost=1.0,
                cand_cost=rng.random(3).astype(np.float32) * 2.0)
            led.observe_tick(t, per, lay)
            exact = np.sum([w[0] for w in led._window], axis=0)
            np.testing.assert_allclose(led._stat_sum, exact,
                                       atol=1e-9)
        e = led._board()["carbon"]
        assert e["comparisons"] == 4 * 3      # window, not lifetime
        assert led.ticks_total == 11
        assert led.comparisons_total == 11 * 3
        led.close()

    def test_ties_do_not_win(self, cfg, tmp_path):
        """Equal projected totals must not count as a win — the K=1
        rule-vs-rule degenerate board stays all-zero."""
        led, lay = self._ledger(cfg, tmp_path, classes=["inference"])
        led.observe_tick(0, self._row(lay, 1, chosen_cost=1.0,
                                      cand_cost=1.0), lay)
        assert led._board()["carbon"]["win_rate"] == 0.0
        led.close()


class TestAuditSignature:
    def test_sign_verify_roundtrip_and_tamper(self):
        rec = {"kind": "promotion_audit", "t": 3, "challenger": "c",
               "decision": "needs-bench-recheck"}
        rec["signature"] = sign_audit(rec, "k1")
        assert verify_audit(rec, "k1")
        assert not verify_audit(rec, "k2")
        assert not verify_audit({**rec, "t": 4}, "k1")
        assert not verify_audit(
            {k: v for k, v in rec.items() if k != "signature"}, "k1")

    def test_gate_never_auto_switches(self):
        obs = ObsConfig(enabled=True, tournament_audit_key="sekrit")
        gate = PromotionGate(obs, "incumbent")
        board = {"carbon": {"win_rate": 0.9, "classes": {}}}
        plain = gate.review("carbon", board, sustained_ticks=8,
                            window_ticks=16, t=5)
        assert plain["decision"] == "needs-bench-recheck"
        assert plain["auto_switch"] is False
        assert verify_audit(plain, "sekrit")
        good = gate.review("carbon", board, sustained_ticks=8,
                           window_ticks=16, t=6,
                           bench_record={"bitwise_identical": True,
                                         "overhead_gate_ok": True,
                                         "board_gate_ok": True})
        assert good["decision"] == "eligible"
        assert good["auto_switch"] is False
        bad = gate.review("carbon", board, sustained_ticks=8,
                          window_ticks=16, t=7,
                          bench_record={"bitwise_identical": False,
                                        "overhead_gate_ok": True})
        assert bad["decision"] == "blocked"
        assert bad["auto_switch"] is False
        assert gate.audits_total == 3


class TestChallengerIncident:
    """The seeded scenario: an over-provisioned incumbent (HPA 1.5x,
    consolidation off) grows slack the carbon candidate's projected
    consolidation reclaims — exactly ONE edge-triggered
    challenger_sustained_win, dump-attributable, audit-signed."""

    @pytest.fixture(scope="class")
    def ch_run(self, tmp_path_factory):
        run_cfg = default_config().with_overrides(**{
            "sim.horizon_steps": 16,
            "obs.tournament_roster": ("carbon",),
            "obs.tournament_window": 8,
            "obs.tournament_sustain_ticks": 4,
            "obs.tournament_win_rate": 0.6,
        })
        tmp = tmp_path_factory.mktemp("challenger")
        svc, reports, _ = _run_service(
            run_cfg, OverProvisionPolicy(run_cfg.cluster), 4,
            _obs(tmp, tournament_roster=("carbon",),
                 tournament_window=8, tournament_sustain_ticks=4,
                 tournament_win_rate=0.6),
            ticks=24,
            profiles=["healthy", "healthy", "batch", "flaky"])
        yield run_cfg, svc, reports
        svc.close()

    def test_exactly_one_edge_triggered_incident(self, ch_run):
        _cfg, svc, reports = ch_run
        counts = svc.incidents.counts()
        assert counts.get("challenger_sustained_win", 0) == 1
        assert svc.tournament.challengers_total == 1
        # The win is sustained, not a blip: the final windowed rate is
        # still at/above the bar and the leader gauge points at it.
        assert reports[-1].candidate_win_rate["carbon"] >= 0.6
        assert reports[-1].tournament_leader == 0

    def test_incident_attributable_to_verified_dump(self, ch_run):
        _cfg, svc, _reports = ch_run
        incs = [i for i in svc.incidents.incidents
                if i.trigger == "challenger_sustained_win"]
        assert len(incs) == 1
        inc = incs[0]
        assert inc.dump_path is not None
        body = verify_dump(inc.dump_path)
        assert body["t"] == inc.t
        assert inc.details["candidate"] == "carbon"
        assert inc.details["incumbent"] == "overprovision"
        assert inc.details["win_rate"] >= 0.6
        assert inc.details["sustained_ticks"] >= 4

    def test_audit_row_signed_and_never_auto_switch(self, ch_run):
        run_cfg, svc, _reports = ch_run
        rows = read_tournament(svc.obs.tournament_log_path)
        audits = [r for r in rows
                  if r.get("kind") == "promotion_audit"]
        boards = [r for r in rows if r.get("kind") == "board"]
        assert len(audits) == 1
        assert boards, "no board rows logged"
        audit = audits[0]
        key = run_cfg.obs.tournament_audit_key
        assert verify_audit(audit, key)
        assert not verify_audit({**audit, "win_rate": 0.123}, key)
        assert audit["challenger"] == "carbon"
        assert audit["incumbent"] == "overprovision"
        assert audit["decision"] == "needs-bench-recheck"
        assert audit["auto_switch"] is False


class TestTournamentCLI:
    @pytest.fixture(scope="class")
    def cli_log(self, tmp_path_factory):
        run_cfg = default_config().with_overrides(**{
            "sim.horizon_steps": 16,
            "obs.tournament_roster": ("carbon",),
            "obs.tournament_window": 8,
            "obs.tournament_sustain_ticks": 4,
            "obs.tournament_win_rate": 0.6,
        })
        tmp = tmp_path_factory.mktemp("cli-tournament")
        svc, _reports, _ = _run_service(
            run_cfg, OverProvisionPolicy(run_cfg.cluster), 4,
            _obs(tmp, tournament_roster=("carbon",),
                 tournament_window=8, tournament_sustain_ticks=4,
                 tournament_win_rate=0.6),
            ticks=16,
            profiles=["healthy", "healthy", "batch", "flaky"])
        svc.close()
        return svc.obs.tournament_log_path

    def test_list_names_every_registered_candidate(self, capsys):
        from ccka_tpu.cli import main

        assert main(["tournament", "list"]) == 0
        out = capsys.readouterr()
        lines = out.out.strip().splitlines()
        names = {ln.split(":", 1)[0] for ln in lines}
        assert names == set(CANDIDATE_BUILDERS)
        assert "registered candidate builder(s)" in out.err

    def test_board_and_explain(self, cli_log, capsys):
        from ccka_tpu.cli import main

        assert main(["tournament", "board", cli_log]) == 0
        text = capsys.readouterr().out
        assert "incumbent=overprovision" in text
        assert "carbon: win" in text

        assert main(["tournament", "explain", cli_log]) == 0
        text = capsys.readouterr().out
        assert "promotion audit @ tick" in text
        assert "carbon vs incumbent overprovision" in text
        assert "signature=valid" in text
        assert "auto_switch=False" in text

        # The wrong key must SAY the signature does not check out.
        assert main(["tournament", "explain", cli_log,
                     "--key", "not-the-key"]) == 0
        assert "signature=INVALID" in capsys.readouterr().out

    def test_errors(self, cli_log, tmp_path):
        from ccka_tpu.cli import main

        with pytest.raises(SystemExit, match="needs the tournament"):
            main(["tournament", "board"])
        with pytest.raises(SystemExit,
                           match="cannot read tournament log"):
            main(["tournament", "board",
                  str(tmp_path / "missing.jsonl")])
        bad = str(tmp_path / "bad.jsonl")
        with open(bad, "w") as fh:
            fh.write('{"kind": "board", "t": 0}\nGARBAGE\n'
                     '{"kind": "board", "t": 1}\n')
        with pytest.raises(SystemExit,
                           match="corrupt tournament log"):
            main(["tournament", "board", bad])
        empty = str(tmp_path / "empty.jsonl")
        open(empty, "w").close()
        with pytest.raises(SystemExit, match="no board rows"):
            main(["tournament", "board", empty])
        with pytest.raises(SystemExit,
                           match="no challenger has sustained"):
            main(["tournament", "explain", empty])
        with pytest.raises(SystemExit, match="at tick 999"):
            main(["tournament", "board", cli_log, "--t", "999"])


class TestBenchDiffTournamentGates:
    CLEAN = {
        "bitwise_identical": True,
        "ledger_overhead_frac": 0.02,
        "roster": ["rule", "carbon"],
        "board": {
            name: {
                "win_rate": 0.5,
                "classes": {c: {"win_rate": 0.5}
                            for c in WORKLOAD_CLASSES},
            } for name in ("rule", "carbon")},
        "challenger": {"incidents": 1, "dumps_verified": 1,
                       "dump_failures": [], "audit_rows": 1,
                       "audits_verified": 1},
    }

    def _diff(self, tour):
        from ccka_tpu.obs import bench_history

        return bench_history.bench_diff({
            "records": [{"round": 20, "file": "BENCH_r20.json",
                         "platform": "cpu",
                         **bench_history._extract_tournament(tour)}],
            "lane": []})

    def _clean(self, **over):
        tour = json.loads(json.dumps(self.CLEAN))
        tour.update(over)
        return tour

    def test_clean_record_passes(self):
        assert self._diff(self._clean())["ok"]

    def test_each_gate_trips(self):
        cases = [
            (self._clean(bitwise_identical=False), "bitwise"),
            (self._clean(ledger_overhead_frac=0.12), "overhead"),
            (self._clean(roster=["rule"]), "1:1 with the roster"),
            (self._clean(challenger={"incidents": 2,
                                     "dumps_verified": 2,
                                     "dump_failures": [],
                                     "audit_rows": 2,
                                     "audits_verified": 2}),
             "exactly one"),
            (self._clean(challenger={"incidents": 1,
                                     "dumps_verified": 1,
                                     "dump_failures": ["checksum"],
                                     "audit_rows": 1,
                                     "audits_verified": 1}),
             "exactly one"),
            (self._clean(challenger={"incidents": 1,
                                     "dumps_verified": 1,
                                     "dump_failures": [],
                                     "audit_rows": 1,
                                     "audits_verified": 0}),
             "exactly one"),
        ]
        # A win rate outside [0, 1] — overall and per class.
        bad_board = self._clean()
        bad_board["board"]["carbon"]["win_rate"] = 1.5
        cases.append((bad_board, "implausible win rate"))
        bad_cls = self._clean()
        bad_cls["board"]["rule"]["classes"]["batch"]["win_rate"] = -0.1
        cases.append((bad_cls, "implausible win rate"))
        for tour, needle in cases:
            d = self._diff(tour)
            assert not d["ok"], needle
            assert any(needle in r["detail"]
                       for r in d["regressions"]), needle
            assert all(r["kind"] == "tournament_invariant"
                       for r in d["regressions"]
                       if needle in r["detail"])
        # Missing claims are PARTIAL regressions, not silent passes.
        for missing in ("bitwise_identical", "ledger_overhead_frac",
                        "roster", "board", "challenger"):
            tour = self._clean()
            tour.pop(missing)
            d = self._diff(tour)
            assert not d["ok"], missing
            assert any("partial tournament record" in r["detail"]
                       for r in d["regressions"]), missing

    def test_cli_bench_diff_doctored_root_exits_one(self, tmp_path,
                                                    capsys):
        from ccka_tpu.cli import main

        os.makedirs(tmp_path / "data", exist_ok=True)
        doctored = dict(self._clean(bitwise_identical=False),
                        stage="--tournament-only",
                        provenance={"platform": "cpu"})
        with open(tmp_path / "BENCH_r20.json", "w") as fh:
            json.dump(doctored, fh)
        with open(tmp_path / "data" / "lane_times.json", "w") as fh:
            json.dump([], fh)
        assert main(["bench-diff", "--root", str(tmp_path)]) == 1
        out = json.loads(capsys.readouterr().out)
        assert out["regressions"][0]["kind"] == "tournament_invariant"

    def test_real_history_carries_round20_and_stays_clean(self):
        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        history = load_bench_history(_ROOT)
        r20 = [r for r in history["records"] if r["round"] == 20]
        assert r20, "BENCH_r20.json missing from the repo root"
        rec = r20[0]
        assert rec["tournament_bitwise"] is True
        assert rec["tournament_overhead_frac"] <= 0.05
        assert rec["tournament_board_matches_roster"] is True
        assert rec["tournament_challenger_ok"] is True
        assert rec["tournament_partial"] == []
        assert rec["tournament_rate_violations"] == []
        diff = bench_diff(history)
        assert diff["ok"], diff["regressions"]


class TestBenchHistoryStdlibOnly:
    def test_bench_diff_runs_with_jax_and_numpy_blocked(self):
        """`ccka bench-diff` is the CI tripwire — it must keep working
        on a box with NO accelerator stack. Import bench_history in a
        subprocess where jax/numpy/flax can never import, and run a
        real diff through it."""
        code = """
import importlib.util, json, sys

BLOCKED = ("jax", "jaxlib", "numpy", "flax", "optax", "orbax")

class Blocker:
    def find_spec(self, name, path=None, target=None):
        if name.split(".")[0] in BLOCKED:
            raise ImportError(name + " blocked: bench_history must "
                              "stay stdlib-only")
        return None

sys.meta_path.insert(0, Blocker())
for mod in list(sys.modules):
    if mod.split(".")[0] in BLOCKED:
        del sys.modules[mod]

spec = importlib.util.spec_from_file_location(
    "bench_history_stdlib", sys.argv[1])
bh = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bh)

tour = {
    "bitwise_identical": True, "ledger_overhead_frac": 0.02,
    "roster": ["rule"],
    "board": {"rule": {"win_rate": 0.25,
                       "classes": {"inference": {"win_rate": 0.25}}}},
    "challenger": {"incidents": 1, "dumps_verified": 1,
                   "dump_failures": [], "audit_rows": 1,
                   "audits_verified": 1},
}
rec = {"round": 20, "file": "BENCH_r20.json", "platform": "cpu"}
rec.update(bh._extract_tournament(tour))
diff = bh.bench_diff({"records": [rec], "lane": []})
assert diff["ok"], diff["regressions"]
bad = dict(rec)
bad["tournament_bitwise"] = False
assert not bh.bench_diff({"records": [bad], "lane": []})["ok"]
print("STDLIB_ONLY_OK")
"""
        path = os.path.join(_ROOT, "ccka_tpu", "obs",
                            "bench_history.py")
        proc = subprocess.run(
            [sys.executable, "-c", code, path],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "STDLIB_ONLY_OK" in proc.stdout
