"""Streaming rollout pipeline (ISSUE 13): carry-state exactness,
donation-chain bounds, sharded pairing, and misuse rejection.

Contract map:

- **Block-boundary carry exactness**: a blocked rollout (carried state
  crossing every block boundary through exact f32 HBM round trips) is
  BITWISE the unblocked single-launch rollout on the concatenated
  stream — for all four megakernel modes, with fault AND workload lanes
  on, and whether the blocks are consumed synchronously or
  double-buffered. The raw accumulator rows are additionally pinned
  against the LEGACY non-carry kernel program, tying the carry family
  to the pre-streaming pinned contract.
- **Donation chain**: the pipelined drive cycles exactly TWO stream
  buffers per chip, warning-free (an unusable donation warns).
- **8-shard parity**: the mesh streaming drive (shard-local blocked
  generation + lane-sharded carried state) is bitwise the single-chip
  cluster-chunked drive of the same (key, seed) — and, transitively,
  within the ONE shared tolerance table of the unblocked reference.
- **Misuse rejection**: block sizes that don't tile the horizon,
  cluster chunks that don't tile the batch, wrong-layout carried
  state, and wrong-length stream blocks are all rejected up front.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import FAULT_PRESETS, WorkloadsConfig, default_config
from ccka_tpu.sim import SimParams, lanes
from ccka_tpu.sim import streaming as streaming_mod
from ccka_tpu.sim.megakernel import (
    SEED_BLOCK_STRIDE,
    SEED_CHUNK_STRIDE,
    block_chunk_seed,
    mean_parity_violations,
    packed_mode_block_summary_fn,
)
from ccka_tpu.signals.synthetic import SyntheticSignalSource

# One shared geometry for the whole module (one compile per mode).
B, T, BLOCK_T, T_CHUNK, B_BLOCK = 32, 64, 32, 16, 16
KW = dict(T=T, block_T=BLOCK_T, t_chunk=T_CHUNK, b_block=B_BLOCK,
          interpret=True, stochastic=False)


@pytest.fixture(scope="module")
def cfg():
    return default_config()


@pytest.fixture(scope="module")
def setup(cfg):
    """(params, source) with BOTH lane families on — the carry state
    then includes the held-signal rows and the workload queues, so the
    exactness tests cover every row the resume threads."""
    params = SimParams.from_config(cfg)
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals,
                                faults=FAULT_PRESETS["moderate"],
                                workloads=WorkloadsConfig(enabled=True))
    return params, src


@pytest.fixture(scope="module")
def net_params(cfg):
    from ccka_tpu.models import ActorCritic, latent_dim
    from ccka_tpu.sim.megakernel import _obs_dim

    net = ActorCritic(act_dim=latent_dim(cfg.cluster))
    return net.init(jax.random.key(5), jnp.zeros(
        (_obs_dim(cfg.cluster.n_pools, cfg.cluster.n_zones),)))


def _bitwise_fields(a, b):
    return {f for f in a._fields
            if not np.array_equal(np.asarray(getattr(a, f)),
                                  np.asarray(getattr(b, f)))}


class TestBlockLayout:
    def test_layout_arithmetic(self):
        assert lanes.block_layout(96, 32, 16) == (3, 96)
        assert lanes.block_layout(90, 32, 32) == (3, 96)  # padded
        assert lanes.chunk_layout(1024, 256) == 4
        assert lanes.block_bytes(32, 40, 16) == 4 * 32 * 40 * 16

    def test_block_not_chunk_multiple_rejected(self):
        with pytest.raises(ValueError, match="t_chunk"):
            lanes.block_layout(96, 24, 16)

    def test_block_not_tiling_horizon_rejected(self):
        with pytest.raises(ValueError, match="tile"):
            lanes.block_layout(96, 64, 32)

    def test_chunk_not_dividing_batch_rejected(self):
        with pytest.raises(ValueError, match="chunk"):
            lanes.chunk_layout(100, 16)


class TestCarryExactness:
    @pytest.mark.parametrize("mode", [
        # ISSUE 16 lane-time rule: the four params run the SAME
        # carried kernel loop, pinned bitwise per record by the
        # streaming bench gates; all four ride the slow lane.
        pytest.param("rule", marks=pytest.mark.slow),
        pytest.param("carbon", marks=pytest.mark.slow),
        pytest.param("neural", marks=pytest.mark.slow),
        pytest.param("plan", marks=pytest.mark.slow)])
    def test_blocked_equals_unblocked_bitwise(self, cfg, setup,
                                              net_params, mode):
        """The tentpole invariant: pipelined blocked == unblocked
        single launch, bitwise, every EpisodeSummary field — fault +
        workload lanes on, per mode."""
        params, src = setup
        key = jax.random.key(3)
        np_ = net_params if mode == "neural" else None
        s_blk, rep = streaming_mod.streaming_rollout_summary(
            src, params, cfg.cluster, mode, key=key, batch=B, seed=7,
            net_params=np_, pipelined=True, **KW)
        assert rep["n_blocks"] == 2
        s_ref = streaming_mod.unblocked_reference_summary(
            src, params, cfg.cluster, mode, key=key, batch=B, seed=7,
            net_params=np_, **KW)
        assert not _bitwise_fields(s_blk, s_ref), mode

    def test_sync_drive_matches_pipelined_bitwise(self, cfg, setup):
        """The overlap machinery reorders dispatch only — the fenced
        synchronous drive and the double-buffered drive produce the
        SAME summaries on the same (key, seed)."""
        params, src = setup
        key = jax.random.key(4)
        s_sync, rep = streaming_mod.streaming_rollout_summary(
            src, params, cfg.cluster, "rule", key=key, batch=B, seed=7,
            pipelined=False, **KW)
        s_pipe, _ = streaming_mod.streaming_rollout_summary(
            src, params, cfg.cluster, "rule", key=key, batch=B, seed=7,
            pipelined=True, **KW)
        assert not _bitwise_fields(s_sync, s_pipe)
        # The sync drive measured a real per-stage ledger.
        occ = rep["occupancy"]["fractions"]
        assert set(occ) == {"generation", "kernel", "host"}
        # The report's fractions are rounded to 6 dp (occupancy
        # snapshot), so three roundings can land the sum at 1 ± 1.5e-6.
        assert abs(sum(occ.values()) - 1.0) <= 2e-6

    def test_raw_rows_match_legacy_noncarry_program(self, cfg, setup):
        """The carry kernel family is tied to the PINNED pre-streaming
        contract: the blocked chain's final accumulator rows equal the
        legacy non-carry program's on the concatenated stream,
        bitwise."""
        from ccka_tpu.policy.rule import offpeak_action, peak_action
        from ccka_tpu.sim import megakernel as mk

        params, src = setup
        key = jax.random.key(5)
        plan = streaming_mod.plan_stream(T, BLOCK_T, T_CHUNK)
        gen = streaming_mod._block_gen(src, plan, B)
        blocks = [gen(j, key) for j in range(plan.n_blocks)]
        full = jnp.concatenate([jnp.asarray(np.asarray(b))
                                for b in blocks], axis=0)
        fns = packed_mode_block_summary_fn(
            params, cfg.cluster, "rule", **KW)
        state = fns.init_state(full.shape[1], B)
        out = None
        for j, blk in enumerate(blocks):
            out, state, _dead = fns.step(blk, state, j, 7)
        off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
        legacy = mk._run(
            mk._pack_params(params),
            jnp.stack([mk._pack_action(off), mk._pack_action(peak)]),
            full, mk._meta(T, False, 7), P=cfg.cluster.n_pools,
            Z=cfg.cluster.n_zones, K=int(params.provision_pipeline_k),
            WD=int(params.wl_batch_deadline_ticks), stochastic=False,
            b_block=B_BLOCK, t_chunk=T_CHUNK, interpret=True)
        assert np.array_equal(np.asarray(out), np.asarray(legacy))

    @pytest.mark.slow  # ISSUE 14 lane-time rule (~13s): the
    # stream-level mechanism behind the stronger fast-lane composition
    # — test_blocked_equals_unblocked_bitwise runs with fault+workload
    # lanes ON, so a lane drifting under blocking would break its
    # bitwise summary gate.
    def test_lanes_stay_bitwise_under_blocking(self, cfg, setup):
        """Widening a blocked stream with fault/workload lanes changes
        neither the exo rows nor the fault rows bitwise — per block,
        the same invariant the unblocked layouts pin."""
        params, _src = setup
        plain = SyntheticSignalSource(cfg.cluster, cfg.workload,
                                      cfg.sim, cfg.signals)
        faulted = SyntheticSignalSource(
            cfg.cluster, cfg.workload, cfg.sim, cfg.signals,
            faults=FAULT_PRESETS["moderate"])
        both = SyntheticSignalSource(
            cfg.cluster, cfg.workload, cfg.sim, cfg.signals,
            faults=FAULT_PRESETS["moderate"],
            workloads=WorkloadsConfig(enabled=True))
        Z = cfg.cluster.n_zones
        key = jax.random.key(11)
        for j in range(2):
            p = np.asarray(plain.packed_block_trace_device(
                BLOCK_T, key, 8, j, t_chunk=T_CHUNK))
            f = np.asarray(faulted.packed_block_trace_device(
                BLOCK_T, key, 8, j, t_chunk=T_CHUNK))
            w = np.asarray(both.packed_block_trace_device(
                BLOCK_T, key, 8, j, t_chunk=T_CHUNK))
            assert np.array_equal(p, f[:, :lanes.exo_rows(Z)])
            assert np.array_equal(f, w[:, :lanes.exo_rows(Z)
                                       + lanes.fault_rows(Z)])


class TestSeedPairing:
    def test_block_chunk_seed_arithmetic(self):
        """Local chunk t of block j draws the GLOBAL chunk's stream —
        and the time fold composes additively with the shard fold, so
        blocked+sharded runs stay paired with unblocked single-chip
        ones."""
        from ccka_tpu.parallel import shard_seed

        seed = 1234
        for bT, tc in ((32, 16), (96, 32)):
            for j in range(4):
                for t_loc in range(bT // tc):
                    local = block_chunk_seed(seed, j, bT, tc) \
                        + t_loc * SEED_CHUNK_STRIDE
                    global_chunk = j * (bT // tc) + t_loc
                    assert local == seed + global_chunk * SEED_CHUNK_STRIDE
        # Additive composition with the batch-axis offset.
        s = block_chunk_seed(shard_seed(seed, 3, 2), 2, 32, 16)
        assert s == seed + 3 * 2 * SEED_BLOCK_STRIDE \
            + 2 * 2 * SEED_CHUNK_STRIDE

    def test_kernel_consumes_exported_strides(self):
        import inspect

        from ccka_tpu.sim import megakernel as mk

        src = inspect.getsource(mk.block_chunk_seed)
        assert "SEED_CHUNK_STRIDE" in src


class TestDonationChain:
    def test_two_buffers_warning_free(self, cfg, setup):
        """The pipelined drive holds exactly TWO stream buffers per
        chip across the whole block loop, with no 'donated buffers
        were not usable' warning anywhere in the chain."""
        params, src = setup
        kw = dict(KW, T=96)  # 3 blocks: the chain actually cycles
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            _s, rep = streaming_mod.streaming_rollout_summary(
                src, params, cfg.cluster, "rule", key=jax.random.key(6),
                batch=B, seed=7, pipelined=True, count_buffers=True,
                **kw)
        assert rep["n_blocks"] == 3
        assert rep["stream_buffers"] == 2
        donation_msgs = [str(m.message) for m in w
                         if "donated" in str(m.message).lower()]
        assert not donation_msgs, donation_msgs

    def test_recycled_generation_is_bitwise_fresh(self, cfg, setup):
        """One donating generation program serves fresh AND recycled
        blocks (a dummy is donated when no dead buffer exists), so the
        bytes are bitwise independent of the chain's warm-up state."""
        _params, src = setup
        key = jax.random.key(12)
        fresh = np.asarray(src.packed_block_trace_device(
            BLOCK_T, key, B, 1, t_chunk=T_CHUNK,
            recycle=jnp.zeros((BLOCK_T, streaming_mod._stream_rows(src),
                               B), jnp.float32)))
        dead = src.packed_block_trace_device(
            BLOCK_T, key, B, 0, t_chunk=T_CHUNK,
            recycle=jnp.zeros((BLOCK_T, streaming_mod._stream_rows(src),
                               B), jnp.float32))
        recycled = np.asarray(src.packed_block_trace_device(
            BLOCK_T, key, B, 1, t_chunk=T_CHUNK, recycle=dead))
        assert np.array_equal(fresh, recycled)


class TestShardedStreaming:
    @pytest.mark.slow  # ISSUE 16 lane-time rule: 8-shard mesh duplicate of the
    # chunked bitwise gate that stays fast; pinned per record by BENCH_r16.
    def test_mesh_bitwise_chunked_and_tolerance_table(self, cfg, setup):
        """8-shard interpret streaming: shard-local blocked generation
        + lane-sharded carried state is BITWISE the single-chip
        cluster-chunked drive of the same (key, seed) — the sharding
        machinery adds no noise at all. Against the UNCHUNKED reference
        the worlds differ (shard-folded generation is its own keyed
        family, exactly like `sharded_packed_trace` vs a single-device
        stream), so that comparison holds under the ONE shared
        tolerance table instead."""
        from ccka_tpu.parallel import make_mesh

        params, src = setup
        key = jax.random.key(8)
        kw = dict(KW, b_block=4)
        mesh = make_mesh(devices=jax.devices()[:8])
        s_mesh, rep = streaming_mod.streaming_rollout_summary(
            src, params, cfg.cluster, "rule", key=key, batch=B, seed=5,
            mesh=mesh, pipelined=True, **kw)
        s_chunk, _ = streaming_mod.chunked_streaming_summary(
            src, params, cfg.cluster, "rule", key=key, batch=B,
            chunk=B // 8, seed=5, pipelined=True, **kw)
        assert not _bitwise_fields(s_mesh, s_chunk)
        ref = streaming_mod.unblocked_reference_summary(
            src, params, cfg.cluster, "rule", key=key, batch=B, seed=5,
            **kw)
        assert not mean_parity_violations(s_mesh, ref)
        assert rep["pipeline"] == "double-buffered"


class TestMisuseRejection:
    def test_block_not_dividing_horizon(self, cfg, setup):
        params, src = setup
        with pytest.raises(ValueError, match="tile"):
            streaming_mod.streaming_rollout_summary(
                src, params, cfg.cluster, "rule", key=jax.random.key(0),
                batch=B, T=96, block_T=64, t_chunk=32,
                b_block=B_BLOCK, interpret=True, stochastic=False)

    def test_block_not_chunk_multiple(self, cfg, setup):
        params, src = setup
        with pytest.raises(ValueError, match="t_chunk"):
            streaming_mod.plan_stream(96, 24, 16)

    def test_chunk_not_dividing_batch(self, cfg, setup):
        params, src = setup
        with pytest.raises(ValueError, match="chunk"):
            streaming_mod.chunked_streaming_summary(
                src, params, cfg.cluster, "rule", key=jax.random.key(0),
                batch=100, chunk=16, **KW)

    def test_chunk_not_b_block_multiple(self, cfg, setup):
        params, src = setup
        with pytest.raises(ValueError, match="b_block"):
            streaming_mod.chunked_streaming_summary(
                src, params, cfg.cluster, "rule", key=jax.random.key(0),
                batch=48, chunk=24, **KW)

    def test_wrong_length_stream_block(self, cfg, setup):
        params, src = setup
        fns = packed_mode_block_summary_fn(params, cfg.cluster, "rule",
                                           **KW)
        short = jnp.zeros((T_CHUNK, streaming_mod._stream_rows(src), B),
                          jnp.float32)
        state = fns.init_state(short.shape[1], B)
        with pytest.raises(ValueError, match="block_T"):
            fns.step(short, state, 0, 0)

    def test_wrong_length_stream_block_sharded(self, cfg, setup):
        """The mesh bundle enforces the same block-length contract as
        the single-chip one (a wrong-length block would silently
        misalign the valid gate / tod clock / chunk seeds). The raise
        happens host-side, before any mesh program compiles."""
        from ccka_tpu.parallel import (
            make_mesh, sharded_packed_mode_block_summary_fn)

        params, src = setup
        mesh = make_mesh(devices=jax.devices()[:8])
        fns = sharded_packed_mode_block_summary_fn(
            mesh, params, cfg.cluster, "rule", **dict(KW, b_block=4))
        short = jnp.zeros((T_CHUNK, streaming_mod._stream_rows(src), B),
                          jnp.float32)
        state = fns.init_state(short.shape[1], B)
        with pytest.raises(ValueError, match="block_T"):
            fns.step(short, state, 0, 0)

    def test_wrong_layout_state(self, cfg, setup):
        """A carried state built for a different lane layout (plain
        stream vs fault+workload-widened) is rejected, not misread."""
        params, src = setup
        key = jax.random.key(9)
        fns = packed_mode_block_summary_fn(params, cfg.cluster, "rule",
                                           **KW)
        stream = src.packed_block_trace_device(BLOCK_T, key, B, 0,
                                               t_chunk=T_CHUNK)
        Z = cfg.cluster.n_zones
        wrong = fns.init_state(lanes.exo_rows(Z), B)  # plain layout
        with pytest.raises(ValueError, match="carried state"):
            fns.step(stream, wrong, 0, 0)


class TestReplayBlockSource:
    def test_blocked_exo_rows_match_unblocked_windows(self, cfg):
        """Replay blocks: block j of each sampled window replays ticks
        [j*block_T, (j+1)*block_T) of the exact windows the unblocked
        packed stream replays — exo rows concatenate bitwise."""
        from ccka_tpu.signals.base import TraceMeta
        from ccka_tpu.signals.replay import ReplaySignalSource

        plain = SyntheticSignalSource(cfg.cluster, cfg.workload,
                                      cfg.sim, cfg.signals)
        stored = plain.trace(128, seed=3)
        meta = TraceMeta(source="replay", start_unix_s=0.0, dt_s=30.0,
                         zones=cfg.cluster.zones)
        rs = ReplaySignalSource(stored, meta)
        key = jax.random.key(13)
        n = 4
        full = np.asarray(rs.packed_trace_device(T, key, n,
                                                 t_chunk=T_CHUNK))
        blocks = [np.asarray(rs.packed_block_trace_device(
            BLOCK_T, key, n, j, total_steps=T, t_chunk=T_CHUNK))
            for j in range(T // BLOCK_T)]
        cat = np.concatenate(blocks, axis=0)
        assert np.array_equal(full[:T], cat[:T])

    @pytest.mark.slow
    def test_replay_streaming_end_to_end(self, cfg):
        """The streaming driver runs a replay source end to end (the
        recycle path included) and matches its unblocked reference
        bitwise — the 'both synthetic and replay sources' half of the
        tentpole. Slow-marked (ROADMAP lane-time rule): its bitwise
        core — blocked replay rows concatenate to the unblocked
        stream's — stays fast-lane via
        `test_blocked_exo_rows_match_unblocked_windows`, and the drive
        machinery it exercises is the same code the synthetic
        end-to-end pins run fast-lane."""
        from ccka_tpu.signals.base import TraceMeta
        from ccka_tpu.signals.replay import ReplaySignalSource

        params = SimParams.from_config(cfg)
        plain = SyntheticSignalSource(cfg.cluster, cfg.workload,
                                      cfg.sim, cfg.signals)
        stored = plain.trace(128, seed=3)
        meta = TraceMeta(source="replay", start_unix_s=0.0, dt_s=30.0,
                         zones=cfg.cluster.zones)
        rs = ReplaySignalSource(stored, meta,
                                faults=FAULT_PRESETS["mild"])
        key = jax.random.key(14)
        kw = dict(KW, T=96)   # 3 blocks: the recycle path engages
        s_blk, rep = streaming_mod.streaming_rollout_summary(
            rs, params, cfg.cluster, "rule", key=key, batch=16, seed=2,
            pipelined=True, **kw)
        s_ref = streaming_mod.unblocked_reference_summary(
            rs, params, cfg.cluster, "rule", key=key, batch=16, seed=2,
            **kw)
        assert rep["n_blocks"] == 3
        assert not _bitwise_fields(s_blk, s_ref)


def _good_stream_record(**overrides) -> dict:
    """A minimal well-formed --stream-only record for the gate tests
    (mirrors `_good_perf_record`'s role for the round-15 gates)."""
    def row(ratio=1.1, kocc_sync=0.66, kocc_pipe=0.75):
        return {
            "batch": 1024, "steps": 192, "block_T": 96,
            "sync": {"wall_s": 1.0, "kernel_s": 0.66,
                     "occupancy_fractions": {"generation": 0.32,
                                             "kernel": kocc_sync,
                                             "host": 0.02},
                     "cluster_days_per_sec": 300.0},
            "pipelined": {"wall_s": 1.0 / ratio,
                          "cluster_days_per_sec": 300.0 * ratio,
                          "kernel_occupancy_fraction": kocc_pipe,
                          "stream_buffers": 2},
            "throughput_ratio": ratio,
            "bitwise_pipelined_vs_sync": True,
            "bitwise_blocked_vs_unblocked": True,
        }

    rec = {
        "metric": "stream", "round": 92, "stage": "--stream-only",
        "platform": "cpu", "virtual": True,
        "overlap_capable": True,
        "rows": [row()],
        "bitwise_all": True,
        "chunked": {"batch": 10240, "chunk": 1024,
                    "live_block_bytes": 2 * 4 * 96 * 40 * 1024,
                    "roofline_floor_s": 0.01,
                    "bitwise_pipelined_vs_sync": True},
        "mesh8": {"shards": 8, "throughput_ratio": 1.05,
                  "bitwise_mesh_vs_chunked": True,
                  "sync": {"cluster_days_per_sec_aggregate": 500.0},
                  "pipelined": {
                      "cluster_days_per_sec_aggregate": 550.0}},
        "single_chip": {"cluster_days_per_sec": 600.0},
        "provenance": {"platform": "cpu"},
    }
    rec.update(overrides)
    return rec


class TestBenchDiffStreamGates:
    """ISSUE 13 satellite: the bench-history sentinel's streaming
    invariant gates — an injected bad record drives exit 1."""

    def _diff_of(self, tmp_path, rec):
        import json

        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        (tmp_path / "BENCH_r92.json").write_text(json.dumps(rec))
        return bench_diff(load_bench_history(str(tmp_path)))

    def test_good_record_is_clean(self, tmp_path):
        diff = self._diff_of(tmp_path, _good_stream_record())
        assert diff["ok"], diff["regressions"]

    def test_bitwise_break_regresses_and_cli_exits_nonzero(
            self, tmp_path, capsys):
        rec = _good_stream_record()
        rec["rows"][0]["bitwise_blocked_vs_unblocked"] = False
        diff = self._diff_of(tmp_path, rec)
        assert any(r["kind"] == "stream_invariant"
                   for r in diff["regressions"])
        from ccka_tpu.cli import main

        assert main(["bench-diff", "--root", str(tmp_path)]) == 1
        assert "REGRESSION" in capsys.readouterr().err

    def test_ratio_below_one_on_capable_host(self, tmp_path):
        rec = _good_stream_record()
        rec["rows"][0]["throughput_ratio"] = 0.93
        assert not self._diff_of(tmp_path, rec)["ok"]
        # A single-core virtual host is held to the floor, not 1.0...
        rec = _good_stream_record(overlap_capable=False)
        rec["rows"][0]["throughput_ratio"] = 0.93
        assert self._diff_of(tmp_path, rec)["ok"]
        # ...but not past it.
        rec = _good_stream_record(overlap_capable=False)
        rec["rows"][0]["throughput_ratio"] = 0.5
        assert not self._diff_of(tmp_path, rec)["ok"]

    def test_occupancy_below_sync_baseline(self, tmp_path):
        rec = _good_stream_record()
        rec["rows"][0]["pipelined"]["kernel_occupancy_fraction"] = 0.5
        diff = self._diff_of(tmp_path, rec)
        assert any("occupancy" in r["detail"]
                   for r in diff["regressions"])

    def test_buffer_bound(self, tmp_path):
        rec = _good_stream_record()
        rec["rows"][0]["pipelined"]["stream_buffers"] = 3
        diff = self._diff_of(tmp_path, rec)
        assert any("buffers" in r["detail"] for r in diff["regressions"])

    def test_partial_record_is_a_regression(self, tmp_path):
        rec = _good_stream_record()
        del rec["chunked"]
        assert not self._diff_of(tmp_path, rec)["ok"]
        rec = _good_stream_record()
        del rec["mesh8"]
        assert not self._diff_of(tmp_path, rec)["ok"]
        rec = _good_stream_record()
        del rec["chunked"]["live_block_bytes"]
        assert not self._diff_of(tmp_path, rec)["ok"]

    def test_scaling_curve_labels_stream_rows(self, tmp_path):
        """`ccka scaling-curve` ingests the streaming record: blocked
        rows labeled with the `pipeline` column, not skipped."""
        import json

        from ccka_tpu.obs.bench_history import (SCALING_CSV_COLUMNS,
                                                scaling_curve,
                                                write_scaling_csv)

        (tmp_path / "BENCH_r92.json").write_text(
            json.dumps(_good_stream_record()))
        curve = scaling_curve(str(tmp_path))
        stream_pts = [p for p in curve["points"]
                      if p["source"].startswith("stream")]
        pipelines = {p.get("pipeline") for p in stream_pts}
        assert {"sync", "double-buffered"} <= pipelines
        assert any(p["source"] == "stream_chunked" for p in stream_pts)
        assert any(p["source"] == "stream_mesh" for p in stream_pts)
        assert any(p["source"] == "stream_single_chip"
                   for p in curve["per_round"])
        assert "pipeline" in SCALING_CSV_COLUMNS
        path = write_scaling_csv(curve, str(tmp_path / "c.csv"))
        head, *rows = open(path, encoding="utf-8").read().splitlines()
        assert "pipeline" in head.split(",")
        assert any(",double-buffered," in r for r in rows)
