"""Mesh-parallel tests on the virtual 8-device CPU mesh (see conftest.py).

Covers the SURVEY §2.4 "Intra-policy parallelism" row: the cluster batch
must actually split across devices, and sharded results must match the
single-device `vmap` path bit-for-bit (pure data parallelism — no
cross-cluster math changes under sharding).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ccka_tpu.parallel  # noqa: F401  (import-health: VERDICT round-1 breakage)
from ccka_tpu.config import ConfigError, MeshConfig
from ccka_tpu.parallel import (
    batch_sharding,
    make_mesh,
    replicate,
    shard_batch,
    shard_params,
    shard_ppo_state,
    sharded_batched_rollout,
)
from ccka_tpu.policy import RulePolicy
from ccka_tpu.sim import SimParams, batched_rollout, initial_state
from ccka_tpu.signals.synthetic import SyntheticSignalSource

# Compile-heavy: every test jits over the 8-device virtual mesh.
pytestmark = pytest.mark.slow


def _batch(cfg, b, steps, seed=0):
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim, cfg.signals)
    traces = src.batch_trace(steps, range(seed, seed + b))
    states = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (b,) + x.shape), initial_state(cfg))
    keys = jax.random.split(jax.random.key(seed), b)
    return states, traces, keys


def test_eight_devices_present():
    # conftest forces --xla_force_host_platform_device_count=8; if this
    # fails, every sharding assertion below is vacuous.
    assert jax.device_count() >= 8


def test_make_mesh_default_uses_all_devices():
    mesh = make_mesh()
    assert mesh.shape["data"] == jax.device_count()
    assert mesh.shape["model"] == 1


def test_make_mesh_rejects_indivisible():
    with pytest.raises(ConfigError):
        make_mesh(MeshConfig(model_parallel=3), devices=jax.devices()[:8])


def test_shard_batch_actually_shards():
    mesh = make_mesh(devices=jax.devices()[:8])
    x = jnp.arange(16 * 3, dtype=jnp.float32).reshape(16, 3)
    sx = shard_batch(mesh, x)
    assert sx.sharding == batch_sharding(mesh, 2)
    # Each of the 8 devices holds a distinct 2-row shard.
    assert len(sx.addressable_shards) == 8
    rows = sorted(s.data.shape[0] for s in sx.addressable_shards)
    assert rows == [2] * 8
    np.testing.assert_array_equal(np.asarray(sx), np.asarray(x))


def test_shard_batch_rejects_indivisible_batch():
    mesh = make_mesh(devices=jax.devices()[:8])
    with pytest.raises(ConfigError):
        shard_batch(mesh, jnp.zeros((10, 3)))


def test_replicate_places_on_all_devices():
    mesh = make_mesh(devices=jax.devices()[:8])
    x = replicate(mesh, jnp.arange(4.0))
    assert x.sharding.is_fully_replicated
    assert len(x.devices()) == 8


def test_shard_params_model_axis():
    mesh = make_mesh(MeshConfig(model_parallel=4, data_parallel=2),
                     devices=jax.devices()[:8])
    params = {
        "kernel": jnp.zeros((16, 32)),   # 32 % 4 == 0 -> column-sharded
        "head": jnp.zeros((16, 5)),      # 5 % 4 != 0 -> replicated
        "bias": jnp.zeros((32,)),        # 1-D -> replicated
    }
    sp = shard_params(mesh, params)
    kernel_shards = {s.data.shape for s in sp["kernel"].addressable_shards}
    assert kernel_shards == {(16, 8)}
    assert sp["head"].sharding.is_fully_replicated
    assert sp["bias"].sharding.is_fully_replicated


def test_sharded_rollout_matches_vmap(small_cfg):
    """Numerical parity: 8-way sharded rollout == single-device vmap."""
    cfg = small_cfg
    params = SimParams.from_config(cfg)
    b, steps = 8, 16
    states, traces, keys = _batch(cfg, b, steps)
    action_fn = RulePolicy(cfg.cluster).action_fn()

    final_ref, metrics_ref = jax.jit(
        lambda s, t, k: batched_rollout(params, s, action_fn, t, k,
                                        stochastic=True))(states, traces, keys)

    mesh = make_mesh(devices=jax.devices()[:8])
    final_sh, metrics_sh = sharded_batched_rollout(
        mesh, params, states, action_fn, traces, keys, stochastic=True)

    # Output stays distributed (no implicit gather to device 0).
    assert len(final_sh.acc_cost_usd.addressable_shards) == 8
    # Parity up to compilation differences: the two lowerings fuse/reorder
    # float reductions differently, and the dynamics' sigmoid gates can
    # amplify those last-ulp differences over a rollout.
    for ref, sh in zip(jax.tree.leaves((final_ref, metrics_ref)),
                       jax.tree.leaves((final_sh, metrics_sh))):
        np.testing.assert_allclose(np.asarray(ref), np.asarray(sh),
                                   rtol=2e-4, atol=1e-5)


def test_sharded_ppo_iteration_runs_and_matches(small_cfg):
    """One full PPO training step under 8-way sharding: executes, and the
    updated params match the unsharded iteration (same rng, same data)."""
    from ccka_tpu.train.ppo import PPOTrainer

    cfg = small_cfg.with_overrides(**{
        "train.batch_clusters": 8, "train.unroll_steps": 4})
    trainer = PPOTrainer(cfg)
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals)
    ts0 = trainer.init_state()
    window = trainer.make_windows(src, 1, seed=7)

    ts_ref, diag_ref = trainer._iteration_fn(ts0, window)

    mesh = make_mesh(devices=jax.devices()[:8])
    ts_sh = shard_ppo_state(mesh, trainer.init_state())
    window_sh = shard_batch(mesh, window)
    ts_out, diag_sh = trainer._iteration_fn(ts_sh, window_sh)

    # Env batch stays sharded through the iteration.
    assert len(ts_out.env_states.acc_cost_usd.addressable_shards) == 8
    np.testing.assert_allclose(float(diag_ref.mean_reward),
                               float(diag_sh.mean_reward), rtol=1e-4)
    for ref, sh in zip(jax.tree.leaves(ts_ref.params),
                       jax.tree.leaves(ts_out.params)):
        np.testing.assert_allclose(np.asarray(ref), np.asarray(sh),
                                   rtol=2e-4, atol=2e-5)
