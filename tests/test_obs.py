"""Tests for the obs subsystem: span tracer + Chrome trace export,
dispatch/recompile counters (incl. the forecaster-instance-keyed MPC
recompile and the steady-state controller loop), structured run logs and
the `ccka obs` CLI, and bench provenance stamping.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.obs import (
    RunLog,
    SpanTracer,
    read_runlog,
    stats_for,
    summarize_runlog,
    validate_chrome_trace,
    watch_jit,
)


class TestSpanTracer:
    def test_nesting_and_chrome_schema(self, tmp_path):
        jsonl = str(tmp_path / "spans.jsonl")
        tr = SpanTracer(jsonl_path=jsonl)
        with tr.span("outer", stage="demo"):
            with tr.span("inner"):
                pass
        with tr.span("outer"):  # re-entry: second event, same name
            pass
        tr.close()

        doc = tr.chrome_trace()
        assert validate_chrome_trace(doc) == []
        by_name = {}
        for ev in doc["traceEvents"]:
            assert ev["ph"] == "X"
            by_name.setdefault(ev["name"], []).append(ev)
        assert len(by_name["outer"]) == 2 and len(by_name["inner"]) == 1
        # Nesting: the child's interval lies inside its parent's.
        inner, outer = by_name["inner"][0], by_name["outer"][0]
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] \
            + 1.0  # 1us rounding slack
        assert inner["args"]["depth"] == 1
        assert outer["args"]["depth"] == 0

        # The JSONL stream carries the same spans, durably.
        records = [json.loads(l) for l in open(jsonl) if l.strip()]
        assert [r["name"] for r in records] == ["inner", "outer", "outer"]
        assert all(r["dur_us"] >= 0 for r in records)

    def test_device_fence_marks_and_blocks(self):
        tr = SpanTracer()
        with tr.span("matmul") as sp:
            y = jnp.ones((64, 64)) @ jnp.ones((64, 64))
            sp.fence(y)
        (span,) = tr.spans()
        assert span.cat == "device"
        assert span.dur_s >= 0.0

    def test_raising_fence_keeps_bookkeeping_intact(self):
        """A fence that raises at block time (XLA runtime error, device
        failure) must not corrupt the nesting stack or drop the span —
        later spans on the thread would otherwise mis-nest forever."""
        class FailingArr:
            def block_until_ready(self):
                raise RuntimeError("xla runtime error")

        tr = SpanTracer()
        with pytest.raises(RuntimeError, match="xla runtime error"):
            with tr.span("outer"):
                with tr.span("bad") as sp:
                    sp.fence(FailingArr())
        with tr.span("after"):
            pass
        spans = {(s.name, s.depth) for s in tr.spans()}
        assert ("bad", 1) in spans       # recorded despite the raise
        assert ("outer", 0) in spans
        assert ("after", 0) in spans     # stack recovered: depth 0

    def test_bounded_retention_drops_oldest(self):
        tr = SpanTracer(max_spans=3)
        for i in range(6):
            with tr.span(f"s{i}"):
                pass
        assert [s.name for s in tr.spans()] == ["s3", "s4", "s5"]

    def test_device_span_requires_fence(self):
        tr = SpanTracer()
        with pytest.raises(RuntimeError, match="without a fence"):
            with tr.device_span("oops"):
                pass
        # The fenced form passes.
        with tr.device_span("ok") as sp:
            sp.fence(jnp.ones(4))

    def test_write_chrome_trace_is_loadable_json(self, tmp_path):
        tr = SpanTracer()
        with tr.span("a"):
            pass
        path = tr.write_chrome_trace(str(tmp_path / "sub" / "trace.json"))
        doc = json.load(open(path))
        assert validate_chrome_trace(doc) == []
        assert doc["traceEvents"][0]["name"] == "a"

    def test_validator_rejects_malformed(self):
        assert validate_chrome_trace({}) == [
            "traceEvents missing or not a list"]
        bad = {"traceEvents": [{"name": "x", "ph": "X", "ts": "zero",
                                "pid": 1, "tid": 1, "dur": 1}]}
        assert any("not numeric" in p for p in validate_chrome_trace(bad))


class TestWatchJit:
    def test_forced_recompile_on_changed_static_arg(self):
        f = watch_jit(jax.jit(lambda x, n: x * n, static_argnums=1),
                      "obs_test.static", warn=lambda m: None)
        x = jnp.ones(8)
        f(x, 2)
        f(x, 2)
        f(x, 3)  # new static value -> recompile
        assert f.stats.compiles == 2
        assert f.stats.cache_hits == 1
        assert f.stats.calls == 3
        assert f.stats.compile_s > 0.0
        # Registry carries the same object.
        assert stats_for("obs_test.static") is f.stats

    def test_steady_loop_zero_recompiles_after_warmup(self):
        warns = []
        f = watch_jit(jax.jit(lambda x: x + 1), "obs_test.steady",
                      hot=True, warn=warns.append)
        x = jnp.zeros(4)
        for _ in range(5):
            x = f(x)
        assert f.stats.compiles == 1  # the warmup compile only
        assert f.stats.cache_hits == 4
        assert not warns

    def test_hot_path_recompile_warns(self):
        warns = []
        f = watch_jit(jax.jit(lambda x, n: x * n, static_argnums=1),
                      "obs_test.hot", hot=True, warn=warns.append)
        x = jnp.ones(4)
        f(x, 1)
        f(x, 2)
        assert len(warns) == 1 and "RECOMPILED" in warns[0]

    def test_traced_calls_pass_through_uncounted(self):
        inner = watch_jit(jax.jit(lambda x: x * 2), "obs_test.inner")
        outer = jax.jit(lambda x: inner(x) + 1)
        assert float(outer(jnp.float32(3.0))) == 7.0
        # The inlined trace-time call must not count as a dispatch.
        assert inner.stats.calls == 0

    def test_attribute_passthrough(self):
        jitted = jax.jit(lambda x: x)
        f = watch_jit(jitted, "obs_test.attrs")
        assert f.lower is jitted.lower  # delegation, not a copy


class TestMPCRecompileDetection:
    @pytest.mark.slow
    def test_forecaster_config_shares_the_replan_compile(self, cfg):
        """Round 7 pinned the HAZARD here (a fresh same-config
        forecaster instance silently recompiled the replan path);
        round 9 fixed the cache key itself (config-keyed
        `Forecaster.__hash__`, ARCHITECTURE §8) — this now pins the
        FIX: same config, fresh instance, cache HIT — while an
        identity-hashed forecaster (the old behavior, simulated)
        still trips the counter, so the detector keeps working.

        Slow lane (round 9, 840s budget): four replan compiles; the
        fast lane pins the fix via `tests/test_forecast.py`'s
        cache-hit test (which rides an existing compile) and the
        detector via the controller/sharded-kernel watch tests."""
        from ccka_tpu.forecast import make_forecaster
        from ccka_tpu.forecast.backends import PersistenceForecaster
        from ccka_tpu.sim import initial_state
        from ccka_tpu.signals.synthetic import SyntheticSignalSource
        from ccka_tpu.train.mpc import MPCBackend

        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        trace = src.trace(8, seed=0)
        s0 = initial_state(cfg)

        def evaluate(fc):
            b = MPCBackend(cfg, horizon=4, iters=1, replan_every=4,
                           forecaster=fc)
            b.evaluate(s0, trace, jax.random.key(0), stochastic=False)

        stats = stats_for("mpc.receding_horizon_rollout")
        f1 = make_forecaster("persistence", dt_s=cfg.sim.dt_s)
        evaluate(f1)
        after_first = stats.compiles
        evaluate(f1)  # same instance: cache hit, no recompile
        assert stats.compiles == after_first
        f2 = make_forecaster("persistence", dt_s=cfg.sim.dt_s)
        evaluate(f2)  # equal config, fresh instance: cache HIT (the fix)
        assert stats.compiles == after_first

        class _IdentityHashed(PersistenceForecaster):
            """The pre-round-9 behavior: instance identity as the key."""

            __eq__ = object.__eq__
            __hash__ = object.__hash__

        evaluate(_IdentityHashed())  # new static value: compile
        after_identity = stats.compiles
        assert after_identity == after_first + 1
        evaluate(_IdentityHashed())  # fresh identity: the hazard, caught
        assert stats.compiles == after_identity + 1
        assert stats.last_compile_call == stats.calls


class TestControllerSteadyState:
    def test_zero_recompiles_after_warmup(self, cfg):
        """Acceptance: the steady-state controller loop compiles its
        estimate step at most once PER CONFIG for the whole session —
        the round-12 config-keyed shared step cache
        (`controller._compiled_steps`) means a second controller of the
        same config (a crash-resume, a recovery-harness pair) reuses the
        first one's compile, so stats are measured as deltas."""
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, DryRunSink(),
                          interval_s=0.0, log_fn=lambda _l: None)
        s = ctrl._step.stats
        calls0, compiles0 = s.calls, s.compiles
        ctrl.run(ticks=4)
        assert s.calls - calls0 == 4
        assert s.compiles - compiles0 <= 1     # 0 if the cfg already ran
        assert s.compiles >= 1
        assert s.cache_hits >= 3
        # And a SECOND controller of the same config pays zero compiles
        # — the crash-resume property, pinned at the compile counter.
        ctrl2 = Controller(cfg, RulePolicy(cfg.cluster), src, DryRunSink(),
                           interval_s=0.0, log_fn=lambda _l: None)
        assert ctrl2._step is ctrl._step
        compiles1 = s.compiles
        ctrl2.run(ticks=2)
        assert s.compiles == compiles1
        ctrl2.close()
        ctrl.close()

    def test_tick_spans_share_a_tracer(self, cfg):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        tracer = SpanTracer()
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, DryRunSink(),
                          interval_s=0.0, tracer=tracer,
                          log_fn=lambda _l: None)
        ctrl.run(ticks=2)
        ctrl.close()
        names = [s.name for s in tracer.spans()]
        # Two ticks x the seven phases, in one Perfetto-exportable trace.
        assert names.count("decide") == 2
        assert names.count("estimate") == 2
        # The device stages fenced (decide on the action, estimate on
        # the step outputs) — category says so.
        cats = {s.name: s.cat for s in tracer.spans()}
        assert cats["decide"] == "device"
        assert cats["estimate"] == "device"
        assert validate_chrome_trace(tracer.chrome_trace()) == []


class TestRunLog:
    def test_events_echo_and_schema(self, tmp_path, capsys):
        path = str(tmp_path / "run.jsonl")
        with RunLog(path, kind="demo", meta={"seed": 3}) as rl:
            rl.note("hello operator")
            rl.event("gen", generation=0, fitness=1.5)
            rl.event("gen", _echo="gen 1 done", generation=1, fitness=0.8)
        records = read_runlog(path)
        assert [r["event"] for r in records] == [
            "start", "note", "gen", "gen", "end"]
        assert records[0]["kind"] == "demo"
        assert records[0]["meta"] == {"seed": 3}
        assert all("elapsed_s" in r for r in records[1:])
        err = capsys.readouterr().err
        assert "hello operator" in err and "gen 1 done" in err

    def test_callable_drops_into_log_callbacks(self, tmp_path):
        rl = RunLog(str(tmp_path / "r.jsonl"))
        log = rl  # the trainers' log= parameter shape
        log("progress line")
        rl.close()
        recs = read_runlog(str(tmp_path / "r.jsonl"))
        assert recs[1] == {"event": "note", "msg": "progress line",
                           "elapsed_s": recs[1]["elapsed_s"]}

    def test_crashed_run_is_flagged_unterminated(self, tmp_path):
        path = str(tmp_path / "crash.jsonl")
        rl = RunLog(path, kind="flagship")
        rl.event("eval", iteration=40, score=1.01)
        # ... process dies here: no close(), no "end" event.
        del rl
        board = summarize_runlog(read_runlog(path))
        assert board["completed"] is False
        assert "unterminated" in board["status"]
        # The completed generations ARE machine-parseable (the bugfix).
        assert board["fields"]["iteration"]["last"] == 40

    def test_tolerates_midwrite_partial_line(self, tmp_path):
        path = str(tmp_path / "live.jsonl")
        with open(path, "w") as fh:
            fh.write('{"event": "start", "kind": "x"}\n')
            fh.write('{"event": "gen", "fitn')  # killed mid-write
        records = read_runlog(path)
        assert len(records) == 1
        with pytest.raises(json.JSONDecodeError):
            read_runlog(path, strict=True)

    def test_torn_final_line_counted_not_swallowed(self, tmp_path):
        """ISSUE 11 satellite: a crash mid-write truncates the FINAL
        record — the reader returns the intact prefix and COUNTS the
        torn tail (pre-round-14 it silently skipped any malformed
        line, so the log just looked shorter)."""
        path = str(tmp_path / "crash.jsonl")
        with RunLog(path, kind="t", echo=lambda s: None) as rl:
            for g in range(4):
                rl.event("gen", generation=g)
        # Truncate mid-way through the final record's bytes.
        full = open(path, "rb").read()
        last_start = full.rstrip(b"\n").rfind(b"\n") + 1
        cut = last_start + (len(full) - last_start) // 2
        with open(path, "wb") as fh:
            fh.write(full[:cut])
        records, stats = read_runlog(path, with_stats=True)
        assert stats == {"torn_tail": 1}
        # The intact prefix: start + 4 gens minus whatever the cut ate
        # (here the "end" record), in order, fully parsed.
        assert [r["event"] for r in records] == ["start"] + ["gen"] * 4
        assert records[-1]["generation"] == 3
        # A clean file counts zero torn tails.
        clean = str(tmp_path / "clean.jsonl")
        with RunLog(clean, kind="t", echo=lambda s: None):
            pass
        _recs, stats = read_runlog(clean, with_stats=True)
        assert stats == {"torn_tail": 0}

    def test_interior_corruption_raises_even_nonstrict(self, tmp_path):
        """Mid-file garbage is corruption, not a mid-write tear — it
        must fail loudly instead of mis-parsing into a plausible
        shorter log (the pre-round-14 behavior)."""
        path = str(tmp_path / "corrupt.jsonl")
        with open(path, "w") as fh:
            fh.write('{"event": "start", "kind": "x"}\n')
            fh.write('NOT JSON\n')
            fh.write('{"event": "end", "status": "ok"}\n')
        with pytest.raises(json.JSONDecodeError, match="corruption"):
            read_runlog(path)

    def test_unregistered_event_rejected_at_write(self, tmp_path):
        """The event registry (round 14): names are schema identifiers
        the incident-timeline join trusts, enforced at write time AND
        statically (tests/test_timing_guard.py)."""
        from ccka_tpu.obs import RUNLOG_EVENTS

        rl = RunLog(str(tmp_path / "r.jsonl"), echo=lambda s: None)
        with pytest.raises(ValueError, match="unregistered RunLog"):
            rl.event("my_novel_event", x=1)
        for name in ("eval", "gen", "iter", "incident"):
            assert name in RUNLOG_EVENTS
        rl.close()

    def test_error_exit_records_status(self, tmp_path):
        path = str(tmp_path / "err.jsonl")
        with pytest.raises(RuntimeError):
            with RunLog(path) as rl:
                rl.event("gen", generation=0)
                raise RuntimeError("boom")
        end = read_runlog(path)[-1]
        assert end["event"] == "end" and end["status"] == "error"
        assert "boom" in end["error"]


class TestObsCLI:
    def _write_runlog(self, path):
        with RunLog(path, kind="t", echo=lambda s: None) as rl:
            for g in range(5):
                rl.event("gen", generation=g, fitness=1.0 - 0.1 * g)

    def test_tail(self, tmp_path, capsys):
        from ccka_tpu.cli import main

        path = str(tmp_path / "r.jsonl")
        self._write_runlog(path)
        assert main(["obs", "tail", path, "-n", "3"]) == 0
        lines = [json.loads(l) for l in
                 capsys.readouterr().out.strip().splitlines()]
        assert len(lines) == 3
        assert lines[-1]["event"] == "end"
        assert lines[0]["generation"] == 3

    def test_summarize(self, tmp_path, capsys):
        from ccka_tpu.cli import main

        path = str(tmp_path / "r.jsonl")
        self._write_runlog(path)
        assert main(["obs", "summarize", path]) == 0
        board = json.loads(capsys.readouterr().out)
        assert board["completed"] is True
        assert board["counts"]["gen"] == 5
        assert board["fields"]["fitness"]["min"] == pytest.approx(0.6)

    def test_missing_file_is_a_clean_error(self):
        from ccka_tpu.cli import main

        with pytest.raises(SystemExit, match="cannot read run log"):
            main(["obs", "summarize", "/nonexistent/run.jsonl"])

    @pytest.mark.slow
    def test_summarize_roundtrips_a_cem_refine_run(self, tmp_path,
                                                   capsys, cfg):
        """Acceptance: `ccka obs summarize` on a RunLog written by a
        short cem_refine run.

        Slow lane (round 9, 840s budget — at 43s this was the lane's
        single worst offender): the expensive half (a real lax
        cem_refine run) duplicates TestRefinementMechanics' coverage
        and the CLI half duplicates test_summarize on a synthetic
        runlog; only their composition (cem's own "gen" events through
        the summarize parser) is unique, which the slow lane keeps."""
        from ccka_tpu.cli import main
        from ccka_tpu.signals.synthetic import SyntheticSignalSource
        from ccka_tpu.train.cem import CEMConfig, cem_refine
        from ccka_tpu.train.ppo import PPOTrainer

        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        params0 = PPOTrainer(cfg).init_state().params
        path = str(tmp_path / "cem.jsonl")
        with RunLog(path, kind="cem", echo=lambda s: None) as rl:
            cem_refine(cfg, params0, src,
                       cem=CEMConfig(generations=2, popsize=4,
                                     traces_per_gen=2, eval_steps=32),
                       seed=3, runlog=rl)
        assert main(["obs", "summarize", path]) == 0
        board = json.loads(capsys.readouterr().out)
        assert board["kind"] == "cem"
        assert board["counts"]["gen"] == 2
        assert board["completed"] is True
        assert np.isfinite(board["fields"]["incumbent_fitness"]["last"])


class TestBenchProvenance:
    def test_provenance_fields_present(self):
        """Acceptance: the BENCH record's provenance block pins device
        kind, jax/jaxlib versions, timing mode, and the roofline floor
        basis — on CPU too."""
        import bench

        prov = bench.bench_provenance()
        for key in ("device_kind", "platform", "n_devices", "jax_version",
                    "jaxlib_version", "timing_mode", "roofline_floor"):
            assert key in prov, key
        assert prov["jax_version"] == jax.__version__
        assert prov["timing_mode"] == bench.TIMING_MODE
        assert "basis" in prov["roofline_floor"]
        assert "measured_bw_bytes_per_s" in prov["roofline_floor"]

    def test_time_best_emits_spans_for_the_trace(self, tmp_path):
        """Every timed bench sample is a span — the Perfetto trace the
        bench writes shows exactly what was measured."""
        import bench

        before = len(bench._TRACER.spans())
        dt = bench._time_best(lambda: None, repeats=2, min_valid_s=0.0,
                              label="obs_test")
        assert dt is not None and dt >= 0.0
        spans = [s for s in bench._TRACER.spans()[before:]
                 if s.name == "bench.obs_test"]
        assert len(spans) == 2
        path = bench._TRACER.write_chrome_trace(
            str(tmp_path / "bench_trace.json"))
        doc = json.load(open(path))
        assert validate_chrome_trace(doc) == []
        assert any(ev["name"] == "bench.obs_test"
                   for ev in doc["traceEvents"])

    def test_mega_time_phase_emits_provenance_and_trace(self, tmp_path,
                                                        capsys):
        """The CPU-path equivalent of `python bench.py --mega-phase
        time`: the phase's JSON record carries provenance and writes a
        Perfetto-loadable trace file even where the Mosaic kernel cannot
        run (its rows are skipped, the record contract holds)."""
        import bench

        trace_out = str(tmp_path / "mega_trace.json")
        rc = bench.main(["--mega-phase", "time", "--mega-sizes", "64",
                         "--mega-horizon", "16", "--mega-repeats", "1",
                         "--trace-out", trace_out])
        assert rc == 0
        rows = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        prov = rows["provenance"]
        for key in ("device_kind", "jax_version", "jaxlib_version",
                    "timing_mode", "roofline_floor"):
            assert key in prov, key
        assert rows["trace_file"] == trace_out
        doc = json.load(open(trace_out))
        assert validate_chrome_trace(doc) == []
        assert any(ev["name"] == "bench.mega_time_phase"
                   for ev in doc["traceEvents"])


class TestFlagshipRunLog:
    @pytest.mark.slow
    def test_train_flagship_writes_structured_evals(self, tmp_path, cfg):
        """The satellite bugfix end-to-end: a flagship run leaves a
        machine-parseable record of every selection evaluation (rides the
        slow lane with the other flagship composition smoke)."""
        from ccka_tpu.train.flagship import train_flagship

        path = str(tmp_path / "flagship.jsonl")
        train_flagship(cfg, iterations=2, eval_every=2, eval_steps=64,
                       n_eval_traces=1, log=lambda s: None, runlog=path)
        records = read_runlog(path)
        evals = [r for r in records if r["event"] == "eval"]
        assert len(evals) >= 2  # it-0 + the trained candidate
        assert all("usd_ratio" in r and "score" in r for r in evals)
        assert records[-1]["event"] == "end"
        assert records[0]["meta"]["refine"] == "ppo"


def test_fleet_spans_and_watch(cfg):
    """Fleet ticks emit dispatch/harvest/fanout spans and the batched
    decide is compile-watched. Round 13 made the fleet tick a
    config-keyed SHARED compile (`fleet._compiled_fleet_tick`,
    shared_stats like the controller's estimate step), so the pins are
    deltas — and a second fleet over the same backend must reuse the
    first one's XLA program with ZERO new compiles (the overload
    board's paired stressed/calm services depend on exactly this)."""
    from ccka_tpu.harness.fleet import fleet_controller_from_config
    from ccka_tpu.policy import RulePolicy

    backend = RulePolicy(cfg.cluster)
    ctrl = fleet_controller_from_config(cfg, backend, 3, horizon_ticks=8)
    stats = ctrl._tick_fn.stats
    calls0, compiles0, hits0 = stats.calls, stats.compiles, \
        stats.cache_hits
    reports = ctrl.run(3)
    names = [s.name for s in ctrl.tracer.spans()]
    assert names.count("fleet.dispatch") == 3
    assert names.count("fleet.fanout") == 3
    assert stats.calls - calls0 == 3
    assert stats.compiles - compiles0 == 1   # one warmup compile
    assert stats.cache_hits - hits0 == 2     # then cache hits
    assert all(r.decide_ms >= 0 and r.fanout_ms >= 0 for r in reports)
    # Shared compile: same (cfg, backend, N, horizon) → same program.
    ctrl2 = fleet_controller_from_config(cfg, backend, 3,
                                         horizon_ticks=8, seed=9)
    compiles1 = stats.compiles
    ctrl2.run(1)
    assert ctrl2._tick_fn is ctrl._tick_fn
    assert stats.compiles == compiles1       # zero new compiles
    ctrl.close()
    ctrl2.close()
