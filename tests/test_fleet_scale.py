"""Fleet-scale host loop (round 21): the bench-diff invariant gates,
the 10^3-tenant chunked-dispatch parity pin, and the async scrape
fan-in's deadline-abandon contract.

The contracts pinned here:

- **bench-diff gates** (`obs/bench_history.py`): the round-21 record
  must carry a >= 10x vectorized-vs-object speedup, true parity flags,
  an exactly-1.0 healthy-tenant isolation ratio in every stressed
  cell, and a monotone-sane per-tenant p99 curve; a doctored record
  drives `ccka bench-diff` to exit 1, partial/unreadable records are
  regressions, and small-N latency noise is NOT a false positive;
- **chunked dispatch parity**: an N=1024 fleet ticked through
  `sim/lanes.chunk_layout`-sized chunks is bitwise the unchunked run
  on a deterministic clock — reports, patch streams, ledgers;
- **deadline-abandon transport** (`signals/transport.ScrapeFanIn`):
  a hung socket is abandoned at the budget edge (never awaited), a
  re-scrape of the still-hung tenant fails fast, and the straggler
  drains once its own socket timeout fires.
"""

from __future__ import annotations

import json

import pytest

from ccka_tpu.config import default_config


def _good_fleet_record(**overrides) -> dict:
    """A minimal healthy `--fleet-scale-only` stage record: every
    surface `_extract_fleet_scale` gates on, with the real record's
    shape (sweep x scenarios cells, parity flags, speedup pair)."""
    sweep = [16, 256, 10240]
    scen = ["calm", "slow0.25_moderate"]
    # Per-tenant p99 falls with N in both scenarios; the n16 cell is
    # deliberately noisy (one slow tick) — the gate must not care.
    p99 = {16: {"calm": 101.0, "slow0.25_moderate": 106.0},
           256: {"calm": 152.0, "slow0.25_moderate": 267.0},
           10240: {"calm": 486.0, "slow0.25_moderate": 224.0}}
    cells = {}
    for n in sweep:
        for s in scen:
            cell = {
                "n_tenants": n, "scenario": s,
                "dispatch_chunk": 256 if n >= 1024 else None,
                "latency_ms": {"p50": p99[n][s] * 0.5,
                               "p99": p99[n][s],
                               "max": p99[n][s] * 1.1},
                "host_loop_us_per_tenant": 10.0 / n,
                "sheds_total": n,
            }
            if s != "calm":
                cell["healthy_usd_ratio_max"] = 1.0
                cell["healthy_usd_ratio_mean"] = 1.0
            cells[f"n{n}/{s}"] = cell
    rec = {
        "stage": "--fleet-scale-only",
        "engine": "vectorized fleet-service host loop",
        "ticks_per_run": 12,
        "sweep_n": sweep,
        "scenarios": scen,
        "cells": cells,
        "parity": {"bitwise_identical": True},
        "chunk_parity": {"bitwise_identical": True},
        "speedup": {"n_tenants": 10240, "scenario": "calm", "ticks": 24,
                    "object_us_per_tenant": 1.5,
                    "vectorized_us_per_tenant": 0.12, "ratio": 12.5},
        "provenance": {"platform": "cpu"},
    }
    rec.update(overrides)
    return rec


class TestBenchDiffFleetScaleGates:
    """ISSUE 18 satellite: the sentinel's fleet-scale invariant gates —
    an injected doctored record drives exit 1, the real history stays
    clean, small-N noise stays green."""

    def _diff_of(self, tmp_path, rec):
        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        (tmp_path / "BENCH_r94.json").write_text(json.dumps(rec))
        return bench_diff(load_bench_history(str(tmp_path)))

    def _fleet_regressions(self, diff):
        return [r for r in diff["regressions"]
                if r["kind"] == "fleet_scale_invariant"]

    def test_good_record_is_clean(self, tmp_path):
        diff = self._diff_of(tmp_path, _good_fleet_record())
        assert diff["ok"], diff["regressions"]

    def test_speedup_below_floor_regresses_and_cli_exits_one(
            self, tmp_path, capsys):
        rec = _good_fleet_record()
        rec["speedup"]["ratio"] = 8.0
        diff = self._diff_of(tmp_path, rec)
        bad = self._fleet_regressions(diff)
        assert any(r.get("threshold") == 10.0 and r.get("value") == 8.0
                   for r in bad), diff["regressions"]
        from ccka_tpu.cli import main

        assert main(["bench-diff", "--root", str(tmp_path)]) == 1
        assert "REGRESSION" in capsys.readouterr().err

    def test_parity_flags_false_regress(self, tmp_path):
        for key in ("parity", "chunk_parity"):
            rec = _good_fleet_record()
            rec[key] = {"bitwise_identical": False}
            diff = self._diff_of(tmp_path, rec)
            assert not diff["ok"], key
            assert any("bitwise" in r["detail"]
                       for r in self._fleet_regressions(diff)), key

    def test_healthy_ratio_off_one_regresses_either_direction(
            self, tmp_path):
        # 0.97 AND 1.03 both regress: the gate is exact equality, not
        # a floor — a "cheaper" healthy tenant under stress means the
        # pairing broke, not that isolation improved.
        for ratio in (0.97, 1.03):
            rec = _good_fleet_record()
            rec["cells"]["n256/slow0.25_moderate"][
                "healthy_usd_ratio_mean"] = ratio
            diff = self._diff_of(tmp_path, rec)
            assert any("isolation" in r["detail"]
                       for r in self._fleet_regressions(diff)), ratio

    def test_rising_per_tenant_p99_regresses(self, tmp_path):
        rec = _good_fleet_record()
        # n10240 calm p99 jumps to 40x the n256 per-tenant level.
        rec["cells"]["n10240/calm"]["latency_ms"] = {
            "p50": 100.0, "p99": 25000.0, "max": 26000.0}
        diff = self._diff_of(tmp_path, rec)
        assert any("monotone" in r["detail"]
                   for r in self._fleet_regressions(diff)), \
            diff["regressions"]

    def test_small_n_noise_is_not_a_false_positive(self, tmp_path):
        # A 100x per-tenant p99 at N=16 (one slow tick swamps the
        # quotient at small N) must NOT trip the monotone gate — the
        # check starts at the _FLEET_P99_MIN_N floor.
        rec = _good_fleet_record()
        rec["cells"]["n16/calm"]["latency_ms"] = {
            "p50": 1.0, "p99": 900.0, "max": 950.0}
        diff = self._diff_of(tmp_path, rec)
        assert diff["ok"], diff["regressions"]

    def test_percentile_ordering_broken_regresses(self, tmp_path):
        rec = _good_fleet_record()
        rec["cells"]["n256/calm"]["latency_ms"] = {
            "p50": 200.0, "p99": 150.0, "max": 160.0}
        diff = self._diff_of(tmp_path, rec)
        assert any("ordering" in r["detail"]
                   for r in self._fleet_regressions(diff))

    def test_partial_records_are_regressions(self, tmp_path):
        # Absent is partial, not green — each degraded shape trips.
        rec = _good_fleet_record()
        del rec["speedup"]
        assert not self._diff_of(tmp_path, rec)["ok"]
        rec = _good_fleet_record()
        del rec["parity"]
        assert not self._diff_of(tmp_path, rec)["ok"]
        rec = _good_fleet_record()
        del rec["cells"]["n10240/calm"]
        diff = self._diff_of(tmp_path, rec)
        assert any("missing" in r["detail"]
                   for r in self._fleet_regressions(diff))
        rec = _good_fleet_record()
        del rec["cells"]
        assert not self._diff_of(tmp_path, rec)["ok"]
        # A full stage record that never reached the 10^4 point.
        rec = _good_fleet_record()
        rec["sweep_n"] = [16, 256]
        rec["cells"] = {k: v for k, v in rec["cells"].items()
                        if "10240" not in k}
        diff = self._diff_of(tmp_path, rec)
        assert any("10^4" in r["detail"]
                   for r in self._fleet_regressions(diff))

    def test_real_history_is_clean_and_round21_extracted(self):
        import os

        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        history = load_bench_history(root)
        diff = bench_diff(history)
        assert diff["ok"], diff["regressions"]
        rec = {r["round"]: r for r in history["records"]}[21]
        # The committed record states the acceptance numbers.
        assert rec["fleet_scale_speedup"] >= 10.0
        assert rec["fleet_scale_parity"] is True
        assert rec["fleet_scale_chunk_parity"] is True
        assert rec["fleet_scale_healthy_exact"] is True
        assert rec["fleet_scale_partial"] == []
        assert rec["fleet_scale_p99_violations"] == []

    def test_scaling_curve_ingests_tenant_axis_rows(self, tmp_path):
        from ccka_tpu.obs.bench_history import scaling_curve

        (tmp_path / "BENCH_r94.json").write_text(
            json.dumps(_good_fleet_record()))
        curve = scaling_curve(str(tmp_path))
        rows = [p for p in curve["points"]
                if p.get("source") == "fleet_scale"]
        # 6 sweep cells + the speedup row, tenant count on the batch
        # axis, the numbers in the note (the CLI's fallback column).
        assert len(rows) == 7
        assert {p["per_device_batch"] for p in rows} == {16, 256, 10240}
        assert any("us/tenant" in p["note"] and "chunk 256" in p["note"]
                   for p in rows)
        assert any(p["note"].startswith("speedup:")
                   and "12.5x" in p["note"] for p in rows)


@pytest.mark.slow
class TestChunkedDispatchParity:
    """ISSUE 18 satellite: an N=1024 fleet ticked in 256-tenant chunks
    (the `sim/lanes.chunk_layout` path) is bitwise the unchunked run
    on a deterministic clock."""

    def test_n1024_chunked_bitwise_unchunked(self):
        from ccka_tpu.harness.fleetscale import _run_paired
        from ccka_tpu.policy import RulePolicy

        cfg = default_config().with_overrides(**{"sim.horizon_steps": 16})
        n = 1024
        profiles = ["healthy"] * n
        from ccka_tpu.config import SERVICE_PRESETS
        import dataclasses
        svc = dataclasses.replace(SERVICE_PRESETS["default"],
                                  admission_queue_cap=n - 64)
        res = _run_paired(
            cfg, RulePolicy(cfg.cluster), n, profiles, svc,
            ticks=4, seed=211, horizon=8,
            variants={"chunked": ("vectorized", 256),
                      "unchunked": ("vectorized", None)})
        assert res["bitwise_identical"], res["mismatches"]
        assert res["variants"]["chunked"]["dispatch_chunk"] == 256


class TestScrapeFanInDeadlines:
    """The async transport's deadline-abandon contract against a real
    hung socket (accepts, never responds)."""

    @pytest.fixture()
    def hung_server(self):
        import socket
        import threading

        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.bind(("127.0.0.1", 0))
        srv.listen(8)
        port = srv.getsockname()[1]
        conns = []
        stop = threading.Event()

        def accept_loop():
            srv.settimeout(0.1)
            while not stop.is_set():
                try:
                    c, _ = srv.accept()
                    conns.append(c)     # hold open, never respond
                except OSError:
                    continue

        th = threading.Thread(target=accept_loop, daemon=True)
        th.start()
        yield port
        stop.set()
        th.join(timeout=2)
        for c in conns:
            c.close()
        srv.close()

    def _hung_fetch(self, port, socket_timeout_s):
        import socket

        def fetch():
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=socket_timeout_s) as s:
                s.settimeout(socket_timeout_s)
                s.sendall(b"GET /metrics\r\n")
                return s.recv(1024)     # never arrives
        return fetch

    def test_hung_socket_abandoned_at_budget_edge_never_awaited(
            self, hung_server):
        import time

        from ccka_tpu.signals.transport import ScrapeFanIn

        fan = ScrapeFanIn(
            [self._hung_fetch(hung_server, socket_timeout_s=1.5),
             lambda: b"ok"], workers=4)
        try:
            t0 = time.monotonic()
            res = fan.fan_in([0, 1], budget_s=0.25)
            took = time.monotonic() - t0
            # The healthy tenant completed, the hung one was recorded
            # as a timeout AT the budget edge — not after the socket's
            # own 1.5s timeout.
            assert res[1] == (True, False)
            assert res[0] == (False, True)
            assert took < 1.0
            assert fan.abandoned_total == 1
            assert fan.stragglers() == [0]
            # Re-scraping the still-hung tenant fails FAST (no second
            # request stacks behind the dead endpoint).
            t0 = time.monotonic()
            assert fan.scrape(0, budget_s=5.0) == (False, True)
            assert time.monotonic() - t0 < 0.5
            # The straggler drains by its OWN socket timeout, proving
            # nothing awaited it: the worker unwinds on schedule.
            deadline = time.monotonic() + 4.0
            while fan.stragglers() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert fan.stragglers() == []
        finally:
            fan.close()

    def test_http_fan_in_builds_per_url_fetchers(self):
        from ccka_tpu.signals.transport import http_scrape_fan_in

        calls = []

        def fetch(url, headers):
            calls.append(url)
            return b"x"

        fan = http_scrape_fan_in(
            ["http://a/metrics", "http://b/metrics"], fetch=fetch)
        try:
            res = fan.fan_in([0, 1], budget_s=2.0)
            assert res == {0: (True, False), 1: (True, False)}
            assert sorted(calls) == ["http://a/metrics",
                                     "http://b/metrics"]
            assert fan.completed_total == 2
        finally:
            fan.close()
