"""MPC-distillation data factory (ISSUE 14): cell mechanics, label
parity against the lax reference engine, dataset plumbing into
`imitate(dataset=...)`, up-front name validation, and the bench-history
sentinel's factory invariant gates (an injected bad record exits 1).
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.models import latent_dim
from ccka_tpu.sim import SimParams
from ccka_tpu.sim.megakernel import mean_parity_violations
from ccka_tpu.sim.rollout import lax_mode_summary
from ccka_tpu.train import factory as factory_mod
from ccka_tpu.workloads.scenarios import WORKLOAD_SCENARIOS

# One tiny shared geometry (compiles cached across the module).
FKW = dict(pairs=8, steps=32, block_T=16, t_chunk=16, b_block=8,
           iters=2)


@pytest.fixture(scope="module")
def cfg():
    return default_config()


@pytest.fixture(scope="module")
def cell(cfg):
    """One produced cell with fault lanes ON (intensity "mild") — the
    widened-stream path through planning, playback and collection."""
    return factory_mod.produce_cell(
        cfg, WORKLOAD_SCENARIOS["diurnal-inference"], "mild", seed=3,
        with_ledger=True, **FKW)


class TestValidation:
    def test_unknown_names_rejected_up_front(self, cfg):
        with pytest.raises(ValueError, match="unknown scenarios"):
            factory_mod.validate_factory_names(
                scenarios=("no-such",), intensities=("off",),
                teacher="mpc")
        with pytest.raises(ValueError, match="unknown intensities"):
            factory_mod.validate_factory_names(
                scenarios=("mixed",), intensities=("catastrophic",),
                teacher="mpc")
        with pytest.raises(ValueError, match="unknown teacher"):
            factory_mod.validate_factory_names(
                scenarios=("mixed",), intensities=("off",),
                teacher="gpt")

    def test_cli_rejects_unknown_names(self):
        from ccka_tpu.cli import main

        with pytest.raises(SystemExit, match="unknown scenarios"):
            main(["distill-factory", "--scenarios", "no-such"])
        with pytest.raises(SystemExit, match="unknown intensities"):
            main(["distill-factory", "--intensities", "huge"])
        with pytest.raises(SystemExit, match="unknown teacher"):
            main(["distill-factory", "--teacher", "oracle"])

    def test_pairs_must_divide_b_block(self, cfg):
        with pytest.raises(ValueError, match="b_block"):
            factory_mod.produce_cell(
                cfg, WORKLOAD_SCENARIOS["mixed"], "off",
                **dict(FKW, pairs=12))


class TestProduceCell:
    @pytest.mark.slow  # ISSUE 16 lane-time rule:
    # the cell fixture's full produce run; shapes are re-proven by the
    # slow lane and the record's bench-diff factory gates.
    def test_dataset_shapes_and_clip(self, cfg, cell):
        n_rows = FKW["pairs"] * FKW["steps"]
        A = latent_dim(cfg.cluster)
        assert cell.dataset.obs.shape[0] == n_rows
        assert cell.dataset.target.shape == (n_rows, A)
        assert cell.dataset.returns.shape == (n_rows,)
        t = np.asarray(cell.dataset.target)
        assert np.all(np.abs(t) <= 3.0 + 1e-6)
        assert cell.plan_latents.shape == (FKW["pairs"], FKW["steps"],
                                           A)

    @pytest.mark.slow  # ISSUE 16 lane-time rule: rides the slow lane
    # with the cell fixture (one produce run serves all three).
    def test_paired_summaries_and_report(self, cell):
        for s in (cell.teacher_summary, cell.rule_summary):
            assert np.asarray(s.usd_per_slo_hour).shape \
                == (FKW["pairs"],)
        rep = cell.report
        for key in ("pairs_per_sec", "plans_per_sec",
                    "playback_cluster_days_per_sec", "wall_s", "seed",
                    "playback_occupancy"):
            assert rep.get(key) is not None, key
        assert rep["dataset_rows"] == FKW["pairs"] * FKW["steps"]
        assert rep["playback"]["pipeline"] == "double-buffered"

    @pytest.mark.slow  # ISSUE 16 lane-time rule:
    # label parity rides the slow lane with the cell fixture.
    def test_labels_match_the_lax_reference_engine(self, cfg, cell):
        """The factory's kernel playback labels == the registry's lax
        plan engine on the SAME stream and plans — the tentpole's
        one-vocabulary claim, end to end (deterministic interpret, the
        ONE shared tolerance table)."""
        params = SimParams.from_config(cfg)
        sc = WORKLOAD_SCENARIOS["diurnal-inference"]
        stream = factory_mod._cell_stream(
            factory_mod._cell_source(cfg, sc, "mild"),
            steps=FKW["steps"], block_T=FKW["block_T"],
            t_chunk=FKW["t_chunk"], pairs=FKW["pairs"],
            key=jax.random.key(cell.report["seed"]))
        lax = lax_mode_summary(params, cfg.cluster, "plan", stream,
                               FKW["steps"], jax.random.key(0),
                               plan_latents=cell.plan_latents)
        bad = mean_parity_violations(cell.teacher_summary, lax)
        assert not bad, bad

    @pytest.mark.slow  # lane-time rule: the receding-horizon teacher
    # compiles its own batch-planner program (~20s) and only re-proves
    # the protocol switch; the "mpc" path carries the pinned contract.
    def test_mpc_rh_teacher_runs(self, cfg):
        cell = factory_mod.produce_cell(
            cfg, WORKLOAD_SCENARIOS["diurnal-inference"], "off",
            teacher="mpc-rh", seed=5, **dict(FKW, iters=4))
        assert cell.report["teacher"] == "mpc-rh"
        assert cell.plan_latents.shape[1] == FKW["steps"]


class TestFactoryRunAndDistill:
    @pytest.mark.slow  # ISSUE 16 lane-time rule: e2e duplicate of the
    # produce-cell + distill units; the ratio is pinned by BENCH_r17.
    def test_sweep_concats_cells_and_distills(self, cfg):
        # Both cells keep the module cell fixture's stream LAYOUT
        # (faults+workloads) so every kernel program is already warm —
        # only the second scenario's generation program compiles.
        run_kw = {k: v for k, v in FKW.items() if k != "pairs"}
        dataset, report = factory_mod.factory_run(
            cfg, scenarios=("diurnal-inference", "batch-backfill"),
            intensities=("mild",), seed=3,
            pairs_per_cell=FKW["pairs"], **run_kw)
        assert len(report["cells"]) == 2
        assert report["pairs_total"] == 2 * FKW["pairs"]
        assert dataset.obs.shape[0] == 2 * FKW["pairs"] * FKW["steps"]
        for row in report["cells"]:
            assert row["teacher_vs_rule_usd_per_slo_hour"] is not None
        from ccka_tpu.train.imitate import imitate

        params, hist = imitate(cfg, None, None, dataset=dataset,
                               iterations=5, minibatch=256, seed=0)
        assert hist[-1]["actor_mse"] >= 0.0
        mean, _, _ = __import__("ccka_tpu.models", fromlist=["x"]) \
            .ActorCritic(act_dim=latent_dim(cfg.cluster)) \
            .apply(params, np.asarray(dataset.obs[0]))
        assert mean.shape == (latent_dim(cfg.cluster),)

    @pytest.mark.slow  # ISSUE 16 lane-time rule: the naive-vs-factory ratio
    # is pinned per record by the bench-diff factory gates.
    def test_naive_baseline_reports_protocol(self, cfg):
        nb = factory_mod.naive_lax_pair_rate(
            cfg, WORKLOAD_SCENARIOS["diurnal-inference"], "off",
            pairs=1, steps=32, block_T=16, t_chunk=16, seed=3)
        assert nb["pairs_per_sec"] > 0
        assert nb["mpc_iters"] == int(cfg.train.mpc_iters)
        assert "receding_horizon_rollout" in nb["engine"] or \
            "receding-horizon" in nb["engine"] or "lax" in nb["engine"]


def _good_factory_record(**overrides) -> dict:
    """A minimal well-formed --factory-only record for the gate tests
    (mirrors `_good_stream_record`'s role for the round-16 gates)."""
    def fcell(scenario, intensity):
        return {
            "scenario": scenario, "intensity": intensity, "pairs": 64,
            "steps": 96, "seed": 41, "pairs_per_sec": 300.0,
            "plans_per_sec": 380.0,
            "playback_cluster_days_per_sec": 90.0,
            "teacher_vs_rule_usd_per_slo_hour": 1.001,
            "playback_occupancy": {"fractions": {"generation": 0.3,
                                                 "kernel": 0.6,
                                                 "host": 0.1}},
        }

    rec = {
        "metric": "factory", "round": 93, "stage": "--factory-only",
        "platform": "cpu", "virtual": True,
        "engine": "train/factory.py",
        "cells": [fcell("diurnal-inference", "off"),
                  fcell("batch-backfill", "moderate")],
        "pairs_total": 128, "pairs_per_sec": 295.0,
        "plans_per_sec": 375.0, "wall_s": 0.43,
        "baseline": {"pairs_per_sec": 12.0, "pairs": 4},
        "throughput_ratio_vs_baseline": 24.6,
        "playback_roofline_floor_s": 0.002,
        "student": {
            "iterations": 400, "final_actor_mse": 0.02,
            "student_vs_teacher_usd_per_slo_hour": 1.006,
            "per_cell": [
                {"scenario": "diurnal-inference", "intensity": "off",
                 "student_vs_teacher_usd_per_slo_hour": 1.004},
                {"scenario": "batch-backfill", "intensity": "moderate",
                 "student_vs_teacher_usd_per_slo_hour": 1.008}],
        },
        "provenance": {"platform": "cpu"},
    }
    rec.update(overrides)
    return rec


class TestBenchDiffFactoryGates:
    """ISSUE 14 satellite: the sentinel's factory invariant gates — an
    injected bad record drives exit 1, the real history stays clean."""

    def _diff_of(self, tmp_path, rec):
        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        (tmp_path / "BENCH_r93.json").write_text(json.dumps(rec))
        return bench_diff(load_bench_history(str(tmp_path)))

    def test_good_record_is_clean(self, tmp_path):
        diff = self._diff_of(tmp_path, _good_factory_record())
        assert diff["ok"], diff["regressions"]

    def test_ratio_below_one_regresses_and_cli_exits_nonzero(
            self, tmp_path, capsys):
        rec = _good_factory_record(throughput_ratio_vs_baseline=0.8)
        diff = self._diff_of(tmp_path, rec)
        assert any(r["kind"] == "factory_invariant"
                   for r in diff["regressions"])
        from ccka_tpu.cli import main

        assert main(["bench-diff", "--root", str(tmp_path)]) == 1
        assert "REGRESSION" in capsys.readouterr().err

    def test_missing_baseline_is_partial(self, tmp_path):
        rec = _good_factory_record()
        del rec["baseline"]
        del rec["throughput_ratio_vs_baseline"]
        diff = self._diff_of(tmp_path, rec)
        assert any("baseline" in r["detail"]
                   for r in diff["regressions"])

    def test_student_ratio_missing_or_implausible(self, tmp_path):
        rec = _good_factory_record()
        rec["student"]["student_vs_teacher_usd_per_slo_hour"] = None
        assert not self._diff_of(tmp_path, rec)["ok"]
        rec = _good_factory_record()
        rec["student"]["student_vs_teacher_usd_per_slo_hour"] = 500.0
        diff = self._diff_of(tmp_path, rec)
        assert any("plausible" in r["detail"]
                   for r in diff["regressions"])

    def test_missing_cells_entirely_is_a_regression(self, tmp_path):
        """The most-degraded record — a factory stage with NO cells at
        all — must not slip past the gates on its shape."""
        rec = _good_factory_record()
        del rec["cells"]
        diff = self._diff_of(tmp_path, rec)
        assert any("cells" in r["detail"]
                   for r in diff["regressions"]), diff

    def test_student_board_dropping_cells_is_a_regression(
            self, tmp_path):
        """The student column is per-CELL: a full-stage record whose
        per_cell board covers fewer cells than it ran dropped rows."""
        rec = _good_factory_record()
        rec["student"]["per_cell"] = []
        diff = self._diff_of(tmp_path, rec)
        assert any("per_cell" in r["detail"]
                   for r in diff["regressions"]), diff

    def test_partial_cell_is_a_regression(self, tmp_path):
        rec = _good_factory_record()
        del rec["cells"][0]["pairs_per_sec"]
        assert not self._diff_of(tmp_path, rec)["ok"]
        rec = _good_factory_record()
        del rec["cells"][1]["teacher_vs_rule_usd_per_slo_hour"]
        assert not self._diff_of(tmp_path, rec)["ok"]
        rec = _good_factory_record()
        del rec["playback_roofline_floor_s"]
        assert not self._diff_of(tmp_path, rec)["ok"]

    def test_real_history_is_clean(self):
        import os

        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        diff = bench_diff(load_bench_history(root))
        assert diff["ok"], diff["regressions"]

    def test_scaling_curve_ingests_factory_rows(self, tmp_path):
        from ccka_tpu.obs.bench_history import (scaling_curve,
                                                write_scaling_csv)

        (tmp_path / "BENCH_r93.json").write_text(
            json.dumps(_good_factory_record()))
        curve = scaling_curve(str(tmp_path))
        rows = [p for p in curve["points"]
                if p.get("source") == "factory_playback"]
        assert len(rows) == 2
        assert all(r["cluster_days_per_sec_aggregate"] == 90.0
                   for r in rows)
        assert "pairs/s" in rows[0]["note"]
        path = write_scaling_csv(curve, str(tmp_path / "c.csv"))
        assert "factory_playback" in open(path).read()
