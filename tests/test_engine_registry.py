"""Rollout-engine registry contract (ISSUE 14 tentpole + satellite).

The claim under test: adding a lane family THROUGH THE REGISTRY ALONE
(`sim/lanes.register_lane_family` + `provide_lane_generator`) reaches
every engine with ZERO per-engine edits — the synthetic source
synthesizes the widened stream, layout resolution accepts it, and the
lax reference engine, all four megakernel modes, the streaming drive
and the 8-shard sharded wrapper all consume it BITWISE identically to
the un-widened stream (a passive lane rides the stream; no engine
consumes it in-kernel), while the lane block itself is bitwise the
hand-threaded reference generation. Plus the mode registry's hygiene:
unknown names rejected with the registered vocabulary, duplicate
registrations rejected, ambiguous row arithmetic rejected, engines
provided before their mode registers attach when it does.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.sim import SimParams, lanes
from ccka_tpu.sim import streaming as streaming_mod
from ccka_tpu.sim.megakernel import packed_mode_summary_fn
from ccka_tpu.sim.rollout import lax_mode_summary
from ccka_tpu.signals.synthetic import SyntheticSignalSource

# One shared small geometry (interpret-mode kernels; one compile per
# mode per stream layout).
B, T, T_CHUNK, B_BLOCK = 32, 16, 8, 8

_TEST_TAG = 0x7E571
_TEST_NAME = "testlane"


def _test_rows(Z: int) -> int:
    # 2*fault_rows + 16 keeps every subset sum distinct for any Z the
    # registration ambiguity check sweeps.
    return 2 * lanes.fault_rows(Z) + 16


def _test_generate(cfg, key, steps, t_pad, z, batch, *, ctx):
    """Deterministic lane content keyed off the family tag — the
    hand-threaded reference the registry-driven synthesis must match
    bitwise. ``cfg`` is the family config (a float scale here)."""
    k = jax.random.fold_in(key, _TEST_TAG)
    block = cfg * jax.random.uniform(k, (steps, _test_rows(z), batch))
    return jnp.pad(block, ((0, t_pad - steps), (0, 0), (0, 0)))


@pytest.fixture(scope="module")
def cfg():
    return default_config()


@pytest.fixture(scope="module")
def testlane():
    """Register the test-only family for the module; leave the
    process-global registry exactly as found."""
    fam = lanes.register_lane_family(_TEST_NAME, rows=_test_rows,
                                     key_tag=_TEST_TAG)
    lanes.provide_lane_generator(_TEST_NAME, _test_generate)
    yield fam
    lanes.unregister_lane_family(_TEST_NAME)


@pytest.fixture(scope="module")
def sources(cfg, testlane):
    """(plain, widened) sources sharing every config except the extra
    registered lane family."""
    plain = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                  cfg.signals)
    widened = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals,
                                    extra_lanes={_TEST_NAME: 0.5})
    return plain, widened


@pytest.fixture(scope="module")
def streams(sources):
    key = jax.random.key(11)
    plain, widened = sources
    return (plain.packed_trace_device(T, key, B, t_chunk=T_CHUNK),
            widened.packed_trace_device(T, key, B, t_chunk=T_CHUNK))


@pytest.fixture(scope="module")
def net_params(cfg):
    from ccka_tpu.models import ActorCritic, latent_dim
    from ccka_tpu.sim.megakernel import _obs_dim

    net = ActorCritic(act_dim=latent_dim(cfg.cluster))
    return net.init(jax.random.key(5), jnp.zeros(
        (_obs_dim(cfg.cluster.n_pools, cfg.cluster.n_zones),)))


def _fields_equal(a, b):
    return {f for f in a._fields
            if not np.array_equal(np.asarray(getattr(a, f)),
                                  np.asarray(getattr(b, f)))}


class TestLaneFamilyRegistry:
    def test_builtin_families_registered_in_order(self):
        names = [f.name for f in lanes.lane_families()]
        assert names[:2] == ["faults", "workloads"]
        from ccka_tpu.faults.process import FAULT_KEY_TAG
        from ccka_tpu.workloads.process import WORKLOAD_KEY_TAG

        assert lanes.LANE_FAMILIES["faults"].key_tag == FAULT_KEY_TAG
        assert lanes.LANE_FAMILIES["workloads"].key_tag \
            == WORKLOAD_KEY_TAG

    def test_duplicate_name_and_tag_rejected(self):
        with pytest.raises(ValueError, match="already registered"):
            lanes.register_lane_family("faults", rows=lanes.fault_rows,
                                       key_tag=0x123)
        with pytest.raises(ValueError, match="key tag"):
            lanes.register_lane_family("dup-tag", rows=lambda z: 64,
                                       key_tag=0xFA117)
        assert "dup-tag" not in lanes.LANE_FAMILIES

    def test_ambiguous_rows_rejected_and_registry_unchanged(self):
        before = tuple(lanes.LANE_FAMILIES)
        # Same rows as the fault block: {new} and {faults} would both
        # resolve the same widened count.
        with pytest.raises(ValueError, match="ambiguous"):
            lanes.register_lane_family("clash", rows=lanes.fault_rows,
                                       key_tag=0x999)
        assert tuple(lanes.LANE_FAMILIES) == before

    def test_unknown_rows_rejected(self):
        with pytest.raises(ValueError, match="rows"):
            lanes.resolve_layout(lanes.exo_rows(3) + 1, 3)

    def test_unknown_family_generator_rejected(self):
        with pytest.raises(ValueError, match="unknown lane family"):
            lanes.lane_generator("never-registered")

    def test_refilling_a_generator_rejected(self, testlane):
        """Two modules silently fighting over one family's generator is
        a bug (the provide_mode_engine rule); re-providing the SAME
        closure (an idempotent re-import) stays legal."""
        lanes.provide_lane_generator(_TEST_NAME, _test_generate)
        with pytest.raises(ValueError, match="already has a generator"):
            lanes.provide_lane_generator(_TEST_NAME, lambda *a, **k: None)


class TestModeRegistry:
    def test_unknown_mode_lists_vocabulary(self, cfg):
        params = SimParams.from_config(cfg)
        with pytest.raises(ValueError, match="unknown packed mode"):
            packed_mode_summary_fn(params, cfg.cluster, "nope", T=T)

    def test_duplicate_mode_rejected(self):
        with pytest.raises(ValueError, match="already registered"):
            lanes.register_mode("rule", watch_name="x")

    def test_missing_engine_slot_raises(self):
        lanes.register_mode("half-mode", watch_name="half",
                            packed_summary=lambda *a, **k: None)
        try:
            assert lanes.mode_engine("half-mode", "packed_summary")
            with pytest.raises(ValueError, match="no block_summary"):
                lanes.mode_engine("half-mode", "block_summary")
        finally:
            lanes.unregister_mode("half-mode")

    def test_engine_provided_before_registration_attaches(self):
        sentinel = object()
        lanes.provide_mode_engine("late-mode", "lax_summary", sentinel)
        try:
            lanes.register_mode("late-mode", watch_name="late")
            assert lanes.mode_engine("late-mode",
                                     "lax_summary") is sentinel
        finally:
            lanes.unregister_mode("late-mode")

    def test_unknown_slot_rejected(self):
        with pytest.raises(ValueError, match="unknown engine slot"):
            lanes.provide_mode_engine("rule", "teleport", lambda: None)


class TestLaneReachesEveryEngine:
    """The satellite's core contract: one registration, every engine."""

    def test_widened_stream_resolves_and_lane_is_bitwise_reference(
            self, cfg, sources, streams, testlane):
        Z = cfg.cluster.n_zones
        plain_s, wide_s = streams
        assert wide_s.shape[1] == lanes.exo_rows(Z) + _test_rows(Z)
        lay = lanes.resolve_layout(wide_s.shape[1], Z)
        assert lay.families == (_TEST_NAME,)
        assert lanes.stream_layout(wide_s.shape[1], Z) == (False, False)
        lo, hi = lay.block(_TEST_NAME)
        # Exo rows bitwise the plain source's (widening never disturbs
        # the exo draws) and the lane block bitwise the hand-threaded
        # reference generation.
        assert np.array_equal(np.asarray(plain_s),
                              np.asarray(wide_s[:, :lo]))
        ref = _test_generate(0.5, jax.random.key(11), T,
                             wide_s.shape[0], Z, B, ctx={})
        assert np.array_equal(np.asarray(wide_s[:, lo:hi]),
                              np.asarray(ref))
        _plain, widened = sources
        assert widened.packed_rows() == wide_s.shape[1]

    @pytest.mark.parametrize("mode", ("rule", "carbon", "neural",
                                      "plan"))
    def test_all_four_kernel_modes_consume_it_bitwise(
            self, cfg, streams, net_params, mode, testlane):
        params = SimParams.from_config(cfg)
        plain_s, wide_s = streams
        kw = dict(T=T, b_block=B_BLOCK, t_chunk=T_CHUNK, interpret=True,
                  stochastic=False,
                  net_params=net_params if mode == "neural" else None)
        kfn = packed_mode_summary_fn(params, cfg.cluster, mode, **kw)
        a = kfn(plain_s, 3)
        b = kfn(wide_s, 3)
        assert not _fields_equal(a, b), mode

    @pytest.mark.slow  # ISSUE 16 lane-time rule: the kernel-mode engines
    # keep the registry-only derivation proof in the fast lane.
    def test_lax_engine_consumes_it_bitwise(self, cfg, streams,
                                            testlane):
        params = SimParams.from_config(cfg)
        plain_s, wide_s = streams
        key = jax.random.key(7)
        a = lax_mode_summary(params, cfg.cluster, "rule", plain_s, T,
                             key)
        b = lax_mode_summary(params, cfg.cluster, "rule", wide_s, T,
                             key)
        assert not _fields_equal(a, b)
        from ccka_tpu.models import latent_dim

        lat = jnp.zeros((B, T, latent_dim(cfg.cluster)), jnp.float32)
        a = lax_mode_summary(params, cfg.cluster, "plan", plain_s, T,
                             key, plan_latents=lat)
        b = lax_mode_summary(params, cfg.cluster, "plan", wide_s, T,
                             key, plan_latents=lat)
        assert not _fields_equal(a, b)

    def test_streaming_pipeline_consumes_it_bitwise(self, cfg, sources,
                                                    testlane):
        params = SimParams.from_config(cfg)
        plain, widened = sources
        key = jax.random.key(13)
        kw = dict(key=key, batch=B, T=T, block_T=T_CHUNK,
                  t_chunk=T_CHUNK, b_block=B_BLOCK, seed=5,
                  interpret=True, stochastic=False, pipelined=True)
        a, _ = streaming_mod.streaming_rollout_summary(
            plain, params, cfg.cluster, "rule", **kw)
        b, rep = streaming_mod.streaming_rollout_summary(
            widened, params, cfg.cluster, "rule", **kw)
        assert rep["n_blocks"] == T // T_CHUNK
        assert not _fields_equal(a, b)

    @pytest.mark.slow  # ISSUE 16 lane-time rule: 8-shard duplicate of the
    # single-chip registry derivation that stays fast.
    def test_8shard_wrapper_consumes_it_bitwise(self, cfg, sources,
                                                testlane):
        """Shard-local synthesis widens per shard and the sharded
        kernel consumes the widened layout — bitwise the plain sharded
        run (and the lane blocks bitwise the per-shard hand folds)."""
        from ccka_tpu.parallel import (make_mesh, sharded_packed_trace)
        from ccka_tpu.parallel.sharded_kernel import (
            sharded_megakernel_summary_from_packed)
        from ccka_tpu.policy.rule import offpeak_action, peak_action

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        params = SimParams.from_config(cfg)
        mesh = make_mesh()
        plain, widened = sources
        key = jax.random.key(17)
        Z = cfg.cluster.n_zones
        sp = sharded_packed_trace(mesh, plain, T, key, B,
                                  t_chunk=T_CHUNK)
        sw = sharded_packed_trace(mesh, widened, T, key, B,
                                  t_chunk=T_CHUNK)
        lay = lanes.resolve_layout(sw.shape[1], Z)
        lo, hi = lay.block(_TEST_NAME)
        assert np.array_equal(np.asarray(sp), np.asarray(sw[:, :lo]))
        # Shard i's lane block = the hand fold of (key, shard=i).
        b_loc = B // 8
        wide_np = np.asarray(sw)
        for i in range(8):
            ref = _test_generate(
                0.5, jax.random.fold_in(key, i), T, sw.shape[0], Z,
                b_loc, ctx={})
            assert np.array_equal(
                wide_np[:, lo:hi, i * b_loc:(i + 1) * b_loc],
                np.asarray(ref)), f"shard {i}"
        off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
        kw = dict(stochastic=False, b_block=b_loc, t_chunk=T_CHUNK,
                  interpret=True)
        a = sharded_megakernel_summary_from_packed(
            mesh, params, off, peak, sp, T, 3, **kw)
        b = sharded_megakernel_summary_from_packed(
            mesh, params, off, peak, sw, T, 3, **kw)
        assert not _fields_equal(a, b)


class TestSourceValidation:
    def test_unknown_extra_lane_rejected(self, cfg):
        with pytest.raises(ValueError, match="unknown lane family"):
            SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                  cfg.signals,
                                  extra_lanes={"no-such": 1.0})

    def test_builtin_via_extra_lanes_rejected(self, cfg):
        with pytest.raises(ValueError, match="built-in"):
            SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                  cfg.signals,
                                  extra_lanes={"faults": 1.0})
