"""Real-HTTP signals lane (VERDICT r4 next #7).

Every other signals test drives `signals/live.py` through injected
``fetch`` transports; this module is the first traffic over ACTUAL HTTP
sockets: an in-process threaded server speaks the Prometheus HTTP API
(`/api/v1/query`, `/api/v1/query_range`, `/api/v1/label/*/values` — the
same shapes the reference smoke-queries through its SigV4 proxy,
`demo_40_watch_observe.sh:106-110`), the OpenCost allocation/assets API
(`06_opencost.sh:430-437`), and the ElectricityMaps carbon endpoint, and
the REAL ``urllib`` default transport carries every request:
URL building, query encoding, headers, status codes, JSON decode and
error mapping are all exercised for real.

Two tiers:

- the in-process tier runs in the default CPU lane (localhost sockets —
  deterministic, no containers, no network egress);
- ``CCKA_TEST_PROM_URL=http://...`` opts into querying an external real
  Prometheus (e.g. one started by the kind-lane operator next to
  `tests/test_kubectl_integration.py`); auto-skipped otherwise.
"""

from __future__ import annotations

import json
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np
import pytest

from ccka_tpu.config import default_config


def _vector(rows):
    """Prometheus instant-vector response body."""
    return {
        "status": "success",
        "data": {"resultType": "vector",
                 "result": [{"metric": m, "value": [1700000000.0, str(v)]}
                            for m, v in rows]},
    }


class _FakeBackendHandler(BaseHTTPRequestHandler):
    """One server, three personae: Prometheus + OpenCost + carbon API
    (path-disjoint, so a single port serves all clients)."""

    server_version = "ccka-test-backend/1.0"

    def log_message(self, *a):  # silence per-request stderr noise
        pass

    def _send(self, doc, status=200, raw: bytes | None = None):
        body = raw if raw is not None else json.dumps(doc).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        u = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(u.query).items()}
        s = self.server  # type: ignore[assignment]
        s.requests.append((u.path, q, dict(self.headers)))

        if u.path == "/api/v1/query":
            return self._send(_vector(self._instant_rows(q["query"])))
        if u.path == "/api/v1/query_range":
            start, end = float(q["start"]), float(q["end"])
            step = float(q["step"].rstrip("s"))
            n = int((end - start) / step)
            pts = [[start + i * step, str(10.0 + i)] for i in range(n)]
            return self._send({"status": "success", "data": {
                "resultType": "matrix",
                "result": [{"metric": {"phase": "Running"},
                            "values": pts}]}})
        if re.fullmatch(r"/api/v1/label/[^/]+/values", u.path):
            return self._send({"status": "success",
                               "data": ["kube_pod_status_phase",
                                        "http_requests_total"]})
        if u.path == "/allocation":
            return self._send({"code": 200, "data": [
                {"nov-22": {"totalCost": 1.25},
                 "kube-system": {"totalCost": 0.75}}]})
        if u.path == "/assets":
            return self._send({"code": 200, "data": {
                "node-a": {"hourlyCost": 0.10},
                "node-b": {"hourlyCost": 0.30}}})
        if u.path == "/carbon-intensity/latest":
            if self.headers.get("auth-token") != "test-key":
                return self._send({"error": "forbidden"}, status=403)
            zone = q.get("zone", "")
            return self._send({"zone": zone,
                               "carbonIntensity": 123.0 + len(zone)})
        if u.path == "/nonjson/api/v1/query":
            return self._send({}, raw=b"<html>not json</html>")
        if u.path == "/error/api/v1/query":
            return self._send({"status": "error", "error": "boom"})
        return self._send({"error": "not found"}, status=404)

    def _instant_rows(self, promql: str):
        ns_pod = ('kube_pod_status_phase{phase=~"Pending|Running",'
                  'namespace="nov-22"}')
        if promql.startswith(ns_pod):
            # Per-pod series: odd burst index → spot class, even → od.
            return [({"pod": "burst-web-1-abc", "phase": "Running"}, 3.0),
                    ({"pod": "burst-web-2-def", "phase": "Running"}, 5.0),
                    ({"pod": "burst-web-3-ghi", "phase": "Pending"}, 2.0)]
        if "histogram_quantile" in promql:
            return [({}, 0.180)]
        if "http_requests_total" in promql:
            return [({}, 240.0)]
        if 'phase="Pending"' in promql:
            return [({}, 4.0)]
        if 'phase="Running"' in promql:
            return [({}, 56.0)]
        return []


@pytest.fixture(scope="module")
def backend():
    server = ThreadingHTTPServer(("127.0.0.1", 0), _FakeBackendHandler)
    server.requests = []  # type: ignore[attr-defined]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield server, f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()
    thread.join(timeout=5)


class TestClientsOverRealHTTP:
    def test_prometheus_instant_and_range(self, backend):
        from ccka_tpu.signals.live import PrometheusClient

        server, url = backend
        prom = PrometheusClient(url)  # default urllib transport
        rows = prom.query('sum(kube_pod_status_phase{phase="Running"})')
        assert rows == [({}, 56.0)]
        series = prom.query_range("x", start=0.0, end=300.0, step_s=30.0)
        labels, times, vals = series[0]
        assert labels == {"phase": "Running"}
        assert len(times) == 10 and vals[0] == 10.0
        assert prom.label_values("__name__") == [
            "kube_pod_status_phase", "http_requests_total"]
        # The wire carried a real urlencoded PromQL.
        path, q, _ = server.requests[0]
        assert path == "/api/v1/query" and "Running" in q["query"]

    def test_slo_metrics_snapshot(self, backend):
        from ccka_tpu.signals.live import PrometheusClient, SLOMetricsClient

        _, url = backend
        slo = SLOMetricsClient(PrometheusClient(url), namespace="nov-22")
        snap = slo.snapshot()
        assert snap["latency_p95_ms"] == pytest.approx(180.0)
        assert snap["rps"] == pytest.approx(240.0)
        assert snap["queue_depth"] == pytest.approx(4.0)

    def test_opencost_allocation_and_prices(self, backend):
        from ccka_tpu.signals.live import OpenCostClient

        _, url = backend
        oc = OpenCostClient(url)
        assert oc.allocation() == {"nov-22": 1.25, "kube-system": 0.75}
        assert oc.node_prices_hr() == {"node-a": 0.10, "node-b": 0.30}

    def test_carbon_auth_and_fallback(self, backend):
        from ccka_tpu.signals.live import CarbonIntensityClient

        _, url = backend
        good = CarbonIntensityClient(url, "test-key", "US-CAL-CISO", 400.0)
        assert good.latest() == pytest.approx(123.0 + len("US-CAL-CISO"))
        # 403 (bad key) → documented fallback, not an exception.
        bad = CarbonIntensityClient(url, "wrong-key", "US-CAL-CISO", 400.0)
        assert bad.latest() == 400.0
        # No key → no request at all, straight to the fallback.
        keyless = CarbonIntensityClient(url, "", "US-CAL-CISO", 411.0)
        assert keyless.latest() == 411.0

    def test_error_mapping_over_http(self, backend):
        from ccka_tpu.signals.live import (PrometheusClient,
                                           SignalUnavailable)

        _, url = backend
        with pytest.raises(SignalUnavailable, match="error response"):
            PrometheusClient(url + "/error").query("up")
        with pytest.raises(SignalUnavailable, match="non-JSON"):
            PrometheusClient(url + "/nonjson").query("up")
        # Nothing listening: URLError → SignalUnavailable, not a crash.
        dead = PrometheusClient("http://127.0.0.1:1", timeout_s=0.5)
        with pytest.raises(SignalUnavailable, match="fetch failed"):
            dead.query("up")


class TestLiveSourceToControllerOverHTTP:
    def test_live_tick_reads_every_backend(self, backend):
        """LiveSignalSource against the HTTP backend: demand classified
        from per-pod series, od price lifted by OpenCost node prices,
        carbon from the API — end to end over sockets."""
        from ccka_tpu.signals.live import LiveSignalSource

        _, url = backend
        cfg = default_config().with_overrides(**{
            "signals.prometheus_url": url,
            "signals.opencost_url": url,
            "signals.carbon_url": url,
            "signals.carbon_api_key": "test-key",
        })
        src = LiveSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                               cfg.signals)
        tick = src.tick(0)
        demand = np.asarray(tick.demand_pods)[0]
        # burst-web-1 (3) + burst-web-3 (2) → spot class; burst-web-2 (5)
        # → od class (the generator's odd/even convention).
        assert demand[0] == pytest.approx(5.0)
        assert demand[1] == pytest.approx(5.0)
        # OpenCost mean node $/hr (0.2) is below the od floor, so the
        # floor holds; carbon carries the API value for every zone.
        assert float(np.asarray(tick.od_price_hr).min()) >= (
            cfg.cluster.node_type.od_price_hr)
        carbon = np.asarray(tick.carbon_g_kwh)
        assert np.allclose(carbon, 123.0 + len("US-CAL-CISO"))

    def test_live_trace_backfills_from_range_queries(self, backend):
        from ccka_tpu.signals.live import LiveSignalSource

        _, url = backend
        cfg = default_config().with_overrides(**{
            "signals.prometheus_url": url,
            "signals.opencost_url": url,
            "signals.carbon_url": url,
        })
        src = LiveSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                               cfg.signals)
        tr = src.trace(8)
        demand = np.asarray(tr.demand_pods)
        assert demand.shape[0] == 8
        # Range values 10, 11, ... land per-tick (split over classes,
        # twice — Pending and Running both answer the same matrix).
        assert demand[0].sum() == pytest.approx(2 * 10.0)
        assert demand[7].sum() == pytest.approx(2 * 17.0)

    def test_controller_ticks_on_live_source(self, backend):
        """The full loop: LiveSignalSource → Controller.decide →
        DryRunSink patches, with live HTTP signals in the KPI line —
        the reference's operational loop with its metrics pipeline
        actually answering."""
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.policy import RulePolicy

        _, url = backend
        cfg = default_config().with_overrides(**{
            "signals.prometheus_url": url,
            "signals.opencost_url": url,
            "signals.carbon_url": url,
            "signals.carbon_api_key": "test-key",
        })
        from ccka_tpu.signals.live import LiveSignalSource

        src = LiveSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                               cfg.signals)
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, DryRunSink(),
                          interval_s=0.0, log_fn=lambda _l: None)
        rep = ctrl.tick(0)
        assert rep.applied
        assert np.isfinite(rep.cost_usd_hr)


@pytest.mark.skipif(not os.environ.get("CCKA_TEST_PROM_URL"),
                    reason="set CCKA_TEST_PROM_URL to an actual "
                           "Prometheus to opt in")
class TestExternalPrometheus:
    """Opt-in: the same client against a REAL Prometheus server (e.g.
    `kubectl -n monitoring port-forward svc/prometheus 9090` next to the
    kind lane)."""

    def test_up_query_and_labels(self):
        from ccka_tpu.signals.live import PrometheusClient

        prom = PrometheusClient(os.environ["CCKA_TEST_PROM_URL"])
        rows = prom.query("up")
        assert isinstance(rows, list)
        assert prom.label_values("__name__")


@pytest.mark.slow
class TestCaptureTrainReplayRoundTrip:
    """VERDICT r5 Next #6 (ISSUE 4 satellite), opt-in via the slow lane:
    the full data path LiveSignalSource → ``ccka capture`` → stored .npz
    → ReplaySignalSource → train + evaluate, over the REAL in-process
    HTTP backends — asserting end-to-end schema fidelity (the capture
    is what the replay family actually trains on, so a silent schema
    drift here would poison every replay scoreboard downstream)."""

    def test_capture_then_train_and_evaluate(self, backend, tmp_path):
        from ccka_tpu.cli import main
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.replay import ReplaySignalSource, load_trace
        from ccka_tpu.train.evaluate import compare_backends
        from ccka_tpu.train.ppo import PPOTrainer

        _, url = backend
        out = str(tmp_path / "live_capture.npz")
        steps = 40
        rc = main(["--set", "signals.backend=live",
                   "--set", f"signals.prometheus_url={url}",
                   "--set", f"signals.opencost_url={url}",
                   "--set", f"signals.carbon_url={url}",
                   "--set", "signals.carbon_api_key=test-key",
                   "capture", "--out", out, "--steps", str(steps)])
        assert rc == 0

        cfg = default_config()
        trace, meta = load_trace(out)          # validates shapes itself
        # Schema fidelity: provenance, cadence, topology and the live
        # backends' actual values all survive the store.
        assert meta.source == "live"
        assert meta.dt_s == cfg.sim.dt_s
        assert tuple(meta.zones) == tuple(cfg.cluster.zones)
        z = cfg.cluster.n_zones
        assert np.asarray(trace.spot_price_hr).shape == (steps, z)
        assert np.asarray(trace.demand_pods).shape == (steps, 2)
        # The Prometheus range series (values 10, 11, ...) lands per
        # tick in the stored demand — the live WIRE values survive the
        # store, not a synthetic stand-in (carbon/prices backfill from
        # the diurnal prior by design; demand is the scraped channel).
        demand = np.asarray(trace.demand_pods)
        assert demand[0].sum() == pytest.approx(2 * 10.0)
        assert demand[-1].sum() == pytest.approx(2 * (10.0 + steps - 1))
        assert float(np.asarray(trace.od_price_hr).min()) >= (
            cfg.cluster.node_type.od_price_hr)  # OpenCost floor held
        assert np.isfinite(np.asarray(trace.carbon_g_kwh)).all()
        assert float(np.asarray(trace.carbon_g_kwh).min()) > 0

        # Train on the capture through the replay path (BASELINE #3's
        # pipeline on a genuinely live-captured store)...
        tcfg = cfg.with_overrides(**{"train.batch_clusters": 4,
                                     "train.unroll_steps": 8})
        src = ReplaySignalSource.from_file(out)
        trainer = PPOTrainer(tcfg)
        ts, history = trainer.train(src, iterations=1, log_every=1)
        assert int(ts.iteration) == 1
        assert np.isfinite(history[0]["mean_reward"])

        # ...and evaluate on it: the scoreboard machinery accepts the
        # captured trace end to end.
        board = compare_backends(tcfg, {"rule": RulePolicy(tcfg.cluster)},
                                 [src.trace(steps)], stochastic=False)
        assert np.isfinite(board["rule"]["usd_per_slo_hour"])
        assert board["rule"]["slo_attainment"] >= 0.0
