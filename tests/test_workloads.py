"""Workload-family subsystem tests (ISSUE 6, ARCHITECTURE §13).

Four contracts, mirroring the round-10 fault gates:

- **Zero-workload bitwise gate**: with workloads DISABLED the packed
  stream and every consumer take the exact pre-workload code path —
  bitwise identical arrays/summaries, protecting every recorded
  BASELINE/BENCH number. The enabled-but-neutral config (all rates 0)
  additionally pins exo/fault-row bitwise identity plus summary
  equality to 1e-5 (the workload-mode kernel is a DIFFERENT XLA
  program) with the family counters exactly zero.
- **Queue semantics**: inference queue/cap/violation, batch EDF aging
  to deadline misses, background best-effort — unit-level on
  `dynamics.step`'s workload path (the conservation invariant lives in
  `tests/test_invariants.py`).
- **Kernel↔lax workload parity**: the workload-mode kernel (fault+
  workload widened stream — the most layered program) matches the
  workloads-threaded lax rollout on the same lanes, deterministic
  interpret mode, under the ONE shared tolerance table.
- **Paired realization**: every policy scored on one stream sees the
  same family arrivals — rule vs plan-playback on one widened stream,
  plus the 8-shard shard-local generation pin (slow lane).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import (ConfigError, FrameworkConfig,
                             WorkloadsConfig)
from ccka_tpu.policy import RulePolicy
from ccka_tpu.policy.rule import offpeak_action, peak_action
from ccka_tpu.signals.synthetic import SyntheticSignalSource
from ccka_tpu.sim import SimParams, initial_state
from ccka_tpu.sim.dynamics import ExoStep, step
from ccka_tpu.sim.megakernel import (
    _exo_rows,
    megakernel_summary_from_packed,
    pack_plan,
    plan_megakernel_summary_from_packed,
    unpack_exo,
)
from ccka_tpu.sim.rollout import batched_rollout_summary
from ccka_tpu.workloads import (
    WORKLOAD_SCENARIOS,
    WorkloadState,
    WorkloadStep,
    resolve_scenarios,
    sample_workload_steps,
    stream_layout,
    unpack_workload_lanes,
    workload_rows,
)
from ccka_tpu.faults.process import fault_rows, unpack_fault_lanes

STEPS, B, T_CHUNK, B_BLOCK = 48, 16, 8, 8
KERNEL_KW = dict(stochastic=False, b_block=B_BLOCK, t_chunk=T_CHUNK,
                 interpret=True)

# A deliberately HOT mix for the parity/paired tests: tight queue cap
# and a short deadline so violations AND misses both fire within the
# CI-sized 48-tick window starting at midnight.
HOT = WorkloadsConfig(enabled=True, inference_rate_pods=12.0,
                      inference_flash_frac=0.1, inference_flash_mult=6.0,
                      inference_queue_max=16.0,
                      batch_rate_pods=8.0, batch_burst_frac=0.1,
                      batch_deadline_ticks=6,
                      background_rate_pods=4.0)


def _src(cfg, faults=None, workloads=None):
    return SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                 cfg.signals, faults=faults,
                                 workloads=workloads)


@pytest.fixture(scope="module")
def hot_cfg(cfg):
    """The session config with the HOT mix enabled — its SimParams carry
    the mix's queue cap / SLO bound / deadline depth."""
    return dataclasses.replace(cfg, workloads=HOT)


@pytest.fixture(scope="module")
def streams(cfg, hot_cfg):
    """One generation key, three stream variants (shape-shared where
    possible so the interpret-mode kernel compiles once per program)."""
    from ccka_tpu.config import FAULT_PRESETS

    key = jax.random.key(5)
    return {
        "plain": _src(cfg).packed_trace_device(
            STEPS, key, B, t_chunk=T_CHUNK),
        "neutral": _src(cfg, workloads=WorkloadsConfig(
            enabled=True)).packed_trace_device(
            STEPS, key, B, t_chunk=T_CHUNK),
        # The most layered program: fault lanes AND workload lanes.
        "hot": _src(hot_cfg, faults=FAULT_PRESETS["mild"],
                    workloads=HOT).packed_trace_device(
            STEPS, key, B, t_chunk=T_CHUNK),
    }


class TestConfig:
    def test_scenarios_validate(self):
        assert len(WORKLOAD_SCENARIOS) >= 4
        for name, sc in WORKLOAD_SCENARIOS.items():
            sc.validate()
            assert sc.name == name
            assert sc.workloads.enabled

    def test_roundtrip_and_overrides(self, cfg):
        c2 = cfg.with_overrides(**{"workloads.enabled": True,
                                   "workloads.batch_rate_pods": 2.5})
        assert c2.workloads.enabled
        assert c2.workloads.batch_rate_pods == 2.5
        c3 = FrameworkConfig.from_json(c2.to_json())
        assert c3.workloads == c2.workloads

    def test_bad_values_rejected(self):
        with pytest.raises(ConfigError):
            WorkloadsConfig(inference_rate_pods=-1.0).validate()
        with pytest.raises(ConfigError):
            WorkloadsConfig(inference_flash_mult=0.5).validate()
        with pytest.raises(ConfigError):
            WorkloadsConfig(batch_deadline_ticks=0).validate()
        with pytest.raises(ConfigError):
            WorkloadsConfig(inference_queue_max=0.0).validate()

    def test_unknown_scenarios_rejected_up_front(self):
        with pytest.raises(ValueError, match="unknown scenarios"):
            resolve_scenarios(("mixed", "no-such-scenario"))
        from ccka_tpu.workloads.scoreboard import workload_scoreboard
        from ccka_tpu.config import default_config
        with pytest.raises(ValueError, match="unknown scenarios"):
            workload_scoreboard(default_config(),
                                scenarios=("typo-scenario",))
        with pytest.raises(ValueError, match="unknown policies"):
            workload_scoreboard(default_config(),
                                scenarios=("mixed",),
                                policies=("rule", "pppo"))


class TestLanes:
    def test_disabled_is_bitwise_pre_workload_stream(self, cfg):
        """THE zero-workload gate, stream half: disabled workloads emit
        the exact pre-PR stream — same shape, same bits."""
        key = jax.random.key(5)
        plain = _src(cfg).packed_trace_device(16, key, 4, t_chunk=8)
        disabled = _src(cfg, workloads=WorkloadsConfig(enabled=False)) \
            .packed_trace_device(16, key, 4, t_chunk=8)
        assert plain.shape == disabled.shape
        assert np.array_equal(np.asarray(plain), np.asarray(disabled))

    @pytest.mark.slow  # ISSUE 16 lane-time rule:
    # widening bitwise keeps the faults-lane + regions-lane fast proofs.
    def test_widened_exo_and_fault_rows_bitwise(self, cfg, streams):
        Z = cfg.cluster.n_zones
        base = _exo_rows(Z)
        assert streams["neutral"].shape[1] == base + workload_rows(Z)
        assert streams["hot"].shape[1] == (base + fault_rows(Z)
                                           + workload_rows(Z))
        assert stream_layout(streams["neutral"].shape[1], Z) == (False,
                                                                 True)
        assert stream_layout(streams["hot"].shape[1], Z) == (True, True)
        # Exo rows bitwise shared with the plain stream.
        for name in ("neutral", "hot"):
            assert np.array_equal(np.asarray(streams["plain"]),
                                  np.asarray(streams[name][:, :base]))
        # Neutral config (rates 0): lanes are EXACTLY zero.
        lanes = np.asarray(streams["neutral"][:STEPS, base:])
        assert np.all(lanes == 0.0)

    def test_hot_lanes_in_range(self, cfg, streams):
        Z = cfg.cluster.n_zones
        wl = unpack_workload_lanes(streams["hot"], STEPS, Z)
        for leaf in wl:
            a = np.asarray(leaf)
            assert a.shape == (B, STEPS)
            assert a.min() >= 0.0 and np.isfinite(a).all()
        assert np.asarray(wl.inf_arrivals).mean() > 0.0
        assert np.asarray(wl.batch_arrivals).mean() > 0.0
        # Fault lanes still unpack cleanly past the workload block.
        fs = unpack_fault_lanes(streams["hot"], STEPS, Z)
        assert np.asarray(fs.preempt_hazard).min() >= 1.0

    def test_bad_row_count_rejected(self, cfg, streams):
        Z = cfg.cluster.n_zones
        with pytest.raises(ValueError, match="rows"):
            stream_layout(streams["neutral"].shape[1] - 1, Z)
        with pytest.raises(ValueError, match="no workload lanes"):
            unpack_workload_lanes(streams["plain"], STEPS, Z)

    @pytest.mark.slow  # integration-grade: lane mechanics already fast-covered
    def test_replay_packed_stream_carries_lanes(self, cfg):
        from ccka_tpu.signals.base import TraceMeta
        from ccka_tpu.signals.replay import ReplaySignalSource

        stored = _src(cfg).trace(48, seed=3)
        meta = TraceMeta(source="replay", start_unix_s=0.0, dt_s=30.0,
                         zones=cfg.cluster.zones)
        Z = cfg.cluster.n_zones
        key = jax.random.key(9)
        plain = ReplaySignalSource(stored, meta).packed_trace_device(
            16, key, 4, t_chunk=8)
        laden = ReplaySignalSource(
            stored, meta, workloads=HOT).packed_trace_device(
            16, key, 4, t_chunk=8)
        assert laden.shape[1] == _exo_rows(Z) + workload_rows(Z)
        # Same key → same windows: exo rows bitwise shared.
        assert np.array_equal(np.asarray(plain),
                              np.asarray(laden[:, :_exo_rows(Z)]))
        assert np.asarray(
            unpack_workload_lanes(laden, 16, Z).inf_arrivals).mean() > 0


class TestZeroWorkloadGate:
    def test_lax_neutral_workload_step_bitwise(self, cfg):
        """step(workload=neutral, wl_state=zero) == step(), bitwise —
        state AND metrics' shared fields, stochastic mode included; the
        family counters and queues exactly zero."""
        params = SimParams.from_config(cfg)
        tr = _src(cfg).trace(1, seed=0)
        from ccka_tpu.sim.rollout import exo_steps
        exo = jax.tree.map(lambda x: x[0], exo_steps(tr))
        st = initial_state(cfg)
        act = RulePolicy(cfg.cluster).decide(st, exo, jnp.int32(0))
        key = jax.random.key(7)
        wl0 = WorkloadState.zero(int(params.wl_batch_deadline_ticks))
        s1, m1 = jax.jit(lambda: step(params, st, act, exo, key,
                                      stochastic=True))()
        s2, m2, w2 = jax.jit(lambda: step(
            params, st, act, exo, key, stochastic=True,
            workload=WorkloadStep.neutral(), wl_state=wl0))()
        for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s2)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        for f in m1._fields:
            assert np.array_equal(np.asarray(getattr(m1, f)),
                                  np.asarray(getattr(m2, f))), f
        for leaf in jax.tree.leaves(w2):
            assert np.all(np.asarray(leaf) == 0.0)

    def test_step_rejects_half_given_workload(self, cfg):
        params = SimParams.from_config(cfg)
        st = initial_state(cfg)
        with pytest.raises(ValueError, match="both workload="):
            step(params, st, None, None, None,
                 workload=WorkloadStep.neutral())

    def test_kernel_disabled_stream_bitwise(self, cfg):
        """Disabled workloads → un-widened stream → the pre-workload
        kernel program — summaries bitwise identical end to end."""
        params = SimParams.from_config(cfg)
        off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
        key = jax.random.key(5)
        kw = dict(stochastic=False, b_block=4, t_chunk=8, interpret=True)
        s1 = megakernel_summary_from_packed(
            params, off, peak,
            _src(cfg).packed_trace_device(16, key, 4, t_chunk=8),
            16, seed=3, **kw)
        s2 = megakernel_summary_from_packed(
            params, off, peak,
            _src(cfg, workloads=WorkloadsConfig(
                enabled=False)).packed_trace_device(16, key, 4,
                                                    t_chunk=8),
            16, seed=3, **kw)
        for f in s1._fields:
            assert np.array_equal(np.asarray(getattr(s1, f)),
                                  np.asarray(getattr(s2, f))), f
        assert np.all(np.asarray(s1.inf_slo_violations) == 0.0)
        assert np.all(np.asarray(s1.batch_deadline_misses) == 0.0)

    @pytest.mark.slow  # weaker than the disabled-bitwise + lax-neutral gates
    def test_kernel_neutral_lanes_match_plain(self, cfg, streams):
        """Enabled-but-neutral lanes: the workload-mode kernel on
        all-zero arrivals reproduces the plain kernel to 1e-5 (different
        XLA program → ~1 ulp of fusion skew) with the family counters
        exactly zero."""
        params = SimParams.from_config(cfg)
        off, peak = offpeak_action(cfg.cluster), peak_action(cfg.cluster)
        s1 = megakernel_summary_from_packed(
            params, off, peak, streams["plain"], STEPS, seed=3,
            **KERNEL_KW)
        s2 = megakernel_summary_from_packed(
            params, off, peak, streams["neutral"], STEPS, seed=3,
            **KERNEL_KW)
        for f in s1._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(s2, f)), np.asarray(getattr(s1, f)),
                rtol=1e-5, atol=1e-6, err_msg=f)
        for f in ("inf_slo_violations", "inf_queue_mean", "inf_dropped",
                  "batch_deadline_misses", "batch_backlog_mean"):
            assert np.all(np.asarray(getattr(s2, f)) == 0.0), f


class TestWorkloadDynamics:
    """Lax-side semantics of each family's queue."""

    def _exo_demand(self, cfg, demand_total: float) -> ExoStep:
        z = cfg.cluster.n_zones
        return ExoStep(
            spot_price_hr=jnp.full((z,), 0.03),
            od_price_hr=jnp.full((z,), 0.096),
            carbon_g_kwh=jnp.full((z,), 400.0),
            demand_pods=jnp.full((2,), demand_total / 2.0),
            is_peak=jnp.float32(0.0))

    def _saturate(self, cfg, params):
        """A state+exo pair with ~zero headroom: demand soaks the base
        capacity and no Karpenter nodes exist."""
        cap = float(params.base_od_nodes) * float(params.pods_per_node)
        return initial_state(cfg), self._exo_demand(cfg, 2.0 * cap)

    def test_inference_queue_builds_drops_and_violates(self, hot_cfg):
        params = SimParams.from_config(hot_cfg)
        st, exo = self._saturate(hot_cfg, params)
        act = RulePolicy(hot_cfg.cluster).decide(st, exo, jnp.int32(0))
        key = jax.random.key(0)
        wl = WorkloadStep.neutral()._replace(
            inf_arrivals=jnp.float32(30.0))
        ws = WorkloadState.zero(int(params.wl_batch_deadline_ticks))
        stepf = jax.jit(lambda s, w: step(params, s, act, exo, key,
                                          workload=wl, wl_state=w))
        for _ in range(3):
            st, m, ws = stepf(st, ws)
        # Headroom ~0: queue pinned at the cap, the rest shed, violation.
        assert float(ws.inf_queue) == pytest.approx(
            float(params.wl_inference_queue_max), abs=1e-3)
        assert float(m.inf_dropped) > 0.0
        assert float(m.inf_slo_violation) == 1.0
        assert float(m.inf_queue_depth) == float(ws.inf_queue)

    def test_batch_work_ages_to_deadline_miss(self, hot_cfg):
        params = SimParams.from_config(hot_cfg)
        D = int(params.wl_batch_deadline_ticks)
        st, exo = self._saturate(hot_cfg, params)
        act = RulePolicy(hot_cfg.cluster).decide(st, exo, jnp.int32(0))
        key = jax.random.key(0)
        ws = WorkloadState.zero(D)
        one = WorkloadStep.neutral()._replace(
            batch_arrivals=jnp.float32(5.0))
        stepf = jax.jit(lambda s, w, a: step(params, s, act, exo, key,
                                             workload=a, wl_state=w))
        # One burst of work, then silence: with zero headroom it must
        # age through the D-slot pipeline and miss at exactly tick D.
        misses = []
        st, m, ws = stepf(st, ws, one)
        misses.append(float(m.batch_deadline_miss))
        for _ in range(D):
            st, m, ws = stepf(st, ws, WorkloadStep.neutral())
            misses.append(float(m.batch_deadline_miss))
        assert misses[D - 1] == pytest.approx(5.0, abs=1e-4)
        assert sum(misses) == pytest.approx(5.0, abs=1e-4)
        assert float(np.asarray(ws.batch_backlog).sum()) == pytest.approx(
            0.0, abs=1e-5)

    def test_priority_inference_before_batch_before_bg(self, cfg):
        """With headroom for exactly the inference load, batch and bg
        starve; with ample headroom everything drains."""
        params = SimParams.from_config(cfg)
        st = initial_state(cfg)
        exo = self._exo_demand(cfg, 0.0)   # whole base capacity free
        cap = float(params.base_od_nodes) * float(params.pods_per_node)
        act = RulePolicy(cfg.cluster).decide(st, exo, jnp.int32(0))
        wl = WorkloadStep(inf_arrivals=jnp.float32(cap),
                          batch_arrivals=jnp.float32(4.0),
                          bg_arrivals=jnp.float32(2.0))
        ws = WorkloadState.zero(int(params.wl_batch_deadline_ticks))
        _, m, ws2 = step(params, st, act, exo, jax.random.key(0),
                         workload=wl, wl_state=ws)
        assert float(m.inf_served) == pytest.approx(cap, rel=1e-5)
        assert float(m.batch_served) == 0.0
        assert float(m.batch_backlog) == pytest.approx(4.0, rel=1e-5)
        assert float(ws2.bg_backlog) == pytest.approx(2.0, rel=1e-5)

    @pytest.mark.slow  # duplicates TestLanes' hot/neutral coverage sampler-side
    def test_sample_workload_steps_matches_config(self, cfg):
        Z = cfg.cluster.n_zones
        wl = jax.jit(lambda k: sample_workload_steps(
            HOT, k, 64, Z, dt_s=30.0))(jax.random.key(3))
        assert wl.inf_arrivals.shape == (64,)
        assert float(np.asarray(wl.inf_arrivals).mean()) > 0.0
        neutral = jax.jit(lambda k: sample_workload_steps(
            WorkloadsConfig(enabled=True), k, 64, Z,
            dt_s=30.0))(jax.random.key(3))
        for leaf in neutral:
            assert np.all(np.asarray(leaf) == 0.0)


class TestKernelLaxWorkloadParity:
    """The workload-mode kernel (fault+workload stream — the most
    layered program) against the workloads-threaded lax rollout on the
    SAME lanes — deterministic interpret mode."""

    def test_rule_profile(self, hot_cfg, streams):
        params = SimParams.from_config(hot_cfg)
        off = offpeak_action(hot_cfg.cluster)
        peak = peak_action(hot_cfg.cluster)
        Z = hot_cfg.cluster.n_zones
        stream = streams["hot"]
        sk = megakernel_summary_from_packed(
            params, off, peak, stream, STEPS, seed=3, **KERNEL_KW)
        traces = unpack_exo(stream, STEPS, Z)
        faults = unpack_fault_lanes(stream, STEPS, Z)
        wl = unpack_workload_lanes(stream, STEPS, Z)
        states = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (B,) + x.shape),
            initial_state(hot_cfg))
        keys = jax.random.split(jax.random.key(0), B)
        _, sl = batched_rollout_summary(
            params, states, RulePolicy(hot_cfg.cluster).action_fn(),
            traces, keys, stochastic=False, faults=faults, workloads=wl)
        for f in sk._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(sk, f)), np.asarray(getattr(sl, f)),
                rtol=3e-4, atol=1e-4, err_msg=f)
        # The families actually bit (this is not a trivial pass).
        assert float(np.asarray(sk.inf_slo_violations).mean()) > 0.0
        assert float(np.asarray(sk.batch_deadline_misses).mean()) > 0.0


class TestPairedRealization:
    """Two policies under one seed see ONE family-arrival realization."""

    def test_rule_vs_plan_playback_same_laden_world(self, hot_cfg,
                                                    streams):
        """A rule-replaying per-cluster plan through the playback kernel
        reproduces the profile kernel on the SAME workload-laden stream
        — the round-9/10 pin extended to workload mode (both consume
        identical family lanes)."""
        import math

        params = SimParams.from_config(hot_cfg)
        off = offpeak_action(hot_cfg.cluster)
        peak = peak_action(hot_cfg.cluster)
        Z = hot_cfg.cluster.n_zones
        stream = streams["hot"]
        s_rule = megakernel_summary_from_packed(
            params, off, peak, stream, STEPS, seed=3, **KERNEL_KW)
        traces = unpack_exo(stream, STEPS, Z)
        is_peak = traces.is_peak > 0.5
        rule_plan = jax.tree.map(
            lambda o, p: jnp.where(
                is_peak.reshape(is_peak.shape + (1,) * o.ndim), p, o),
            off, peak)
        t_pad = math.ceil(STEPS / T_CHUNK) * T_CHUNK
        s_plan = plan_megakernel_summary_from_packed(
            params, hot_cfg.cluster, pack_plan(rule_plan, t_pad),
            stream, STEPS, seed=3, **KERNEL_KW)
        for f in s_rule._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(s_plan, f)),
                np.asarray(getattr(s_rule, f)), rtol=1e-5, atol=1e-6,
                err_msg=f)

    @pytest.mark.slow  # the 8-shard mesh + kernel compiles cost ~30s
    # and the sharding machinery is pinned plain-stream in
    # tests/test_sharded_kernel.py (and fault-stream in test_faults);
    # the fast lane keeps the cross-policy paired pin above — this
    # extends the shard-local lane pin to workload lanes in the slow
    # lane (ISSUE 6 lane-hygiene satellite).
    def test_sharded_generation_lanes_bitwise(self, hot_cfg):
        """8 interpret-mode shards: each shard's workload lanes equal
        the single-device generation with that shard's folded key, and
        the sharded rule kernel on the laden stream matches the
        single-device kernel on the gathered stream."""
        from ccka_tpu.config import FAULT_PRESETS, MeshConfig
        from ccka_tpu.parallel import make_mesh
        from ccka_tpu.parallel.sharded_kernel import (
            sharded_megakernel_summary_from_packed, sharded_packed_trace)

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual CPU mesh")
        mesh = make_mesh(MeshConfig(data_parallel=8))
        src = _src(hot_cfg, faults=FAULT_PRESETS["mild"], workloads=HOT)
        key = jax.random.key(11)
        b_loc = 2
        stream = sharded_packed_trace(mesh, src, STEPS, key, 8 * b_loc,
                                      t_chunk=T_CHUNK)
        gathered = np.asarray(stream)
        for shard in range(8):
            want = np.asarray(src.packed_trace_device(
                STEPS, jax.random.fold_in(key, shard), b_loc,
                t_chunk=T_CHUNK))
            got = gathered[:, :, shard * b_loc:(shard + 1) * b_loc]
            np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6,
                                       err_msg=f"shard {shard}")

        params = SimParams.from_config(hot_cfg)
        off = offpeak_action(hot_cfg.cluster)
        peak = peak_action(hot_cfg.cluster)
        kw = dict(stochastic=False, b_block=b_loc, t_chunk=T_CHUNK,
                  interpret=True)
        s_sh = sharded_megakernel_summary_from_packed(
            mesh, params, off, peak, stream, STEPS, seed=3, **kw)
        s_1d = megakernel_summary_from_packed(
            params, off, peak, jnp.asarray(gathered), STEPS, seed=3,
            **kw)
        for f in s_sh._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(s_sh, f)),
                np.asarray(getattr(s_1d, f)),
                rtol=1e-5, atol=1e-6, err_msg=f)


class TestPromExportWorkloads:
    def test_workload_gauges_exported_and_paneled(self):
        """ISSUE 6 observability satellite: the per-family gauges stay
        exported, resolvable from a TickReport, and on the dashboard —
        both parity directions, like the fault gauges."""
        from ccka_tpu.harness.controller import TickReport
        from ccka_tpu.harness.dashboard import _PANEL_DEFS
        from ccka_tpu.harness.promexport import (SERIES,
                                                 referenced_series,
                                                 render_exposition,
                                                 resolve_field)

        gauges = {"ccka_inference_queue_depth",
                  "ccka_inference_slo_violations_total",
                  "ccka_batch_deadline_misses_total"}
        assert gauges <= set(SERIES)
        paneled = set()
        for _t, expr, _u in _PANEL_DEFS:
            paneled |= referenced_series(expr)
        assert gauges <= paneled, "workload gauges missing a panel"

        rec = dataclasses.asdict(TickReport(
            t=3, is_peak=False, profile="offpeak", applied=True,
            verified=True, fallbacks=0, cost_usd_hr=0.0, carbon_g_hr=0.0,
            nodes_spot=0.0, nodes_od=0.0, pending_pods=0.0, slo_ok=True,
            inference_queue_depth=3.5, batch_backlog=7.0,
            inference_slo_violations_total=2.0,
            batch_deadline_misses_total=9.25))
        assert resolve_field(
            rec, SERIES["ccka_inference_queue_depth"][0]) == 3.5
        text = render_exposition(rec)
        assert "ccka_inference_queue_depth 3.5" in text
        assert "ccka_inference_slo_violations_total 2" in text
        assert "ccka_batch_deadline_misses_total 9.25" in text

    @pytest.mark.slow  # end-to-end duplicate of dynamics + gauge-parity tests
    def test_controller_tracks_workload_queues(self, cfg):
        """A workloads-enabled controller advances the family track and
        re-states cumulative counters on every TickReport."""
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller

        cfg2 = dataclasses.replace(cfg, workloads=HOT)
        src = _src(cfg2, workloads=HOT)
        ctrl = Controller(cfg2, RulePolicy(cfg2.cluster), src,
                          DryRunSink(), interval_s=0.0,
                          log_fn=lambda _l: None)
        reports = ctrl.run(ticks=3)
        assert all(r.inference_queue_depth >= 0.0 for r in reports)
        totals = [r.inference_slo_violations_total for r in reports]
        assert totals == sorted(totals)   # cumulative, never decreasing
        # The plain controller keeps the pre-workload shape: zeros.
        ctrl0 = Controller(cfg, RulePolicy(cfg.cluster), _src(cfg),
                           DryRunSink(), interval_s=0.0,
                           log_fn=lambda _l: None)
        r0 = ctrl0.tick(0)
        assert r0.inference_queue_depth == 0.0
        assert r0.batch_deadline_misses_total == 0.0
