"""Crash-safe control loop (ISSUE 9): ChaosSink injection modes, the
desired-state Reconciler, durable snapshot/resume with the kill-and-
resume bitwise invariant, and the recovery harness end to end.

The load-bearing pins:

- **zero-injection gate**: a `ChaosSink(off)`-wrapped run is
  command-for-command identical to the bare sink (the chaos analog of
  the zero-fault bitwise gate);
- **snapshot round-trip**: save -> load -> `jax.tree_util` equality,
  PRNG key path included (subsequent splits produce identical keys);
- **kill-and-resume bitwise invariant**: for a fixed seed and kill
  tick, the resumed run's decision stream and applied-patch sequence
  are identical to an uninterrupted run's, with ZERO duplicate and
  ZERO lost patches — pinned across >= 3 kill points fast-lane and at
  every tick in the slow sweep;
- **reconciler convergence** under each chaos failure mode, with a
  bounded give-up that never raises.
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.actuation.chaos import ChaosSink, make_chaos_sink
from ccka_tpu.actuation.patches import render_nodepool_patches
from ccka_tpu.actuation.reconcile import Reconciler, verify_pool
from ccka_tpu.actuation.sink import DryRunSink
from ccka_tpu.config import (CHAOS_PRESETS, ChaosConfig, ConfigError,
                             default_config)
from ccka_tpu.harness.controller import Controller
from ccka_tpu.harness.snapshot import (SnapshotError, decode_key,
                                       decode_like, encode_key,
                                       encode_tree, load_snapshot,
                                       save_snapshot)
from ccka_tpu.policy import RulePolicy
from ccka_tpu.policy.rule import offpeak_action
from ccka_tpu.signals.synthetic import SyntheticSignalSource


def _src(cfg, **kw):
    return SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                 cfg.signals, **kw)


def _controller(cfg, sink, *, seed=0, snapshot_path="", src=None, **kw):
    return Controller(cfg, RulePolicy(cfg.cluster), src or _src(cfg),
                      sink, interval_s=0.0, seed=seed,
                      log_fn=lambda _l: None, snapshot_path=snapshot_path,
                      reconcile_backoff_s=0.0, **kw)


def _fingerprints(reports):
    return [(r.t, r.profile, r.cost_usd_hr, r.carbon_g_hr, r.nodes_spot,
             r.nodes_od, r.pending_pods, r.slo_ok, r.applied, r.verified)
            for r in reports]


# ---------------------------------------------------------------------------
# ChaosSink
# ---------------------------------------------------------------------------


class TestChaosConfig:
    def test_presets_validate(self):
        for name, preset in CHAOS_PRESETS.items():
            preset.validate()
            assert preset.enabled, name

    def test_bad_probabilities_rejected(self):
        with pytest.raises(ConfigError):
            ChaosConfig(enabled=True, drop_prob=1.5).validate()
        with pytest.raises(ConfigError):
            ChaosConfig(enabled=True, timeout_prob=0.6,
                        drop_prob=0.6).validate()

    def test_unknown_intensity_rejected_up_front(self):
        with pytest.raises(ValueError, match="unknown chaos intensity"):
            make_chaos_sink(DryRunSink(), "catastrophic")


class TestChaosSink:
    def _patches(self, cfg):
        return render_nodepool_patches(offpeak_action(cfg.cluster),
                                       cfg.cluster, op="replace")

    def test_zero_injection_gate_is_command_for_command(self, cfg):
        """ChaosSink(off) must be a bitwise no-op on the command stream —
        wrapper in the path, nothing injected, nothing drawn."""
        bare, inner = DryRunSink(), DryRunSink()
        wrapped = make_chaos_sink(inner, "off", seed=123)
        for sink in (bare, wrapped):
            for ps in self._patches(cfg):
                sink.apply_nodepool(ps)
        assert ([c.render() for c in bare.commands]
                == [c.render() for c in inner.commands])
        assert wrapped.stats["commands"] == 0   # no draws, not just no hits

    def test_timeout_and_transient_block_the_mutation(self, cfg):
        for field, counter in (("timeout_prob", "timeouts"),
                               ("transient_exit_prob", "transient_exits")):
            inner = DryRunSink()
            sink = ChaosSink(inner, ChaosConfig(enabled=True,
                                                **{field: 1.0}), seed=0)
            results = [sink.apply_nodepool(ps)
                       for ps in self._patches(cfg)]
            assert not any(r.ok for r in results)
            assert inner.commands == []          # nothing reached kubectl
            assert sink.stats[counter] > 0

    def test_silent_drop_reports_ok_but_readback_catches_it(self, cfg):
        inner = DryRunSink()
        sink = ChaosSink(inner, ChaosConfig(enabled=True, drop_prob=1.0),
                         seed=0)
        results = [sink.apply_nodepool(ps) for ps in self._patches(cfg)]
        # The disruption merge "succeeded" (the lie), so apply proceeds,
        # but the requirements never land and BOTH read-backs come up
        # empty — the apply-and-verify discipline catches the drop.
        assert not any(r.ok for r in results)
        assert all(r.used_fallback for r in results)
        assert inner.commands == []
        assert sink.stats["dropped"] > 0

    def test_admission_rewrite_lands_but_diverges_from_intent(self, cfg):
        inner = DryRunSink()
        sink = ChaosSink(inner, ChaosConfig(enabled=True,
                                            rewrite_prob=1.0), seed=0)
        patches = self._patches(cfg)
        results = [sink.apply_nodepool(ps) for ps in patches]
        # Rewritten patches APPLY cleanly (read-back is non-empty)...
        assert all(r.ok for r in results)
        assert inner.commands
        # ...but the skeptical intent-vs-observed check fails: the
        # webhook trimmed a requirement value list / clamped disruption.
        assert not all(verify_pool(sink.observed_state(ps.pool), ps)
                       for ps in patches)
        assert sink.stats["rewrites"] > 0

    def test_seeded_realization_is_deterministic(self, cfg):
        stats = []
        for _ in range(2):
            sink = ChaosSink(DryRunSink(), CHAOS_PRESETS["severe"],
                             seed=42)
            for _i in range(4):
                for ps in self._patches(cfg):
                    sink.apply_nodepool(ps)
            stats.append(dict(sink.stats))
        assert stats[0] == stats[1]

    def test_reads_stay_honest_under_full_chaos(self, cfg):
        inner = DryRunSink()
        for ps in self._patches(cfg):
            inner.apply_nodepool(ps)
        sink = ChaosSink(inner, CHAOS_PRESETS["severe"], seed=0)
        pool = cfg.cluster.pools[0].name
        assert sink.observed_state(pool) == inner.observed_state(pool)


# ---------------------------------------------------------------------------
# Reconciler
# ---------------------------------------------------------------------------


class _FlakyFirstN(DryRunSink):
    """Rejects the first ``n`` patch commands, then behaves."""

    def __init__(self, n):
        super().__init__()
        self.reject_left = n

    def _patch(self, cmd):
        if self.reject_left > 0:
            self.reject_left -= 1
            return False
        return super()._patch(cmd)


class TestReconciler:
    def _patches(self, cfg):
        return render_nodepool_patches(offpeak_action(cfg.cluster),
                                       cfg.cluster, op="replace")

    def test_converges_through_transient_failures(self, cfg):
        sink = _FlakyFirstN(2)
        rec = Reconciler(sink, max_rounds=3, backoff_s=0.0)
        out = rec.converge(self._patches(cfg))
        assert out.converged
        assert out.retries > 0 and out.rounds > 1
        assert all(r.ok for r in out.results)
        assert rec.retries_total == out.retries

    def test_converges_under_each_seeded_chaos_mode(self, cfg):
        """Sub-certain per-command failure: a few retry rounds converge
        every mode (drop included — retries re-issue the write)."""
        for field in ("timeout_prob", "transient_exit_prob", "drop_prob",
                      "rewrite_prob"):
            sink = ChaosSink(DryRunSink(),
                             ChaosConfig(enabled=True, **{field: 0.4}),
                             seed=4)
            rec = Reconciler(sink, max_rounds=8, backoff_s=0.0,
                             deadline_s=30.0)
            out = rec.converge(self._patches(cfg))
            assert out.converged, field
            assert verify_pool(
                sink.observed_state(self._patches(cfg)[0].pool),
                self._patches(cfg)[0]), field

    def test_bounded_give_up_surfaces_instead_of_raising(self, cfg):
        sink = ChaosSink(DryRunSink(),
                         ChaosConfig(enabled=True, drop_prob=1.0), seed=0)
        rec = Reconciler(sink, max_rounds=3, backoff_s=0.0)
        out = rec.converge(self._patches(cfg))
        assert not out.converged
        assert out.rounds == 3
        assert set(out.diverged) == {ps.pool for ps in self._patches(cfg)}
        assert all(out.divergence[p] == 3 for p in out.diverged)

    def test_deadline_bounds_the_rounds(self, cfg):
        clock = {"t": 0.0}
        sleeps = []
        sink = ChaosSink(DryRunSink(),
                         ChaosConfig(enabled=True, drop_prob=1.0), seed=0)
        rec = Reconciler(sink, max_rounds=100, backoff_s=1.0,
                         deadline_s=2.5, sleep_fn=sleeps.append,
                         clock=lambda: clock["t"])

        def tick_clock(s):
            clock["t"] += s
        rec.sleep_fn = tick_clock
        out = rec.converge(self._patches(cfg))
        assert not out.converged
        assert out.rounds < 5                  # deadline, not max_rounds

    def test_reapply_is_idempotent(self, cfg):
        """Converging the same desired state twice changes nothing — the
        property that makes re-applying after a mid-tick kill safe."""
        sink = DryRunSink()
        rec = Reconciler(sink, backoff_s=0.0)
        patches = self._patches(cfg)
        assert rec.converge(patches).converged
        store_before = json.loads(json.dumps(sink.store))
        out2 = rec.converge(patches)
        assert out2.converged and out2.retries == 0
        assert sink.store == store_before


# ---------------------------------------------------------------------------
# Snapshot codec
# ---------------------------------------------------------------------------


class TestSnapshotCodec:
    def test_pytree_round_trip_with_key_path(self, tmp_path, cfg):
        """save -> load -> tree equality, PRNG key path included: a
        restored key's NEXT split matches the original's next split."""
        from ccka_tpu.sim.rollout import initial_state

        key = jax.random.key(11)
        for _ in range(5):                      # walk the split path
            key, _sub = jax.random.split(key)
        state = initial_state(cfg)
        body = {"state": encode_tree(state), "prng_key": encode_key(key),
                "next_tick": 5}
        path = os.path.join(tmp_path, "s.snap")
        save_snapshot(path, body)
        loaded = load_snapshot(path)
        state2 = decode_like(state, loaded["state"])
        assert jax.tree_util.tree_all(jax.tree.map(
            lambda a, b: bool(jnp.array_equal(a, b)), state, state2))
        key2 = decode_key(loaded["prng_key"])
        assert jnp.array_equal(jax.random.key_data(key),
                               jax.random.key_data(key2))
        n1, s1 = jax.random.split(key)
        n2, s2 = jax.random.split(key2)
        assert jnp.array_equal(jax.random.key_data(n1),
                               jax.random.key_data(n2))
        assert jnp.array_equal(jax.random.key_data(s1),
                               jax.random.key_data(s2))

    def test_corrupt_file_is_refused(self, tmp_path):
        path = os.path.join(tmp_path, "c.snap")
        save_snapshot(path, {"next_tick": 3, "x": encode_tree(
            jnp.arange(4.0))})
        doc = json.load(open(path))
        doc["body"]["next_tick"] = 4            # tamper without re-hashing
        with open(path, "w") as f:
            json.dump(doc, f)
        with pytest.raises(SnapshotError, match="checksum"):
            load_snapshot(path)

    def test_torn_write_and_bad_format_refused(self, tmp_path):
        path = os.path.join(tmp_path, "t.snap")
        with open(path, "w") as f:
            f.write('{"format": "ccka-snapshot", "version": 1, "bo')
        with pytest.raises(SnapshotError, match="JSON"):
            load_snapshot(path)
        with open(path, "w") as f:
            json.dump({"format": "something-else"}, f)
        with pytest.raises(SnapshotError):
            load_snapshot(path)
        with pytest.raises(SnapshotError, match="cannot read"):
            load_snapshot(os.path.join(tmp_path, "absent.snap"))

    def test_version_mismatch_refused(self, tmp_path):
        path = os.path.join(tmp_path, "v.snap")
        save_snapshot(path, {"next_tick": 1})
        doc = json.load(open(path))
        doc["version"] = 99
        with open(path, "w") as f:
            json.dump(doc, f)
        with pytest.raises(SnapshotError, match="version"):
            load_snapshot(path)

    def test_write_is_atomic_no_temp_left(self, tmp_path):
        path = os.path.join(tmp_path, "a.snap")
        for _ in range(3):                      # overwrites replace atomically
            save_snapshot(path, {"next_tick": 1})
        assert sorted(os.listdir(tmp_path)) == ["a.snap"]

    def test_missing_leaf_and_shape_drift_refused(self, cfg):
        tree = {"a": jnp.ones((2, 3))}
        enc = encode_tree(tree)
        with pytest.raises(SnapshotError, match="missing leaf"):
            decode_like({"b": jnp.ones(1)}, enc)
        with pytest.raises(SnapshotError, match="shape"):
            decode_like({"a": jnp.ones((3, 2))}, enc)


# ---------------------------------------------------------------------------
# Kill-and-resume bitwise invariant
# ---------------------------------------------------------------------------


def _kill_resume_pair(cfg, *, ticks, kill_tick, seed, chaos, tmp_path,
                      stale_frac=0.0):
    from ccka_tpu.harness.recovery import _run_pair
    return _run_pair(cfg, RulePolicy(cfg.cluster), chaos, stale_frac,
                     ticks=ticks, seed=seed, kill_tick=kill_tick,
                     snap_path=os.path.join(tmp_path,
                                            f"k{kill_tick}.snap"))


class TestKillResume:
    def test_bitwise_across_three_kill_points_under_chaos(self, cfg,
                                                          tmp_path):
        """ACCEPTANCE: fixed seed, >= 3 kill points, severe actuation
        chaos + stale scrapes — decision stream and patch sequence
        identical, zero duplicate, zero lost."""
        for kill_tick in (2, 4, 6):
            pair = _kill_resume_pair(
                cfg, ticks=8, kill_tick=kill_tick, seed=17,
                chaos=CHAOS_PRESETS["severe"], tmp_path=tmp_path,
                stale_frac=0.15)
            assert pair["resume_bitwise"], kill_tick
            assert pair["duplicate_patches"] == 0
            assert pair["lost_patches"] == 0
            assert pair["ticks_to_reconverge"] == 0
            assert pair["usd_ratio"] == pytest.approx(1.0)
            assert pair["resumes"] == 1

    def test_killed_at_every_tick_sweep(self, cfg, tmp_path):
        """The full boundary sweep on a short horizon: killing after ANY
        completed tick resumes bitwise."""
        ticks = 6
        for kill_tick in range(1, ticks):
            pair = _kill_resume_pair(
                cfg, ticks=ticks, kill_tick=kill_tick, seed=23,
                chaos=CHAOS_PRESETS["moderate"], tmp_path=tmp_path)
            assert pair["resume_bitwise"], kill_tick
            assert pair["duplicate_patches"] == 0
            assert pair["lost_patches"] == 0

    def test_resume_restores_session_counters_and_machine(self, cfg,
                                                          tmp_path):
        snap = os.path.join(tmp_path, "ctr.snap")
        sink = ChaosSink(DryRunSink(), CHAOS_PRESETS["severe"], seed=3)
        ctrl = _controller(cfg, sink, seed=3, snapshot_path=snap)
        reports = ctrl.run(6)
        ctrl.close()
        ctrl2 = _controller(cfg, sink, seed=3, snapshot_path=snap)
        start = ctrl2.restore(load_snapshot(snap))
        assert start == 6
        assert ctrl2.reconcile_retries_total == \
            reports[-1].reconcile_retries_total
        assert ctrl2.actuation_failures_total == \
            reports[-1].actuation_failures_total
        assert ctrl2.degraded_ticks_total == \
            reports[-1].degraded_ticks_total
        assert ctrl2._degraded == reports[-1].degraded
        assert ctrl2.resumes_total == 1
        ctrl2.close()

    def test_mpc_plan_state_survives_resume_bitwise(self, cfg, tmp_path):
        """Receding-horizon plan state rides the snapshot: a resumed MPC
        controller keeps executing the SAME optimized plan at the same
        cadence — killing at a non-replan tick (3, with replan_every=4)
        would otherwise force a fresh replan and fork the stream."""
        from ccka_tpu.train.mpc import MPCBackend

        def mk(sink, snap=""):
            return Controller(cfg, MPCBackend(cfg, horizon=8, iters=2,
                                              replan_every=4),
                              _src(cfg), sink, interval_s=0.0, seed=4,
                              log_fn=lambda _l: None, snapshot_path=snap,
                              reconcile_backoff_s=0.0)

        sink_b = DryRunSink()
        base = mk(sink_b).run(6)
        snap = os.path.join(tmp_path, "mpc.snap")
        sink_k = DryRunSink()
        c1 = mk(sink_k, snap)
        pre = c1.run(3)
        c1.close()
        c2 = mk(sink_k, snap)                  # fresh backend, fresh plan
        start = c2.restore(load_snapshot(snap))
        assert c2._force_replan is False       # plan restored, not rebuilt
        post = c2.run(6 - start, start_tick=start)
        c2.close()
        assert _fingerprints(pre + post) == _fingerprints(base)
        assert ([c.render() for c in sink_k.commands]
                == [c.render() for c in sink_b.commands])

    def test_pending_interruption_warnings_survive_resume(self, cfg,
                                                          tmp_path):
        """The SQS ack happens at poll time, so the carried-warning
        buffer is a warning's ONLY memory — a crash must not drop an
        unresolved terminate warning (the drain would never happen; the
        queue will not redeliver)."""
        from ccka_tpu.signals.live import InterruptionWarning

        snap = os.path.join(tmp_path, "pw.snap")
        ctrl = _controller(cfg, DryRunSink(), seed=2, snapshot_path=snap)
        ctrl.run(2)
        w = InterruptionWarning("i-0abc", "terminate",
                                "EC2 Spot Instance Interruption Warning",
                                region="us-east-2")
        ctrl._pending_warnings = {"i-0abc": (w, 3)}
        ctrl.write_snapshot(2)
        ctrl.close()
        ctrl2 = _controller(cfg, DryRunSink(), seed=2, snapshot_path=snap)
        ctrl2.restore(load_snapshot(snap))
        (w2, ttl) = ctrl2._pending_warnings["i-0abc"]
        assert (w2.instance_id, w2.action, w2.detail_type, w2.region) == \
            ("i-0abc", "terminate",
             "EC2 Spot Instance Interruption Warning", "us-east-2")
        assert ttl == 3
        ctrl2.close()

    def test_restore_refuses_identity_mismatches(self, cfg, tmp_path):
        snap = os.path.join(tmp_path, "id.snap")
        ctrl = _controller(cfg, DryRunSink(), seed=1, snapshot_path=snap)
        ctrl.run(2)
        ctrl.close()
        body = load_snapshot(snap)
        # Wrong seed: the PRNG path would fork.
        with pytest.raises(SnapshotError, match="seed"):
            _controller(cfg, DryRunSink(), seed=2).restore(body)
        # Wrong backend: the decision stream would change policy.
        from ccka_tpu.policy import CarbonAwarePolicy
        other = Controller(cfg, CarbonAwarePolicy(cfg.cluster), _src(cfg),
                           DryRunSink(), interval_s=0.0, seed=1,
                           log_fn=lambda _l: None)
        with pytest.raises(SnapshotError, match="backend"):
            other.restore(body)
        # Wrong config: the estimate topology would not match.
        cfg2 = cfg.with_overrides(**{"sim.dt_s": 15})
        with pytest.raises(SnapshotError, match="config"):
            _controller(cfg2, DryRunSink(), seed=1).restore(body)

    def test_workload_family_state_survives_resume(self, tmp_path):
        """Per-family queue state + session SLO counters round-trip, and
        the resumed arrival track stays phase-anchored to the ORIGINAL
        clock (wl.anchor_unix_s), so the estimate stream stays bitwise."""
        cfg = default_config().with_overrides(**{
            "workloads.enabled": True,
            "workloads.inference_rate_pods": 6.0,
            "workloads.batch_rate_pods": 3.0,
            "sim.horizon_steps": 64,
        })
        src = _src(cfg, start_unix_s=8 * 3600)
        snap = os.path.join(tmp_path, "wl.snap")
        base = _controller(cfg, DryRunSink(), seed=5, src=src)
        base_reports = base.run(6)
        base.close()
        k1 = _controller(cfg, DryRunSink(), seed=5, src=src,
                         snapshot_path=snap)
        pre = k1.run(3)
        k1.close()
        k2 = _controller(cfg, DryRunSink(), seed=5, src=src,
                         snapshot_path=snap)
        start = k2.restore(load_snapshot(snap))
        post = k2.run(6 - start, start_tick=start)
        k2.close()
        assert _fingerprints(pre + post) == _fingerprints(base_reports)
        got = [(r.inference_queue_depth, r.batch_backlog,
                r.inference_slo_violations_total,
                r.batch_deadline_misses_total) for r in pre + post]
        want = [(r.inference_queue_depth, r.batch_backlog,
                 r.inference_slo_violations_total,
                 r.batch_deadline_misses_total) for r in base_reports]
        assert got == want


# ---------------------------------------------------------------------------
# Controller integration: divergence -> degraded mode, gauges on the wire
# ---------------------------------------------------------------------------


class TestControllerChaosIntegration:
    def test_unconvergeable_actuation_drives_degraded_fallback(self, cfg):
        """A cluster that never accepts patches walks the existing
        ok -> hold -> rule-fallback machine via the divergence streak —
        the reconciler's give-up surfaces, it does not raise."""
        sink = ChaosSink(DryRunSink(),
                         ChaosConfig(enabled=True, drop_prob=1.0), seed=0)
        ctrl = _controller(cfg, sink, degraded_fallback_after=3)
        reports = ctrl.run(6)
        ctrl.close()
        assert all(not r.verified for r in reports)
        assert all(r.reconcile_diverged > 0 for r in reports)
        modes = [r.degraded for r in reports]
        assert modes[0] == "ok"              # divergence is known post-apply
        assert "hold" in modes and "fallback" in modes
        assert reports[-1].degraded == "fallback"
        assert reports[-1].actuation_failures_total > 0

    def test_recovery_gauges_reach_the_exposition(self, cfg, tmp_path):
        from ccka_tpu.harness.promexport import MetricsExporter

        snap = os.path.join(tmp_path, "g.snap")
        exporter = MetricsExporter()
        sink = ChaosSink(DryRunSink(), CHAOS_PRESETS["moderate"], seed=1)
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), _src(cfg), sink,
                          interval_s=0.0, log_fn=lambda _l: None,
                          snapshot_path=snap, reconcile_backoff_s=0.0,
                          exporter=exporter)
        ctrl.run(3)
        ctrl.close()
        body = exporter.exposition()
        for series in ("ccka_reconcile_retries_total",
                       "ccka_reconcile_diverged",
                       "ccka_actuation_failures_total",
                       "ccka_snapshot_age_ticks", "ccka_resumes_total"):
            assert series in body, series


# ---------------------------------------------------------------------------
# Fleet snapshot/resume
# ---------------------------------------------------------------------------


class TestFleetSnapshotResume:
    def _fleet(self, cfg, n=8, seed=3):
        from ccka_tpu.harness.fleet import fleet_controller_from_config
        return fleet_controller_from_config(
            cfg, RulePolicy(cfg.cluster), n, horizon_ticks=16, seed=seed,
            fanout_workers=1)

    def test_fleet_resume_is_bitwise(self, cfg, tmp_path):
        base = self._fleet(cfg)
        base_reports = [base.tick(t) for t in range(6)]
        base.close()

        path = os.path.join(tmp_path, "fleet.snap")
        k1 = self._fleet(cfg)
        pre = [k1.tick(t) for t in range(3)]
        k1.write_snapshot(path, 3)
        k1.close()
        k2 = self._fleet(cfg)
        start = k2.restore(load_snapshot(path))
        post = [k2.tick(t) for t in range(start, 6)]
        k2.close()

        def fp(rs):
            return [(r.t, r.applied, r.slo_ok, r.cost_usd_hr,
                     r.carbon_g_hr, r.pending_pods) for r in rs]
        assert fp(pre + post) == fp(base_reports)

    def test_fleet_restore_refuses_mismatch(self, cfg, tmp_path):
        path = os.path.join(tmp_path, "f.snap")
        f8 = self._fleet(cfg, n=8)
        f8.write_snapshot(path, 1)
        f8.close()
        f4 = self._fleet(cfg, n=4)
        with pytest.raises(SnapshotError, match="clusters"):
            f4.restore(load_snapshot(path))
        f9 = self._fleet(cfg, n=8, seed=9)
        with pytest.raises(SnapshotError, match="seed"):
            f9.restore(load_snapshot(path))
        f4.close()
        f9.close()


# ---------------------------------------------------------------------------
# Recovery scoreboard + CLI
# ---------------------------------------------------------------------------


class TestRecoveryScoreboard:
    def test_unknown_names_rejected_up_front(self, cfg):
        from ccka_tpu.harness.recovery import recovery_scoreboard
        with pytest.raises(ValueError, match="unknown chaos intensities"):
            recovery_scoreboard(cfg, intensities=("off", "apocalyptic"))
        with pytest.raises(ValueError, match="unknown policies"):
            recovery_scoreboard(cfg, policies=("rule", "oracle"))

    def test_tiny_board_holds_the_invariants(self, cfg):
        from ccka_tpu.harness.recovery import recovery_scoreboard
        board = recovery_scoreboard(cfg, policies=("rule",),
                                    intensities=("off", "severe"),
                                    runs_per_cell=2, ticks=6, seed=9)
        inv = board["invariants"]
        assert inv["duplicate_patches_total"] == 0
        assert inv["lost_patches_total"] == 0
        assert inv["resume_bitwise_frac"] == 1.0
        assert board["n_paired_runs"] == 4
        sev = board["cells"]["severe"]["rows"]["rule"]
        assert sev["chaos_injected"]["dropped"] >= 0
        assert sev["usd_per_slo_hr_vs_baseline"] == pytest.approx(1.0)


class TestCLI:
    def test_recover_eval_rejects_unknown_intensity(self):
        from ccka_tpu.cli import main
        with pytest.raises(SystemExit, match="unknown chaos intensities"):
            main(["recover-eval", "--intensities", "off,bogus",
                  "--policies", "rule", "--runs", "1", "--ticks", "4"])
        with pytest.raises(SystemExit, match="unknown policies"):
            main(["recover-eval", "--policies", "rule,bogus",
                  "--runs", "1", "--ticks", "4"])

    def test_run_resume_needs_snapshot(self):
        from ccka_tpu.cli import main
        with pytest.raises(SystemExit, match="--resume needs --snapshot"):
            main(["run", "--ticks", "1", "--resume"])

    def test_run_resume_refuses_corrupt_snapshot(self, tmp_path):
        from ccka_tpu.cli import main
        path = os.path.join(tmp_path, "bad.snap")
        with open(path, "w") as f:
            f.write("not a snapshot")
        with pytest.raises(SystemExit, match="not valid JSON"):
            main(["run", "--ticks", "1", "--snapshot", path, "--resume"])

    def test_run_snapshot_then_resume_round_trip(self, tmp_path, capsys):
        """--ticks is the RUN's total length: re-running the identical
        killed command with --resume completes the original run (ticks
        already done count toward it), it does not run N more."""
        from ccka_tpu.cli import main
        path = os.path.join(tmp_path, "cli.snap")
        assert main(["run", "--ticks", "3", "--interval", "0",
                     "--snapshot", path]) == 0
        assert os.path.exists(path)
        assert main(["run", "--ticks", "5", "--interval", "0",
                     "--snapshot", path, "--resume"]) == 0
        err = capsys.readouterr().err
        assert "resumed at tick 3" in err
        assert "controller ran 2 tick(s)" in err
        assert load_snapshot(path)["next_tick"] == 5
        # Already complete: the same command again runs zero ticks.
        assert main(["run", "--ticks", "5", "--interval", "0",
                     "--snapshot", path, "--resume"]) == 0
        assert "controller ran 0 tick(s)" in capsys.readouterr().err
        assert load_snapshot(path)["next_tick"] == 5


# ---------------------------------------------------------------------------
# Satellite fix: the runner capability probe is cached per runner object
# ---------------------------------------------------------------------------


class TestBudgetProbeCache:
    def test_signature_probed_once_per_runner(self, monkeypatch):
        import inspect

        from ccka_tpu.actuation import sink as sink_mod

        calls = {"n": 0}
        real = inspect.signature

        def counting(fn, *a, **kw):
            calls["n"] += 1
            return real(fn, *a, **kw)
        monkeypatch.setattr(inspect, "signature", counting)

        def runner(argv, **kw):
            return (0, "")
        assert sink_mod._accepts_budget(runner) is True
        for _ in range(5):                      # hot-path repeats: cached
            assert sink_mod._accepts_budget(runner) is True
        assert calls["n"] == 1

        def narrow(argv):
            return (0, "")
        assert sink_mod._accepts_budget(narrow) is False
        assert sink_mod._accepts_budget(narrow) is False
        assert calls["n"] == 2
