"""Learned-backend tests: codec invertibility, MPC improvement over its
initialization, PPO iteration mechanics and learning signal, checkpoints.

Kept small (tiny horizons/batches) so the suite stays fast on the 8-device
CPU mesh; the full-scale configs run through bench.py / train scripts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.models import (
    ActorCritic,
    PolicyMLP,
    action_to_latent,
    latent_dim,
    latent_to_action,
)
from ccka_tpu.policy.rule import neutral_action, offpeak_action
from ccka_tpu.sim import SimParams, initial_state, rollout, summarize
from ccka_tpu.sim.rollout import rollout_actions
from ccka_tpu.signals import SyntheticSignalSource
from ccka_tpu.train import MPCBackend, optimize_plan, save_state, load_state
from ccka_tpu.train.objective import episode_objective
from ccka_tpu.train.ppo import PPOBackend, PPOTrainer


@pytest.fixture(scope="module")
def cfg():
    return default_config().with_overrides(**{
        "train.batch_clusters": 4,
        "train.unroll_steps": 8,
        "train.mpc_horizon": 16,
        "train.mpc_iters": 15,
    })


@pytest.fixture(scope="module")
def source(cfg):
    return SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                 cfg.signals)


def test_latent_dim(cfg):
    # P*Z + P*2 + P + P + C = 6 + 4 + 2 + 2 + 2
    assert latent_dim(cfg.cluster) == 16


def test_codec_round_trip(cfg):
    a = offpeak_action(cfg.cluster)
    u = action_to_latent(a, cfg.cluster)
    back = latent_to_action(u, cfg.cluster)
    # Values at {0,1} saturate the logit; recovered within clip tolerance.
    assert np.allclose(np.asarray(back.zone_weight),
                       np.asarray(a.zone_weight), atol=1e-3)
    assert np.allclose(np.asarray(back.consolidation_aggr),
                       np.asarray(a.consolidation_aggr), atol=1e-3)
    assert np.allclose(np.asarray(back.hpa_scale),
                       np.asarray(a.hpa_scale), atol=1e-2)


def test_latent_to_action_always_feasible(cfg):
    # Any latent, however extreme, maps to a Kyverno-feasible action.
    for seed in range(3):
        u = jax.random.normal(jax.random.key(seed),
                              (latent_dim(cfg.cluster),)) * 10.0
        a = latent_to_action(u, cfg.cluster)
        od_idx = cfg.cluster.pool_index("on-demand-slo")
        assert float(a.ct_allow[od_idx, 0]) == 0.0   # never spot on SLO pool
        assert float(a.ct_allow[od_idx, 1]) >= 0.99  # od guaranteed
        assert float(a.hpa_scale.min()) >= 0.1


def test_policy_mlp_shapes(cfg):
    net = PolicyMLP(out_dim=latent_dim(cfg.cluster))
    obs = jnp.ones((29,))
    params = net.init(jax.random.key(0), obs)
    u = net.apply(params, obs)
    assert u.shape == (16,)
    batched = jax.vmap(lambda o: net.apply(params, o))(jnp.ones((8, 29)))
    assert batched.shape == (8, 16)


def test_actor_critic_zero_init_starts_near_neutral(cfg):
    net = ActorCritic(act_dim=latent_dim(cfg.cluster))
    obs = jnp.ones((29,))
    params = net.init(jax.random.key(0), obs)
    mean, log_std, value = net.apply(params, obs)
    assert mean.shape == (16,)
    assert np.allclose(np.asarray(mean), 0.0)  # zero-init head
    a = latent_to_action(mean, cfg.cluster)
    assert np.allclose(np.asarray(a.zone_weight), 0.5, atol=1e-6)


def test_mpc_plan_improves_objective(cfg, source):
    params = SimParams.from_config(cfg)
    tr = source.trace(16, seed=3)
    base = action_to_latent(neutral_action(cfg.cluster), cfg.cluster)
    init = jnp.broadcast_to(base, (16,) + base.shape)
    result = optimize_plan(params, cfg.cluster, cfg.train,
                           initial_state(cfg), tr, init, iters=15)
    assert np.isfinite(np.asarray(result.losses)).all()
    assert float(result.losses[-1]) < float(result.losses[0])


def test_mpc_backend_closed_loop(cfg, source):
    mpc = MPCBackend(cfg, horizon=8, iters=5, replan_every=4)
    tr = source.trace(12, seed=0)
    final, metrics = mpc.evaluate(initial_state(cfg), tr,
                                  jax.random.key(0), stochastic=False)
    assert metrics.cost_usd.shape == (12,)
    s = summarize(SimParams.from_config(cfg), metrics)
    assert float(s.cost_usd) > 0


def test_ppo_iteration_runs_and_shapes(cfg, source):
    trainer = PPOTrainer(cfg)
    ts, history = trainer.train(source, iterations=2, log_every=1)
    assert int(ts.iteration) == 2
    assert len(history) == 2
    for rec in history:
        assert np.isfinite(rec["policy_loss"])
        assert np.isfinite(rec["mean_reward"])


def test_ppo_backend_decides_feasible_actions(cfg, source):
    trainer = PPOTrainer(cfg)
    ts = trainer.init_state()
    backend = PPOBackend(cfg, ts.params)
    params = SimParams.from_config(cfg)
    tr = source.trace(8, seed=0)
    final, metrics = rollout(params, initial_state(cfg),
                             backend.action_fn(), tr, jax.random.key(0))
    assert metrics.cost_usd.shape == (8,)
    assert np.isfinite(np.asarray(metrics.cost_usd)).all()


def test_ppo_reward_improves_on_tiny_problem(cfg, source):
    # Learnability: 12 iterations on the tiny fixture must genuinely move
    # mean reward up. Measured margin is +0.08 across seeds 0-2 on this
    # exact config; the bound sits at half that, so regression to
    # "didn't collapse" fails while seed jitter passes.
    trainer = PPOTrainer(cfg)
    ts, history = trainer.train(source, iterations=12, log_every=1)
    first = np.mean([h["mean_reward"] for h in history[:3]])
    last = np.mean([h["mean_reward"] for h in history[-3:]])
    assert last > first + 0.04


def test_checkpoint_round_trip(tmp_path, cfg):
    trainer = PPOTrainer(cfg)
    ts = trainer.init_state()
    path = save_state(str(tmp_path / "ckpt"), ts.params, step=3)
    assert "step_00000003" in path
    restored = load_state(str(tmp_path / "ckpt"))
    orig = jax.tree.leaves(ts.params)
    back = jax.tree.leaves(restored)
    assert all(np.allclose(np.asarray(a), np.asarray(b))
               for a, b in zip(orig, back))
