"""Learned-backend tests: codec invertibility, MPC improvement over its
initialization, PPO iteration mechanics and learning signal, checkpoints.

Kept small (tiny horizons/batches) so the suite stays fast on the 8-device
CPU mesh; the full-scale configs run through bench.py / train scripts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.models import (
    ActorCritic,
    PolicyMLP,
    action_to_latent,
    latent_dim,
    latent_to_action,
)
from ccka_tpu.policy.rule import neutral_action, offpeak_action
from ccka_tpu.sim import SimParams, initial_state, rollout, summarize
from ccka_tpu.sim.rollout import rollout_actions
from ccka_tpu.signals import SyntheticSignalSource
from ccka_tpu.train import MPCBackend, optimize_plan, save_state, load_state
from ccka_tpu.train.objective import episode_objective
from ccka_tpu.train.ppo import PPOBackend, PPOTrainer


@pytest.fixture(scope="module")
def cfg():
    return default_config().with_overrides(**{
        "train.batch_clusters": 4,
        "train.unroll_steps": 8,
        "train.mpc_horizon": 16,
        "train.mpc_iters": 15,
    })


@pytest.fixture(scope="module")
def source(cfg):
    return SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                 cfg.signals)


def test_latent_dim(cfg):
    # P*Z + P*2 + P + P + C = 6 + 4 + 2 + 2 + 2
    assert latent_dim(cfg.cluster) == 16


def test_codec_round_trip(cfg):
    a = offpeak_action(cfg.cluster)
    u = action_to_latent(a, cfg.cluster)
    back = latent_to_action(u, cfg.cluster)
    # Values at {0,1} saturate the logit; recovered within clip tolerance.
    assert np.allclose(np.asarray(back.zone_weight),
                       np.asarray(a.zone_weight), atol=1e-3)
    assert np.allclose(np.asarray(back.consolidation_aggr),
                       np.asarray(a.consolidation_aggr), atol=1e-3)
    assert np.allclose(np.asarray(back.hpa_scale),
                       np.asarray(a.hpa_scale), atol=1e-2)


def test_latent_to_action_always_feasible(cfg):
    # Any latent, however extreme, maps to a Kyverno-feasible action.
    for seed in range(3):
        u = jax.random.normal(jax.random.key(seed),
                              (latent_dim(cfg.cluster),)) * 10.0
        a = latent_to_action(u, cfg.cluster)
        od_idx = cfg.cluster.pool_index("on-demand-slo")
        assert float(a.ct_allow[od_idx, 0]) == 0.0   # never spot on SLO pool
        assert float(a.ct_allow[od_idx, 1]) >= 0.99  # od guaranteed
        assert float(a.hpa_scale.min()) >= 0.1


def test_policy_mlp_shapes(cfg):
    net = PolicyMLP(out_dim=latent_dim(cfg.cluster))
    obs = jnp.ones((29,))
    params = net.init(jax.random.key(0), obs)
    u = net.apply(params, obs)
    assert u.shape == (16,)
    batched = jax.vmap(lambda o: net.apply(params, o))(jnp.ones((8, 29)))
    assert batched.shape == (8, 16)


def test_actor_critic_zero_init_starts_near_neutral(cfg):
    net = ActorCritic(act_dim=latent_dim(cfg.cluster))
    obs = jnp.ones((29,))
    params = net.init(jax.random.key(0), obs)
    mean, log_std, value = net.apply(params, obs)
    assert mean.shape == (16,)
    assert np.allclose(np.asarray(mean), 0.0)  # zero-init head
    a = latent_to_action(mean, cfg.cluster)
    assert np.allclose(np.asarray(a.zone_weight), 0.5, atol=1e-6)


def test_mpc_plan_improves_objective(cfg, source):
    params = SimParams.from_config(cfg)
    tr = source.trace(16, seed=3)
    base = action_to_latent(neutral_action(cfg.cluster), cfg.cluster)
    init = jnp.broadcast_to(base, (16,) + base.shape)
    result = optimize_plan(params, cfg.cluster, cfg.train,
                           initial_state(cfg), tr, init, iters=15)
    assert np.isfinite(np.asarray(result.losses)).all()
    assert float(result.losses[-1]) < float(result.losses[0])


def test_mpc_backend_closed_loop(cfg, source):
    mpc = MPCBackend(cfg, horizon=8, iters=5, replan_every=4)
    tr = source.trace(12, seed=0)
    final, metrics = mpc.evaluate(initial_state(cfg), tr,
                                  jax.random.key(0), stochastic=False)
    assert metrics.cost_usd.shape == (12,)
    s = summarize(SimParams.from_config(cfg), metrics)
    assert float(s.cost_usd) > 0


def test_ppo_iteration_runs_and_shapes(cfg, source):
    trainer = PPOTrainer(cfg)
    ts, history = trainer.train(source, iterations=2, log_every=1)
    assert int(ts.iteration) == 2
    assert len(history) == 2
    for rec in history:
        assert np.isfinite(rec["policy_loss"])
        assert np.isfinite(rec["mean_reward"])


def test_ppo_backend_decides_feasible_actions(cfg, source):
    trainer = PPOTrainer(cfg)
    ts = trainer.init_state()
    backend = PPOBackend(cfg, ts.params)
    params = SimParams.from_config(cfg)
    tr = source.trace(8, seed=0)
    final, metrics = rollout(params, initial_state(cfg),
                             backend.action_fn(), tr, jax.random.key(0))
    assert metrics.cost_usd.shape == (8,)
    assert np.isfinite(np.asarray(metrics.cost_usd)).all()


def test_ppo_reward_improves_on_tiny_problem(cfg, source):
    # Learnability: 12 iterations on the tiny fixture must genuinely move
    # mean reward up. Calibration on the round-3 objective (carbon 5e-4,
    # pending 0.002, violation 0.02): deltas +0.011..+0.020 across seeds
    # 0-3. Bound sits at roughly half the weakest seed — fails a
    # didn't-learn regression without pinning seed luck (round-2 advisor:
    # RL variance across platforms makes near-margin bounds flaky).
    trainer = PPOTrainer(cfg)
    ts, history = trainer.train(source, iterations=12, log_every=1)
    first = np.mean([h["mean_reward"] for h in history[:3]])
    last = np.mean([h["mean_reward"] for h in history[-3:]])
    assert last > first + 0.005


def test_checkpoint_round_trip(tmp_path, cfg):
    trainer = PPOTrainer(cfg)
    ts = trainer.init_state()
    path = save_state(str(tmp_path / "ckpt"), ts.params, step=3)
    assert "step_00000003" in path
    restored = load_state(str(tmp_path / "ckpt"))
    orig = jax.tree.leaves(ts.params)
    back = jax.tree.leaves(restored)
    assert all(np.allclose(np.asarray(a), np.asarray(b))
               for a, b in zip(orig, back))


def test_params_npz_round_trip_drives_policy(tmp_path, cfg, source):
    """The single-file flagship format: params + provenance survive, and
    the restored tree actually drives the policy net (not just shapes)."""
    import jax.numpy as jnp

    from ccka_tpu.sim import initial_state
    from ccka_tpu.sim.rollout import exo_steps
    from ccka_tpu.train.checkpoint import (PARAMS_DIGEST_KEY,
                                           load_params_npz,
                                           params_digest,
                                           save_params_npz)
    from ccka_tpu.train.ppo import PPOBackend

    trainer = PPOTrainer(cfg)
    ts = trainer.init_state()
    meta = {"iterations_total": 7, "wins_both": False}
    path = save_params_npz(str(tmp_path / "flag.npz"), ts.params, meta=meta)
    params, got_meta = load_params_npz(path)
    # Round 23: every save stamps the params digest next to the caller's
    # meta, and the load path re-derives + verifies it (tamper refusal).
    assert got_meta.pop(PARAMS_DIGEST_KEY) == params_digest(params)
    assert got_meta == meta
    # Same decide() output from original and restored params.
    exo = jax.tree.map(lambda x: x[0], exo_steps(source.trace(1)))
    state = initial_state(cfg)
    a1 = PPOBackend(cfg, ts.params).decide(state, exo, jnp.int32(0))
    a2 = PPOBackend(cfg, params).decide(state, exo, jnp.int32(0))
    for x, y in zip(jax.tree.leaves(a1), jax.tree.leaves(a2)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


def test_imitation_distills_teacher(cfg, source):
    """Behavior cloning: actor MSE collapses and the student's decisions
    track the teacher's on fresh states (the PPO-warm-start path that
    sidesteps the early overprovision excursion)."""
    import jax.numpy as jnp

    from ccka_tpu.policy import CarbonAwarePolicy
    from ccka_tpu.sim.rollout import exo_steps, initial_state
    from ccka_tpu.train.imitate import collect_dataset, imitate

    teacher = CarbonAwarePolicy(cfg.cluster)
    data = collect_dataset(cfg, teacher, source, steps=16, seed=0)
    assert data.obs.shape[0] == data.target.shape[0] == 4 * 16
    # Targets are inside the trainable band, not the saturated corners.
    assert float(jnp.abs(data.target).max()) <= 3.0
    params, hist = imitate(cfg, teacher, source, iterations=300,
                           minibatch=64, dataset=data)
    assert hist[-1]["actor_mse"] < hist[0]["actor_mse"] * 0.3
    # Student ~ teacher on a state outside the dataset.
    exo = jax.tree.map(lambda x: x[0], exo_steps(source.trace(1, seed=77)))
    s0 = initial_state(cfg)
    a_t = teacher.decide(s0, exo, jnp.int32(0))
    a_s = PPOBackend(cfg, params).decide(s0, exo, jnp.int32(0))
    # hpa is the costly coordinate: both must be near serve-exactly.
    np.testing.assert_allclose(np.asarray(a_s.hpa_scale),
                               np.asarray(a_t.hpa_scale), atol=0.25)


@pytest.mark.slow
def test_flagship_init_from_distill(cfg):
    # Slow lane (57s, worst tier-1 offender measured round 6): this is a
    # composition smoke — distillation itself is pinned by
    # test_imitation_distills_teacher and checkpoint selection by
    # test_flagship_checkpoint_path_is_topology_keyed, both in the fast
    # lane.
    from ccka_tpu.train.flagship import train_flagship

    out = train_flagship(cfg, iterations=2, eval_every=2, eval_steps=64,
                         n_eval_traces=1, init_from="distill:carbon",
                         distill_iterations=50, log=lambda s: None)
    assert out["meta"]["init_from"] == "distill:carbon"
    with pytest.raises(ValueError, match="init_from"):
        train_flagship(cfg, iterations=2, eval_every=2, eval_steps=64,
                       n_eval_traces=1, init_from="nonsense",
                       log=lambda s: None)


class TestRefinementMechanics:
    """VERDICT r3 #1: the levers that let PPO improve ON a distilled
    teacher — critic-first warmup, KL-anchor, advantage clipping, actor
    LR scaling — each verified at the mechanism level."""

    def test_critic_warmup_freezes_actor_head(self, cfg, source):
        wcfg = cfg.with_overrides(**{"train.critic_warmup_iters": 2})
        trainer = PPOTrainer(wcfg)
        ts0 = trainer.init_state()
        ts, _ = trainer.train(source, iterations=2)
        p0, p1 = ts0.params["params"], ts.params["params"]
        # Actor head + log_std untouched during warmup (zero policy grad
        # through adam keeps them exactly at init)...
        np.testing.assert_array_equal(np.asarray(p0["actor_mean"]["kernel"]),
                                      np.asarray(p1["actor_mean"]["kernel"]))
        np.testing.assert_array_equal(np.asarray(p0["log_std"]),
                                      np.asarray(p1["log_std"]))
        # ...while the critic head trains.
        assert not np.allclose(np.asarray(p0["critic"]["kernel"]),
                               np.asarray(p1["critic"]["kernel"]))

    @pytest.mark.slow  # ISSUE 16 lane-time rule: refinement mechanics ride
    # the fast-lane PPO shape/reward tests; exactness unchanged.
    def test_warmup_then_actor_resumes(self, cfg, source):
        wcfg = cfg.with_overrides(**{"train.critic_warmup_iters": 1})
        trainer = PPOTrainer(wcfg)
        ts0 = trainer.init_state()
        ts, _ = trainer.train(source, iterations=3)
        p0, p1 = ts0.params["params"], ts.params["params"]
        # After warmup the actor head moves again.
        assert not np.allclose(np.asarray(p0["actor_mean"]["kernel"]),
                               np.asarray(p1["actor_mean"]["kernel"]))

    @pytest.mark.slow  # ISSUE 16 lane-time rule: refinement mechanics ride
    # the fast-lane PPO shape/reward tests; exactness unchanged.
    def test_anchor_bounds_policy_drift(self, cfg, source):
        # With a strong anchor, the refined policy's action means stay
        # near the anchor policy's; without, they drift further.
        base = PPOTrainer(cfg)
        anchor_params = base.init_state().params

        def drift(anchor_coef):
            acfg = cfg.with_overrides(**{
                "train.anchor_coef": anchor_coef,
                "train.learning_rate": 3e-3})   # exaggerate movement
            tr = PPOTrainer(acfg, anchor_params=anchor_params)
            ts, _ = tr.train(source, iterations=6)
            obs = jnp.asarray(np.random.default_rng(0).normal(
                size=(64, 29)), jnp.float32)
            m_ref, _, _ = tr.net.apply(anchor_params, obs)
            m_new, _, _ = tr.net.apply(ts.params, obs)
            return float(jnp.abs(m_new - m_ref).mean())

        assert drift(10.0) < drift(0.0) * 0.7

    def test_adv_clip_and_actor_scale_run_finite(self, cfg, source):
        rcfg = cfg.with_overrides(**{
            "train.adv_clip": 3.0, "train.actor_lr_scale": 0.25,
            "train.critic_warmup_iters": 1, "train.anchor_coef": 0.1})
        trainer = PPOTrainer(rcfg,
                             anchor_params=PPOTrainer(rcfg)
                             .init_state().params)
        ts, history = trainer.train(source, iterations=3, log_every=1)
        assert int(ts.iteration) == 3
        for rec in history:
            assert np.isfinite(rec["policy_loss"])
            assert np.isfinite(rec["value_loss"])

    def test_scale_actor_updates_targets_right_leaves(self, cfg):
        trainer = PPOTrainer(
            cfg.with_overrides(**{"train.actor_lr_scale": 0.5}))
        params = trainer.init_state().params
        ones = jax.tree.map(jnp.ones_like, params)
        scaled = trainer._scale_actor_updates(ones)
        p = scaled["params"]
        assert float(np.asarray(p["actor_mean"]["kernel"]).mean()) == 0.5
        assert float(np.asarray(p["log_std"]).mean()) == 0.5
        assert float(np.asarray(p["critic"]["kernel"]).mean()) == 1.0
        assert float(np.asarray(p["Dense_0"]["kernel"]).mean()) == 1.0

    @pytest.mark.slow  # ISSUE 14 lane-time rule: two 3-iter PPO runs
    # (~25s) re-proving the dual-ascent direction per iteration — the
    # multiplier's ascent and clamp stay fast-lane via
    # test_lagrangian_respects_bounds (which drives the same update to
    # its max) and test_fixed_weight_mode_unchanged (the off path).
    def test_lagrangian_multiplier_tracks_attainment_gap(self, cfg, source):
        """Dual ascent on the attainment constraint: the violation price
        rises while measured attainment is under target and decays above
        it — above-target attainment must stop earning reward."""
        def run(target):
            lcfg = cfg.with_overrides(**{"train.attain_target": target})
            trainer = PPOTrainer(lcfg)
            ts, hist = trainer.train(source, iterations=3, log_every=1)
            return float(ts.violation_weight), hist

        w0 = cfg.train.slo_violation_weight
        # Target nobody meets → multiplier grows.
        w_hi, hist_hi = run(0.999)
        assert w_hi > w0
        # Modest target: the ENDPOINT depends on where the untrained
        # policy's attainment starts on a given host (it can sit under
        # even 5% for the first iterations), so pin the mechanism
        # per-iteration instead — above-target iterations must shrink
        # the multiplier, below-target ones must grow it.
        w_lo, hist_lo = run(0.05)
        ws = [h["violation_weight"] for h in hist_lo] + [w_lo]
        assert len(ws) == len(hist_lo) + 1
        for h, w_used, w_next in zip(hist_lo, ws, ws[1:]):
            if h["attainment"] > 0.05:
                assert w_next < w_used, h
            else:
                assert w_next > w_used, h
        # Diagnostics expose the adaptation.
        assert all("attainment" in h and "violation_weight" in h
                   for h in hist_hi)

    def test_lagrangian_respects_bounds(self, cfg, source):
        lcfg = cfg.with_overrides(**{
            "train.attain_target": 0.999, "train.lagrange_lr": 50.0,
            "train.lagrange_max": 0.03})
        trainer = PPOTrainer(lcfg)
        ts, _ = trainer.train(source, iterations=3)
        assert float(ts.violation_weight) <= 0.03 + 1e-9

    def test_fixed_weight_mode_unchanged(self, cfg, source):
        trainer = PPOTrainer(cfg)   # attain_target = 0 (off)
        ts, _ = trainer.train(source, iterations=2)
        assert float(ts.violation_weight) == pytest.approx(
            cfg.train.slo_violation_weight)

    def test_cem_head_mask_targets_actor_head_only(self, cfg):
        from ccka_tpu.train.cem import _flatten, _head_mask

        params = PPOTrainer(cfg).init_state().params
        mask = np.asarray(_head_mask(params))
        flat, spec = _flatten(params)
        assert mask.shape == flat.shape
        # Exactly the actor head's parameter count is perturbable.
        head = params["params"]["actor_mean"]
        n_head = head["kernel"].size + head["bias"].size
        assert int(mask.sum()) == n_head
        # And the mask is positioned on the actor_mean leaves: zeroing
        # masked coords changes only actor_mean.
        from ccka_tpu.train.cem import _unflatten
        perturbed = _unflatten(flat + 7.0 * jnp.asarray(mask), spec)
        assert not np.allclose(
            np.asarray(perturbed["params"]["actor_mean"]["kernel"]),
            np.asarray(params["params"]["actor_mean"]["kernel"]))
        np.testing.assert_array_equal(
            np.asarray(perturbed["params"]["critic"]["kernel"]),
            np.asarray(params["params"]["critic"]["kernel"]))
        np.testing.assert_array_equal(
            np.asarray(perturbed["params"]["Dense_0"]["kernel"]),
            np.asarray(params["params"]["Dense_0"]["kernel"]))

    @pytest.mark.slow  # ISSUE 14 lane-time rule (~24s): the plain
    # "runs and reports" composition — the same cem_refine loop is
    # driven fast-lane by the sibling refinement-mechanics tests
    # (anchor drift, lagrangian bounds, warmup resume, fixed-weight),
    # each asserting a sharper claim on the identical machinery.
    def test_cem_refine_runs_and_reports(self, cfg, source):
        from ccka_tpu.train.cem import CEMConfig, cem_refine

        params0 = PPOTrainer(cfg).init_state().params
        best, hist, info = cem_refine(
            cfg, params0, source,
            cem=CEMConfig(generations=2, popsize=4, traces_per_gen=2,
                          eval_steps=32), seed=3)
        assert len(hist) == 2
        assert {"gen", "fitness", "final_sigma"} <= set(info)
        for rec in hist:
            assert np.isfinite(rec["incumbent_fitness"])
            assert rec["best_fitness"] <= rec["incumbent_fitness"] + 1e-9
        # The refined pytree has the net's structure.
        assert "actor_mean" in best["params"]

    @pytest.mark.slow
    def test_cem_mega_engine_matches_contract(self, cfg, source):
        """The kernel-backed generation: candidates, rule and the carbon
        teacher all scored by the megakernel in one paired launch
        (interpret mode on the CPU lane). Verifies the engine runs,
        reports the same history schema, and rejects misuse."""
        from ccka_tpu.policy import CarbonAwarePolicy
        from ccka_tpu.train.cem import CEMConfig, cem_refine

        params0 = PPOTrainer(cfg).init_state().params
        best, hist, info = cem_refine(
            cfg, params0, source,
            cem=CEMConfig(generations=1, popsize=3, traces_per_gen=128,
                          eval_steps=16),
            engine="mega", mega_interpret=True,
            teacher_policy=CarbonAwarePolicy(cfg.cluster), seed=3)
        assert len(hist) == 1
        assert np.isfinite(hist[0]["incumbent_fitness"])
        assert "actor_mean" in best["params"]

        with pytest.raises(ValueError, match="teacher_policy"):
            cem_refine(cfg, params0, source, engine="mega",
                       teacher_fn=lambda s, e, t: None)
        with pytest.raises(ValueError, match="multiple of 128"):
            cem_refine(cfg, params0, source,
                       cem=CEMConfig(generations=1, traces_per_gen=4,
                                     eval_steps=16),
                       engine="mega", mega_interpret=True)

    @pytest.mark.slow
    def test_cem_accepts_replay_sources(self, cfg, tmp_path):
        """Replay sources (no batch_trace_device) feed the ES through
        the coprime-window batch_trace fallback.

        Slow lane (48s measured round 6): the coprime-window sampling is
        pinned fast in test_signals, the ES loop by
        test_cem_refine_runs_and_reports — this adds only their
        composition."""
        from ccka_tpu.signals.base import TraceMeta
        from ccka_tpu.signals.replay import ReplaySignalSource, save_trace
        from ccka_tpu.train.cem import CEMConfig, cem_refine

        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        path = str(tmp_path / "t.npz")
        save_trace(path, src.trace(128, seed=0),
                   TraceMeta(source="test", start_unix_s=0.0,
                             dt_s=cfg.sim.dt_s, zones=cfg.cluster.zones))
        replay = ReplaySignalSource.from_file(path)
        params0 = PPOTrainer(cfg).init_state().params
        _best, hist, _info = cem_refine(
            cfg, params0, replay,
            cem=CEMConfig(generations=1, popsize=4, traces_per_gen=2,
                          eval_steps=32), seed=0)
        assert np.isfinite(hist[0]["incumbent_fitness"])

    def test_beats_teacher_criterion(self):
        from ccka_tpu.train.flagship import beats_teacher

        teacher = {"usd_per_slo_hour": 1.0, "g_co2_per_kreq": 1.0,
                   "slo_attainment": 0.95}
        better = {"usd_per_slo_hour": 0.98, "g_co2_per_kreq": 1.0,
                  "slo_attainment": 0.95}
        worse_co2 = {"usd_per_slo_hour": 0.9, "g_co2_per_kreq": 1.05,
                     "slo_attainment": 0.95}
        tie = {"usd_per_slo_hour": 1.0, "g_co2_per_kreq": 1.0,
               "slo_attainment": 0.96}
        low_attain = {"usd_per_slo_hour": 0.9, "g_co2_per_kreq": 0.9,
                      "slo_attainment": 0.90}
        assert beats_teacher(better, teacher)
        assert not beats_teacher(worse_co2, teacher)   # pays the other axis
        assert not beats_teacher(tie, teacher)         # no strict improvement
        assert not beats_teacher(low_attain, teacher)  # attainment shortfall


def test_flagship_checkpoint_path_is_topology_keyed():
    from ccka_tpu.config import default_config, multi_region_config
    from ccka_tpu.train.flagship import flagship_checkpoint_path

    single = flagship_checkpoint_path(default_config())
    multi = flagship_checkpoint_path(multi_region_config())
    assert single.endswith("ppo_flagship.npz")
    assert multi.endswith("ppo_flagship_multiregion.npz")
    assert flagship_checkpoint_path() == single


class TestMeshShardedPlanning:
    """ISSUE 4: `optimize_plan_batch`/`receding_horizon_plan_batch` fan
    the cluster batch over the mesh's data axis (mirroring
    `cem_refine(mesh=)`), with a donated warm-start buffer; and the
    receding-horizon PLANNER returns the exact sequence the closed loop
    would execute — the kernel plan-playback contract."""

    @staticmethod
    def _batch(cfg, source, n, h):
        from ccka_tpu.train.mpc import optimize_plan_batch  # noqa: F401

        base = jnp.zeros_like(
            action_to_latent(neutral_action(cfg.cluster), cfg.cluster))
        lat = jnp.broadcast_to(base, (n, h) + base.shape)
        states = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n,) + x.shape),
            initial_state(cfg))
        traces = source.batch_trace_device(h, jax.random.key(3), n)
        return states, traces, lat

    @pytest.mark.slow
    def test_mesh_fanout_matches_single_device(self, cfg, source):
        """Slow lane: compiles a shard_map'd Adam loop twice — the mesh
        composition idiom is already pinned by the (slow) cem-mesh test;
        this adds only the planner instance of it."""
        from ccka_tpu.parallel import make_mesh
        from ccka_tpu.train.mpc import optimize_plan_batch

        params = SimParams.from_config(cfg)
        states, traces, lat = self._batch(cfg, source, 8, 8)
        r0 = optimize_plan_batch(params, cfg.cluster, cfg.train, states,
                                 traces, lat, iters=2)
        mesh = make_mesh(devices=jax.devices()[:8])
        r1 = optimize_plan_batch(params, cfg.cluster, cfg.train, states,
                                 traces, lat, iters=2, mesh=mesh)
        np.testing.assert_allclose(np.asarray(r0.plan_latent),
                                   np.asarray(r1.plan_latent), atol=1e-5)
        with pytest.raises(ValueError, match="data-axis"):
            s6, t6, l6 = self._batch(cfg, source, 6, 8)
            optimize_plan_batch(params, cfg.cluster, cfg.train, s6, t6,
                                l6, iters=2, mesh=mesh)

    @pytest.mark.slow
    def test_donated_warm_start_aliases(self, cfg, source):
        """Slow lane: an extra donating compile of the batch planner;
        the donation mechanics themselves are pinned in the fast lane
        by the sharded-kernel donation-chain test."""
        from ccka_tpu.train.mpc import optimize_plan_batch

        params = SimParams.from_config(cfg)
        states, traces, lat = self._batch(cfg, source, 8, 8)
        donated = jnp.array(lat)
        r = optimize_plan_batch(params, cfg.cluster, cfg.train, states,
                                traces, donated, iters=2,
                                donate_plans=True)
        jax.block_until_ready(r.plan_latent)
        assert donated.is_deleted(), "warm-start buffer was not donated"
        assert r.plan_latent.shape == lat.shape

    @pytest.mark.slow  # ISSUE 16 lane-time rule: mesh duplicate of the
    # single-chip plan-replay parity that stays in the fast lane.
    def test_receding_horizon_plan_replays_the_closed_loop(self, cfg,
                                                           source):
        from ccka_tpu.train.mpc import (receding_horizon_plan,
                                        receding_horizon_rollout)

        params = SimParams.from_config(cfg)
        base = jnp.zeros_like(
            action_to_latent(neutral_action(cfg.cluster), cfg.cluster))
        lat0 = jnp.broadcast_to(base, (8,) + base.shape)
        # Same (steps, horizon, replan, iters) statics as
        # test_mpc_backend_closed_loop, so the closed-loop program is a
        # compile-cache hit in the full lane.
        tr = source.trace(12, seed=5)
        seq = receding_horizon_plan(params, cfg.cluster, cfg.train,
                                    initial_state(cfg), tr, lat0,
                                    horizon=8, replan_every=4, iters=5)
        assert seq.shape == (12, latent_dim(cfg.cluster))
        acts = jax.vmap(lambda u: latent_to_action(u, cfg.cluster))(seq)
        _, m_play = rollout_actions(params, initial_state(cfg), acts, tr,
                                    jax.random.key(0), stochastic=False)
        _, m_rh = receding_horizon_rollout(
            params, cfg.cluster, cfg.train, initial_state(cfg), tr, lat0,
            jax.random.key(0), horizon=8, replan_every=4, iters=5,
            stochastic=False)
        for a, b in zip(m_play, m_rh):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)
