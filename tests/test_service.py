"""Multi-tenant fleet service (round 13): bounded batched ticks,
per-tenant bulkheads/circuit breakers, backpressure with load shedding.

The contracts pinned here:

- the "off" `SERVICE_PRESETS` posture is BYTE-IDENTICAL to the
  pre-service `FleetController` loop (reports and per-sink command
  streams) — the zero-overhead gate, same idiom as ChaosSink "off";
- breaker state-machine edges: open after the failure threshold,
  half-open probe success re-closes, probe failure re-opens with grown
  (seeded-jitter, capped) delay, renewed chaos re-opens a recovered
  breaker;
- bulkhead isolation: a stressed run's HEALTHY tenants accumulate
  bitwise the same per-tenant $/SLO-hour as the paired calm run;
- bounded ticks: a hung scrape is abandoned at the budget edge
  (deferred, never awaited), latency stays under the deadline;
- backpressure: admission overflow sheds stale-tolerant tenants first,
  and sustained saturation degrades their cadence (bounded divisor).
"""

from __future__ import annotations

import numpy as np
import pytest

from ccka_tpu.config import SERVICE_PRESETS, ServiceConfig, default_config
from ccka_tpu.harness.fleet import fleet_controller_from_config
from ccka_tpu.harness.service import (LANE_FALLBACK, LANE_FRESH,
                                      CircuitBreaker, TENANT_PROFILES,
                                      fleet_service_from_config,
                                      resolve_profiles)
from ccka_tpu.policy import RulePolicy


@pytest.fixture(scope="module")
def cfg():
    return default_config().with_overrides(**{"sim.horizon_steps": 16})


@pytest.fixture(scope="module")
def rule(cfg):
    # ONE backend instance module-wide: the service-tick compile cache
    # keys on it, so every test below shares a single XLA program.
    return RulePolicy(cfg.cluster)


def _svc(**kw) -> ServiceConfig:
    base = dict(enabled=True, tick_deadline_ms=200.0)
    base.update(kw)
    return ServiceConfig(**base)


class TestCircuitBreaker:
    """The closed→open→half-open machine, edge by edge (host-only)."""

    def test_opens_after_threshold_then_probe_success_recloses(self):
        svc = _svc(breaker_failures=2, breaker_probe_ticks=3,
                   breaker_probe_jitter=0.0)
        br = CircuitBreaker(svc, seed=1)
        assert br.allow(0) and br.state == "closed"
        br.record_failure(0)
        assert br.state == "closed"          # threshold not reached
        br.record_failure(1)
        assert br.state == "open" and br.level == 2
        assert not br.allow(2)               # probe not due: bulkheaded
        assert not br.allow(3)
        assert br.allow(4)                   # 1 + 3 ticks: probe due
        assert br.state == "half-open" and br.level == 1
        br.record_success()
        assert br.state == "closed" and br.level == 0
        assert br.transitions == {"opened": 1, "half_open": 1,
                                  "closed": 1}

    def test_probe_failure_reopens_with_doubled_delay(self):
        svc = _svc(breaker_failures=1, breaker_probe_ticks=3,
                   breaker_probe_jitter=0.0)
        br = CircuitBreaker(svc, seed=0)
        br.record_failure(0)                 # open; probe at t=3
        assert br.allow(3) and br.state == "half-open"
        br.record_failure(3)                 # half-open probe fails
        assert br.state == "open"
        # Backoff doubled: next probe 3 + 2*3 = 9, not 3 + 3.
        assert not br.allow(8)
        assert br.allow(9) and br.state == "half-open"

    def test_reopens_under_renewed_chaos_with_reset_backoff(self):
        svc = _svc(breaker_failures=1, breaker_probe_ticks=4,
                   breaker_probe_jitter=0.0)
        br = CircuitBreaker(svc, seed=0)
        br.record_failure(0)
        assert br.allow(4)
        br.record_success()                  # recovered
        assert br.state == "closed"
        br.record_failure(10)                # renewed chaos
        assert br.state == "open"
        assert br.transitions["opened"] == 2
        # Recovery reset the consecutive-open counter: the new probe
        # delay is the BASE again, not the doubled one.
        assert br.allow(14)

    def test_probe_delay_capped_and_seeded_jitter_deterministic(self):
        svc = _svc(breaker_failures=1, breaker_probe_ticks=4,
                   breaker_probe_jitter=0.3, breaker_max_probe_ticks=16)
        a = CircuitBreaker(svc, seed=7)
        b = CircuitBreaker(svc, seed=7)
        t = 0
        for _ in range(6):                   # exponent would hit 128
            a.record_failure(t)
            b.record_failure(t)
            assert a._probe_at == b._probe_at  # seeded: paired runs agree
            assert a._probe_at - t <= svc.breaker_max_probe_ticks
            t = a._probe_at
            assert a.allow(t) and b.allow(t)   # half-open probe

    def test_open_ticks_drives_hold_to_fallback_escalation(self):
        svc = _svc(breaker_failures=1, hold_fallback_after=3)
        br = CircuitBreaker(svc, seed=0)
        assert br.open_ticks(5) == 0
        br.record_failure(5)
        assert br.open_ticks(6) == 1
        assert br.open_ticks(9) >= svc.hold_fallback_after
        br.record_success()
        assert br.open_ticks(12) == 0


class TestOffGate:
    """SERVICE_PRESETS['off'] is pinned byte-identical to the current
    FleetController behavior — the zero-overhead gate."""

    def test_off_preset_byte_identical_to_fleet_controller(self, cfg,
                                                           rule):
        n, ticks = 6, 3
        svc = fleet_service_from_config(
            cfg, rule, n, service=SERVICE_PRESETS["off"],
            horizon_ticks=8, seed=5)
        ctl = fleet_controller_from_config(
            cfg, rule, n, horizon_ticks=8, seed=5, fanout_workers=1)
        r_svc = svc.run(ticks)
        r_ctl = [ctl.tick(t) for t in range(ticks)]
        for a, b in zip(r_svc, r_ctl):
            # Delegated ticks return FleetTickReports with identical
            # decisions and accounting — bitwise, not approximately.
            assert (a.t, a.applied, a.slo_ok) == (b.t, b.applied, b.slo_ok)
            assert a.cost_usd_hr == b.cost_usd_hr
            assert a.carbon_g_hr == b.carbon_g_hr
        for sa, sb in zip(svc.sinks, ctl.sinks):
            assert [(c.name, c.patch_type, c.patch) for c in sa.commands] \
                == [(c.name, c.patch_type, c.patch) for c in sb.commands]
        # Zero overhead: the off gate builds NO breaker/queue machinery.
        assert not hasattr(svc, "breakers")
        svc.close()
        ctl.close()

    def test_cli_fleet_service_summary_and_unknown_preset(self, capsys):
        import json

        from ccka_tpu.cli import main

        assert main(["fleet", "--clusters", "4", "--ticks", "2",
                     "--service", "default"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["service"] == "default"
        assert out["admitted_frac"] == 1.0      # all-healthy fleet
        with pytest.raises(SystemExit, match="unknown service preset"):
            main(["fleet", "--clusters", "2", "--ticks", "1",
                  "--service", "nope"])
        with pytest.raises(SystemExit, match="unknown tenant profiles"):
            main(["fleet", "--clusters", "2", "--ticks", "1",
                  "--service", "default", "--profiles", "bogus"])


class TestBulkheadIsolation:
    """One slow/byzantine tenant must cost the OTHER tenants nothing:
    their decide rows (and therefore their accumulated $/SLO-hour) are
    bitwise the calm run's."""

    def test_healthy_tenants_bitwise_match_calm_run(self, cfg, rule):
        n, ticks = 8, 8
        stress = fleet_service_from_config(
            cfg, rule, n,
            profiles=["healthy"] * 5 + ["slow"] * 2 + ["flaky"],
            service=SERVICE_PRESETS["default"], horizon_ticks=16, seed=3)
        calm = fleet_service_from_config(
            cfg, rule, n, profiles=["healthy"] * n,
            service=SERVICE_PRESETS["default"], horizon_ticks=16, seed=3)
        stress.warmup()
        calm.warmup()
        stress.run(ticks)
        calm.run(ticks)
        s = stress.tenant_usd_per_slo_hr()
        c = calm.tenant_usd_per_slo_hr()
        np.testing.assert_array_equal(s[:5], c[:5])   # bitwise
        # The stressed tenants genuinely degraded (held/fallback lanes,
        # skipped scrapes) — isolation is meaningful, not vacuous.
        assert stress.tenant_fresh_ticks[:5].min() == ticks
        assert stress.tenant_fresh_ticks[5:].max() < ticks
        stress.close()
        calm.close()

    def test_hung_scrape_deferred_then_breaker_bulkheads(self, cfg, rule):
        n, ticks = 4, 10
        svc = fleet_service_from_config(
            cfg, rule, n, profiles=["healthy"] * 3 + ["slow"],
            service=_svc(breaker_failures=2, breaker_probe_ticks=4),
            horizon_ticks=16, seed=11)
        svc.warmup()
        reports = svc.run(ticks)
        # The hung scrape timed out at the budget edge (never awaited),
        # opened its breaker, and was then bulkheaded outright.
        assert svc.scrape_timeouts_total >= 2
        assert svc.breakers[3].transitions["opened"] >= 1
        assert svc.bulkhead_skips_total > 0
        # Healthy tenants kept full cadence throughout.
        assert all(r.admitted >= 3 for r in reports)
        assert svc.tenant_fresh_ticks[:3].min() == ticks
        # Bounded ticks: every latency under the deadline; the slow
        # scrape burned budget only until the breaker opened.
        assert max(svc.latencies_ms) < svc.svc.tick_deadline_ms
        assert any(r.tick_latency_ms > 50.0 for r in reports[:3])
        # Generous bound: the tail tick is breaker-bulkheaded (no slow
        # scrape), but real dispatch/host time rides the clock too and
        # a loaded CI machine must not flake this.
        assert reports[-1].tick_latency_ms < 100.0
        # The per-tick accounting stays a PARTITION even across breaker
        # opens: a tenant is bulkheaded OR scrape-failed OR admitted,
        # never double-counted between the scrape and fan-out phases.
        assert any(r.scrape_failed > 0 for r in reports)
        assert any(r.bulkhead_skipped > 0 for r in reports)
        for r in reports:
            assert (r.admitted + r.shed + r.cadence_skipped + r.deferred
                    + r.bulkhead_skipped + r.scrape_failed) == n, r
        svc.close()

    def test_open_breaker_escalates_hold_to_rule_fallback(self, cfg,
                                                          rule):
        n = 3
        svc = fleet_service_from_config(
            cfg, rule, n, profiles=["healthy"] * 2 + ["slow"],
            service=_svc(breaker_failures=1, hold_fallback_after=2,
                         breaker_probe_ticks=32),
            horizon_ticks=16, seed=2)
        svc.warmup()
        svc.run(6)
        assert svc.last_lanes[0] == LANE_FRESH
        assert svc.last_lanes[1] == LANE_FRESH
        assert svc.last_lanes[2] == LANE_FALLBACK
        svc.close()


class TestBackpressure:
    """Fixed-capacity admission: overflow sheds stale-tolerant tenants
    first, every shed is counted, saturation degrades cadence."""

    def test_shed_priority_and_cadence_degradation(self, cfg, rule):
        n, ticks = 6, 8
        svc = fleet_service_from_config(
            cfg, rule, n, profiles=["healthy"] * 3 + ["batch"] * 3,
            service=_svc(admission_queue_cap=4, shed_backoff_after=2,
                         cadence_backoff_max=4),
            horizon_ticks=16, seed=9)
        svc.warmup()
        reports = svc.run(ticks)
        # Overflow shed from the back of the priority order: the
        # stale-tolerant batch tenants, never the healthy three.
        assert svc.sheds_total > 0
        assert svc.tenant_fresh_ticks[:3].min() == ticks
        assert svc.tenant_fresh_ticks[3:].max() < ticks
        # Sustained saturation degraded the stale-tolerant cadence
        # (bounded), and the skips are accounted.
        assert reports[-1].cadence_divisor > 1
        assert reports[-1].cadence_divisor <= 4
        assert svc.cadence_skips_total > 0
        # Every dropped decide is on the record: shed + cadence-skipped
        # + admitted + deferred + bulkheaded + scrape-failed covers each
        # tick's fleet — a partition, not overlapping tallies.
        for r in reports:
            assert (r.admitted + r.shed + r.cadence_skipped + r.deferred
                    + r.bulkhead_skipped + r.scrape_failed) == n
        svc.close()

    def test_unknown_profiles_rejected_up_front(self, cfg, rule):
        with pytest.raises(ValueError, match="unknown tenant profiles"):
            resolve_profiles(["healthy", "bogus"])
        with pytest.raises(ValueError, match="unknown tenant profiles"):
            fleet_service_from_config(
                cfg, rule, 2, profiles=["healthy", "bogus"],
                service=SERVICE_PRESETS["default"], horizon_ticks=8)
        # And the registry itself stays the vocabulary: every named
        # archetype resolves.
        assert [p.name for p in resolve_profiles(list(TENANT_PROFILES))] \
            == list(TENANT_PROFILES)


class TestOverloadScoreboard:
    """The paired stressed/calm board: isolation + bounded latency on
    the record; unknown names rejected up front (satellite 6)."""

    def test_small_grid_invariants(self, cfg):
        from ccka_tpu.harness.overload import overload_scoreboard

        board = overload_scoreboard(
            cfg, policies=("rule",), tenants=(6,),
            intensities=("off", "severe"), slow_fracs=(0.0, 0.5),
            ticks=8, seed=5)
        inv = board["invariants"]
        # The acceptance surface: healthy isolation holds exactly, the
        # null cell pins zero service overhead, and no tick ran past
        # its deadline.
        assert inv["healthy_usd_ratio_max"] == 1.0
        assert inv["null_cell_ratio_max"] == 1.0
        # Latencies include real host time: allow one stray tick on a
        # loaded CI machine rather than flaking (the committed BENCH
        # record, not this mini-grid, is the bounded-ticks evidence).
        assert inv["deadline_violations_total"] <= 1
        cell = board["cells"]["n6/severe/slow0.5"]["rows"]["rule"]
        assert cell["healthy_bitwise_frac"] == 1.0
        assert cell["breaker_transitions"]["opened"] > 0
        assert cell["sheds_total"] > 0
        assert cell["latency_ms"]["p99"] < cell["latency_ms"]["max"] + 1
        assert board["cells"]["n6/severe/slow0.5"]["tick_deadline_ms"] \
            > cell["latency_ms"]["p99"]
        # The stress was real: chaos injected on the stressed edge.
        assert sum(cell["chaos_injected"][k] for k in
                   ("timeouts", "transient_exits", "dropped",
                    "rewrites")) > 0

    def test_unknown_names_rejected_up_front(self, cfg):
        from ccka_tpu.harness.overload import overload_scoreboard

        with pytest.raises(ValueError, match="unknown chaos"):
            overload_scoreboard(cfg, intensities=("off", "nope"),
                                policies=("rule",))
        with pytest.raises(ValueError, match="unknown tenant profile"):
            overload_scoreboard(cfg, slow_profile="nope",
                                policies=("rule",))
        with pytest.raises(ValueError, match="unknown service preset"):
            overload_scoreboard(cfg, service_preset="nope",
                                policies=("rule",))
        with pytest.raises(ValueError, match="unknown policies"):
            overload_scoreboard(cfg, policies=("rule", "nope"))
        with pytest.raises(ValueError, match="off gate"):
            overload_scoreboard(cfg, service_preset="off",
                                policies=("rule",))
        with pytest.raises(ValueError, match="empty grid axis"):
            overload_scoreboard(cfg, tenants=(), policies=("rule",))
        # Flagship-only without a committed checkpoint for this
        # topology must fail BEFORE the grid runs, not after it.
        with pytest.raises(ValueError, match="no runnable policy"):
            overload_scoreboard(cfg, policies=("flagship",),
                                tenants=(2,), intensities=("off",),
                                slow_fracs=(0.0,), ticks=4)

    def test_cli_overload_eval_rejects_unknown_names(self):
        from ccka_tpu.cli import main

        with pytest.raises(SystemExit, match="unknown chaos"):
            main(["overload-eval", "--intensities", "off,bogus",
                  "--policies", "rule", "--ticks", "4"])
        with pytest.raises(SystemExit, match="unknown tenant profile"):
            main(["overload-eval", "--profile", "bogus",
                  "--policies", "rule", "--ticks", "4"])
        with pytest.raises(SystemExit, match="unknown service preset"):
            main(["overload-eval", "--service", "bogus",
                  "--policies", "rule", "--ticks", "4"])

    def test_cli_overload_eval_small_board(self, capsys):
        import json

        from ccka_tpu.cli import main

        assert main(["overload-eval", "--tenants", "4",
                     "--intensities", "off", "--slow-fracs", "0",
                     "--policies", "rule", "--ticks", "4"]) == 0
        board = json.loads(capsys.readouterr().out)
        assert board["cells"]["n4/off/slow0"]["rows"]["rule"][
            "healthy_usd_ratio_max"] == 1.0


class TestFleetScaleHostLoop:
    """Round 21: the flat-array admission machine is BITWISE the
    retired per-tenant object loop, and the counter-based jitter
    streams consume identical draw counts in either machine — the
    paired parity pin the vectorized refactor rides on."""

    def test_vectorized_bitwise_object_at_small_n(self, cfg, rule):
        from ccka_tpu.harness.fleetscale import _run_paired

        profiles = ["healthy", "batch", "jittery", "slow", "flaky"] * 2
        n = len(profiles)
        svc = _svc(breaker_failures=2, admission_queue_cap=n - 2)
        res = _run_paired(
            cfg, rule, n, profiles, svc, ticks=10, seed=211, horizon=14,
            variants={"vectorized": ("vectorized", None),
                      "object": ("object", None)})
        assert res["bitwise_identical"], res["mismatches"]
        assert res["mismatches"] == []
        # The comparison covered every deterministic surface, and the
        # run exercised the machinery it claims to compare (flaky
        # tenants fail scrapes, the cap sheds).
        assert set(res["checked"]) >= {"report_counters", "patch_streams",
                                       "held_rows", "tenant_usd",
                                       "tenant_slo_ticks",
                                       "breaker_transitions"}

    def test_counter_stream_addressing_is_pure(self):
        from ccka_tpu.harness.service import counter_u01

        u_vec = counter_u01(123, np.arange(8))
        u_scalar = np.array([float(counter_u01(123, k))
                             for k in range(8)])
        # Vector and scalar addressing of the same (seed, counter)
        # cells agree exactly — the memoized schedule is a pure
        # function of the address, not of call batching.
        assert np.array_equal(u_vec, u_scalar)
        assert np.all((u_vec >= 0.0) & (u_vec < 1.0))
        assert len(set(u_vec.tolist())) == 8
        # Distinct streams diverge.
        assert float(counter_u01(123, 0)) != float(counter_u01(124, 0))

    def test_banks_consume_identical_draw_counts(self):
        from ccka_tpu.harness.service import (_ObjectBreakerBank,
                                              _VectorBreakerBank)

        svc = _svc(breaker_failures=1, breaker_probe_ticks=3,
                   breaker_probe_jitter=0.3, breaker_max_probe_ticks=32)
        n, seed = 6, 211
        obj = _ObjectBreakerBank(svc, seed, n)
        vec = _VectorBreakerBank(svc, seed, n)

        def drive(bank_fail, bank_ok):
            # Mixed schedule: batch failures, scalar failures, a
            # recovery, renewed chaos — every _open path draws.
            bank_fail(np.arange(0, n, 2), 0)
            bank_fail(np.arange(n), 4)
            bank_ok(np.asarray([1, 3]))
            bank_fail(np.asarray([1]), 9)

        drive(vec.record_failure_idx, vec.record_success_idx)
        drive(lambda idx, t: [obj.record_failure(int(i), t)
                              for i in idx],
              lambda idx: [obj.record_success(int(i)) for i in idx])
        # Draw-count determinism: both machines consumed the same
        # number of jitter draws per tenant from the same streams, so
        # the memoized probe schedules are bitwise identical.
        assert vec.draws.tolist() == [b.draws for b in obj.breakers]
        assert vec.probe_at.tolist() == \
            [b._probe_at for b in obj.breakers]
        assert vec.transition_counts() == obj.transition_counts()
        # And a replay of the same schedule reproduces it exactly.
        vec2 = _VectorBreakerBank(svc, seed, n)
        drive(vec2.record_failure_idx, vec2.record_success_idx)
        assert np.array_equal(vec2.probe_at, vec.probe_at)
        assert np.array_equal(vec2.draws, vec.draws)
